"""DeepConsensus-TPU: a TPU-native framework for polishing PacBio CCS reads.

A from-scratch JAX/XLA/Pallas re-design with the capabilities of
google/deepconsensus (reference: /root/reference): it turns subreads
aligned to a draft circular-consensus sequence (CCS) into higher-quality
consensus reads using a gap-aware encoder-only transformer.

Subpackages:
  constants     -- vocabulary, cigar ops, dataset split regions
  utils         -- phred/sequence helpers (numpy + jax variants)
  io            -- BAM/FASTQ/TFRecord I/O with zero external deps
  preprocess    -- alignment-domain core: spacing, windowing, features
  models        -- flax transformer, losses/metrics, training loops
  ops           -- TPU kernels (banded attention, wavefront DP)
  parallel      -- device meshes, shardings, ring attention
  inference     -- batched inference runner
  postprocess   -- window stitching
  calibration   -- base-quality calibration + read filtering
"""

__version__ = '0.1.0'
