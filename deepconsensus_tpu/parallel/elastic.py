"""Elastic pod membership: epoch-numbered member sets over bounded
collectives.

The PR-9/14 degradation ladder made single-host training survivable
(device loss -> mesh rebuild at lower dp) but deliberately refused on
multi-host meshes, and every cross-host barrier in the stock stack —
`jax.distributed`'s collectives, the PreemptionGuard stop vote, orbax's
multihost save protocol — waits FOREVER on a peer that will never
answer. This module is the membership layer that makes pod-scale
training elastic both ways:

* **Epoch-numbered member set.** The pod's authoritative state is
  (epoch, members, step), bumped by every membership change and
  committed by the leader (the lowest live host id) to `epoch.json`.
  Barrier namespaces embed the epoch, so a rebuilt pod can re-run the
  failed step without colliding with payloads the old membership left
  behind.

* **Bounded barriers.** Every collective is a deadline-bounded
  file-transport allgather: each member atomically publishes its
  payload under `barrier/<epoch>/<name>/` and polls for the others
  until `barrier_timeout`. A missed deadline raises a typed
  `HostLostError` NAMING the missing process indices — never a hang.
  `bounded_call` extends the same guarantee to collectives we don't
  own (the legacy `process_allgather` stop vote, orbax's save barrier)
  by running them under a watchdog deadline.

* **Agreement round.** On `HostLostError` every survivor proposes its
  candidate member set (hosts with fresh heartbeats), the proposals are
  allgathered and intersected, a confirm round checks all survivors
  computed the same set, and the epoch bumps. Bounded retries shrink
  the candidate set until it converges; exhaustion raises the permanent
  `ElasticRebuildError` instead of looping.

* **Re-admission.** A recovered host writes a join request and waits;
  live members observe it piggybacked on the per-step sync, admit it at
  the next step boundary (epoch bump, leader-written state snapshot),
  and the joiner resumes from the exact step the pod is on.

Transport is a shared directory (`<out_dir>/.pod/`) rather than a
socket mesh: TPU pods already share the checkpoint filesystem, atomic
rename gives publish-or-nothing semantics, and — critically for the
fault model — a payload a host wrote before dying REMAINS readable, so
a step where every survivor collected the full set completes
consistently even if the writer is already gone. The jit-visible mesh
of an elastic member never spans processes (`mesh.local_mesh`);
cross-host gradient reduction happens at host level through
`step_sync`'s weighted mean, which reproduces the global-batch-mean
gradient exactly (up to summation order) because per-host losses are
batch means weighted by their slice sizes. On a real multi-controller
pod the same membership protocol drives `distributed.reinitialize`
to re-enter jax.distributed at the agreed process count
(docs/training.md "Elastic multi-host training").
"""
from __future__ import annotations

import collections
import io
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepconsensus_tpu.faults import ElasticRebuildError, HostLostError

log = logging.getLogger(__name__)

# Pod-dir layout (all paths relative to pod_dir):
#   hb/<host>.json               heartbeat, touched every interval
#   epoch.json                   authoritative (epoch, members, step)
#   join/<host>.json             re-admission requests
#   barrier/<epoch>/<name>/<h>.npz   one bounded-collective payload
#   state/epoch-<E>.npz          leader-written snapshot for joiners
_HB_DIR = 'hb'
_JOIN_DIR = 'join'
_BARRIER_DIR = 'barrier'
_STATE_DIR = 'state'
_EPOCH_FILE = 'epoch.json'

# Collect-side poll interval. Publishing is one atomic rename; waiting
# is a listdir poll, so the floor on barrier latency is this interval.
_POLL_S = 0.01


def _atomic_write_bytes(path: str, payload: bytes) -> None:
  tmp = f'{path}.tmp.{os.getpid()}'
  with open(tmp, 'wb') as f:
    f.write(payload)
    f.flush()
    os.fsync(f.fileno())
  os.replace(tmp, path)


def _write_payload(path: str, meta: Dict[str, Any],
                   arrays: Optional[Sequence[np.ndarray]] = None) -> None:
  """Publishes one barrier payload atomically: meta (JSON) + arrays in
  a single .npz, written to a temp name and renamed into place so a
  reader never observes a torn file."""
  buf = io.BytesIO()
  named = {
      f'arr_{i}': np.asarray(a) for i, a in enumerate(arrays or ())
  }
  named['__meta__'] = np.frombuffer(
      json.dumps(meta).encode('utf-8'), dtype=np.uint8
  )
  np.savez(buf, **named)
  _atomic_write_bytes(path, buf.getvalue())


def _read_payload(path: str) -> Tuple[Dict[str, Any], List[np.ndarray]]:
  with np.load(path) as z:
    meta = json.loads(bytes(z['__meta__'].tobytes()).decode('utf-8'))
    n = sum(1 for k in z.files if k.startswith('arr_'))
    arrays = [np.asarray(z[f'arr_{i}']) for i in range(n)]
  return meta, arrays


def bounded_call(fn: Callable[[], Any], timeout_s: float, name: str):
  """Runs a blocking collective under a deadline: the typed-HostLostError
  counterpart of the PR-9 dispatch watchdog, for barriers whose C++
  implementations cannot be cancelled (the legacy multihost
  `process_allgather` stop vote, orbax's multihost save protocol).

  The call runs on a daemon worker thread; if it misses the deadline
  the caller gets `HostLostError` immediately and the stuck thread is
  abandoned (it holds no locks the training loop needs — exactly the
  trade the dispatch watchdog already makes for hung device packs).
  Values and exceptions from a call that DOES finish pass through
  unchanged.
  """
  # dclint: lock-free (single hand-off dict: the worker writes, the
  # caller reads only after join() establishes the ordering)
  box: Dict[str, Any] = {}

  def run():
    try:
      box['value'] = fn()
    # dclint: allow=typed-faults (cross-thread hand-off: the exception
    # is re-raised verbatim on the caller's thread below)
    except BaseException as e:
      box['error'] = e

  worker = threading.Thread(target=run, daemon=True,
                            name=f'bounded-{name}')
  worker.start()
  worker.join(timeout=max(timeout_s, 0.0))
  if worker.is_alive():
    raise HostLostError(
        f'collective {name!r} exceeded its {timeout_s:.1f}s deadline '
        '(bounded-barrier watchdog); a peer died inside the barrier',
        barrier=name,
    )
  if 'error' in box:
    raise box['error']
  return box.get('value')


class StepSync:
  """Result of one `ElasticPod.step_sync`: the weighted-mean arrays,
  the per-host metas, and the merged control plane (stop votes ORed,
  join requests unioned) every member computed identically from the
  same payload files."""

  __slots__ = ('arrays', 'metas', 'stop', 'join_requests', 'weight_total')

  def __init__(self, arrays, metas, stop, join_requests, weight_total):
    self.arrays = arrays
    self.metas = metas
    self.stop = stop
    self.join_requests = join_requests
    self.weight_total = weight_total


class PodStart:
  """Outcome of `ElasticPod.start`: whether this host booted with the
  founding member set or joined a live pod (in which case `state`
  carries the leader's snapshot leaves and `step` the resume step)."""

  __slots__ = ('joined', 'epoch', 'members', 'step', 'state')

  def __init__(self, joined, epoch, members, step, state=None):
    self.joined = joined
    self.epoch = epoch
    self.members = members
    self.step = step
    self.state = state


class ElasticPod:
  """One host's membership endpoint: heartbeats, bounded collectives,
  the agreement round, and join/admit. See the module docstring for
  the protocol; `models/train.py run_training` is the driver."""

  def __init__(self, pod_dir: str, host_id: int, n_hosts: int, *,
               barrier_timeout: float = 30.0,
               heartbeat_interval: float = 0.25,
               boot_timeout: Optional[float] = None,
               join_timeout: Optional[float] = None,
               rebuild_attempts: int = 4,
               readmit: bool = True,
               defer_join_until_step: int = 0):
    if n_hosts < 1 or not 0 <= host_id < max(n_hosts, host_id + 1):
      # dclint: allow=typed-faults (startup flag validation)
      raise ValueError(
          f'invalid pod geometry: host_id={host_id} n_hosts={n_hosts}')
    if barrier_timeout <= 0:
      # dclint: allow=typed-faults (startup flag validation)
      raise ValueError('barrier_timeout must be > 0 (the bounded-'
                       'barrier rule: no collective may wait unbounded)')
    self.pod_dir = os.path.abspath(pod_dir)
    self.host_id = int(host_id)
    self.n_hosts = int(n_hosts)
    self.barrier_timeout = float(barrier_timeout)
    self.heartbeat_interval = float(heartbeat_interval)
    # A host counts as a live candidate while its heartbeat file is
    # fresher than this; comfortably above the touch interval so one
    # slow fsync doesn't evict a healthy member.
    self.heartbeat_timeout = max(2.0, 8.0 * self.heartbeat_interval)
    self.boot_timeout = float(
        boot_timeout if boot_timeout is not None else barrier_timeout)
    self.join_timeout = float(
        join_timeout if join_timeout is not None
        else max(120.0, 4.0 * barrier_timeout))
    self.rebuild_attempts = int(rebuild_attempts)
    self.readmit = bool(readmit)
    self.defer_join_until_step = int(defer_join_until_step)
    # Incarnation distinguishes a restarted host from its dead previous
    # self (same id) in epoch.json / join records.
    self.incarnation = int(time.time() * 1e6) ^ os.getpid()
    self._lock = threading.Lock()
    self._epoch = 0  # guarded by: self._lock
    self._members: Tuple[int, ...] = ()  # guarded by: self._lock
    self._step = 0  # guarded by: self._lock
    self._round = 0  # guarded by: self._lock
    # First step barrier after a re-admission runs under join_timeout
    # instead of barrier_timeout: the joiner still has to adopt the
    # snapshot and compile its step before it can post, and evicting it
    # for warming up would turn every admission into a rebuild.
    self._grace_until_step = 0  # guarded by: self._lock
    self._counters: collections.Counter = (
        collections.Counter())  # guarded by: self._lock
    self._abandoned = False  # guarded by: self._lock
    self._stop = threading.Event()
    # dclint: lock-free (written once in start() before any concurrent
    # access; abandon/close only join() it, which is thread-safe)
    self._hb_thread: Optional[threading.Thread] = None
    for sub in (_HB_DIR, _JOIN_DIR, _BARRIER_DIR, _STATE_DIR):
      os.makedirs(os.path.join(self.pod_dir, sub), exist_ok=True)

  # ---- views ---------------------------------------------------------
  @property
  def epoch(self) -> int:
    with self._lock:
      return self._epoch

  @property
  def members(self) -> Tuple[int, ...]:
    with self._lock:
      return self._members

  @property
  def is_leader(self) -> bool:
    with self._lock:
      return bool(self._members) and self.host_id == min(self._members)

  def advance_round(self) -> None:
    """Call when the training loop rewinds its step counter (NaN
    rollback): named barriers are namespaced by (epoch, round, step),
    so replayed step numbers get fresh barriers instead of matching the
    stale payload files their first pass left behind. The rollback
    decision is deterministic pod-wide (every member judges the same
    merged metrics), so rounds advance in lockstep."""
    with self._lock:
      self._round += 1

  def counters(self) -> Dict[str, float]:
    """Snapshot for the train metrics sidecar's `faults` split."""
    with self._lock:
      out = {k: float(v) for k, v in self._counters.items()}
      out['pod_epoch'] = float(self._epoch)
      out.setdefault('n_host_rebuilds', 0.0)
      out.setdefault('n_host_readmissions', 0.0)
      out.setdefault('n_barrier_timeouts', 0.0)
    return out

  # ---- heartbeats ----------------------------------------------------
  def _hb_path(self, host: int) -> str:
    return os.path.join(self.pod_dir, _HB_DIR, f'{host}.json')

  def _write_heartbeat(self, left: bool = False) -> None:
    with self._lock:
      beat = {
          'host': self.host_id,
          'incarnation': self.incarnation,
          'epoch': self._epoch,
          'step': self._step,
          'left': bool(left),
      }
    _atomic_write_bytes(self._hb_path(self.host_id),
                        json.dumps(beat).encode('utf-8'))

  def _heartbeat_main(self) -> None:
    while not self._stop.wait(self.heartbeat_interval):
      try:
        self._write_heartbeat()
      except OSError:  # pragma: no cover - transient fs hiccup
        continue

  def read_heartbeat(self, host: int) -> Optional[Dict[str, Any]]:
    """The peer's last beat plus its staleness, or None when the host
    never checked in. `fresh` is the liveness verdict the agreement
    round uses."""
    path = self._hb_path(host)
    try:
      age = time.time() - os.stat(path).st_mtime
      with open(path, 'rb') as f:
        beat = json.loads(f.read().decode('utf-8'))
    except (OSError, ValueError):
      return None
    beat['age_s'] = age
    beat['fresh'] = age < self.heartbeat_timeout and not beat.get('left')
    return beat

  def _live_candidates(self) -> List[int]:
    """Hosts (self always included) whose heartbeats are fresh — the
    candidate set each survivor proposes in the agreement round."""
    live = {self.host_id}
    hb_dir = os.path.join(self.pod_dir, _HB_DIR)
    for entry in sorted(os.listdir(hb_dir)):
      if not entry.endswith('.json'):
        continue
      host = int(entry[:-5])
      beat = self.read_heartbeat(host)
      if beat is not None and beat['fresh']:
        live.add(host)
    return sorted(live)

  def observed_step(self) -> int:
    """Highest step any live peer advertises — what a deferred joiner
    polls to time its announcement to a target step boundary."""
    best = 0
    for host in self._live_candidates():
      beat = self.read_heartbeat(host)
      if beat is not None:
        best = max(best, int(beat.get('step', 0)))
    return best

  # ---- bounded barrier primitives ------------------------------------
  def _barrier_dir(self, epoch: int, name: str) -> str:
    return os.path.join(self.pod_dir, _BARRIER_DIR, str(epoch), name)

  def _post(self, epoch: int, name: str, meta: Dict[str, Any],
            arrays: Optional[Sequence[np.ndarray]] = None) -> None:
    bdir = self._barrier_dir(epoch, name)
    os.makedirs(bdir, exist_ok=True)
    _write_payload(os.path.join(bdir, f'{self.host_id}.npz'),
                   meta, arrays)

  def _collect(self, epoch: int, name: str, expected: Sequence[int],
               timeout_s: float
               ) -> Dict[int, Tuple[Dict[str, Any], List[np.ndarray]]]:
    """Waits (bounded) for every expected host's payload. The deadline
    is absolute from entry: no code path through here can block longer
    than `timeout_s`, and a miss raises HostLostError naming exactly
    the hosts whose payloads never appeared."""
    bdir = self._barrier_dir(epoch, name)
    expected = sorted(set(int(h) for h in expected))
    deadline = time.monotonic() + timeout_s
    got: Dict[int, Tuple[Dict[str, Any], List[np.ndarray]]] = {}
    while True:
      for host in expected:
        if host in got:
          continue
        path = os.path.join(bdir, f'{host}.npz')
        if os.path.exists(path):
          try:
            got[host] = _read_payload(path)
          except (OSError, ValueError, KeyError):
            # Concurrent GC or a torn read under a dying writer: treat
            # as not-yet-posted; the deadline still bounds the wait.
            continue
      if len(got) == len(expected):
        return got
      if time.monotonic() >= deadline:
        missing = [h for h in expected if h not in got]
        with self._lock:
          self._counters['n_barrier_timeouts'] += 1
        raise HostLostError(
            f'bounded barrier expired after {timeout_s:.1f}s waiting '
            f'for {len(missing)} of {len(expected)} member(s)',
            missing=missing, barrier=name, epoch=epoch,
        )
      time.sleep(_POLL_S)

  def allgather(self, name: str, meta: Dict[str, Any],
                arrays: Optional[Sequence[np.ndarray]] = None,
                timeout_s: Optional[float] = None
                ) -> Dict[int, Tuple[Dict[str, Any], List[np.ndarray]]]:
    """Bounded allgather across the CURRENT member set. Names are
    additionally namespaced by the rollback round (advance_round), so a
    training loop that rewinds its step counter never collides with the
    stale payloads of the first pass."""
    with self._lock:
      epoch, members = self._epoch, self._members
      name = f'r{self._round}-{name}'
    self._post(epoch, name, meta, arrays)
    return self._collect(
        epoch, name, members,
        self.barrier_timeout if timeout_s is None else timeout_s)

  def barrier(self, name: str,
              timeout_s: Optional[float] = None) -> None:
    """Bounded rendezvous with no payload (e.g. checkpoint-commit
    alignment)."""
    self.allgather(name, {'host': self.host_id}, timeout_s=timeout_s)

  # ---- per-step sync --------------------------------------------------
  def step_sync(self, step: int, arrays: Sequence[np.ndarray],
                weight: float, meta: Optional[Dict[str, Any]] = None,
                stop_vote: bool = False) -> StepSync:
    """The elastic data-plane collective: weighted-mean allreduce of
    this step's host arrays (gradients + model-state deltas), with the
    control plane piggybacked — stop votes (the PreemptionGuard's
    unanimity requirement, now bounded for free) and join requests, so
    membership changes land exactly at step boundaries without extra
    barriers.

    Weights are local slice sizes: sum(w_k * mean_k) / sum(w_k) is the
    exact global-batch mean, so elastic training matches the fused
    single-mesh step to summation order.
    """
    payload_meta = {
        'host': self.host_id,
        'weight': float(weight),
        'stop': bool(stop_vote),
        'join_requests': self._scan_join_requests() if self.readmit
                         else [],
    }
    if meta:
      payload_meta.update(meta)
    with self._lock:
      epoch, members = self._epoch, self._members
      name = f'r{self._round}-step-{step}'
      timeout = (self.join_timeout if step <= self._grace_until_step
                 else self.barrier_timeout)
    self._post(epoch, name, payload_meta, arrays)
    got = self._collect(epoch, name, members, timeout)
    hosts = sorted(got)
    weights = np.asarray(
        [float(got[h][0]['weight']) for h in hosts], np.float32)
    total = float(weights.sum()) or 1.0
    merged: List[np.ndarray] = []
    for i in range(len(arrays)):
      acc = np.zeros_like(np.asarray(got[hosts[0]][1][i], np.float32))
      for h, w in zip(hosts, weights):
        acc += (w / total) * np.asarray(got[h][1][i], np.float32)
      merged.append(acc)
    join_requests = sorted({
        int(j) for h in hosts for j in got[h][0].get('join_requests', ())
    })
    with self._lock:
      self._step = max(self._step, int(step))
    self._gc_step_barriers(step)
    return StepSync(
        arrays=merged,
        metas={h: got[h][0] for h in hosts},
        stop=any(bool(got[h][0].get('stop')) for h in hosts),
        join_requests=join_requests,
        weight_total=total,
    )

  def _gc_step_barriers(self, step: int, keep: int = 4) -> None:
    """Removes this host's own payloads for long-completed steps.
    Members run in lockstep (a step completes only when everyone
    posted), so files `keep` steps back are unreachable; empty barrier
    dirs are reaped best-effort."""
    with self._lock:
      epoch = self._epoch
      rnd = self._round
    for old in (step - keep, step - keep - 1):
      if old < 0:
        continue
      bdir = self._barrier_dir(epoch, f'r{rnd}-step-{old}')
      try:
        os.unlink(os.path.join(bdir, f'{self.host_id}.npz'))
        os.rmdir(bdir)
      except OSError:
        pass

  # ---- formation ------------------------------------------------------
  def start(self, resume_step: int = 0) -> PodStart:
    """Boot or join. A live pod (fresh peer heartbeat + committed
    epoch.json) means this host is a RE-ADMISSION: it announces itself
    and waits to be admitted at a step boundary. Otherwise all
    founding hosts rendezvous (bounded by boot_timeout), agree on the
    founding member set, and epoch 1 (or stale-epoch + 1 on a
    whole-pod restart) commits."""
    self._write_heartbeat()
    self._hb_thread = threading.Thread(
        target=self._heartbeat_main, daemon=True,
        name=f'pod-heartbeat-{self.host_id}')
    self._hb_thread.start()
    committed = self._read_epoch_file()
    peers_alive = any(
        h != self.host_id for h in self._live_candidates())
    if committed is not None and peers_alive and self.readmit:
      return self._join(committed)
    return self._boot(committed, resume_step)

  def _read_epoch_file(self) -> Optional[Dict[str, Any]]:
    try:
      with open(os.path.join(self.pod_dir, _EPOCH_FILE), 'rb') as f:
        return json.loads(f.read().decode('utf-8'))
    except (OSError, ValueError):
      return None

  def _commit_epoch(self, epoch: int, members: Sequence[int],
                    step: int, incarnations: Dict[int, int]) -> None:
    with self._lock:
      rnd = self._round
    record = {
        'epoch': int(epoch),
        'members': sorted(int(m) for m in members),
        'step': int(step),
        'round': rnd,
        'incarnations': {str(k): int(v) for k, v in incarnations.items()},
    }
    _atomic_write_bytes(os.path.join(self.pod_dir, _EPOCH_FILE),
                        json.dumps(record).encode('utf-8'))

  def _boot(self, committed: Optional[Dict[str, Any]],
            resume_step: int) -> PodStart:
    base_epoch = int(committed['epoch']) if committed else 0
    target = base_epoch + 1
    self._post(0, f'boot-{target}',
               {'host': self.host_id, 'incarnation': self.incarnation})
    expected = sorted(set(range(self.n_hosts)) | {self.host_id})
    try:
      got = self._collect(0, f'boot-{target}', expected,
                          self.boot_timeout)
      candidates = sorted(got)
    except HostLostError as e:
      # Founding members that never arrived are left out; they come
      # back through the join path. A pod of one is still a pod.
      log.warning('pod boot proceeding without missing host(s): %s', e)
      candidates = sorted(
          set(self._barrier_posters(0, f'boot-{target}')) | {self.host_id})
    epoch, members, incarnations = self._agree(
        target, participants=candidates, proposal=candidates,
        round_name='boot')
    with self._lock:
      self._epoch, self._members = epoch, tuple(members)
      self._step = int(resume_step)
    if self.host_id == min(members):
      self._commit_epoch(epoch, members, resume_step, incarnations)
    self._write_heartbeat()
    log.info('pod booted: epoch=%d members=%s host=%d',
             epoch, members, self.host_id)
    return PodStart(joined=False, epoch=epoch, members=tuple(members),
                    step=int(resume_step))

  def _barrier_posters(self, epoch: int, name: str) -> List[int]:
    bdir = self._barrier_dir(epoch, name)
    try:
      return sorted(
          int(f[:-4]) for f in os.listdir(bdir) if f.endswith('.npz'))
    except OSError:
      return []

  # ---- agreement round ------------------------------------------------
  def _agree(self, target_epoch: int, participants: Sequence[int],
             proposal: Sequence[int], round_name: str
             ) -> Tuple[int, List[int], Dict[int, int]]:
    """Two-phase bounded agreement: allgather proposals, intersect,
    then confirm every participant computed the same set. A participant
    that dies mid-round is dropped and the round retries at the next
    epoch number; `rebuild_attempts` misses raise ElasticRebuildError
    (permanent — the pod cannot converge)."""
    participants = sorted(set(int(p) for p in participants))
    proposal = sorted(set(int(p) for p in proposal))
    epoch = int(target_epoch)
    for attempt in range(self.rebuild_attempts):
      name = f'{round_name}-{epoch}'
      try:
        got = self._collect_after_post(
            0, f'propose-{name}',
            {'host': self.host_id, 'incarnation': self.incarnation,
             'members': proposal},
            participants)
        agreed = set(proposal)
        incarnations = {self.host_id: self.incarnation}
        for h, (meta, _) in got.items():
          agreed &= set(int(m) for m in meta['members'])
          incarnations[int(h)] = int(meta.get('incarnation', 0))
        # Participants that posted survive; proposed non-participants
        # (joiners being admitted) stay without voting.
        agreed |= set(proposal) - set(participants)
        agreed &= set(proposal)
        agreed |= {int(h) for h in got}
        confirm = self._collect_after_post(
            0, f'confirm-{name}',
            {'host': self.host_id, 'members': sorted(agreed)},
            sorted(set(got) | {self.host_id}))
        views = {tuple(sorted(meta['members']))
                 for meta, _ in confirm.values()}
        if len(views) == 1:
          members = sorted(agreed)
          if self.host_id not in members:
            raise ElasticRebuildError(
                f'host {self.host_id} was voted out of the pod at '
                f'epoch {epoch} (agreed members: {members}); its '
                'heartbeats went stale during the agreement round')
          return epoch, members, incarnations
        # Divergent views (a candidate died between propose and
        # confirm): shrink to the still-live intersection and retry.
        proposal = sorted(set.intersection(*[set(v) for v in views]))
        participants = [p for p in proposal if p in participants]
      except HostLostError as e:
        with self._lock:
          self._counters['n_agreement_retries'] += 1
        participants = [p for p in participants if p not in e.missing]
        proposal = [p for p in proposal if p not in e.missing]
        log.warning('agreement round %s retrying without %s (%s)',
                    name, list(e.missing), e)
      epoch += 1
      if not participants or participants == [self.host_id] and (
          len(proposal) > 1):
        proposal = [self.host_id]
        participants = [self.host_id]
    raise ElasticRebuildError(
        f'pod agreement failed to converge after '
        f'{self.rebuild_attempts} round(s) (last proposal {proposal}, '
        f'participants {participants}); refusing to continue with an '
        'ambiguous member set')

  def _collect_after_post(self, epoch: int, name: str,
                          meta: Dict[str, Any],
                          expected: Sequence[int]
                          ) -> Dict[int, Tuple[Dict[str, Any],
                                               List[np.ndarray]]]:
    self._post(epoch, name, meta)
    return self._collect(epoch, name, expected, self.barrier_timeout)

  # ---- rebuild (host loss) -------------------------------------------
  def rebuild(self) -> Tuple[int, ...]:
    """The coordinated survivor-side rebuild: candidates are the hosts
    with fresh heartbeats, the agreement round converges the member
    set, the epoch bumps, and the leader commits. Returns the new
    member set; raises ElasticRebuildError when no consistent set can
    form (or this host was voted out)."""
    with self._lock:
      old_members = self._members
      old_epoch = self._epoch
      step = self._step
    candidates = []
    for h in self._live_candidates():
      if h == self.host_id:
        candidates.append(h)
        continue
      if h not in old_members:
        continue
      # A restarted instance of a lost member heartbeats at epoch 0
      # until it is re-admitted; it must come back through the join
      # path, not vote in a rebuild it has no membership state for.
      beat = self.read_heartbeat(h)
      if beat is not None and int(beat.get('epoch', 0)) >= old_epoch:
        candidates.append(h)
    epoch, members, incarnations = self._agree(
        old_epoch + 1, participants=candidates, proposal=candidates,
        round_name='rebuild')
    with self._lock:
      self._epoch, self._members = epoch, tuple(members)
      self._counters['n_host_rebuilds'] += 1
    if self.host_id == min(members):
      self._commit_epoch(epoch, members, step, incarnations)
    self._write_heartbeat()
    log.warning(
        'pod rebuilt: epoch %d -> %d, members %s -> %s',
        old_epoch, epoch, list(old_members), members)
    return tuple(members)

  # ---- re-admission ---------------------------------------------------
  def _join_path(self, host: int) -> str:
    return os.path.join(self.pod_dir, _JOIN_DIR, f'{host}.json')

  def _scan_join_requests(self) -> List[int]:
    """Join requests from hosts that are NOT current members and whose
    requester still heartbeats (a joiner that died while waiting is
    ignored rather than admitted into a timeout)."""
    with self._lock:
      members = set(self._members)
    out = []
    jdir = os.path.join(self.pod_dir, _JOIN_DIR)
    try:
      entries = sorted(os.listdir(jdir))
    except OSError:
      return out
    for entry in entries:
      if not entry.endswith('.json'):
        continue
      host = int(entry[:-5])
      if host in members:
        continue
      beat = self.read_heartbeat(host)
      if beat is not None and beat['fresh']:
        out.append(host)
    return sorted(out)

  def admit(self, joiners: Sequence[int], state_arrays: Sequence[np.ndarray],
            step: int) -> Tuple[int, ...]:
    """Survivor side of re-admission, run at a step boundary: the
    leader snapshots the live state for the incoming host(s), current
    members agree on the expanded set, the epoch bumps, and the commit
    record (which the joiner is polling) publishes the admission. The
    joiners do not vote — they are proposed members; a joiner that died
    while waiting simply goes missing at the next step's sync."""
    with self._lock:
      members = list(self._members)
      old_epoch = self._epoch
    joiners = sorted(set(int(j) for j in joiners) - set(members))
    if not joiners:
      return tuple(members)
    target = old_epoch + 1
    if self.host_id == min(members):
      self.write_state_snapshot(target, step, state_arrays)
    epoch, new_members, incarnations = self._agree(
        target, participants=members, proposal=members + joiners,
        round_name='admit')
    for j in joiners:
      beat = self.read_heartbeat(j)
      if beat is not None:
        incarnations[j] = int(beat.get('incarnation', 0))
    with self._lock:
      self._epoch, self._members = epoch, tuple(sorted(new_members))
      self._grace_until_step = int(step) + 1
      self._counters['n_host_readmissions'] += len(
          set(new_members) - set(members))
    if self.host_id == min(new_members + [self.host_id]):
      self._commit_epoch(epoch, new_members, step, incarnations)
    self._write_heartbeat()
    log.warning('pod re-admitted %s at step %d: epoch %d -> %d, '
                'members %s', joiners, step, old_epoch, epoch,
                sorted(new_members))
    return tuple(sorted(new_members))

  def _join(self, committed: Dict[str, Any]) -> PodStart:
    """Joiner side: announce, optionally defer to a target step
    boundary (the DCTPU_FAULT_HOST_REJOIN_AT_STEP hook), then poll the
    commit record until an epoch admits THIS incarnation. Bounded by
    join_timeout — an unresponsive pod raises HostLostError (transient:
    the retry wrapper restarts, and a truly dead pod boots fresh)."""
    deadline = time.monotonic() + self.join_timeout
    while (self.defer_join_until_step
           and self.observed_step() < self.defer_join_until_step):
      if time.monotonic() >= deadline:
        with self._lock:
          self._counters['n_barrier_timeouts'] += 1
        raise HostLostError(
            f'pod never reached step {self.defer_join_until_step} '
            f'within the {self.join_timeout:.0f}s join deadline',
            barrier='join-defer')
      time.sleep(_POLL_S)
    _atomic_write_bytes(
        self._join_path(self.host_id),
        json.dumps({'host': self.host_id,
                    'incarnation': self.incarnation}).encode('utf-8'))
    log.info('host %d requesting re-admission (incarnation %d)',
             self.host_id, self.incarnation)
    while True:
      record = self._read_epoch_file()
      if (record is not None
          and self.host_id in record.get('members', ())
          and int(record.get('incarnations', {}).get(
              str(self.host_id), -1)) == self.incarnation):
        break
      if time.monotonic() >= deadline:
        with self._lock:
          self._counters['n_barrier_timeouts'] += 1
        raise HostLostError(
            f'pod did not admit host {self.host_id} within the '
            f'{self.join_timeout:.0f}s join deadline',
            barrier='join-admit')
      time.sleep(_POLL_S)
    epoch = int(record['epoch'])
    members = tuple(sorted(int(m) for m in record['members']))
    step = int(record['step'])
    state = self.read_state_snapshot(epoch)
    with self._lock:
      self._epoch, self._members, self._step = epoch, members, step
      # Adopt the pod's rollback round or the joiner's barrier names
      # would never match the survivors' after a NaN rollback.
      self._round = int(record.get('round', 0))
      self._grace_until_step = step + 1
      self._counters['n_host_readmissions'] += 1
    try:
      os.unlink(self._join_path(self.host_id))
    except OSError:
      pass
    self._write_heartbeat()
    log.info('host %d re-admitted: epoch=%d members=%s step=%d',
             self.host_id, epoch, members, step)
    return PodStart(joined=True, epoch=epoch, members=members,
                    step=step, state=state)

  # ---- state snapshots ------------------------------------------------
  def _snapshot_path(self, epoch: int) -> str:
    return os.path.join(self.pod_dir, _STATE_DIR, f'epoch-{epoch}.npz')

  def write_state_snapshot(self, epoch: int, step: int,
                           arrays: Sequence[np.ndarray]) -> None:
    """Leader-written flattened TrainState leaves a joiner adopts, so
    re-admission re-places state OUTWARD (live memory -> new member)
    instead of rolling the pod back to a checkpoint."""
    _write_payload(self._snapshot_path(epoch),
                   {'epoch': int(epoch), 'step': int(step)}, arrays)

  def read_state_snapshot(self, epoch: int
                          ) -> Optional[List[np.ndarray]]:
    try:
      _, arrays = _read_payload(self._snapshot_path(epoch))
      return arrays
    except (OSError, ValueError, KeyError):
      return None

  # ---- lifecycle ------------------------------------------------------
  def abandon(self) -> None:
    """Abrupt detach for fault drills (ENV_HOST_LOST_MODE=drop): stop
    heartbeating WITHOUT a tombstone, so peers observe exactly what a
    SIGKILL leaves behind — a stale heartbeat and a missed barrier."""
    with self._lock:
      self._abandoned = True
    self._stop.set()
    if self._hb_thread is not None:
      self._hb_thread.join(timeout=2.0)

  def close(self) -> None:
    """Clean shutdown at end of training: the final heartbeat carries a
    `left` tombstone so late peers classify this host as departed, not
    lost."""
    self._stop.set()
    if self._hb_thread is not None:
      self._hb_thread.join(timeout=2.0)
    with self._lock:
      abandoned = self._abandoned
    if not abandoned:
      try:
        self._write_heartbeat(left=True)
      except OSError:  # pragma: no cover - best-effort tombstone
        pass
