from deepconsensus_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    make_mesh,
    param_shardings,
    replicated,
)
from deepconsensus_tpu.parallel.partition_rules import (  # noqa: F401
    DEFAULT_RULES,
    PartitionRuleError,
    match_partition_rules,
    tree_shardings,
)
