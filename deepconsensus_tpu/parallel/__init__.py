from deepconsensus_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    make_mesh,
    param_shardings,
    replicated,
)
