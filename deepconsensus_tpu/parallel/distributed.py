"""Multi-host initialization and per-host input sharding helpers.

The reference reaches multi-host scale through TPUStrategy's cluster
resolver (reference: models/model_train_custom_loop.py:333-343). The
JAX equivalent is jax.distributed plus global device meshes; each host
feeds its local shard of the global batch.
"""
from __future__ import annotations

import logging
from typing import Optional

import jax
import numpy as np

log = logging.getLogger(__name__)


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
  """Initializes jax.distributed (no-op when single-process).

  On Cloud TPU pods the arguments auto-detect from the environment.
  """
  if num_processes in (None, 1) and coordinator_address is None:
    if jax.process_count() == 1:
      log.info('single-process run; skipping jax.distributed')
      return
  jax.distributed.initialize(
      coordinator_address=coordinator_address,
      num_processes=num_processes,
      process_id=process_id,
  )
  log.info(
      'distributed initialized: process %d/%d, %d local / %d global devices',
      jax.process_index(), jax.process_count(),
      jax.local_device_count(), jax.device_count(),
  )


def reinitialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
  """Re-enters `initialize` semantics after an elastic membership
  change: tears down the current jax.distributed client and re-forms
  at the agreed process count (survivor count after a rebuild, full
  count after a re-admission). Only meaningful on a real
  multi-controller pod — the elastic CPU/file-transport pod keeps each
  host single-process and never calls this."""
  if jax.process_count() == 1:
    return
  try:
    jax.distributed.shutdown()
  # dclint-style teardown: the old cohort is gone; a shutdown barrier
  # failing against dead peers is exactly the condition being repaired.
  except Exception as e:  # pylint: disable=broad-except
    log.warning('jax.distributed shutdown before re-init failed '
                '(expected when peers died): %s', e)
  jax.distributed.initialize(
      coordinator_address=coordinator_address,
      num_processes=num_processes,
      process_id=process_id,
  )
  log.info(
      'distributed re-initialized: process %d/%d, %d local / %d global '
      'devices', jax.process_index(), jax.process_count(),
      jax.local_device_count(), jax.device_count(),
  )


def local_batch_slice(global_batch_size: int) -> slice:
  """The slice of the global batch this host should feed."""
  per_host = global_batch_size // jax.process_count()
  start = jax.process_index() * per_host
  return slice(start, start + per_host)


def member_batch_slice(global_batch_size: int, n_members: int,
                       rank: int) -> slice:
  """The contiguous rows of the global batch that pod member `rank`
  (position in the sorted member set, not host id) owns. np.array_split
  semantics: when the batch doesn't divide evenly the first
  `global_batch_size % n_members` members take one extra row, so every
  row is owned exactly once at ANY member count — the property the
  elastic rebuild relies on when n_members changes mid-run."""
  bounds = [len(part) for part in
            np.array_split(np.arange(global_batch_size), n_members)]
  start = sum(bounds[:rank])
  return slice(start, start + bounds[rank])


def host_local_to_global(mesh, pspec, local_array):
  """Assembles a globally-sharded array from per-host local shards."""
  from jax.experimental import multihost_utils

  return multihost_utils.host_local_array_to_global_array(
      local_array, mesh, pspec
  )
