"""Multi-host initialization and per-host input sharding helpers.

The reference reaches multi-host scale through TPUStrategy's cluster
resolver (reference: models/model_train_custom_loop.py:333-343). The
JAX equivalent is jax.distributed plus global device meshes; each host
feeds its local shard of the global batch.
"""
from __future__ import annotations

import logging
from typing import Optional

import jax

log = logging.getLogger(__name__)


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
  """Initializes jax.distributed (no-op when single-process).

  On Cloud TPU pods the arguments auto-detect from the environment.
  """
  if num_processes in (None, 1) and coordinator_address is None:
    if jax.process_count() == 1:
      log.info('single-process run; skipping jax.distributed')
      return
  jax.distributed.initialize(
      coordinator_address=coordinator_address,
      num_processes=num_processes,
      process_id=process_id,
  )
  log.info(
      'distributed initialized: process %d/%d, %d local / %d global devices',
      jax.process_index(), jax.process_count(),
      jax.local_device_count(), jax.device_count(),
  )


def local_batch_slice(global_batch_size: int) -> slice:
  """The slice of the global batch this host should feed."""
  per_host = global_batch_size // jax.process_count()
  start = jax.process_index() * per_host
  return slice(start, start + per_host)


def host_local_to_global(mesh, pspec, local_array):
  """Assembles a globally-sharded array from per-host local shards."""
  from jax.experimental import multihost_utils

  return multihost_utils.host_local_array_to_global_array(
      local_array, mesh, pspec
  )
