"""Ring attention: sequence-parallel exact attention over a mesh axis.

The production window model attends over fixed 100-bp windows, but the
framework treats long-context as first-class: this module computes
exact (optionally banded) attention for sequences sharded across
devices. Queries stay resident; key/value blocks rotate around the ring
via ppermute while a flash-style online softmax accumulates partial
results, so memory per device is O(L/N) and the collectives ride ICI.

Usage is via shard_map with the sequence axis sharded on a mesh axis;
ring_attention_sharded wraps that plumbing.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
  from jax import shard_map  # jax >= 0.8
except ImportError:
  from jax.experimental.shard_map import shard_map

Array = jnp.ndarray

_NEG_INF = -1e30

# Count of ring_attention_blockwise *traces* (the Python body runs only
# when jit traces a new shape). Training surfaces this as
# n_ring_attention_traces in the faults sidecar, and the long-insert
# tests use it to prove the L=500 forward really routed through the
# blockwise scan rather than the fused/XLA logits path.
n_blockwise_traces = 0


def _mark_varying(x: Array, axis_name: str) -> Array:
  """Marks x device-varying over axis_name so the scan carry types line
  up with the ppermuted K/V blocks. jax >= 0.8 spells this
  jax.lax.pcast(to='varying'), 0.5-0.7 jax.lax.pvary; older versions
  don't track varying-ness in the type system, so identity is correct
  there."""
  pcast = getattr(jax.lax, 'pcast', None)
  if pcast is not None:
    return pcast(x, axis_name, to='varying')
  pvary = getattr(jax.lax, 'pvary', None)
  if pvary is not None:
    return pvary(x, axis_name)
  return x


def _block_attention(
    q: Array,
    k: Array,
    v: Array,
    q_offset: Array,
    k_offset: Array,
    attn_win_size: Optional[int],
):
  """Scores of one (q_block, k_block) pair with optional band mask.

  q: [B, Lq, H, D]; k, v: [B, Lk, H, D]. Returns (scores [B, H, Lq, Lk],
  value tensor) with masked logits at -inf.
  """
  depth = q.shape[-1]
  s = jnp.einsum('bqhd,bkhd->bhqk', q, k) * (depth**-0.5)
  if attn_win_size is not None:
    qi = q_offset + jnp.arange(q.shape[1])
    ki = k_offset + jnp.arange(k.shape[1])
    band = jnp.abs(qi[:, None] - ki[None, :]) <= attn_win_size
    s = jnp.where(band[None, None], s, _NEG_INF)
  return s


def ring_attention(
    q: Array,
    k: Array,
    v: Array,
    axis_name: str,
    attn_win_size: Optional[int] = None,
) -> Array:
  """Exact attention with K/V rotating around `axis_name`.

  Inside shard_map: q/k/v are the local shards [B, L_local, H, D]; the
  global sequence is the concatenation over the axis in index order.
  Returns the local output shard [B, L_local, H, D].
  """
  axis_size = jax.lax.psum(1, axis_name)
  my_index = jax.lax.axis_index(axis_name)
  l_local = q.shape[1]
  b, _, h, d = q.shape

  q_offset = my_index * l_local

  # Online softmax state, marked device-varying (see _mark_varying).
  m = _mark_varying(
      jnp.full((b, h, l_local), _NEG_INF, q.dtype), axis_name
  )  # running max
  l_sum = _mark_varying(
      jnp.zeros((b, h, l_local), q.dtype), axis_name
  )  # running denominator
  o = _mark_varying(
      jnp.zeros((b, l_local, h, d), q.dtype), axis_name
  )  # running numerator

  perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

  def step(carry, block_idx):
    k_cur, v_cur, m, l_sum, o = carry
    # K/V block `block_idx` steps behind this device's shard.
    k_owner = (my_index - block_idx) % axis_size
    k_offset = k_owner * l_local
    s = _block_attention(q, k_cur, v_cur, q_offset, k_offset, attn_win_size)
    m_block = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_block)
    # Renormalize previous accumulators.
    scale = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l_sum * scale + jnp.sum(p, axis=-1)
    o_new = (
        o * jnp.transpose(scale, (0, 2, 1))[..., None]
        + jnp.einsum('bhqk,bkhd->bqhd', p, v_cur)
    )
    k_next = jax.lax.ppermute(k_cur, axis_name, perm)
    v_next = jax.lax.ppermute(v_cur, axis_name, perm)
    return (k_next, v_next, m_new, l_new, o_new), None

  (k, v, m, l_sum, o), _ = jax.lax.scan(
      step, (k, v, m, l_sum, o), jnp.arange(axis_size)
  )
  denom = jnp.transpose(l_sum, (0, 2, 1))[..., None]
  return o / jnp.maximum(denom, 1e-30)


def ring_attention_blockwise(
    q: Array,
    k: Array,
    v: Array,
    attn_win_size: Optional[int] = None,
    block_size: int = 128,
) -> Array:
  """Single-device ring attention: K/V stream through the online
  softmax in blocks instead of rotating over a mesh axis.

  The degenerate ring (axis_size = ceil(L / block_size), identity
  permutation) keeps queries resident and accumulates flash-style
  partial softmaxes per K/V block, so the [B, H, L, L] logits tensor is
  never materialized — peak activation memory is O(L * block_size) per
  head. This is the training forward for windows past the fused
  kernel's VMEM limit (the L=500 long-insert bucket): a plain lax.scan
  of differentiable ops, so gradients flow through it with no custom
  VJP.

  Fully-banded-out (q, k-block) rows self-heal exactly as in
  ring_attention: their running max stays _NEG_INF, and the first real
  block rescales the junk accumulator by exp(_NEG_INF - m_real) == 0.

  q, k, v: [B, L, H, D] -> [B, L, H, D]. Like ring_attention, scores
  are scaled by D**-0.5 internally — pass the unscaled query.
  """
  global n_blockwise_traces
  n_blockwise_traces += 1
  b, l, h, d = q.shape
  block = int(min(block_size, l))
  n_blocks = -(-l // block)
  pad = n_blocks * block - l
  k_p = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
  v_p = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
  k_blocks = jnp.moveaxis(k_p.reshape(b, n_blocks, block, h, d), 1, 0)
  v_blocks = jnp.moveaxis(v_p.reshape(b, n_blocks, block, h, d), 1, 0)
  k_offsets = jnp.arange(n_blocks) * block

  m0 = jnp.full((b, h, l), _NEG_INF, q.dtype)
  l0 = jnp.zeros((b, h, l), q.dtype)
  o0 = jnp.zeros((b, l, h, d), q.dtype)

  def step(carry, xs):
    m, l_sum, o = carry
    k_cur, v_cur, k_off = xs
    s = _block_attention(q, k_cur, v_cur, jnp.asarray(0), k_off,
                         attn_win_size)
    # Padded key slots (global index >= L) are masked out regardless of
    # the band so the pad never enters any softmax.
    valid = (k_off + jnp.arange(block)) < l
    s = jnp.where(valid[None, None, None, :], s, _NEG_INF)
    m_block = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_block)
    scale = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l_sum * scale + jnp.sum(p, axis=-1)
    o_new = (
        o * jnp.transpose(scale, (0, 2, 1))[..., None]
        + jnp.einsum('bhqk,bkhd->bqhd', p, v_cur)
    )
    return (m_new, l_new, o_new), None

  (_, l_sum, o), _ = jax.lax.scan(
      step, (m0, l0, o0), (k_blocks, v_blocks, k_offsets)
  )
  denom = jnp.transpose(l_sum, (0, 2, 1))[..., None]
  return o / jnp.maximum(denom, 1e-30)


def ring_attention_sharded(
    q: Array,
    k: Array,
    v: Array,
    mesh: Mesh,
    seq_axis: str,
    attn_win_size: Optional[int] = None,
) -> Array:
  """Global-view wrapper: shards [B, L, H, D] on L over `seq_axis`."""
  spec = P(None, seq_axis, None, None)
  fn = functools.partial(
      ring_attention, axis_name=seq_axis, attn_win_size=attn_win_size
  )
  return shard_map(
      fn,
      mesh=mesh,
      in_specs=(spec, spec, spec),
      out_specs=spec,
  )(q, k, v)


def full_attention_reference(
    q: Array, k: Array, v: Array, attn_win_size: Optional[int] = None
) -> Array:
  """Single-device reference for testing."""
  s = _block_attention(q, k, v, jnp.asarray(0), jnp.asarray(0),
                       attn_win_size)
  w = jax.nn.softmax(s, axis=-1)
  return jnp.einsum('bhqk,bkhd->bqhd', w, v)
