"""Declarative regex partition rules for the training state.

One rule table maps parameter-path regexes to PartitionSpecs and is
shared by train, eval, and distill (and, through mesh.param_shardings,
by inference loading). Rules are matched with re.search over
'/'-joined key paths, first match wins, and every non-scalar leaf MUST
match some rule — an unmatched leaf raises a typed error instead of
silently replicating, so adding a parameter family to the model forces
a sharding decision.

Because the optimizer state (optax LAMB's mu/nu moments) mirrors the
parameter tree, its leaf paths CONTAIN the parameter paths
('opt_state/.../mu/encoder/.../kernel'), and the same re.search rules
shard the moments exactly like their parameters — the property pjit
needs for a donated, fully-sharded update step. Scalars (step counters,
schedule state) always get P().
"""
from __future__ import annotations

import logging
import re
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = 'data'
MODEL_AXIS = 'model'


class PartitionRuleError(ValueError):
  """A leaf path matched no partition rule (or a rule table problem).

  Typed so tests and callers can distinguish a coverage hole in the
  rule table from generic config errors; the message carries the
  offending path so the fix is one added rule."""


# The declarative rule table. Kernel layouts: DenseGeneral qkv
# [E, N, H] shards heads; output_transform [N, H, E] shards heads; FFN
# filter [E, F] / [F, E] shards the filter dim. The trailing catch-all
# replicates everything else — remove it to surface unmatched leaves.
DEFAULT_RULES: Tuple[Tuple[str, P], ...] = (
    (r'self_attention[^/]*/(query|key|value)/kernel',
     P(None, MODEL_AXIS, None)),
    (r'self_attention[^/]*/output_transform/kernel',
     P(MODEL_AXIS, None, None)),
    (r'ffn_\d+/filter_layer/kernel', P(None, MODEL_AXIS)),
    (r'ffn_\d+/filter_layer/bias', P(MODEL_AXIS)),
    (r'ffn_\d+/output_layer/kernel', P(MODEL_AXIS, None)),
    (r'.*', P()),
)


def _path_str(path) -> str:
  return '/'.join(
      getattr(k, 'key', getattr(k, 'name', str(k))) for k in path
  )


def _is_scalar_leaf(leaf) -> bool:
  return np.ndim(leaf) == 0


def match_partition_rules(rules: Sequence[Tuple[str, P]], tree):
  """PartitionSpec tree for `tree` via first-match re.search rules.

  Scalar leaves get P() without consulting the table (an int step
  count should never be forced to match a kernel rule). Every
  non-scalar leaf must match exactly one rule — the FIRST whose regex
  re.search-matches its '/'-joined path; no match raises
  PartitionRuleError naming the path.
  """
  flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
  specs = []
  for path, leaf in flat:
    if _is_scalar_leaf(leaf):
      specs.append(P())
      continue
    name = _path_str(path)
    for pattern, spec in rules:
      if re.search(pattern, name):
        specs.append(spec)
        break
    else:
      raise PartitionRuleError(
          f'partition rule not found for param: {name!r} (shape '
          f'{np.shape(leaf)}); extend the rule table or keep the '
          f"catch-all ('.*', P()) as the last rule")
  return jax.tree_util.tree_unflatten(treedef, specs)


def explain_matches(rules: Sequence[Tuple[str, P]], tree):
  """{leaf path: index of the (single) rule that matched} — the
  round-trip observability hook tests assert exactly-once matching
  with. Scalar leaves are reported with rule index -1."""
  flat, _ = jax.tree_util.tree_flatten_with_path(tree)
  out = {}
  for path, leaf in flat:
    name = _path_str(path)
    if _is_scalar_leaf(leaf):
      out[name] = -1
      continue
    for i, (pattern, _) in enumerate(rules):
      if re.search(pattern, name):
        out[name] = i
        break
    else:
      raise PartitionRuleError(
          f'partition rule not found for param: {name!r}')
  return out


def _divisible(leaf, spec: P, mesh: Mesh) -> bool:
  shape = np.shape(leaf)
  for dim, axis in zip(shape, spec):
    if axis is None:
      continue
    axes = axis if isinstance(axis, tuple) else (axis,)
    n = 1
    for a in axes:
      n *= mesh.shape[a]
    if n and dim % n != 0:
      return False
  return True


def tree_shardings(mesh: Mesh, tree,
                   rules: Optional[Sequence[Tuple[str, P]]] = None):
  """NamedSharding tree for any state pytree under the rule table.

  Applies match_partition_rules and lowers each spec to a
  NamedSharding, guarding divisibility: a leaf whose sharded dims do
  not divide the mesh axis replicates instead — loudly, because a
  silent fallback would degrade tp>1 to pure DP with no signal.
  """
  rules = DEFAULT_RULES if rules is None else rules
  specs = match_partition_rules(rules, tree)
  flat_specs, treedef = jax.tree_util.tree_flatten(
      specs, is_leaf=lambda x: isinstance(x, P))
  flat_leaves = jax.tree_util.tree_leaves(tree)
  shardings = []
  for leaf, spec in zip(flat_leaves, flat_specs):
    if not _divisible(leaf, spec, mesh):
      logging.getLogger(__name__).warning(
          'param (shape %s) not divisible by the mesh along %s; '
          'replicating instead', np.shape(leaf), spec)
      spec = P()
    shardings.append(NamedSharding(mesh, spec))
  return jax.tree_util.tree_unflatten(treedef, shardings)


def compile_parallel(fn, *, in_shardings=None, out_shardings=None,
                     donate_argnums=(), static_argnums=()):
  """Compile an SPMD step: pjit when explicit shardings are provided.

  jax.jit with explicit in/out shardings IS pjit in modern JAX; this
  helper keeps the choice in one place. shard_map would be the
  alternative when per-device code (manual collectives) is needed —
  nothing in the train/eval/distill steps is, so the helper always
  takes the pjit path and exists so a future manual-collective step
  changes one function instead of three call sites.
  """
  kwargs = {}
  if in_shardings is not None:
    kwargs['in_shardings'] = in_shardings
  if out_shardings is not None:
    kwargs['out_shardings'] = out_shardings
  if donate_argnums:
    kwargs['donate_argnums'] = donate_argnums
  if static_argnums:
    kwargs['static_argnums'] = static_argnums
  return jax.jit(fn, **kwargs)
