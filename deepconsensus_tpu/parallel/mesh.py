"""Device meshes and sharding rules.

The reference distributes with tf.distribute (TPUStrategy/
MirroredStrategy, reference: models/model_train_custom_loop.py:333-343);
here distribution is SPMD over a jax.sharding.Mesh: data parallelism
shards the batch axis, tensor parallelism shards attention heads and the
FFN filter dimension, and XLA inserts the ICI collectives. Multi-host
runs use the same code path via jax.distributed initialization.
"""
from __future__ import annotations

import logging
import re
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = 'data'
MODEL_AXIS = 'model'


def make_mesh(
    dp: Optional[int] = None,
    tp: int = 1,
    devices=None,
) -> Mesh:
  """Builds a (data, model) mesh over the available devices."""
  devices = devices if devices is not None else jax.devices()
  n = len(devices)
  if dp is None:
    if n % tp:
      raise ValueError(f'{n} devices not divisible by tp={tp}')
    dp = n // tp
  if dp * tp != n:
    raise ValueError(f'dp*tp = {dp*tp} != {n} devices')
  arr = np.asarray(devices).reshape(dp, tp)
  return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
  return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
  """Shard the leading (batch) axis across the data axis."""
  return NamedSharding(mesh, P(DATA_AXIS))


# Rules mapping parameter path regexes to PartitionSpecs. Kernel layouts:
# DenseGeneral qkv [E, N, H] shards heads; output_transform [N, H, E]
# shards heads; FFN filter [E, F] / [F, E] shards the filter dim.
_PARAM_RULES: Tuple[Tuple[str, P], ...] = (
    (r'.*self_attention.*/(query|key|value)/kernel', P(None, MODEL_AXIS, None)),
    (r'.*self_attention.*/output_transform/kernel', P(MODEL_AXIS, None, None)),
    (r'.*ffn_\d+/filter_layer/kernel', P(None, MODEL_AXIS)),
    (r'.*ffn_\d+/filter_layer/bias', P(MODEL_AXIS)),
    (r'.*ffn_\d+/output_layer/kernel', P(MODEL_AXIS, None)),
)


def _spec_for_path(path: str) -> P:
  for pattern, spec in _PARAM_RULES:
    if re.fullmatch(pattern, path):
      return spec
  return P()


def param_shardings(mesh: Mesh, params):
  """NamedSharding tree for a parameter pytree.

  Attention heads and FFN filter dims shard over the model axis; all
  other parameters replicate. With tp=1 meshes every spec degenerates
  to replication, so the same code serves pure-DP runs.
  """
  flat, treedef = jax.tree_util.tree_flatten_with_path(params)
  shardings = []
  for path, leaf in flat:
    path_str = '/'.join(
        getattr(k, 'key', getattr(k, 'name', str(k))) for k in path
    )
    spec = _spec_for_path(path_str)
    # Guard: only shard if dims divide; otherwise replicate (loudly —
    # a silent fallback would degrade tp>1 to pure DP with no signal).
    ok = True
    for dim, axis in zip(leaf.shape, spec):
      if axis is not None and dim % mesh.shape[MODEL_AXIS] != 0:
        ok = False
    if not ok:
      logging.getLogger(__name__).warning(
          'param %s (shape %s) not divisible by tp=%d along %s; '
          'replicating instead', path_str, leaf.shape,
          mesh.shape[MODEL_AXIS], spec,
      )
    shardings.append(NamedSharding(mesh, spec if ok else P()))
  return jax.tree_util.tree_unflatten(treedef, shardings)


def count_model_sharded(shardings) -> int:
  """Number of params actually sharded on the model axis (observability
  for tp>1 runs; see dryrun_multichip's assertion)."""
  flat, _ = jax.tree_util.tree_flatten(
      shardings, is_leaf=lambda x: isinstance(x, NamedSharding)
  )
  return sum(
      1 for s in flat
      if isinstance(s, NamedSharding) and MODEL_AXIS in str(s.spec)
  )
