"""Device meshes and sharding rules.

The reference distributes with tf.distribute (TPUStrategy/
MirroredStrategy, reference: models/model_train_custom_loop.py:333-343);
here distribution is SPMD over a jax.sharding.Mesh: data parallelism
shards the batch axis, tensor parallelism shards attention heads and the
FFN filter dimension, and XLA inserts the ICI collectives. Multi-host
runs use the same code path via jax.distributed initialization.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepconsensus_tpu.parallel import partition_rules

DATA_AXIS = 'data'
MODEL_AXIS = 'model'


def make_mesh(
    dp: Optional[int] = None,
    tp: int = 1,
    devices=None,
) -> Mesh:
  """Builds a (data, model) mesh over the available devices."""
  devices = devices if devices is not None else jax.devices()
  n = len(devices)
  if dp is None:
    if n % tp:
      raise ValueError(f'{n} devices not divisible by tp={tp}')
    dp = n // tp
  if dp * tp != n:
    raise ValueError(f'dp*tp = {dp*tp} != {n} devices')
  arr = np.asarray(devices).reshape(dp, tp)
  return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def local_mesh(tp: int = 1) -> Mesh:
  """A mesh over THIS host's devices only. The jit-visible mesh of an
  elastic pod member: cross-host reduction happens at host level
  through `parallel/elastic.py` step_sync, so the compiled step never
  spans processes and a lost host can never wedge a collective inside
  XLA."""
  return make_mesh(tp=tp, devices=jax.local_devices())


def replicated(mesh: Mesh) -> NamedSharding:
  return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
  """Shard the leading (batch) axis across the data axis."""
  return NamedSharding(mesh, P(DATA_AXIS))


# The declarative regex rule table now lives in partition_rules.py and
# is shared by train, eval, distill, and the inference loaders; this
# alias keeps the historical import site working.
_PARAM_RULES = partition_rules.DEFAULT_RULES


def param_shardings(mesh: Mesh, params):
  """NamedSharding tree for a parameter pytree.

  Attention heads and FFN filter dims shard over the model axis; all
  other parameters replicate (the trailing catch-all rule). With tp=1
  meshes every spec degenerates to replication, so the same code
  serves pure-DP runs. Delegates to the shared declarative rule table
  (partition_rules.DEFAULT_RULES), which also shards the full training
  state — params here, plus optimizer moments in train.py.
  """
  return partition_rules.tree_shardings(mesh, params)


def count_model_sharded(shardings) -> int:
  """Number of params actually sharded on the model axis (observability
  for tp>1 runs; see dryrun_multichip's assertion)."""
  flat, _ = jax.tree_util.tree_flatten(
      shardings, is_leaf=lambda x: isinstance(x, NamedSharding)
  )
  return sum(
      1 for s in flat
      if isinstance(s, NamedSharding) and MODEL_AXIS in str(s.spec)
  )
