"""Stitching model-output windows back into full reads.

Equivalent of the reference's postprocess stage (reference:
deepconsensus/postprocess/stitch_utils.py:39-189): concatenate sorted
windows, fail (or N-fill) on missing windows, strip gap columns, apply
quality/length filters, and emit FASTQ text.
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Iterable, Optional, Tuple

import numpy as np

from deepconsensus_tpu import constants
from deepconsensus_tpu.utils import phred

log = logging.getLogger(__name__)


@dataclasses.dataclass
class DCModelOutput:
  molecule_name: str
  window_pos: int
  ec: Optional[float] = None
  np_num_passes: Optional[int] = None
  rq: Optional[float] = None
  rg: Optional[str] = None
  sequence: Optional[str] = None
  quality_string: Optional[str] = None


@dataclasses.dataclass
class OutcomeCounter:
  empty_sequence: int = 0
  only_gaps: int = 0
  failed_quality_filter: int = 0
  failed_length_filter: int = 0
  success: int = 0


def get_full_sequence(
    outputs: Iterable[DCModelOutput],
    max_length: int,
    fill_n: bool = False,
) -> Tuple[Optional[str], str]:
  """Concatenates sorted windows; missing windows fail the read unless
  fill_n pads them with Ns (reference: stitch_utils.py:51-81)."""
  sequence_parts = []
  quality_parts = []
  start = 0
  for out in outputs:
    while out.window_pos > start:
      if not fill_n:
        return None, ''
      sequence_parts.append('N' * max_length)
      quality_parts.append(
          phred.quality_scores_to_string([constants.EMPTY_QUAL] * max_length)
      )
      start += max_length
    sequence_parts.append(out.sequence)
    quality_parts.append(out.quality_string)
    start += max_length
  return ''.join(sequence_parts), ''.join(quality_parts)


def remove_gaps(sequence: str, quality_string: str) -> Tuple[str, str]:
  """Drops gap columns and their quality values."""
  seq = np.frombuffer(sequence.encode('ascii'), dtype=np.uint8)
  qual = np.frombuffer(quality_string.encode('ascii'), dtype=np.uint8)
  keep = seq != ord(constants.GAP)
  return (
      seq[keep].tobytes().decode('ascii'),
      qual[keep].tobytes().decode('ascii'),
  )


def is_quality_above_threshold(quality_string: str, min_quality: int) -> bool:
  scores = phred.quality_string_to_array(quality_string)
  # Round to dodge float noise right at the threshold
  # (reference: stitch_utils.py:101-109).
  return round(phred.avg_phred(scores), 5) >= min_quality


def format_as_fastq(name: str, sequence: str, quality_string: str) -> str:
  return f'@{name}\n{sequence}\n+\n{quality_string}\n'


def fallback_to_arrays(
    molecule_name: str,
    sequence: str,
    quality_scores,
    min_quality: int,
    min_length: int,
    max_base_quality: int,
    counter,
) -> Optional[Tuple[bytes, np.ndarray]]:
  """Array-native core of fallback_to_fastq: gates a quarantined ZMW's
  draft CCS read and returns (sequence bytes, phred uint8 array), or
  None when filtered. Counted under n_fallback_* keys — deliberately
  not OutcomeCounter, so `success` keeps meaning "model-polished reads"
  and fallback yield stays separately accountable."""
  del molecule_name  # kept for call-site symmetry with stitch_arrays
  if not sequence:
    counter['n_fallback_empty'] += 1
    return None
  quals = np.clip(
      np.asarray(quality_scores, dtype=np.int64), 0, max_base_quality
  )
  if round(phred.avg_phred(quals), 5) < min_quality:
    counter['n_fallback_failed_quality_filter'] += 1
    return None
  if len(sequence) < min_length:
    counter['n_fallback_failed_length_filter'] += 1
    return None
  counter['n_fallback_emitted'] += 1
  return sequence.encode('ascii'), quals.astype(np.uint8)


def fallback_to_fastq(
    molecule_name: str,
    sequence: str,
    quality_scores,
    min_quality: int,
    min_length: int,
    max_base_quality: int,
    counter,
) -> Optional[str]:
  """String-plane wrapper over fallback_to_arrays (legacy API)."""
  result = fallback_to_arrays(
      molecule_name, sequence, quality_scores, min_quality, min_length,
      max_base_quality, counter,
  )
  if result is None:
    return None
  seq_bytes, quals = result
  return format_as_fastq(
      molecule_name, seq_bytes.decode('ascii'),
      phred.quality_scores_to_string(quals),
  )


def stitch_to_fastq(
    molecule_name: str,
    predictions: Iterable[DCModelOutput],
    max_length: int,
    min_quality: int,
    min_length: int,
    outcome_counter: OutcomeCounter,
) -> Optional[str]:
  """Stitch + filter + format one molecule
  (reference: stitch_utils.py:131-189)."""
  full_seq, full_qual = get_full_sequence(predictions, max_length)
  if not full_seq:
    outcome_counter.empty_sequence += 1
    return None
  final_seq, final_qual = remove_gaps(full_seq, full_qual)
  if not final_seq:
    outcome_counter.only_gaps += 1
    return None
  if not is_quality_above_threshold(final_qual, min_quality):
    outcome_counter.failed_quality_filter += 1
    return None
  if len(final_seq) < min_length:
    outcome_counter.failed_length_filter += 1
    return None
  outcome_counter.success += 1
  return format_as_fastq(molecule_name, final_seq, final_qual)


def stitch_arrays(
    molecule_name: str,
    window_pos: np.ndarray,
    ids: np.ndarray,
    quals: np.ndarray,
    max_length: int,
    min_quality: int,
    min_length: int,
    outcome_counter: OutcomeCounter,
) -> Optional[Tuple[bytes, np.ndarray]]:
  """Array-native stitch_to_fastq: one molecule's windows as contiguous
  arrays in, (sequence ASCII bytes, phred uint8 array) out.

  window_pos: [n] window start offsets; ids: [n, L] vocab-id uint8 —
  or, for bucketed variable-length windows, a sequence of n 1-D uint8
  arrays with per-window lengths; quals likewise. The gap strip,
  quality gate, and ASCII conversion are each a single vectorized pass
  — no per-window Python objects or intermediate strings. Filter
  semantics (and counter attribution) match stitch_to_fastq exactly,
  including the legacy missing-window rule generalized to ragged rows:
  sorted window k must not start past the cumulative capacity of the
  windows before it (for uniform L=max_length rows that is exactly the
  legacy k * max_length bound, so fixed-shape output is byte-identical).
  """
  del molecule_name  # name formatting happens at the emit sink
  n = len(window_pos)
  order = np.argsort(window_pos, kind='stable')
  pos = np.asarray(window_pos)[order]
  if isinstance(ids, np.ndarray) and ids.dtype != object:
    lengths = np.full(n, ids.shape[1] if ids.ndim > 1 else 0,
                      dtype=np.int64)
  else:
    ids = [np.asarray(w) for w in ids]
    quals = [np.asarray(w) for w in quals]
    lengths = np.array([len(ids[i]) for i in order], dtype=np.int64)
  capacity = np.zeros(n, dtype=np.int64)
  if n:
    np.cumsum(lengths[:-1], out=capacity[1:])
  if n == 0 or np.any(pos > capacity):
    outcome_counter.empty_sequence += 1
    return None
  if isinstance(ids, np.ndarray):
    flat_ids = np.ascontiguousarray(ids[order]).reshape(-1)
    flat_quals = np.ascontiguousarray(quals[order]).reshape(-1)
  else:
    flat_ids = np.concatenate([ids[i] for i in order])
    flat_quals = np.concatenate([quals[i] for i in order])
  keep = flat_ids != constants.GAP_INT
  flat_ids = flat_ids[keep]
  if flat_ids.size == 0:
    outcome_counter.only_gaps += 1
    return None
  flat_quals = flat_quals[keep]
  if round(phred.avg_phred(flat_quals), 5) < min_quality:
    outcome_counter.failed_quality_filter += 1
    return None
  if flat_ids.size < min_length:
    outcome_counter.failed_length_filter += 1
    return None
  outcome_counter.success += 1
  return phred.encoded_sequence_to_bytes(flat_ids), flat_quals


def format_fastq_bytes(name: str, seq: bytes, quals: np.ndarray) -> bytes:
  """(name, sequence bytes, phred uint8) -> one FASTQ record's bytes."""
  return b'@%s\n%s\n+\n%s\n' % (
      name.encode('ascii'), seq, phred.quality_scores_to_bytes(quals)
  )
