from deepconsensus_tpu.postprocess.stitch import (  # noqa: F401
    DCModelOutput,
    OutcomeCounter,
    stitch_to_fastq,
)
