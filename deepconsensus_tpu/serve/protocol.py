"""Wire format for `dctpu serve`: npz request/response bodies.

One POST /v1/polish body carries one molecule's featurized windows
(the client runs preprocessing; the server owns triage + model +
stitch so serve output is byte-identical to the batch pipeline's).
npz keeps the bulk float32 tensors out of JSON and decodes with
allow_pickle=False, so a request body can never smuggle arbitrary
objects. Every field is validated against the loaded model's shapes
BEFORE the request is admitted — an oversized or malformed body is a
typed 4xx, not server memory growth (same posture as the PR-4 bounded
decoders).

Request arrays:
  subreads    float32 [n, total_rows, L, 1]
  window_pos  int64   [n]
  ccs_bq      int32   [n, L]   (draft CCS base qualities)
  overflow    uint8   [n]

where L must be one of the model's window length buckets
(params.window_buckets; max_length alone when bucketing is off). npz
arrays are rectangular, so one request carries one width; clients with
different window lengths share the server's per-bucket packs
concurrently.
  name        0-d str (molecule name)
  meta_json   0-d str (optional: ec / np_num_passes / rq / rg)

Response arrays (application/octet-stream):
  status      0-d str: ok | fallback | filtered | quarantined
  seq         uint8 [len]  (ascii bases; empty unless ok/fallback)
  quals       uint8 [len]  (phred values, not ascii)
  counters_json  0-d str   (per-request triage/window counters)
  error       0-d str      (quarantine detail; empty otherwise)

Versioned frames (fleet tier). A body with no `frame` field is the
legacy float32 request above — old clients keep working unchanged.
New bodies carry a 0-d `frame` string naming format+version:

  features/1  compact uint8 window pack (featurize tier -> model
              replica): every non-SN row of the float32 tensor holds
              clip-bounded integers (ccs_bq ships biased +1 so its -1
              pad sentinels survive the uint8 cast) and the 4 SN rows
              are per-window constants, so the bulk tensor ships as
              main_u8 uint8 [n, total_rows-4, L, 1] + sn float32
              [n, 4] (~4x fewer bytes) and reconstructs losslessly.
  bam/1       raw-BAM request (client -> featurize tier): whole
              mini-BAM file bytes for one molecule's subreads + draft
              CCS; a featurize worker runs decode/pileup on it.

A server that doesn't recognize a frame answers a typed 400 naming
the frames it speaks — version negotiation is an error message, not a
parse crash (an old server predating `frame` rejects a features/1
body with its ordinary missing-field 400 for the same reason).

Errors travel as application/json: {"error", "kind", "status"}.
"""
from __future__ import annotations

import io
import json
from typing import Any, Dict, Optional

import numpy as np

from deepconsensus_tpu import faults as faults_lib

CONTENT_TYPE = 'application/octet-stream'
DEADLINE_HEADER = 'X-Dctpu-Deadline-S'
# Request/trace id minted at the outermost tier (router for fleet
# traffic) and carried across every hop so spans from router,
# featurize worker and replica join into one trace (obs.trace).
TRACE_HEADER = 'X-Dctpu-Trace-Id'
# Multi-tenant QoS (fleet tier). CLASS_HEADER names the priority class
# the request is admitted under ('interactive', 'bulk', ...; lowercase
# [a-z0-9_-], ≤32 chars — anything else is a typed 400). CLIENT_HEADER
# is the tenant id per-client quotas are charged against; absent, the
# router falls back to the peer address. Both are advisory to a bare
# replica (it serves FIFO) — the router is where weighted-fair
# admission happens.
CLASS_HEADER = 'X-Dctpu-Class'
CLIENT_HEADER = 'X-Dctpu-Client'
REQUEST_FIELDS = ('name', 'subreads', 'window_pos', 'ccs_bq', 'overflow')
_META_KEYS = ('ec', 'np_num_passes', 'rq', 'rg')

FRAME_FEATURES = 'features/1'
FRAME_BAM = 'bam/1'
# Frames a model replica's decode_request speaks (bam/1 is understood
# but redirected: it belongs to the featurize tier).
KNOWN_FRAMES = (FRAME_FEATURES, FRAME_BAM)
FEATURES_FIELDS = ('name', 'main_u8', 'sn', 'window_pos', 'ccs_bq',
                   'overflow')
BAM_FIELDS = ('name', 'subreads_bam', 'ccs_bam')
_SN_ROWS = 4  # trailing per-window SN constant rows (preprocess.pileup)


def _bq_row_for_total_rows(total_rows: int) -> Optional[int]:
  """ccs_bq row index within the non-SN block, derived from the row
  count alone: total_rows is 4*max_passes+5 without a ccs_bq row and
  4*max_passes+6 with one, so total_rows mod 4 (1 vs 2) disambiguates
  and both encode and decode agree without shipping layout metadata."""
  if total_rows % 4 == 2:
    max_passes = (total_rows - 6) // 4
    return 4 * max_passes + 1
  return None


def encode_request(name: str, subreads: np.ndarray,
                   window_pos: np.ndarray, ccs_bq: np.ndarray,
                   overflow: np.ndarray,
                   meta: Optional[Dict[str, Any]] = None) -> bytes:
  buf = io.BytesIO()
  np.savez(
      buf,
      name=np.array(str(name)),
      subreads=np.asarray(subreads, dtype=np.float32),
      window_pos=np.asarray(window_pos, dtype=np.int64),
      ccs_bq=np.asarray(ccs_bq, dtype=np.int32),
      overflow=np.asarray(overflow, dtype=np.uint8),
      meta_json=np.array(json.dumps(
          {k: meta[k] for k in _META_KEYS if meta and meta.get(k) is not None}
      )),
  )
  return buf.getvalue()


def request_from_features(features) -> bytes:
  """Builds a request body from one molecule's preprocess window
  feature dicts (runner.preprocess_zmw output)."""
  fd0 = features[0]
  name = fd0['name'] if isinstance(fd0['name'], str) else fd0['name'].decode()
  return encode_request(
      name=name,
      subreads=np.stack([fd['subreads'] for fd in features]),
      window_pos=np.array([fd['window_pos'] for fd in features]),
      ccs_bq=np.stack(
          [np.asarray(fd['ccs_base_quality_scores']) for fd in features]),
      overflow=np.array([bool(fd['overflow']) for fd in features]),
      meta={k: fd0.get(k) for k in _META_KEYS},
  )


def features_pack_from_features(features) -> Optional[bytes]:
  """Compact features/1 body from one molecule's preprocess window
  feature dicts, or None when the tensor is not losslessly uint8-
  packable (non-integral or out-of-range values, SN rows that are not
  per-window constants) — callers fall back to request_from_features,
  so packing is an optimization, never a correctness risk."""
  fd0 = features[0]
  name = fd0['name'] if isinstance(fd0['name'], str) else fd0['name'].decode()
  subreads = np.stack(
      [fd['subreads'] for fd in features]).astype(np.float32, copy=False)
  body = encode_features_pack(
      name=name,
      subreads=subreads,
      window_pos=np.array([fd['window_pos'] for fd in features]),
      ccs_bq=np.stack(
          [np.asarray(fd['ccs_base_quality_scores']) for fd in features]),
      overflow=np.array([bool(fd['overflow']) for fd in features]),
      meta={k: fd0.get(k) for k in _META_KEYS},
  )
  return body


def encode_features_pack(name: str, subreads: np.ndarray,
                         window_pos: np.ndarray, ccs_bq: np.ndarray,
                         overflow: np.ndarray,
                         meta: Optional[Dict[str, Any]] = None
                         ) -> Optional[bytes]:
  """Encodes the float32 window tensor as a features/1 compact pack,
  or returns None when the split would be lossy (see
  features_pack_from_features)."""
  subreads = np.asarray(subreads, dtype=np.float32)
  if subreads.ndim != 4 or subreads.shape[1] <= _SN_ROWS:
    return None
  sn_block = subreads[:, -_SN_ROWS:]
  if not (sn_block == sn_block[:, :, :1, :]).all():
    return None
  main = np.array(subreads[:, :-_SN_ROWS])
  bq_row = _bq_row_for_total_rows(subreads.shape[1])
  if bq_row is not None:
    main[:, bq_row] += 1.0
  if main.size and (main.min() < 0.0 or main.max() > 255.0):
    return None
  main_u8 = main.astype(np.uint8)
  if not np.array_equal(main_u8.astype(np.float32), main):
    return None  # non-integral values would round
  buf = io.BytesIO()
  np.savez(
      buf,
      frame=np.array(FRAME_FEATURES),
      name=np.array(str(name)),
      main_u8=main_u8,
      sn=np.ascontiguousarray(sn_block[:, :, 0, 0].astype(np.float32)),
      window_pos=np.asarray(window_pos, dtype=np.int64),
      ccs_bq=np.asarray(ccs_bq, dtype=np.int32),
      overflow=np.asarray(overflow, dtype=np.uint8),
      meta_json=np.array(json.dumps(
          {k: meta[k] for k in _META_KEYS if meta and meta.get(k) is not None}
      )),
  )
  return buf.getvalue()


def encode_bam_request(subreads_bam: bytes, ccs_bam: bytes,
                       name: str = '',
                       meta: Optional[Dict[str, Any]] = None) -> bytes:
  """bam/1 body: whole mini-BAM file bytes for one molecule (subreads
  aligned to the draft CCS, plus the draft CCS itself). The featurize
  tier owns decoding them with the hardened io.bam readers."""
  buf = io.BytesIO()
  np.savez(
      buf,
      frame=np.array(FRAME_BAM),
      name=np.array(str(name)),
      subreads_bam=np.frombuffer(subreads_bam, dtype=np.uint8),
      ccs_bam=np.frombuffer(ccs_bam, dtype=np.uint8),
      meta_json=np.array(json.dumps(
          {k: meta[k] for k in _META_KEYS if meta and meta.get(k) is not None}
      )),
  )
  return buf.getvalue()


def decode_bam_request(body: bytes) -> Dict[str, Any]:
  """Parses a bam/1 body (featurize-worker side). Size bounds are the
  HTTP layer's max_body_bytes; record-level bounds are the BAM
  reader's own max_record_bytes guard."""
  try:
    with np.load(io.BytesIO(body), allow_pickle=False) as z:
      frame = str(z['frame']) if 'frame' in z.files else None
      if frame != FRAME_BAM:
        raise faults_lib.BadRequestError(
            f'featurize worker expects a {FRAME_BAM} frame, got '
            f'{frame or "a legacy polish request"}')
      missing = [f for f in BAM_FIELDS if f not in z.files]
      if missing:
        raise faults_lib.BadRequestError(
            f'{FRAME_BAM} request missing field(s): {missing}')
      name = str(z['name'])
      subreads_bam = z['subreads_bam']
      ccs_bam = z['ccs_bam']
      meta = json.loads(str(z['meta_json'])) if 'meta_json' in z.files else {}
  except faults_lib.BadRequestError:
    raise
  except Exception as e:
    raise faults_lib.BadRequestError(
        f'undecodable request body: {type(e).__name__}: {e}') from e
  if subreads_bam.dtype != np.uint8 or ccs_bam.dtype != np.uint8:
    raise faults_lib.BadRequestError('subreads_bam/ccs_bam must be uint8')
  if subreads_bam.size == 0 or ccs_bam.size == 0:
    raise faults_lib.BadRequestError('empty BAM payload')
  if not isinstance(meta, dict):
    raise faults_lib.BadRequestError('meta_json must encode an object')
  return {
      'name': name,
      'subreads_bam': subreads_bam.tobytes(),
      'ccs_bam': ccs_bam.tobytes(),
      'meta': meta,
  }


def sniff_frame(body: bytes) -> Optional[str]:
  """Reads just the frame tag of a request body (None = legacy float32
  request) without touching the bulk arrays — the router's steering
  decision. Undecodable bodies are a typed 400 here, before any bytes
  are forwarded to a replica."""
  try:
    with np.load(io.BytesIO(body), allow_pickle=False) as z:
      if 'frame' not in z.files:
        return None
      return str(z['frame'])
  except Exception as e:
    raise faults_lib.BadRequestError(
        f'undecodable request body: {type(e).__name__}: {e}') from e


def decode_request(body: bytes, *, total_rows: int, max_length: int,
                   max_windows: int,
                   window_buckets=None) -> Dict[str, Any]:
  """Parses + validates one request body. Raises BadRequestError (400)
  on anything malformed and RequestTooLargeError (413) when the window
  count exceeds the admission cap. window_buckets: allowed window
  lengths (defaults to (max_length,))."""
  allowed = tuple(window_buckets) if window_buckets else (max_length,)
  try:
    with np.load(io.BytesIO(body), allow_pickle=False) as z:
      frame = str(z['frame']) if 'frame' in z.files else None
      if frame == FRAME_BAM:
        raise faults_lib.BadRequestError(
            f'{FRAME_BAM} carries raw BAM bytes; POST it to a dctpu '
            'route front tier or a featurize worker, not to a model '
            'replica')
      if frame is not None and frame != FRAME_FEATURES:
        raise faults_lib.BadRequestError(
            f'unsupported request frame {frame!r}; this server '
            f'speaks the legacy float32 request and {KNOWN_FRAMES}')
      if frame == FRAME_FEATURES:
        missing = [f for f in FEATURES_FIELDS if f not in z.files]
        if missing:
          raise faults_lib.BadRequestError(
              f'{FRAME_FEATURES} request missing field(s): {missing}')
        name = str(z['name'])
        main_u8 = z['main_u8']
        sn = z['sn']
        if main_u8.dtype != np.uint8 or main_u8.ndim != 4:
          raise faults_lib.BadRequestError(
              f'main_u8 must be uint8 [n, rows, L, 1], got '
              f'{main_u8.dtype} {main_u8.shape}')
        if (sn.ndim != 2 or sn.shape != (main_u8.shape[0], _SN_ROWS)
            or not np.issubdtype(sn.dtype, np.floating)):
          raise faults_lib.BadRequestError(
              f'sn must be float [n, {_SN_ROWS}], got {sn.dtype} '
              f'{sn.shape}')
        # Lossless inverse of encode_features_pack: uint8 -> f32, undo
        # the ccs_bq +1 bias, re-broadcast the per-window SN scalars.
        main = main_u8.astype(np.float32)
        bq_row = _bq_row_for_total_rows(main_u8.shape[1] + _SN_ROWS)
        if bq_row is not None:
          main[:, bq_row] -= 1.0
        n_w, _, width_w, _ = main_u8.shape
        subreads = np.concatenate(
            [main,
             np.broadcast_to(
                 np.asarray(sn, dtype=np.float32)[:, :, None, None],
                 (n_w, _SN_ROWS, width_w, 1))],
            axis=1)
      else:
        missing = [f for f in REQUEST_FIELDS if f not in z.files]
        if missing:
          raise faults_lib.BadRequestError(
              f'request missing field(s): {missing}')
        name = str(z['name'])
        subreads = z['subreads']
      window_pos = z['window_pos']
      ccs_bq = z['ccs_bq']
      overflow = z['overflow']
      meta = json.loads(str(z['meta_json'])) if 'meta_json' in z.files else {}
  except faults_lib.BadRequestError:
    raise
  except Exception as e:  # zip/npz framing, bad JSON, disallowed pickle
    raise faults_lib.BadRequestError(
        f'undecodable request body: {type(e).__name__}: {e}') from e
  n = len(subreads)
  if n < 1:
    raise faults_lib.BadRequestError('request carries zero windows')
  if n > max_windows:
    raise faults_lib.RequestTooLargeError(
        f'{n} windows exceeds max_windows_per_request={max_windows}')
  if (subreads.ndim != 4 or subreads.shape[1] != total_rows
      or subreads.shape[2] not in allowed or subreads.shape[3] != 1):
    raise faults_lib.BadRequestError(
        f'subreads shape {subreads.shape} does not match the loaded '
        f'model: expected [n, {total_rows}, L, 1] with window length '
        f'L in {list(allowed)}')
  width = int(subreads.shape[2])
  if window_pos.shape != (n,) or overflow.shape != (n,):
    raise faults_lib.BadRequestError(
        'window_pos/overflow must be [n] aligned with subreads')
  if ccs_bq.shape != (n, width):
    raise faults_lib.BadRequestError(
        f'ccs_bq shape {ccs_bq.shape} != [n, {width}]')
  if not np.isfinite(subreads).all():
    raise faults_lib.BadRequestError('subreads contains non-finite values')
  if not isinstance(meta, dict):
    raise faults_lib.BadRequestError('meta_json must encode an object')
  return {
      'name': name,
      'subreads': subreads.astype(np.float32, copy=False),
      'window_pos': window_pos.astype(np.int64, copy=False),
      'ccs_bq': ccs_bq.astype(np.int32, copy=False),
      'overflow': overflow.astype(bool, copy=False),
      'meta': tuple(meta.get(k) for k in _META_KEYS),
  }


def encode_response(status: str, seq: bytes = b'',
                    quals: Optional[np.ndarray] = None,
                    counters: Optional[Dict[str, Any]] = None,
                    error: str = '') -> bytes:
  buf = io.BytesIO()
  np.savez(
      buf,
      status=np.array(status),
      seq=np.frombuffer(seq, dtype=np.uint8),
      quals=(np.asarray(quals, dtype=np.uint8) if quals is not None
             else np.zeros(0, dtype=np.uint8)),
      counters_json=np.array(json.dumps(counters or {})),
      error=np.array(error[:4000]),
  )
  return buf.getvalue()


def decode_response(body: bytes) -> Dict[str, Any]:
  with np.load(io.BytesIO(body), allow_pickle=False) as z:
    return {
        'status': str(z['status']),
        'seq': z['seq'].tobytes(),
        'quals': np.array(z['quals']),
        'counters': json.loads(str(z['counters_json'])),
        'error': str(z['error']),
    }
