"""Wire format for `dctpu serve`: npz request/response bodies.

One POST /v1/polish body carries one molecule's featurized windows
(the client runs preprocessing; the server owns triage + model +
stitch so serve output is byte-identical to the batch pipeline's).
npz keeps the bulk float32 tensors out of JSON and decodes with
allow_pickle=False, so a request body can never smuggle arbitrary
objects. Every field is validated against the loaded model's shapes
BEFORE the request is admitted — an oversized or malformed body is a
typed 4xx, not server memory growth (same posture as the PR-4 bounded
decoders).

Request arrays:
  subreads    float32 [n, total_rows, L, 1]
  window_pos  int64   [n]
  ccs_bq      int32   [n, L]   (draft CCS base qualities)
  overflow    uint8   [n]

where L must be one of the model's window length buckets
(params.window_buckets; max_length alone when bucketing is off). npz
arrays are rectangular, so one request carries one width; clients with
different window lengths share the server's per-bucket packs
concurrently.
  name        0-d str (molecule name)
  meta_json   0-d str (optional: ec / np_num_passes / rq / rg)

Response arrays (application/octet-stream):
  status      0-d str: ok | fallback | filtered | quarantined
  seq         uint8 [len]  (ascii bases; empty unless ok/fallback)
  quals       uint8 [len]  (phred values, not ascii)
  counters_json  0-d str   (per-request triage/window counters)
  error       0-d str      (quarantine detail; empty otherwise)

Errors travel as application/json: {"error", "kind", "status"}.
"""
from __future__ import annotations

import io
import json
from typing import Any, Dict, Optional

import numpy as np

from deepconsensus_tpu import faults as faults_lib

CONTENT_TYPE = 'application/octet-stream'
DEADLINE_HEADER = 'X-Dctpu-Deadline-S'
REQUEST_FIELDS = ('name', 'subreads', 'window_pos', 'ccs_bq', 'overflow')
_META_KEYS = ('ec', 'np_num_passes', 'rq', 'rg')


def encode_request(name: str, subreads: np.ndarray,
                   window_pos: np.ndarray, ccs_bq: np.ndarray,
                   overflow: np.ndarray,
                   meta: Optional[Dict[str, Any]] = None) -> bytes:
  buf = io.BytesIO()
  np.savez(
      buf,
      name=np.array(str(name)),
      subreads=np.asarray(subreads, dtype=np.float32),
      window_pos=np.asarray(window_pos, dtype=np.int64),
      ccs_bq=np.asarray(ccs_bq, dtype=np.int32),
      overflow=np.asarray(overflow, dtype=np.uint8),
      meta_json=np.array(json.dumps(
          {k: meta[k] for k in _META_KEYS if meta and meta.get(k) is not None}
      )),
  )
  return buf.getvalue()


def request_from_features(features) -> bytes:
  """Builds a request body from one molecule's preprocess window
  feature dicts (runner.preprocess_zmw output)."""
  fd0 = features[0]
  name = fd0['name'] if isinstance(fd0['name'], str) else fd0['name'].decode()
  return encode_request(
      name=name,
      subreads=np.stack([fd['subreads'] for fd in features]),
      window_pos=np.array([fd['window_pos'] for fd in features]),
      ccs_bq=np.stack(
          [np.asarray(fd['ccs_base_quality_scores']) for fd in features]),
      overflow=np.array([bool(fd['overflow']) for fd in features]),
      meta={k: fd0.get(k) for k in _META_KEYS},
  )


def decode_request(body: bytes, *, total_rows: int, max_length: int,
                   max_windows: int,
                   window_buckets=None) -> Dict[str, Any]:
  """Parses + validates one request body. Raises BadRequestError (400)
  on anything malformed and RequestTooLargeError (413) when the window
  count exceeds the admission cap. window_buckets: allowed window
  lengths (defaults to (max_length,))."""
  allowed = tuple(window_buckets) if window_buckets else (max_length,)
  try:
    with np.load(io.BytesIO(body), allow_pickle=False) as z:
      missing = [f for f in REQUEST_FIELDS if f not in z.files]
      if missing:
        raise faults_lib.BadRequestError(
            f'request missing field(s): {missing}')
      name = str(z['name'])
      subreads = z['subreads']
      window_pos = z['window_pos']
      ccs_bq = z['ccs_bq']
      overflow = z['overflow']
      meta = json.loads(str(z['meta_json'])) if 'meta_json' in z.files else {}
  except faults_lib.BadRequestError:
    raise
  except Exception as e:  # zip/npz framing, bad JSON, disallowed pickle
    raise faults_lib.BadRequestError(
        f'undecodable request body: {type(e).__name__}: {e}') from e
  n = len(subreads)
  if n < 1:
    raise faults_lib.BadRequestError('request carries zero windows')
  if n > max_windows:
    raise faults_lib.RequestTooLargeError(
        f'{n} windows exceeds max_windows_per_request={max_windows}')
  if (subreads.ndim != 4 or subreads.shape[1] != total_rows
      or subreads.shape[2] not in allowed or subreads.shape[3] != 1):
    raise faults_lib.BadRequestError(
        f'subreads shape {subreads.shape} does not match the loaded '
        f'model: expected [n, {total_rows}, L, 1] with window length '
        f'L in {list(allowed)}')
  width = int(subreads.shape[2])
  if window_pos.shape != (n,) or overflow.shape != (n,):
    raise faults_lib.BadRequestError(
        'window_pos/overflow must be [n] aligned with subreads')
  if ccs_bq.shape != (n, width):
    raise faults_lib.BadRequestError(
        f'ccs_bq shape {ccs_bq.shape} != [n, {width}]')
  if not np.isfinite(subreads).all():
    raise faults_lib.BadRequestError('subreads contains non-finite values')
  if not isinstance(meta, dict):
    raise faults_lib.BadRequestError('meta_json must encode an object')
  return {
      'name': name,
      'subreads': subreads.astype(np.float32, copy=False),
      'window_pos': window_pos.astype(np.int64, copy=False),
      'ccs_bq': ccs_bq.astype(np.int32, copy=False),
      'overflow': overflow.astype(bool, copy=False),
      'meta': tuple(meta.get(k) for k in _META_KEYS),
  }


def encode_response(status: str, seq: bytes = b'',
                    quals: Optional[np.ndarray] = None,
                    counters: Optional[Dict[str, Any]] = None,
                    error: str = '') -> bytes:
  buf = io.BytesIO()
  np.savez(
      buf,
      status=np.array(status),
      seq=np.frombuffer(seq, dtype=np.uint8),
      quals=(np.asarray(quals, dtype=np.uint8) if quals is not None
             else np.zeros(0, dtype=np.uint8)),
      counters_json=np.array(json.dumps(counters or {})),
      error=np.array(error[:4000]),
  )
  return buf.getvalue()


def decode_response(body: bytes) -> Dict[str, Any]:
  with np.load(io.BytesIO(body), allow_pickle=False) as z:
    return {
        'status': str(z['status']),
        'seq': z['seq'].tobytes(),
        'quals': np.array(z['quals']),
        'counters': json.loads(str(z['counters_json'])),
        'error': str(z['error']),
    }
