"""ConsensusService: continuous batching with per-request isolation.

One model-loop thread owns the ConsensusEngine. HTTP handler threads
only decode, submit, and wait on a per-request event — the model loop
never waits on a client, so a hung or disconnected client can never
wedge the device pipeline (the request-scoped watchdog is this
structural property plus the per-request deadline).

Admission control: a request is admitted only while fewer than
max_pending requests are outstanding AND the admission queue has room;
otherwise it is shed with a typed BackpressureError (429). While
draining (SIGTERM), submission raises DrainingError (503) but
everything already admitted still completes — zero accepted-then-lost.

Continuous batching: the loop greedily ingests every queued request,
so windows from many concurrent requests share fixed-shape packs (the
engine cuts full packs as they fill). Only when the queue is empty and
windows are still buffered does it flush — batching under load, low
latency when idle. Pack composition cannot change results: attention
is strictly within-window, so serve output is byte-identical to a solo
batch run.

Fault isolation: when a shared pack fails, each affected request's
windows are retried once in a solo "isolation pack" (after a full
flush, so no innocent bystander rides along). A second failure
quarantines that request via the shared faults taxonomy — dead-letter
line with request attribution (request_id, client, pack seq), policy
skip/ccs-fallback — while every other request in the original pack
proceeds normally.
"""
from __future__ import annotations

import collections
import dataclasses
import logging
import os
import queue as queue_lib
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deepconsensus_tpu import faults as shared_faults
from deepconsensus_tpu import obs as obs_lib
from deepconsensus_tpu.inference import engine as engine_lib
from deepconsensus_tpu.inference import faults
from deepconsensus_tpu.models import data as data_lib
from deepconsensus_tpu.postprocess import stitch

log = logging.getLogger(__name__)


@dataclasses.dataclass
class ServeOptions:
  """Admission / robustness knobs (docs/serving.md)."""

  max_pending: int = 64          # outstanding admitted requests
  admit_queue_depth: int = 32    # requests queued ahead of the loop
  max_windows_per_request: int = 512
  max_body_bytes: int = 64 * 1024 * 1024
  default_deadline_s: float = 120.0
  max_deadline_s: float = 600.0
  io_timeout_s: float = 20.0     # per-socket read/write (slowloris cap)
  # Policy for a request whose windows fail the model stage twice
  # (shared pack + isolation retry). 'fail' is deliberately not
  # offered: a resident service degrades per-request, never crashes
  # the loop.
  on_request_error: str = faults.OnZmwError.CCS_FALLBACK
  dead_letter_path: Optional[str] = None

  def __post_init__(self):
    if self.on_request_error not in (faults.OnZmwError.SKIP,
                                     faults.OnZmwError.CCS_FALLBACK):
      # dclint: allow=typed-faults (startup flag validation: cli main
      # maps ValueError to exit code 2 before the service exists)
      raise ValueError(
          "on_request_error must be 'skip' or 'ccs-fallback', got "
          f'{self.on_request_error!r}')


class _Ticket:
  """One model window of one request, as seen by the engine.

  slot indexes the request's pos/ids/quals arrays; row indexes its
  retained formatted model_rows (for isolation retries); the draft CCS
  copy makes ccs-fallback possible after the request tensors are gone.
  """

  __slots__ = ('state', 'slot', 'row', 'ccs_ids', 'ccs_bq')

  def __init__(self, state: '_RequestState', slot: int, row: int,
               ccs_ids: np.ndarray, ccs_bq: np.ndarray):
    self.state = state
    self.slot = slot
    self.row = row
    self.ccs_ids = ccs_ids
    self.ccs_bq = ccs_bq


class _RequestState:
  """One admitted request flowing through the model loop."""

  __slots__ = (
      'request_id', 'name', 'client', 'req', 'deadline', 't_submit',
      't_submit_wall', 'trace_id',
      'pos', 'ids', 'quals', 'tickets', 'model_rows', 'pending',
      'ingested', 'retried', 'adopted', 'cancelled', 'finished',
      'counters', 'result', 'error', 'event')

  def __init__(self, request_id: int, req: Dict[str, Any],
               client: str, deadline: float,
               trace_id: Optional[str] = None):
    self.request_id = request_id
    self.name = req['name']
    self.client = client
    self.req = req
    self.deadline = deadline
    self.t_submit = time.monotonic()
    # Wall-clock twin of t_submit: trace spans live on the shared
    # wall-clock timeline (obs/trace.py), monotonic stays for deadlines.
    self.t_submit_wall = time.time()
    self.trace_id = trace_id or obs_lib.trace.mint_trace_id()
    self.pos: List[int] = []
    self.ids: List[Optional[np.ndarray]] = []
    self.quals: List[Optional[np.ndarray]] = []
    self.tickets: List[_Ticket] = []
    # Per-window formatted [total_rows, L, 1] tensors (indexed by
    # ticket.row); a list, not a stacked array, because one request's
    # windows may span length buckets.
    self.model_rows: Optional[List[np.ndarray]] = None
    self.pending = 0
    self.ingested = False
    self.retried = False
    self.adopted = False      # ccs-fallback applied (or skip-dropped)
    self.cancelled = False
    self.finished = False
    self.counters: collections.Counter = collections.Counter()
    self.result: Optional[Dict[str, Any]] = None
    self.error: Optional[str] = None
    self.event = threading.Event()

  @property
  def expired(self) -> bool:
    return time.monotonic() > self.deadline


class ConsensusService:
  """The resident engine + its model loop; see module docstring."""

  def __init__(self, runner, options, serve_options: ServeOptions):
    self.options = options          # InferenceOptions (model knobs)
    self.serve_options = serve_options
    self._queue: 'queue_lib.Queue[_RequestState]' = queue_lib.Queue(
        maxsize=max(1, serve_options.admit_queue_depth))
    self._lock = threading.Lock()
    self._outstanding: set = set()  # guarded by: self._lock
    # dclint: lock-free (monotonic False->True flag; a stale read only
    # delays drain one loop tick, and the loop re-checks under lock)
    self._draining = False
    self._stopped = threading.Event()
    # dclint: lock-free (written once by warmup before traffic starts)
    self._warm = False
    # dclint: lock-free (single writer: the model loop; handlers read
    # at-worst-stale None and fail the next health check instead)
    self._loop_error: Optional[BaseException] = None
    self._next_id = 0  # guarded by: self._lock
    self._retries: List[Tuple[_RequestState, List[_Ticket], int, str]] = []
    # One metrics registry per replica: shared with the runner (whose
    # stage histograms land in the same /metricz view) when it has one;
    # stub runners in tests get a service-local registry.
    self.metrics: obs_lib.MetricsRegistry = (
        getattr(runner, 'obs', None) or obs_lib.MetricsRegistry())
    self.metrics.tier = self.metrics.tier or 'serve'
    self._latency_hist = self.metrics.histogram(
        'serve_request_latency_s',
        help='end-to-end request latency (submit to result)')
    # dclint: lock-free (mutated only by the model loop via stitch;
    # stats() reads int fields whose torn values are tolerable)
    self.outcome = stitch.OutcomeCounter()
    dead_letter = None
    if serve_options.dead_letter_path:
      dead_letter = shared_faults.DeadLetterWriter(
          serve_options.dead_letter_path, append=True)
    self.quarantine = faults.Quarantine(
        serve_options.on_request_error, dead_letter)
    self.engine = engine_lib.ConsensusEngine(
        runner, options,
        deliver=self._deliver,
        on_pack_failure=self._on_pack_failure)
    self._thread = threading.Thread(
        target=self._model_loop, name='dctpu-serve-model', daemon=True)

  # ------------------------------------------------------------------
  # Lifecycle

  def warmup(self) -> float:
    """Pays the jit compile before /readyz flips (with a persistent
    compilation cache this is a cache hit, not a compile)."""
    params = self.engine.params
    t0 = time.monotonic()
    if getattr(self.options, 'use_ragged_kernel', False):
      # Single-pack-stream dispatch: ONE ragged forward shape serves
      # every bucket width, so warmup is one compile, not one per
      # bucket (lengths are traced as data, not shape).
      packer = self.engine._packer_for(max(self.engine.window_buckets))
      pack = np.zeros(
          (packer.n_slots, params.total_rows, packer.slot_len, 1),
          dtype=np.float32)
      lengths = np.zeros(
          (packer.n_slots, packer.windows_per_slot), dtype=np.int32)
      self.engine.runner.finalize(
          self.engine.runner.dispatch_ragged(pack, lengths))
    else:
      for width in self.engine.window_buckets:
        self.engine.runner.predict(np.zeros(
            (1, params.total_rows, width, 1), dtype=np.float32))
    self._warm = True
    return time.monotonic() - t0

  def start(self) -> None:
    self._thread.start()

  def begin_drain(self) -> None:
    """Stops admission; already-admitted requests keep completing."""
    self._draining = True

  def drain(self, timeout: Optional[float] = None) -> bool:
    """begin_drain + wait for the model loop to finish all admitted
    work and exit. True when fully drained."""
    self.begin_drain()
    self._thread.join(timeout=timeout)
    drained = not self._thread.is_alive()
    if drained and self.quarantine.dead_letter is not None:
      self.quarantine.dead_letter.close()
    return drained

  @property
  def healthy(self) -> bool:
    return self._loop_error is None and (
        self._thread.is_alive() or not self._thread.ident)

  @property
  def ready(self) -> bool:
    return (self._warm and not self._draining and self.healthy
            and self._thread.is_alive())

  # ------------------------------------------------------------------
  # Handler-thread side

  def submit(self, req: Dict[str, Any], deadline_s: Optional[float],
             client: str = '',
             trace_id: Optional[str] = None) -> _RequestState:
    """Admits one decoded request or raises a typed ServeRejection."""
    self.quarantine.bump('n_requests')
    if self._draining or self._stopped.is_set():
      raise shared_faults.DrainingError()
    if not self.healthy:
      raise shared_faults.ServeRejection(
          f'model loop died: {self._loop_error!r}')
    opts = self.serve_options
    deadline_s = min(deadline_s or opts.default_deadline_s,
                     opts.max_deadline_s)
    with self._lock:
      if len(self._outstanding) >= opts.max_pending:
        self.quarantine.bump('n_rejected_backpressure')
        raise shared_faults.BackpressureError(
            f'{len(self._outstanding)} requests outstanding '
            f'(max_pending={opts.max_pending})')
      self._next_id += 1
      state = _RequestState(self._next_id, req, client,
                            time.monotonic() + deadline_s,
                            trace_id=trace_id)
      self._outstanding.add(state)
    try:
      self._queue.put_nowait(state)
    except queue_lib.Full:
      with self._lock:
        self._outstanding.discard(state)
      self.quarantine.bump('n_rejected_backpressure')
      raise shared_faults.BackpressureError(
          f'admission queue full (depth={opts.admit_queue_depth})')
    return state

  def wait(self, state: _RequestState) -> Dict[str, Any]:
    """Blocks the handler thread until the result or the deadline.
    Raises DeadlineExceededError after cancelling the request (queued
    windows are never submitted; in-flight deliveries are dropped)."""
    remaining = state.deadline - time.monotonic()
    if not state.event.wait(timeout=max(0.0, remaining) + 0.25):
      self._cancel(state, 'deadline elapsed while awaiting the model loop')
      raise shared_faults.DeadlineExceededError(
          f'request {state.request_id} ({state.name}) missed its deadline')
    if state.cancelled:
      raise shared_faults.DeadlineExceededError(
          f'request {state.request_id} ({state.name}) cancelled at '
          'deadline')
    assert state.result is not None
    return state.result

  def _cancel(self, state: _RequestState, reason: str) -> None:
    with self._lock:
      if state.finished or state.cancelled:
        return
      state.cancelled = True
    self.quarantine.bump('n_deadline_cancelled')
    log.warning('request %d (%s): cancelled: %s',
                state.request_id, state.name, reason)
    # Un-ingested states are skipped (and released) when the loop pops
    # them; in-flight ones are released as their deliveries drain.
    if state.ingested and state.pending == 0:
      self._release(state)
    state.event.set()

  # ------------------------------------------------------------------
  # Model-loop side

  def _model_loop(self) -> None:
    while True:
      try:
        try:
          state = self._queue.get(timeout=0.05)
        except queue_lib.Empty:
          if self._retries:
            self._process_retries()
          elif self.engine.has_work:
            # Idle with a buffered tail: don't hold it hostage waiting
            # for traffic that may never come.
            self.engine.flush(drain=True)
          elif self._draining:
            # Exit only once every admitted request has resolved — a
            # submit that won admission just before the drain flag
            # flipped still lands in the queue and must be served
            # (zero accepted-then-lost).
            with self._lock:
              done = not self._outstanding
            if done:
              break
          continue
        self._ingest(state)
        # Continuous batching: everything already queued joins the
        # same packs before we consider flushing a partial tail.
        while True:
          try:
            self._ingest(self._queue.get_nowait())
          except queue_lib.Empty:
            break
        if self._retries:
          self._process_retries()
      except BaseException as e:  # never die silently: fail loudly
        self._loop_error = e
        log.exception('serve model loop died')
        self._fail_all_outstanding(e)
        break
    self._stopped.set()

  def _ingest(self, state: _RequestState) -> None:
    if state.cancelled:
      self._release(state)
      return
    if state.expired:
      self._cancel(state, 'expired in admission queue')
      self._release(state)
      return
    req = state.req
    opts = self.options
    fds = [
        {
            'overflow': bool(req['overflow'][i]),
            'ccs_base_quality_scores': req['ccs_bq'][i],
            'subreads': req['subreads'][i],
            'window_pos': int(req['window_pos'][i]),
        }
        for i in range(len(req['subreads']))
    ]
    to_model, to_skip = engine_lib.triage_windows(
        fds, opts, state.counters)
    for fd in to_skip:
      state.pos.append(fd['window_pos'])
      ids, quals = engine_lib.skipped_window_arrays(fd, opts)
      state.ids.append(ids)
      state.quals.append(quals)
    ccs_row = engine_lib.row_indices(
        opts.max_passes, opts.use_ccs_bq)[4][0]
    for row, fd in enumerate(to_model):
      slot = len(state.pos)
      state.pos.append(fd['window_pos'])
      state.ids.append(None)
      state.quals.append(None)
      state.tickets.append(_Ticket(
          state, slot, row,
          fd['subreads'][ccs_row, :, 0].astype(np.uint8),
          np.array(fd['ccs_base_quality_scores'])))
    state.pending = len(to_model)
    state.ingested = True
    state.req = None  # the raw request tensors are no longer needed
    if to_model:
      # Formatted once and retained per window: isolation retries
      # re-dispatch the same rows without the raw tensors
      # (~34 KB/window). Formatting batches per width group (a
      # mixed-length request spans buckets); submit hands the whole
      # list to the engine, which regroups per bucket and lets windows
      # from concurrent requests share each bucket's packs.
      groups: Dict[int, Tuple[List[int], List[np.ndarray]]] = {}
      for row, fd in enumerate(to_model):
        rows_idx, raws = groups.setdefault(
            int(fd['subreads'].shape[1]), ([], []))
        rows_idx.append(row)
        raws.append(fd['subreads'])
      formatted: List[Optional[np.ndarray]] = [None] * len(to_model)
      for width in sorted(groups):
        rows_idx, raws = groups[width]
        batch = data_lib.format_rows_batch(
            np.stack(raws), self.engine.params,
            window_buckets=self.engine.window_buckets)
        for row, formatted_row in zip(rows_idx, batch):
          formatted[row] = formatted_row
      state.model_rows = formatted
      poison = os.environ.get(shared_faults.ENV_POISON_WINDOW)
      if poison and poison in state.name:
        self.engine.poison_ticket(state.tickets[0])
      self.engine.submit_formatted(state.model_rows, state.tickets)
    else:
      self._finish(state)

  def _deliver(self, ticket: _Ticket, ids: np.ndarray,
               quals: np.ndarray) -> None:
    state = ticket.state
    if not state.adopted and not state.cancelled:
      state.ids[ticket.slot] = ids
      state.quals[ticket.slot] = quals
    state.pending -= 1
    if state.pending == 0 and state.ingested:
      self._finish(state)

  def _on_pack_failure(self, tickets, pack_seq: int,
                       error: BaseException) -> None:
    """One shared pack failed: route each member request to an
    isolation retry (first failure) or quarantine (second)."""
    text = f'{type(error).__name__}: {error}'
    by_state: Dict[int, Tuple[_RequestState, List[_Ticket]]] = {}
    for t in tickets:
      by_state.setdefault(id(t.state), (t.state, []))[1].append(t)
    for state, ts in by_state.values():
      if state.cancelled or state.adopted:
        state.pending -= len(ts)
        if state.pending == 0 and state.ingested:
          self._finish(state)
      elif not state.retried:
        state.retried = True
        self.quarantine.bump('n_isolation_retries')
        log.warning(
            'pack %d failed (%s); scheduling isolation retry for '
            'request %d (%s, %d window(s))', pack_seq, text,
            state.request_id, state.name, len(ts))
        self._retries.append((state, ts, pack_seq, text))
      else:
        self._quarantine_request(state, ts, pack_seq, text)

  def _process_retries(self) -> None:
    retries, self._retries = self._retries, []
    # Empty the packer (buffered + in flight) so each retry below forms
    # a pure isolation pack: a second failure indicts this request
    # alone. May itself reveal more failures -> self._retries refills
    # and the loop comes back around.
    self.engine.flush(drain=True)
    for state, ts, pack_seq, text in retries:
      if state.cancelled or state.adopted:
        state.pending -= len(ts)
        if state.pending == 0 and state.ingested:
          self._finish(state)
        continue
      poison = os.environ.get(shared_faults.ENV_POISON_WINDOW)
      if poison and poison in state.name:
        # The injected poison rides with the payload, so the isolation
        # pack fails too -> quarantine (matching a genuinely bad
        # window, which fails solo just as it failed shared).
        self.engine.poison_ticket(ts[0])
      self.engine.submit_formatted(
          [state.model_rows[t.row] for t in ts], ts)
      self.engine.flush(drain=True)

  def _quarantine_request(self, state: _RequestState, ts: List[_Ticket],
                          pack_seq: int, text: str) -> None:
    """Second model-stage failure for this request: apply the policy
    (whole-request, like the batch plane's whole-molecule fallback) and
    dead-letter it with request attribution."""
    self.quarantine.bump('n_quarantined_by_request')

    def adopt_all() -> bool:
      for t in state.tickets:
        state.ids[t.slot] = t.ccs_ids
        state.quals[t.slot] = engine_lib.ccs_quals_array(
            t.ccs_bq, self.options)
      return True

    adopted = self.quarantine.handle(
        state.name, 'model', text,
        fallback=adopt_all,
        extra={
            'request_id': state.request_id,
            'client': state.client,
            'trace_id': state.trace_id,
            'model_pack': pack_seq,
            'n_windows_in_pack': len(ts),
        })
    state.adopted = True
    state.error = text
    if not adopted:
      state.result = {'status': 'quarantined', 'error': text}
    state.pending -= len(ts)
    if state.pending == 0 and state.ingested:
      self._finish(state)

  def _finish(self, state: _RequestState) -> None:
    with self._lock:
      if state.finished:
        return
      state.finished = True
    self._release(state)
    if state.cancelled:
      return
    if state.result is None:  # not quarantined-skip
      status = 'fallback' if state.adopted else 'ok'
      t_stitch = time.time()
      try:
        stitched = stitch.stitch_arrays(
            state.name,
            np.asarray(state.pos, dtype=np.int64),
            state.ids,
            state.quals,
            max_length=self.options.max_length,
            min_quality=self.options.min_quality,
            min_length=self.options.min_length,
            outcome_counter=self.outcome,
        )
      except Exception as e:
        self.quarantine.handle(
            state.name, 'stitch', e, fallback=None,
            extra={'request_id': state.request_id,
                   'client': state.client,
                   'trace_id': state.trace_id})
        stitched = None
        status = 'quarantined'
        state.error = f'{type(e).__name__}: {e}'
      obs_lib.record_stage(self.metrics, obs_lib.trace.STAGE_STITCH,
                           t_stitch, time.time(),
                           trace_id=state.trace_id, zmw=state.name)
      if stitched is None and status != 'quarantined':
        status = 'filtered'
      state.result = {
          'status': status,
          'seq': stitched[0] if stitched else b'',
          'quals': stitched[1] if stitched else None,
          'counters': dict(state.counters),
          'error': state.error or '',
      }
    t_done = time.time()
    self._latency_hist.observe(time.monotonic() - state.t_submit)
    # Request-level span: the replica's leg of the cross-tier trace
    # (joined to router/featurize-worker spans by trace_id).
    obs_lib.trace.complete_event(
        'serve_request', 'request', state.t_submit_wall, t_done,
        {'trace_id': state.trace_id, 'zmw': state.name,
         'request_id': state.request_id,
         'status': (state.result or {}).get('status', 'cancelled')})
    state.event.set()

  def _release(self, state: _RequestState) -> None:
    with self._lock:
      self._outstanding.discard(state)

  def _fail_all_outstanding(self, error: BaseException) -> None:
    with self._lock:
      stuck = list(self._outstanding)
      self._outstanding.clear()
    for state in stuck:
      state.result = {
          'status': 'quarantined',
          'error': f'model loop died: {type(error).__name__}: {error}',
      }
      state.event.set()

  # ------------------------------------------------------------------
  # Observability

  def capacity(self) -> Dict[str, Any]:
    """Device capacity for /readyz and /metricz: the current vs launch
    data-parallel width, and whether the mesh degradation ladder has
    stepped down (stub runners report a healthy single device)."""
    runner = self.engine.runner
    return {
        'mesh_dp': int(getattr(runner, 'mesh_dp', 0) or 0),
        'initial_dp': int(getattr(runner, '_initial_dp', 0) or 0),
        'degraded': bool(getattr(runner, 'is_degraded', False)),
    }

  def latency_percentiles(self) -> Dict[str, Optional[float]]:
    """Nearest-rank p50/p99 from the request-latency histogram.

    The deque-era index math (lat[int(n * 0.99)]) under-reported p99
    at small n; the histogram percentile is the textbook nearest-rank
    definition, quantized to bucket edges."""
    return self._latency_hist.percentiles()

  def prom_text(self) -> str:
    """/metricz?format=prom payload: the registry's typed exposition
    plus the pre-registry quarantine counters as untyped samples (the
    registry-owned names are excluded so no sample appears twice)."""
    registry_keys = set(self.metrics.snapshot()['counters'])
    extra = {k: v for k, v in self.stats()['counters'].items()
             if k not in registry_keys}
    return (self.metrics.to_prom('serve')
            + obs_lib.metrics.prom_counters_text(extra, tier='serve'))

  def stats(self) -> Dict[str, Any]:
    """The unified /metricz split: per-request serve counters next to
    the quarantine counters the batch pipeline already reports."""
    counters = dict(self.quarantine.counters)
    counters.setdefault('n_requests', 0)
    counters.setdefault('n_rejected_backpressure', 0)
    counters.setdefault('n_deadline_cancelled', 0)
    counters.setdefault('n_quarantined_by_request', 0)
    # Sharded-dispatch / transfer-overlap counters live in the faults
    # split; the zero defaults keep the keys present under stub
    # runners that don't implement the full dispatch contract.
    counters.setdefault('n_packs_dispatched_sharded', 0)
    counters.setdefault('n_transfer_overlapped', 0)
    counters.setdefault('n_transfer_direct', 0)
    counters.setdefault('transfer_overlap_fraction', 0.0)
    # Device fault domain (--on_device_error / --dispatch_timeout).
    counters.setdefault('n_oom_bisections', 0)
    counters.setdefault('n_device_faults', 0)
    counters.setdefault('n_dispatch_timeouts', 0)
    counters.setdefault('n_mesh_degradations', 0)
    # Quantized-inference levers (--inference_dtype/--quantize_matmuls):
    # the real values ride in from runner.dispatch_stats() through
    # engine.stats() and replace these defaults below.
    counters.setdefault('inference_dtype', 'float32')
    counters.setdefault('n_quantized_matmuls', 0)
    # Device-resident output plane (--device_epilogue): uint8 drain
    # counters, real values ride in the same way.
    counters.setdefault('device_epilogue', 0)
    counters.setdefault('n_epilogue_packs', 0)
    counters.setdefault('d2h_bytes_per_pack', 0)
    # Bucketed dispatch (--window_buckets): per-bucket pack counts,
    # compile count (distinct compiled forward shapes), and the
    # measured pad-to-max waste avoided; real values ride in from
    # engine.stats() the same way.
    counters.setdefault('n_packs_by_bucket', {})
    counters.setdefault('n_forward_shapes', 0)
    counters.setdefault('padding_fraction', 0.0)
    # Starvation-flush cost (--bucket_flush_packs) and the ragged
    # single-stream gate (--use_ragged_kernel): real values ride in
    # from engine.stats() the same way. flush_padding_fraction is
    # structurally 0.0 on the ragged path (no starvation flush).
    counters.setdefault('n_starvation_flushes', 0)
    counters.setdefault('flush_padding_fraction', 0.0)
    counters.setdefault('use_ragged_kernel', 0)
    with self._lock:
      outstanding = len(self._outstanding)
    engine_stats = self.engine.stats()
    for key in tuple(engine_stats):
      if key in counters:
        counters[key] = engine_stats.pop(key)
    registry_view = self.metrics.snapshot()
    out = {
        # Unified cross-tier schema (docs/observability.md): every tier
        # exposes tier/ready/draining/outstanding/counters/latency/
        # histograms at the top level; tier-specific keys nest beside.
        'tier': 'serve',
        'outstanding': outstanding,
        'draining': self._draining,
        'ready': self.ready,
        'counters': {**registry_view['counters'], **counters},
        'histograms': registry_view['histograms'],
        'capacity': self.capacity(),
        'latency': self.latency_percentiles(),
        'outcomes': dataclasses.asdict(self.outcome),
    }
    out.update(engine_stats)
    return out
