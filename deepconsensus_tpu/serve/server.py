"""HTTP front end for ConsensusService + serve_main (SIGTERM drain).

stdlib-only: ThreadingHTTPServer with daemon handler threads. Each
connection gets a socket timeout (ServeOptions.io_timeout_s), so a
slow-drip ("slowloris") or half-dead client costs one handler thread
for at most that long and never touches the model loop.

Endpoints:
  POST /v1/polish   one molecule's windows (protocol.py npz) -> npz
  GET  /healthz     200 while the model loop is alive (also during
                    drain), 503 after a loop crash
  GET  /readyz      200 only when warmed AND admitting; 503 while
                    draining -> load balancers stop routing here first
  GET  /metricz     JSON: faults counters (n_requests,
                    n_rejected_backpressure, n_deadline_cancelled,
                    n_quarantined_by_request, quarantine counters),
                    latency p50/p99, engine pack stats

Shutdown follows the training PreemptionGuard pattern
(models/train.py): the SIGTERM/SIGINT handler only sets a flag; the
main thread performs the drain — stop admitting (503 on new polish),
let the model loop finish every admitted request, then stop the
listener and exit 0.
"""
from __future__ import annotations

import io
import json
import logging
import os
import signal
import socket
import tempfile
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from deepconsensus_tpu import faults as shared_faults
from deepconsensus_tpu import obs as obs_lib
from deepconsensus_tpu.serve import protocol

# ConsensusService/ServeOptions are imported inside serve_main: the
# service pulls in the jax-backed engine, and fleet's CPU-only tiers
# (dctpu route / featurize-worker) reuse this module's socket plumbing
# without paying for it. Annotations stay as strings (PEP 563).

log = logging.getLogger(__name__)


class _DeadlineSocketIO(io.RawIOBase):
  """Raw socket reader enforcing an ABSOLUTE per-request deadline.

  A per-recv socket timeout alone does not stop a slowloris: a client
  dripping one byte per interval satisfies every individual recv while
  holding the handler thread forever. Each request (headers + body)
  must complete within io_timeout_s of its first byte; past the
  deadline the next read raises socket.timeout, which the http.server
  machinery turns into a closed connection.
  """

  def __init__(self, sock: socket.socket, io_timeout_s: float):
    super().__init__()
    self._sock = sock
    self._io_timeout_s = io_timeout_s
    self.deadline = time.monotonic() + io_timeout_s

  def reset_deadline(self) -> None:
    self.deadline = time.monotonic() + self._io_timeout_s

  def readable(self) -> bool:
    return True

  def readinto(self, b) -> int:
    remaining = self.deadline - time.monotonic()
    if remaining <= 0:
      # dclint: allow=typed-faults (socket.timeout is what
      # http.server's rfile machinery expects from a slow read; a
      # faults.py type would bypass its 408 handling)
      raise socket.timeout(
          f'request not fully read within io_timeout_s='
          f'{self._io_timeout_s}')
    self._sock.settimeout(min(self._io_timeout_s, remaining))
    return self._sock.recv_into(b)


def _make_handler(service: ConsensusService):
  opts = service.serve_options
  params = service.engine.params

  class Handler(BaseHTTPRequestHandler):
    server_version = 'dctpu-serve/1'
    protocol_version = 'HTTP/1.1'

    def setup(self):
      super().setup()
      # The request-scoped watchdog's socket half: a client that stops
      # sending (or reading) trips this timeout and only its own
      # handler thread dies. The deadline reader additionally bounds
      # the WHOLE request read, so drip-feeding can't evade it.
      self.connection.settimeout(opts.io_timeout_s)
      self._raw_in = _DeadlineSocketIO(self.connection, opts.io_timeout_s)
      self.rfile = io.BufferedReader(self._raw_in)

    def handle_one_request(self):
      self._raw_in.reset_deadline()  # keep-alive: per request, not conn
      super().handle_one_request()

    def log_message(self, fmt, *args):
      log.debug('%s %s', self.address_string(), fmt % args)

    def _reply(self, status: int, body: bytes,
               content_type: str = 'application/json') -> None:
      try:
        self.send_response(status)
        self.send_header('Content-Type', content_type)
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)
      except (BrokenPipeError, ConnectionResetError, socket.timeout,
              TimeoutError):
        # Client gone or stalled on read; its result is simply dropped.
        self.close_connection = True

    def _reply_json(self, status: int, obj: Dict[str, Any]) -> None:
      self._reply(status, json.dumps(obj).encode())

    def _reply_error(self, e: shared_faults.ServeRejection) -> None:
      self._reply_json(
          e.http_status,
          {'error': str(e), 'kind': e.kind, 'status': e.http_status})

    def do_GET(self):
      path, _, query = self.path.partition('?')
      params_qs = urllib.parse.parse_qs(query)
      if path == '/healthz':
        if service.healthy:
          self._reply_json(200, {'ok': True})
        else:
          self._reply_json(503, {'ok': False, 'error': 'model loop died'})
      elif path == '/readyz':
        # Degraded capacity (mesh stepped down a dp level) stays ready
        # — the service still answers, just slower — but the body says
        # so, so orchestrators can rebalance replicas.
        capacity = service.capacity()
        if service.ready:
          self._reply_json(200, dict({'ready': True}, **capacity))
        else:
          self._reply_json(
              503, dict({'ready': False, 'draining': service._draining},
                        **capacity))
      elif path == '/metricz':
        if params_qs.get('format', [''])[0] == 'prom':
          self._reply(200, service.prom_text().encode(),
                      content_type='text/plain; version=0.0.4')
        else:
          self._reply_json(200, service.stats())
      elif path == '/debugz/profile':
        # On-demand jax.profiler capture: blocks this handler thread
        # for the capture window, never the model loop.
        try:
          seconds = float(params_qs.get('seconds', ['5'])[0])
        except ValueError as e:
          self._reply_error(
              shared_faults.BadRequestError(f'bad seconds param: {e}'))
          return
        out_dir = (params_qs.get('out', [''])[0]
                   or os.path.join(tempfile.gettempdir(),
                                   f'dctpu-profile-{os.getpid()}'))
        result = obs_lib.profiler.capture_profile(out_dir, seconds)
        self._reply_json(200 if result['ok'] else 503, result)
      else:
        self._reply_json(404, {'error': f'no such path: {self.path}'})

    def do_POST(self):
      if self.path != '/v1/polish':
        self._reply_json(404, {'error': f'no such path: {self.path}'})
        return
      try:
        length = int(self.headers.get('Content-Length', ''))
      except ValueError:
        self._reply_json(411, {'error': 'Content-Length required'})
        return
      if length > opts.max_body_bytes:
        # Rejected before reading: an oversized body never allocates.
        self.close_connection = True
        self._reply_error(shared_faults.RequestTooLargeError(
            f'body of {length} bytes exceeds '
            f'max_body_bytes={opts.max_body_bytes}'))
        return
      try:
        body = self.rfile.read(length)
      except (socket.timeout, TimeoutError, ConnectionResetError):
        self.close_connection = True
        return  # slowloris / mid-request disconnect: drop silently
      if len(body) < length:
        self.close_connection = True
        return  # client disconnected mid-body
      try:
        deadline_s: Optional[float] = None
        header = self.headers.get(protocol.DEADLINE_HEADER)
        if header:
          deadline_s = float(header)
        trace_id = self.headers.get(protocol.TRACE_HEADER) or None
        req = protocol.decode_request(
            body,
            total_rows=params.total_rows,
            max_length=params.max_length,
            max_windows=opts.max_windows_per_request,
            window_buckets=service.engine.window_buckets)
        state = service.submit(req, deadline_s,
                               client=self.address_string(),
                               trace_id=trace_id)
        result = service.wait(state)
      except ValueError as e:
        self._reply_error(
            shared_faults.BadRequestError(f'bad deadline header: {e}'))
        return
      except shared_faults.ServeRejection as e:
        self._reply_error(e)
        return
      self._reply(
          200,
          protocol.encode_response(
              status=result['status'],
              seq=result.get('seq', b''),
              quals=result.get('quals'),
              counters=result.get('counters'),
              error=result.get('error', ''),
          ),
          content_type=protocol.CONTENT_TYPE)

  return Handler


class ServeHTTPServer(ThreadingHTTPServer):
  daemon_threads = True
  allow_reuse_address = True


def build_server(service: ConsensusService, host: str,
                 port: int) -> ServeHTTPServer:
  return ServeHTTPServer((host, port), _make_handler(service))


class _PreemptionWatch:
  """Preemption notice: an early warning that this replica is about to
  be killed (cloud preemption, spot reclaim, scale-in). Two delivery
  paths set the same flag:

    * SIGUSR1 — the external notice (inject_faults.py preempt, or a
      node-agent relaying the provider's preemption warning).
    * DCTPU_FAULT_PREEMPT_AT_S — the env fault hook: a daemon timer
      self-delivers the notice N seconds after serve start, so tests
      and soaks exercise the path without process signals.

  Like _StopFlag, the handler only sets a flag; serve_main's main
  thread sees it and runs the normal drain — /readyz flips to 503
  draining (the router stops routing here), admitted work finishes,
  and the process exits 0 well before the provider's hard kill."""

  def __init__(self):
    self.noticed = threading.Event()
    self._saved = None
    self._timer: Optional[threading.Timer] = None

  def install(self):
    try:
      self._saved = signal.signal(signal.SIGUSR1, self._handle)
    except ValueError:
      # Not the main thread (in-process tests): the env timer below
      # still works, and tests can call notice() directly.
      pass
    at_s = shared_faults.preempt_notice_after_s()
    if at_s > 0:
      self._timer = threading.Timer(at_s, self.notice)
      self._timer.daemon = True
      self._timer.start()

  def notice(self) -> None:
    log.warning('preemption notice: draining ahead of the kill')
    self.noticed.set()

  def restore(self):
    if self._timer is not None:
      self._timer.cancel()
    if self._saved is not None:
      signal.signal(signal.SIGUSR1, self._saved)

  def _handle(self, signum, frame):
    del signum, frame
    self.notice()


class _StopFlag:
  """PreemptionGuard-style: the signal handler only sets a flag (and
  remembers which signal); the main thread owns the drain."""

  def __init__(self):
    self.event = threading.Event()
    self.signum: Optional[int] = None
    self._saved = {}

  def install(self):
    for sig in (signal.SIGTERM, signal.SIGINT):
      try:
        self._saved[sig] = signal.signal(sig, self._handle)
      except ValueError:
        # Not the main thread (in-process tests): run without signal
        # handling; the caller stops us via request_stop().
        break

  def request_stop(self, signum: int = signal.SIGTERM) -> None:
    self._handle(signum, None)

  def restore(self):
    for sig, handler in self._saved.items():
      signal.signal(sig, handler)

  def _handle(self, signum, frame):
    del frame
    self.signum = signum
    self.event.set()


def serve_main(runner, options, serve_options: ServeOptions,
               host: str = '127.0.0.1', port: int = 0,
               ready_fn=None, stop_event=None) -> Dict[str, Any]:
  """Runs the service until SIGTERM/SIGINT, then drains. Returns the
  final stats dict (the CLI exits 0 on a clean drain).

  ready_fn(info) is called once the endpoint is warm and listening —
  the CLI prints the info line to stdout; tests use it to learn the
  bound port. stop_event (threading.Event) is the in-process stand-in
  for SIGTERM when serve_main runs off the main thread.
  """
  from deepconsensus_tpu.serve.service import ConsensusService

  # Fleet tracing: every tier appends to the shared trace file named
  # by DCTPU_TRACE (no-op when unset).
  obs_lib.trace.configure_from_env(tier='serve')
  service = ConsensusService(runner, options, serve_options)
  warm_s = service.warmup()
  service.start()
  httpd = build_server(service, host, port)
  bound_port = httpd.server_address[1]
  http_thread = threading.Thread(
      target=httpd.serve_forever, name='dctpu-serve-http', daemon=True)
  http_thread.start()
  stop = _StopFlag()
  stop.install()
  preempt = _PreemptionWatch()
  preempt.install()
  info = {
      'event': 'ready',
      'host': host,
      'port': bound_port,
      'warmup_s': round(warm_s, 3),
  }
  log.info('dctpu serve ready on %s:%d (warmup %.3fs)',
           host, bound_port, warm_s)
  if ready_fn is not None:
    ready_fn(info)
  try:
    while not stop.event.wait(timeout=0.5):
      if stop_event is not None and stop_event.is_set():
        break
      if preempt.noticed.is_set():
        break
      if not service.healthy:
        log.error('model loop died; shutting down')
        break
    if stop.signum is not None:
      log.warning('signal %d: draining (no new admissions)', stop.signum)
    # Drain while the listener stays up: in-flight handler threads can
    # still deliver their responses; new polish requests get 503. A
    # preemption notice takes the same path — the only difference is
    # who asked (provider warning vs operator SIGTERM).
    service.begin_drain()
    drained = service.drain(timeout=serve_options.max_deadline_s + 30)
    if not drained:
      log.error('drain timed out with work outstanding')
  finally:
    stop.restore()
    preempt.restore()
    httpd.shutdown()
    httpd.server_close()
  stats = service.stats()
  stats['drained'] = bool(drained)
  stats['preempted'] = preempt.noticed.is_set()
  return stats
