"""`dctpu serve`: a resident consensus service on the ConsensusEngine.

Layers (each importable on its own):

* protocol.py — the npz-over-HTTP wire format with byte/window caps
  enforced before any allocation is trusted.
* service.py  — ConsensusService: admission control, the single model
  loop doing continuous batching across concurrent requests, per-
  request deadlines, pack-failure isolation retries, and per-request
  quarantine with dead-letter attribution.
* server.py   — the stdlib ThreadingHTTPServer front end (/v1/polish,
  /healthz, /readyz, /metricz) and serve_main with SIGTERM drain.
* client.py   — ServeClient plus the raw-socket fault senders used by
  scripts/inject_faults.py.
"""
from deepconsensus_tpu.serve.service import (  # noqa: F401
    ConsensusService,
    ServeOptions,
)
