"""`dctpu serve`: a resident consensus service on the ConsensusEngine.

Layers (each importable on its own):

* protocol.py — the npz-over-HTTP wire format with byte/window caps
  enforced before any allocation is trusted.
* service.py  — ConsensusService: admission control, the single model
  loop doing continuous batching across concurrent requests, per-
  request deadlines, pack-failure isolation retries, and per-request
  quarantine with dead-letter attribution.
* server.py   — the stdlib ThreadingHTTPServer front end (/v1/polish,
  /healthz, /readyz, /metricz) and serve_main with SIGTERM drain.
* client.py   — ServeClient plus the raw-socket fault senders used by
  scripts/inject_faults.py.

The re-exports below resolve lazily (PEP 562): service.py pulls in the
jax-backed engine, but protocol/server/client are pure stdlib+numpy —
featurize workers and routers import those on jax-free CPU boxes, and
an eager `from .service import ...` here would defeat that.
"""

_SERVICE_EXPORTS = ('ConsensusService', 'ServeOptions')

__all__ = list(_SERVICE_EXPORTS)


def __getattr__(name):
  if name in _SERVICE_EXPORTS:
    from deepconsensus_tpu.serve import service

    return getattr(service, name)
  # dclint: allow=typed-faults (PEP 562 module __getattr__ must raise
  # AttributeError — anything else breaks hasattr/dir/import machinery)
  raise AttributeError(
      f'module {__name__!r} has no attribute {name!r}')
