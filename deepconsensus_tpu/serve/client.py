"""ServeClient: the thin client side of `dctpu serve`.

polish() ships one molecule's featurized windows and returns the
polished read; the client assembles FASTQ with the same
stitch.format_fastq_bytes the batch pipeline uses, so a serve run and
a batch run over the same input produce byte-identical files.

Also home to the raw-socket fault senders scripts/inject_faults.py
drives (mid-request disconnect, garbage body, oversized body,
slowloris), plus env-hook self-sabotage: with
DCTPU_FAULT_SERVE_CLIENT set, polish() misbehaves on the wire instead
of sending its request — letting an otherwise-correct client binary
(soak_e2e.py's workers) become the adversarial client in fault drills.
"""
from __future__ import annotations

import http.client
import json
import os
import socket
import time
from typing import Any, Dict, Optional

import numpy as np

from deepconsensus_tpu import faults as shared_faults
from deepconsensus_tpu.serve import protocol

CLIENT_FAULT_MODES = ('disconnect', 'garbage', 'oversized', 'slowloris')


class ServeClientError(RuntimeError):
  """A non-200 response, with the server's typed error attached."""

  def __init__(self, status: int, payload: Dict[str, Any]):
    super().__init__(
        f'HTTP {status}: {payload.get("error", "<no error body>")}')
    self.status = status
    self.kind = payload.get('kind', shared_faults.FaultKind.PERMANENT)
    self.payload = payload


class ServeClient:
  """One connection-per-call HTTP client (stdlib http.client)."""

  def __init__(self, host: str = '127.0.0.1', port: int = 8764,
               timeout: float = 180.0, klass: Optional[str] = None,
               client: Optional[str] = None):
    self.host = host
    self.port = port
    self.timeout = timeout
    # Multi-tenant QoS identity, sent as headers on every polish: the
    # router charges admission to (class, client). Unset = the
    # router's defaults (interactive class, peer-address client).
    self.klass = klass
    self.client = client

  def _request(self, method: str, path: str, body: bytes = b'',
               headers: Optional[Dict[str, str]] = None):
    conn = http.client.HTTPConnection(
        self.host, self.port, timeout=self.timeout)
    try:
      conn.request(method, path, body=body, headers=headers or {})
      resp = conn.getresponse()
      return resp.status, resp.read(), resp.getheader('Content-Type', '')
    finally:
      conn.close()

  def _polish_headers(self, deadline_s: Optional[float],
                      trace_id: Optional[str]) -> Dict[str, str]:
    headers = {'Content-Type': protocol.CONTENT_TYPE}
    if deadline_s is not None:
      headers[protocol.DEADLINE_HEADER] = str(deadline_s)
    if trace_id:
      headers[protocol.TRACE_HEADER] = trace_id
    if self.klass:
      headers[protocol.CLASS_HEADER] = self.klass
    if self.client:
      headers[protocol.CLIENT_HEADER] = self.client
    return headers

  def _get_json(self, path: str) -> Dict[str, Any]:
    status, body, _ = self._request('GET', path)
    out = json.loads(body)
    out['_status'] = status
    return out

  def healthz(self) -> Dict[str, Any]:
    return self._get_json('/healthz')

  def readyz(self) -> Dict[str, Any]:
    return self._get_json('/readyz')

  def metricz(self) -> Dict[str, Any]:
    return self._get_json('/metricz')

  def wait_ready(self, timeout: float = 120.0,
                 interval: float = 0.2) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
      try:
        if self.readyz().get('ready'):
          return True
      except (ConnectionError, socket.timeout, TimeoutError, OSError):
        pass
      time.sleep(interval)
    return False

  def polish(self, name: str, subreads: np.ndarray,
             window_pos: np.ndarray, ccs_bq: np.ndarray,
             overflow: np.ndarray,
             meta: Optional[Dict[str, Any]] = None,
             deadline_s: Optional[float] = None,
             trace_id: Optional[str] = None) -> Dict[str, Any]:
    """Polishes one molecule. Returns the decoded response dict
    (status/seq/quals/counters/error); raises ServeClientError on a
    typed rejection. Honors the DCTPU_FAULT_SERVE_CLIENT sabotage
    hooks (see maybe_sabotage)."""
    body = protocol.encode_request(
        name, subreads, window_pos, ccs_bq, overflow, meta)
    sabotaged = maybe_sabotage(self.host, self.port, name, body)
    if sabotaged:
      return {'status': 'client-fault', 'mode': sabotaged,
              'seq': b'', 'quals': None}
    status, resp_body, ctype = self._request(
        'POST', '/v1/polish', body=body,
        headers=self._polish_headers(deadline_s, trace_id))
    if status != 200:
      try:
        payload = json.loads(resp_body)
      except (ValueError, UnicodeDecodeError):
        payload = {'error': resp_body[:200].decode('latin-1')}
      raise ServeClientError(status, payload)
    del ctype
    return protocol.decode_response(resp_body)

  def polish_features(self, features, deadline_s: Optional[float] = None,
                      compact: bool = False,
                      trace_id: Optional[str] = None) -> Dict[str, Any]:
    """polish() from preprocess window feature dicts. compact=True
    ships a features/1 uint8 pack (~4x fewer wire bytes) when the
    tensor packs losslessly, silently falling back to the legacy
    float32 frame when it doesn't — the server reconstructs the exact
    same tensor either way."""
    body = None
    if compact:
      body = protocol.features_pack_from_features(features)
    if body is None:
      body = protocol.request_from_features(features)
    fd0 = features[0]
    name = (fd0['name'] if isinstance(fd0['name'], str)
            else fd0['name'].decode())
    return self.polish_body(body, name=name, deadline_s=deadline_s,
                            trace_id=trace_id)

  def polish_body(self, body: bytes, name: str = '',
                  deadline_s: Optional[float] = None,
                  trace_id: Optional[str] = None) -> Dict[str, Any]:
    """POSTs an already-encoded /v1/polish body (legacy, features/1,
    or — against a router — bam/1). The featurize tier and the soak
    harness reuse this to ship packs without re-encoding."""
    sabotaged = maybe_sabotage(self.host, self.port, name, body)
    if sabotaged:
      return {'status': 'client-fault', 'mode': sabotaged,
              'seq': b'', 'quals': None}
    status, resp_body, _ = self._request(
        'POST', '/v1/polish', body=body,
        headers=self._polish_headers(deadline_s, trace_id))
    if status != 200:
      try:
        payload = json.loads(resp_body)
      except (ValueError, UnicodeDecodeError):
        payload = {'error': resp_body[:200].decode('latin-1')}
      raise ServeClientError(status, payload)
    return protocol.decode_response(resp_body)

  def polish_bam(self, subreads_bam: bytes, ccs_bam: bytes,
                 name: str = '',
                 deadline_s: Optional[float] = None,
                 trace_id: Optional[str] = None) -> Dict[str, Any]:
    """polish() from one molecule's raw mini-BAM bytes, for use
    against a `dctpu route` front tier with a featurize tier behind
    it (a bare model replica answers a typed 400)."""
    body = protocol.encode_bam_request(subreads_bam, ccs_bam, name=name)
    return self.polish_body(body, name=name, deadline_s=deadline_s,
                            trace_id=trace_id)


# ----------------------------------------------------------------------
# Adversarial senders (scripts/inject_faults.py serve_client)


def _connect(host: str, port: int, timeout: float = 30.0) -> socket.socket:
  return socket.create_connection((host, port), timeout=timeout)


def send_disconnect(host: str, port: int, body: bytes) -> int:
  """Mid-request disconnect: claims the full body length, sends half,
  slams the connection. Returns bytes actually sent."""
  half = body[: max(1, len(body) // 2)]
  with _connect(host, port) as sock:
    sock.sendall(
        b'POST /v1/polish HTTP/1.1\r\n'
        b'Host: x\r\n'
        b'Content-Type: application/octet-stream\r\n'
        + f'Content-Length: {len(body)}\r\n\r\n'.encode()
    )
    sock.sendall(half)
    # RST rather than FIN where possible, the rudest disconnect.
    sock.setsockopt(
        socket.SOL_SOCKET, socket.SO_LINGER,
        __import__('struct').pack('ii', 1, 0))
  return len(half)


def send_garbage(host: str, port: int, n_bytes: int = 4096,
                 seed: int = 0) -> int:
  """Well-framed HTTP carrying a body that is not an npz at all.
  Returns the HTTP status (expected: 400)."""
  rng = np.random.default_rng(seed)
  body = rng.integers(0, 256, size=n_bytes, dtype=np.uint8).tobytes()
  conn = http.client.HTTPConnection(host, port, timeout=30)
  try:
    conn.request('POST', '/v1/polish', body=body,
                 headers={'Content-Type': protocol.CONTENT_TYPE})
    return conn.getresponse().status
  finally:
    conn.close()


def send_oversized(host: str, port: int,
                   claimed_bytes: int = 1 << 40) -> int:
  """Claims an absurd Content-Length with no body behind it. The
  server must reject on the header alone (413) without allocating.
  Returns the HTTP status."""
  with _connect(host, port) as sock:
    sock.sendall(
        b'POST /v1/polish HTTP/1.1\r\n'
        b'Host: x\r\n'
        + f'Content-Length: {claimed_bytes}\r\n\r\n'.encode())
    data = sock.recv(64)
  try:
    return int(data.split(b' ')[1])
  except (IndexError, ValueError):
    return -1


def send_slowloris(host: str, port: int, duration_s: float = 60.0,
                   interval_s: float = 1.0) -> float:
  """Drips one header byte per interval. A hardened server cuts the
  socket at io_timeout_s; returns how long the connection survived."""
  t0 = time.monotonic()
  payload = b'POST /v1/polish HTTP/1.1\r\nHost: x\r\nX-Drip: '
  try:
    with _connect(host, port, timeout=interval_s * 2 + 5) as sock:
      for i in range(int(duration_s / interval_s)):
        sock.sendall(payload[i:i + 1] if i < len(payload) else b'a')
        time.sleep(interval_s)
        # A closed peer surfaces as ECONNRESET/EPIPE on the next send.
  except OSError:
    pass
  return time.monotonic() - t0


def maybe_sabotage(host: str, port: int, name: str,
                   body: bytes) -> Optional[str]:
  """Env-hook self-sabotage: when DCTPU_FAULT_SERVE_CLIENT names a
  fault mode (and DCTPU_FAULT_SERVE_CLIENT_ZMW, if set, is a substring
  of this molecule's name), misbehave on the wire instead of sending
  the request. Returns the mode fired, or None."""
  mode = os.environ.get(shared_faults.ENV_SERVE_CLIENT_FAULT)
  if not mode:
    return None
  scope = os.environ.get(shared_faults.ENV_SERVE_CLIENT_FAULT_ZMW)
  if scope and scope not in name:
    return None
  if mode not in CLIENT_FAULT_MODES:
    # dclint: allow=typed-faults (fault-injection env validation: a
    # typo in the harness knob should abort the test loudly)
    raise ValueError(
        f'{shared_faults.ENV_SERVE_CLIENT_FAULT}={mode!r}: must be one '
        f'of {CLIENT_FAULT_MODES}')
  if mode == 'disconnect':
    send_disconnect(host, port, body)
  elif mode == 'garbage':
    send_garbage(host, port, n_bytes=min(len(body), 65536) or 4096)
  elif mode == 'oversized':
    send_oversized(host, port)
  elif mode == 'slowloris':
    send_slowloris(host, port, duration_s=30.0, interval_s=0.5)
  return mode
