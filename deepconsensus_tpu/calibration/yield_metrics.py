"""Yield@Q benchmark metrics: empirical identity of polished reads.

Implements the reference's published evaluation methodology
(reference docs/yield_metrics.md:80-98): align polished reads to the
truth, compute per-read empirical identity, then report — per
predicted-quality threshold — the surviving read count, base yield,
and the fraction meeting the identity bar (0.999 for "Q30-equivalent"
yield). The alignment itself comes from an external aligner (pbmm2 in
the reference); this tool consumes that BAM plus the truth FASTA.
"""
from __future__ import annotations

import csv
import dataclasses
from typing import Dict, List, Optional

import numpy as np

from deepconsensus_tpu import constants
from deepconsensus_tpu.io import bam as bam_lib
from deepconsensus_tpu.io import fastx
from deepconsensus_tpu.utils import phred

Cigar = constants.Cigar


@dataclasses.dataclass
class ReadAssessment:
  name: str
  length: int
  avg_quality: float
  matches: int
  mismatches: int
  insertions: int
  deletions: int

  @property
  def identity(self) -> float:
    aligned = self.matches + self.mismatches + self.insertions + self.deletions
    return self.matches / aligned if aligned else 0.0


def assess_read(
    record: bam_lib.BamRecord, ref_seqs: Dict[str, str]
) -> Optional[ReadAssessment]:
  """Per-read alignment accounting from the cigar walk."""
  if record.is_unmapped or record.is_secondary or record.is_supplementary:
    return None
  ref = ref_seqs.get(record.reference_name)
  if ref is None:
    return None
  m = x = ins = dels = 0
  ref_pos = record.pos
  read_idx = 0
  seq = record.seq.upper()
  for op, length in zip(record.cigar_ops, record.cigar_lens):
    if op in (Cigar.MATCH, Cigar.EQUAL, Cigar.DIFF):
      chunk_ref = ref[ref_pos : ref_pos + length].upper()
      for i in range(length):
        if i < len(chunk_ref) and chunk_ref[i] == seq[read_idx + i]:
          m += 1
        else:
          x += 1
      ref_pos += length
      read_idx += length
    elif op in (Cigar.INS,):
      ins += length
      read_idx += length
    elif op in (Cigar.SOFT_CLIP,):
      read_idx += length
    elif op in (Cigar.DEL, Cigar.REF_SKIP):
      dels += length
      ref_pos += length
  quals = record.quals if record.quals is not None else np.empty(0)
  return ReadAssessment(
      name=record.qname,
      length=len(seq),
      avg_quality=phred.avg_phred(quals),
      matches=m,
      mismatches=x,
      insertions=ins,
      deletions=dels,
  )


def yield_at_thresholds(
    reads: List[ReadAssessment],
    quality_thresholds=(20, 30, 40),
    identity_bar: float = 0.999,
) -> List[Dict[str, float]]:
  """Per quality threshold: reads kept, bases, and high-identity yield
  (the reference's yield@emQ definition)."""
  rows = []
  for q in quality_thresholds:
    kept = [r for r in reads if round(r.avg_quality, 5) >= q]
    good = [r for r in kept if r.identity >= identity_bar]
    rows.append({
        'quality_threshold': q,
        'num_reads': len(kept),
        'num_bases': sum(r.length for r in kept),
        'num_reads_identity_ok': len(good),
        'yield_bases': sum(r.length for r in good),
        'mean_identity': (
            float(np.mean([r.identity for r in kept])) if kept else 0.0
        ),
    })
  return rows


def calculate_yield_metrics(
    bam: str,
    ref: str,
    output: str,
    quality_thresholds=(20, 30, 40),
    identity_bar: float = 0.999,
) -> List[Dict[str, float]]:
  """Assesses every read and writes the yield table CSV."""
  ref_seqs = fastx.read_fasta(ref)
  reads = []
  for record in bam_lib.BamReader(bam):
    assessment = assess_read(record, ref_seqs)
    if assessment is not None:
      reads.append(assessment)
  rows = yield_at_thresholds(reads, quality_thresholds, identity_bar)
  with open(output, 'w', newline='') as f:
    writer = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
    writer.writeheader()
    writer.writerows(rows)
  return rows
