"""Filter FASTQ/BAM reads by average phred quality
(reference: deepconsensus/quality_calibration/filter_reads.py:68-140).
"""
from __future__ import annotations

import logging

from deepconsensus_tpu.io import bam as bam_lib
from deepconsensus_tpu.io import fastx
from deepconsensus_tpu.utils import phred

log = logging.getLogger(__name__)


def filter_bam_or_fastq_by_quality(
    input_path: str, output_path: str, min_quality: int
) -> int:
  """Writes reads with round(avg_phred) >= min_quality; returns count."""
  kept = 0
  total = 0
  with fastx.FastqWriter(output_path) as out:
    if input_path.endswith('.bam'):
      with bam_lib.BamReader(input_path) as reader:
        for rec in reader:
          total += 1
          if rec.quals is None:
            continue
          if round(phred.avg_phred(rec.quals), 5) >= min_quality:
            out.write(
                rec.qname,
                rec.seq,
                phred.quality_scores_to_string(rec.quals),
            )
            kept += 1
    else:
      for name, seq, qual in fastx.read_fastq(input_path):
        total += 1
        scores = phred.quality_string_to_array(qual)
        if round(phred.avg_phred(scores), 5) >= min_quality:
          out.write(name, seq, qual)
          kept += 1
  log.info('filter_reads: kept %d/%d reads at q>=%d', kept, total,
           min_quality)
  return kept
