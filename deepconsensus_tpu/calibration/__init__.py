from deepconsensus_tpu.calibration.lib import (  # noqa: F401
    QualityCalibrationValues,
    calibrate_quality_scores,
    parse_calibration_string,
)
