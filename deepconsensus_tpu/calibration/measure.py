"""Empirical base-quality calibration measurement (`calibrate`).

Walks a predictions-aligned BAM against the reference genome, counting
matches/mismatches per predicted base quality; insertions and
soft-clipped bases count as mismatches (reference:
deepconsensus/quality_calibration/calculate_baseq_calibration.py:64-483).
Intervals fan out over a process pool like the reference; the
unindexed-BAM path here streams once and bins reads to intervals.
"""
from __future__ import annotations

import collections
import csv
import dataclasses
import multiprocessing
from typing import Dict, List, Optional, Tuple

import numpy as np

from deepconsensus_tpu import constants
from deepconsensus_tpu.calibration import lib as calibration_lib
from deepconsensus_tpu.io import bam as bam_lib
from deepconsensus_tpu.io import fastx

MAX_BASEQ = 100
INTERVAL_LENGTH = 1000

Cigar = constants.Cigar


@dataclasses.dataclass
class RegionRecord:
  contig: str
  start: int
  stop: int


def get_contig_regions(
    contig_lengths: Dict[str, int],
    region: Optional[str] = None,
    interval_length: int = INTERVAL_LENGTH,
) -> List[RegionRecord]:
  """Splits contigs (or one samtools-style region) into intervals
  (reference: calculate_baseq_calibration.py:190-247)."""
  regions = []
  if region:
    if ':' in region:
      contig, span = region.split(':')
      start, stop = (int(x) for x in span.split('-'))
    else:
      contig, start, stop = region, 0, contig_lengths[region]
    spans = [(contig, start, stop)]
  else:
    spans = [(c, 0, ln) for c, ln in contig_lengths.items()]
  for contig, start, stop in spans:
    pos = start
    while pos < stop:
      regions.append(
          RegionRecord(contig, pos, min(pos + interval_length - 1, stop))
      )
      pos += interval_length
  return regions


def stats_for_read(
    record: bam_lib.BamRecord,
    ref_sequence: str,
    interval: RegionRecord,
    quals: np.ndarray,
    counts: List[Dict[str, int]],
) -> None:
  """Accumulates per-quality match/mismatch counts for one read within
  one interval (reference: calculate_baseq_calibration.py:303-375)."""
  ref_pos = record.pos
  read_idx = 0
  seq = record.seq
  for op, length in zip(record.cigar_ops, record.cigar_lens):
    if ref_pos > interval.stop:
      break
    if op in (Cigar.MATCH, Cigar.DIFF, Cigar.EQUAL):
      for _ in range(length):
        if (
            interval.start <= ref_pos <= interval.stop
            and ref_pos - interval.start < len(ref_sequence)
        ):
          ref_base = ref_sequence[ref_pos - interval.start].upper()
          if ref_base in 'ACGT':
            q = int(quals[read_idx])
            key = 'M' if ref_base == seq[read_idx].upper() else 'X'
            counts[q][key] += 1
        read_idx += 1
        ref_pos += 1
    elif op in (Cigar.SOFT_CLIP, Cigar.INS):
      for _ in range(length):
        if interval.start <= ref_pos <= interval.stop:
          counts[int(quals[read_idx])]['X'] += 1
        read_idx += 1
    elif op in (Cigar.REF_SKIP, Cigar.DEL):
      ref_pos += length


# Per-worker state, set up once by the pool initializer so the
# reference FASTA parses once per worker instead of once per task.
_WORKER: Dict[str, object] = {}


def _init_worker(ref, region_by_contig, min_mapq, dc_calibration):
  _WORKER['ref_seqs'] = fastx.read_fasta(ref)
  _WORKER['regions'] = region_by_contig
  _WORKER['min_mapq'] = min_mapq
  _WORKER['cal'] = calibration_lib.parse_calibration_string(dc_calibration)


def _process_record_batch(records) -> List[Dict[str, int]]:
  counts = [{'M': 0, 'X': 0} for _ in range(MAX_BASEQ)]
  for record in records:
    _accumulate_record(
        record, _WORKER['ref_seqs'], _WORKER['regions'], _WORKER['cal'],
        _WORKER['min_mapq'], counts,
    )
  return counts


def _accumulate_record(record, ref_seqs, region_by_contig, cal, min_mapq,
                       counts) -> None:
  if (
      record.is_unmapped
      or record.is_secondary
      or record.is_supplementary
      or record.mapq < min_mapq
      or record.quals is None
      or record.reference_name not in ref_seqs
  ):
    return
  quals = record.quals
  if cal.enabled:
    quals = np.round(
        calibration_lib.calibrate_quality_scores(quals.astype(np.uint8), cal)
    ).astype(np.int32)
  # Calibration can push qualities outside the histogram range.
  quals = np.clip(quals, 0, MAX_BASEQ - 1)
  ref_end = record.pos + int(
      np.sum(
          record.cigar_lens[
              np.isin(record.cigar_ops,
                      [Cigar.MATCH, Cigar.DEL, Cigar.REF_SKIP,
                       Cigar.EQUAL, Cigar.DIFF])
          ]
      )
  )
  for interval in region_by_contig.get(record.reference_name, []):
    if interval.stop < record.pos or interval.start >= ref_end:
      continue
    ref_slice = ref_seqs[record.reference_name][
        interval.start : interval.stop + 1
    ]
    stats_for_read(record, ref_slice, interval, quals, counts)


def calculate_quality_calibration(
    bam: str,
    ref: str,
    output: str,
    region: Optional[str] = None,
    min_mapq: int = 60,
    cpus: int = 0,
    dc_calibration: str = 'skip',
) -> List[Tuple[int, int, int]]:
  """Writes CSV rows (baseq, total_match, total_mismatch); returns them.

  With cpus>1, the BAM streams once in the parent and record batches
  fan out over a process pool whose workers hold the parsed reference
  (the reference pools over interval round-robins:
  calculate_baseq_calibration.py:450-463).
  """
  reader = bam_lib.BamReader(bam)
  contig_lengths = dict(
      zip(reader.references, reader.reference_lengths)
  )
  regions = get_contig_regions(contig_lengths, region)
  region_by_contig: Dict[str, List[RegionRecord]] = collections.defaultdict(
      list
  )
  for r in regions:
    region_by_contig[r.contig].append(r)

  counts = [{'M': 0, 'X': 0} for _ in range(MAX_BASEQ)]

  if cpus and cpus > 1:

    def batches(it, size=500):
      batch = []
      for record in it:
        batch.append(record)
        if len(batch) >= size:
          yield batch
          batch = []
      if batch:
        yield batch

    with multiprocessing.Pool(
        cpus,
        initializer=_init_worker,
        initargs=(ref, dict(region_by_contig), min_mapq, dc_calibration),
    ) as pool:
      for partial in pool.imap_unordered(
          _process_record_batch, batches(reader)
      ):
        for q in range(MAX_BASEQ):
          counts[q]['M'] += partial[q]['M']
          counts[q]['X'] += partial[q]['X']
  else:
    # Only the serial path needs the reference in the parent.
    ref_seqs = fastx.read_fasta(ref)
    cal = calibration_lib.parse_calibration_string(dc_calibration)
    for record in reader:
      _accumulate_record(record, ref_seqs, region_by_contig, cal, min_mapq,
                         counts)

  rows = [
      (q, counts[q]['M'], counts[q]['X']) for q in range(MAX_BASEQ)
  ]
  with open(output, 'w', newline='') as f:
    writer = csv.writer(f)
    writer.writerow(['baseq', 'total_match', 'total_mismatch'])
    writer.writerows(rows)
  return rows
