"""Base-quality calibration: thresholded linear phred transform
(reference: deepconsensus/quality_calibration/calibration_lib.py:35-99).
"""
from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class QualityCalibrationValues:
  enabled: bool
  threshold: float
  w: float
  b: float


def parse_calibration_string(calibration: str) -> QualityCalibrationValues:
  """Parses 'threshold,w,b' or 'skip'."""
  if calibration == 'skip':
    return QualityCalibrationValues(enabled=False, threshold=0.0, w=1.0, b=0.0)
  parts = calibration.split(',')
  if len(parts) != 3:
    raise ValueError(
        'Malformed calibration string; expected "threshold,w,b" or "skip": '
        f'{calibration!r}'
    )
  return QualityCalibrationValues(
      enabled=True,
      threshold=float(parts[0]),
      w=float(parts[1]),
      b=float(parts[2]),
  )


def calibration_string(values: QualityCalibrationValues) -> str:
  """Inverse of parse_calibration_string: a CLI-pasteable string, used
  by error messages that tell the operator the exact flag to re-run
  (e.g. exported-artifact epilogue mismatches)."""
  if not values.enabled:
    return 'skip'
  return f'{values.threshold:g},{values.w:g},{values.b:g}'


def calibrate_quality_scores(
    quality_scores: np.ndarray,
    calibration_values: QualityCalibrationValues,
) -> np.ndarray:
  """Applies q*w + b to scores above the threshold (all scores when the
  threshold is zero)."""
  q = np.asarray(quality_scores)
  cv = calibration_values
  if cv.threshold == 0:
    return q * cv.w + cv.b
  w = np.where(q > cv.threshold, cv.w, 1.0)
  b = np.where(q > cv.threshold, cv.b, 0.0)
  return q * w + b
