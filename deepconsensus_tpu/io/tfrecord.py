"""Pure-Python TFRecord reader/writer with optional gzip compression.

TFRecord framing per record: little-endian uint64 length, masked crc32c of
the length bytes, payload, masked crc32c of the payload. The reference
pipeline writes gzip-compressed TFRecord shards
(reference: deepconsensus/preprocess/preprocess.py:183-196,
models/data_providers.py:346).
"""
from __future__ import annotations

import glob as globlib
import gzip
import struct
import zlib
from typing import Iterable, Iterator, List, Optional, Union

from deepconsensus_tpu.faults import CorruptInputError

# Per-record allocation cap: the length field of a TFRecord frame is
# untrusted until its CRC verifies, and even a CRC-valid length must
# stay under this bound before the payload is allocated. A window
# example in this pipeline is ~100 KiB; 64 MiB is two-plus orders of
# magnitude of headroom.
DEFAULT_MAX_RECORD_BYTES = 64 << 20

# Exceptions the gzip/zlib machinery can raise mid-stream on corrupt or
# truncated compressed input.
_DECOMPRESS_ERRORS = (EOFError, gzip.BadGzipFile, zlib.error)

# ---------------------------------------------------------------------------
# crc32c (Castagnoli), table-driven.
# ---------------------------------------------------------------------------
_CRC_TABLE = []


def _build_table() -> None:
  poly = 0x82F63B78
  for i in range(256):
    crc = i
    for _ in range(8):
      crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
    _CRC_TABLE.append(crc)


_build_table()


def _crc32c_py(data: bytes, value: int = 0) -> int:
  crc = value ^ 0xFFFFFFFF
  table = _CRC_TABLE
  for b in data:
    crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
  return crc ^ 0xFFFFFFFF


def crc32c(data: bytes, value: int = 0) -> int:
  try:
    from deepconsensus_tpu import native

    result = native.crc32c(data, value)
    if result is not None:
      return result
  # dclint: allow=typed-faults (native crc32c is an optional
  # accelerator: any failure falls back to the pure-Python CRC)
  except Exception:  # pragma: no cover
    pass
  return _crc32c_py(data, value)


def _masked_crc(data: bytes) -> int:
  crc = crc32c(data)
  return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


class TFRecordWriter:
  """Writes TFRecord files; gzip-compressed when path ends with .gz.

  compression='BGZF' writes the gzip stream as BGZF blocks (64 KiB
  independent gzip members). BGZF is valid multi-member gzip, so the
  shard stays readable by any gzip TFRecord reader (including TF's),
  while the native decode path can inflate its blocks in parallel.
  """

  def __init__(self, path: str, compression: Optional[str] = None):
    if compression is None and path.endswith('.gz'):
      compression = 'GZIP'
    if compression == 'BGZF':
      from deepconsensus_tpu.io.bam_writer import BgzfWriter

      self._f = BgzfWriter(path)
    elif compression == 'GZIP':
      self._f = gzip.open(path, 'wb')
    else:
      self._f = open(path, 'wb')

  def write(self, record: bytes) -> None:
    header = struct.pack('<Q', len(record))
    self._f.write(header)
    self._f.write(struct.pack('<I', _masked_crc(header)))
    self._f.write(record)
    self._f.write(struct.pack('<I', _masked_crc(record)))

  def close(self) -> None:
    self._f.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


# Whole-shard native decode reads the full decompressed shard into
# memory (transiently about twice: the C output buffer plus the Python
# record list); skip it for shards that would be unreasonable on the
# host (streaming fallback handles any size). The compressed cap is a
# cheap pre-check; the decompressed cap is the real bound, probed from
# BGZF per-block ISIZE fields without inflating anything. It is sized
# so a few StreamingDataset workers decoding concurrently stay bounded.
_NATIVE_MAX_COMPRESSED_BYTES = 512 * 1024 * 1024
_NATIVE_MAX_DECOMPRESSED_BYTES = 1024 * 1024 * 1024


def bgzf_decompressed_size(path: str) -> Optional[int]:
  """Total decompressed size of a BGZF file by summing block ISIZEs.

  Seeks block-to-block using the BSIZE extra subfield, so cost is two
  small reads per 64 KiB block — no inflation. Returns None unless
  EVERY member is a standard BGZF block: a partial sum or a gzip
  footer ISIZE (mod 2^32, final member only) would under-report and
  defeat the size gate, so non-conforming files report unknown and the
  native decoder's in-C output cap becomes the enforcement point."""
  try:
    with open(path, 'rb') as f:
      total = 0
      while True:
        start = f.tell()
        hdr = f.read(12)
        if not hdr:
          return total
        # gzip magic, deflate, FEXTRA set.
        if len(hdr) < 12 or hdr[:4] != b'\x1f\x8b\x08\x04':
          return None
        xlen = int.from_bytes(hdr[10:12], 'little')
        extra = f.read(xlen)
        if len(extra) < xlen:
          return None
        # Walk the FEXTRA subfields (SI1, SI2, u16 SLEN, data) for the
        # BGZF 'BC' field; the spec allows other subfields in any
        # order, so requiring XLEN == 6 would reject legal files.
        bsize = None
        off = 0
        while off + 4 <= xlen:
          si, slen = extra[off:off + 2], int.from_bytes(
              extra[off + 2:off + 4], 'little')
          off += 4
          if off + slen > xlen:
            return None  # subfield overruns XLEN: malformed
          if si == b'BC' and slen == 2:
            bsize = int.from_bytes(extra[off:off + 2], 'little') + 1
          off += slen
        if bsize is None or off != xlen:
          return None
        f.seek(start + bsize - 4)
        isize = f.read(4)
        if len(isize) < 4:
          return None  # truncated final block
        total += int.from_bytes(isize, 'little')
        # Position is already start + bsize (footer read ends there).
  except OSError:
    return None


class TFRecordReader:
  """Iterates serialized records from a TFRecord file.

  Single-pass on every path: a second iteration yields nothing (the
  contract must not depend on which decode path ran).

  native_decode=True decodes the whole shard in one native shot
  (parallel BGZF inflate for BGZF-written shards + C record framing) —
  the measured single-core bottleneck of the streaming loader. It
  materializes the shard's records in memory, so callers must consume
  shards one at a time (StreamingDataset does); the default streaming
  path holds only small buffers. check_crc or any native failure falls
  back to streaming.
  """

  def __init__(self, path: str, compression: Optional[str] = None,
               check_crc: bool = False, native_decode: bool = False,
               native_threads: int = 4,
               max_record_bytes: int = DEFAULT_MAX_RECORD_BYTES):
    if compression is None and path.endswith('.gz'):
      compression = 'GZIP'
    import os

    os.stat(path)  # fail fast on missing/unreadable paths (open is lazy)
    self._path = path
    self._compressed = compression in ('GZIP', 'BGZF')
    self._native = native_decode and not check_crc
    self._native_threads = native_threads
    self._f = None  # streaming handle, opened lazily on first use
    self._consumed = False
    self._check_crc = check_crc
    self._max_record_bytes = int(max_record_bytes)

  def _native_records(self) -> Optional[List[bytes]]:
    try:
      import os

      if os.path.getsize(self._path) > _NATIVE_MAX_COMPRESSED_BYTES:
        return None
      if self._compressed:
        # Cheap pre-gate: exact for conforming BGZF (the preprocess
        # default). Non-BGZF reports None and the in-C max_out cap
        # below is the enforcement point.
        dsize = bgzf_decompressed_size(self._path)
        if dsize is not None and dsize > _NATIVE_MAX_DECOMPRESSED_BYTES:
          return None
      from deepconsensus_tpu import native

      return native.read_tfrecord_records(
          self._path, n_threads=self._native_threads,
          compressed=self._compressed,
          max_out=_NATIVE_MAX_DECOMPRESSED_BYTES)
    # dclint: allow=typed-faults (native reader is an optional
    # accelerator: returning None routes to the Python decode path,
    # which re-raises real corruption as CorruptInputError)
    except Exception:  # pragma: no cover - any native issue -> fallback
      return None

  def __iter__(self) -> Iterator[bytes]:
    if self._consumed:
      return
    if self._native:
      records = self._native_records()
      if records is not None:
        self._consumed = True
        yield from records
        return
    # Mark consumed as soon as streaming iteration begins — same moment
    # the native path does — so a partially-consumed reader yields
    # nothing on re-iteration regardless of which decode path ran.
    self._consumed = True
    if self._f is None:
      self._f = (gzip.open(self._path, 'rb') if self._compressed
                 else open(self._path, 'rb'))

    def checked_read(n: int, what: str, offset: int) -> bytes:
      try:
        return self._f.read(n)
      except _DECOMPRESS_ERRORS as e:
        raise CorruptInputError(
            f'compressed TFRecord stream corrupt or truncated reading '
            f'{what} ({type(e).__name__}: {e})',
            path=self._path, offset=offset) from e

    offset = 0  # decompressed-stream offset of the current frame
    while True:
      header = checked_read(8, 'length header', offset)
      if not header:
        return
      if len(header) != 8:
        raise CorruptInputError(
            'truncated TFRecord length header',
            path=self._path, offset=offset)
      (length,) = struct.unpack('<Q', header)
      len_crc = checked_read(4, 'length crc', offset)
      if len(len_crc) != 4:
        raise CorruptInputError(
            'truncated TFRecord length crc', path=self._path, offset=offset)
      # The length field is untrusted until its CRC verifies; check it
      # unconditionally (not just under check_crc) BEFORE allocating
      # `length` bytes — a corrupt header must not OOM the host.
      if struct.unpack('<I', len_crc)[0] != _masked_crc(header):
        raise CorruptInputError(
            'TFRecord length crc mismatch', path=self._path, offset=offset)
      if length > self._max_record_bytes:
        raise CorruptInputError(
            f'TFRecord length {length} exceeds max_record_bytes '
            f'{self._max_record_bytes}', path=self._path, offset=offset)
      data = checked_read(length, 'payload', offset)
      data_crc = checked_read(4, 'payload crc', offset)
      if len(data) != length or len(data_crc) != 4:
        raise CorruptInputError(
            'truncated TFRecord payload', path=self._path, offset=offset)
      if self._check_crc:
        if struct.unpack('<I', data_crc)[0] != _masked_crc(data):
          raise CorruptInputError(
              'TFRecord data crc mismatch', path=self._path, offset=offset)
      offset += 8 + 4 + length + 4
      yield data

  def close(self) -> None:
    if self._f is not None:
      self._f.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


def glob_paths(patterns: Union[str, Iterable[str]]) -> List[str]:
  if isinstance(patterns, str):
    patterns = [patterns]
  out: List[str] = []
  for p in patterns:
    matches = sorted(globlib.glob(p))
    out.extend(matches if matches else ([p] if '*' not in p else []))
  return out


def read_tfrecords(patterns: Union[str, Iterable[str]],
                   check_crc: bool = False) -> Iterator[bytes]:
  """Yields all serialized records matching the glob pattern(s).

  Shards are consumed one at a time, so the native whole-shard decode
  is safe here (bounded by the largest single shard)."""
  for path in glob_paths(patterns):
    # The reader itself gates native decode off when check_crc is set.
    with TFRecordReader(path, check_crc=check_crc,
                        native_decode=True) as reader:
      yield from reader
