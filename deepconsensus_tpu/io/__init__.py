from deepconsensus_tpu.io.example_proto import Example  # noqa: F401
from deepconsensus_tpu.io.tfrecord import (  # noqa: F401
    TFRecordReader,
    TFRecordWriter,
    read_tfrecords,
)
