"""Minimal FASTA/FASTQ reading and FASTQ writing (gzip-aware).

Hardened against untrusted input: structural violations (a FASTQ record
whose separator line is not '+', mismatched sequence/quality lengths,
EOF mid-record, lines beyond a per-record cap, undecodable bytes,
corrupt gzip streams) raise the typed
``deepconsensus_tpu.faults.CorruptInputError`` naming file and line
instead of silently mis-parsing or leaking a codec/zlib error.
"""
from __future__ import annotations

import gzip
import zlib
from typing import Dict, Iterator, Tuple

from deepconsensus_tpu.faults import CorruptInputError

# Longest single line accepted (sequence/quality lines; a CCS read is a
# few hundred KiB). readline() is capped at this so a corrupt stream
# with no newlines cannot buffer unbounded bytes.
MAX_LINE_BYTES = 64 << 20

_DECOMPRESS_ERRORS = (EOFError, gzip.BadGzipFile, zlib.error)


def _open(path: str, mode: str = 'rt'):
  if path.endswith('.gz'):
    return gzip.open(path, mode)
  return open(path, mode)


def _readline(f, path: str, lineno: int) -> str:
  """Bounded, error-wrapped readline: decompression/codec failures and
  over-long lines raise CorruptInputError naming file + line."""
  try:
    line = f.readline(MAX_LINE_BYTES)
  except _DECOMPRESS_ERRORS as e:
    raise CorruptInputError(
        f'compressed stream corrupt or truncated at line {lineno} '
        f'({type(e).__name__}: {e})', path=path, offset=lineno) from e
  except (UnicodeDecodeError, ValueError) as e:
    raise CorruptInputError(
        f'undecodable text at line {lineno} ({e})',
        path=path, offset=lineno) from e
  if len(line) >= MAX_LINE_BYTES and not line.endswith('\n'):
    raise CorruptInputError(
        f'line {lineno} exceeds {MAX_LINE_BYTES} bytes',
        path=path, offset=lineno)
  return line


def read_fasta(path: str) -> Dict[str, str]:
  """Loads a FASTA file into {name: sequence}."""
  seqs: Dict[str, str] = {}
  name = None
  parts = []
  lineno = 0
  with _open(path) as f:
    while True:
      lineno += 1
      line = _readline(f, path, lineno)
      if not line:
        break
      line = line.rstrip('\n')
      if line.startswith('>'):
        if name is not None:
          seqs[name] = ''.join(parts)
        fields = line[1:].split()
        if not fields:
          raise CorruptInputError(
              f'FASTA header with no name at line {lineno}',
              path=path, offset=lineno)
        name = fields[0]
        parts = []
      else:
        if name is None and line:
          raise CorruptInputError(
              f'FASTA sequence data before any header at line {lineno}',
              path=path, offset=lineno)
        parts.append(line)
  if name is not None:
    seqs[name] = ''.join(parts)
  return seqs


def read_fastq(path: str) -> Iterator[Tuple[str, str, str]]:
  """Yields (name, sequence, quality_string)."""
  with _open(path) as f:
    lineno = 0
    while True:
      header = _readline(f, path, lineno + 1)
      if not header:
        return
      seq = _readline(f, path, lineno + 2)
      plus = _readline(f, path, lineno + 3)
      qual = _readline(f, path, lineno + 4)
      if not header.startswith('@'):
        raise CorruptInputError(
            f'FASTQ record header at line {lineno + 1} does not start '
            f'with "@"', path=path, offset=lineno + 1)
      if not qual:
        raise CorruptInputError(
            f'truncated FASTQ record starting at line {lineno + 1} '
            f'(stream ended mid-record)', path=path, offset=lineno + 1)
      if not plus.startswith('+'):
        raise CorruptInputError(
            f'FASTQ separator at line {lineno + 3} is not "+"',
            path=path, offset=lineno + 3)
      seq = seq.rstrip('\n')
      qual = qual.rstrip('\n')
      if len(seq) != len(qual):
        raise CorruptInputError(
            f'FASTQ record at line {lineno + 1} has sequence length '
            f'{len(seq)} but quality length {len(qual)}',
            path=path, offset=lineno + 1)
      lineno += 4
      yield header.rstrip('\n')[1:], seq, qual


class FastqWriter:
  def __init__(self, path: str):
    self._f = _open(path, 'wt')

  def write(self, name: str, sequence: str, quality_string: str) -> None:
    self._f.write(f'@{name}\n{sequence}\n+\n{quality_string}\n')

  def close(self) -> None:
    self._f.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()
