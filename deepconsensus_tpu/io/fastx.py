"""Minimal FASTA/FASTQ reading and FASTQ writing (gzip-aware)."""
from __future__ import annotations

import gzip
from typing import Dict, Iterator, Tuple


def _open(path: str, mode: str = 'rt'):
  if path.endswith('.gz'):
    return gzip.open(path, mode)
  return open(path, mode)


def read_fasta(path: str) -> Dict[str, str]:
  """Loads a FASTA file into {name: sequence}."""
  seqs: Dict[str, str] = {}
  name = None
  parts = []
  with _open(path) as f:
    for line in f:
      line = line.rstrip('\n')
      if line.startswith('>'):
        if name is not None:
          seqs[name] = ''.join(parts)
        name = line[1:].split()[0]
        parts = []
      else:
        parts.append(line)
  if name is not None:
    seqs[name] = ''.join(parts)
  return seqs


def read_fastq(path: str) -> Iterator[Tuple[str, str, str]]:
  """Yields (name, sequence, quality_string)."""
  with _open(path) as f:
    while True:
      header = f.readline()
      if not header:
        return
      seq = f.readline().rstrip('\n')
      f.readline()  # '+'
      qual = f.readline().rstrip('\n')
      yield header.rstrip('\n')[1:], seq, qual


class FastqWriter:
  def __init__(self, path: str):
    self._f = _open(path, 'wt')

  def write(self, name: str, sequence: str, quality_string: str) -> None:
    self._f.write(f'@{name}\n{sequence}\n+\n{quality_string}\n')

  def close(self) -> None:
    self._f.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()
