"""Minimal, dependency-free tf.train.Example protobuf codec.

The training examples written by the reference pipeline are serialized
`tf.train.Example` protos inside gzipped TFRecord files
(reference: deepconsensus/preprocess/pre_lib.py:764-787 and
models/data_providers.py:41-58). To stay free of a TensorFlow dependency
in the core framework we speak the wire format directly; the schema is a
flat map<string, Feature> where Feature is a oneof{BytesList, FloatList,
Int64List}. This file implements exactly that subset of proto2.
"""
from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Tuple, Union

from deepconsensus_tpu.faults import CorruptInputError

FeatureValue = Union[List[bytes], List[float], List[int]]

_BYTES_KIND = 1
_FLOAT_KIND = 2
_INT64_KIND = 3

_KIND_NAMES = {_BYTES_KIND: 'bytes', _FLOAT_KIND: 'float', _INT64_KIND: 'int64'}


def _write_varint(out: bytearray, value: int) -> None:
  while True:
    bits = value & 0x7F
    value >>= 7
    if value:
      out.append(bits | 0x80)
    else:
      out.append(bits)
      return


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
  result = 0
  shift = 0
  while True:
    b = buf[pos]
    pos += 1
    result |= (b & 0x7F) << shift
    if not b & 0x80:
      return result, pos
    shift += 7


def _zigzag_decode_int64(value: int) -> int:
  # int64 fields are encoded as plain (non-zigzag) varints; negative values
  # occupy 10 bytes in two's complement. Normalize to signed.
  if value >= 1 << 63:
    value -= 1 << 64
  return value


def _encode_int64(value: int) -> int:
  if value < 0:
    value += 1 << 64
  return value


class Example:
  """A flat feature map with the same API shape as tf.train.Example usage.

  features: dict name -> (kind, list) where kind in {'bytes','float','int64'}.
  """

  def __init__(self):
    self.features: Dict[str, Tuple[str, FeatureValue]] = {}

  # ---- building --------------------------------------------------------
  def add_bytes(self, name: str, values: List[bytes]) -> 'Example':
    self.features[name] = ('bytes', list(values))
    return self

  def add_float(self, name: str, values) -> 'Example':
    self.features[name] = ('float', [float(v) for v in values])
    return self

  def add_int64(self, name: str, values) -> 'Example':
    self.features[name] = ('int64', [int(v) for v in values])
    return self

  # ---- accessors -------------------------------------------------------
  def __contains__(self, name: str) -> bool:
    return name in self.features

  def kind(self, name: str) -> str:
    return self.features[name][0]

  def __getitem__(self, name: str) -> FeatureValue:
    return self.features[name][1]

  def get(self, name: str, default=None):
    entry = self.features.get(name)
    return entry[1] if entry is not None else default

  # ---- serialization ---------------------------------------------------
  def _serialize_feature(self, kind: str, values: FeatureValue) -> bytes:
    inner = bytearray()
    if kind == 'bytes':
      for v in values:
        inner.append((1 << 3) | 2)  # field 1, length-delimited
        _write_varint(inner, len(v))
        inner += v
      field_num = _BYTES_KIND
    elif kind == 'float':
      packed = struct.pack(f'<{len(values)}f', *values)
      inner.append((1 << 3) | 2)
      _write_varint(inner, len(packed))
      inner += packed
      field_num = _FLOAT_KIND
    elif kind == 'int64':
      packed = bytearray()
      for v in values:
        _write_varint(packed, _encode_int64(v))
      inner.append((1 << 3) | 2)
      _write_varint(inner, len(packed))
      inner += packed
      field_num = _INT64_KIND
    else:
      # dclint: allow=typed-faults (serialisation path: the kind comes
      # from our own feature tables, so this is a programmer error)
      raise ValueError(f'unknown feature kind {kind!r}')
    out = bytearray()
    out.append((field_num << 3) | 2)
    _write_varint(out, len(inner))
    out += inner
    return bytes(out)

  def serialize(self) -> bytes:
    features_msg = bytearray()
    # Deterministic ordering for reproducible bytes.
    for name in sorted(self.features):
      kind, values = self.features[name]
      entry = bytearray()
      key_bytes = name.encode('utf-8')
      entry.append((1 << 3) | 2)
      _write_varint(entry, len(key_bytes))
      entry += key_bytes
      feat = self._serialize_feature(kind, values)
      entry.append((2 << 3) | 2)
      _write_varint(entry, len(feat))
      entry += feat
      features_msg.append((1 << 3) | 2)  # Features.feature map entry
      _write_varint(features_msg, len(entry))
      features_msg += entry
    out = bytearray()
    out.append((1 << 3) | 2)  # Example.features
    _write_varint(out, len(features_msg))
    out += features_msg
    return bytes(out)

  # ---- parsing ---------------------------------------------------------
  @staticmethod
  def _iter_fields(buf: bytes, start: int, end: int) -> Iterator[Tuple[int, int, bytes]]:
    """Yields (field_number, wire_type, payload) for length/varint fields."""
    pos = start
    while pos < end:
      tag, pos = _read_varint(buf, pos)
      field_num, wire_type = tag >> 3, tag & 7
      if wire_type == 2:
        length, pos = _read_varint(buf, pos)
        yield field_num, wire_type, buf[pos : pos + length]
        pos += length
      elif wire_type == 0:
        value, pos = _read_varint(buf, pos)
        yield field_num, wire_type, value
      elif wire_type == 5:
        yield field_num, wire_type, buf[pos : pos + 4]
        pos += 4
      elif wire_type == 1:
        yield field_num, wire_type, buf[pos : pos + 8]
        pos += 8
      else:
        raise CorruptInputError(
            f'unsupported wire type {wire_type}', offset=pos,
            recoverable=False)

  @classmethod
  def _parse_feature(cls, buf: bytes) -> Tuple[str, FeatureValue]:
    for field_num, wire_type, payload in cls._iter_fields(buf, 0, len(buf)):
      kind = _KIND_NAMES.get(field_num)
      if kind is None:
        continue
      values: FeatureValue = []
      for f2, w2, inner in cls._iter_fields(payload, 0, len(payload)):
        if f2 != 1:
          continue
        if kind == 'bytes':
          values.append(inner)
        elif kind == 'float':
          if w2 == 2:
            values.extend(struct.unpack(f'<{len(inner) // 4}f', inner))
          else:  # unpacked fixed32
            values.append(struct.unpack('<f', inner)[0])
        else:  # int64
          if w2 == 2:
            pos = 0
            while pos < len(inner):
              v, pos = _read_varint(inner, pos)
              values.append(_zigzag_decode_int64(v))
          else:
            values.append(_zigzag_decode_int64(inner))
      return kind, values
    return 'bytes', []

  @classmethod
  def parse(cls, data: bytes, fields=None) -> 'Example':
    """Parses a serialized Example.

    fields: optional collection of feature names; payloads of other
    features are skipped without decoding (the per-varint walk of
    unneeded int64 lists is the measured hot spot of the training
    input pipeline).
    """
    ex = cls()
    for field_num, _, features_buf in cls._iter_fields(data, 0, len(data)):
      if field_num != 1:
        continue
      for f2, _, entry in cls._iter_fields(features_buf, 0, len(features_buf)):
        if f2 != 1:
          continue
        key = None
        feat_buf = None
        for f3, _, payload in cls._iter_fields(entry, 0, len(entry)):
          if f3 == 1:
            key = payload.decode('utf-8')
          elif f3 == 2:
            feat_buf = payload
        if key is not None and feat_buf is not None:
          if fields is not None and key not in fields:
            continue
          kind, values = cls._parse_feature(feat_buf)
          ex.features[key] = (kind, values)
    return ex
