"""Dependency-free BAM reading (BGZF + BAM record + aux tag parsing).

The reference relies on pysam/htslib for all BAM I/O
(reference: deepconsensus/preprocess/pre_lib.py:50-91,966-998). This
module implements the BAM spec (SAMv1, section 4) directly so the
framework needs no native htslib: BGZF files are concatenated gzip
members, which Python's gzip module decompresses transparently; records
are fixed-layout structs parsed with struct/numpy.

A C++ accelerated reader (ops/native) can drop in behind the same API;
this file is the always-available fallback and the semantics reference.
"""
from __future__ import annotations

import gzip
import struct
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from deepconsensus_tpu import constants

# 4-bit encoded base alphabet from the SAM spec.
SEQ_NIBBLE = '=ACMGRSVTWYHKDBN'
_NIBBLE_LUT = np.frombuffer(SEQ_NIBBLE.encode('ascii'), dtype=np.uint8)

# flag bits
FUNMAP = 0x4
FREVERSE = 0x10
FSECONDARY = 0x100
FSUPPLEMENTARY = 0x800

_TAG_FMT = {
    ord('A'): ('c', 1),
    ord('c'): ('b', 1),
    ord('C'): ('B', 1),
    ord('s'): ('h', 2),
    ord('S'): ('H', 2),
    ord('i'): ('i', 4),
    ord('I'): ('I', 4),
    ord('f'): ('f', 4),
}

_B_DTYPES = {
    ord('c'): np.int8,
    ord('C'): np.uint8,
    ord('s'): np.int16,
    ord('S'): np.uint16,
    ord('i'): np.int32,
    ord('I'): np.uint32,
    ord('f'): np.float32,
}

# Ops consuming query bases / reference bases (SAMv1 table).
_QUERY_OPS = np.array([1, 1, 0, 0, 1, 0, 0, 1, 1, 0], dtype=bool)
_REF_OPS = np.array([1, 0, 1, 1, 0, 0, 0, 1, 1, 0], dtype=bool)


class TruncatedBamError(IOError):
  """The BAM stream ended mid-record (or mid-BGZF-block).

  Raised as a distinct type so the inference quarantine layer
  (inference/faults.py) can classify it as a decode-stage fault: a
  truncated stream cannot be advanced past, unlike a single malformed
  record."""


@dataclass
class BamRecord:
  """One BAM alignment record."""

  qname: str
  flag: int
  ref_id: int
  pos: int  # 0-based leftmost coordinate
  mapq: int
  cigar_ops: np.ndarray  # uint8 op codes
  cigar_lens: np.ndarray  # int32 lengths
  seq: str
  quals: Optional[np.ndarray]  # int32 phred values, None if absent (0xff)
  tags: Dict[str, Any] = field(default_factory=dict)
  reference_name: Optional[str] = None

  @property
  def is_unmapped(self) -> bool:
    return bool(self.flag & FUNMAP)

  @property
  def is_reverse(self) -> bool:
    return bool(self.flag & FREVERSE)

  @property
  def is_supplementary(self) -> bool:
    return bool(self.flag & FSUPPLEMENTARY)

  @property
  def is_secondary(self) -> bool:
    return bool(self.flag & FSECONDARY)

  @property
  def cigartuples(self) -> List[Tuple[int, int]]:
    return list(zip(self.cigar_ops.tolist(), self.cigar_lens.tolist()))

  def get_tag(self, name: str):
    return self.tags[name]

  def has_tag(self, name: str) -> bool:
    return name in self.tags

  @property
  def query_alignment_start(self) -> int:
    """Index of the first non-soft-clipped base of seq."""
    start = 0
    for op, ln in zip(self.cigar_ops, self.cigar_lens):
      if op == constants.Cigar.SOFT_CLIP:
        start += int(ln)
      elif op != constants.Cigar.HARD_CLIP:
        break
    return start

  @property
  def query_alignment_end(self) -> int:
    """One past the last non-soft-clipped base of seq."""
    end = len(self.seq)
    for op, ln in zip(self.cigar_ops[::-1], self.cigar_lens[::-1]):
      if op == constants.Cigar.SOFT_CLIP:
        end -= int(ln)
      elif op != constants.Cigar.HARD_CLIP:
        break
    return end

  def expanded_cigar(self) -> np.ndarray:
    """Per-position cigar ops (uint8), hard clips excluded."""
    keep = self.cigar_ops != constants.Cigar.HARD_CLIP
    return np.repeat(self.cigar_ops[keep], self.cigar_lens[keep])

  def aligned_index_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized equivalent of pysam get_aligned_pairs().

    Returns (read_idx, ref_idx): for every alignment column (expanded
    cigar without hard clips), the query index or -1, and the reference
    index or -1 (reference: pre_lib.py:1157-1161).
    """
    ops = self.expanded_cigar()
    q_mask = _QUERY_OPS[ops]
    r_mask = _REF_OPS[ops]
    read_idx = np.where(q_mask, np.cumsum(q_mask) - 1, -1).astype(np.int64)
    ref_idx = np.where(r_mask, self.pos + np.cumsum(r_mask) - 1, -1).astype(
        np.int64
    )
    return read_idx, ref_idx


def _parse_tags(buf: memoryview) -> Dict[str, Any]:
  tags: Dict[str, Any] = {}
  pos = 0
  n = len(buf)
  raw = bytes(buf)
  while pos < n - 2:
    tag = raw[pos : pos + 2].decode('ascii')
    val_type = raw[pos + 2]
    pos += 3
    if val_type in _TAG_FMT:
      fmt, size = _TAG_FMT[val_type]
      (value,) = struct.unpack_from('<' + fmt, raw, pos)
      if val_type == ord('A'):
        value = value.decode('ascii')
      pos += size
    elif val_type in (ord('Z'), ord('H')):
      end = raw.index(b'\x00', pos)
      value = raw[pos:end].decode('ascii')
      pos = end + 1
    elif val_type == ord('B'):
      subtype = raw[pos]
      (count,) = struct.unpack_from('<I', raw, pos + 1)
      dtype = _B_DTYPES[subtype]
      itemsize = np.dtype(dtype).itemsize
      value = np.frombuffer(
          raw, dtype=dtype, count=count, offset=pos + 5
      ).copy()
      pos += 5 + count * itemsize
    else:
      raise ValueError(f'unknown BAM tag type {chr(val_type)!r}')
    tags[tag] = value
  return tags


def parse_record(data: bytes, references: List[str]) -> BamRecord:
  """Parses one BAM alignment block (excluding the block_size prefix)."""
  (
      ref_id,
      pos,
      l_read_name,
      mapq,
      _bin,
      n_cigar_op,
      flag,
      l_seq,
      _next_ref,
      _next_pos,
      _tlen,
  ) = struct.unpack_from('<iiBBHHHiiii', data, 0)
  off = 32
  qname = data[off : off + l_read_name - 1].decode('ascii')
  off += l_read_name
  cigar_raw = np.frombuffer(data, dtype=np.uint32, count=n_cigar_op, offset=off)
  cigar_ops = (cigar_raw & 0xF).astype(np.uint8)
  cigar_lens = (cigar_raw >> 4).astype(np.int32)
  off += 4 * n_cigar_op
  n_seq_bytes = (l_seq + 1) // 2
  packed = np.frombuffer(data, dtype=np.uint8, count=n_seq_bytes, offset=off)
  nibbles = np.empty(n_seq_bytes * 2, dtype=np.uint8)
  nibbles[0::2] = packed >> 4
  nibbles[1::2] = packed & 0xF
  seq = _NIBBLE_LUT[nibbles[:l_seq]].tobytes().decode('ascii')
  off += n_seq_bytes
  quals_raw = np.frombuffer(data, dtype=np.uint8, count=l_seq, offset=off)
  # htslib marks absent qualities with 0xFF in EVERY byte; a legitimate
  # first qual of 0xFF alone must not be treated as missing.
  if l_seq and quals_raw[0] == 0xFF and np.all(quals_raw == 0xFF):
    quals = None
  else:
    quals = quals_raw.astype(np.int32)
  off += l_seq
  tags = _parse_tags(memoryview(data)[off:])
  ref_name = references[ref_id] if 0 <= ref_id < len(references) else None
  return BamRecord(
      qname=qname,
      flag=flag,
      ref_id=ref_id,
      pos=pos,
      mapq=mapq,
      cigar_ops=cigar_ops,
      cigar_lens=cigar_lens,
      seq=seq,
      quals=quals,
      tags=tags,
      reference_name=ref_name,
  )


class BamReader:
  """Streams records from a BAM file in file order.

  When the native library is available and the file is modest, BGZF
  blocks decompress in parallel in C++ (htslib-style); otherwise the
  gzip module streams the concatenated members.
  """

  NATIVE_MAX_BYTES = 4 << 30

  def __init__(self, path: str, use_native: bool = True,
               native_threads: int = 4):
    self.path = path
    self._f = None
    if use_native:
      try:
        import os

        from deepconsensus_tpu import native

        if os.path.getsize(path) <= self.NATIVE_MAX_BYTES:
          data = native.bgzf_decompress_file(path, native_threads)
          if data is not None:
            import io

            self._f = io.BytesIO(data)
      except Exception:  # pragma: no cover - fallback path
        self._f = None
    if self._f is None:
      self._f = gzip.open(path, 'rb')
    magic = self._f.read(4)
    if magic != b'BAM\x01':
      raise IOError(f'{path} is not a BAM file (magic={magic!r})')
    (l_text,) = struct.unpack('<i', self._f.read(4))
    self.header_text = self._f.read(l_text).decode('utf-8', errors='replace')
    (n_ref,) = struct.unpack('<i', self._f.read(4))
    self.references: List[str] = []
    self.reference_lengths: List[int] = []
    for _ in range(n_ref):
      (l_name,) = struct.unpack('<i', self._f.read(4))
      name = self._f.read(l_name)[:-1].decode('ascii')
      (l_ref,) = struct.unpack('<i', self._f.read(4))
      self.references.append(name)
      self.reference_lengths.append(l_ref)

  def __iter__(self) -> Iterator[BamRecord]:
    read = self._f.read
    refs = self.references
    while True:
      try:
        size_bytes = read(4)
        if not size_bytes:
          return
        if len(size_bytes) != 4:
          raise TruncatedBamError(
              f'{self.path}: truncated BAM record header')
        (block_size,) = struct.unpack('<i', size_bytes)
        data = read(block_size)
        if len(data) != block_size:
          raise TruncatedBamError(f'{self.path}: truncated BAM record')
      except (EOFError, gzip.BadGzipFile) as e:
        # gzip raises when a BGZF member is cut mid-block; normalize to
        # the taxonomy's decode-stage truncation type.
        raise TruncatedBamError(
            f'{self.path}: BGZF stream truncated ({e})') from e
      yield parse_record(data, refs)

  def close(self) -> None:
    self._f.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


class SubreadGrouper:
  """Yields the mapped subreads of one ZMW at a time.

  Relies on the input being grouped by the `zm` tag, as written by actc
  (reference: pre_lib.py:50-91).
  """

  def __init__(self, subreads_to_ccs: str):
    self.reader = BamReader(subreads_to_ccs)
    self._iter = iter(self.reader)
    self._pending: List[BamRecord] = []
    self._zmw: Optional[int] = None

  def __iter__(self) -> Iterator[List[BamRecord]]:
    for read in self._iter:
      if read.is_unmapped:
        continue
      zmw = int(read.get_tag('zm'))
      if self._zmw is None:
        self._zmw = zmw
      if zmw == self._zmw:
        self._pending.append(read)
      else:
        group = self._pending
        self._pending = [read]
        self._zmw = zmw
        if group:
          yield group
    if self._pending:
      yield self._pending


def read_bam_by_name(path: str) -> Dict[str, List[BamRecord]]:
  """Loads a (small) BAM keyed by reference name, e.g. truth_to_ccs.

  Replaces pysam's indexed fetch(ccs_seqname) used for label lookup
  (reference: pre_lib.py:1001-1014) with a single in-memory pass.
  """
  by_ref: Dict[str, List[BamRecord]] = {}
  with BamReader(path) as reader:
    for record in reader:
      if record.is_unmapped or record.reference_name is None:
        continue
      by_ref.setdefault(record.reference_name, []).append(record)
  return by_ref
