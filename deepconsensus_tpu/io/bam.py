"""Dependency-free BAM reading (BGZF + BAM record + aux tag parsing).

The reference relies on pysam/htslib for all BAM I/O
(reference: deepconsensus/preprocess/pre_lib.py:50-91,966-998). This
module implements the BAM spec (SAMv1, section 4) directly so the
framework needs no native htslib: BGZF files are concatenated gzip
members, which Python's gzip module decompresses transparently; records
are fixed-layout structs parsed with struct/numpy.

A C++ accelerated reader (ops/native) can drop in behind the same API;
this file is the always-available fallback and the semantics reference.

Untrusted-input hardening: every length/count field read from the file
(block_size, l_text, n_ref, l_name, l_read_name, n_cigar_op, l_seq, tag
counts) is validated against the remaining buffer and a configurable
``max_record_bytes`` cap *before* any allocation, and every short read
is detected. Violations raise the typed
``deepconsensus_tpu.faults.CorruptInputError`` (or its stream-level
subclass ``TruncatedBamError``) carrying file, byte offset, and read
context — never a bare ``struct.error``/``ValueError``/``MemoryError``.
Record-body damage inside intact framing is *recoverable*: the reader
is positioned at the next record when it raises, so callers (or
``skip_corrupt_records=True``) can keep streaming.
"""
from __future__ import annotations

import gzip
import struct
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from deepconsensus_tpu import constants
from deepconsensus_tpu.faults import CorruptInputError

# 4-bit encoded base alphabet from the SAM spec.
SEQ_NIBBLE = '=ACMGRSVTWYHKDBN'
_NIBBLE_LUT = np.frombuffer(SEQ_NIBBLE.encode('ascii'), dtype=np.uint8)

# flag bits
FUNMAP = 0x4
FREVERSE = 0x10
FSECONDARY = 0x100
FSUPPLEMENTARY = 0x800

_TAG_FMT = {
    ord('A'): ('c', 1),
    ord('c'): ('b', 1),
    ord('C'): ('B', 1),
    ord('s'): ('h', 2),
    ord('S'): ('H', 2),
    ord('i'): ('i', 4),
    ord('I'): ('I', 4),
    ord('f'): ('f', 4),
}

_B_DTYPES = {
    ord('c'): np.int8,
    ord('C'): np.uint8,
    ord('s'): np.int16,
    ord('S'): np.uint16,
    ord('i'): np.int32,
    ord('I'): np.uint32,
    ord('f'): np.float32,
}

# Ops consuming query bases / reference bases (SAMv1 table).
_QUERY_OPS = np.array([1, 1, 0, 0, 1, 0, 0, 1, 1, 0], dtype=bool)
_REF_OPS = np.array([1, 0, 1, 1, 0, 0, 0, 1, 1, 0], dtype=bool)

# Default per-record allocation cap (--max_record_bytes). A real PacBio
# subread record is a few hundred KiB at most; 64 MiB leaves two orders
# of magnitude of headroom while keeping a flipped length byte from
# allocating gigabytes.
DEFAULT_MAX_RECORD_BYTES = 64 << 20

# Reference names are capped well above any real assembly's (a PacBio
# ccs reference name is ~40 chars); a corrupt l_name must not allocate.
_MAX_REF_NAME_BYTES = 65536
# n_ref guard: each reference entry needs >= 9 bytes of stream, so this
# cap can never reject a legitimate header that the stream can back.
_MAX_N_REF = 500_000_000

# Exceptions the gzip module (and the zlib machinery underneath it) can
# raise mid-stream on corrupt/truncated BGZF members.
_DECOMPRESS_ERRORS = (EOFError, gzip.BadGzipFile, zlib.error)


class TruncatedBamError(CorruptInputError):
  """The BAM stream ended mid-record (or mid-BGZF-block).

  Raised as a distinct type so the inference quarantine layer
  (inference/faults.py) can classify it as a decode-stage fault: a
  truncated stream cannot be advanced past, unlike a single malformed
  record (``recoverable`` is always False)."""


@dataclass
class BamRecord:
  """One BAM alignment record."""

  qname: str
  flag: int
  ref_id: int
  pos: int  # 0-based leftmost coordinate
  mapq: int
  cigar_ops: np.ndarray  # uint8 op codes
  cigar_lens: np.ndarray  # int32 lengths
  seq: str
  quals: Optional[np.ndarray]  # int32 phred values, None if absent (0xff)
  tags: Dict[str, Any] = field(default_factory=dict)
  reference_name: Optional[str] = None

  @property
  def is_unmapped(self) -> bool:
    return bool(self.flag & FUNMAP)

  @property
  def is_reverse(self) -> bool:
    return bool(self.flag & FREVERSE)

  @property
  def is_supplementary(self) -> bool:
    return bool(self.flag & FSUPPLEMENTARY)

  @property
  def is_secondary(self) -> bool:
    return bool(self.flag & FSECONDARY)

  @property
  def cigartuples(self) -> List[Tuple[int, int]]:
    return list(zip(self.cigar_ops.tolist(), self.cigar_lens.tolist()))

  def get_tag(self, name: str):
    return self.tags[name]

  def has_tag(self, name: str) -> bool:
    return name in self.tags

  @property
  def query_alignment_start(self) -> int:
    """Index of the first non-soft-clipped base of seq."""
    start = 0
    for op, ln in zip(self.cigar_ops, self.cigar_lens):
      if op == constants.Cigar.SOFT_CLIP:
        start += int(ln)
      elif op != constants.Cigar.HARD_CLIP:
        break
    return start

  @property
  def query_alignment_end(self) -> int:
    """One past the last non-soft-clipped base of seq."""
    end = len(self.seq)
    for op, ln in zip(self.cigar_ops[::-1], self.cigar_lens[::-1]):
      if op == constants.Cigar.SOFT_CLIP:
        end -= int(ln)
      elif op != constants.Cigar.HARD_CLIP:
        break
    return end

  def expanded_cigar(self) -> np.ndarray:
    """Per-position cigar ops (uint8), hard clips excluded."""
    keep = self.cigar_ops != constants.Cigar.HARD_CLIP
    return np.repeat(self.cigar_ops[keep], self.cigar_lens[keep])

  def aligned_index_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized equivalent of pysam get_aligned_pairs().

    Returns (read_idx, ref_idx): for every alignment column (expanded
    cigar without hard clips), the query index or -1, and the reference
    index or -1 (reference: pre_lib.py:1157-1161).
    """
    ops = self.expanded_cigar()
    q_mask = _QUERY_OPS[ops]
    r_mask = _REF_OPS[ops]
    read_idx = np.where(q_mask, np.cumsum(q_mask) - 1, -1).astype(np.int64)
    ref_idx = np.where(r_mask, self.pos + np.cumsum(r_mask) - 1, -1).astype(
        np.int64
    )
    return read_idx, ref_idx


def _parse_tags(buf: memoryview, path: Optional[str] = None,
                qname: Optional[str] = None) -> Dict[str, Any]:
  """Parses the aux-tag region of one record with full bounds checks.

  Every count/size field and string scan is validated against the
  buffer before use; violations raise CorruptInputError carrying the
  read name + file so one bad tag is attributable (recoverable: the
  caller's record framing is intact)."""
  tags: Dict[str, Any] = {}
  pos = 0
  n = len(buf)
  raw = bytes(buf)

  def corrupt(msg: str) -> CorruptInputError:
    return CorruptInputError(msg, path=path, zmw=qname, recoverable=True)

  while pos < n:
    if n - pos < 3:
      raise corrupt(
          f'{n - pos} trailing byte(s) after the last BAM tag')
    try:
      tag = raw[pos : pos + 2].decode('ascii')
    except UnicodeDecodeError:
      raise corrupt(f'non-ASCII BAM tag name {raw[pos:pos + 2]!r}')
    val_type = raw[pos + 2]
    pos += 3
    if val_type in _TAG_FMT:
      fmt, size = _TAG_FMT[val_type]
      if pos + size > n:
        raise corrupt(
            f'BAM tag {tag}:{chr(val_type)} overruns the record '
            f'(needs {size} byte(s), {n - pos} left)')
      (value,) = struct.unpack_from('<' + fmt, raw, pos)
      if val_type == ord('A'):
        try:
          value = value.decode('ascii')
        except UnicodeDecodeError:
          raise corrupt(f'non-ASCII value for BAM tag {tag}:A')
      pos += size
    elif val_type in (ord('Z'), ord('H')):
      end = raw.find(b'\x00', pos)
      if end < 0:
        raise corrupt(f'unterminated string for BAM tag {tag}')
      try:
        value = raw[pos:end].decode('ascii')
      except UnicodeDecodeError:
        raise corrupt(f'non-ASCII string for BAM tag {tag}')
      pos = end + 1
    elif val_type == ord('B'):
      if pos + 5 > n:
        raise corrupt(f'truncated B-array header for BAM tag {tag}')
      subtype = raw[pos]
      dtype = _B_DTYPES.get(subtype)
      if dtype is None:
        raise corrupt(
            f'unknown BAM B-array subtype {chr(subtype)!r} for tag {tag}')
      (count,) = struct.unpack_from('<I', raw, pos + 1)
      itemsize = np.dtype(dtype).itemsize
      if count * itemsize > n - pos - 5:
        raise corrupt(
            f'B-array count {count} for BAM tag {tag} overruns the '
            f'record ({count * itemsize} > {n - pos - 5} bytes)')
      value = np.frombuffer(
          raw, dtype=dtype, count=count, offset=pos + 5
      ).copy()
      pos += 5 + count * itemsize
    else:
      raise corrupt(
          f'unknown BAM tag type {chr(val_type)!r} (0x{val_type:02x}) '
          f'for tag {tag}')
    tags[tag] = value
  return tags


def parse_record(data: bytes, references: List[str],
                 path: Optional[str] = None,
                 offset: Optional[int] = None) -> BamRecord:
  """Parses one BAM alignment block (excluding the block_size prefix).

  All variable-length sections are bounds-checked against len(data)
  before any allocation; since the caller already capped len(data) at
  max_record_bytes, no parse can allocate beyond that. Violations raise
  a recoverable CorruptInputError (the record's framing was intact, so
  the stream can continue at the next record)."""
  n = len(data)

  def corrupt(msg: str, zmw: Optional[str] = None) -> CorruptInputError:
    return CorruptInputError(
        msg, path=path, offset=offset, zmw=zmw, recoverable=True)

  if n < 32:
    raise corrupt(f'BAM record body too short ({n} < 32 bytes)')
  (
      ref_id,
      pos,
      l_read_name,
      mapq,
      _bin,
      n_cigar_op,
      flag,
      l_seq,
      _next_ref,
      _next_pos,
      _tlen,
  ) = struct.unpack_from('<iiBBHHHiiii', data, 0)
  if l_read_name < 1:
    raise corrupt('BAM record with l_read_name == 0')
  if l_seq < 0:
    raise corrupt(f'negative BAM record l_seq {l_seq}')
  if pos < -1:
    raise corrupt(f'implausible BAM record pos {pos}')
  off = 32
  if off + l_read_name > n:
    raise corrupt(
        f'read name (l_read_name={l_read_name}) overruns the record')
  try:
    qname = data[off : off + l_read_name - 1].decode('ascii')
  except UnicodeDecodeError:
    raise corrupt('non-ASCII BAM read name')
  off += l_read_name
  if off + 4 * n_cigar_op > n:
    raise corrupt(
        f'cigar ({n_cigar_op} ops) overruns the record', zmw=qname)
  cigar_raw = np.frombuffer(data, dtype=np.uint32, count=n_cigar_op, offset=off)
  cigar_ops = (cigar_raw & 0xF).astype(np.uint8)
  cigar_lens = (cigar_raw >> 4).astype(np.int32)
  off += 4 * n_cigar_op
  n_seq_bytes = (l_seq + 1) // 2
  if off + n_seq_bytes + l_seq > n:
    raise corrupt(
        f'sequence/qualities (l_seq={l_seq}) overrun the record',
        zmw=qname)
  packed = np.frombuffer(data, dtype=np.uint8, count=n_seq_bytes, offset=off)
  nibbles = np.empty(n_seq_bytes * 2, dtype=np.uint8)
  nibbles[0::2] = packed >> 4
  nibbles[1::2] = packed & 0xF
  seq = _NIBBLE_LUT[nibbles[:l_seq]].tobytes().decode('ascii')
  off += n_seq_bytes
  quals_raw = np.frombuffer(data, dtype=np.uint8, count=l_seq, offset=off)
  # htslib marks absent qualities with 0xFF in EVERY byte; a legitimate
  # first qual of 0xFF alone must not be treated as missing.
  if l_seq and quals_raw[0] == 0xFF and np.all(quals_raw == 0xFF):
    quals = None
  else:
    quals = quals_raw.astype(np.int32)
  off += l_seq
  tags = _parse_tags(memoryview(data)[off:], path=path, qname=qname)
  ref_name = references[ref_id] if 0 <= ref_id < len(references) else None
  return BamRecord(
      qname=qname,
      flag=flag,
      ref_id=ref_id,
      pos=pos,
      mapq=mapq,
      cigar_ops=cigar_ops,
      cigar_lens=cigar_lens,
      seq=seq,
      quals=quals,
      tags=tags,
      reference_name=ref_name,
  )


def bgzf_decompress_file_py(path: str,
                            max_out: int = 0) -> bytes:
  """Pure-Python BGZF/gzip whole-file decompression with a typed error
  surface: corrupt or truncated streams raise CorruptInputError (never
  a bare gzip/zlib error), and max_out > 0 bounds the decompressed
  allocation (a zip bomb raises instead of exhausting the host). The
  Python counterpart of native.bgzf_decompress_file for the
  corrupt-input parity tests."""
  chunks: List[bytes] = []
  total = 0
  try:
    with gzip.open(path, 'rb') as f:
      while True:
        chunk = f.read(1 << 20)
        if not chunk:
          break
        total += len(chunk)
        if max_out and total > max_out:
          raise CorruptInputError(
              f'decompressed BGZF stream exceeds the {max_out}-byte cap',
              path=path, offset=total)
        chunks.append(chunk)
  except _DECOMPRESS_ERRORS as e:
    raise TruncatedBamError(
        f'BGZF stream corrupt or truncated ({type(e).__name__}: {e})',
        path=path, offset=total) from e
  return b''.join(chunks)


class BamReader:
  """Streams records from a BAM file in file order.

  When the native library is available and the file is modest, BGZF
  blocks decompress in parallel in C++ (htslib-style); otherwise the
  gzip module streams the concatenated members.

  BamReader is its own iterator (``__iter__`` returns self): a
  recoverable CorruptInputError raised by ``next()`` leaves the stream
  positioned at the following record, so callers may catch it and keep
  iterating. ``skip_corrupt_records=True`` does that internally,
  counting skips in ``n_corrupt_records``. Stream-level damage
  (truncation, BGZF corruption, bad framing) raises TruncatedBamError /
  a non-recoverable CorruptInputError and ends the stream.
  """

  NATIVE_MAX_BYTES = 4 << 30
  # Decompressed-size cap handed to the native whole-file decode: BGZF
  # tops out near 4x compression on genomic data, so a conforming file
  # under NATIVE_MAX_BYTES stays well inside it; a zip bomb aborts in C
  # (and falls back to the bounded streaming path) instead of
  # exhausting the host.
  NATIVE_MAX_OUT_BYTES = 16 << 30

  def __init__(self, path: str, use_native: bool = True,
               native_threads: int = 4,
               max_record_bytes: int = DEFAULT_MAX_RECORD_BYTES,
               skip_corrupt_records: bool = False):
    self.path = path
    self.max_record_bytes = int(max_record_bytes)
    self.skip_corrupt_records = skip_corrupt_records
    self.n_corrupt_records = 0
    self._f = None
    if use_native:
      try:
        import os

        from deepconsensus_tpu import native

        if os.path.getsize(path) <= self.NATIVE_MAX_BYTES:
          data = native.bgzf_decompress_file(
              path, native_threads, max_out=self.NATIVE_MAX_OUT_BYTES)
          if data is not None:
            import io

            self._f = io.BytesIO(data)
      # dclint: allow=typed-faults (native decompress is an optional
      # accelerator: any failure falls back to the gzip path below)
      except Exception:  # pragma: no cover - fallback path
        self._f = None
    if self._f is None:
      self._f = gzip.open(path, 'rb')
    magic = self._read(4, 'BAM magic')
    if magic != b'BAM\x01':
      raise CorruptInputError(
          f'not a BAM file (magic={magic!r})', path=path, offset=0)
    (l_text,) = struct.unpack('<i', self._read(4, 'header l_text', exact=True))
    if l_text < 0 or l_text > self.max_record_bytes:
      raise CorruptInputError(
          f'implausible BAM header text length {l_text} '
          f'(cap {self.max_record_bytes})', path=path, offset=4)
    self.header_text = self._read(
        l_text, 'header text', exact=True).decode('utf-8', errors='replace')
    (n_ref,) = struct.unpack('<i', self._read(4, 'n_ref', exact=True))
    if n_ref < 0 or n_ref > _MAX_N_REF:
      raise CorruptInputError(
          f'implausible BAM reference count {n_ref}', path=path)
    self.references: List[str] = []
    self.reference_lengths: List[int] = []
    for i in range(n_ref):
      (l_name,) = struct.unpack(
          '<i', self._read(4, f'reference {i} l_name', exact=True))
      if l_name < 1 or l_name > _MAX_REF_NAME_BYTES:
        raise CorruptInputError(
            f'implausible BAM reference name length {l_name} '
            f'for reference {i}', path=path)
      name_bytes = self._read(l_name, f'reference {i} name', exact=True)
      try:
        name = name_bytes[:-1].decode('ascii')
      except UnicodeDecodeError:
        raise CorruptInputError(
            f'non-ASCII name for BAM reference {i}', path=path)
      (l_ref,) = struct.unpack(
          '<i', self._read(4, f'reference {i} l_ref', exact=True))
      if l_ref < 0:
        raise CorruptInputError(
            f'negative length {l_ref} for BAM reference {name!r}',
            path=path)
      self.references.append(name)
      self.reference_lengths.append(l_ref)

  def _read(self, n: int, what: str, exact: bool = False) -> bytes:
    """Checked read: decompression errors become TruncatedBamError, and
    with exact=True a short read does too (naming path + offset)."""
    try:
      offset = self._f.tell()
      data = self._f.read(n)
    except _DECOMPRESS_ERRORS as e:
      raise TruncatedBamError(
          f'BGZF stream corrupt or truncated reading {what} '
          f'({type(e).__name__}: {e})', path=self.path) from e
    if exact and len(data) != n:
      raise TruncatedBamError(
          f'truncated BAM: short read of {what} '
          f'(wanted {n} bytes, got {len(data)})',
          path=self.path, offset=offset)
    return data

  def _skip_bytes(self, n: int, offset: int) -> None:
    """Consumes n stream bytes in bounded chunks (skipping an oversized
    record without allocating it)."""
    remaining = n
    while remaining > 0:
      chunk = self._read(min(remaining, 1 << 20), 'oversized record body')
      if not chunk:
        raise TruncatedBamError(
            f'truncated BAM: stream ended inside an oversized record '
            f'({remaining} of {n} bytes missing)',
            path=self.path, offset=offset)
      remaining -= len(chunk)

  def __iter__(self) -> Iterator[BamRecord]:
    return self

  def __next__(self) -> BamRecord:
    while True:
      offset = self._f.tell()
      size_bytes = self._read(4, 'record block_size')
      if not size_bytes:
        raise StopIteration
      if len(size_bytes) != 4:
        raise TruncatedBamError(
            'truncated BAM record header', path=self.path, offset=offset)
      (block_size,) = struct.unpack('<i', size_bytes)
      if block_size < 0:
        raise CorruptInputError(
            f'negative BAM record block_size {block_size}',
            path=self.path, offset=offset)
      if block_size > self.max_record_bytes:
        # The framing may still be intact (one inflated length field);
        # skip past the claimed extent in bounded chunks so the stream
        # survives without ever allocating block_size bytes.
        self._skip_bytes(block_size, offset)
        error: CorruptInputError = CorruptInputError(
            f'BAM record block_size {block_size} exceeds '
            f'max_record_bytes {self.max_record_bytes}',
            path=self.path, offset=offset, recoverable=True)
      elif block_size < 32:
        self._skip_bytes(block_size, offset)
        error = CorruptInputError(
            f'implausible BAM record block_size {block_size} (< 32)',
            path=self.path, offset=offset, recoverable=True)
      else:
        data = self._read(block_size, 'record body')
        if len(data) != block_size:
          raise TruncatedBamError(
              'truncated BAM record', path=self.path, offset=offset)
        try:
          return parse_record(
              data, self.references, path=self.path, offset=offset)
        except CorruptInputError as e:
          error = e
      self.n_corrupt_records += 1
      if self.skip_corrupt_records and error.recoverable:
        continue
      raise error

  def close(self) -> None:
    self._f.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


class SubreadGrouper:
  """Yields the mapped subreads of one ZMW at a time.

  Relies on the input being grouped by the `zm` tag, as written by actc
  (reference: pre_lib.py:50-91).

  skip_corrupt_records=True turns a recoverable corrupt record into an
  in-stream CorruptInputError *event item* (callers dispatch on type):
  the in-progress molecule is dropped — its membership can no longer be
  trusted — and grouping resumes at the next parseable record, with any
  stragglers of the poisoned ZMW discarded. The event's ``zmw``
  attribute names the poisoned molecule when known. Without the flag,
  corrupt records propagate (historical fail-fast).
  """

  def __init__(self, subreads_to_ccs: str,
               max_record_bytes: int = DEFAULT_MAX_RECORD_BYTES,
               skip_corrupt_records: bool = False):
    self.reader = BamReader(subreads_to_ccs,
                            max_record_bytes=max_record_bytes)
    self._skip_corrupt = skip_corrupt_records
    self._iter = iter(self.reader)
    self._pending: List[BamRecord] = []
    self._zmw: Optional[int] = None

  def __iter__(self) -> Iterator[Any]:
    poisoned: Optional[int] = None
    while True:
      try:
        read = next(self._iter)
      except StopIteration:
        break
      except CorruptInputError as e:
        if not (self._skip_corrupt and e.recoverable):
          raise
        if e.zmw is None and self._pending:
          e.zmw = self._pending[0].reference_name
        # Drop the in-progress molecule: the corrupt record most likely
        # belonged to it, and a group with an unknown hole must not be
        # polished as if complete.
        poisoned = self._zmw
        self._pending = []
        self._zmw = None
        yield e
        continue
      if read.is_unmapped:
        continue
      try:
        zmw = int(read.get_tag('zm'))
      except (KeyError, TypeError, ValueError) as tag_err:
        error = CorruptInputError(
            f'subread {read.qname!r} lacks a usable zm tag '
            f'({type(tag_err).__name__}: {tag_err})',
            path=self.reader.path, zmw=read.reference_name,
            recoverable=True)
        if not self._skip_corrupt:
          raise error
        yield error
        continue
      if poisoned is not None:
        if zmw == poisoned:
          continue  # straggler of a dropped molecule
        poisoned = None
      if self._zmw is None:
        self._zmw = zmw
      if zmw == self._zmw:
        self._pending.append(read)
      else:
        group = self._pending
        self._pending = [read]
        self._zmw = zmw
        if group:
          yield group
    if self._pending:
      yield self._pending


def read_bam_by_name(path: str) -> Dict[str, List[BamRecord]]:
  """Loads a (small) BAM keyed by reference name, e.g. truth_to_ccs.

  Replaces pysam's indexed fetch(ccs_seqname) used for label lookup
  (reference: pre_lib.py:1001-1014) with a single in-memory pass.
  """
  by_ref: Dict[str, List[BamRecord]] = {}
  with BamReader(path) as reader:
    for record in reader:
      if record.is_unmapped or record.reference_name is None:
        continue
      by_ref.setdefault(record.reference_name, []).append(record)
  return by_ref
