"""Preflight input validation for the `dctpu validate` subcommand.

Streams an actc/ccs BAM pair or a TFRecord glob end to end through the
hardened decoders (io/bam.py, io/tfrecord.py) and reports, per file:
magic/header sanity, per-record parse health, ZMW grouping order, BGZF
EOF-marker presence, and actc↔ccs name consistency — as a
machine-readable report dict (the CLI emits it as JSON and exits
nonzero when any check fails). The point is to catch a truncated upload
or bit-rotted shard on the submit host, before a TPU slice is burning
time on it.

Every error entry carries `file` and `offset` (plus `zmw` when known)
so operators and tooling can locate the damage without re-parsing.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from deepconsensus_tpu.faults import CorruptInputError
from deepconsensus_tpu.io import bam as bam_lib
from deepconsensus_tpu.io import tfrecord as tfrecord_lib
from deepconsensus_tpu.io.bam_writer import BGZF_EOF

# Per-file cap on enumerated record errors: corruption tends to cascade
# (one flipped length desynchronizes everything after it), so reports
# stay useful and bounded.
DEFAULT_MAX_ERRORS = 20


def _error_entry(e: Exception, path: str) -> Dict[str, Any]:
  return {
      'file': getattr(e, 'path', None) or path,
      'offset': getattr(e, 'offset', None),
      'zmw': getattr(e, 'zmw', None),
      'recoverable': bool(getattr(e, 'recoverable', False)),
      'error': str(e),
  }


def check_bgzf_eof(path: str) -> bool:
  """True when the file ends with the 28-byte BGZF EOF marker.

  Its absence is the classic signature of a truncated upload: writers
  (htslib, BgzfWriter here) append it at close, so a file missing it
  almost certainly lost its tail."""
  try:
    size = os.path.getsize(path)
    if size < len(BGZF_EOF):
      return False
    with open(path, 'rb') as f:
      f.seek(size - len(BGZF_EOF))
      return f.read(len(BGZF_EOF)) == BGZF_EOF
  except OSError:
    return False


def validate_bam(path: str,
                 max_record_bytes: int = bam_lib.DEFAULT_MAX_RECORD_BYTES,
                 max_errors: int = DEFAULT_MAX_ERRORS,
                 collect_names: Optional[str] = None) -> Dict[str, Any]:
  """Streams every record of one BAM through the hardened decoder.

  Recoverable (record-local) errors are enumerated up to max_errors and
  scanning continues; a stream-level error (truncation, BGZF damage)
  ends the scan. Also verifies `zm`-tag grouping: a ZMW that reappears
  after a different ZMW interleaved means the file is not actc-grouped
  and SubreadGrouper would silently split the molecule.

  collect_names='reference' records the run-length-deduplicated order
  of reference names (the ccs read each actc subread aligns to);
  'qname' records read-name order (the ccs BAM side). Used by the
  pair-consistency check."""
  report: Dict[str, Any] = {
      'path': path,
      'format': 'bam',
      'ok': False,
      'bgzf_eof': check_bgzf_eof(path),
      'n_records': 0,
      'n_corrupt_records': 0,
      'zmw_ordering_ok': True,
      'errors': [],
  }
  names: List[str] = []
  try:
    reader = bam_lib.BamReader(path, max_record_bytes=max_record_bytes)
  except CorruptInputError as e:
    report['errors'].append(_error_entry(e, path))
    return report
  report['header_ok'] = True
  report['n_references'] = len(reader.references)
  seen_zmws = set()
  last_zmw: Optional[int] = None
  with reader:
    while True:
      try:
        record = next(reader)
      except StopIteration:
        break
      except CorruptInputError as e:
        report['n_corrupt_records'] += 1
        if len(report['errors']) < max_errors:
          report['errors'].append(_error_entry(e, path))
        if not e.recoverable:
          return report
        continue
      report['n_records'] += 1
      if collect_names:
        name = (record.qname if collect_names == 'qname'
                else record.reference_name)
        if name is not None and (not names or names[-1] != name):
          names.append(name)
      zmw = record.tags.get('zm')
      if zmw is not None and isinstance(zmw, (int,)) and zmw != last_zmw:
        if zmw in seen_zmws:
          report['zmw_ordering_ok'] = False
          if len(report['errors']) < max_errors:
            report['errors'].append({
                'file': path,
                'offset': None,
                'zmw': str(zmw),
                'recoverable': True,
                'error': f'ZMW {zmw} reappears after other ZMWs '
                         '(input is not grouped by zm tag)',
            })
        seen_zmws.add(zmw)
        last_zmw = zmw
  if not report['bgzf_eof']:
    report['errors'].append({
        'file': path,
        'offset': max(os.path.getsize(path) - len(BGZF_EOF), 0),
        'zmw': None,
        'recoverable': False,
        'error': 'missing BGZF EOF marker (file tail truncated?)',
    })
  report['ok'] = (report['n_corrupt_records'] == 0
                  and report['zmw_ordering_ok'] and report['bgzf_eof']
                  and not report['errors'])
  if collect_names:
    report['_names'] = names
  return report


def validate_tfrecord(path: str,
                      max_record_bytes: int = (
                          tfrecord_lib.DEFAULT_MAX_RECORD_BYTES),
                      max_errors: int = DEFAULT_MAX_ERRORS) -> Dict[str, Any]:
  """Streams one TFRecord shard with full CRC checking.

  TFRecord framing has no resync point — once a frame is corrupt every
  later offset is untrusted — so the scan stops at the first error."""
  report: Dict[str, Any] = {
      'path': path,
      'format': 'tfrecord',
      'ok': False,
      'n_records': 0,
      'errors': [],
  }
  if path.endswith('.gz'):
    report['bgzf_eof'] = check_bgzf_eof(path)
  try:
    with tfrecord_lib.TFRecordReader(
        path, check_crc=True, max_record_bytes=max_record_bytes) as reader:
      for _ in reader:
        report['n_records'] += 1
  except CorruptInputError as e:
    report['errors'].append(_error_entry(e, path))
    return report
  except OSError as e:
    report['errors'].append({
        'file': path, 'offset': None, 'zmw': None, 'recoverable': False,
        'error': f'{type(e).__name__}: {e}',
    })
    return report
  # bgzf_eof stays informational for .gz shards (only BGZF writers emit
  # the marker); the CRC-checked scan above is the authoritative verdict.
  report['ok'] = True
  return report


def validate_actc_ccs_pair(subreads_report: Dict[str, Any],
                           ccs_report: Dict[str, Any]) -> Dict[str, Any]:
  """Cross-checks actc subread alignments against the ccs BAM.

  Every reference (= ccs read) the subreads align to must exist in the
  ccs BAM, and the actc group order must follow the ccs read order —
  the preprocess feeder walks both files in lockstep and desynchronizes
  otherwise."""
  result: Dict[str, Any] = {'checked': True, 'ok': True, 'errors': []}
  actc_names = subreads_report.pop('_names', None)
  ccs_names = ccs_report.pop('_names', None)
  if actc_names is None or ccs_names is None:
    result['checked'] = False
    return result
  ccs_order = {name: i for i, name in enumerate(ccs_names)}
  prev_idx = -1
  seen = set()
  for name in actc_names:
    if name in seen:
      result['ok'] = False
      result['errors'].append({
          'file': subreads_report['path'], 'offset': None, 'zmw': name,
          'recoverable': False,
          'error': f'subread group for {name!r} is split (reappears '
                   'after other groups)',
      })
      continue
    seen.add(name)
    idx = ccs_order.get(name)
    if idx is None:
      result['ok'] = False
      result['errors'].append({
          'file': subreads_report['path'], 'offset': None, 'zmw': name,
          'recoverable': False,
          'error': f'subreads align to {name!r} which is absent from '
                   'the ccs BAM',
      })
      continue
    if idx < prev_idx:
      result['ok'] = False
      result['errors'].append({
          'file': subreads_report['path'], 'offset': None, 'zmw': name,
          'recoverable': False,
          'error': f'subread group {name!r} is out of order relative '
                   'to the ccs BAM (lockstep scan would desync)',
      })
      continue
    prev_idx = idx
  return result


def validate_inputs(subreads_to_ccs: Optional[str] = None,
                    ccs_bam: Optional[str] = None,
                    tfrecords: Optional[List[str]] = None,
                    max_record_bytes: Optional[int] = None,
                    max_errors: int = DEFAULT_MAX_ERRORS) -> Dict[str, Any]:
  """Runs every applicable check; returns the full report dict.

  report['ok'] is the single pass/fail verdict the CLI turns into an
  exit code."""
  report: Dict[str, Any] = {'ok': True, 'files': [], 'n_errors': 0}
  bam_cap = (max_record_bytes if max_record_bytes is not None
             else bam_lib.DEFAULT_MAX_RECORD_BYTES)
  tfr_cap = (max_record_bytes if max_record_bytes is not None
             else tfrecord_lib.DEFAULT_MAX_RECORD_BYTES)
  pair = subreads_to_ccs is not None and ccs_bam is not None
  subreads_report = None
  ccs_report = None
  if subreads_to_ccs is not None:
    subreads_report = validate_bam(
        subreads_to_ccs, max_record_bytes=bam_cap, max_errors=max_errors,
        collect_names='reference' if pair else None)
    report['files'].append(subreads_report)
  if ccs_bam is not None:
    ccs_report = validate_bam(
        ccs_bam, max_record_bytes=bam_cap, max_errors=max_errors,
        collect_names='qname' if pair else None)
    report['files'].append(ccs_report)
  if pair:
    report['pair'] = validate_actc_ccs_pair(subreads_report, ccs_report)
    if not report['pair']['ok']:
      report['ok'] = False
      report['n_errors'] += len(report['pair']['errors'])
  for path in tfrecord_lib.glob_paths(tfrecords or []):
    report['files'].append(
        validate_tfrecord(path, max_record_bytes=tfr_cap,
                          max_errors=max_errors))
  for entry in report['files']:
    entry.pop('_names', None)
    if not entry['ok']:
      report['ok'] = False
    report['n_errors'] += len(entry['errors'])
  return report
