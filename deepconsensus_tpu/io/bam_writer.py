"""BAM writing: BGZF blocks + BAM record encoding + aux tags.

Counterpart of io/bam.py for the inference driver's .bam output mode
(reference: deepconsensus/inference/quick_inference.py:738-760 writes
pysam records with ec/np/rq/RG/zm tags). Unaligned records (flag 4,
ref -1) like the reference's output BAM.
"""
from __future__ import annotations

import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

BGZF_EOF = bytes.fromhex(
    '1f8b08040000000000ff0600424302001b0003000000000000000000'
)

_NIBBLE = {c: i for i, c in enumerate('=ACMGRSVTWYHKDBN')}


class BgzfWriter:
  """Writes BGZF-framed gzip blocks (max 64 KiB payload each).

  append=True continues an existing file (resume support): the caller
  must have truncated it to a block boundary (the progress manifest
  records flushed sizes, which flush() guarantees are boundaries).
  """

  MAX_BLOCK = 0xFF00

  def __init__(self, path: str, append: bool = False):
    self._f = open(path, 'ab' if append else 'wb')
    self._buf = bytearray()

  def write(self, data: bytes) -> None:
    self._buf += data
    while len(self._buf) >= self.MAX_BLOCK:
      self._flush_block(self._buf[: self.MAX_BLOCK])
      del self._buf[: self.MAX_BLOCK]

  def flush(self) -> None:
    """Flushes buffered payload as a (possibly short) block to the OS.
    BGZF permits arbitrary block boundaries, so the file is a valid
    prefix afterwards — the durability point for the progress
    manifest."""
    if self._buf:
      self._flush_block(bytes(self._buf))
      self._buf.clear()
    self._f.flush()

  def tell(self) -> int:
    """Byte size of the durable file prefix (call flush() first)."""
    return self._f.tell()

  def _flush_block(self, payload: bytes) -> None:
    compressor = zlib.compressobj(6, zlib.DEFLATED, -15)
    comp = compressor.compress(payload) + compressor.flush()
    # BSIZE field = total block size - 1; total = 18 header + comp + 8.
    bsize = len(comp) + 25
    block = (
        b'\x1f\x8b\x08\x04\x00\x00\x00\x00\x00\xff'
        + struct.pack('<HHHH', 6, 0x4342, 2, bsize)
        + comp
        + struct.pack('<II', zlib.crc32(payload) & 0xFFFFFFFF, len(payload))
    )
    self._f.write(block)

  def close(self) -> None:
    if self._buf:
      self._flush_block(bytes(self._buf))
      self._buf.clear()
    self._f.write(BGZF_EOF)
    self._f.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


def _encode_tag(name: str, value: Any) -> bytes:
  out = bytearray(name.encode('ascii'))
  if isinstance(value, float) or isinstance(value, np.floating):
    out += b'f' + struct.pack('<f', float(value))
  elif isinstance(value, (int, np.integer)):
    out += b'i' + struct.pack('<i', int(value))
  elif isinstance(value, str):
    out += b'Z' + value.encode('ascii') + b'\x00'
  elif isinstance(value, (list, tuple, np.ndarray)):
    arr = np.asarray(value)
    if arr.dtype.kind == 'f':
      out += b'B' + b'f' + struct.pack('<I', arr.size)
      out += arr.astype('<f4').tobytes()
    else:
      out += b'B' + b'i' + struct.pack('<I', arr.size)
      out += arr.astype('<i4').tobytes()
  else:
    # dclint: allow=typed-faults (output plane: the tag values are
    # produced by our own emit code, so this is a programmer error)
    raise ValueError(f'unsupported tag type for {name}: {type(value)}')
  return bytes(out)


def encode_record(
    qname: str,
    seq: str,
    quals: Optional[np.ndarray],
    flag: int = 4,
    tags: Optional[Dict[str, Any]] = None,
    ref_id: int = -1,
    pos: int = -1,
    mapq: Optional[int] = None,
    cigar: Optional[List[Tuple[int, int]]] = None,
) -> bytes:
  """Encodes one BAM record (unmapped by default; pass ref_id/pos/cigar
  for mapped records, e.g. the fault-injection harness's synthetic
  subreads-to-CCS alignments)."""
  name_b = qname.encode('ascii') + b'\x00'
  l_seq = len(seq)
  packed = bytearray((l_seq + 1) // 2)
  for i, c in enumerate(seq):
    nib = _NIBBLE.get(c.upper(), 15)
    if i % 2 == 0:
      packed[i // 2] |= nib << 4
    else:
      packed[i // 2] |= nib
  if quals is None:
    qual_b = b'\xff' * l_seq
  else:
    qual_b = np.asarray(quals, dtype=np.uint8).tobytes()
  tag_b = b''
  for tag_name, value in (tags or {}).items():
    tag_b += _encode_tag(tag_name, value)
  cigar = cigar or []
  cigar_b = b''.join(
      struct.pack('<I', (int(ln) << 4) | int(op)) for op, ln in cigar
  )
  if mapq is None:
    mapq = 255 if flag & 4 else 0
  body = (
      struct.pack(
          '<iiBBHHHiiii',
          ref_id,
          pos,
          len(name_b),
          mapq,
          4680,  # bin (unused by our reader)
          len(cigar),
          flag,
          l_seq,
          -1,
          -1,
          0,
      )
      + name_b
      + cigar_b
      + bytes(packed)
      + qual_b
      + tag_b
  )
  return struct.pack('<i', len(body)) + body


class BamWriter:
  """Writes a BAM with the given header text (unaligned by default).

  append=True continues an existing (header-bearing) file without
  re-emitting the header — the resume path for atomic <output>.tmp
  BAMs after the caller truncated to the manifest's committed size.
  """

  def __init__(self, path: str, header_text: str = '',
               references: Optional[List[Tuple[str, int]]] = None,
               append: bool = False):
    self._bgzf = BgzfWriter(path, append=append)
    if append:
      return
    references = references or []
    head = b'BAM\x01'
    text = header_text.encode('ascii')
    head += struct.pack('<i', len(text)) + text
    head += struct.pack('<i', len(references))
    for name, length in references:
      name_b = name.encode('ascii') + b'\x00'
      head += struct.pack('<i', len(name_b)) + name_b
      head += struct.pack('<i', length)
    self._bgzf.write(head)

  def write(self, qname: str, seq: str, quals: Optional[np.ndarray],
            tags: Optional[Dict[str, Any]] = None, flag: int = 4,
            ref_id: int = -1, pos: int = -1,
            cigar: Optional[List[Tuple[int, int]]] = None) -> None:
    self._bgzf.write(
        encode_record(qname, seq, quals, flag=flag, tags=tags,
                      ref_id=ref_id, pos=pos, cigar=cigar)
    )

  def flush(self) -> None:
    self._bgzf.flush()

  def tell(self) -> int:
    return self._bgzf.tell()

  def close(self) -> None:
    self._bgzf.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()
