"""Shared fault taxonomy, dead-letter sidecars, and injection hooks.

PR 1 built these primitives for the inference pipeline
(inference/faults.py); the training loop needs the identical
transient/permanent classification for its retry loop, the identical
dead-letter JSONL format for NaN-batch forensics, and its own set of
env-var fault-injection hooks. Promoting them here makes the two halves
share one vocabulary: a dead-letter line written by training replays
with the same tooling as one written by inference.

inference/faults.py re-exports everything below, so existing imports
keep working unchanged.
"""
from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, List, Optional

log = logging.getLogger(__name__)


# ----------------------------------------------------------------------
# Error taxonomy


class FaultKind:
  TRANSIENT = 'transient'
  PERMANENT = 'permanent'


# Device-runtime signatures (TPU preemption/unavailability) plus
# host-side pool/timeout signatures.
_TRANSIENT_MARKERS = (
    'UNAVAILABLE', 'DEADLINE_EXCEEDED', 'RESOURCE_EXHAUSTED', 'PREEMPT',
    'timed out', 'Timeout', 'Connection reset', 'Broken pipe',
    'watchdog',
)


def classify_error(error_text: str) -> str:
  """Transient (worth retrying) vs permanent (bad data/config) by
  message."""
  if any(marker in error_text for marker in _TRANSIENT_MARKERS):
    return FaultKind.TRANSIENT
  return FaultKind.PERMANENT


class CorruptInputError(IOError):
  """Untrusted input bytes failed decode-layer validation.

  The single typed error every hardened decoder (io/bam.py,
  io/tfrecord.py, io/fastx.py, the native ctypes wrappers) raises in
  place of bare struct.error / ValueError / MemoryError when a length,
  count, magic, or CRC field in the input cannot be trusted. Carries
  machine-readable context so the fault policies and `dctpu validate`
  can report file + byte offset + ZMW without parsing the message:

  * path:   the input file
  * offset: byte offset of the bad frame (decompressed-stream offset
            for BGZF-compressed inputs, raw file offset otherwise)
  * zmw:    per-molecule context when known (read name / ZMW)
  * recoverable: True when the stream is positioned past the damaged
            record so the caller may keep reading (record-local body
            corruption inside intact framing); False when the stream
            cannot be advanced (bad framing, truncation, compression
            errors).

  Permanent by construction: the message carries no transient markers,
  so retry loops re-raise instead of re-reading bad bytes.
  """

  def __init__(self, message: str, *, path: Optional[str] = None,
               offset: Optional[int] = None, zmw: Optional[str] = None,
               recoverable: bool = False):
    context = [
        f'file={path}' if path else None,
        f'offset={offset}' if offset is not None else None,
        f'zmw={zmw}' if zmw else None,
    ]
    context = [c for c in context if c]
    super().__init__(
        f'{message} [{" ".join(context)}]' if context else message)
    self.path = path
    self.offset = offset
    self.zmw = zmw
    self.recoverable = recoverable


class ServeRejection(RuntimeError):
  """Base for typed `dctpu serve` admission rejections. Carries an HTTP
  status so the server layer maps taxonomy -> wire code without
  parsing messages; `kind` feeds the shared transient/permanent
  classification (clients retry transient rejections with backoff)."""

  http_status = 500

  @property
  def kind(self) -> str:
    return classify_error(str(self))


class BackpressureError(ServeRejection):
  """Admission queue full: the service sheds load instead of growing
  without bound (429-style). Message embeds RESOURCE_EXHAUSTED so
  classify_error reports transient — retry after backoff."""

  http_status = 429

  def __init__(self, detail: str):
    super().__init__(f'RESOURCE_EXHAUSTED: {detail}')


class DrainingError(ServeRejection):
  """Service received SIGTERM and stopped admitting; in-flight work is
  finishing. Transient (UNAVAILABLE): retry against another replica."""

  http_status = 503

  def __init__(self, detail: str = 'service is draining'):
    super().__init__(f'UNAVAILABLE: {detail}')


class DeadlineExceededError(ServeRejection):
  """Per-request deadline elapsed before the result was ready; the
  request's remaining windows were cancelled and its packer slots
  reclaimed. Transient marker by construction (DEADLINE_EXCEEDED)."""

  http_status = 504

  def __init__(self, detail: str):
    super().__init__(f'DEADLINE_EXCEEDED: {detail}')


class BadRequestError(ServeRejection):
  """Malformed request payload (undecodable npz, missing fields, shape
  mismatch against the loaded model). Permanent: no transient markers,
  so clients must not retry the same bytes."""

  http_status = 400


class RequestTooLargeError(BadRequestError):
  """Request body exceeds the configured byte/window caps — rejected
  before decode, so an oversized body can't balloon server memory."""

  http_status = 413


class FleetRejection(ServeRejection):
  """`dctpu route` could not place the request on any replica: every
  eligible replica of the required tier is saturated (at its bounded
  in-flight cap), draining, or dead. Transient (UNAVAILABLE): capacity
  returns when a replica drains its queue or rejoins."""

  http_status = 503

  def __init__(self, detail: str):
    super().__init__(f'UNAVAILABLE: {detail}')


class ReplicaLostError(FleetRejection):
  """A replica died after the router finished sending it a request
  (the replica may have accepted the work), so the router must NOT
  retry elsewhere — a blind retry could duplicate an accepted request.
  Surfaced to the client as a transient 503; requests the dead replica
  provably never read ARE retried router-side and never raise this."""

  http_status = 503


class QuotaExceededError(FleetRejection):
  """A client is at its per-client concurrent-request quota at the
  router's multi-tenant admission gate. Transient by construction
  (RESOURCE_EXHAUSTED): the quota frees as the client's own in-flight
  requests complete, so a well-behaved client retries with backoff —
  but unlike BackpressureError this rejection is attributable to ONE
  tenant, never to fleet capacity, so the shed cannot starve other
  clients."""

  http_status = 429

  def __init__(self, detail: str):
    # Skip FleetRejection's UNAVAILABLE prefix: quota exhaustion is the
    # client's own concurrency, not fleet capacity.
    ServeRejection.__init__(self, f'RESOURCE_EXHAUSTED: {detail}')


class CrashLoopError(RuntimeError):
  """Raised by run_training_with_retry when restarts stop making
  progress: the same resume step across K consecutive transient
  failures means retrying cannot help (e.g. the failure happens before
  the first new checkpoint every time)."""


class NonFiniteTrainingError(RuntimeError):
  """Raised when the NaN sentinel exhausts its rollback budget (or has
  no valid checkpoint to roll back to). Permanent by construction: the
  message carries no transient markers, so the retry loop re-raises
  instead of looping on a diverged model."""


class WindowBucketError(ValueError):
  """`window_buckets` itself is invalid: non-increasing widths, a
  width below the condenser chunk, a largest bucket that disagrees
  with `max_length`, or a model family whose parameter shapes depend
  on the window width (the FC head sizes its output Dense by
  max_length, so one param tree cannot serve two widths). Raised at
  config time with the actionable remedy instead of failing later
  with an opaque shape mismatch inside a jitted step. Operator
  error: exit code 2."""


class FlywheelGateError(RuntimeError):
  """A `dctpu flywheel` accuracy gate failed: the quantized student
  (int8 identity delta, bf16 per-base QV delta) regressed past the
  documented threshold, so the pipeline refuses to export a servable
  artifact from it. Permanent by construction (no transient markers):
  re-running the same flywheel cannot pass the same gate.

  Carries the machine-readable gate verdict so the manifest writer and
  tests never parse the message."""

  def __init__(self, gate: str, measured: float, threshold: float,
               detail: str = ''):
    msg = (f'flywheel gate {gate!r} failed: measured {measured:.6g} '
           f'exceeds threshold {threshold:.6g}')
    if detail:
      msg = f'{msg} ({detail})'
    super().__init__(msg)
    self.gate = gate
    self.measured = measured
    self.threshold = threshold


class FlywheelStageError(RuntimeError):
  """A `dctpu flywheel` stage failed permanently: a non-transient error
  escaped the stage body, or the stage-level retry loop hit its
  crash-loop breaker without the stage's progress marker advancing.
  The failing stage is recorded as `failed` in flywheel_journal.json
  before this raises, so `--resume` re-enters exactly that stage.

  Permanent by construction (no transient markers): spinning the same
  flywheel again reproduces the same failure; the journal entry carries
  the cause for the operator instead."""

  def __init__(self, stage: str, detail: str):
    super().__init__(f'flywheel stage {stage!r} failed: {detail}')
    self.stage = stage


class FlywheelResumeError(ValueError):
  """`dctpu flywheel --resume` found a journal whose recorded stage
  inputs do not match this invocation: a completed stage's outputs were
  produced under different parameters, so skipping it would silently
  publish an artifact built from a mixed configuration. Names the first
  mismatched field and both values so the operator can either restore
  the original flags or start a fresh cycle (new --out_dir, or drop
  --resume). Operator error: exit code 2 (ValueError family)."""

  def __init__(self, field: str, journal_value, current_value,
               stage: str = ''):
    where = f' (stage {stage!r})' if stage else ''
    super().__init__(
        f'flywheel journal mismatch on field {field!r}{where}: journal '
        f'recorded {journal_value!r} but this invocation has '
        f'{current_value!r}; restore the original flags or start a '
        f'fresh cycle without --resume')
    self.field = field
    self.journal_value = journal_value
    self.current_value = current_value
    self.stage = stage


class ExportedArtifactMismatchError(ValueError):
  """An exported StableHLO artifact cannot serve the requested topology
  (fixed-batch artifact under a --dp mesh, or any mesh with a model
  axis > 1). Operator error at startup, not a data-plane fault: the
  CLI maps it to exit code 2 like other config ValueErrors.

  reexport_command, when the fix is a re-export, is appended to the
  message so the operator can copy-paste the remedy."""

  def __init__(self, message: str,
               reexport_command: Optional[str] = None):
    if reexport_command:
      message = f'{message} (re-export with: {reexport_command})'
    super().__init__(message)
    self.reexport_command = reexport_command


class DeviceFault(RuntimeError):
  """Base for device-runtime failures surfaced at pack launch/finalize.

  The sharded dispatch path wraps `XlaRuntimeError`s (and anything else
  the jitted forward throws) into this family via
  classify_device_error(), so the engine's fault policy can pattern
  match on *types* instead of scraping runtime message strings. `kind`
  feeds the shared transient/permanent classification; subclasses embed
  the right marker by construction.
  """

  @property
  def kind(self) -> str:
    return classify_error(str(self))


class DeviceOomError(DeviceFault):
  """Device memory exhausted while launching/running a pack
  (RESOURCE_EXHAUSTED). Transient by construction: the same windows
  succeed at a smaller batch, so `--on_device_error=degrade` bisects
  the pack instead of failing it."""

  def __init__(self, detail: str):
    if 'RESOURCE_EXHAUSTED' not in detail:
      detail = f'RESOURCE_EXHAUSTED: {detail}'
    super().__init__(detail)


class DeviceLostError(DeviceFault):
  """A device in the mesh halted or lost state (DATA_LOSS / INTERNAL /
  halted). Permanent for the *current* mesh: no transient markers, so
  retry-at-same-shape loops re-raise; `--on_device_error=degrade`
  rebuilds the mesh at lower dp instead."""


class DispatchTimeoutError(DeviceFault):
  """The dispatch watchdog gave up waiting for a pack's finalize
  (`--dispatch_timeout`). Transient by construction ('watchdog'
  marker): the device may recover, but this pack's tickets are
  attributed and failed so the model loop never wedges."""

  def __init__(self, detail: str):
    if 'watchdog' not in detail:
      detail = f'{detail} (dispatch watchdog)'
    super().__init__(detail)


class HostLostError(RuntimeError):
  """A bounded pod barrier expired: one or more member hosts never
  posted their payload within the deadline (`--elastic_barrier_timeout`),
  or a watchdog-wrapped legacy collective (the PreemptionGuard stop
  vote, orbax's multihost save) missed its deadline. Carries the
  missing process indices so the rebuild path logs WHO was lost, the
  barrier name, and the pod epoch the barrier ran under.

  Transient by construction (UNAVAILABLE marker): with
  `--on_host_error=degrade` the survivors run the agreement round and
  rebuild; with `--on_host_error=fail` the retry loop restarts the
  process, which re-forms the pod from the surviving heartbeats."""

  def __init__(self, detail: str, *, missing: Any = (),
               barrier: str = '', epoch: Optional[int] = None):
    self.missing = tuple(int(m) for m in missing)
    self.barrier = barrier
    self.epoch = epoch
    parts = [f'UNAVAILABLE: {detail}']
    if self.missing:
      parts.append(f'missing host(s) {list(self.missing)}')
    if barrier:
      parts.append(f'barrier={barrier!r}')
    if epoch is not None:
      parts.append(f'pod_epoch={epoch}')
    super().__init__('; '.join(parts))

  @property
  def kind(self) -> str:
    return classify_error(str(self))


class ElasticRebuildError(RuntimeError):
  """The pod-wide agreement round could not converge on a consistent
  member set (survivor proposals never intersected to a stable quorum
  within the retry budget, or this host was voted out of the pod).
  Permanent by construction: no transient markers, so the retry loop
  re-raises instead of looping on a pod that cannot re-form — the
  operator must restart the lost hosts or the whole pod."""


class InjectedHostDeath(RuntimeError):
  """Raised by the ENV_HOST_LOST_AT_STEP hook in `drop` mode: the
  in-process analog of a SIGKILLed host for threaded drills — the
  host's pod endpoint is abandoned (heartbeats stop, no tombstone)
  and its training loop unwinds, leaving exactly the wreckage a real
  host death leaves: a stale heartbeat and a missed barrier. Permanent
  for the dying host itself (it must not retry); survivors never see
  this type — they see the HostLostError their next barrier raises."""


# Message signatures of a halted/lost device, as surfaced by the XLA
# CPU/TPU runtimes.
_DEVICE_LOST_MARKERS = (
    'DATA_LOSS', 'INTERNAL:', 'halted', 'DEVICE_LOST', 'device is lost',
)


def classify_device_error(error: BaseException) -> BaseException:
  """Wraps a device-runtime error into the typed DeviceFault family.

  DeviceFaults pass through untouched. RESOURCE_EXHAUSTED becomes
  DeviceOomError; halted/lost-device signatures become DeviceLostError;
  anything else returns unchanged (host-side bugs keep their type so
  per-ZMW attribution still sees e.g. the original ValueError). The
  original error is chained via __cause__ for forensics.
  """
  if isinstance(error, DeviceFault):
    return error
  text = f'{type(error).__name__}: {error}'
  wrapped: Optional[DeviceFault] = None
  if 'RESOURCE_EXHAUSTED' in text:
    wrapped = DeviceOomError(text)
  elif any(marker in text for marker in _DEVICE_LOST_MARKERS):
    wrapped = DeviceLostError(text)
  if wrapped is None:
    return error
  wrapped.__cause__ = error
  return wrapped


# ----------------------------------------------------------------------
# Dead-letter sidecar (JSONL, one object per line)


class DeadLetterWriter:
  """Streams quarantined-item records to a .failed.jsonl sidecar.

  One JSON object per line: {zmw, stage, kind, error, action, time}.
  `zmw` is the per-item id (ZMW name for inference, None for training
  records, which carry their window ids in `extra`). The file is
  created lazily on the first record so clean runs leave no empty
  sidecar; every line is flushed so a later crash can't lose the
  forensic trail.
  """

  def __init__(self, path: str, append: bool = False):
    self.path = path
    self._append = append
    self._f = None
    self.count = 0

  def record(self, zmw: Optional[str], stage: str, kind: str, error: str,
             action: str, extra: Optional[Dict[str, Any]] = None) -> None:
    if self._f is None:
      self._f = open(self.path, 'a' if self._append else 'w')
    entry = {
        'zmw': zmw,
        'stage': stage,
        'kind': kind,
        'error': error[:4000],
        'action': action,
        'time': time.time(),
    }
    if extra:
      # e.g. packed-batch attribution (inference) or the offending
      # batch's window ids / fingerprint (training NaN sentinel).
      entry.update(extra)
    if 'trace_id' not in entry:
      # Cross-tier forensics: a failed item's dead letter carries the
      # request/run trace id when one is bound to this thread (serve
      # paths pass it explicitly in `extra` instead — the model loop
      # serves many requests). Lazy import: obs.summarize reads this
      # module's fault types.
      from deepconsensus_tpu.obs import trace as _trace_lib

      trace_id = _trace_lib.get_trace_id()
      if trace_id:
        entry['trace_id'] = trace_id
    json.dump(
        entry,
        self._f,
    )
    self._f.write('\n')
    self._f.flush()
    self.count += 1

  def close(self) -> None:
    if self._f is not None:
      self._f.close()
      self._f = None


def read_dead_letters(path: str) -> List[Dict[str, Any]]:
  """Parses a dead-letter sidecar back into records (for replay)."""
  entries = []
  with open(path) as f:
    for line in f:
      line = line.strip()
      if line:
        entries.append(json.loads(line))
  return entries


# ----------------------------------------------------------------------
# Fault-injection hooks (driven by scripts/inject_faults.py + tests)
#
# Inference hooks (ENV_KILL_ZMW / ENV_CRASH_AFTER_BATCHES) target
# per-item stages; the training hooks below target step boundaries and
# shard readers. ENV_KILL_TOKEN is shared: pointing it at a path makes
# any kill-style hook fire exactly once (the first process to create
# the token file dies; the retried run then succeeds), so recovery is
# observable rather than an infinite crash loop.

ENV_KILL_ZMW = 'DCTPU_FAULT_KILL_ZMW'
ENV_KILL_TOKEN = 'DCTPU_FAULT_KILL_TOKEN'
ENV_CRASH_AFTER_BATCHES = 'DCTPU_FAULT_CRASH_AFTER_BATCHES'
ENV_NAN_AT_STEP = 'DCTPU_FAULT_NAN_AT_STEP'
ENV_SIGTERM_AT_STEP = 'DCTPU_FAULT_SIGTERM_AT_STEP'
ENV_KILL_TRAIN_AT_STEP = 'DCTPU_FAULT_KILL_TRAIN_AT_STEP'
ENV_KILL_SHARD_READER = 'DCTPU_FAULT_KILL_SHARD_READER'
# Serve-path hooks. ENV_POISON_WINDOW names a ZMW substring: the serve
# triage stage poisons that request's pack so the model stage fails for
# it (isolation retry -> quarantine path). ENV_SERVE_CLIENT_FAULT makes
# the *client* (scripts/inject_faults.py serve_client / ServeClient)
# misbehave on the wire: one of disconnect|garbage|oversized|slowloris,
# scoped to ZMW names containing ENV_SERVE_CLIENT_FAULT_ZMW (default:
# every request).
ENV_POISON_WINDOW = 'DCTPU_FAULT_POISON_WINDOW'
ENV_SERVE_CLIENT_FAULT = 'DCTPU_FAULT_SERVE_CLIENT'
ENV_SERVE_CLIENT_FAULT_ZMW = 'DCTPU_FAULT_SERVE_CLIENT_ZMW'
# Device-fault hooks (`inject_faults.py device`). Each targets a
# 1-based pack ordinal in dispatch order and fires once per process:
# OOM_AT_PACK raises RESOURCE_EXHAUSTED inside the pack's launch (the
# degrade policy bisects it), LOST_AT_PACK raises a halted-device error
# (the degrade policy drops to the next lower dp), HANG_AT_PACK makes
# the pack's finalize sleep ENV_DEVICE_HANG_S seconds (default 30) so
# the dispatch watchdog must fire.
ENV_DEVICE_OOM_AT_PACK = 'DCTPU_FAULT_DEVICE_OOM_AT_PACK'
ENV_DEVICE_LOST_AT_PACK = 'DCTPU_FAULT_DEVICE_LOST_AT_PACK'
ENV_DEVICE_HANG_AT_PACK = 'DCTPU_FAULT_DEVICE_HANG_AT_PACK'
ENV_DEVICE_HANG_S = 'DCTPU_FAULT_DEVICE_HANG_S'
# Training analog of LOST_AT_PACK: raise a halted-device error inside
# the Nth train step's dispatch (1-based; fires once per process) so
# `dctpu train --on_device_error=degrade` must rebuild the mesh one dp
# step down mid-run.
ENV_DEVICE_LOST_AT_STEP = 'DCTPU_FAULT_DEVICE_LOST_AT_STEP'
# Preemption-notice hook (`inject_faults.py preempt` / soak drills):
# a serve replica started with this set delivers itself a preemption
# notice after the given number of seconds — /readyz flips to 503
# draining, admissions stop, in-flight requests finish, and the
# process exits cleanly, exactly as if SIGUSR1 had arrived from the
# cloud provider's preemption agent. Fractional seconds allowed.
ENV_PREEMPT_AT_S = 'DCTPU_FAULT_PREEMPT_AT_S'
# Elastic-pod host hooks (`inject_faults.py host`). HOST_LOST_AT_STEP
# targets a 1-based training step: at that step the targeted host dies
# (consume-once). HOST_LOST_HOST scopes the hook to one pod host id
# (default: whichever host reaches the step first and claims the
# ENV_KILL_TOKEN). HOST_LOST_MODE picks the death style: `kill`
# (default) SIGKILLs the process — the real drill for subprocess pods;
# `drop` abandons the host's pod endpoint in-process and raises
# InjectedHostDeath — the threaded-drill analog, leaving the same
# wreckage (stale heartbeat, missed barrier) without taking the test
# runner down. HOST_REJOIN_AT_STEP arms the *restarted* host: it defers
# its re-admission announcement until the pod's observed step reaches
# the target, so rejoin drills land at a deterministic step boundary.
ENV_HOST_LOST_AT_STEP = 'DCTPU_FAULT_HOST_LOST_AT_STEP'
ENV_HOST_LOST_HOST = 'DCTPU_FAULT_HOST_LOST_HOST'
ENV_HOST_LOST_MODE = 'DCTPU_FAULT_HOST_LOST_MODE'
ENV_HOST_REJOIN_AT_STEP = 'DCTPU_FAULT_HOST_REJOIN_AT_STEP'
# Flywheel orchestration hook (`inject_faults.py flywheel`): SIGKILL
# the flywheel process right after the named stage (train | distill |
# gates | export) commits its `running` journal entry — the stage
# boundary where the durable-resume guarantee is cheapest to break.
# Consume-once per process; honors ENV_KILL_TOKEN so a drill can arm
# one kill across a whole kill/resume sequence.
ENV_FLYWHEEL_KILL_AT_STAGE = 'DCTPU_FAULT_FLYWHEEL_KILL_AT_STAGE'

# Hooks that already fired in this process (consume-once semantics:
# after a NaN-sentinel rollback the training loop passes the same step
# numbers again and the injected fault must not re-fire).
_fired: set = set()


def _env_int(name: str) -> int:
  try:
    return int(os.environ.get(name, '0'))
  except ValueError:
    return 0


def _claim_token() -> bool:
  """True when this process may fire a once-only kill (no token file
  configured, or this process created it first)."""
  token = os.environ.get(ENV_KILL_TOKEN)
  if not token:
    return True
  try:
    fd = os.open(token, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
  except FileExistsError:
    return False
  os.close(fd)
  return True


def _fire_once(env_name: str, step: int) -> bool:
  target = _env_int(env_name)
  if target <= 0 or step != target or env_name in _fired:
    return False
  _fired.add(env_name)
  return True


def maybe_kill_worker(zmw_name: str) -> None:
  """SIGKILLs the current process when fault injection targets this
  ZMW. With ENV_KILL_TOKEN set, the kill fires exactly once (the first
  worker to create the token file dies; retries then succeed) so the
  watchdog's recovery is observable rather than an infinite loop."""
  target = os.environ.get(ENV_KILL_ZMW)
  if not target or target != zmw_name:
    return
  if not _claim_token():
    return
  import signal

  os.kill(os.getpid(), signal.SIGKILL)


def injected_crash_after_batches() -> int:
  """>0: the consumer loop raises after this many consumed batches."""
  return _env_int(ENV_CRASH_AFTER_BATCHES)


def maybe_poison_batch(step: int, batch: Dict[str, Any]) -> bool:
  """Overwrites the batch's rows with NaN when ENV_NAN_AT_STEP targets
  this step (once per process) — the canonical diverged-batch fault the
  NaN sentinel must absorb."""
  if not _fire_once(ENV_NAN_AT_STEP, step):
    return False
  import numpy as np

  batch['rows'] = np.full_like(batch['rows'], np.nan)
  log.warning('fault injection: poisoned training batch at step %d', step)
  return True


def maybe_sigterm_at_step(step: int) -> bool:
  """Delivers SIGTERM to this process at the target step (once per
  process) — simulates the preemption notice a TPU VM receives."""
  if not _fire_once(ENV_SIGTERM_AT_STEP, step):
    return False
  import signal

  log.warning('fault injection: SIGTERM at step %d', step)
  os.kill(os.getpid(), signal.SIGTERM)
  return True


def maybe_kill_train_at_step(step: int) -> None:
  """SIGKILLs the training process at the target step — simulates a
  hard preemption with no grace period. Honors ENV_KILL_TOKEN for
  fire-once behavior across restarts."""
  if _env_int(ENV_KILL_TRAIN_AT_STEP) != step:
    return
  if not _claim_token():
    return
  import signal

  os.kill(os.getpid(), signal.SIGKILL)


def maybe_kill_flywheel_at_stage(stage: str) -> None:
  """SIGKILLs the flywheel process when fault injection targets this
  stage boundary. Fires once per process (a resumed flywheel passes
  earlier stage names again as it skips them) and honors
  ENV_KILL_TOKEN so the restarted run survives the same environment."""
  target = os.environ.get(ENV_FLYWHEEL_KILL_AT_STAGE)
  if not target or target != stage or ENV_FLYWHEEL_KILL_AT_STAGE in _fired:
    return
  _fired.add(ENV_FLYWHEEL_KILL_AT_STAGE)
  if not _claim_token():
    return
  import signal

  log.warning('fault injection: SIGKILL at flywheel stage %r', stage)
  os.kill(os.getpid(), signal.SIGKILL)


def injected_device_fault(pack_ordinal: int) -> None:
  """Raises a synthetic device fault when an injection hook targets
  this pack (1-based dispatch ordinal; fires once per process). Called
  from inside the pack launch so the error surfaces exactly where a
  real XlaRuntimeError would."""
  if _fire_once(ENV_DEVICE_OOM_AT_PACK, pack_ordinal):
    log.warning('fault injection: device OOM at pack %d', pack_ordinal)
    raise DeviceOomError(
        f'injected device OOM at pack {pack_ordinal}')
  if _fire_once(ENV_DEVICE_LOST_AT_PACK, pack_ordinal):
    log.warning('fault injection: device lost at pack %d', pack_ordinal)
    raise DeviceLostError(
        f'injected halted device at pack {pack_ordinal}')


def injected_train_device_fault(step: int) -> None:
  """Raises a synthetic halted-device fault when ENV_DEVICE_LOST_AT_STEP
  targets this training step (1-based; fires once per process). Called
  from inside the train-step dispatch so the error surfaces exactly
  where a real XlaRuntimeError would — under the degradation ladder's
  classify/rebuild handler."""
  if _fire_once(ENV_DEVICE_LOST_AT_STEP, step):
    log.warning('fault injection: device lost at train step %d', step)
    raise DeviceLostError(f'injected halted device at train step {step}')


def injected_device_hang(pack_ordinal: int) -> float:
  """Seconds the targeted pack's finalize should hang (0.0 when this
  pack is not targeted). Fires once per process; the watchdog converts
  the hang into a DispatchTimeoutError."""
  if not _fire_once(ENV_DEVICE_HANG_AT_PACK, pack_ordinal):
    return 0.0
  try:
    hang_s = float(os.environ.get(ENV_DEVICE_HANG_S, '30.0'))
  except ValueError:
    hang_s = 30.0
  log.warning('fault injection: device hang %.1fs at pack %d',
              hang_s, pack_ordinal)
  return hang_s


def preempt_notice_after_s() -> float:
  """Seconds after serve start at which the replica should deliver
  itself a preemption notice (0.0 = hook unarmed). The serve lifecycle
  (serve/server.py _PreemptionWatch) arms a timer with this value so
  the notice fires without any external agent — the deterministic
  in-process analog of the SIGUSR1 a real preemption agent sends."""
  raw = os.environ.get(ENV_PREEMPT_AT_S, '')
  if not raw:
    return 0.0
  try:
    return max(0.0, float(raw))
  except ValueError:
    return 0.0


def maybe_kill_shard_reader(shard_path: str) -> None:
  """SIGKILLs the current (shard-reader worker) process when
  ENV_KILL_SHARD_READER is a substring of the shard path about to be
  read. Honors ENV_KILL_TOKEN for fire-once behavior."""
  target = os.environ.get(ENV_KILL_SHARD_READER)
  if not target or target not in shard_path:
    return
  if not _claim_token():
    return
  import signal

  os.kill(os.getpid(), signal.SIGKILL)


def maybe_host_lost(step: int, host_id: int,
                    abandon: Optional[Any] = None) -> None:
  """Kills the targeted pod host at the target training step (1-based,
  consume-once). ENV_HOST_LOST_HOST scopes the hook to one host id —
  checked BEFORE consuming, so the hook stays armed in processes it
  doesn't target. Mode `kill` (default) SIGKILLs, honoring
  ENV_KILL_TOKEN across restarts; mode `drop` calls `abandon()` (the
  host's `ElasticPod.abandon`) and raises InjectedHostDeath for
  in-process threaded drills."""
  scoped = os.environ.get(ENV_HOST_LOST_HOST, '')
  if scoped and int(scoped) != host_id:
    return
  mode = os.environ.get(ENV_HOST_LOST_MODE, 'kill')
  if mode == 'drop':
    if not _fire_once(ENV_HOST_LOST_AT_STEP, step):
      return
    log.warning('fault injection: dropping pod host %d at step %d',
                host_id, step)
    if abandon is not None:
      abandon()
    raise InjectedHostDeath(
        f'injected host death: host {host_id} dropped at step {step}')
  if _env_int(ENV_HOST_LOST_AT_STEP) != step:
    return
  if not _claim_token():
    return
  import signal

  log.warning('fault injection: SIGKILL pod host %d at step %d',
              host_id, step)
  os.kill(os.getpid(), signal.SIGKILL)


def host_rejoin_step() -> int:
  """1-based pod step before which a restarted host should defer its
  re-admission announcement (0 = hook unarmed). Consume-once: after the
  deferred join lands, later pod restarts in the same process announce
  immediately."""
  if ENV_HOST_REJOIN_AT_STEP in _fired:
    return 0
  target = _env_int(ENV_HOST_REJOIN_AT_STEP)
  if target > 0:
    _fired.add(ENV_HOST_REJOIN_AT_STEP)
  return target
