"""Shared helpers for the Pallas TPU kernels."""
from __future__ import annotations

from typing import Optional

import jax


def resolve_interpret(interpret: Optional[bool]) -> bool:
  """None -> interpret everywhere but real TPU, so the same flag runs
  the kernels under CPU tests and the virtual mesh."""
  if interpret is None:
    return jax.default_backend() != 'tpu'
  return bool(interpret)
