"""Pallas TPU kernels: whole-DP wavefront alignment scorer + custom VJP.

Runs the entire anti-diagonal recursion of the alignment score inside
one VMEM-resident kernel per batch tile (fori_loop over diagonals),
instead of a 200-step XLA while-loop whose per-step work is a few
hundred lanes. `alignment_scores` is the forward scorer matching
ops/wavefront.alignment_scan semantics exactly; `alignment_scores_vjp`
wraps it in a jax.custom_vjp whose backward is a second whole-DP kernel
(forward-rows recompute into VMEM scratch + reverse adjoint sweep), so
AlignmentLoss trains through Pallas end-to-end (the reference trains
through this DP: losses_and_metrics.py:346-411). Validated against
alignment_scan values and jax.grad in interpret mode.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepconsensus_tpu.ops import pallas_util
from deepconsensus_tpu.ops import wavefront

Array = jnp.ndarray


def _make_minop(loss_reg):
  if loss_reg is None:
    return lambda t: jnp.min(t, axis=0)
  reg = jnp.float32(loss_reg)
  return lambda t: -reg * jax.nn.logsumexp(-t / reg, axis=0)


def _init_rows(bt, m, ins0, del_cost, inf):
  """DP rows V[0], V[1] as full [BT, m+1] vectors (cells (i, k-i))."""
  row0 = jnp.concatenate(
      [jnp.zeros((bt, 1), jnp.float32),
       jnp.full((bt, m), inf, jnp.float32)], axis=1,
  )
  row1 = jnp.concatenate(
      [ins0[:, :1],
       jnp.full((bt, 1), del_cost, jnp.float32),
       jnp.full((bt, m - 1), inf, jnp.float32)], axis=1,
  )
  return row0, row1


def _dp_step(k, v_p2, v_p1, subs_k, ins_k, *, i_range, n, del_cost,
             minop, inf):
  """One anti-diagonal update, shared by the forward scorer and the
  backward kernel's recompute pass (drift here would silently decouple
  loss values from gradients)."""
  valid = (k - i_range >= 0) & (k - i_range <= n)
  o_m = v_p2 + subs_k
  o_i = v_p1 + ins_k
  v_p2_next = v_p1[:, :-1]
  o_d = v_p2_next + del_cost
  body_vals = minop(jnp.stack([o_m, o_i[:, 1:], o_d]))
  v_new = jnp.where(
      valid, jnp.concatenate([o_i[:, :1], body_vals], axis=1), inf
  )
  return v_p2_next, v_new


def _kernel(subs_ref, ins_ref, lens_ref, out_ref, *, m, n, del_cost,
            loss_reg, inf):
  # Blocks: subs [K, BT, m], ins [K+1, BT, m+1], lens [BT], out [BT].
  bt = out_ref.shape[0]
  i_range = jax.lax.broadcasted_iota(jnp.int32, (1, m + 1), 1)
  minop = _make_minop(loss_reg)

  lens = lens_ref[:]  # [BT]
  k_end = lens + n
  onehot_len = (
      jax.lax.broadcasted_iota(jnp.int32, (bt, m + 1), 1)
      == lens[:, None]
  ).astype(jnp.float32)

  row0, row1 = _init_rows(bt, m, ins_ref[0], del_cost, inf)
  v_opt = jnp.full((bt,), inf, jnp.float32)

  def body(k, carry):
    v_p2, v_p1, v_opt = carry
    v_p2_next, v_new = _dp_step(
        k, v_p2, v_p1, subs_ref[k - 2], ins_ref[k - 1],
        i_range=i_range, n=n, del_cost=del_cost, minop=minop, inf=inf,
    )
    v_at_len = jnp.sum(v_new * onehot_len, axis=1)
    v_opt = jnp.where(k_end == k, v_at_len, v_opt)
    return v_p2_next, v_new, v_opt

  _, _, v_opt = jax.lax.fori_loop(
      2, m + n + 1, body, (row0[:, :m], row1, v_opt)
  )
  out_ref[:] = v_opt


def alignment_scores(
    subs_costs: Array,
    ins_costs: Array,
    del_cost: float,
    seq_lens: Array,
    loss_reg: Optional[float] = None,
    inf: float = 1e9,
    batch_tile: int = 8,
    interpret: bool = False,
) -> Array:
  """Pallas twin of wavefront.alignment_scan (same args/semantics)."""
  batch, m, n = subs_costs.shape
  while batch % batch_tile:
    batch_tile -= 1
  subs_w = wavefront.wavefrontify(subs_costs)  # [K, B, m]
  ins_w = wavefront.wavefrontify_vec(ins_costs, m + 1)  # [K+1, B, m+1]
  k_dim = subs_w.shape[0]

  grid = (batch // batch_tile,)
  return pl.pallas_call(
      functools.partial(
          _kernel, m=m, n=n, del_cost=float(del_cost),
          loss_reg=None if loss_reg is None else float(loss_reg),
          inf=float(inf),
      ),
      grid=grid,
      in_specs=[
          pl.BlockSpec((k_dim, batch_tile, m), lambda i: (0, i, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((k_dim + 1, batch_tile, m + 1), lambda i: (0, i, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((batch_tile,), lambda i: (i,),
                       memory_space=pltpu.VMEM),
      ],
      out_specs=pl.BlockSpec((batch_tile,), lambda i: (i,),
                             memory_space=pltpu.VMEM),
      out_shape=jax.ShapeDtypeStruct((batch,), jnp.float32),
      interpret=interpret,
  )(subs_w.astype(jnp.float32), ins_w.astype(jnp.float32),
    seq_lens.astype(jnp.int32))


def _unwavefrontify(t_w: Array, n: int) -> Array:
  """Inverse of wavefront.wavefrontify: [K, B, m] -> [B, m, n] with
  out[b, i, j] = t_w[i+j, b, i] (the forward map is one-to-one)."""
  _, _, m = t_w.shape
  i = jnp.arange(m)[:, None]
  j = jnp.arange(n)[None, :]
  return jnp.transpose(t_w, (1, 0, 2))[:, i + j, i]


def _unwavefrontify_vec_grad(v_w: Array, n: int) -> Array:
  """Adjoint of wavefront.wavefrontify_vec: [K2, B, L] -> [B, n].

  The forward broadcasts v[b, j] to every slot (k=i+j, i), so the
  adjoint sums over i: out[b, j] = sum_i v_w[i+j, b, i].
  """
  _, _, length = v_w.shape
  i = jnp.arange(length)[:, None]
  j = jnp.arange(n)[None, :]
  return jnp.sum(jnp.transpose(v_w, (1, 0, 2))[:, i + j, i], axis=1)


def _soft_weights(t: Array, loss_reg):
  """d minop / d t for the [3, BT, m] option stack (softmax of -t/reg;
  even split among ties for the hard min, matching reduce_min's JVP)."""
  if loss_reg is None:
    tmin = jnp.min(t, axis=0, keepdims=True)
    eq = (t == tmin).astype(jnp.float32)
    return eq / jnp.sum(eq, axis=0, keepdims=True)
  return jax.nn.softmax(-t / jnp.float32(loss_reg), axis=0)


def _bwd_kernel(subs_ref, ins_ref, lens_ref, g_ref, dsubs_ref, dins_ref,
                rows_ref, *, m, n, del_cost, loss_reg, inf):
  # Blocks: subs [K, BT, m], ins [K+1, BT, m+1], lens [BT], g [BT];
  # outputs dsubs [K, BT, m], dins [K+1, BT, m+1];
  # scratch rows [m+n+1, BT, m+1] holds every DP row V[k].
  bt = g_ref.shape[0]
  i_range = jax.lax.broadcasted_iota(jnp.int32, (1, m + 1), 1)
  lens = lens_ref[:]
  k_end = lens + n
  onehot_len = (
      jax.lax.broadcasted_iota(jnp.int32, (bt, m + 1), 1) == lens[:, None]
  ).astype(jnp.float32)

  minop = _make_minop(loss_reg)

  # Pass 1: forward recompute, materializing all rows in VMEM.
  row0, row1 = _init_rows(bt, m, ins_ref[0], del_cost, inf)
  rows_ref[0] = row0
  rows_ref[1] = row1

  def fwd_body(k, carry):
    v_p2, v_p1 = carry  # [BT, m], [BT, m+1]
    v_p2_next, v_new = _dp_step(
        k, v_p2, v_p1, subs_ref[k - 2], ins_ref[k - 1],
        i_range=i_range, n=n, del_cost=del_cost, minop=minop, inf=inf,
    )
    rows_ref[k] = v_new
    return v_p2_next, v_new

  jax.lax.fori_loop(2, m + n + 1, fwd_body, (row0[:, :m], row1))

  # Pass 2: reverse adjoint sweep. Carry holds the adjoints of rows
  # V[k] and V[k-1]; step k spreads dV[k] onto its three predecessors
  # weighted by the (recomputed) soft-min weights and emits the cost
  # gradients for diagonal k.
  g = g_ref[:]
  zeros_row = jnp.zeros((bt, m + 1), jnp.float32)

  def bwd_body(idx, carry):
    dA, dB = carry  # adjoints of V[k], V[k-1]
    k = m + n - idx
    valid = (k - i_range >= 0) & (k - i_range <= n)
    inject = g[:, None] * onehot_len * (k_end == k)[:, None].astype(
        jnp.float32
    )
    dA = jnp.where(valid, dA + inject, 0.0)
    v_p2 = rows_ref[k - 2][:, :m]
    v_p1 = rows_ref[k - 1]
    subs_k = subs_ref[k - 2]
    ins_k = ins_ref[k - 1]
    t = jnp.stack([
        v_p2 + subs_k,
        v_p1[:, 1:] + ins_k[:, 1:],
        v_p1[:, :-1] + del_cost,
    ])
    w = _soft_weights(t, loss_reg)
    dbody = dA[:, 1:]
    d_m = w[0] * dbody
    d_i1 = w[1] * dbody
    d_d = w[2] * dbody
    dsubs_ref[k - 2] = d_m
    dins_row = jnp.concatenate([dA[:, :1], d_i1], axis=1)
    dins_ref[k - 1] = dins_row
    zero_col = jnp.zeros((bt, 1), jnp.float32)
    dB_new = dB + dins_row + jnp.concatenate([d_d, zero_col], axis=1)
    dC = jnp.concatenate([d_m, zero_col], axis=1)
    return dB_new, dC

  dV1, _ = jax.lax.fori_loop(
      0, m + n - 1, bwd_body, (zeros_row, zeros_row)
  )
  # V[1][0] = ins_w[0][:, 0] is the only input-dependent init entry.
  dins_ref[0] = jnp.concatenate(
      [dV1[:, :1], jnp.zeros((bt, m), jnp.float32)], axis=1
  )


def _scores_fwd_impl(subs_costs, ins_costs, seq_lens, del_cost, loss_reg,
                     inf, batch_tile, interpret):
  return alignment_scores(
      subs_costs, ins_costs, del_cost, seq_lens, loss_reg=loss_reg,
      inf=inf, batch_tile=batch_tile,
      interpret=pallas_util.resolve_interpret(interpret),
  )



@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def alignment_scores_vjp(
    subs_costs: Array,
    ins_costs: Array,
    seq_lens: Array,
    del_cost: float,
    loss_reg: Optional[float],
    inf: float = 1e9,
    batch_tile: int = 8,
    interpret: Optional[bool] = None,
) -> Array:
  """Differentiable Pallas twin of wavefront.alignment_scan.

  Same scores as `alignment_scores`; gradients w.r.t. subs_costs and
  ins_costs come from the whole-DP backward kernel.
  """
  return _scores_fwd_impl(
      subs_costs, ins_costs, seq_lens, del_cost, loss_reg, inf,
      batch_tile, interpret,
  )


def _vjp_fwd(subs_costs, ins_costs, seq_lens, del_cost, loss_reg, inf,
             batch_tile, interpret):
  out = _scores_fwd_impl(
      subs_costs, ins_costs, seq_lens, del_cost, loss_reg, inf,
      batch_tile, interpret,
  )
  return out, (subs_costs, ins_costs, seq_lens)


def _vjp_bwd(del_cost, loss_reg, inf, batch_tile, interpret, res, g):
  import numpy as np

  subs_costs, ins_costs, seq_lens = res
  batch, m, n = subs_costs.shape
  bt = batch_tile
  while batch % bt:
    bt -= 1
  subs_w = wavefront.wavefrontify(subs_costs).astype(jnp.float32)
  ins_w = wavefront.wavefrontify_vec(ins_costs, m + 1).astype(jnp.float32)
  k_dim = subs_w.shape[0]

  d_subs_w, d_ins_w = pl.pallas_call(
      functools.partial(
          _bwd_kernel, m=m, n=n, del_cost=float(del_cost),
          loss_reg=None if loss_reg is None else float(loss_reg),
          inf=float(inf),
      ),
      grid=(batch // bt,),
      in_specs=[
          pl.BlockSpec((k_dim, bt, m), lambda i: (0, i, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((k_dim + 1, bt, m + 1), lambda i: (0, i, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((bt,), lambda i: (i,), memory_space=pltpu.VMEM),
          pl.BlockSpec((bt,), lambda i: (i,), memory_space=pltpu.VMEM),
      ],
      out_specs=[
          pl.BlockSpec((k_dim, bt, m), lambda i: (0, i, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((k_dim + 1, bt, m + 1), lambda i: (0, i, 0),
                       memory_space=pltpu.VMEM),
      ],
      out_shape=[
          jax.ShapeDtypeStruct((k_dim, batch, m), jnp.float32),
          jax.ShapeDtypeStruct((k_dim + 1, batch, m + 1), jnp.float32),
      ],
      scratch_shapes=[pltpu.VMEM((m + n + 1, bt, m + 1), jnp.float32)],
      interpret=pallas_util.resolve_interpret(interpret),
  )(subs_w, ins_w, seq_lens.astype(jnp.int32), g.astype(jnp.float32))

  d_subs = _unwavefrontify(d_subs_w, n).astype(subs_costs.dtype)
  d_ins = _unwavefrontify_vec_grad(d_ins_w, n).astype(ins_costs.dtype)
  d_lens = np.zeros(seq_lens.shape, jax.dtypes.float0)
  return d_subs, d_ins, d_lens


alignment_scores_vjp.defvjp(_vjp_fwd, _vjp_bwd)
