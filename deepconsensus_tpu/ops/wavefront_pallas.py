"""Pallas TPU kernel: whole-DP wavefront alignment scorer.

Runs the entire anti-diagonal recursion of the alignment score inside
one VMEM-resident kernel per batch tile (fori_loop over diagonals),
instead of a 200-step XLA while-loop whose per-step work is a few
hundred lanes. Forward-only scorer matching ops/wavefront.alignment_scan
semantics exactly; the differentiated training path keeps the lax.scan
formulation (a custom-VJP kernel is future work), so this kernel serves
hard-scoring/eval-style uses and as the measured baseline for that
work. Validated against alignment_scan in interpret mode.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepconsensus_tpu.ops import wavefront

Array = jnp.ndarray


def _kernel(subs_ref, ins_ref, lens_ref, out_ref, *, m, n, del_cost,
            loss_reg, inf):
  # Blocks: subs [K, BT, m], ins [K+1, BT, m+1], lens [BT], out [BT].
  bt = out_ref.shape[0]
  i_range = jax.lax.broadcasted_iota(jnp.int32, (1, m + 1), 1)

  if loss_reg is None:
    minop = lambda t: jnp.min(t, axis=0)
  else:
    reg = jnp.float32(loss_reg)
    minop = lambda t: -reg * jax.nn.logsumexp(-t / reg, axis=0)

  lens = lens_ref[:]  # [BT]
  k_end = lens + n
  onehot_len = (
      jax.lax.broadcasted_iota(jnp.int32, (bt, m + 1), 1)
      == lens[:, None]
  ).astype(jnp.float32)

  v_p2 = jnp.full((bt, m), inf, jnp.float32).at[:, 0].set(0.0)
  ins0 = ins_ref[0]  # [BT, m+1]
  v_p1 = jnp.concatenate(
      [
          ins0[:, :1],
          jnp.full((bt, 1), del_cost, jnp.float32),
          jnp.full((bt, m - 1), inf, jnp.float32),
      ],
      axis=1,
  )
  v_opt = jnp.full((bt,), inf, jnp.float32)

  def body(k, carry):
    v_p2, v_p1, v_opt = carry
    subs_k = subs_ref[k - 2]  # [BT, m]
    ins_k = ins_ref[k - 1]  # [BT, m+1]
    j_range = k - i_range  # [1, m+1]
    valid = (j_range >= 0) & (j_range <= n)

    o_m = v_p2 + subs_k
    o_i = v_p1 + ins_k
    v_p2_next = v_p1[:, :-1]
    o_d = v_p2_next + del_cost

    body_vals = minop(jnp.stack([o_m, o_i[:, 1:], o_d]))  # [BT, m]
    v_new = jnp.concatenate([o_i[:, :1], body_vals], axis=1)
    v_new = jnp.where(valid, v_new, inf)
    v_at_len = jnp.sum(v_new * onehot_len, axis=1)
    v_opt = jnp.where(k_end == k, v_at_len, v_opt)
    return v_p2_next, v_new, v_opt

  _, _, v_opt = jax.lax.fori_loop(2, m + n + 1, body, (v_p2, v_p1, v_opt))
  out_ref[:] = v_opt


def alignment_scores(
    subs_costs: Array,
    ins_costs: Array,
    del_cost: float,
    seq_lens: Array,
    loss_reg: Optional[float] = None,
    inf: float = 1e9,
    batch_tile: int = 8,
    interpret: bool = False,
) -> Array:
  """Pallas twin of wavefront.alignment_scan (same args/semantics)."""
  batch, m, n = subs_costs.shape
  while batch % batch_tile:
    batch_tile -= 1
  subs_w = wavefront.wavefrontify(subs_costs)  # [K, B, m]
  ins_w = wavefront.wavefrontify_vec(ins_costs, m + 1)  # [K+1, B, m+1]
  k_dim = subs_w.shape[0]

  grid = (batch // batch_tile,)
  return pl.pallas_call(
      functools.partial(
          _kernel, m=m, n=n, del_cost=float(del_cost),
          loss_reg=None if loss_reg is None else float(loss_reg),
          inf=float(inf),
      ),
      grid=grid,
      in_specs=[
          pl.BlockSpec((k_dim, batch_tile, m), lambda i: (0, i, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((k_dim + 1, batch_tile, m + 1), lambda i: (0, i, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((batch_tile,), lambda i: (i,),
                       memory_space=pltpu.VMEM),
      ],
      out_specs=pl.BlockSpec((batch_tile,), lambda i: (i,),
                             memory_space=pltpu.VMEM),
      out_shape=jax.ShapeDtypeStruct((batch,), jnp.float32),
      interpret=interpret,
  )(subs_w.astype(jnp.float32), ins_w.astype(jnp.float32),
    seq_lens.astype(jnp.int32))
