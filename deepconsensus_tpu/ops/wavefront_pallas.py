"""Pallas TPU kernels: pipelined whole-DP wavefront alignment scorer.

The alignment score is an anti-diagonal DP with a sequential dependence
over k = i + j. The TPU-native formulation here makes the *grid* the
diagonal axis: each grid step consumes a streamed block of `unroll`
diagonals of the wavefrontified cost tensors (Pallas double-buffers
the HBM->VMEM DMAs automatically) and updates carry rows held in VMEM
scratch that persist across grid steps. The full batch rides the
vector lanes of every step, so per-step work is `unroll` [B, m+1]
vector ops instead of the [batch_tile, m+1] slice a whole-DP-in-VMEM
kernel is limited to, and VMEM holds a few diagonal blocks instead of
the entire cost tensor.

`alignment_scores` is the forward scorer matching
ops/wavefront.alignment_scan semantics exactly; `alignment_scores_vjp`
wraps it in a jax.custom_vjp: the forward rule streams every DP row
V[k] to HBM and saves them as residuals, and the backward runs one
reverse-order adjoint sweep whose blocks walk the diagonals backwards
(soft-min weights recomputed per diagonal from the saved rows), so
AlignmentLoss trains through Pallas end-to-end in two DP sweeps per
step (the reference trains through this DP:
losses_and_metrics.py:346-411). Validated against alignment_scan
values and jax.grad in interpret mode and on TPU hardware.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepconsensus_tpu.ops import pallas_util
from deepconsensus_tpu.ops import wavefront

Array = jnp.ndarray

# Max diagonals computed per grid step in the forward kernel. Each
# diagonal's vector work ([B, m+1]) is tiny next to a grid step's fixed
# overhead, so unrolling amortizes the ~m+n sequential steps that
# dominate this DP's runtime. VMEM cost grows linearly with unroll
# (Pallas double-buffers the streamed [unroll, B, m]/[B, m+1] blocks,
# and emit_rows streams an [unroll, B, m+1] output block too), so the
# effective unroll is capped per call by _auto_unroll to keep streamed
# blocks inside a VMEM budget. Override the max via
# DC_TPU_PALLAS_UNROLL (1 disables unrolling).
import os as _os

PALLAS_UNROLL = int(_os.environ.get('DC_TPU_PALLAS_UNROLL', '8'))

# Streamed-block VMEM budget (bytes). ~16 MB/core total; leave room
# for the three [B, m+1] scratch rows and the non-streamed operands.
_VMEM_STREAM_BUDGET = 8 * 1024 * 1024


def _auto_unroll(requested, batch, lanes):
  """Largest unroll <= requested whose double-buffered streamed blocks
  fit in _VMEM_STREAM_BUDGET. `lanes` is the summed last-dim width of
  every [unroll, B, lanes_i] block the kernel streams (inputs and
  outputs), so per-diagonal bytes = 2 (double-buffer) * 4 (f32) * B *
  lanes."""
  per_diag = 2 * 4 * batch * lanes
  fit = max(1, _VMEM_STREAM_BUDGET // max(per_diag, 1))
  return max(1, min(requested, fit))


def _make_minop(loss_reg):
  if loss_reg is None:
    return lambda t: jnp.min(t, axis=0)
  reg = jnp.float32(loss_reg)
  return lambda t: -reg * jax.nn.logsumexp(-t / reg, axis=0)


def _init_rows(b, m, ins0, del_cost, inf):
  """DP rows V[0], V[1] as full [B, m+1] vectors (cells (i, k-i))."""
  row0 = jnp.concatenate(
      [jnp.zeros((b, 1), jnp.float32),
       jnp.full((b, m), inf, jnp.float32)], axis=1,
  )
  row1 = jnp.concatenate(
      [ins0[:, :1],
       jnp.full((b, 1), del_cost, jnp.float32),
       jnp.full((b, m - 1), inf, jnp.float32)], axis=1,
  )
  return row0, row1


def _dp_step(k, v_p2, v_p1, subs_k, ins_k, *, i_range, n, del_cost,
             minop, inf):
  """One anti-diagonal update (forward scorer; the backward recomputes
  its soft-min weights from the rows this step produced, so drift here
  would silently decouple loss values from gradients)."""
  valid = (k - i_range >= 0) & (k - i_range <= n)
  o_m = v_p2 + subs_k
  o_i = v_p1 + ins_k
  v_p2_next = v_p1[:, :-1]
  o_d = v_p2_next + del_cost
  body_vals = minop(jnp.stack([o_m, o_i[:, 1:], o_d]))
  v_new = jnp.where(
      valid, jnp.concatenate([o_i[:, :1], body_vals], axis=1), inf
  )
  return v_p2_next, v_new


def _recompute_band(k, rows_p2, rows_p1, subs_k, ins_k, del_cost,
                    loss_reg):
  """Option stack + soft-min weights at diagonal k (backward pass)."""
  t = jnp.stack([
      rows_p2[:, :-1] + subs_k,
      rows_p1[:, 1:] + ins_k[:, 1:],
      rows_p1[:, :-1] + del_cost,
  ])
  if loss_reg is None:
    tmin = jnp.min(t, axis=0, keepdims=True)
    eq = (t == tmin).astype(jnp.float32)
    w = eq / jnp.sum(eq, axis=0, keepdims=True)
  else:
    w = jax.nn.softmax(-t / jnp.float32(loss_reg), axis=0)
  return w


def _fwd_kernel(subs_ref, ins_ref, ins0_ref, lens_ref, out_ref, rows_ref,
                v_p2_ref, v_p1_ref, v_opt_ref, *, m, n, del_cost,
                loss_reg, inf, unroll):
  """Grid step g computes diagonals k = g*unroll + u + 2, u = 0..unroll-1.

  Streams subs[k-2] and ins[k-1] in blocks of `unroll` diagonals;
  carries V[k-2], V[k-1] in VMEM scratch across grid steps. The
  per-diagonal vector work ([B, m+1]) is far smaller than a grid
  step's fixed cost, so unrolling several diagonals per step amortizes
  the sequential-grid overhead that dominates this DP. Diagonals past
  m+n (grid padding) are masked invalid by the k-range check inside
  _dp_step. With emit_rows (rows_ref not None), every V[k] streams
  back to HBM for the backward sweep.
  """
  g = pl.program_id(0)
  b = v_p1_ref.shape[0]
  i_range = jax.lax.broadcasted_iota(jnp.int32, (1, m + 1), 1)
  minop = _make_minop(loss_reg)
  lens = lens_ref[:, 0]
  k_end = lens + n
  onehot_len = (
      jax.lax.broadcasted_iota(jnp.int32, (b, m + 1), 1) == lens[:, None]
  ).astype(jnp.float32)

  @pl.when(g == 0)
  def _init():
    row0, row1 = _init_rows(b, m, ins0_ref[:], del_cost, inf)
    v_p2_ref[:] = row0
    v_p1_ref[:] = row1
    v_opt_ref[:] = jnp.full((b, 1), inf, jnp.float32)

  v_p2 = v_p2_ref[:]
  v_p1 = v_p1_ref[:]
  v_opt = v_opt_ref[:]
  for u in range(unroll):
    k = g * unroll + u + 2
    v_p2_next, v_new = _dp_step(
        k, v_p2[:, :m], v_p1, subs_ref[u], ins_ref[u],
        i_range=i_range, n=n, del_cost=del_cost, minop=minop, inf=inf,
    )
    if rows_ref is not None:
      rows_ref[u] = v_new
    v_at_len = jnp.sum(v_new * onehot_len, axis=1, keepdims=True)
    hit = (k_end == k)[:, None].astype(jnp.float32)
    v_opt = v_opt * (1.0 - hit) + v_at_len * hit
    v_p2 = jnp.concatenate(
        [v_p2_next, jnp.full((b, 1), inf, jnp.float32)], axis=1
    )
    v_p1 = v_new
  v_p2_ref[:] = v_p2
  v_p1_ref[:] = v_p1
  v_opt_ref[:] = v_opt
  out_ref[:] = v_opt


def _pad_diagonals(t, n_pad, front=False):
  """Zero-pads a [K, ...]-leading diagonal stream to n_pad entries.

  front=True pads before entry 0, which keeps reverse-order block
  sweeps block-aligned (the backward kernel's block g covers the
  highest-k diagonals when g = 0)."""
  k_dim = t.shape[0]
  if k_dim == n_pad:
    return t
  pad = (n_pad - k_dim, 0) if front else (0, n_pad - k_dim)
  pad_widths = [pad] + [(0, 0)] * (t.ndim - 1)
  return jnp.pad(t, pad_widths)


def _fwd_call(subs_w, ins_w, seq_lens, m, n, del_cost, loss_reg, inf,
              interpret, emit_rows, unroll):
  k_dim = subs_w.shape[0]  # m + n - 1
  batch = subs_w.shape[1]
  lanes = 2 * m + 1 + ((m + 1) if emit_rows else 0)
  unroll = _auto_unroll(unroll, batch, lanes)
  unroll = max(1, min(unroll, k_dim))
  n_blocks = -(-k_dim // unroll)
  n_pad = n_blocks * unroll
  ins0 = ins_w[0]  # [B, m+1]
  subs_pad = _pad_diagonals(subs_w, n_pad)
  # ins diagonal for k lives at ins_w[k-1]; shift so entry j serves
  # k = j + 2, aligning ins blocks with subs blocks.
  ins_shift = _pad_diagonals(ins_w[1:], n_pad)
  impl = functools.partial(
      _fwd_kernel, m=m, n=n, del_cost=float(del_cost),
      loss_reg=None if loss_reg is None else float(loss_reg),
      inf=float(inf), unroll=unroll,
  )
  if emit_rows:
    kernel = impl
  else:
    def kernel(subs, ins, ins0_r, lens, out, s1, s2, s3):
      impl(subs, ins, ins0_r, lens, out, None, s1, s2, s3)
  out_specs = [
      pl.BlockSpec((batch, 1), lambda g: (0, 0),
                   memory_space=pltpu.VMEM),
  ]
  out_shape = [jax.ShapeDtypeStruct((batch, 1), jnp.float32)]
  if emit_rows:
    # rows[k] for k = 2..m+n; rows[0:2] are closed-form, filled XLA-side.
    out_specs.append(
        pl.BlockSpec((unroll, batch, m + 1), lambda g: (g, 0, 0),
                     memory_space=pltpu.VMEM)
    )
    out_shape.append(
        jax.ShapeDtypeStruct((n_pad, batch, m + 1), jnp.float32)
    )
  results = pl.pallas_call(
      kernel,
      grid=(n_blocks,),
      in_specs=[
          pl.BlockSpec((unroll, batch, m), lambda g: (g, 0, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((unroll, batch, m + 1), lambda g: (g, 0, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((batch, m + 1), lambda g: (0, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((batch, 1), lambda g: (0, 0),
                       memory_space=pltpu.VMEM),
      ],
      out_specs=out_specs,
      out_shape=out_shape,
      scratch_shapes=[
          pltpu.VMEM((batch, m + 1), jnp.float32),
          pltpu.VMEM((batch, m + 1), jnp.float32),
          pltpu.VMEM((batch, 1), jnp.float32),
      ],
      interpret=interpret,
  )(subs_pad, ins_shift, ins0, seq_lens.astype(jnp.int32)[:, None])
  if emit_rows:
    return results[0], results[1][:k_dim]
  return results


def _scores_and_rows(subs_costs, ins_costs, del_cost, seq_lens, loss_reg,
                     inf, interpret, emit_rows, unroll=None):
  """Shared forward pipeline (wavefrontify + kernel call) for the plain
  scorer and the custom-VJP fwd rule — one copy, so the rule's output
  can never drift from the primal's. Returns (scores, rows|None)."""
  _, m, n = subs_costs.shape
  subs_w = wavefrontify32(subs_costs)  # [K, B, m]
  ins_w = wavefrontify_vec32(ins_costs, m + 1)  # [K+1, B, m+1]
  res = _fwd_call(
      subs_w, ins_w, seq_lens, m, n, del_cost, loss_reg, inf,
      interpret, emit_rows=emit_rows,
      unroll=PALLAS_UNROLL if unroll is None else unroll,
  )
  if emit_rows:
    out, rows = res
    return out[:, 0], rows
  (out,) = res
  return out[:, 0], None


def alignment_scores(
    subs_costs: Array,
    ins_costs: Array,
    del_cost: float,
    seq_lens: Array,
    loss_reg: Optional[float] = None,
    inf: float = 1e9,
    interpret: bool = False,
    unroll: Optional[int] = None,
) -> Array:
  """Pallas twin of wavefront.alignment_scan (same args/semantics)."""
  out, _ = _scores_and_rows(
      subs_costs, ins_costs, del_cost, seq_lens, loss_reg, inf,
      interpret, emit_rows=False, unroll=unroll,
  )
  return out


def wavefrontify32(t: Array) -> Array:
  return wavefront.wavefrontify(t).astype(jnp.float32)


def wavefrontify_vec32(v: Array, len1: int) -> Array:
  return wavefront.wavefrontify_vec(v, len1).astype(jnp.float32)


def _unwavefrontify(t_w: Array, n: int) -> Array:
  """Inverse of wavefront.wavefrontify: [K, B, m] -> [B, m, n] with
  out[b, i, j] = t_w[i+j, b, i] (the forward map is one-to-one)."""
  _, _, m = t_w.shape
  i = jnp.arange(m)[:, None]
  j = jnp.arange(n)[None, :]
  return jnp.transpose(t_w, (1, 0, 2))[:, i + j, i]


def _unwavefrontify_vec_grad(v_w: Array, n: int) -> Array:
  """Adjoint of wavefront.wavefrontify_vec: [K2, B, L] -> [B, n].

  The forward broadcasts v[b, j] to every slot (k=i+j, i), so the
  adjoint sums over i: out[b, j] = sum_i v_w[i+j, b, i].
  """
  _, _, length = v_w.shape
  i = jnp.arange(length)[:, None]
  j = jnp.arange(n)[None, :]
  return jnp.sum(jnp.transpose(v_w, (1, 0, 2))[:, i + j, i], axis=1)


def _bwd_kernel(subs_ref, ins_ref, rows_p2_ref, rows_p1_ref, lens_ref,
                g_ref, dsubs_ref, dins_ref, dv1_ref, dA_ref, dB_ref, *,
                m, n, del_cost, loss_reg, inf, k_total, unroll):
  """Reverse adjoint sweep; grid step g handles diagonals
  k = j + 2 for j = (k_total-1) - (g+1)*unroll + u, u descending.

  Every stream (subs[k-2], ins[k-1], recorded DP rows V[k-2], V[k-1],
  and the emitted gradients) is indexed by j = k - 2 and front-padded
  to a multiple of `unroll`, so the reverse sweep walks whole blocks
  from the high-k end (block index n_blocks-1-g) and stays
  block-aligned. Front-padding entries have k < 2; their carry
  updates are masked out (their block writes land in the padding,
  sliced off by the caller). Carry: dA = adjoint of V[k], dB =
  adjoint of V[k-1]. Step k spreads dA onto the three predecessor
  rows weighted by the recomputed soft-min weights and emits the
  cost-gradient diagonals dsubs[k-2], dins[k-1].
  """
  del inf
  g = pl.program_id(0)
  b = dA_ref.shape[0]
  i_range = jax.lax.broadcasted_iota(jnp.int32, (1, m + 1), 1)
  lens = lens_ref[:, 0]
  k_end = lens + n
  onehot_len = (
      jax.lax.broadcasted_iota(jnp.int32, (b, m + 1), 1) == lens[:, None]
  ).astype(jnp.float32)

  @pl.when(g == 0)
  def _init():
    dA_ref[:] = jnp.zeros((b, m + 1), jnp.float32)
    dB_ref[:] = jnp.zeros((b, m + 1), jnp.float32)
    dv1_ref[:] = jnp.zeros((b, m + 1), jnp.float32)

  dA_c = dA_ref[:]
  dB_c = dB_ref[:]
  dv1 = dv1_ref[:]
  zero_col = jnp.zeros((b, 1), jnp.float32)
  for u in reversed(range(unroll)):
    k = (k_total - 1) - (g + 1) * unroll + u + 2
    valid = (k - i_range >= 0) & (k - i_range <= n)
    inject = g_ref[:, :1] * onehot_len * (k_end == k)[:, None].astype(
        jnp.float32
    )
    dA = jnp.where(valid, dA_c + inject, 0.0)

    w = _recompute_band(
        k, rows_p2_ref[u], rows_p1_ref[u], subs_ref[u], ins_ref[u],
        del_cost, loss_reg,
    )
    dbody = dA[:, 1:]
    d_m = w[0] * dbody
    d_i1 = w[1] * dbody
    d_d = w[2] * dbody
    dsubs_ref[u] = d_m
    dins_row = jnp.concatenate([dA[:, :1], d_i1], axis=1)
    dins_ref[u] = dins_row
    dB_new = dB_c + dins_row + jnp.concatenate([d_d, zero_col], axis=1)
    # Front-padding diagonals (k < 2) must not advance the carry: the
    # final dv1 (written at k = 2) is the closed-form dV[1] adjoint.
    ok = k >= 2
    dA_c = jnp.where(ok, dB_new, dA_c)
    dB_c = jnp.where(
        ok, jnp.concatenate([d_m, zero_col], axis=1), dB_c
    )
    dv1 = jnp.where(ok, dB_new, dv1)
  dA_ref[:] = dA_c
  dB_ref[:] = dB_c
  dv1_ref[:] = dv1


def _scores_fwd_impl(subs_costs, ins_costs, seq_lens, del_cost, loss_reg,
                     inf, interpret, emit_rows=False, unroll=None):
  return _scores_and_rows(
      subs_costs, ins_costs, del_cost, seq_lens, loss_reg, inf,
      pallas_util.resolve_interpret(interpret), emit_rows=emit_rows,
      unroll=unroll,
  )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def alignment_scores_vjp(
    subs_costs: Array,
    ins_costs: Array,
    seq_lens: Array,
    del_cost: float,
    loss_reg: Optional[float],
    inf: float = 1e9,
    interpret: Optional[bool] = None,
    unroll: Optional[int] = None,
) -> Array:
  """Differentiable Pallas twin of wavefront.alignment_scan.

  Same scores as `alignment_scores`; gradients w.r.t. subs_costs and
  ins_costs come from the pipelined backward kernels. `unroll` caps the
  per-grid-step diagonal unroll for both sweeps (None = PALLAS_UNROLL;
  the VMEM fit still applies, so forward and backward may resolve to
  different effective unrolls — results are unroll-invariant either
  way).
  """
  out, _ = _scores_fwd_impl(
      subs_costs, ins_costs, seq_lens, del_cost, loss_reg, inf,
      interpret, unroll=unroll,
  )
  return out


def _vjp_fwd(subs_costs, ins_costs, seq_lens, del_cost, loss_reg, inf,
             interpret, unroll):
  # Run the forward with emit_rows=True and save every DP row V[k] as
  # a residual: the backward then starts directly at the reverse
  # adjoint sweep instead of re-running the whole forward DP (one of
  # three otherwise-equal-cost sweeps per training step). The rows
  # residual is [m+n+1, B, m+1] f32 in HBM — ~110 MB at B=1024,
  # m=121, well inside a v5e's 16 GB. The cost tensors are saved in
  # their original [B, m, n] layout/dtype; the backward re-derives the
  # wavefrontified views (a cheap XLA gather next to the DP sweep).
  out, rows_kernel = _scores_fwd_impl(
      subs_costs, ins_costs, seq_lens, del_cost, loss_reg, inf,
      interpret, emit_rows=True, unroll=unroll,
  )
  return out, (subs_costs, ins_costs, seq_lens, rows_kernel)


def _vjp_bwd(del_cost, loss_reg, inf, interpret, unroll, res, g):
  import numpy as np

  subs_costs, ins_costs, seq_lens, rows_kernel = res
  batch, m, n = subs_costs.shape
  subs_w = wavefrontify32(subs_costs)
  ins_w = wavefrontify_vec32(ins_costs, m + 1)
  k_dim = subs_w.shape[0]  # m + n - 1
  interp = pallas_util.resolve_interpret(interpret)
  k_total = m + n

  row0, row1 = _init_rows(batch, m, ins_w[0], float(del_cost), float(inf))
  rows = jnp.concatenate(
      [row0[None], row1[None], rows_kernel], axis=0
  )  # [m+n+1, B, m+1], rows[k] = V[k]

  # Pass 2: reverse sweep in blocks of `unroll` diagonals. Every
  # stream is re-indexed by j = k - 2 (subs[j], ins_w[j+1], V[j],
  # V[j+1], gradients) and front-padded to a block multiple, so block
  # n_blocks-1-g holds the g-th-from-the-top group of diagonals and
  # the kernel walks u descending inside it.
  # Backward streams 6 [unroll, B, ~m] blocks per diagonal (4 in,
  # 2 out), so the VMEM-fitted unroll is smaller than the forward's.
  unroll = _auto_unroll(
      PALLAS_UNROLL if unroll is None else unroll, batch, 6 * m + 4
  )
  unroll = max(1, min(unroll, k_dim))
  n_blocks = -(-k_dim // unroll)
  n_pad = n_blocks * unroll
  subs_b = _pad_diagonals(subs_w, n_pad, front=True)
  ins_b = _pad_diagonals(ins_w[1:], n_pad, front=True)
  rows_p2_b = _pad_diagonals(rows[:-2], n_pad, front=True)
  rows_p1_b = _pad_diagonals(rows[1:-1], n_pad, front=True)
  rev_spec_m = pl.BlockSpec(
      (unroll, batch, m), lambda gi: (n_blocks - 1 - gi, 0, 0),
      memory_space=pltpu.VMEM)
  rev_spec_m1 = pl.BlockSpec(
      (unroll, batch, m + 1), lambda gi: (n_blocks - 1 - gi, 0, 0),
      memory_space=pltpu.VMEM)
  d_subs_pad, d_ins_pad, dv1 = pl.pallas_call(
      functools.partial(
          _bwd_kernel, m=m, n=n, del_cost=float(del_cost),
          loss_reg=None if loss_reg is None else float(loss_reg),
          inf=float(inf), k_total=k_total, unroll=unroll,
      ),
      grid=(n_blocks,),
      in_specs=[
          rev_spec_m,
          rev_spec_m1,
          rev_spec_m1,
          rev_spec_m1,
          pl.BlockSpec((batch, 1), lambda gi: (0, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((batch, 1), lambda gi: (0, 0),
                       memory_space=pltpu.VMEM),
      ],
      out_specs=[
          rev_spec_m,
          rev_spec_m1,
          pl.BlockSpec((batch, m + 1), lambda gi: (0, 0),
                       memory_space=pltpu.VMEM),
      ],
      out_shape=[
          jax.ShapeDtypeStruct((n_pad, batch, m), jnp.float32),
          jax.ShapeDtypeStruct((n_pad, batch, m + 1), jnp.float32),
          jax.ShapeDtypeStruct((batch, m + 1), jnp.float32),
      ],
      scratch_shapes=[
          pltpu.VMEM((batch, m + 1), jnp.float32),
          pltpu.VMEM((batch, m + 1), jnp.float32),
      ],
      interpret=interp,
  )(subs_b, ins_b, rows_p2_b, rows_p1_b,
    seq_lens.astype(jnp.int32)[:, None], g.astype(jnp.float32)[:, None])

  d_subs_w = d_subs_pad[n_pad - k_dim:]
  # The kernel emits dins at j = k - 2 >= 0, i.e. ins_w entries 1..;
  # V[1][0] = ins_w[0][:, 0] is the only input-dependent init entry,
  # so dins[0] comes from the dV[1] carry.
  d_ins_w = jnp.concatenate(
      [jnp.concatenate(
          [dv1[:, :1], jnp.zeros((batch, m), jnp.float32)], axis=1
      )[None],
       d_ins_pad[n_pad - k_dim:]], axis=0
  )
  d_subs = _unwavefrontify(d_subs_w, n).astype(subs_costs.dtype)
  d_ins = _unwavefrontify_vec_grad(d_ins_w, n).astype(ins_costs.dtype)
  d_lens = np.zeros(seq_lens.shape, jax.dtypes.float0)
  return d_subs, d_ins, d_lens


alignment_scores_vjp.defvjp(_vjp_fwd, _vjp_bwd)


# ---------------------------------------------------------------------------
# Banded wavefront DP: Pallas twins of wavefront.banded_alignment_scan.
#
# Band coordinates: cell (x, y) of the [m+1, m+1] DP matrix lives at
# (k = x + y, d = y - x + width); odd-parity slots hold no cell and
# stay at `inf` (the cost streams put `inf` there, and valid slots only
# ever read same-parity predecessors). The grid walks k = 2..2m with
# [B, 2*width+1] carries in VMEM scratch — the band-space analogue of
# the unbanded diagonal-grid kernel above, reusing its streaming /
# unroll / rows-as-residuals design (reference banded recursion:
# losses_and_metrics.py:413-547).
# ---------------------------------------------------------------------------


def _band_cost_streams(subs_costs, ins_costs, width, inf):
  """Per-diagonal cost bands for k = 2..2m: ([K, B, n_diag],) * 2 f32
  with K = 2m - 1 — the vectorized form of banded_alignment_scan's
  subs_at/ins_at gathers, computed once XLA-side and streamed."""
  batch, m, n = subs_costs.shape
  n_diag = 2 * width + 1
  d = jnp.arange(n_diag)
  ks = jnp.arange(2, 2 * m + 1)
  x2 = ks[:, None] - d[None, :] + width  # [K, n_diag]
  y2 = ks[:, None] + d[None, :] - width
  s_valid = (
      (x2 % 2 == 0) & (x2 >= 2) & (y2 >= 2) & (x2 <= 2 * m) & (y2 <= 2 * n)
  )
  xi = jnp.clip(x2 // 2 - 1, 0, m - 1)
  yi = jnp.clip(y2 // 2 - 1, 0, n - 1)
  subs_band = jnp.where(
      s_valid[None], subs_costs[:, xi, yi], inf
  )  # [B, K, n_diag]
  i_valid = (x2 % 2 == 0) & (x2 >= 0) & (y2 >= 0)
  y = jnp.clip(y2 // 2, 0, n)
  ins_pad = jnp.concatenate(
      [jnp.zeros((batch, 1), ins_costs.dtype), ins_costs], axis=1
  )
  ins_band = jnp.where(i_valid[None], ins_pad[:, y], inf)
  return (
      jnp.transpose(subs_band, (1, 0, 2)).astype(jnp.float32),
      jnp.transpose(ins_band, (1, 0, 2)).astype(jnp.float32),
  )


def _band_init_rows(b, n_diag, width, ins0, del_cost, inf):
  """Band rows at k=0 (only cell (0,0)=0) and k=1 (cells (1,0)=del and
  (0,1)=ins[0]), as [B, n_diag] f32."""
  d = jax.lax.broadcasted_iota(jnp.int32, (b, n_diag), 1)
  row0 = jnp.where(d == width, 0.0, jnp.float32(inf))
  row1 = jnp.full((b, n_diag), inf, jnp.float32)
  row1 = jnp.where(d == width - 1, jnp.float32(del_cost), row1)
  row1 = jnp.where(d == width + 1, ins0, row1)
  return row0, row1


def _band_ends(lens, n, width):
  """Band evaluation cell (reference index_ending_band):
  (x, y) = (lens, min(n, lens + width)) -> (k_end, d_end)."""
  y_end = jnp.minimum(n, lens + width)
  return lens + y_end, y_end - lens + width


def _band_step(p2, p1, subs_k, ins_k, del_cost, minop, inf, b):
  """One band diagonal update (identical algebra to the scan step)."""
  inf_col = jnp.full((b, 1), inf, jnp.float32)
  o_m = p2 + subs_k
  o_d = jnp.concatenate([p1[:, 1:], inf_col], axis=1) + del_cost
  o_i = jnp.concatenate([inf_col, p1[:, :-1]], axis=1) + ins_k
  return minop(jnp.stack([o_m, o_d, o_i]))


def _band_fwd_kernel(subs_ref, ins_ref, ins0_ref, lens_ref, out_ref,
                     rows_ref, p2_ref, p1_ref, opt_ref, *, m, width,
                     del_cost, loss_reg, inf, unroll):
  """Grid step g computes band diagonals k = g*unroll + u + 2."""
  g = pl.program_id(0)
  b = p1_ref.shape[0]
  n_diag = 2 * width + 1
  minop = _make_minop(loss_reg)
  lens = lens_ref[:, 0]
  k_end, d_end = _band_ends(lens, m, width)
  onehot_d = (
      jax.lax.broadcasted_iota(jnp.int32, (b, n_diag), 1) == d_end[:, None]
  ).astype(jnp.float32)

  @pl.when(g == 0)
  def _init():
    row0, row1 = _band_init_rows(
        b, n_diag, width, ins0_ref[:, :1], del_cost, inf
    )
    p2_ref[:] = row0
    p1_ref[:] = row1
    # k_end < 2 never fires inside the streamed loop; latch the
    # closed-form rows here (k_end = 0 needs width = 0 or an empty
    # window; k_end = 1 happens at lens = 0, width = 1).
    opt = jnp.full((b, 1), inf, jnp.float32)
    opt0 = jnp.sum(row0 * onehot_d, axis=1, keepdims=True)
    opt1 = jnp.sum(row1 * onehot_d, axis=1, keepdims=True)
    opt = jnp.where((k_end == 0)[:, None], opt0, opt)
    opt = jnp.where((k_end == 1)[:, None], opt1, opt)
    opt_ref[:] = opt

  p2 = p2_ref[:]
  p1 = p1_ref[:]
  opt = opt_ref[:]
  for u in range(unroll):
    k = g * unroll + u + 2
    new = _band_step(p2, p1, subs_ref[u], ins_ref[u], del_cost, minop,
                     inf, b)
    if rows_ref is not None:
      rows_ref[u] = new
    hit = (k_end == k)[:, None].astype(jnp.float32)
    v_at = jnp.sum(new * onehot_d, axis=1, keepdims=True)
    opt = opt * (1.0 - hit) + v_at * hit
    p2 = p1
    p1 = new
  p2_ref[:] = p2
  p1_ref[:] = p1
  opt_ref[:] = opt
  out_ref[:] = opt


def _band_fwd_call(subs_band, ins_band, ins0, seq_lens, m, width,
                   del_cost, loss_reg, inf, interpret, emit_rows, unroll):
  k_dim = subs_band.shape[0]  # 2m - 1
  batch = subs_band.shape[1]
  n_diag = 2 * width + 1
  lanes = 2 * n_diag + (n_diag if emit_rows else 0)
  unroll = _auto_unroll(unroll, batch, lanes)
  unroll = max(1, min(unroll, k_dim))
  n_blocks = -(-k_dim // unroll)
  n_pad = n_blocks * unroll
  subs_pad = _pad_diagonals(subs_band, n_pad)
  ins_pad = _pad_diagonals(ins_band, n_pad)
  impl = functools.partial(
      _band_fwd_kernel, m=m, width=width, del_cost=float(del_cost),
      loss_reg=None if loss_reg is None else float(loss_reg),
      inf=float(inf), unroll=unroll,
  )
  if emit_rows:
    kernel = impl
  else:
    def kernel(subs, ins, ins0_r, lens, out, s1, s2, s3):
      impl(subs, ins, ins0_r, lens, out, None, s1, s2, s3)
  out_specs = [
      pl.BlockSpec((batch, 1), lambda g: (0, 0), memory_space=pltpu.VMEM),
  ]
  out_shape = [jax.ShapeDtypeStruct((batch, 1), jnp.float32)]
  if emit_rows:
    out_specs.append(
        pl.BlockSpec((unroll, batch, n_diag), lambda g: (g, 0, 0),
                     memory_space=pltpu.VMEM)
    )
    out_shape.append(
        jax.ShapeDtypeStruct((n_pad, batch, n_diag), jnp.float32)
    )
  results = pl.pallas_call(
      kernel,
      grid=(n_blocks,),
      in_specs=[
          pl.BlockSpec((unroll, batch, n_diag), lambda g: (g, 0, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((unroll, batch, n_diag), lambda g: (g, 0, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((batch, 1), lambda g: (0, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((batch, 1), lambda g: (0, 0),
                       memory_space=pltpu.VMEM),
      ],
      out_specs=out_specs,
      out_shape=out_shape,
      scratch_shapes=[
          pltpu.VMEM((batch, n_diag), jnp.float32),
          pltpu.VMEM((batch, n_diag), jnp.float32),
          pltpu.VMEM((batch, 1), jnp.float32),
      ],
      interpret=interpret,
  )(subs_pad, ins_pad, ins0, seq_lens.astype(jnp.int32)[:, None])
  if emit_rows:
    return results[0], results[1][:k_dim]
  return results


def _banded_scores_and_rows(subs_costs, ins_costs, del_cost, seq_lens,
                            width, loss_reg, inf, interpret, emit_rows,
                            unroll=None):
  batch, m, n = subs_costs.shape
  if m != n:
    raise ValueError('banded alignment requires m == n')
  if width < 1:
    raise ValueError('band width must be >= 1')
  subs_band, ins_band = _band_cost_streams(
      subs_costs, ins_costs, width, float(inf)
  )
  ins0 = ins_costs[:, :1].astype(jnp.float32)
  res = _band_fwd_call(
      subs_band, ins_band, ins0, seq_lens, m, width, del_cost, loss_reg,
      inf, interpret, emit_rows=emit_rows,
      unroll=PALLAS_UNROLL if unroll is None else unroll,
  )
  if emit_rows:
    out, rows = res
    return out[:, 0], rows
  (out,) = res
  return out[:, 0], None


def banded_alignment_scores(
    subs_costs: Array,
    ins_costs: Array,
    del_cost: float,
    seq_lens: Array,
    width: int,
    loss_reg: Optional[float] = None,
    inf: float = 1e9,
    interpret: bool = False,
    unroll: Optional[int] = None,
) -> Array:
  """Pallas twin of wavefront.banded_alignment_scan (same semantics)."""
  out, _ = _banded_scores_and_rows(
      subs_costs, ins_costs, del_cost, seq_lens, int(width), loss_reg,
      inf, interpret, emit_rows=False, unroll=unroll,
  )
  return out


def _band_bwd_kernel(subs_ref, ins_ref, rows_p2_ref, rows_p1_ref,
                     lens_ref, g_ref, dsubs_ref, dins_ref, dv1_ref,
                     dA_ref, dB_ref, *, m, width, del_cost, loss_reg,
                     inf, k_total, unroll):
  """Reverse adjoint sweep over band diagonals (block-aligned like the
  unbanded backward: streams are front-padded, block g covers the
  (g+1)-th-from-the-top group of diagonals, u walks descending).

  Carry: dA = adjoint of band[k], dB = adjoint of band[k-1]. A step
  spreads dA over the three predecessors with the recomputed soft-min
  weights: match -> band[k-2][d], delete -> band[k-1][d+1], insert ->
  band[k-1][d-1]; emits dsubs[k], dins[k] cost-band gradients."""
  g = pl.program_id(0)
  b = dA_ref.shape[0]
  n_diag = 2 * width + 1
  lens = lens_ref[:, 0]
  k_end, d_end = _band_ends(lens, m, width)
  onehot_d = (
      jax.lax.broadcasted_iota(jnp.int32, (b, n_diag), 1) == d_end[:, None]
  ).astype(jnp.float32)

  @pl.when(g == 0)
  def _init():
    dA_ref[:] = jnp.zeros((b, n_diag), jnp.float32)
    dB_ref[:] = jnp.zeros((b, n_diag), jnp.float32)
    dv1_ref[:] = jnp.zeros((b, n_diag), jnp.float32)

  dA_c = dA_ref[:]
  dB_c = dB_ref[:]
  dv1 = dv1_ref[:]
  zero_col = jnp.zeros((b, 1), jnp.float32)
  for u in reversed(range(unroll)):
    k = (k_total - 1) - (g + 1) * unroll + u + 2
    inject = g_ref[:, :1] * onehot_d * (k_end == k)[:, None].astype(
        jnp.float32
    )
    dA = dA_c + inject

    p2 = rows_p2_ref[u]
    p1 = rows_p1_ref[u]
    inf_col = jnp.full((b, 1), inf, jnp.float32)
    t = jnp.stack([
        p2 + subs_ref[u],
        jnp.concatenate([p1[:, 1:], inf_col], axis=1) + del_cost,
        jnp.concatenate([inf_col, p1[:, :-1]], axis=1) + ins_ref[u],
    ])
    if loss_reg is None:
      tmin = jnp.min(t, axis=0, keepdims=True)
      eq = (t == tmin).astype(jnp.float32)
      w = eq / jnp.sum(eq, axis=0, keepdims=True)
    else:
      w = jax.nn.softmax(-t / jnp.float32(loss_reg), axis=0)

    d_m = w[0] * dA
    a_del = w[1] * dA
    b_ins = w[2] * dA
    dsubs_ref[u] = d_m
    dins_ref[u] = b_ins
    dp1 = (
        dB_c
        + jnp.concatenate([zero_col, a_del[:, :-1]], axis=1)
        + jnp.concatenate([b_ins[:, 1:], zero_col], axis=1)
    )
    ok = k >= 2
    dA_c = jnp.where(ok, dp1, dA_c)
    dB_c = jnp.where(ok, d_m, dB_c)
    dv1 = jnp.where(ok, dp1, dv1)
  dA_ref[:] = dA_c
  dB_ref[:] = dB_c
  dv1_ref[:] = dv1


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def banded_alignment_scores_vjp(
    subs_costs: Array,
    ins_costs: Array,
    seq_lens: Array,
    del_cost: float,
    loss_reg: Optional[float],
    width: int,
    inf: float = 1e9,
    interpret: Optional[bool] = None,
    unroll: Optional[int] = None,
) -> Array:
  """Differentiable Pallas twin of wavefront.banded_alignment_scan."""
  out, _ = _banded_scores_and_rows(
      subs_costs, ins_costs, del_cost, seq_lens, int(width), loss_reg,
      inf, pallas_util.resolve_interpret(interpret), emit_rows=False,
      unroll=unroll,
  )
  return out


def _banded_vjp_fwd(subs_costs, ins_costs, seq_lens, del_cost, loss_reg,
                    width, inf, interpret, unroll):
  out, rows_kernel = _banded_scores_and_rows(
      subs_costs, ins_costs, del_cost, seq_lens, int(width), loss_reg,
      inf, pallas_util.resolve_interpret(interpret), emit_rows=True,
      unroll=unroll,
  )
  return out, (subs_costs, ins_costs, seq_lens, rows_kernel)


def _banded_vjp_bwd(del_cost, loss_reg, width, inf, interpret, unroll,
                    res, g):
  import numpy as np

  subs_costs, ins_costs, seq_lens, rows_kernel = res
  batch, m, n = subs_costs.shape
  width = int(width)
  n_diag = 2 * width + 1
  interp = pallas_util.resolve_interpret(interpret)
  subs_band, ins_band = _band_cost_streams(
      subs_costs, ins_costs, width, float(inf)
  )
  k_dim = subs_band.shape[0]  # 2m - 1
  k_total = 2 * m  # maximum band diagonal (k runs 2..2m)

  ins0 = ins_costs[:, :1].astype(jnp.float32)
  row0, row1 = _band_init_rows(
      batch, n_diag, width, ins0, float(del_cost), float(inf)
  )
  rows = jnp.concatenate([row0[None], row1[None], rows_kernel], axis=0)

  unroll_eff = _auto_unroll(
      PALLAS_UNROLL if unroll is None else unroll, batch, 6 * n_diag
  )
  unroll_eff = max(1, min(unroll_eff, k_dim))
  n_blocks = -(-k_dim // unroll_eff)
  n_pad = n_blocks * unroll_eff
  subs_b = _pad_diagonals(subs_band, n_pad, front=True)
  ins_b = _pad_diagonals(ins_band, n_pad, front=True)
  rows_p2_b = _pad_diagonals(rows[:-2], n_pad, front=True)
  rows_p1_b = _pad_diagonals(rows[1:-1], n_pad, front=True)
  rev_spec = pl.BlockSpec(
      (unroll_eff, batch, n_diag), lambda gi: (n_blocks - 1 - gi, 0, 0),
      memory_space=pltpu.VMEM)
  d_subs_pad, d_ins_pad, dv1 = pl.pallas_call(
      functools.partial(
          _band_bwd_kernel, m=m, width=width, del_cost=float(del_cost),
          loss_reg=None if loss_reg is None else float(loss_reg),
          inf=float(inf), k_total=k_total, unroll=unroll_eff,
      ),
      grid=(n_blocks,),
      in_specs=[
          rev_spec,
          rev_spec,
          rev_spec,
          rev_spec,
          pl.BlockSpec((batch, 1), lambda gi: (0, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((batch, 1), lambda gi: (0, 0),
                       memory_space=pltpu.VMEM),
      ],
      out_specs=[
          rev_spec,
          rev_spec,
          pl.BlockSpec((batch, n_diag), lambda gi: (0, 0),
                       memory_space=pltpu.VMEM),
      ],
      out_shape=[
          jax.ShapeDtypeStruct((n_pad, batch, n_diag), jnp.float32),
          jax.ShapeDtypeStruct((n_pad, batch, n_diag), jnp.float32),
          jax.ShapeDtypeStruct((batch, n_diag), jnp.float32),
      ],
      scratch_shapes=[
          pltpu.VMEM((batch, n_diag), jnp.float32),
          pltpu.VMEM((batch, n_diag), jnp.float32),
      ],
      interpret=interp,
  )(subs_b, ins_b, rows_p2_b, rows_p1_b,
    seq_lens.astype(jnp.int32)[:, None], g.astype(jnp.float32)[:, None])

  d_subs_band = d_subs_pad[n_pad - k_dim:]  # [K, B, n_diag], K index = k-2
  d_ins_band = d_ins_pad[n_pad - k_dim:]

  # Un-band dsubs: cell (i, j) of subs_costs was consumed by slot
  # (k = i + j + 2, d = j - i + width) iff inside the band.
  i = jnp.arange(m)[:, None]
  j = jnp.arange(n)[None, :]
  kidx = i + j  # stream index k - 2
  didx = j - i + width
  s_ok = (didx >= 0) & (didx < n_diag)
  d_subs = jnp.where(
      s_ok[None],
      jnp.transpose(d_subs_band, (1, 0, 2))[
          :, kidx, jnp.clip(didx, 0, n_diag - 1)
      ],
      0.0,
  )

  # Un-band dins: ins_costs[:, y-1] was consumed by every band slot
  # with that y: (k = x + y, d = y - x + width) for x = 0..m in band —
  # plus the k = 1 init slot (0, 1), whose adjoint is dv1[width+1].
  xs = jnp.arange(m + 1)[None, :]  # [1, m+1]
  ys = jnp.arange(1, n + 1)[:, None]  # [n, 1] (y = j + 1)
  kidx_i = xs + ys - 2  # stream index k - 2
  didx_i = ys - xs + width
  i_ok = (kidx_i >= 0) & (kidx_i < k_dim) & (didx_i >= 0) & (
      didx_i < n_diag
  )
  gathered = jnp.transpose(d_ins_band, (1, 0, 2))[
      :, jnp.clip(kidx_i, 0, k_dim - 1), jnp.clip(didx_i, 0, n_diag - 1)
  ]  # [B, n, m+1]
  d_ins = jnp.sum(jnp.where(i_ok[None], gathered, 0.0), axis=2)
  d_ins = d_ins.at[:, 0].add(dv1[:, width + 1])

  d_lens = np.zeros(seq_lens.shape, jax.dtypes.float0)
  return (
      d_subs.astype(subs_costs.dtype),
      d_ins.astype(ins_costs.dtype),
      d_lens,
  )


banded_alignment_scores_vjp.defvjp(_banded_vjp_fwd, _banded_vjp_bwd)
