"""Pallas TPU kernel: ragged mixed-width windows in fixed page-style slots.

The bucketed engine (PR 12) ended pad-to-max waste but left N buckets
= N packers and N compiled forwards, plus a starvation flush that
re-introduces padding whenever one bucket starves. This kernel removes
the bucket dimension entirely, borrowing the page layout from Ragged
Paged Attention (arxiv 2604.15464): windows of any bucket width are
packed back-to-back into fixed-length SLOTS (slot length = the largest
bucket), and a per-slot ``lengths`` int32 vector — not the compile-time
L — drives everything that used to depend on the window width:

  * the banded attention mask becomes band AND same-window AND valid,
    where the window ownership of each position is derived from
    ``lengths`` with static iota/compare ops (`slot_geometry`);
  * the sinusoidal position add becomes a per-position gather of
    ``pos[p - window_start(p)]``, done in-kernel as a one-hot matmul
    (exact: each one-hot row has a single 1, so the MXU sum has one
    non-zero term);
  * the condenser contraction needs no change at all — embed+condense
    are position-wise, and pad positions carry id 0, which the masked
    one-hot embeds to the zero vector.

One pack stream, one compiled forward: every pack has the same
[n_slots, R, S] shape regardless of the width mix, so
``n_forward_shapes`` collapses to 1 and the per-bucket packer fleet
(and its starvation flush) disappears.

Semantics are defined by `reference_ragged_forward` (pure jnp, shares
the helpers below and fused_window_attention's embed/condense); the
kernel is validated against it in interpret mode on CPU at every
configured bucket width and at an overflow width
(tests/test_ragged_kernel.py). The byte-identity contract with the
bucketed engine is carried by the XLA model path (models/model.py
reshape-select routing), which this kernel mirrors numerically —
identical-shape reshaped compute is bitwise, masked-wide compute is
1-ulp-close (XLA reassociates reductions over different contraction
lengths), so kernel parity is asserted with tight allclose rather
than bitwise.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepconsensus_tpu.ops import fused_window_attention as fwa

Array = jnp.ndarray

_NEG = -1e9

# Slot-length ceiling for the whole-S score block ([tile, S, S] f32 in
# VMEM). Deliberately above FUSED_MAX_WINDOW_LEN: slots span the
# LARGEST bucket, and the score block at 256 is ~2 MB per tile — still
# comfortable next to the weights. Above this, callers stay bucketed.
RAGGED_MAX_SLOT_LEN = 256


def validate_ragged_buckets(buckets: Sequence[int]) -> Tuple[int, ...]:
  """Ragged packing needs a divisibility chain: each bucket must divide
  every larger bucket.

  Largest-first packing into a slot then guarantees every window
  starts at an offset that is a multiple of its own width, which is
  what lets the XLA byte-identity path recover each window as one
  contiguous reshape chunk (models/model.py) and keeps mixed
  compositions aligned for the kernel mask. The default (100, 200)
  chain satisfies this; an operator bucket spec that does not fails
  loudly here instead of silently corrupting window boundaries.
  """
  out = tuple(int(b) for b in buckets)
  if not out or any(b <= 0 for b in out):
    raise ValueError(f'ragged buckets must be positive ints, got {out}')
  if list(out) != sorted(set(out)):
    raise ValueError(f'ragged buckets must be strictly ascending, got {out}')
  for small, big in zip(out, out[1:]):
    if big % small:
      raise ValueError(
          f'ragged buckets must form a divisibility chain (each bucket '
          f'divides every larger one); {small} does not divide {big} '
          f'in {out}')
  return out


def windows_per_slot(buckets: Sequence[int]) -> int:
  """Max windows one slot can hold: slot_len // smallest bucket."""
  b = validate_ragged_buckets(buckets)
  return b[-1] // b[0]


def slot_geometry(lengths: Array, slot_len: int
                  ) -> Tuple[Array, Array, Array, Array]:
  """Per-position window geometry derived from per-slot window lengths.

  lengths: [B, wps] int32 — widths of the windows packed back-to-back
  into each slot in placement order (0 = unused capacity; zeros are
  trailing). Returns (seg, start, width, valid), each [B, slot_len]:
  the window ordinal owning each position, that window's start offset
  and width, and whether the position holds real window data.

  Built from static-shape iota/compare/where only (no gather, no
  cumsum primitive), so the same helper runs inside the Pallas kernel,
  the jnp reference, and the XLA model path.
  """
  lengths = lengths.astype(jnp.int32)
  b, wps = lengths.shape
  p = jax.lax.broadcasted_iota(jnp.int32, (b, slot_len), 1)
  seg = jnp.zeros((b, slot_len), jnp.int32)
  width = jnp.zeros((b, slot_len), jnp.int32)
  start = jnp.zeros((b, slot_len), jnp.int32)
  cur = jnp.zeros((b, 1), jnp.int32)
  for j in range(wps):
    w_j = lengths[:, j:j + 1]
    nxt = cur + w_j
    sel = (p >= cur) & (p < nxt)
    seg = jnp.where(sel, j, seg)
    width = jnp.where(sel, w_j, width)
    start = jnp.where(sel, cur, start)
    cur = nxt
  valid = p < cur
  return seg, start, width, valid


def ragged_attention_mask(lengths: Array, slot_len: int,
                          attn_win_size: Optional[int]) -> Array:
  """[B, S, S] bool attention mask for ragged slots: the static band
  AND same-window AND both-positions-valid. Within one window the
  absolute-position band equals the window-relative band (|i - j| is
  offset-invariant), so this is exactly the per-width band the
  bucketed path applies."""
  seg, _start, _width, valid = slot_geometry(lengths, slot_len)
  mask = (seg[:, :, None] == seg[:, None, :])
  mask = mask & valid[:, :, None] & valid[:, None, :]
  if attn_win_size is not None:
    b = lengths.shape[0]
    rows = jax.lax.broadcasted_iota(jnp.int32, (b, slot_len, slot_len), 1)
    cols = jax.lax.broadcasted_iota(jnp.int32, (b, slot_len, slot_len), 2)
    mask = mask & (jnp.abs(rows - cols) <= attn_win_size)
  return mask


def _pos_contribution(start: Array, valid: Array, pos: Array) -> Array:
  """Per-position sinusoidal encoding pos[p - start(p)] as a one-hot
  matmul (MXU-friendly and exact: one 1 per row, so the accumulation
  has a single non-zero term). Invalid positions contribute zero."""
  b, slot_len = start.shape
  pos_len = pos.shape[0]
  p = jax.lax.broadcasted_iota(jnp.int32, (b, slot_len), 1)
  off = jnp.clip(p - start, 0, pos_len - 1)
  k = jax.lax.broadcasted_iota(jnp.int32, (b, slot_len, pos_len), 2)
  onehot = ((off[:, :, None] == k) & valid[:, :, None]).astype(jnp.float32)
  return jax.lax.dot_general(
      onehot.reshape(b * slot_len, pos_len), pos.astype(jnp.float32),
      (((1,), (0,)), ((), ())),
      preferred_element_type=jnp.float32,
  ).reshape(b, slot_len, pos.shape[1])


def _ragged_attention(x, mask, wq, wk, wv, wo, *, num_heads, qscale,
                      slot_len, softmax_dtype):
  """Banded MHA on a [tile, S, H] f32 block with a precomputed ragged
  mask; mirrors fused_window_attention._attention's op order (batch-
  major projections, per-head softmax in softmax_dtype, output
  projection) with the band test swapped for the lengths-derived
  mask. Shared between the kernel and the jnp reference."""
  tile, _, hidden = x.shape
  head_dim = hidden // num_heads
  x2 = x.reshape(tile * slot_len, hidden)

  def proj(w):
    return jax.lax.dot_general(
        x2, w.astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).reshape(tile, slot_len, num_heads, head_dim)

  q = proj(wq) * qscale
  k = proj(wk)
  v = proj(wv)
  outs = []
  for h in range(num_heads):
    s = jax.lax.dot_general(
        q[:, :, h, :], k[:, :, h, :], (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )  # [tile, S, S]
    s = jnp.where(mask, s, _NEG)
    sd = s.astype(softmax_dtype)
    m = jnp.max(sd, axis=2, keepdims=True)
    p = jnp.exp(sd - m)
    w = (p / jnp.sum(p, axis=2, keepdims=True)).astype(jnp.float32)
    outs.append(jax.lax.dot_general(
        w, v[:, :, h, :], (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ))
  o = jnp.concatenate(outs, axis=-1).reshape(tile * slot_len, hidden)
  out = jax.lax.dot_general(
      o, wo.astype(jnp.float32), (((1,), (0,)), ((), ())),
      preferred_element_type=jnp.float32,
  )
  return out.reshape(tile, slot_len, hidden)


def _kernel(*refs, specs, n_tables, num_heads, qscale, attn_win_size,
            slot_len, hidden, softmax_dtype):
  ids_ref = refs[0]
  lengths_ref = refs[1]
  table_refs = refs[2:2 + n_tables]
  w_cond_ref, wq_ref, wk_ref, wv_ref, wo_ref, pos_ref = refs[
      2 + n_tables:8 + n_tables]
  xbase_ref, attn_ref = refs[8 + n_tables:10 + n_tables]

  tile = ids_ref.shape[0]
  ids = ids_ref[:]
  lengths = lengths_ref[:]
  table_vals = [t[:] for t in table_refs]
  w_cond = w_cond_ref[:].astype(jnp.float32)
  _seg, start, _width, valid = slot_geometry(lengths, slot_len)
  mask = ragged_attention_mask(lengths, slot_len, attn_win_size)
  x = fwa._embed_condense(
      ids, table_vals, w_cond, specs, tile, slot_len, hidden)
  x = x + _pos_contribution(start, valid, pos_ref[:])
  xbase_ref[:] = x.astype(xbase_ref.dtype)
  out = _ragged_attention(
      x, mask, wq_ref[:], wk_ref[:], wv_ref[:], wo_ref[:],
      num_heads=num_heads, qscale=qscale, slot_len=slot_len,
      softmax_dtype=softmax_dtype,
  )
  attn_ref[:] = out.astype(attn_ref.dtype)


def ragged_embed_condense_attention(
    rows: Array,
    lengths: Array,
    tables: Dict[str, Array],
    w_cond: Array,
    wq: Array,
    wk: Array,
    wv: Array,
    wo: Array,
    pos: Optional[Array],
    *,
    specs: Tuple[fwa.FamilySpec, ...],
    table_keys: Tuple[str, ...],
    num_heads: int,
    attn_win_size: Optional[int],
    softmax_dtype: Any = jnp.float32,
    compute_dtype: Any = jnp.float32,
    tile_windows: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Tuple[Array, Array]:
  """Fused embed->condense->pos->layer-0 attention over ragged slots.

  rows: [B, R, S] raw pileup rows with mixed-width windows packed
  back-to-back per slot (pad positions zero). lengths: [B, wps] int32
  per-slot window widths. Weight arguments match
  fused_window_attention.fused_embed_condense_attention; pos is the
  [S, H] sinusoidal table indexed per position by window offset.

  Returns (x_base, attn_out), both [B, S, H] in compute_dtype; the
  caller applies the ReZero residual outside, same split as the
  bucketed kernel.
  """
  from deepconsensus_tpu.ops import pallas_util

  b, r, slot_len = rows.shape
  if slot_len > RAGGED_MAX_SLOT_LEN:
    raise ValueError(
        f'ragged slot length {slot_len} exceeds RAGGED_MAX_SLOT_LEN '
        f'{RAGGED_MAX_SLOT_LEN}')
  hidden = w_cond.shape[1]
  head_dim = hidden // num_heads
  cond_in = sum(s.n_rows * s.width for s in specs)
  if cond_in != w_cond.shape[0]:
    raise ValueError(
        f'condenser expects {w_cond.shape[0]} input features, family '
        f'specs cover {cond_in}; config and weights disagree')
  if hidden % num_heads:
    raise ValueError('hidden size must divide num_heads')

  tile = tile_windows or fwa.DEFAULT_TILE_WINDOWS
  tile = max(1, min(tile, b))
  ids = fwa.prepare_ids(rows, specs)
  lengths = jnp.asarray(lengths, jnp.int32)
  pad = (-b) % tile
  if pad:
    # Zero lengths mark every position of a padded slot invalid; zero
    # ids embed to zero vectors. Padded slots are sliced away.
    ids = jnp.pad(ids, ((0, pad), (0, 0), (0, 0)))
    lengths = jnp.pad(lengths, ((0, pad), (0, 0)))
  n_tiles = (b + pad) // tile
  wps = lengths.shape[1]

  # dclint: allow=dtype-downcast (kernel inputs follow the configured
  # compute dtype; bf16 here is the inference_dtype lever, not a leak)
  cast = lambda a: jnp.asarray(a, compute_dtype)
  table_in = [
      # dclint: allow=dtype-downcast (sqrt(width) embed scale folded at
      # compute dtype, same fold as the bucketed kernel)
      cast(tables[key]) * jnp.asarray(
          next(s.width for s in specs if s.table_idx == i) ** 0.5,
          compute_dtype)
      for i, key in enumerate(table_keys)
  ]
  if pos is None:
    pos = jnp.zeros((slot_len, hidden), compute_dtype)

  full = lambda a: pl.BlockSpec(
      a.shape, lambda i: (0,) * a.ndim, memory_space=pltpu.VMEM)
  ids_spec = pl.BlockSpec((tile, r, slot_len), lambda i: (i, 0, 0),
                          memory_space=pltpu.VMEM)
  lengths_spec = pl.BlockSpec((tile, wps), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)
  out_spec = pl.BlockSpec((tile, slot_len, hidden), lambda i: (i, 0, 0),
                          memory_space=pltpu.VMEM)
  inputs = [ids, lengths, *table_in, cast(w_cond), cast(wq), cast(wk),
            cast(wv), cast(wo), cast(pos)]
  x_base, attn_out = pl.pallas_call(
      functools.partial(
          _kernel, specs=specs, n_tables=len(table_keys),
          num_heads=num_heads, qscale=head_dim ** -0.5,
          attn_win_size=attn_win_size, slot_len=slot_len, hidden=hidden,
          softmax_dtype=jnp.dtype(softmax_dtype),
      ),
      grid=(n_tiles,),
      in_specs=[ids_spec, lengths_spec] + [full(a) for a in inputs[2:]],
      out_specs=[out_spec, out_spec],
      out_shape=[
          jax.ShapeDtypeStruct((b + pad, slot_len, hidden), compute_dtype),
          jax.ShapeDtypeStruct((b + pad, slot_len, hidden), compute_dtype),
      ],
      interpret=pallas_util.resolve_interpret(interpret),
  )(*inputs)
  return x_base[:b], attn_out[:b]


def reference_ragged_forward(
    rows: Array,
    lengths: Array,
    tables: Dict[str, Array],
    w_cond: Array,
    wq: Array,
    wk: Array,
    wv: Array,
    wo: Array,
    pos: Optional[Array],
    *,
    specs: Tuple[fwa.FamilySpec, ...],
    table_keys: Tuple[str, ...],
    num_heads: int,
    attn_win_size: Optional[int],
    softmax_dtype: Any = jnp.float32,
) -> Tuple[Array, Array]:
  """Pure-jnp semantics of the ragged kernel (same helpers, no
  Pallas): the interpret-mode parity oracle for unit tests."""
  b, _, slot_len = rows.shape
  hidden = w_cond.shape[1]
  head_dim = hidden // num_heads
  ids = fwa.prepare_ids(rows, specs)
  lengths = jnp.asarray(lengths, jnp.int32)
  table_vals = [
      tables[key].astype(jnp.float32) * (
          next(s.width for s in specs if s.table_idx == i) ** 0.5)
      for i, key in enumerate(table_keys)
  ]
  _seg, start, _width, valid = slot_geometry(lengths, slot_len)
  mask = ragged_attention_mask(lengths, slot_len, attn_win_size)
  x = fwa._embed_condense(ids, table_vals, w_cond.astype(jnp.float32),
                          specs, b, slot_len, hidden)
  if pos is not None:
    x = x + _pos_contribution(start, valid, pos)
  out = _ragged_attention(
      x, mask, wq, wk, wv, wo, num_heads=num_heads,
      qscale=head_dim ** -0.5, slot_len=slot_len,
      softmax_dtype=jnp.dtype(softmax_dtype),
  )
  return x, out
