"""Anti-diagonal (wavefront) dynamic programming primitives.

The alignment loss and metric both run edit-distance-style DPs. On TPU
the natural formulation is a `lax.scan` over anti-diagonals: each scan
step updates a full diagonal vector at once, so the DP parallelizes
across the batch and the diagonal dimension with static shapes
(reference formulation: deepconsensus/models/losses_and_metrics.py:
210-260,346-411; here re-expressed with gather-based wavefrontification
and scan instead of Python-level tf loops).

Conventions: y_true has length m (padded), y_pred length n; DP matrices
are [m+1, n+1]; anti-diagonal k holds cells (i, k-i).
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

Array = jnp.ndarray

# Scan unroll factor. Measured on TPU v5e at batch 256: unroll=4 makes
# the differentiated loss scan ~5x faster per step, but inflates the
# full train-step XLA compile from ~4 min to >9 min on this stack, so
# the default stays 1. Set DC_TPU_SCAN_UNROLL for long production runs
# where the persistent compilation cache
# (train.enable_compilation_cache) amortizes the one-time cost.
import os as _os

SCAN_UNROLL = int(_os.environ.get('DC_TPU_SCAN_UNROLL', '1'))


def wavefrontify(t: Array) -> Array:
  """[B, m, n] -> [m+n-1, B, m] with out[k, b, i] = t[b, i, k-i].

  Out-of-range entries are 0.
  """
  b, m, n = t.shape
  k = jnp.arange(m + n - 1)
  i = jnp.arange(m)
  j = k[:, None] - i[None, :]  # [K, m]
  valid = (j >= 0) & (j < n)
  jc = jnp.clip(j, 0, n - 1)
  # gathered[b, k, i] = t[b, i, jc[k, i]]
  gathered = t[:, i[None, :], jc]  # [B, K, m]
  gathered = jnp.where(valid[None], gathered, 0)
  return jnp.transpose(gathered, (1, 0, 2))


def wavefrontify_vec(v: Array, len1: int) -> Array:
  """[B, n] -> [len1+n-1, B, len1] with out[k, b, i] = v[b, k-i]."""
  b, n = v.shape
  k = jnp.arange(len1 + n - 1)
  i = jnp.arange(len1)
  j = k[:, None] - i[None, :]
  valid = (j >= 0) & (j < n)
  jc = jnp.clip(j, 0, n - 1)
  gathered = v[:, jc]  # [B, K, len1]
  gathered = jnp.where(valid[None], gathered, 0)
  return jnp.transpose(gathered, (1, 0, 2))


def alignment_scan(
    subs_costs: Array,
    ins_costs: Array,
    del_cost: Array,
    seq_lens: Array,
    minop: Callable[[Array], Array],
    inf: float = 1e9,
) -> Array:
  """Single-state edit DP over anti-diagonals (alignment loss core).

  Args:
    subs_costs: [B, m, n] substitution costs.
    ins_costs: [B, n] insertion costs (consuming a predicted base).
    del_cost: scalar cost of deleting a true base.
    seq_lens: [B] true sequence lengths (excluding padding).
    minop: soft or hard minimum over the leading axis of a [3, ...] stack.
    inf: large positive float.

  Returns:
    [B] alignment scores, evaluated at cell (seq_lens[b], n).
  """
  batch, m, n = subs_costs.shape
  subs_w = wavefrontify(subs_costs)  # [m+n-1, B, m]
  ins_w = wavefrontify_vec(ins_costs, m + 1)  # [m+n, B, m+1]

  i_range = jnp.arange(m + 1)
  k_end = seq_lens + n

  v_p2 = jnp.full((batch, m), inf).at[:, 0].set(0.0)
  v_p1 = jnp.concatenate(
      [
          ins_w[0][:, :1],
          jnp.full((batch, 1), del_cost),
          jnp.full((batch, m - 1), inf),
      ],
      axis=1,
  )
  v_opt = jnp.full((batch,), inf)

  ks = jnp.arange(2, m + n + 1)

  def step(carry, xs):
    v_p2, v_p1, v_opt = carry
    k, subs_k, ins_k = xs  # subs_k: [B, m], ins_k: [B, m+1]
    j_range = k - i_range
    valid = (j_range >= 0) & (j_range <= n)  # [m+1]

    o_m = v_p2 + subs_k
    o_i = v_p1 + ins_k
    v_p2_next = v_p1[:, :-1]
    o_d = v_p2_next + del_cost

    body = minop(jnp.stack([o_m, o_i[:, 1:], o_d]))  # [B, m]
    v_new = jnp.concatenate([o_i[:, :1], body], axis=1)
    v_new = jnp.where(valid[None, :], v_new, inf)
    v_at_len = jnp.take_along_axis(v_new, seq_lens[:, None], axis=1)[:, 0]
    v_opt = jnp.where(k_end == k, v_at_len, v_opt)
    return (v_p2_next, v_new, v_opt), None

  (_, _, v_opt), _ = jax.lax.scan(
      step, (v_p2, v_p1, v_opt), (ks, subs_w, ins_w[1:]),
      unroll=SCAN_UNROLL,
  )
  return v_opt


def banded_alignment_scan(
    subs_costs: Array,
    ins_costs: Array,
    del_cost: Array,
    seq_lens: Array,
    width: int,
    minop: Callable[[Array], Array],
    inf: float = 1e9,
) -> Array:
  """Band-restricted edit DP in (anti-diagonal, offset) coordinates.

  Replicates the reference's woven-band recursion
  (losses_and_metrics.py:413-547) without materializing the woven
  tensors. Cell (x, y) — x true bases consumed, y predicted bases
  consumed — lives at band[k=x+y, d=y-x+width] (the weave_band example
  and index_ending_band agree on d=y-x+width; the docstring formula in
  the reference contradicts its own example). Moves into (x, y):
  diagonal subs[x-1, y-1], deletion from (x-1, y) at del_cost, and
  insertion from (x, y-1) at ins[y-1]. Evaluation fetches
  (seq_lens, min(n, seq_lens + width)): trailing predicted positions
  outside the band are never charged. Requires square inputs (m == n),
  which holds for fixed-length windows.
  """
  batch, m, n = subs_costs.shape
  if m != n:
    raise ValueError('banded alignment requires m == n')
  n_diag = 2 * width + 1
  length = m + 1  # DP matrix side

  d = jnp.arange(n_diag)

  # k=0: only cell (0, 0) -> value 0.
  band_p2 = jnp.where((d == width)[None], 0.0, jnp.full((batch, n_diag), inf))
  # k=1: cells (1, 0) [d=width-1] and (0, 1) [d=width+1], taken from
  # the reference's boundary init (V[x, 0] = x*del, V[0, y] = cum-ins).
  band_p1 = jnp.full((batch, n_diag), inf)
  if width >= 1:
    band_p1 = band_p1.at[:, width - 1].set(del_cost)
    band_p1 = band_p1.at[:, width + 1].set(ins_costs[:, 0])

  # Cell coordinates for band slot (k, d): 2x = k - d + width,
  # 2y = k + d - width; odd parity slots hold no cell.
  def subs_at(k):
    x2 = k - d + width
    y2 = k + d - width
    valid = (x2 % 2 == 0) & (x2 >= 2) & (y2 >= 2) & (x2 <= 2 * m) & (
        y2 <= 2 * n
    )
    xi = jnp.clip(x2 // 2 - 1, 0, m - 1)
    yi = jnp.clip(y2 // 2 - 1, 0, n - 1)
    vals = subs_costs[:, xi, yi]  # [B, n_diag]
    return jnp.where(valid[None], vals, inf)

  def ins_at(k):
    # Insertion into (x, y) consumes predicted base y at ins[y-1]
    # (ins_pad[0] = 0 per the reference's padded column).
    x2 = k - d + width
    y2 = k + d - width
    valid = (x2 % 2 == 0) & (x2 >= 0) & (y2 >= 0)
    y = jnp.clip(y2 // 2, 0, n)
    ins_pad = jnp.concatenate([jnp.zeros((batch, 1)), ins_costs], axis=1)
    vals = ins_pad[:, y]
    return jnp.where(valid[None], vals, inf)

  ks = jnp.arange(2, 2 * length - 1)

  def step(carry, k):
    band_p2, band_p1 = carry
    o_m = band_p2 + subs_at(k)
    shifted_up = jnp.concatenate(
        [band_p1[:, 1:], jnp.full((batch, 1), inf)], axis=1
    )
    o_d = shifted_up + del_cost
    shifted_down = jnp.concatenate(
        [jnp.full((batch, 1), inf), band_p1[:, :-1]], axis=1
    )
    o_i = shifted_down + ins_at(k)
    new = minop(jnp.stack([o_m, o_d, o_i]))
    return (band_p1, new), new

  (_, _), rows = jax.lax.scan(
      step, (band_p2, band_p1), ks, unroll=SCAN_UNROLL
  )
  # rows: [2*length-3, B, n_diag] for k = 2..2*length-2.
  all_rows = jnp.concatenate(
      [band_p2[None], band_p1[None], rows], axis=0
  )  # [2*length-1, B, n_diag]

  # Fetch (x, y) = (seq_lens, min(n, seq_lens + width))
  # (reference index_ending_band: losses_and_metrics.py:458-473).
  x_end = seq_lens
  y_end = jnp.minimum(n, seq_lens + width)
  k_end = x_end + y_end
  d_end = y_end - x_end + width
  return all_rows[k_end, jnp.arange(batch), d_end]
