"""Pallas TPU kernel: block-banded flash attention for long windows.

The short-window kernel (ops/banded_attention.py) holds the full
[G, L, L] logits in VMEM, which is ideal at the pileup default L=100
but caps out near L~512 and wastes MXU work on masked-out tiles. This
kernel makes the band structural instead: the grid walks
(batch*head groups, query blocks, key blocks *within the band*), so
compute and VMEM scale with L*band instead of L^2. Keys/values are
zero-padded by one block on each side so the banded index map never
clamps (out-of-range tiles are killed by the mask, never revisited),
and the online-softmax state (row max, row sum, output accumulator)
lives in VMEM scratch across the sequential key-block axis.

Semantics match ops/banded_attention.reference_banded_attention (the
reference's band_part mask + softmax: attention_layer.py:112-120,207);
validated against it in interpret mode and, at L=100, against the
short-window kernel. Forward-only by design: the flagship training
window is L=100 where the short-window VJP kernels already train; this
kernel serves long-window inference and composes with
parallel/ring_attention.py for cross-device sequence parallelism.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepconsensus_tpu.ops import pallas_util

Array = jnp.ndarray

# jax >= 0.8 renamed TPUCompilerParams -> CompilerParams; accept either
# so the kernel builds across the versions this repo sees.
_CompilerParams = getattr(pltpu, 'CompilerParams', None) or pltpu.TPUCompilerParams

_NEG = -1e9

# Above this window length the whole-L kernel (banded_attention.py)
# stops being the right tool — its [G, L, L] VMEM block grows past
# what fits/compiles — and callers should switch to this kernel.
WHOLE_L_LIMIT = 128


def _kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
            attn_win_size, length, block_q, block_k, n_kblocks,
            w_blocks, lse_ref=None):
  j = pl.program_id(2)
  qi = pl.program_id(1)

  @pl.when(j == 0)
  def _init():
    m_ref[:] = jnp.full_like(m_ref, _NEG)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

  q = q_ref[:].astype(jnp.float32)  # [G, BQ, D]
  k = k_ref[:].astype(jnp.float32)  # [G, BK, D]
  s = jax.lax.dot_general(
      q, k, (((2,), (2,)), ((0,), (0,))),
      preferred_element_type=jnp.float32,
  )  # [G, BQ, BK]
  # Global coordinates: rows from the query block, cols from the key
  # block's position in the *unpadded* sequence (the padded array is
  # shifted right by w_blocks*block_k).
  rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
  if attn_win_size is None:
    col_start = j * block_k  # index map (g, j): plain key-block walk
  else:
    col_start = qi * block_q - w_blocks * block_k + j * block_k
  cols = col_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
  valid = (cols >= 0) & (cols < length)
  if attn_win_size is not None:
    valid = valid & (jnp.abs(rows - cols) <= attn_win_size)
  s = jnp.where(valid, s, _NEG)

  m_prev = m_ref[:]                      # [G, BQ]
  m_new = jnp.maximum(m_prev, jnp.max(s, axis=2))
  alpha = jnp.exp(m_prev - m_new)        # rescale of previous state
  p = jnp.exp(s - m_new[:, :, None])     # [G, BQ, BK]
  # Fully-masked tiles (all _NEG) must contribute exactly zero even
  # when the running max is still _NEG (exp(0)=1 otherwise).
  p = jnp.where(valid, p, 0.0)
  l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=2)
  acc_ref[:] = (
      acc_ref[:] * alpha[:, :, None]
      + jax.lax.dot_general(
          p, v_ref[:].astype(jnp.float32),
          (((2,), (1,)), ((0,), (0,))),
          preferred_element_type=jnp.float32,
      )
  )
  m_ref[:] = m_new

  @pl.when(j == n_kblocks - 1)
  def _finalize():
    denom = l_ref[:]
    denom = jnp.where(denom == 0.0, 1.0, denom)  # padded query rows
    o_ref[:] = (acc_ref[:] / denom[:, :, None]).astype(o_ref.dtype)
    if lse_ref is not None:
      # Safe logsumexp per row; fully-masked rows get 0 (their w in
      # the backward is forced to 0 by the same validity mask).
      lse_ref[:] = jnp.where(
          l_ref[:] == 0.0, 0.0, m_ref[:] + jnp.log(denom)
      )


class _Plan:
  """Shared blocking geometry for the forward and backward kernels."""

  def __init__(self, b, l, h, d, attn_win_size, block_q, group):
    self.l, self.d = l, d
    self.n = b * h
    self.group = min(group, self.n)
    while self.n % self.group:
      self.group -= 1
    self.block_q = min(block_q, _round_up(l, 128))
    self.block_k = self.block_q
    self.lq = _round_up(l, self.block_q)
    if attn_win_size is None:
      self.w_blocks = 0
      self.n_kblocks = self.lq // self.block_k
      self.pad = 0
    else:
      self.w_blocks = -(-attn_win_size // self.block_k)  # ceil
      self.n_kblocks = 2 * self.w_blocks + 1
      self.pad = self.w_blocks * self.block_k

  def to_blocks(self, x, pad_lo, pad_hi):
    x = jnp.transpose(x, (0, 2, 1, 3)).reshape(self.n, self.l, self.d)
    return jnp.pad(x, ((0, 0), (pad_lo, pad_hi), (0, 0)))

  def from_blocks(self, x, b, h):
    x = x[:, : self.l]
    return jnp.transpose(x.reshape(b, h, self.l, self.d), (0, 2, 1, 3))

  def spec(self, index_map, block_len=None, rank2=False):
    block_len = block_len or self.block_q
    if rank2:
      return pl.BlockSpec((self.group, block_len), index_map,
                          memory_space=pltpu.VMEM)
    return pl.BlockSpec((self.group, block_len, self.d), index_map,
                        memory_space=pltpu.VMEM)


def _forward(q, k, v, attn_win_size, interpret, emit_lse):
  b, l, h, d = q.shape
  plan = _Plan(b, l, h, d, attn_win_size, 128, 8)
  qb = plan.to_blocks(q, 0, plan.lq - l)
  # Keys/values get w_blocks blocks of zeros each side so the banded
  # index map stays in range for every (qi, j); the mask kills them.
  kv_hi = (plan.lq - l) + plan.pad
  kb = plan.to_blocks(k, plan.pad, kv_hi)
  vb = plan.to_blocks(v, plan.pad, kv_hi)

  q_spec = plan.spec(lambda g, i, j: (g, i, 0))
  if attn_win_size is None:
    kv_index = lambda g, i, j: (g, j, 0)
  else:
    # Padded block 0 sits w_blocks blocks left of query block 0.
    kv_index = lambda g, i, j: (g, i + j, 0)
  kv_spec = plan.spec(kv_index)
  kwargs = dict(
      attn_win_size=attn_win_size, length=l, block_q=plan.block_q,
      block_k=plan.block_k, n_kblocks=plan.n_kblocks,
      w_blocks=plan.w_blocks,
  )
  if emit_lse:
    kernel = functools.partial(_kernel_with_lse, **kwargs)
    out_shape = [
        jax.ShapeDtypeStruct((plan.n, plan.lq, d), q.dtype),
        jax.ShapeDtypeStruct((plan.n, plan.lq), jnp.float32),
    ]
    out_specs = [q_spec, plan.spec(lambda g, i, j: (g, i), rank2=True)]
  else:
    kernel = functools.partial(_kernel, **kwargs)
    out_shape = jax.ShapeDtypeStruct((plan.n, plan.lq, d), q.dtype)
    out_specs = q_spec
  result = pl.pallas_call(
      kernel,
      grid=(plan.n // plan.group, plan.lq // plan.block_q,
            plan.n_kblocks),
      in_specs=[q_spec, kv_spec, kv_spec],
      out_specs=out_specs,
      out_shape=out_shape,
      scratch_shapes=[
          pltpu.VMEM((plan.group, plan.block_q), jnp.float32),
          pltpu.VMEM((plan.group, plan.block_q), jnp.float32),
          pltpu.VMEM((plan.group, plan.block_q, d), jnp.float32),
      ],
      compiler_params=_CompilerParams(
          dimension_semantics=('parallel', 'parallel', 'arbitrary'),
      ),
      interpret=pallas_util.resolve_interpret(interpret),
  )(qb, kb, vb)
  if emit_lse:
    out, lse = result
    return plan.from_blocks(out, b, h), lse
  return plan.from_blocks(result, b, h)


def _kernel_with_lse(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref,
                     acc_ref, **kwargs):
  _kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
          lse_ref=lse_ref, **kwargs)


def flash_band_attention(
    q: Array,
    k: Array,
    v: Array,
    attn_win_size: Optional[int],
    interpret: Optional[bool] = None,
) -> Array:
  """Banded flash attention. q,k,v: [B, L, H, D], q pre-scaled.

  attn_win_size None means full (unbanded) attention; the key-block
  loop then covers the whole sequence.
  """
  return _forward(q, k, v, attn_win_size, interpret, emit_lse=False)


def _round_up(x: int, m: int) -> int:
  return -(-x // m) * m


def _recompute_w(q, k, lse, rows, cols, attn_win_size, length):
  """Softmax weights for one (q-block, k-block) tile from the saved
  row logsumexp; fully-masked positions get exactly 0."""
  s = jax.lax.dot_general(
      q, k, (((2,), (2,)), ((0,), (0,))),
      preferred_element_type=jnp.float32,
  )
  valid = (cols >= 0) & (cols < length) & (rows < length)
  if attn_win_size is not None:
    valid = valid & (jnp.abs(rows - cols) <= attn_win_size)
  return jnp.where(valid, jnp.exp(s - lse[:, :, None]), 0.0)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, acc_ref, *, attn_win_size, length, block_q,
                   block_k, n_kblocks, w_blocks):
  j = pl.program_id(2)
  qi = pl.program_id(1)

  @pl.when(j == 0)
  def _init():
    acc_ref[:] = jnp.zeros_like(acc_ref)

  q = q_ref[:].astype(jnp.float32)
  k = k_ref[:].astype(jnp.float32)
  rows = qi * block_q + jax.lax.broadcasted_iota(
      jnp.int32, (q.shape[0], block_q, block_k), 1)
  if attn_win_size is None:
    col_start = j * block_k
  else:
    col_start = qi * block_q - w_blocks * block_k + j * block_k
  cols = col_start + jax.lax.broadcasted_iota(
      jnp.int32, (q.shape[0], block_q, block_k), 2)
  w = _recompute_w(q, k, lse_ref[:], rows, cols, attn_win_size, length)
  dw = jax.lax.dot_general(
      do_ref[:].astype(jnp.float32), v_ref[:].astype(jnp.float32),
      (((2,), (2,)), ((0,), (0,))),
      preferred_element_type=jnp.float32,
  )
  ds = w * (dw - delta_ref[:][:, :, None])
  acc_ref[:] += jax.lax.dot_general(
      ds, k, (((2,), (1,)), ((0,), (0,))),
      preferred_element_type=jnp.float32,
  )

  @pl.when(j == n_kblocks - 1)
  def _finalize():
    dq_ref[:] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, attn_win_size,
                    length, block_q, block_k, n_qblocks, w_blocks):
  jq = pl.program_id(2)
  ki = pl.program_id(1)

  @pl.when(jq == 0)
  def _init():
    dk_acc[:] = jnp.zeros_like(dk_acc)
    dv_acc[:] = jnp.zeros_like(dv_acc)

  q = q_ref[:].astype(jnp.float32)
  k = k_ref[:].astype(jnp.float32)
  do = do_ref[:].astype(jnp.float32)
  if attn_win_size is None:
    row_start = jq * block_q
  else:
    row_start = ki * block_k - w_blocks * block_q + jq * block_q
  g = q.shape[0]
  rows = row_start + jax.lax.broadcasted_iota(
      jnp.int32, (g, block_q, block_k), 1)
  cols = ki * block_k + jax.lax.broadcasted_iota(
      jnp.int32, (g, block_q, block_k), 2)
  w = _recompute_w(q, k, lse_ref[:], rows, cols, attn_win_size, length)
  dv_acc[:] += jax.lax.dot_general(
      w, do, (((1,), (1,)), ((0,), (0,))),
      preferred_element_type=jnp.float32,
  )
  dw = jax.lax.dot_general(
      do, v_ref[:].astype(jnp.float32),
      (((2,), (2,)), ((0,), (0,))),
      preferred_element_type=jnp.float32,
  )
  ds = w * (dw - delta_ref[:][:, :, None])
  dk_acc[:] += jax.lax.dot_general(
      ds, q, (((1,), (1,)), ((0,), (0,))),
      preferred_element_type=jnp.float32,
  )

  @pl.when(jq == n_qblocks - 1)
  def _finalize():
    dk_ref[:] = dk_acc[:].astype(dk_ref.dtype)
    dv_ref[:] = dv_acc[:].astype(dv_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_band_attention_vjp(q, k, v, attn_win_size, interpret=None):
  """Differentiable banded flash attention (same semantics as
  flash_band_attention; flash-attention-style backward: the forward
  saves the per-row logsumexp, the backward recomputes weight tiles
  and accumulates dq over key blocks and dk/dv over the query blocks
  whose band reaches each key block)."""
  return _forward(q, k, v, attn_win_size, interpret, emit_lse=False)


def _vjp_fwd(q, k, v, attn_win_size, interpret):
  out, lse = _forward(q, k, v, attn_win_size, interpret, emit_lse=True)
  return out, (q, k, v, out, lse)


def _vjp_bwd(attn_win_size, interpret, res, do):
  q, k, v, out, lse = res
  b, l, h, d = q.shape
  plan = _Plan(b, l, h, d, attn_win_size, 128, 8)
  interp = pallas_util.resolve_interpret(interpret)
  pad, lq = plan.pad, plan.lq

  # delta[f] = sum_d do[f, d] * out[f, d], rows beyond l are dead.
  delta = jnp.sum(
      do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
  )  # [B, L, H]
  delta_b = jnp.pad(
      jnp.transpose(delta, (0, 2, 1)).reshape(plan.n, l),
      ((0, 0), (0, lq - l)),
  )
  lse_b = lse  # already [n, lq] from the forward

  qb = plan.to_blocks(q, 0, lq - l)
  dob = plan.to_blocks(do, 0, lq - l)
  kv_hi = (lq - l) + pad
  kb = plan.to_blocks(k, pad, kv_hi)
  vb = plan.to_blocks(v, pad, kv_hi)

  q_spec = plan.spec(lambda g, i, j: (g, i, 0))
  if attn_win_size is None:
    kv_index = lambda g, i, j: (g, j, 0)
  else:
    kv_index = lambda g, i, j: (g, i + j, 0)
  kv_spec = plan.spec(kv_index)
  rank2_q = plan.spec(lambda g, i, j: (g, i), rank2=True)
  dq = pl.pallas_call(
      functools.partial(
          _bwd_dq_kernel, attn_win_size=attn_win_size, length=l,
          block_q=plan.block_q, block_k=plan.block_k,
          n_kblocks=plan.n_kblocks, w_blocks=plan.w_blocks,
      ),
      grid=(plan.n // plan.group, lq // plan.block_q, plan.n_kblocks),
      in_specs=[q_spec, kv_spec, kv_spec, q_spec, rank2_q, rank2_q],
      out_specs=q_spec,
      out_shape=jax.ShapeDtypeStruct((plan.n, lq, d), q.dtype),
      scratch_shapes=[pltpu.VMEM((plan.group, plan.block_q, d),
                                 jnp.float32)],
      compiler_params=_CompilerParams(
          dimension_semantics=('parallel', 'parallel', 'arbitrary'),
      ),
      interpret=interp,
  )(qb, kb, vb, dob, lse_b, delta_b)

  # dk/dv: key block ki attends from query blocks ki-w..ki+w, so pad
  # the query-side arrays by w_blocks blocks on each side (mirror of
  # the forward's key-side padding).
  if attn_win_size is None:
    n_qblocks = lq // plan.block_q
    q_pad_lo = 0
    qk_index = lambda g, i, j: (g, j, 0)
    qk_index2 = lambda g, i, j: (g, j)
  else:
    n_qblocks = 2 * plan.w_blocks + 1
    q_pad_lo = pad
    qk_index = lambda g, i, j: (g, i + j, 0)
    qk_index2 = lambda g, i, j: (g, i + j)
  q_hi = (lq - l) + q_pad_lo
  qb2 = plan.to_blocks(q, q_pad_lo, q_hi)
  dob2 = plan.to_blocks(do, q_pad_lo, q_hi)
  kb2 = plan.to_blocks(k, 0, lq - l)
  vb2 = plan.to_blocks(v, 0, lq - l)
  # Mirror qb2/dob2's two-sided padding so every (g, i+j) block index
  # is in range: lse_b/delta_b are already lq wide (high-padded by
  # lq-l), so add q_pad_lo on both sides rather than relying on
  # Pallas' OOB block clamping for the trailing masked tiles.
  pad2 = ((0, 0), (q_pad_lo, q_pad_lo))
  lse2 = jnp.pad(lse_b, pad2)
  delta2 = jnp.pad(delta_b, pad2)

  k_spec = plan.spec(lambda g, i, j: (g, i, 0), block_len=plan.block_k)
  qd_spec = plan.spec(qk_index)
  rank2_spec = plan.spec(qk_index2, rank2=True)
  dk, dv = pl.pallas_call(
      functools.partial(
          _bwd_dkv_kernel, attn_win_size=attn_win_size, length=l,
          block_q=plan.block_q, block_k=plan.block_k,
          n_qblocks=n_qblocks, w_blocks=plan.w_blocks,
      ),
      grid=(plan.n // plan.group, lq // plan.block_k, n_qblocks),
      in_specs=[qd_spec, k_spec, k_spec, qd_spec, rank2_spec,
                rank2_spec],
      out_specs=[k_spec, k_spec],
      out_shape=[jax.ShapeDtypeStruct((plan.n, lq, d), q.dtype)] * 2,
      scratch_shapes=[
          pltpu.VMEM((plan.group, plan.block_k, d), jnp.float32),
          pltpu.VMEM((plan.group, plan.block_k, d), jnp.float32),
      ],
      compiler_params=_CompilerParams(
          dimension_semantics=('parallel', 'parallel', 'arbitrary'),
      ),
      interpret=interp,
  )(qb2, kb2, vb2, dob2, lse2, delta2)

  return (plan.from_blocks(dq, b, h), plan.from_blocks(dk, b, h),
          plan.from_blocks(dv, b, h))


flash_band_attention_vjp.defvjp(_vjp_fwd, _vjp_bwd)
