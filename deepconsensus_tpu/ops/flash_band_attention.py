"""Pallas TPU kernel: block-banded flash attention for long windows.

The short-window kernel (ops/banded_attention.py) holds the full
[G, L, L] logits in VMEM, which is ideal at the pileup default L=100
but caps out near L~512 and wastes MXU work on masked-out tiles. This
kernel makes the band structural instead: the grid walks
(batch*head groups, query blocks, key blocks *within the band*), so
compute and VMEM scale with L*band instead of L^2. Keys/values are
zero-padded by one block on each side so the banded index map never
clamps (out-of-range tiles are killed by the mask, never revisited),
and the online-softmax state (row max, row sum, output accumulator)
lives in VMEM scratch across the sequential key-block axis.

Semantics match ops/banded_attention.reference_banded_attention (the
reference's band_part mask + softmax: attention_layer.py:112-120,207);
validated against it in interpret mode and, at L=100, against the
short-window kernel. Forward-only by design: the flagship training
window is L=100 where the short-window VJP kernels already train; this
kernel serves long-window inference and composes with
parallel/ring_attention.py for cross-device sequence parallelism.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepconsensus_tpu.ops import pallas_util

Array = jnp.ndarray

_NEG = -1e9

# Above this window length the whole-L kernel (banded_attention.py)
# stops being the right tool — its [G, L, L] VMEM block grows past
# what fits/compiles — and callers should switch to this kernel.
WHOLE_L_LIMIT = 128


def _kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
            attn_win_size, length, block_q, block_k, n_kblocks,
            w_blocks):
  j = pl.program_id(2)
  qi = pl.program_id(1)

  @pl.when(j == 0)
  def _init():
    m_ref[:] = jnp.full_like(m_ref, _NEG)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

  q = q_ref[:].astype(jnp.float32)  # [G, BQ, D]
  k = k_ref[:].astype(jnp.float32)  # [G, BK, D]
  s = jax.lax.dot_general(
      q, k, (((2,), (2,)), ((0,), (0,))),
      preferred_element_type=jnp.float32,
  )  # [G, BQ, BK]
  # Global coordinates: rows from the query block, cols from the key
  # block's position in the *unpadded* sequence (the padded array is
  # shifted right by w_blocks*block_k).
  rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
  if attn_win_size is None:
    col_start = j * block_k  # index map (g, j): plain key-block walk
  else:
    col_start = qi * block_q - w_blocks * block_k + j * block_k
  cols = col_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
  valid = (cols >= 0) & (cols < length)
  if attn_win_size is not None:
    valid = valid & (jnp.abs(rows - cols) <= attn_win_size)
  s = jnp.where(valid, s, _NEG)

  m_prev = m_ref[:]                      # [G, BQ]
  m_new = jnp.maximum(m_prev, jnp.max(s, axis=2))
  alpha = jnp.exp(m_prev - m_new)        # rescale of previous state
  p = jnp.exp(s - m_new[:, :, None])     # [G, BQ, BK]
  # Fully-masked tiles (all _NEG) must contribute exactly zero even
  # when the running max is still _NEG (exp(0)=1 otherwise).
  p = jnp.where(valid, p, 0.0)
  l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=2)
  acc_ref[:] = (
      acc_ref[:] * alpha[:, :, None]
      + jax.lax.dot_general(
          p, v_ref[:].astype(jnp.float32),
          (((2,), (1,)), ((0,), (0,))),
          preferred_element_type=jnp.float32,
      )
  )
  m_ref[:] = m_new

  @pl.when(j == n_kblocks - 1)
  def _finalize():
    denom = l_ref[:]
    denom = jnp.where(denom == 0.0, 1.0, denom)  # padded query rows
    o_ref[:] = (acc_ref[:] / denom[:, :, None]).astype(o_ref.dtype)


def flash_band_attention(
    q: Array,
    k: Array,
    v: Array,
    attn_win_size: Optional[int],
    interpret: Optional[bool] = None,
    block_q: int = 128,
    group: int = 8,
) -> Array:
  """Banded flash attention. q,k,v: [B, L, H, D], q pre-scaled.

  attn_win_size None means full (unbanded) attention; the key-block
  loop then covers the whole sequence.
  """
  b, l, h, d = q.shape
  n = b * h
  group = min(group, n)
  while n % group:
    group -= 1
  block_q = min(block_q, _round_up(l, 128))
  block_k = block_q
  lq = _round_up(l, block_q)

  if attn_win_size is None:
    w_blocks = 0
    n_kblocks = lq // block_k
    pad_lo = 0
  else:
    w_blocks = -(-attn_win_size // block_k)  # ceil
    n_kblocks = 2 * w_blocks + 1
    pad_lo = w_blocks * block_k

  def to_blocks(x, pad_seq_lo, pad_seq_hi):
    x = jnp.transpose(x, (0, 2, 1, 3)).reshape(n, l, d)
    return jnp.pad(x, ((0, 0), (pad_seq_lo, pad_seq_hi), (0, 0)))

  qb = to_blocks(q, 0, lq - l)
  # Keys/values get w_blocks blocks of zeros each side so the banded
  # index map stays in range for every (qi, j); the mask kills them.
  kv_hi = (lq - l) + pad_lo
  kb = to_blocks(k, pad_lo, kv_hi)
  vb = to_blocks(v, pad_lo, kv_hi)

  q_spec = pl.BlockSpec((group, block_q, d), lambda g, i, j: (g, i, 0),
                        memory_space=pltpu.VMEM)
  if attn_win_size is None:
    kv_index = lambda g, i, j: (g, j, 0)
  else:
    # Padded block 0 sits w_blocks blocks left of query block 0.
    kv_index = lambda g, i, j: (g, i + j, 0)
  kv_spec = pl.BlockSpec((group, block_k, d), kv_index,
                         memory_space=pltpu.VMEM)
  out = pl.pallas_call(
      functools.partial(
          _kernel, attn_win_size=attn_win_size, length=l,
          block_q=block_q, block_k=block_k, n_kblocks=n_kblocks,
          w_blocks=w_blocks,
      ),
      grid=(n // group, lq // block_q, n_kblocks),
      in_specs=[q_spec, kv_spec, kv_spec],
      out_specs=q_spec,
      out_shape=jax.ShapeDtypeStruct((n, lq, d), q.dtype),
      scratch_shapes=[
          pltpu.VMEM((group, block_q), jnp.float32),
          pltpu.VMEM((group, block_q), jnp.float32),
          pltpu.VMEM((group, block_q, d), jnp.float32),
      ],
      compiler_params=pltpu.CompilerParams(
          dimension_semantics=('parallel', 'parallel', 'arbitrary'),
      ),
      interpret=pallas_util.resolve_interpret(interpret),
  )(qb, kb, vb)
  out = out[:, :l]
  return jnp.transpose(out.reshape(b, h, l, d), (0, 2, 1, 3))


def _round_up(x: int, m: int) -> int:
  return -(-x // m) * m
