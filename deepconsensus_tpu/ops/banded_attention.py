"""Pallas TPU kernel: fused banded self-attention for pileup windows.

Fuses QK^T, the static band mask, the numerically-stable softmax, and
PV into one VMEM-resident kernel per (batch, head) program, eliminating
the intermediate [B, H, L, L] logits/weights round-trips through HBM
that the unfused path materializes. Window length (100) and head width
pad up to the 8x128 tile internally.

The jnp reference path (reference_banded_attention) defines the
semantics; the kernel is validated against it in interpret mode on CPU
and used on TPU when params.use_pallas_attention is set.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jnp.ndarray

_NEG = -1e9


def reference_banded_attention(
    q: Array, k: Array, v: Array, attn_win_size: Optional[int]
) -> Array:
  """Unfused semantics: q,k,v [B, L, H, D] (q pre-scaled) -> [B, L, H, D]."""
  logits = jnp.einsum('BTNH,BFNH->BNFT', k, q)
  length = q.shape[1]
  if attn_win_size is not None:
    i = jnp.arange(length)
    band = jnp.abs(i[:, None] - i[None, :]) <= attn_win_size
    logits = jnp.where(band[None, None], logits, _NEG)
  weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(
      q.dtype
  )
  return jnp.einsum('BNFT,BTNH->BFNH', weights, v)


def _kernel(q_ref, k_ref, v_ref, o_ref, *, attn_win_size, length):
  # Blocks are [G, L, D]: G (batch*head) pairs per program.
  q = q_ref[:].astype(jnp.float32)
  k = k_ref[:].astype(jnp.float32)
  v = v_ref[:].astype(jnp.float32)
  s = jax.lax.dot_general(
      q, k, (((2,), (2,)), ((0,), (0,))),
      preferred_element_type=jnp.float32,
  )  # [G, L, L]
  rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
  cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
  valid = cols < length
  if attn_win_size is not None:
    valid = valid & (jnp.abs(rows - cols) <= attn_win_size)
  s = jnp.where(valid, s, _NEG)
  m = jnp.max(s, axis=2, keepdims=True)
  p = jnp.exp(s - m)
  denom = jnp.sum(p, axis=2, keepdims=True)
  o = jax.lax.dot_general(
      p, v, (((2,), (1,)), ((0,), (0,))),
      preferred_element_type=jnp.float32,
  )
  o_ref[:] = (o / denom).astype(o_ref.dtype)


def banded_attention(
    q: Array,
    k: Array,
    v: Array,
    attn_win_size: Optional[int],
    interpret: bool = False,
    group: int = 16,
) -> Array:
  """Fused banded attention. q,k,v: [B, L, H, D], q pre-scaled."""
  b, l, h, d = q.shape
  n = b * h
  group = min(group, n)
  while n % group:
    group -= 1

  # [B, L, H, D] -> [B*H, L, D] program blocks.
  def to_blocks(x):
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(n, l, d)

  qb, kb, vb = to_blocks(q), to_blocks(k), to_blocks(v)
  spec = pl.BlockSpec((group, l, d), lambda i: (i, 0, 0),
                      memory_space=pltpu.VMEM)
  out = pl.pallas_call(
      functools.partial(_kernel, attn_win_size=attn_win_size, length=l),
      grid=(n // group,),
      in_specs=[spec, spec, spec],
      out_specs=spec,
      out_shape=jax.ShapeDtypeStruct((n, l, d), q.dtype),
      interpret=interpret,
  )(qb, kb, vb)
  return jnp.transpose(out.reshape(b, h, l, d), (0, 2, 1, 3))
