"""Pallas TPU kernel: fused banded self-attention for pileup windows.

Fuses QK^T, the static band mask, the numerically-stable softmax, and
PV into one VMEM-resident kernel per (batch, head) program, eliminating
the intermediate [B, H, L, L] logits/weights round-trips through HBM
that the unfused path materializes. Window length (100) and head width
pad up to the 8x128 tile internally.

The jnp reference path (reference_banded_attention) defines the
semantics; the kernel is validated against it in interpret mode on CPU
and used on TPU when params.use_pallas_attention is set.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepconsensus_tpu.ops import pallas_util

Array = jnp.ndarray

_NEG = -1e9


def reference_banded_attention(
    q: Array, k: Array, v: Array, attn_win_size: Optional[int]
) -> Array:
  """Unfused semantics: q,k,v [B, L, H, D] (q pre-scaled) -> [B, L, H, D]."""
  logits = jnp.einsum('BTNH,BFNH->BNFT', k, q)
  length = q.shape[1]
  if attn_win_size is not None:
    i = jnp.arange(length)
    band = jnp.abs(i[:, None] - i[None, :]) <= attn_win_size
    logits = jnp.where(band[None, None], logits, _NEG)
  weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(
      q.dtype
  )
  return jnp.einsum('BNFT,BTNH->BFNH', weights, v)


def _kernel(q_ref, k_ref, v_ref, o_ref, *, attn_win_size, length):
  # Blocks are [G, L, D]: G (batch*head) pairs per program.
  q = q_ref[:].astype(jnp.float32)
  k = k_ref[:].astype(jnp.float32)
  v = v_ref[:].astype(jnp.float32)
  s = jax.lax.dot_general(
      q, k, (((2,), (2,)), ((0,), (0,))),
      preferred_element_type=jnp.float32,
  )  # [G, L, L]
  rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
  cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
  valid = cols < length
  if attn_win_size is not None:
    valid = valid & (jnp.abs(rows - cols) <= attn_win_size)
  s = jnp.where(valid, s, _NEG)
  m = jnp.max(s, axis=2, keepdims=True)
  p = jnp.exp(s - m)
  denom = jnp.sum(p, axis=2, keepdims=True)
  o = jax.lax.dot_general(
      p, v, (((2,), (1,)), ((0,), (0,))),
      preferred_element_type=jnp.float32,
  )
  o_ref[:] = (o / denom).astype(o_ref.dtype)


def banded_attention(
    q: Array,
    k: Array,
    v: Array,
    attn_win_size: Optional[int],
    interpret: bool = False,
    group: int = 16,
) -> Array:
  """Fused banded attention. q,k,v: [B, L, H, D], q pre-scaled."""
  b, l, h, d = q.shape
  n = b * h
  group = min(group, n)
  while n % group:
    group -= 1

  # [B, L, H, D] -> [B*H, L, D] program blocks.
  def to_blocks(x):
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(n, l, d)

  qb, kb, vb = to_blocks(q), to_blocks(k), to_blocks(v)
  spec = pl.BlockSpec((group, l, d), lambda i: (i, 0, 0),
                      memory_space=pltpu.VMEM)
  out = pl.pallas_call(
      functools.partial(_kernel, attn_win_size=attn_win_size, length=l),
      grid=(n // group,),
      in_specs=[spec, spec, spec],
      out_specs=spec,
      out_shape=jax.ShapeDtypeStruct((n, l, d), q.dtype),
      interpret=interpret,
  )(qb, kb, vb)
  return jnp.transpose(out.reshape(b, h, l, d), (0, 2, 1, 3))


def _fwd_dropout_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *,
                        attn_win_size, length, keep_prob):
  """Forward with a precomputed dropout mask on the attention weights.

  The mask is generated outside the kernel (XLA-side bernoulli): the
  TPU in-kernel PRNG has no interpret-mode lowering, and a shared mask
  input keeps forward/backward bit-identical by construction. The big
  [G, L, L] logits/weights tensors still never touch HBM.
  """
  q = q_ref[:].astype(jnp.float32)
  k = k_ref[:].astype(jnp.float32)
  v = v_ref[:].astype(jnp.float32)
  s = jax.lax.dot_general(
      q, k, (((2,), (2,)), ((0,), (0,))),
      preferred_element_type=jnp.float32,
  )
  rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
  cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
  valid = cols < length
  if attn_win_size is not None:
    valid = valid & (jnp.abs(rows - cols) <= attn_win_size)
  s = jnp.where(valid, s, _NEG)
  m = jnp.max(s, axis=2, keepdims=True)
  p = jnp.exp(s - m)
  denom = jnp.sum(p, axis=2, keepdims=True)
  w = p / denom
  w = w * (mask_ref[:].astype(jnp.float32) / keep_prob)
  o = jax.lax.dot_general(
      w, v, (((2,), (1,)), ((0,), (0,))),
      preferred_element_type=jnp.float32,
  )
  o_ref[:] = o.astype(o_ref.dtype)


def _bwd_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, dq_ref, dk_ref,
                dv_ref, *, attn_win_size, length, keep_prob, has_mask):
  """Backward: recompute the weights in VMEM, then the three grads.

  Softmax rows: w = softmax(mask(q k^T)); dropped = w * mask/keep.
    dv = dropped^T do
    d(dropped) = do v^T;  dw = d(dropped) * mask/keep
    ds = w * (dw - rowsum(dw * w))   (softmax VJP; masked cols have
                                      w == 0, so ds == 0 there)
    dq = ds k;  dk = ds^T q
  """
  q = q_ref[:].astype(jnp.float32)
  k = k_ref[:].astype(jnp.float32)
  v = v_ref[:].astype(jnp.float32)
  do = do_ref[:].astype(jnp.float32)
  s = jax.lax.dot_general(
      q, k, (((2,), (2,)), ((0,), (0,))),
      preferred_element_type=jnp.float32,
  )
  rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
  cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
  valid = cols < length
  if attn_win_size is not None:
    valid = valid & (jnp.abs(rows - cols) <= attn_win_size)
  s = jnp.where(valid, s, _NEG)
  m = jnp.max(s, axis=2, keepdims=True)
  p = jnp.exp(s - m)
  denom = jnp.sum(p, axis=2, keepdims=True)
  w = p / denom
  if has_mask:
    drop = mask_ref[:].astype(jnp.float32) / keep_prob
  else:
    drop = 1.0
  dropped = w * drop
  dv_ref[:] = jax.lax.dot_general(
      dropped, do, (((1,), (1,)), ((0,), (0,))),
      preferred_element_type=jnp.float32,
  ).astype(dv_ref.dtype)
  d_dropped = jax.lax.dot_general(
      do, v, (((2,), (2,)), ((0,), (0,))),
      preferred_element_type=jnp.float32,
  )
  dw = d_dropped * drop
  ds = w * (dw - jnp.sum(dw * w, axis=2, keepdims=True))
  dq_ref[:] = jax.lax.dot_general(
      ds, k, (((2,), (1,)), ((0,), (0,))),
      preferred_element_type=jnp.float32,
  ).astype(dq_ref.dtype)
  dk_ref[:] = jax.lax.dot_general(
      ds, q, (((1,), (1,)), ((0,), (0,))),
      preferred_element_type=jnp.float32,
  ).astype(dk_ref.dtype)



def _blocks(x, n, l, d):
  return jnp.transpose(x, (0, 2, 1, 3)).reshape(n, l, d)


def _unblocks(x, b, h, l, d):
  return jnp.transpose(x.reshape(b, h, l, d), (0, 2, 1, 3))


def _bwd_call(q, k, v, mask, do, attn_win_size, keep_prob, interpret,
              group=8):
  b, l, h, d = q.shape
  n = b * h
  group = min(group, n)
  while n % group:
    group -= 1
  qb, kb, vb = (_blocks(x, n, l, d) for x in (q, k, v))
  dob = _blocks(do, n, l, d)
  has_mask = mask is not None
  if has_mask:
    # f32 cast happens XLA-side: Mosaic has no uint8->f32 lowering.
    maskb = mask.reshape(n, l, l).astype(jnp.float32)
  else:
    maskb = jnp.zeros((n, 1, 1), jnp.float32)  # unread placeholder
  spec = pl.BlockSpec((group, l, d), lambda i: (i, 0, 0),
                      memory_space=pltpu.VMEM)
  mask_spec = pl.BlockSpec(
      (group, l, l) if has_mask else (group, 1, 1),
      lambda i: (i, 0, 0), memory_space=pltpu.VMEM,
  )
  dq, dk, dv = pl.pallas_call(
      functools.partial(
          _bwd_kernel, attn_win_size=attn_win_size, length=l,
          keep_prob=keep_prob, has_mask=has_mask,
      ),
      grid=(n // group,),
      in_specs=[spec, spec, spec, mask_spec, spec],
      out_specs=[spec, spec, spec],
      out_shape=[jax.ShapeDtypeStruct((n, l, d), q.dtype)] * 3,
      interpret=pallas_util.resolve_interpret(interpret),
  )(qb, kb, vb, maskb, dob)
  return tuple(_unblocks(x, b, h, l, d) for x in (dq, dk, dv))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def banded_attention_vjp(q, k, v, attn_win_size, interpret=None):
  """Differentiable fused banded attention (no dropout).

  Same semantics as banded_attention/reference_banded_attention; the
  backward recomputes the weights in VMEM (flash-attention style).
  """
  return banded_attention(q, k, v, attn_win_size,
                          interpret=pallas_util.resolve_interpret(interpret))


def _vjp_fwd(q, k, v, attn_win_size, interpret):
  return banded_attention_vjp(q, k, v, attn_win_size, interpret), (
      q, k, v)


def _vjp_bwd(attn_win_size, interpret, res, do):
  q, k, v = res
  return _bwd_call(q, k, v, None, do, attn_win_size, 1.0, interpret)


banded_attention_vjp.defvjp(_vjp_fwd, _vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def banded_attention_dropout_vjp(q, k, v, mask, attn_win_size,
                                 keep_prob, interpret=None):
  """Differentiable fused banded attention with weight dropout.

  mask: [B, H, L, L] bernoulli(keep_prob) keep-mask (uint8/bool),
  generated by the caller so forward and backward share it exactly
  (the unfused path's nn.Dropout semantics: weights * mask/keep_prob).
  """
  b, l, h, d = q.shape
  n = b * h
  group = min(16, n)
  while n % group:
    group -= 1
  qb, kb, vb = (_blocks(x, n, l, d) for x in (q, k, v))
  # f32 cast happens XLA-side: Mosaic has no uint8->f32 lowering.
  maskb = mask.reshape(n, l, l).astype(jnp.float32)
  spec = pl.BlockSpec((group, l, d), lambda i: (i, 0, 0),
                      memory_space=pltpu.VMEM)
  mask_spec = pl.BlockSpec((group, l, l), lambda i: (i, 0, 0),
                           memory_space=pltpu.VMEM)
  out = pl.pallas_call(
      functools.partial(
          _fwd_dropout_kernel, attn_win_size=attn_win_size, length=l,
          keep_prob=keep_prob,
      ),
      grid=(n // group,),
      in_specs=[spec, spec, spec, mask_spec],
      out_specs=spec,
      out_shape=jax.ShapeDtypeStruct((n, l, d), q.dtype),
      interpret=pallas_util.resolve_interpret(interpret),
  )(qb, kb, vb, maskb)
  return _unblocks(out, b, h, l, d)


def _dvjp_fwd(q, k, v, mask, attn_win_size, keep_prob, interpret):
  out = banded_attention_dropout_vjp(
      q, k, v, mask, attn_win_size, keep_prob, interpret
  )
  return out, (q, k, v, mask)


def _dvjp_bwd(attn_win_size, keep_prob, interpret, res, do):
  import numpy as np

  q, k, v, mask = res
  dq, dk, dv = _bwd_call(
      q, k, v, mask, do, attn_win_size, keep_prob, interpret
  )
  d_mask = np.zeros(mask.shape, jax.dtypes.float0)
  return dq, dk, dv, d_mask


banded_attention_dropout_vjp.defvjp(_dvjp_fwd, _dvjp_bwd)
