"""Pallas TPU kernel: batch-major fused embed->condense->attention.

The L=100 production hot path. The per-(batch, head) kernels in
ops/banded_attention.py measured 0.82x the XLA path *inside the model*
at the production window length (MEASURED_FLASH_r2.json): with L=100
every per-window matmul is smaller than one 128x128 MXU tile, so a
grid that hands each program one window (or one batch*head pair)
starves the systolic array no matter how well it tiles. The short-
sequence lesson from the TPU serving literature (Ragged Paged
Attention, arxiv 2604.15464) is to make the *batch* dimension the
unit of work: each grid program here processes a TILE OF WINDOWS and
runs every projection as one [tile*L, K] x [K, N] matmul, so the MXU
sees token-major operands hundreds of rows tall instead of window-
sized crumbs.

Per grid program, for a tile of windows, one VMEM-resident pass:

  1. one-hot feature embedding (the `embed_onehot` MFU lever, done
     structurally: the one-hot is built in VMEM with an iota compare
     and immediately matmul'd against the family table — the gather
     path's scalar-unit traffic and the [B, R, L, E] HBM intermediate
     both disappear);
  2. the condenser projection (`condense_transformer_input`), fused
     per row-chunk as a two-axis contraction so the 560-wide concat
     never materializes anywhere;
  3. sinusoidal position add;
  4. layer-0 banded multi-head attention: q/k/v projections
     (batch-major), per-head banded softmax with configurable
     accumulation dtype (the `attn_softmax_dtype` lever), and the
     output projection.

The kernel returns (x_base, attn_out) — the embedded/condensed/
position-encoded activations and the attention block output — and the
caller applies the ReZero residual, so checkpointed alpha scalars and
any residual-wrapper variant stay outside the kernel.

Semantics are defined by `reference_fused_forward` (pure jnp, mirrors
models/model.py exactly); the kernel is validated against it and
against the full XLA model in interpret mode on CPU
(tests/test_fused_hotpath.py), so correctness is provable without a
chip. models/model.py routes through this kernel when
params.use_fused_hotpath is set and the config is eligible
(inference, condensed learn-values input, ReZero, L <= MAX_WINDOW_LEN).
"""
from __future__ import annotations

import functools
import os
from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepconsensus_tpu import constants
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.preprocess.pileup import row_indices

Array = jnp.ndarray

_NEG = -1e9

# Above this window length the [tile, L, L] score block stops paying
# for itself against the flash kernel's structural band; callers fall
# back to the XLA path / flash kernel (same boundary as
# flash_band_attention.WHOLE_L_LIMIT). With window buckets, eligibility
# is per bucket: traces at L <= this run fused, longer buckets XLA.
MAX_WINDOW_LEN = config_lib.FUSED_MAX_WINDOW_LEN

# Windows per grid program. 8 keeps the peak VMEM footprint (one-hot
# chunk + live q/k/v/x values + weights) near 11 MB at the production
# shape; override for sweeps without a code change.
DEFAULT_TILE_WINDOWS = int(os.environ.get('DC_TPU_FUSED_TILE', '8'))

# VMEM budget for one transient one-hot block [tile, chunk, L, V] f32;
# bounds how many rows of a family are one-hot-encoded at once.
_ONEHOT_BUDGET_BYTES = 4 << 20


class FamilySpec(NamedTuple):
  """Static description of one feature family's slice of the pileup.

  cond_offset is the family's first row in the condenser weight (the
  concat order of DeepConsensusModel._embed_rows); shift is added to
  raw ids before clipping/embedding (ccs_bq stores gap as -1).
  """

  name: str
  row_start: int
  n_rows: int
  vocab: int
  width: int
  table_idx: int
  cond_offset: int
  shift: int


def build_family_specs(params) -> Tuple[Tuple[FamilySpec, ...],
                                        Tuple[str, ...], int]:
  """Family specs + table keys + condenser input width for a config.

  Mirrors DeepConsensusModel._embed_rows: same row ranges, same concat
  order, same table sharing (ccs rows embed through the bases table).
  Table keys name the embedding param that backs each table input.
  """
  (base_r, pw_r, ip_r, strand_r, ccs_r, ccs_bq_r, sn_r) = row_indices(
      params.max_passes, params.use_ccs_bq
  )
  specs = []
  table_keys: list = []
  offset = 0

  def add(name, rng, vocab, width, table_key, shift=0):
    nonlocal offset
    if table_key not in table_keys:
      table_keys.append(table_key)
    specs.append(FamilySpec(
        name=name, row_start=rng[0], n_rows=rng[1] - rng[0], vocab=vocab,
        width=width, table_idx=table_keys.index(table_key),
        cond_offset=offset, shift=shift,
    ))
    offset += (rng[1] - rng[0]) * width

  if params.use_bases:
    add('bases', base_r, constants.SEQ_VOCAB_SIZE,
        params.per_base_hidden_size, 'bases')
  if params.use_pw:
    add('pw', pw_r, params.PW_MAX + 1, params.pw_hidden_size, 'pw')
  if params.use_ip:
    add('ip', ip_r, params.IP_MAX + 1, params.ip_hidden_size, 'ip')
  if params.use_strand:
    add('strand', strand_r, params.STRAND_MAX + 1,
        params.strand_hidden_size, 'strand')
  if params.use_ccs:
    add('ccs', ccs_r, constants.SEQ_VOCAB_SIZE,
        params.per_base_hidden_size, 'bases')
  if params.use_ccs_bq:
    add('ccs_bq', ccs_bq_r, params.CCS_BQ_MAX,
        params.ccs_bq_hidden_size, 'ccs_bq', shift=1)
  if params.use_sn:
    add('sn', sn_r, params.SN_MAX + 1, params.sn_hidden_size, 'sn')
  return tuple(specs), tuple(table_keys), offset


def prepare_ids(rows: Array, specs: Sequence[FamilySpec]) -> Array:
  """[B, R, L] raw float/int rows -> int32 ids, shifted and clipped
  per family exactly like MaskedEmbed's gather (mode='clip') and
  one-hot (jnp.clip) paths — both clamp to [0, vocab-1]."""
  ids = rows.astype(jnp.int32)
  for spec in specs:
    seg = ids[:, spec.row_start:spec.row_start + spec.n_rows, :]
    seg = jnp.clip(seg + spec.shift, 0, spec.vocab - 1)
    ids = ids.at[:, spec.row_start:spec.row_start + spec.n_rows, :].set(seg)
  return ids


def _row_chunk(tile: int, length: int, spec: FamilySpec) -> int:
  per_row = tile * length * spec.vocab * 4
  return max(1, min(spec.n_rows, _ONEHOT_BUDGET_BYTES // max(per_row, 1)))


def _embed_condense(ids, table_vals, w_cond, specs, tile, length, hidden):
  """One-hot embed + condense for a tile: x[b, l, :] accumulated per
  row-chunk as a two-axis contraction, so neither the one-hot nor the
  pre-condense concat ever leaves VMEM. Shared between the kernel and
  the jnp reference (plain jnp ops only)."""
  x = jnp.zeros((tile, length, hidden), jnp.float32)
  for spec in specs:
    table = table_vals[spec.table_idx].astype(jnp.float32)
    chunk = _row_chunk(tile, length, spec)
    for c0 in range(0, spec.n_rows, chunk):
      c = min(chunk, spec.n_rows - c0)
      r0 = spec.row_start + c0
      seg = ids[:, r0:r0 + c, :]  # [tile, c, L] int32
      iota = jax.lax.broadcasted_iota(
          jnp.int32, (tile, c, length, spec.vocab), 3)
      # Masked one-hot: id 0 embeds to the zero vector (MaskedEmbed's
      # (ids != 0) mask); matching col 0 and masking it are the same.
      onehot = ((seg[..., None] == iota) & (seg[..., None] > 0)).astype(
          jnp.float32)
      emb = jax.lax.dot_general(
          onehot.reshape(tile * c * length, spec.vocab), table,
          (((1,), (0,)), ((), ())),
          preferred_element_type=jnp.float32,
      ).reshape(tile, c, length, spec.width)
      w0 = spec.cond_offset + c0 * spec.width
      w_slice = w_cond[w0:w0 + c * spec.width, :].reshape(
          c, spec.width, hidden)
      # Contract (row, width) against the condenser rows owned by this
      # chunk: the 560-wide concat never materializes.
      x = x + jax.lax.dot_general(
          emb, w_slice, (((1, 3), (0, 1)), ((), ())),
          preferred_element_type=jnp.float32,
      )
  return x


def _attention(x, wq, wk, wv, wo, *, num_heads, qscale, attn_win_size,
               length, softmax_dtype):
  """Layer-0 banded MHA on a [tile, L, H] f32 block: batch-major
  projections, per-head banded softmax in softmax_dtype (the
  attn_softmax_dtype lever), output projection. Shared between the
  kernel and the jnp reference."""
  tile, _, hidden = x.shape
  head_dim = hidden // num_heads
  x2 = x.reshape(tile * length, hidden)

  def proj(w):
    return jax.lax.dot_general(
        x2, w.astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).reshape(tile, length, num_heads, head_dim)

  q = proj(wq) * qscale
  k = proj(wk)
  v = proj(wv)
  if attn_win_size is not None:
    rows = jax.lax.broadcasted_iota(jnp.int32, (tile, length, length), 1)
    cols = jax.lax.broadcasted_iota(jnp.int32, (tile, length, length), 2)
    band = jnp.abs(rows - cols) <= attn_win_size
  outs = []
  for h in range(num_heads):
    s = jax.lax.dot_general(
        q[:, :, h, :], k[:, :, h, :], (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )  # [tile, L, L]
    if attn_win_size is not None:
      s = jnp.where(band, s, _NEG)
    sd = s.astype(softmax_dtype)
    m = jnp.max(sd, axis=2, keepdims=True)
    p = jnp.exp(sd - m)
    w = (p / jnp.sum(p, axis=2, keepdims=True)).astype(jnp.float32)
    outs.append(jax.lax.dot_general(
        w, v[:, :, h, :], (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ))
  o = jnp.concatenate(outs, axis=-1).reshape(tile * length, hidden)
  out = jax.lax.dot_general(
      o, wo.astype(jnp.float32), (((1,), (0,)), ((), ())),
      preferred_element_type=jnp.float32,
  )
  return out.reshape(tile, length, hidden)


def _kernel(*refs, specs, n_tables, num_heads, qscale, attn_win_size,
            length, hidden, softmax_dtype):
  ids_ref = refs[0]
  table_refs = refs[1:1 + n_tables]
  w_cond_ref, wq_ref, wk_ref, wv_ref, wo_ref, pos_ref = refs[
      1 + n_tables:7 + n_tables]
  xbase_ref, attn_ref = refs[7 + n_tables:9 + n_tables]

  tile = ids_ref.shape[0]
  ids = ids_ref[:]
  table_vals = [t[:] for t in table_refs]
  w_cond = w_cond_ref[:].astype(jnp.float32)
  x = _embed_condense(ids, table_vals, w_cond, specs, tile, length, hidden)
  x = x + pos_ref[:].astype(jnp.float32)[None]
  xbase_ref[:] = x.astype(xbase_ref.dtype)
  out = _attention(
      x, wq_ref[:], wk_ref[:], wv_ref[:], wo_ref[:],
      num_heads=num_heads, qscale=qscale, attn_win_size=attn_win_size,
      length=length, softmax_dtype=softmax_dtype,
  )
  attn_ref[:] = out.astype(attn_ref.dtype)


def fused_embed_condense_attention(
    rows: Array,
    tables: Dict[str, Array],
    w_cond: Array,
    wq: Array,
    wk: Array,
    wv: Array,
    wo: Array,
    pos: Optional[Array],
    *,
    specs: Tuple[FamilySpec, ...],
    table_keys: Tuple[str, ...],
    num_heads: int,
    attn_win_size: Optional[int],
    softmax_dtype: Any = jnp.float32,
    compute_dtype: Any = jnp.float32,
    tile_windows: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Tuple[Array, Array]:
  """Fused embed->condense->pos->layer-0-attention over a window batch.

  rows: [B, R, L] raw pileup rows (float or int). tables: unscaled
  embedding params keyed per build_family_specs. w_cond: [cond_in, H]
  condenser kernel. wq/wk/wv: [H, H] (DenseGeneral kernels flattened;
  the 1/sqrt(head_dim) query scale is applied in-kernel after the
  projection, matching the model's op order). wo: [H, H] output
  projection. pos: [L, H] positional encoding or None.

  Returns (x_base, attn_out), both [B, L, H] in compute_dtype: the
  pre-attention activations and the attention block output. The caller
  applies the residual (ReZero alpha lives with its checkpointed
  parameter, not in the kernel).
  """
  from deepconsensus_tpu.ops import pallas_util

  b, r, length = rows.shape
  hidden = w_cond.shape[1]
  head_dim = hidden // num_heads
  cond_in = sum(s.n_rows * s.width for s in specs)
  if cond_in != w_cond.shape[0]:
    raise ValueError(
        f'condenser expects {w_cond.shape[0]} input features, family '
        f'specs cover {cond_in}; config and weights disagree')
  if hidden % num_heads:
    raise ValueError('hidden size must divide num_heads')

  tile = tile_windows or DEFAULT_TILE_WINDOWS
  tile = max(1, min(tile, b))
  ids = prepare_ids(rows, specs)
  pad = (-b) % tile
  if pad:
    # Zero ids embed to zero vectors; padded windows compute garbage-
    # free attention over pure position encodings and are sliced away.
    ids = jnp.pad(ids, ((0, pad), (0, 0), (0, 0)))
  n_tiles = (b + pad) // tile

  # dclint: allow=dtype-downcast (kernel inputs follow the configured
  # compute dtype; bf16 here is the inference_dtype lever, not a leak)
  cast = lambda a: jnp.asarray(a, compute_dtype)
  # Fold the sqrt(width) embedding output scale into the tables
  # (MaskedEmbed multiplies after the lookup; the lookup is linear so
  # the fold is exact up to one f32 rounding).
  table_in = [
      # dclint: allow=dtype-downcast (scale folded at compute dtype)
      cast(tables[key]) * jnp.asarray(
          next(s.width for s in specs if s.table_idx == i) ** 0.5,
          compute_dtype)
      for i, key in enumerate(table_keys)
  ]
  if pos is None:
    pos = jnp.zeros((length, hidden), compute_dtype)

  full = lambda a: pl.BlockSpec(
      a.shape, lambda i: (0,) * a.ndim, memory_space=pltpu.VMEM)
  ids_spec = pl.BlockSpec((tile, r, length), lambda i: (i, 0, 0),
                          memory_space=pltpu.VMEM)
  out_spec = pl.BlockSpec((tile, length, hidden), lambda i: (i, 0, 0),
                          memory_space=pltpu.VMEM)
  inputs = [ids, *table_in, cast(w_cond), cast(wq), cast(wk), cast(wv),
            cast(wo), cast(pos)]
  x_base, attn_out = pl.pallas_call(
      functools.partial(
          _kernel, specs=specs, n_tables=len(table_keys),
          num_heads=num_heads, qscale=head_dim ** -0.5,
          attn_win_size=attn_win_size, length=length, hidden=hidden,
          softmax_dtype=jnp.dtype(softmax_dtype),
      ),
      grid=(n_tiles,),
      in_specs=[ids_spec] + [full(a) for a in inputs[1:]],
      out_specs=[out_spec, out_spec],
      out_shape=[
          jax.ShapeDtypeStruct((b + pad, length, hidden), compute_dtype),
          jax.ShapeDtypeStruct((b + pad, length, hidden), compute_dtype),
      ],
      interpret=pallas_util.resolve_interpret(interpret),
  )(*inputs)
  return x_base[:b], attn_out[:b]


def reference_fused_forward(
    rows: Array,
    tables: Dict[str, Array],
    w_cond: Array,
    wq: Array,
    wk: Array,
    wv: Array,
    wo: Array,
    pos: Optional[Array],
    *,
    specs: Tuple[FamilySpec, ...],
    table_keys: Tuple[str, ...],
    num_heads: int,
    attn_win_size: Optional[int],
    softmax_dtype: Any = jnp.float32,
) -> Tuple[Array, Array]:
  """Pure-jnp semantics of the fused kernel (same helpers, no Pallas):
  the parity oracle for unit tests and a CPU-debuggable mirror."""
  b, _, length = rows.shape
  hidden = w_cond.shape[1]
  head_dim = hidden // num_heads
  ids = prepare_ids(rows, specs)
  table_vals = [
      tables[key].astype(jnp.float32) * (
          next(s.width for s in specs if s.table_idx == i) ** 0.5)
      for i, key in enumerate(table_keys)
  ]
  x = _embed_condense(ids, table_vals, w_cond.astype(jnp.float32), specs,
                      b, length, hidden)
  if pos is not None:
    x = x + pos.astype(jnp.float32)[None]
  out = _attention(
      x, wq, wk, wv, wo, num_heads=num_heads, qscale=head_dim ** -0.5,
      attn_win_size=attn_win_size, length=length,
      softmax_dtype=jnp.dtype(softmax_dtype),
  )
  return x, out
