"""Pallas TPU kernel: batch-major fused encoder blocks (MHA+FFN+ReZero).

Completes the L=100 fused hot path started in
ops/fused_window_attention.py (PR 5): that kernel covers
embed->condense->pos->layer-0 attention; this one covers everything
after it — for each remaining encoder block, banded multi-head
attention, the relu FFN, and both ReZero residuals run as ONE grid
program per tile of windows, with the same batch-major tiling
(DC_TPU_FUSED_TILE windows per program, every projection an MXU-shaped
[tile*L, K] x [K, N] matmul).

One pallas_call per encoder block, not one for the whole stack: five
layers of f32 weights (~29 MB at the distilled student's 280/2048
shape) would blow the ~16 MB VMEM budget, while a single block's
weights plus the [tile*L, filter] relu intermediate stay near 14 MB at
tile=8.

Quantization support (params.quantize_matmuls=int8): each matmul
weight arrives as a `QuantizedWeight` — either a plain f32/bf16 kernel
(scale=None) or int8 values with a per-output-channel f32 scale. The
dequant is folded into the matmul epilogue, `(x @ q) * scale`, which
is exact per column because the scale is constant along the
contraction; int8 values stay int8 in HBM and VMEM, so the weight
transfer shrinks 4x. ReZero alphas are passed as (1, 1) SMEM scalars
— NOT folded into the weights — so quantization and the residual stay
independent and the op order matches the XLA model exactly.

Semantics are defined by `reference_encoder_stack` (pure jnp, shares
the math helpers below); the kernel is validated against it per block
and against the full XLA model in interpret mode on CPU
(tests/test_fused_encoder_block.py). models/model.py routes through
here after the PR-5 kernel when params.use_fused_hotpath is set, with
the same bitwise-tested XLA fallback for training/init/L>128.
"""
from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepconsensus_tpu.ops import fused_window_attention as fwa

Array = jnp.ndarray

_NEG = -1e9


class QuantizedWeight(NamedTuple):
  """One matmul weight, optionally int8-quantized.

  values: [K, N] kernel — compute-dtype floats when scale is None,
  int8 otherwise. scale: per-output-channel f32 [N] such that the
  effective weight is values * scale[None, :].
  """

  values: Array
  scale: Optional[Array] = None


class EncoderBlockWeights(NamedTuple):
  """Weights for one encoder block (banded MHA + FFN + ReZero).

  The attention half (wq..wo, attn_alpha) is None for the layer-0
  remainder block when the PR-5 kernel already applied attention_0's
  residual (skip_first_attention).
  """

  wq: Optional[QuantizedWeight]
  wk: Optional[QuantizedWeight]
  wv: Optional[QuantizedWeight]
  wo: Optional[QuantizedWeight]
  attn_alpha: Optional[Array]
  w_filter: QuantizedWeight
  b_filter: Array
  w_output: QuantizedWeight
  b_output: Array
  ffn_alpha: Array


def _dequant_matmul(x2: Array, values: Array, scale: Optional[Array]) -> Array:
  """[M, K] x QuantizedWeight -> [M, N] f32, dequant in the epilogue.

  The per-output-channel scale commutes with the contraction, so
  (x @ q) * scale equals x @ (q * scale) up to f32 rounding; with
  scale=None (or exact ones) this is the plain f32 matmul.
  """
  out = jax.lax.dot_general(
      x2, values.astype(jnp.float32), (((1,), (0,)), ((), ())),
      preferred_element_type=jnp.float32,
  )
  if scale is not None:
    out = out * scale.astype(jnp.float32)
  return out


def _attention(x, wq, wk, wv, wo, *, num_heads, qscale, attn_win_size,
               length, softmax_dtype, mask=None):
  """Banded MHA on a [tile, L, H] f32 block with quant-aware
  projections; mirrors fused_window_attention._attention (same band
  mask, same softmax_dtype lever, same op order). Each w is a
  (values, scale_row_or_None) pair. mask (ragged slots): a
  [tile, L, L] bool mask that REPLACES the static band — it already
  ANDs the band with the lengths-derived same-window/valid tests
  (ragged_window_attention.ragged_attention_mask). Shared with the
  jnp reference."""
  tile, _, hidden = x.shape
  head_dim = hidden // num_heads
  x2 = x.reshape(tile * length, hidden)

  def proj(w):
    return _dequant_matmul(x2, w[0], w[1]).reshape(
        tile, length, num_heads, head_dim)

  q = proj(wq) * qscale
  k = proj(wk)
  v = proj(wv)
  band = mask
  if band is None and attn_win_size is not None:
    rows = jax.lax.broadcasted_iota(jnp.int32, (tile, length, length), 1)
    cols = jax.lax.broadcasted_iota(jnp.int32, (tile, length, length), 2)
    band = jnp.abs(rows - cols) <= attn_win_size
  outs = []
  for h in range(num_heads):
    s = jax.lax.dot_general(
        q[:, :, h, :], k[:, :, h, :], (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )  # [tile, L, L]
    if band is not None:
      s = jnp.where(band, s, _NEG)
    sd = s.astype(softmax_dtype)
    m = jnp.max(sd, axis=2, keepdims=True)
    p = jnp.exp(sd - m)
    w = (p / jnp.sum(p, axis=2, keepdims=True)).astype(jnp.float32)
    outs.append(jax.lax.dot_general(
        w, v[:, :, h, :], (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ))
  o = jnp.concatenate(outs, axis=-1).reshape(tile * length, hidden)
  return _dequant_matmul(o, wo[0], wo[1]).reshape(tile, length, hidden)


def _ffn(x, w_filter, b_filter, w_output, b_output, *, length, hidden):
  """filter relu -> output on a [tile, L, H] f32 block as two
  [tile*L, K] x [K, N] matmuls. Shared with the jnp reference."""
  tile = x.shape[0]
  x2 = x.reshape(tile * length, hidden)
  h = _dequant_matmul(x2, w_filter[0], w_filter[1])
  h = jnp.maximum(h + b_filter.astype(jnp.float32), 0.0)
  out = _dequant_matmul(h, w_output[0], w_output[1])
  out = out + b_output.astype(jnp.float32)
  return out.reshape(tile, length, hidden)


def _block_body(x, attn, ffn, attn_alpha, ffn_alpha, *, num_heads, qscale,
                attn_win_size, length, hidden, softmax_dtype, mask=None):
  """One encoder block on a [tile, L, H] f32 block: optional attention
  residual, then FFN residual, both ReZero (x + alpha * y)."""
  if attn is not None:
    y = _attention(
        x, *attn, num_heads=num_heads, qscale=qscale,
        attn_win_size=attn_win_size, length=length,
        softmax_dtype=softmax_dtype, mask=mask,
    )
    x = x + attn_alpha * y
  y = _ffn(x, *ffn, length=length, hidden=hidden)
  return x + ffn_alpha * y


def _kernel(*refs, has_attn, has_lengths, num_heads, qscale, attn_win_size,
            length, hidden, softmax_dtype):
  it = iter(refs)
  x_ref = next(it)
  mask = None
  if has_lengths:
    from deepconsensus_tpu.ops import ragged_window_attention as rwa

    mask = rwa.ragged_attention_mask(next(it)[:], length, attn_win_size)
  attn = attn_alpha = None
  if has_attn:
    attn = tuple((next(it)[:], next(it)[:]) for _ in range(4))
    attn_alpha = next(it)[0, 0]
  ffn = (
      (next(it)[:], next(it)[:]), next(it)[:],
      (next(it)[:], next(it)[:]), next(it)[:],
  )
  ffn_alpha = next(it)[0, 0]
  out_ref = next(it)

  x = x_ref[:].astype(jnp.float32)
  x = _block_body(
      x, attn, ffn, attn_alpha, ffn_alpha, num_heads=num_heads,
      qscale=qscale, attn_win_size=attn_win_size, length=length,
      hidden=hidden, softmax_dtype=softmax_dtype, mask=mask,
  )
  out_ref[:] = x.astype(out_ref.dtype)


def _weight_inputs(qw: QuantizedWeight, compute_dtype) -> Tuple[Array, Array]:
  """(values, scale_row) kernel inputs for one QuantizedWeight: int8
  values ride as int8 (4x smaller VMEM/transfer); unquantized kernels
  get an exact ones scale so the kernel signature stays uniform."""
  values, scale = qw
  n = values.shape[1]
  if scale is None:
    # dclint: allow=dtype-downcast (unquantized weights ride at the
    # configured compute dtype; the ones scale keeps them exact)
    return (jnp.asarray(values, compute_dtype),
            jnp.ones((1, n), jnp.float32))
  return jnp.asarray(values), jnp.asarray(scale, jnp.float32).reshape(1, n)


def _bias_input(b: Array) -> Array:
  return jnp.asarray(b, jnp.float32).reshape(1, -1)


def _alpha_input(a: Array) -> Array:
  return jnp.asarray(a, jnp.float32).reshape(1, 1)


def _block_call(xp: Array, block: EncoderBlockWeights, *, num_heads,
                attn_win_size, softmax_dtype, compute_dtype, tile,
                interpret, lengths: Optional[Array] = None) -> Array:
  """One pallas_call over an already tile-padded [B', L, H] batch."""
  bp, length, hidden = xp.shape
  head_dim = hidden // num_heads
  n_tiles = bp // tile
  has_attn = block.wq is not None
  has_lengths = has_attn and lengths is not None

  inputs = [xp]
  in_specs = [pl.BlockSpec((tile, length, hidden), lambda i: (i, 0, 0),
                           memory_space=pltpu.VMEM)]
  full = lambda a: pl.BlockSpec(
      a.shape, lambda i: (0,) * a.ndim, memory_space=pltpu.VMEM)
  smem = pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)

  def add_weight(qw):
    for a in _weight_inputs(qw, compute_dtype):
      inputs.append(a)
      in_specs.append(full(a))

  def add(a, spec=None):
    inputs.append(a)
    in_specs.append(spec if spec is not None else full(a))

  if has_lengths:
    add(jnp.asarray(lengths, jnp.int32),
        pl.BlockSpec((tile, lengths.shape[1]), lambda i: (i, 0),
                     memory_space=pltpu.VMEM))
  if has_attn:
    for qw in (block.wq, block.wk, block.wv, block.wo):
      add_weight(qw)
    add(_alpha_input(block.attn_alpha), smem)
  add_weight(block.w_filter)
  add(_bias_input(block.b_filter))
  add_weight(block.w_output)
  add(_bias_input(block.b_output))
  add(_alpha_input(block.ffn_alpha), smem)

  return pl.pallas_call(
      functools.partial(
          _kernel, has_attn=has_attn, has_lengths=has_lengths,
          num_heads=num_heads,
          qscale=head_dim ** -0.5, attn_win_size=attn_win_size,
          length=length, hidden=hidden,
          softmax_dtype=jnp.dtype(softmax_dtype),
      ),
      grid=(n_tiles,),
      in_specs=in_specs,
      out_specs=pl.BlockSpec((tile, length, hidden), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM),
      out_shape=jax.ShapeDtypeStruct((bp, length, hidden), compute_dtype),
      interpret=interpret,
  )(*inputs)


def fused_encoder_block(
    x: Array,
    block: EncoderBlockWeights,
    *,
    num_heads: int,
    attn_win_size: Optional[int],
    softmax_dtype: Any = jnp.float32,
    compute_dtype: Any = jnp.float32,
    tile_windows: Optional[int] = None,
    interpret: Optional[bool] = None,
    lengths: Optional[Array] = None,
) -> Array:
  """One fused encoder block over a [B, L, H] window batch."""
  return fused_encoder_stack(
      x, [block], num_heads=num_heads, attn_win_size=attn_win_size,
      softmax_dtype=softmax_dtype, compute_dtype=compute_dtype,
      tile_windows=tile_windows, interpret=interpret, lengths=lengths,
  )


def fused_encoder_stack(
    x: Array,
    blocks: Sequence[EncoderBlockWeights],
    *,
    num_heads: int,
    attn_win_size: Optional[int],
    softmax_dtype: Any = jnp.float32,
    compute_dtype: Any = jnp.float32,
    tile_windows: Optional[int] = None,
    interpret: Optional[bool] = None,
    lengths: Optional[Array] = None,
) -> Array:
  """Run a sequence of fused encoder blocks over a [B, L, H] batch.

  Pads the batch to a tile multiple once (padded windows compute
  garbage-free blocks over zero activations and are sliced away),
  launches one pallas_call per block, and returns [B, L, H] in
  compute_dtype. The final output LayerNorm stays outside — it is the
  caller's (cheap, dtype-sensitive) op, matching the PR-5 split where
  checkpointed scalars live with their parameters.

  lengths (ragged slots): a [B, wps] int32 per-slot window-widths
  vector; every attention block then masks with the lengths-derived
  ragged mask (band AND same-window AND valid) instead of the static
  band alone. FFN/residual halves are position-wise and unaffected.
  """
  from deepconsensus_tpu.ops import pallas_util

  b, length, hidden = x.shape
  if hidden % num_heads:
    raise ValueError('hidden size must divide num_heads')
  tile = tile_windows or fwa.DEFAULT_TILE_WINDOWS
  tile = max(1, min(tile, b))
  pad = (-b) % tile
  # dclint: allow=dtype-downcast (activations enter the fused stack at
  # the configured compute dtype; accumulation stays f32 in-kernel)
  xp = jnp.asarray(x, compute_dtype)
  lp = None
  if lengths is not None:
    lp = jnp.asarray(lengths, jnp.int32)
  if pad:
    xp = jnp.pad(xp, ((0, pad), (0, 0), (0, 0)))
    if lp is not None:
      # Zero lengths: every position of a padded slot is masked invalid.
      lp = jnp.pad(lp, ((0, pad), (0, 0)))
  interpret = pallas_util.resolve_interpret(interpret)
  for block in blocks:
    xp = _block_call(
        xp, block, num_heads=num_heads, attn_win_size=attn_win_size,
        softmax_dtype=softmax_dtype, compute_dtype=compute_dtype,
        tile=tile, interpret=interpret, lengths=lp,
    )
  return xp[:b]


def _reference_pair(qw: QuantizedWeight) -> Tuple[Array, Optional[Array]]:
  values, scale = qw
  if scale is None:
    return values.astype(jnp.float32), None
  return jnp.asarray(values), jnp.asarray(scale, jnp.float32).reshape(
      1, values.shape[1])


def reference_encoder_block(
    x: Array,
    block: EncoderBlockWeights,
    *,
    num_heads: int,
    attn_win_size: Optional[int],
    softmax_dtype: Any = jnp.float32,
    lengths: Optional[Array] = None,
) -> Array:
  """Pure-jnp semantics of one fused block (same helpers, no Pallas):
  the per-block parity oracle for unit tests."""
  _, length, hidden = x.shape
  head_dim = hidden // num_heads
  attn = None
  mask = None
  if block.wq is not None:
    attn = tuple(_reference_pair(w)
                 for w in (block.wq, block.wk, block.wv, block.wo))
    if lengths is not None:
      from deepconsensus_tpu.ops import ragged_window_attention as rwa

      mask = rwa.ragged_attention_mask(
          jnp.asarray(lengths, jnp.int32), length, attn_win_size)
  ffn = (
      _reference_pair(block.w_filter), _bias_input(block.b_filter),
      _reference_pair(block.w_output), _bias_input(block.b_output),
  )
  return _block_body(
      x.astype(jnp.float32), attn, ffn,
      None if block.attn_alpha is None else jnp.asarray(
          block.attn_alpha, jnp.float32),
      jnp.asarray(block.ffn_alpha, jnp.float32),
      num_heads=num_heads, qscale=head_dim ** -0.5,
      attn_win_size=attn_win_size, length=length, hidden=hidden,
      softmax_dtype=jnp.dtype(softmax_dtype), mask=mask,
  )


def reference_encoder_stack(
    x: Array,
    blocks: Sequence[EncoderBlockWeights],
    *,
    num_heads: int,
    attn_win_size: Optional[int],
    softmax_dtype: Any = jnp.float32,
    lengths: Optional[Array] = None,
) -> Array:
  """Pure-jnp mirror of fused_encoder_stack (no pad/tile, f32)."""
  for block in blocks:
    x = reference_encoder_block(
        x, block, num_heads=num_heads, attn_win_size=attn_win_size,
        softmax_dtype=softmax_dtype, lengths=lengths,
    )
  return x


def blocks_from_params(
    encoder_params,
    quant,
    num_layers: int,
    *,
    skip_first_attention: bool = False,
) -> Tuple[EncoderBlockWeights, ...]:
  """Extract per-block kernel weights from the encoder param subtree.

  encoder_params: variables['params']['encoder']. quant: the matching
  'quant' collection subtree ({module: {sub: {values, scale}}}) or
  None; when a leaf is present there, its int8 values + per-channel
  scale replace the (already dequantized-effective) params kernel.
  DenseGeneral attention kernels are reshaped to their 2D matmul form
  ([H, heads, hd] -> [H, H]; output [heads, hd, H] -> [H, H]).
  """

  def pick(mod: str, sub: str, kernel2d: Array) -> QuantizedWeight:
    entry = None
    if quant is not None and mod in quant:
      entry = quant[mod].get(sub)
    if entry is not None:
      return QuantizedWeight(entry['values'], entry['scale'])
    return QuantizedWeight(kernel2d, None)

  blocks = []
  for n in range(num_layers):
    if n == 0 and skip_first_attention:
      wq = wk = wv = wo = attn_alpha = None
    else:
      attn_p = encoder_params[f'self_attention_{n}']
      h = attn_p['query']['kernel'].shape[0]
      wq = pick(f'self_attention_{n}', 'query',
                attn_p['query']['kernel'].reshape(h, -1))
      wk = pick(f'self_attention_{n}', 'key',
                attn_p['key']['kernel'].reshape(h, -1))
      wv = pick(f'self_attention_{n}', 'value',
                attn_p['value']['kernel'].reshape(h, -1))
      wo = pick(f'self_attention_{n}', 'output_transform',
                attn_p['output_transform']['kernel'].reshape(-1, h))
      attn_alpha = encoder_params[f'attention_wrapper_{n}']['alpha']
    ffn_p = encoder_params[f'ffn_{n}']
    blocks.append(EncoderBlockWeights(
        wq=wq, wk=wk, wv=wv, wo=wo, attn_alpha=attn_alpha,
        w_filter=pick(f'ffn_{n}', 'filter_layer',
                      ffn_p['filter_layer']['kernel']),
        b_filter=ffn_p['filter_layer']['bias'],
        w_output=pick(f'ffn_{n}', 'output_layer',
                      ffn_p['output_layer']['kernel']),
        b_output=ffn_p['output_layer']['bias'],
        ffn_alpha=encoder_params[f'ffn_wrapper_{n}']['alpha'],
    ))
  return tuple(blocks)
