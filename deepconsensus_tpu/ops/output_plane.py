"""Device-resident output plane: softmax preds -> uint8 (ids, quals).

The host epilogue (inference/runner._finalize_sync) turns the device
max-prob into a Phred integer with numpy transcendentals:

    error = np.maximum(1.0 - max_prob, 1e-12)
    q     = -10 * np.log10(error)            # then calibrate / clamp /
    q     = round_half_even(min(q, maxq))    # round / floor at 0

Re-evaluating that math on device cannot be byte-identical: XLA CPU
lowers log10 through its own polynomial approximations, TPU through
different ones again, and a 1-ulp drift flips any quality that lands
within a ulp of a .5 boundary. So the device never computes a
logarithm. Instead the host precomputes — with the real numpy pipeline
as the oracle — the smallest float32 probability at which each integer
quality step first becomes reachable. The final quality is a monotone
step function of max_prob with at most max_base_quality steps, so on
device a quality is just a count of thresholds <= max_prob: pure IEEE
comparisons, bit-exact on every backend by construction.

Two device implementations share the thresholds: a plain-XLA epilogue
(compare + sum) and a Pallas kernel that fuses argmax + threshold
count into one VMEM pass appended after the last fused encoder block.
Both emit two uint8 planes — base ids and Phred qualities — shrinking
D2H per pack from 8 bytes/position (int32 ids + f32 max_prob) to 2.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepconsensus_tpu.calibration import lib as calibration_lib
from deepconsensus_tpu.ops import pallas_util

# The host epilogue's error-probability floor (runner._finalize_sync).
MIN_ERROR_PROB = 1e-12

# uint8 output plane: the largest quality the device contract can emit.
MAX_DEVICE_QUALITY = 255

# Verification probes per threshold build (vectorized, ~milliseconds):
# a uniform f32 sweep of [0, 1] plus a log-spaced cluster hugging
# p -> 1 where the quality curve is steepest.
_VERIFY_LINEAR = 1 << 16
_VERIFY_LOG = 1 << 14


def host_quality_reference(
    max_prob: np.ndarray,
    calibration_values: calibration_lib.QualityCalibrationValues,
    max_base_quality: int,
) -> np.ndarray:
  """The host epilogue, verbatim (runner._finalize_sync's tail).

  This is the oracle the threshold table is bisected against; it must
  stay operation-for-operation identical to the host fallback path —
  including dtype promotion inside calibrate_quality_scores — or the
  byte-identity contract silently breaks.
  """
  max_prob = np.asarray(max_prob)
  error_prob = np.maximum(1.0 - max_prob, MIN_ERROR_PROB)
  quality = -10.0 * np.log10(error_prob)
  if calibration_values.enabled:
    quality = calibration_lib.calibrate_quality_scores(
        quality, calibration_values)
  quality = np.minimum(quality, max_base_quality)
  quality = np.round(quality, decimals=0).astype(np.int32)
  return np.maximum(quality, 0)


def calibration_is_monotone(
    calibration_values: calibration_lib.QualityCalibrationValues) -> bool:
  """True when the calibrated quality is non-decreasing in the raw
  quality — the precondition for representing the prob->quality map as
  a threshold table. q*w+b applies above the threshold (everywhere
  when the threshold is 0), so monotonicity needs w >= 0 and no
  downward jump where the transform kicks in."""
  cv = calibration_values
  if not cv.enabled:
    return True
  if cv.w < 0:
    return False
  if cv.threshold > 0 and cv.threshold * cv.w + cv.b < cv.threshold:
    return False
  return True


def _bits(p: np.ndarray) -> np.ndarray:
  return np.asarray(p, np.float32).view(np.uint32).astype(np.int64)


def _from_bits(bits: np.ndarray) -> np.ndarray:
  return bits.astype(np.uint32).view(np.float32)


def quality_thresholds(
    calibration_values: calibration_lib.QualityCalibrationValues,
    max_base_quality: int,
) -> Optional[np.ndarray]:
  """Exact f32 probability thresholds for the device quality plane.

  thresholds[k-1] is the smallest float32 p in [0, 1] with
  host_quality_reference(p) >= k, found by bisection over the f32 bit
  lattice (non-negative floats are monotone in their bit patterns), so
  `sum(p >= thresholds)` reproduces the host integer exactly for every
  representable probability. Returns None when the map is not
  device-representable — non-monotone calibration, a top quality past
  the uint8 plane, or (defensively) a failed verification sweep — and
  the caller falls back to the host epilogue.
  """
  if not calibration_is_monotone(calibration_values):
    return None
  oracle = functools.partial(
      host_quality_reference,
      calibration_values=calibration_values,
      max_base_quality=max_base_quality)
  q_top = int(oracle(np.float32([1.0]))[0])
  if q_top > MAX_DEVICE_QUALITY:
    return None
  if q_top == 0:
    thresholds = np.zeros((0,), np.float32)
  else:
    ks = np.arange(1, q_top + 1, dtype=np.int64)
    # Invariant: oracle(lo) < k <= oracle(hi), over bit patterns.
    lo = np.full(q_top, -1, np.int64)  # one below bits(0.0) == 0
    hi = np.full(q_top, int(_bits(np.float32([1.0]))[0]), np.int64)
    while int((hi - lo).max()) > 1:
      active = (hi - lo) > 1
      mid = np.where(active, (lo + hi) // 2, hi)
      ge = oracle(_from_bits(mid)) >= ks
      hi = np.where(active & ge, mid, hi)
      lo = np.where(active & ~ge, mid, lo)
    thresholds = _from_bits(hi)
  if not _verify_thresholds(thresholds, oracle):
    return None  # pragma: no cover - defensive; bisection is exact
  return thresholds


def _verify_thresholds(thresholds: np.ndarray, oracle) -> bool:
  """Belt-and-braces sweep: the threshold count must match the oracle
  on a dense probe set evaluated at realistic (vectorized) array sizes,
  including every threshold's bit neighbourhood."""
  probes = [
      np.linspace(0.0, 1.0, _VERIFY_LINEAR, dtype=np.float32),
      (1.0 - np.logspace(-12, 0, _VERIFY_LOG)).astype(np.float32),
  ]
  if thresholds.size:
    bits = _bits(thresholds)[:, None] + np.arange(-2, 3)[None, :]
    bits = np.clip(bits, 0, int(_bits(np.float32([1.0]))[0]))
    probes.append(_from_bits(bits.ravel()))
  p = np.unique(np.concatenate(probes))
  p = p[(p >= 0.0) & (p <= 1.0)]
  counted = (p[:, None] >= thresholds[None, :]).sum(axis=1).astype(np.int32)
  return bool(np.array_equal(counted, oracle(p)))


def d2h_bytes_per_position(device_epilogue: bool) -> int:
  """Bytes/position the finalize drain pulls over D2H: two uint8
  planes with the device epilogue, int32 ids + f32 max_prob without."""
  return 2 if device_epilogue else 8


# ---------------------------------------------------------------------------
# Device epilogues (XLA + Pallas) — same thresholds, same outputs.
# ---------------------------------------------------------------------------


def phred_epilogue(
    preds: jnp.ndarray,
    thresholds: np.ndarray,
    *,
    use_pallas: bool = False,
    interpret: Optional[bool] = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
  """Softmax preds [B, L, V] -> (ids uint8 [B, L], quals uint8 [B, L]).

  ids is the same argmax the split outputs shipped (first-index ties);
  quals counts how many precomputed thresholds the per-position max
  prob clears — exactly host_quality_reference, with no device
  transcendentals (see module docstring).
  """
  if use_pallas:
    return phred_epilogue_pallas(preds, thresholds, interpret=interpret)
  thr = jnp.asarray(thresholds, jnp.float32)
  ids = jnp.argmax(preds, axis=-1).astype(jnp.uint8)
  max_prob = jnp.max(preds, axis=-1)
  quals = jnp.sum(
      max_prob[..., None] >= thr[None, None, :], axis=-1
  ).astype(jnp.uint8)
  return ids, quals


def _epilogue_kernel(preds_ref, thr_ref, ids_ref, quals_ref):
  """One VMEM pass per window tile: argmax + threshold count."""
  preds = preds_ref[...]
  ids_ref[...] = jnp.argmax(preds, axis=-1).astype(jnp.uint8)
  max_prob = jnp.max(preds, axis=-1)
  thr = thr_ref[...]
  quals_ref[...] = jnp.sum(
      max_prob[:, :, None] >= thr[0][None, None, :], axis=-1
  ).astype(jnp.uint8)


def _pick_tile(batch: int, want: int = 8) -> int:
  while want > 1 and batch % want:
    want //= 2
  return max(1, want)


def phred_epilogue_pallas(
    preds: jnp.ndarray,
    thresholds: np.ndarray,
    *,
    interpret: Optional[bool] = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
  """Pallas twin of phred_epilogue: the output-plane epilogue appended
  after the last fused encoder block, tiled batch-major like the block
  kernels. Thresholds ride in as one f32 lane row padded with +inf
  (padding can never count: p >= inf is false)."""
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  interpret = pallas_util.resolve_interpret(interpret)
  b, length, vocab = preds.shape
  lane = 128
  k = int(np.asarray(thresholds).size)
  k_pad = max(lane, ((k + lane - 1) // lane) * lane)
  thr = np.full((1, k_pad), np.inf, np.float32)
  thr[0, :k] = np.asarray(thresholds, np.float32)
  tile = _pick_tile(b)
  grid = (b // tile,)
  ids, quals = pl.pallas_call(
      _epilogue_kernel,
      grid=grid,
      in_specs=[
          pl.BlockSpec((tile, length, vocab), lambda i: (i, 0, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((1, k_pad), lambda i: (0, 0),
                       memory_space=pltpu.VMEM),
      ],
      out_specs=[
          pl.BlockSpec((tile, length), lambda i: (i, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((tile, length), lambda i: (i, 0),
                       memory_space=pltpu.VMEM),
      ],
      out_shape=[
          jax.ShapeDtypeStruct((b, length), jnp.uint8),
          jax.ShapeDtypeStruct((b, length), jnp.uint8),
      ],
      interpret=interpret,
  )(preds, jnp.asarray(thr))
  return ids, quals
