from deepconsensus_tpu.ops.wavefront import (  # noqa: F401
    wavefrontify,
    wavefrontify_vec,
)
