"""Structured trace spans: Chrome-trace-event JSONL, fleet-safe.

Every ticket/pack in the pipeline gets spans — featurize, pack-wait,
H2D transfer, device compute, finalize drain, stitch — plus one
request-level span per tier (route / featurize / serve_request), all
stamped with a trace id minted at the outermost tier (the router for
fleet traffic, the CLI for batch runs) and carried across processes in
the ``X-Dctpu-Trace-Id`` protocol header. Load the file straight into
Perfetto / chrome://tracing, or summarize it with ``dctpu trace``.

File format. Chrome's JSON trace format tolerates a missing closing
``]`` and a trailing comma, so the file is written as a ``[`` header
line followed by one complete-event object per line, each line ending
``,``. Each line is a single O_APPEND write, which POSIX keeps atomic
for these sizes, so N fleet processes share ONE trace file with no
coordination: the header is written only by the process that wins the
O_CREAT|O_EXCL race, and every other writer just appends events. pid
distinguishes tiers (a process_name metadata event labels each).

Overhead when off. Tracing is enabled by ``DCTPU_TRACE=<path>`` (or
``configure(path)``); when unset, ``enabled()`` is a module-global
``is None`` check and ``span()`` yields a no-op context — the hot path
pays one branch, which is the acceptance bar for "zero measurable
overhead with tracing off".

Timestamps are wall-clock microseconds (``time.time()``): the one
clock every fleet process shares, so cross-tier spans land on one
timeline. Within a process, launch-before-finalize ordering (what the
span-derived overlap fraction reads) is preserved because both stamps
come from the same clock in the same thread.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, Optional

ENV_TRACE = 'DCTPU_TRACE'

# Stage-span categories (docs/observability.md#span-model). `cat` is
# 'stage' for pipeline stages, 'request' for per-request tier spans.
STAGE_FEATURIZE = 'featurize'
STAGE_PACK_WAIT = 'pack_wait'
STAGE_H2D = 'h2d_transfer'
STAGE_DEVICE_COMPUTE = 'device_compute'
STAGE_FINALIZE = 'finalize_drain'
STAGE_STITCH = 'stitch'
STAGES = (STAGE_FEATURIZE, STAGE_PACK_WAIT, STAGE_H2D,
          STAGE_DEVICE_COMPUTE, STAGE_FINALIZE, STAGE_STITCH)


class TraceWriter:
  """Appends Chrome trace events to one (possibly shared) file."""

  def __init__(self, path: str, tier: str = ''):
    self.path = path
    self.tier = tier
    self._lock = threading.Lock()
    self._pid = os.getpid()
    try:
      # Exactly one process wins the create and owns the `[` header;
      # everyone else appends events only.
      fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644)
      try:
        os.write(fd, b'[\n')
      finally:
        os.close(fd)
    except FileExistsError:
      pass
    self._fd = os.open(path, os.O_WRONLY | os.O_APPEND)  # guarded by: self._lock
    if tier:
      self._emit_raw({
          'name': 'process_name', 'ph': 'M', 'pid': self._pid, 'tid': 0,
          'args': {'name': f'dctpu-{tier}'},
      })

  def _emit_raw(self, event: Dict[str, Any]) -> None:
    line = (json.dumps(event, separators=(',', ':')) + ',\n').encode()
    with self._lock:
      os.write(self._fd, line)

  def complete_event(self, name: str, cat: str, ts_s: float, dur_s: float,
                     args: Optional[Dict[str, Any]] = None) -> None:
    """One 'X' (complete) event; ts/dur in seconds of time.time()."""
    self._emit_raw({
        'name': name, 'cat': cat, 'ph': 'X',
        'ts': ts_s * 1e6, 'dur': max(0.0, dur_s) * 1e6,
        'pid': self._pid, 'tid': threading.get_ident() & 0xffffffff,
        'args': args or {},
    })

  def close(self) -> None:
    with self._lock:
      if self._fd >= 0:
        os.close(self._fd)
        self._fd = -1


# Module state: one writer per process. `_writer is None` is the
# tracing-off fast path read on every span() call.
# dclint: lock-free (configure() runs at process startup before worker
# threads exist; after that the cell is read-only)
_writer: Optional[TraceWriter] = None
_local = threading.local()


def configure(path: Optional[str], tier: str = '') -> Optional[TraceWriter]:
  """Enables tracing to `path` (None/'' disables). Returns the writer."""
  global _writer
  if _writer is not None:
    _writer.close()
    _writer = None
  if path:
    _writer = TraceWriter(path, tier=tier)
  return _writer


def configure_from_env(tier: str = '') -> Optional[TraceWriter]:
  """Enables tracing when DCTPU_TRACE names a path (fleet processes
  inherit the env var from their spawner — that is how soak_e2e points
  every tier at one shared trace file)."""
  return configure(os.environ.get(ENV_TRACE) or None, tier=tier)


def enabled() -> bool:
  return _writer is not None


def writer() -> Optional[TraceWriter]:
  return _writer


def mint_trace_id() -> str:
  """16-hex-char trace id (half a UUID; collision-safe at fleet scale)."""
  return os.urandom(8).hex()


def set_trace_id(trace_id: Optional[str]) -> None:
  """Binds `trace_id` to the current thread; span() stamps it into
  every event's args until cleared."""
  _local.trace_id = trace_id


def get_trace_id() -> Optional[str]:
  return getattr(_local, 'trace_id', None)


def complete_event(name: str, cat: str, t0: float, t1: float,
                   args: Optional[Dict[str, Any]] = None) -> None:
  """After-the-fact span from two time.time() stamps. No-op when
  tracing is off, so instrumentation sites call it unconditionally."""
  w = _writer
  if w is None:
    return
  args = dict(args or {})
  trace_id = get_trace_id()
  if trace_id and 'trace_id' not in args:
    args['trace_id'] = trace_id
  w.complete_event(name, cat, t0, t1 - t0, args)


@contextlib.contextmanager
def span(name: str, cat: str = 'stage',
         **args: Any) -> Iterator[None]:
  """Context-managed stage span. The tracing-off path is one global
  read and an empty yield."""
  if _writer is None:
    yield
    return
  t0 = time.time()
  try:
    yield
  finally:
    complete_event(name, cat, t0, time.time(), args)
