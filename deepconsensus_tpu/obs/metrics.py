"""Central metrics registry: typed counters/gauges + fixed-bucket
latency histograms.

One ``MetricsRegistry`` per tier process (serve replica, router,
featurize worker, batch run, train loop) replaces the scattered
per-class counter dicts and the sorted-deque percentile math that used
to live in serve/service.py, fleet/router.py and
fleet/featurize_worker.py. All three exposed slightly different
/metricz shapes and all three shared the same nearest-rank bug
(``lat[int(n * 0.99)]`` is the (0.99*n)+1-th order statistic only by
accident and under-reports p99 at small n).

Design points:

* Typed metrics. ``counter`` is a monotone int, ``gauge`` a settable
  float, ``histogram`` a fixed-bucket latency/size distribution
  carrying per-bucket counts plus an exact running sum. The exact sum
  is what lets trace spans reconcile against /metricz: a stage's
  span-duration total and its histogram ``sum`` come from the same
  measured interval, so they must agree to float rounding.
* Nearest-rank percentiles on the histogram: p(q) is the upper bound
  of the first bucket whose cumulative count reaches ``ceil(q * n)``
  (the textbook nearest-rank definition). Bucket granularity bounds
  the error; the deque bug does not come back.
* Thread safety: one registry lock guards the name->metric maps AND
  every metric's mutable cells (metrics share the registry's lock
  rather than carrying one each — observation is a few adds, never
  worth a second acquisition). dclint's guarded-by checker runs over
  this file.
* Prometheus text exposition (``to_prom``) for ``/metricz?format=prom``
  on every tier, using the standard histogram ``_bucket``/``_sum``/
  ``_count`` triplet with cumulative ``le`` labels.

Nothing here imports jax or numpy: the featurize tier is contractually
jax-free and every tier pays only stdlib import cost for metrics.
"""
from __future__ import annotations

import math
import re
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

# Default latency bucket upper bounds (seconds): roughly geometric from
# 1 ms to the serve max deadline (600 s). A +Inf bucket is implicit.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 25.0, 60.0, 120.0, 300.0, 600.0)

_PROM_NAME_RE = re.compile(r'[^a-zA-Z0-9_:]')


def _prom_name(name: str) -> str:
  return _PROM_NAME_RE.sub('_', name)


def prom_counters_text(counters: Dict[str, Any], tier: str = '') -> str:
  """Renders a plain numeric counter dict (quarantine/faults counters
  that predate the registry) as untyped Prometheus samples, so every
  tier's ?format=prom exposes its full /metricz counter surface."""
  label = f'{{tier="{tier}"}}' if tier else ''
  lines: List[str] = []
  for name in sorted(counters):
    value = counters[name]
    if isinstance(value, bool) or not isinstance(value, (int, float)):
      continue
    lines.append(f'{_prom_name(f"dctpu_{name}")}{label} {value}')
  return '\n'.join(lines) + ('\n' if lines else '')


class Counter:
  """Monotone integer counter. Mutate via inc() only."""

  __slots__ = ('name', 'help', '_lock', '_value')

  def __init__(self, name: str, lock: threading.Lock, help: str = ''):
    self.name = name
    self.help = help
    self._lock = lock
    self._value = 0  # guarded by: self._lock

  def inc(self, n: int = 1) -> None:
    with self._lock:
      self._value += n

  @property
  def value(self) -> int:
    with self._lock:
      return self._value


class Gauge:
  """Point-in-time float value (queue depth, overlap fraction, ...)."""

  __slots__ = ('name', 'help', '_lock', '_value')

  def __init__(self, name: str, lock: threading.Lock, help: str = ''):
    self.name = name
    self.help = help
    self._lock = lock
    self._value = 0.0  # guarded by: self._lock

  def set(self, value: float) -> None:
    with self._lock:
      self._value = float(value)

  @property
  def value(self) -> float:
    with self._lock:
      return self._value


class Histogram:
  """Fixed-bucket distribution with exact running sum.

  ``bounds`` are the bucket upper edges (an implicit +Inf bucket
  catches the overflow). observe() is O(log n_buckets).
  """

  __slots__ = ('name', 'help', 'bounds', '_lock', '_counts', '_sum',
               '_count')

  def __init__(self, name: str, lock: threading.Lock,
               bounds: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
               help: str = ''):
    self.name = name
    self.help = help
    self.bounds: Tuple[float, ...] = tuple(sorted(float(b) for b in bounds))
    if not self.bounds:
      # dclint: allow=typed-faults (registry construction is a
      # programming error surface, not the data plane; it fails at
      # process startup before any request exists)
      raise ValueError(f'histogram {name!r} needs at least one bucket')
    self._lock = lock
    self._counts = [0] * (len(self.bounds) + 1)  # guarded by: self._lock
    self._sum = 0.0  # guarded by: self._lock
    self._count = 0  # guarded by: self._lock

  def observe(self, value: float) -> None:
    value = float(value)
    lo, hi = 0, len(self.bounds)
    while lo < hi:
      mid = (lo + hi) // 2
      if value <= self.bounds[mid]:
        hi = mid
      else:
        lo = mid + 1
    with self._lock:
      self._counts[lo] += 1
      self._sum += value
      self._count += 1

  def percentile(self, q: float) -> Optional[float]:
    """Nearest-rank percentile: the upper edge of the first bucket
    whose cumulative count reaches ceil(q * n). None when empty."""
    with self._lock:
      total = self._count
      counts = list(self._counts)
    if not total:
      return None
    rank = max(1, math.ceil(q * total))
    cum = 0
    for i, c in enumerate(counts):
      cum += c
      if cum >= rank:
        if i < len(self.bounds):
          return self.bounds[i]
        return self.bounds[-1]  # +Inf bucket: report the last edge
    return self.bounds[-1]

  def snapshot(self) -> Dict[str, Any]:
    with self._lock:
      counts = list(self._counts)
      total = self._count
      total_sum = self._sum
    return {
        'count': total,
        'sum': round(total_sum, 6),
        'buckets': [[self.bounds[i] if i < len(self.bounds) else 'inf',
                     counts[i]] for i in range(len(counts))],
    }

  def percentiles(self) -> Dict[str, Any]:
    p50 = self.percentile(0.50)
    p99 = self.percentile(0.99)
    with self._lock:
      n = self._count
    return {
        'p50': None if p50 is None else round(p50, 4),
        'p99': None if p99 is None else round(p99, 4),
        'count': n,
    }


class MetricsRegistry:
  """Name -> metric map shared by one tier process.

  Accessors create-on-first-use so instrumentation sites need no
  registration ceremony; convenience ``inc``/``set_gauge``/``observe``
  cover the common one-shot paths. snapshot()/to_prom() render the
  whole registry for /metricz JSON and Prometheus scrapes.
  """

  def __init__(self, tier: str = ''):
    self.tier = tier
    self._lock = threading.Lock()
    self._counters: Dict[str, Counter] = {}  # guarded by: self._lock
    self._gauges: Dict[str, Gauge] = {}  # guarded by: self._lock
    self._histograms: Dict[str, Histogram] = {}  # guarded by: self._lock

  # -- accessors ---------------------------------------------------------

  def counter(self, name: str, help: str = '') -> Counter:
    with self._lock:
      metric = self._counters.get(name)
      if metric is None:
        metric = Counter(name, self._lock, help=help)
        self._counters[name] = metric
      return metric

  def gauge(self, name: str, help: str = '') -> Gauge:
    with self._lock:
      metric = self._gauges.get(name)
      if metric is None:
        metric = Gauge(name, self._lock, help=help)
        self._gauges[name] = metric
      return metric

  def histogram(self, name: str,
                bounds: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
                help: str = '') -> Histogram:
    with self._lock:
      metric = self._histograms.get(name)
    if metric is None:
      # Constructed outside the lock (Histogram.__init__ validates and
      # may raise); the double-checked insert below keeps first-wins.
      candidate = Histogram(name, self._lock, bounds=bounds, help=help)
      with self._lock:
        metric = self._histograms.setdefault(name, candidate)
    return metric

  # -- one-shot mutation helpers ----------------------------------------

  def inc(self, name: str, n: int = 1) -> None:
    self.counter(name).inc(n)

  def set_gauge(self, name: str, value: float) -> None:
    self.gauge(name).set(value)

  def observe(self, name: str, value: float,
              bounds: Sequence[float] = DEFAULT_LATENCY_BUCKETS) -> None:
    self.histogram(name, bounds=bounds).observe(value)

  # -- views -------------------------------------------------------------

  def counter_values(self) -> Dict[str, int]:
    with self._lock:
      metrics = list(self._counters.values())
    return {m.name: m.value for m in metrics}

  def snapshot(self) -> Dict[str, Any]:
    """Plain-dict view for the unified /metricz JSON schema."""
    with self._lock:
      counters = list(self._counters.values())
      gauges = list(self._gauges.values())
      histograms = list(self._histograms.values())
    return {
        'counters': {m.name: m.value for m in counters},
        'gauges': {m.name: round(m.value, 6) for m in gauges},
        'histograms': {m.name: m.snapshot() for m in histograms},
    }

  def latency_summary(self) -> Dict[str, Dict[str, Any]]:
    """Per-histogram nearest-rank percentiles (the /metricz `latency`
    nesting)."""
    with self._lock:
      histograms = list(self._histograms.values())
    return {m.name: m.percentiles() for m in histograms}

  def to_prom(self, tier: Optional[str] = None) -> str:
    """Prometheus text exposition (v0.0.4) of the whole registry."""
    tier = tier if tier is not None else self.tier
    label = f'{{tier="{tier}"}}' if tier else ''
    lines: List[str] = []
    with self._lock:
      counters = list(self._counters.values())
      gauges = list(self._gauges.values())
      histograms = list(self._histograms.values())
    for m in sorted(counters, key=lambda m: m.name):
      name = _prom_name(f'dctpu_{m.name}')
      if m.help:
        lines.append(f'# HELP {name} {m.help}')
      lines.append(f'# TYPE {name} counter')
      lines.append(f'{name}{label} {m.value}')
    for m in sorted(gauges, key=lambda m: m.name):
      name = _prom_name(f'dctpu_{m.name}')
      if m.help:
        lines.append(f'# HELP {name} {m.help}')
      lines.append(f'# TYPE {name} gauge')
      lines.append(f'{name}{label} {m.value}')
    for m in sorted(histograms, key=lambda m: m.name):
      name = _prom_name(f'dctpu_{m.name}')
      snap = m.snapshot()
      if m.help:
        lines.append(f'# HELP {name} {m.help}')
      lines.append(f'# TYPE {name} histogram')
      cum = 0
      for le, count in snap['buckets']:
        cum += count
        le_txt = '+Inf' if le == 'inf' else repr(float(le))
        if tier:
          lines.append(f'{name}_bucket{{tier="{tier}",le="{le_txt}"}} {cum}')
        else:
          lines.append(f'{name}_bucket{{le="{le_txt}"}} {cum}')
      lines.append(f'{name}_sum{label} {snap["sum"]}')
      lines.append(f'{name}_count{label} {snap["count"]}')
    return '\n'.join(lines) + '\n'
