"""On-demand jax.profiler capture.

Two entry points over one guarded capture primitive:

* ``/debugz/profile?seconds=N`` on a serve replica (serve/server.py)
  — an operator points Perfetto at a live replica without restarting
  it;
* SIGUSR2 on batch ``dctpu run`` / ``dctpu train`` — ``kill -USR2``
  a long batch job and collect the device trace it was too late to
  have asked for at launch.

jax is imported lazily inside the capture so this module stays
importable on the jax-free featurize tier, and a concurrent second
capture is refused (jax.profiler supports one active trace per
process) rather than crashing the first.
"""
from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Any, Dict, Optional

log = logging.getLogger(__name__)

_MAX_CAPTURE_S = 120.0

# One capture at a time per process (jax.profiler is a singleton).
_capture_lock = threading.Lock()


def capture_profile(out_dir: str, seconds: float) -> Dict[str, Any]:
  """Runs one bounded jax.profiler trace into `out_dir`.

  Returns a status dict (never raises on an unavailable profiler: the
  debug endpoint reports the problem instead of 500ing a live
  replica). Blocks for `seconds`, so callers own threading.
  """
  seconds = min(max(0.1, float(seconds)), _MAX_CAPTURE_S)
  if not _capture_lock.acquire(blocking=False):
    return {'ok': False, 'error': 'a profiler capture is already running'}
  try:
    try:
      import jax
    except Exception as e:  # dclint: allow=typed-faults (availability
      # probe on a debug endpoint: the error is data, not control flow)
      return {'ok': False, 'error': f'jax unavailable: {e}'}
    os.makedirs(out_dir, exist_ok=True)
    t0 = time.time()
    try:
      jax.profiler.start_trace(out_dir)
      time.sleep(seconds)
      jax.profiler.stop_trace()
    except Exception as e:  # dclint: allow=typed-faults (profiler
      # backends fail in environment-specific ways; the debug endpoint
      # reports them as payload instead of crashing the replica)
      return {'ok': False, 'error': f'{type(e).__name__}: {e}'}
    return {
        'ok': True,
        'out_dir': out_dir,
        'seconds': round(time.time() - t0, 3),
    }
  finally:
    _capture_lock.release()


def install_sigusr2(out_dir: str, seconds: float = 5.0) -> bool:
  """SIGUSR2 -> background jax.profiler capture into `out_dir`.

  Returns False (and stays uninstalled) off the main thread — signal
  handlers can only be set there, and in-process test harnesses drive
  run/train from worker threads.
  """

  def _handler(signum, frame):
    del signum, frame
    thread = threading.Thread(
        target=lambda: log.warning(
            'SIGUSR2 profile capture: %s',
            capture_profile(out_dir, seconds)),
        name='dctpu-profile-capture', daemon=True)
    thread.start()

  try:
    signal.signal(signal.SIGUSR2, _handler)
  except ValueError:  # not the main thread
    return False
  return True
