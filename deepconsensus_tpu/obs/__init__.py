"""Unified observability plane: metrics registry, trace spans,
profiler capture, trace summarization.

``record_stage`` is the one helper every pipeline instrumentation site
calls: it feeds the SAME measured interval to both the stage histogram
(``stage_<name>_s`` on the tier's MetricsRegistry) and the trace span,
which is what makes span-derived per-stage totals reconcile with
/metricz histogram sums by construction.
"""
from __future__ import annotations

from typing import Any, Optional

from deepconsensus_tpu.obs import metrics
from deepconsensus_tpu.obs import profiler
from deepconsensus_tpu.obs import summarize
from deepconsensus_tpu.obs import trace
from deepconsensus_tpu.obs.metrics import (DEFAULT_LATENCY_BUCKETS,
                                           MetricsRegistry)


def stage_histogram_name(stage: str) -> str:
  return f'stage_{stage}_s'


def record_stage(registry: Optional[MetricsRegistry], stage: str,
                 t0: float, t1: float, **args: Any) -> None:
  """Records one pipeline-stage interval [t0, t1] (time.time() stamps)
  as both a histogram observation and a trace span."""
  if registry is not None:
    registry.observe(stage_histogram_name(stage), t1 - t0)
  trace.complete_event(stage, 'stage', t0, t1, args)


__all__ = [
    'DEFAULT_LATENCY_BUCKETS',
    'MetricsRegistry',
    'metrics',
    'profiler',
    'record_stage',
    'stage_histogram_name',
    'summarize',
    'trace',
]
