"""Trace-file summarization: the analysis half of ``dctpu trace``.

Reads a Chrome-trace-event file written by obs.trace (possibly by many
fleet processes appending to one file) and derives:

* per-stage time breakdown — total span-duration and union-of-interval
  coverage per pipeline stage (featurize, pack_wait, h2d_transfer,
  device_compute, finalize_drain, stitch);
* critical-path attribution — each stage's coverage as a fraction of
  the end-to-end wall interval, sorted so the stage that bounds the
  pipeline tops the list (stages overlap by design, so fractions sum
  past 1.0 exactly when the pipeline is doing its job);
* straggler packs — the slowest decile of device_compute spans with
  their bucket / dp / row-count context;
* a span-derived transfer-overlap fraction that must agree with the
  counter-derived ``transfer_overlap_fraction``: a pack's forward
  launch (the device_compute span start) happening strictly BEFORE its
  own finalize_drain span start means a later dispatch launched it —
  the overlapped double-buffer path — while a direct launch happens
  inside finalize. Same pipeline property, measured through a second
  mechanism; disagreement means the instrumentation (or the double
  buffer) broke.

The per-stage totals here and the ``stage_*_s`` histogram sums in
/metricz come from the same measured intervals (obs.record_stage), so
they reconcile within float rounding — bench.py asserts within 1%.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from deepconsensus_tpu import faults as faults_lib
from deepconsensus_tpu.obs import trace as trace_lib


def load_trace(path: str) -> List[Dict[str, Any]]:
  """Parses an obs.trace file into a list of event dicts."""
  events: List[Dict[str, Any]] = []
  try:
    with open(path, 'r', encoding='utf-8') as f:
      lines = f.readlines()
  except OSError as e:
    raise faults_lib.CorruptInputError(
        f'cannot read trace file {path}: {e}') from e
  for i, line in enumerate(lines, start=1):
    text = line.strip()
    if not text or text in ('[', ']'):
      continue
    if text.endswith(','):
      text = text[:-1]
    try:
      event = json.loads(text)
    except ValueError as e:
      raise faults_lib.CorruptInputError(
          f'{path}:{i}: undecodable trace event: {e}') from e
    if not isinstance(event, dict):
      raise faults_lib.CorruptInputError(
          f'{path}:{i}: trace event is not an object')
    events.append(event)
  return events


def _complete_spans(events: List[Dict[str, Any]]
                    ) -> List[Dict[str, Any]]:
  return [e for e in events if e.get('ph') == 'X']


def _union_s(intervals: List[Tuple[float, float]]) -> float:
  """Total length of the union of [start, end) intervals, in seconds
  (inputs in microseconds)."""
  if not intervals:
    return 0.0
  total = 0.0
  cur_lo, cur_hi = None, None
  for lo, hi in sorted(intervals):
    if cur_lo is None:
      cur_lo, cur_hi = lo, hi
    elif lo <= cur_hi:
      cur_hi = max(cur_hi, hi)
    else:
      total += cur_hi - cur_lo
      cur_lo, cur_hi = lo, hi
  total += cur_hi - cur_lo
  return total / 1e6


def tier_names(events: List[Dict[str, Any]]) -> Dict[int, str]:
  """pid -> tier label from process_name metadata events."""
  out: Dict[int, str] = {}
  for e in events:
    if e.get('ph') == 'M' and e.get('name') == 'process_name':
      out[int(e.get('pid', 0))] = str(
          (e.get('args') or {}).get('name', ''))
  return out


def trace_groups(events: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
  """trace_id -> {'pids': sorted pids, 'names': span names, 'n_spans'}.

  The fleet-soak connectivity check: a delivered request's id must
  group spans from every tier it crossed (router -> featurize worker
  -> replica for the bam/1 leg) into ONE connected trace.
  """
  groups: Dict[str, Dict[str, Any]] = {}
  for e in _complete_spans(events):
    trace_id = (e.get('args') or {}).get('trace_id')
    if not trace_id:
      continue
    g = groups.setdefault(str(trace_id),
                          {'pids': set(), 'names': set(), 'n_spans': 0})
    g['pids'].add(int(e.get('pid', 0)))
    g['names'].add(str(e.get('name', '')))
    g['n_spans'] += 1
  return {
      tid: {'pids': sorted(g['pids']), 'names': sorted(g['names']),
            'n_spans': g['n_spans']}
      for tid, g in groups.items()
  }


def span_overlap(events: List[Dict[str, Any]]) -> Dict[str, Any]:
  """Span-derived transfer/compute overlap: per (pid, pack), the
  device_compute span starting strictly before its finalize_drain span
  means the launch was overlapped by a later dispatch."""
  compute_ts: Dict[Tuple[int, Any], float] = {}
  finalize_ts: Dict[Tuple[int, Any], float] = {}
  for e in _complete_spans(events):
    args = e.get('args') or {}
    if 'pack' not in args:
      continue
    key = (int(e.get('pid', 0)), args['pack'])
    if e.get('name') == trace_lib.STAGE_DEVICE_COMPUTE:
      compute_ts[key] = float(e['ts'])
    elif e.get('name') == trace_lib.STAGE_FINALIZE:
      finalize_ts[key] = float(e['ts'])
  n_overlapped = 0
  n_direct = 0
  for key, ts in compute_ts.items():
    fin = finalize_ts.get(key)
    if fin is None:
      # Drain-free pack: a fully device-resident run batches its drain
      # at end-of-input, so the pack has a device_compute span but no
      # finalize_drain span of its own. Its launch was necessarily
      # overlapped — a direct launch only ever happens INSIDE finalize
      # (runner._finalize_sync), which would have emitted the span.
      # Dropping these from the sample (the old behavior) skewed the
      # span-derived fraction low on exactly the runs that overlap
      # best.
      n_overlapped += 1
      continue
    if ts < fin:
      n_overlapped += 1
    else:
      n_direct += 1
  launches = n_overlapped + n_direct
  return {
      'n_packs': launches,
      'n_overlapped': n_overlapped,
      'n_direct': n_direct,
      'span_overlap_fraction': (
          round(n_overlapped / launches, 4) if launches else 0.0),
  }


def device_gaps(events: List[Dict[str, Any]]) -> Dict[str, Any]:
  """Host gaps between consecutive device_compute spans, per pid.

  The device-residency signal for the pack loop: in a fully resident
  run (weights pinned, donated pack buffers cycling device-side) the
  only thing between pack N's compute ending and pack N+1's compute
  starting is the H2D transfer of a later pack's uint8 planes — so
  each gap should be covered by h2d_transfer spans. Residual
  uncovered time (host_gap_s) is host work on the critical path: pack
  assembly stalls, per-pack weight re-transfer, python overhead.
  transfer_only_fraction is the covered share of all gap time (1.0
  when there are no gaps at all)."""
  compute: Dict[int, List[Tuple[float, float]]] = {}
  h2d: Dict[int, List[Tuple[float, float]]] = {}
  for e in _complete_spans(events):
    name = e.get('name')
    if name not in (trace_lib.STAGE_DEVICE_COMPUTE, trace_lib.STAGE_H2D):
      continue
    pid = int(e.get('pid', 0))
    ts = float(e['ts'])
    iv = (ts, ts + float(e.get('dur', 0.0)))
    (compute if name == trace_lib.STAGE_DEVICE_COMPUTE else h2d
     ).setdefault(pid, []).append(iv)
  n_gaps = 0
  gap_s = 0.0
  transfer_s = 0.0
  max_host_gap_s = 0.0
  for pid, intervals in compute.items():
    intervals.sort()
    transfers = h2d.get(pid, [])
    for (_lo_a, hi_a), (lo_b, _hi_b) in zip(intervals, intervals[1:]):
      if lo_b <= hi_a:
        continue  # overlapping/adjacent compute: no host gap at all
      n_gaps += 1
      gap = (lo_b - hi_a) / 1e6
      covered = _union_s([
          (max(lo, hi_a), min(hi, lo_b))
          for lo, hi in transfers if hi > hi_a and lo < lo_b])
      gap_s += gap
      transfer_s += covered
      max_host_gap_s = max(max_host_gap_s, gap - covered)
  host_gap_s = gap_s - transfer_s
  return {
      'n_gaps': n_gaps,
      'gap_s': round(gap_s, 6),
      'transfer_s': round(transfer_s, 6),
      'host_gap_s': round(host_gap_s, 6),
      'max_host_gap_s': round(max_host_gap_s, 6),
      'transfer_only_fraction': (
          round(transfer_s / gap_s, 4) if gap_s else 1.0),
  }


def summarize(events: List[Dict[str, Any]],
              straggler_decile: float = 0.9) -> Dict[str, Any]:
  """Full trace summary (the ``dctpu trace`` payload)."""
  spans = _complete_spans(events)
  if not spans:
    raise faults_lib.CorruptInputError(
        'trace contains no complete (ph=X) spans')
  t_min = min(float(e['ts']) for e in spans)
  t_max = max(float(e['ts']) + float(e.get('dur', 0.0)) for e in spans)
  wall_s = (t_max - t_min) / 1e6

  stage_totals: Dict[str, float] = {}
  stage_counts: Dict[str, int] = {}
  stage_intervals: Dict[str, List[Tuple[float, float]]] = {}
  for e in spans:
    if e.get('cat') != 'stage':
      continue
    name = str(e.get('name', ''))
    ts = float(e['ts'])
    dur = float(e.get('dur', 0.0))
    stage_totals[name] = stage_totals.get(name, 0.0) + dur / 1e6
    stage_counts[name] = stage_counts.get(name, 0) + 1
    stage_intervals.setdefault(name, []).append((ts, ts + dur))

  coverage = {name: _union_s(iv) for name, iv in stage_intervals.items()}
  critical_path = sorted(
      ({'stage': name,
        'coverage_s': round(cov, 6),
        'fraction_of_wall': round(cov / wall_s, 4) if wall_s else 0.0}
       for name, cov in coverage.items()),
      key=lambda row: -row['coverage_s'])

  compute_spans = sorted(
      (e for e in spans
       if e.get('name') == trace_lib.STAGE_DEVICE_COMPUTE),
      key=lambda e: float(e.get('dur', 0.0)))
  stragglers = []
  if compute_spans:
    cut = int(len(compute_spans) * straggler_decile)
    for e in compute_spans[cut:]:
      args = e.get('args') or {}
      stragglers.append({
          'pack': args.get('pack'),
          'dur_s': round(float(e.get('dur', 0.0)) / 1e6, 6),
          'bucket': args.get('bucket'),
          'dp': args.get('dp'),
          'n_rows': args.get('n_rows'),
          'pid': e.get('pid'),
      })
    stragglers.sort(key=lambda row: -row['dur_s'])

  return {
      'n_events': len(events),
      'n_spans': len(spans),
      'wall_s': round(wall_s, 6),
      'tiers': tier_names(events),
      'stage_totals_s': {k: round(v, 6)
                         for k, v in sorted(stage_totals.items())},
      'stage_counts': dict(sorted(stage_counts.items())),
      'stage_coverage_s': {k: round(v, 6)
                           for k, v in sorted(coverage.items())},
      'critical_path': critical_path,
      'stragglers': stragglers,
      'overlap': span_overlap(events),
      'device_gaps': device_gaps(events),
      'n_traces': len(trace_groups(events)),
  }


def format_summary(summary: Dict[str, Any]) -> str:
  """Human-readable rendering for the CLI."""
  lines = [
      f'trace: {summary["n_spans"]} spans over '
      f'{summary["wall_s"]:.3f}s wall',
  ]
  if summary.get('tiers'):
    tiers = ', '.join(f'{pid}={name}'
                      for pid, name in sorted(summary['tiers'].items()))
    lines.append(f'tiers: {tiers}')
  lines.append('per-stage breakdown (critical-path order):')
  totals = summary['stage_totals_s']
  counts = summary['stage_counts']
  for row in summary['critical_path']:
    stage = row['stage']
    lines.append(
        f'  {stage:<16} coverage {row["coverage_s"]:>10.4f}s '
        f'({100 * row["fraction_of_wall"]:5.1f}% of wall)  '
        f'total {totals.get(stage, 0.0):>10.4f}s  '
        f'n={counts.get(stage, 0)}')
  overlap = summary['overlap']
  lines.append(
      f'transfer overlap (span-derived): '
      f'{overlap["n_overlapped"]}/{overlap["n_packs"]} packs '
      f'(fraction {overlap["span_overlap_fraction"]})')
  gaps = summary.get('device_gaps')
  if gaps:
    lines.append(
        f'device gaps: {gaps["n_gaps"]} gaps totalling '
        f'{gaps["gap_s"]:.4f}s, host (non-transfer) '
        f'{gaps["host_gap_s"]:.4f}s, transfer-only fraction '
        f'{gaps["transfer_only_fraction"]} '
        f'(max host gap {gaps["max_host_gap_s"]:.4f}s)')
  if summary['stragglers']:
    lines.append('straggler packs (slowest decile of device compute):')
    for row in summary['stragglers'][:10]:
      lines.append(
          f'  pack {row["pack"]} {row["dur_s"]:.4f}s '
          f'bucket={row["bucket"]} dp={row["dp"]} '
          f'n_rows={row["n_rows"]} pid={row["pid"]}')
  if summary.get('n_traces'):
    lines.append(f'distinct request traces: {summary["n_traces"]}')
  return '\n'.join(lines)
