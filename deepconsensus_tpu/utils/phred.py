"""Phred-quality and sequence helpers (numpy domain).

Behavior parity with reference deepconsensus/utils/utils.py:36-118; jax
variants of left-shift live in models/losses (they operate on device).
"""
from __future__ import annotations

from typing import List, Union

import numpy as np

from deepconsensus_tpu import constants


def encoded_sequence_to_string(encoded_sequence: np.ndarray) -> str:
  """Vocab-int array -> string, e.g. [1,2,0] -> 'AT '."""
  idx = np.asarray(encoded_sequence).astype(np.int64)
  return constants.VOCAB_BYTES[idx].tobytes().decode('ascii')


def encoded_sequence_to_bytes(encoded_sequence: np.ndarray) -> bytes:
  """Vocab-int array -> ASCII bytes in one LUT gather + tobytes(); the
  array-native emit path's counterpart of encoded_sequence_to_string
  (no str round-trip)."""
  idx = np.asarray(encoded_sequence)
  if idx.dtype != np.uint8:
    idx = idx.astype(np.int64)
  return constants.VOCAB_BYTES[idx].tobytes()


def quality_scores_to_bytes(scores: np.ndarray) -> bytes:
  """Phred int array -> FASTQ quality bytes (offset 33), single pass."""
  arr = np.asarray(scores)
  if arr.dtype == np.uint8:
    return (arr + np.uint8(33)).tobytes()
  return (arr.astype(np.int64) + 33).astype(np.uint8).tobytes()


def quality_score_to_string(score: int) -> str:
  """Phred int -> FASTQ char (offset 33)."""
  return chr(score + 33)


def quality_scores_to_string(scores: Union[np.ndarray, List[int]]) -> str:
  """Phred int array -> FASTQ quality string."""
  arr = np.asarray(scores)
  if arr.dtype == np.uint8:
    # Device-epilogue drain path: already the FASTQ byte domain minus
    # the offset — no int64 intermediate.
    return (arr + np.uint8(33)).tobytes().decode('ascii')
  arr = (arr.astype(np.int64) + 33).astype(np.uint8)
  return arr.tobytes().decode('ascii')


def quality_string_to_array(quality_string: str) -> List[int]:
  """FASTQ quality string -> list of phred ints."""
  return [ord(char) - 33 for char in quality_string]


def avg_phred(base_qualities: Union[np.ndarray, List[int]]) -> float:
  """Average quality of a read, computed in probability domain.

  Negative entries encode spacing and are excluded
  (reference: utils.py:88-106).
  """
  base_qualities = np.asarray(base_qualities)
  base_qualities = base_qualities[base_qualities >= 0]
  if not base_qualities.any():
    return 0.0
  probs = 10 ** (base_qualities / -10.0)
  avg_prob = probs.sum() / len(probs)
  return float(-10 * np.log10(avg_prob))


def left_shift_seq(seq: np.ndarray) -> np.ndarray:
  """Moves all gap tokens to the end, preserving base order."""
  return np.concatenate(
      [seq[seq != constants.GAP_INT], seq[seq == constants.GAP_INT]]
  )


def left_shift(batch_seq: np.ndarray, axis: int = 1) -> np.ndarray:
  """Batched left_shift_seq via the two-stage sort trick (vectorized;
  same semantics as the per-row concatenate, and the numpy twin of
  losses.left_shift_sequence)."""
  if axis != 1 or batch_seq.ndim != 2:
    return np.apply_along_axis(left_shift_seq, axis, batch_seq)
  length = batch_seq.shape[1]
  ixs = np.broadcast_to(np.arange(length), batch_seq.shape)
  order = np.sort(
      np.where(batch_seq != constants.GAP_INT, ixs, length + ixs), axis=1
  )
  order = np.where(order < length, order, order - length)
  return np.take_along_axis(batch_seq, order, axis=1)
