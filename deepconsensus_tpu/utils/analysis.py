"""Error-analysis helpers (notebook/colab-style utilities).

Counterpart of the reference's colab utilities (reference:
deepconsensus/utils/colab_utils.py:28-159): run a model over example
dicts, pretty-print base-level diffs, and summarize error k-mers.
"""
from __future__ import annotations

import collections
from typing import Dict, Iterable, List, Tuple

import numpy as np

from deepconsensus_tpu.utils import phred


def get_prediction(model_apply, variables, rows: np.ndarray) -> Dict:
  """Runs the model on one example's rows; returns bases + qualities."""
  import jax.numpy as jnp

  preds = np.asarray(model_apply(variables, jnp.asarray(rows[None])))[0]
  pred_ids = preds.argmax(-1)
  error_prob = np.maximum(1 - preds.max(-1), 1e-12)
  quals = np.minimum(-10 * np.log10(error_prob), 93).round().astype(int)
  return {
      'probabilities': preds,
      'sequence': phred.encoded_sequence_to_string(pred_ids),
      'quality_scores': quals,
  }


def diff_strings(truth: str, pred: str) -> List[Tuple[int, str, str]]:
  """Positions where truth and prediction disagree."""
  out = []
  for i, (t, p) in enumerate(zip(truth, pred)):
    if t != p:
      out.append((i, t, p))
  return out


def format_diff(truth: str, pred: str, width: int = 80) -> str:
  """Three-line alignment view with carets at mismatches."""
  lines = []
  for start in range(0, max(len(truth), len(pred)), width):
    t = truth[start : start + width]
    p = pred[start : start + width]
    marks = ''.join(
        '^' if i < len(t) and i < len(p) and t[i] != p[i] else ' '
        for i in range(max(len(t), len(p)))
    )
    lines.extend([f'truth {t}', f'pred  {p}', f'      {marks}'])
  return '\n'.join(lines)


def error_kmers(
    truth: str, pred: str, k: int = 5
) -> collections.Counter:
  """Counts truth-context k-mers centered on mismatch positions."""
  counter: collections.Counter = collections.Counter()
  half = k // 2
  for pos, _, _ in diff_strings(truth, pred):
    lo = max(pos - half, 0)
    kmer = truth[lo : lo + k]
    if len(kmer) == k:
      counter[kmer] += 1
  return counter


def summarize_errors(
    pairs: Iterable[Tuple[str, str]], k: int = 5, top: int = 20
) -> List[Tuple[str, int]]:
  """Aggregates the most error-prone k-mer contexts across reads."""
  total: collections.Counter = collections.Counter()
  for truth, pred in pairs:
    total.update(error_kmers(truth, pred, k))
  return total.most_common(top)
