"""Error-analysis helpers (notebook/colab-style utilities).

Counterpart of the reference's colab utilities (reference:
deepconsensus/utils/colab_utils.py:28-159): run a model over example
dicts, pretty-print base-level diffs, and summarize error k-mers.
"""
from __future__ import annotations

import collections
from typing import Dict, Iterable, List, Tuple

import numpy as np

from deepconsensus_tpu import constants
from deepconsensus_tpu.utils import phred


def get_prediction(model_apply, variables, rows: np.ndarray) -> Dict:
  """Runs the model on one example's rows; returns bases + qualities."""
  import jax.numpy as jnp

  preds = np.asarray(model_apply(variables, jnp.asarray(rows[None])))[0]
  pred_ids = preds.argmax(-1)
  error_prob = np.maximum(1 - preds.max(-1), 1e-12)
  quals = np.minimum(-10 * np.log10(error_prob), 93).round().astype(int)
  return {
      'probabilities': preds,
      'sequence': phred.encoded_sequence_to_string(pred_ids),
      'quality_scores': quals,
  }


def diff_strings(truth: str, pred: str) -> List[Tuple[int, str, str]]:
  """Positions where truth and prediction disagree."""
  out = []
  for i, (t, p) in enumerate(zip(truth, pred)):
    if t != p:
      out.append((i, t, p))
  return out


def format_diff(truth: str, pred: str, width: int = 80) -> str:
  """Three-line alignment view with carets at mismatches."""
  lines = []
  for start in range(0, max(len(truth), len(pred)), width):
    t = truth[start : start + width]
    p = pred[start : start + width]
    marks = ''.join(
        '^' if i < len(t) and i < len(p) and t[i] != p[i] else ' '
        for i in range(max(len(t), len(p)))
    )
    lines.extend([f'truth {t}', f'pred  {p}', f'      {marks}'])
  return '\n'.join(lines)


def error_kmers(
    truth: str, pred: str, k: int = 5
) -> collections.Counter:
  """Counts truth-context k-mers centered on mismatch positions."""
  counter: collections.Counter = collections.Counter()
  half = k // 2
  for pos, _, _ in diff_strings(truth, pred):
    lo = max(pos - half, 0)
    kmer = truth[lo : lo + k]
    if len(kmer) == k:
      counter[kmer] += 1
  return counter


def summarize_errors(
    pairs: Iterable[Tuple[str, str]], k: int = 5, top: int = 20
) -> List[Tuple[str, int]]:
  """Aggregates the most error-prone k-mer contexts across reads."""
  total: collections.Counter = collections.Counter()
  for truth, pred in pairs:
    total.update(error_kmers(truth, pred, k))
  return total.most_common(top)


def edit_distance(s1: str, s2: str) -> int:
  """Levenshtein distance between two sequences, gaps stripped first
  (reference: model_inference_transforms.py:35-69). Vectorized over the
  DP rows with numpy instead of the reference's per-cell Python loop.
  """
  s1 = s1.replace(constants.GAP, '')
  s2 = s2.replace(constants.GAP, '')
  # Vector axis = the longer string; the Python loop runs over the
  # shorter one.
  if len(s1) < len(s2):
    s1, s2 = s2, s1
  if not s2:
    return len(s1)
  a = np.frombuffer(s1.encode('ascii'), dtype=np.uint8)
  b = np.frombuffer(s2.encode('ascii'), dtype=np.uint8)
  prev = np.arange(a.size + 1, dtype=np.int64)
  idx = np.arange(1, a.size + 1)
  for i, c in enumerate(b):
    subst = prev[:-1] + (a != c)
    delete = prev[1:] + 1
    cur = np.minimum(subst, delete)
    # Insertion carries a left-to-right dependency; numpy's running
    # minimum over (cur - index) linearizes it.
    cur = np.minimum.accumulate(
        np.minimum(cur, np.concatenate(([i + 1], cur[:-1] + 1))) - idx
    ) + idx
    prev = np.concatenate(([i + 1], cur))
  return int(prev[-1])


def homopolymer_content(seq: str) -> float:
  """Fraction of the sequence inside homopolymer runs of length >= 3
  (reference: model_inference_transforms.py:72-79)."""
  seq = seq.replace(constants.GAP, '')
  if not seq:
    return 0.0
  arr = np.frombuffer(seq.encode('ascii'), dtype=np.uint8)
  boundaries = np.flatnonzero(np.diff(arr) != 0)
  run_lengths = np.diff(
      np.concatenate(([0], boundaries + 1, [arr.size]))
  )
  hp = int(run_lengths[run_lengths >= 3].sum())
  return round(hp / arr.size, 2)
