from deepconsensus_tpu.utils.phred import (  # noqa: F401
    avg_phred,
    encoded_sequence_to_string,
    left_shift,
    left_shift_seq,
    quality_score_to_string,
    quality_scores_to_string,
    quality_string_to_array,
)
