"""Weighted least-loaded replica pick with bounded in-flight counts
and weighted-fair multi-tenant admission.

The placement score is work-per-capacity: (router in-flight + replica
queue depth) / mesh_dp, with a degraded replica (its mesh stepped down
a dp level but /readyz stays green) weighted at half capacity so the
healthy replicas absorb more of the load. queue_depth comes from the
registry's cached /metricz probe, in_flight is the router's own
ground truth — together they see both work this router placed and
work other routers/clients placed directly.

In-flight is bounded per replica at max_inflight * mesh_dp: one slow
replica saturates its own bound and the pick moves on; when every
eligible replica of the tier is at its bound the fleet is saturated
and the caller sheds with a typed FleetRejection (503, transient).

Multi-tenant QoS. Every acquire carries a priority class and a client
id; admission is two-layered:

  * per-client quota: a client already holding `client_quota`
    concurrent requests is shed with a typed QuotaExceededError (429,
    transient) before it can touch fleet capacity — one tenant's
    runaway concurrency is charged to that tenant alone.
  * weighted fair queueing: when every eligible replica is at its
    in-flight bound, acquirers wait (bounded by queue_wait_s) in
    start-time-fair-queueing order. Each waiter gets a virtual finish
    time vft = max(tier virtual time, its class's last vft) +
    1/weight, and a freed slot goes to the smallest vft that can
    actually place (a waiter whose exclusions block it does not
    head-of-line-block the rest). A saturating weight-1 bulk stream
    therefore cannot starve a weight-4 interactive trickle: the
    interactive waiter's vft lands ahead of the queued bulk backlog,
    so it is served within about one slot turnover. Per-class queue
    depth is bounded (max_queued_per_class); the class that overflows
    its own queue is the class that sheds — a typed FleetRejection
    naming the class, never a penalty on the others.

With queue_wait_s=0 (the construction default) admission never waits
and the balancer behaves exactly as before QoS existed: a saturated
acquire sheds immediately with the tier-level FleetRejection.

acquire() and its in-flight increment are one atomic step under the
registry lock (the QoS condition variable wraps the same lock): two
handler threads can't both claim the last slot.
"""
from __future__ import annotations

import bisect
import dataclasses
import threading
import time
from typing import Any, Dict, Iterable, Optional

from deepconsensus_tpu import faults as shared_faults
from deepconsensus_tpu.fleet import registry as registry_lib

# Priority-class defaults: unlabeled traffic is interactive (old
# clients predate classes and are human-facing); bulk backfill must
# label itself to get bulk treatment.
DEFAULT_CLASS = 'interactive'
DEFAULT_CLASS_WEIGHTS: Dict[str, float] = {'interactive': 4.0, 'bulk': 1.0}


class _Waiter:
  """One parked acquire: its WFQ finish time plus what it needs to
  place. Ordered by (vft, seq) — seq breaks ties FIFO."""

  __slots__ = ('vft', 'seq', 'klass', 'excluded')

  def __init__(self, vft: float, seq: int, klass: str, excluded: set):
    self.vft = vft
    self.seq = seq
    self.klass = klass
    self.excluded = excluded

  def __lt__(self, other: '_Waiter') -> bool:
    return (self.vft, self.seq) < (other.vft, other.seq)


class LeastLoadedBalancer:

  def __init__(self, registry: registry_lib.ReplicaRegistry,
               max_inflight: int = 8,
               class_weights: Optional[Dict[str, float]] = None,
               default_class: str = DEFAULT_CLASS,
               client_quota: int = 0,
               queue_wait_s: float = 0.0,
               max_queued_per_class: int = 16):
    self._registry = registry
    self.max_inflight = max_inflight
    self.class_weights = dict(class_weights or DEFAULT_CLASS_WEIGHTS)
    self.default_class = default_class
    self.client_quota = client_quota
    self.queue_wait_s = queue_wait_s
    self.max_queued_per_class = max_queued_per_class
    # QoS state shares the registry lock (the condition wraps it), so
    # a grant and its in-flight/accounting increments stay one atomic
    # step with the replica pick.
    self._cond = threading.Condition(registry.lock)
    self._waiters: Dict[str, list] = {}  # guarded by: self._registry.lock
    self._vtime: Dict[str, float] = {}  # guarded by: self._registry.lock
    self._last_vft: Dict[Any, float] = {}  # guarded by: self._registry.lock
    self._class_inflight: Dict[str, int] = {}  # guarded by: self._registry.lock
    self._client_inflight: Dict[str, int] = {}  # guarded by: self._registry.lock
    self._seq = 0  # guarded by: self._registry.lock

  def weight(self, klass: str) -> float:
    return max(0.001, float(self.class_weights.get(klass, 1.0)))

  def _bound(self, replica: registry_lib.Replica) -> int:
    return self.max_inflight * max(1, replica.mesh_dp)

  def _score(self, replica: registry_lib.Replica) -> float:
    weight = max(1, replica.mesh_dp) * (0.5 if replica.degraded else 1.0)
    return (replica.in_flight + replica.queue_depth) / weight

  # -- placement ---------------------------------------------------------

  def _try_pick(self, tier: str,
                excluded: set) -> Optional[registry_lib.Replica]:
    """The least-loaded READY open-slot replica, or None. Caller holds
    the registry lock; the returned replica is the LIVE object (the
    caller claims its slot under the same lock hold)."""
    open_slots = [
        r for r in self._registry._replicas.values()
        if r.tier == tier and r.state == registry_lib.ReplicaState.READY
        and r.url not in excluded and r.in_flight < self._bound(r)
    ]
    if not open_slots:
      return None
    return min(open_slots, key=lambda r: (self._score(r), r.url))

  def _saturation_error(self, tier: str,
                        excluded: set) -> shared_faults.FleetRejection:
    """The typed rejection for an acquire that cannot place (and, with
    queue_wait_s=0, will not wait). Caller holds the registry lock."""
    tier_members = [
        r for r in self._registry._replicas.values() if r.tier == tier
    ]
    if not tier_members:
      return shared_faults.FleetRejection(
          f'no {tier} replicas registered')
    candidates = [
        r for r in tier_members
        if r.state == registry_lib.ReplicaState.READY
        and r.url not in excluded
    ]
    if not candidates:
      return shared_faults.FleetRejection(
          f'no {tier} replica is ready '
          f'({self._describe(tier_members, excluded)})')
    return shared_faults.FleetRejection(
        f'all {len(candidates)} ready {tier} replica(s) are at '
        f'their in-flight bound (max_inflight={self.max_inflight} '
        'per dp)')

  def _grant(self, replica: registry_lib.Replica, klass: str,
             client: Optional[str]) -> registry_lib.Replica:
    """Claims one slot + the class/client accounting. Caller holds the
    registry lock and passes the live replica object."""
    replica.in_flight += 1
    replica.n_routed += 1
    self._class_inflight[klass] = self._class_inflight.get(klass, 0) + 1
    if client is not None:
      self._client_inflight[client] = (
          self._client_inflight.get(client, 0) + 1)
    return dataclasses.replace(replica)

  # -- admission ---------------------------------------------------------

  def acquire(self, tier: str, exclude: Iterable[str] = (),
              klass: Optional[str] = None,
              client: Optional[str] = None) -> registry_lib.Replica:
    """Picks the least-loaded READY replica of `tier` (skipping urls in
    `exclude` — the retry path never re-picks a replica it already
    tried) and claims one in-flight slot on it, charging the slot to
    `klass`/`client`. Raises QuotaExceededError when the client is at
    its quota, and FleetRejection when no replica is eligible — after
    a weighted-fair wait of up to queue_wait_s when waiting is on."""
    excluded = set(exclude)
    klass = klass or self.default_class
    with self._cond:
      if client is not None and self.client_quota > 0:
        if self._client_inflight.get(client, 0) >= self.client_quota:
          raise shared_faults.QuotaExceededError(
              f'client {client!r} is at its quota of '
              f'{self.client_quota} concurrent request(s)')
      queue = self._waiters.setdefault(tier, [])
      if not queue:
        replica = self._try_pick(tier, excluded)
        if replica is not None:
          return self._grant(replica, klass, client)
      if self.queue_wait_s <= 0:
        # dclint: allow=typed-faults (_saturation_error builds a typed
        # FleetRejection — the helper exists so the wait path below can
        # reuse the same message taxonomy)
        raise self._saturation_error(tier, excluded)
      if sum(1 for w in queue if w.klass == klass) >= \
          self.max_queued_per_class:
        raise shared_faults.FleetRejection(
            f'{tier} tier: class {klass!r} admission queue is full '
            f'({self.max_queued_per_class} waiting) — shedding the '
            'overflowing class only')
      self._seq += 1
      vft = max(self._vtime.get(tier, 0.0),
                self._last_vft.get((tier, klass), 0.0)
                ) + 1.0 / self.weight(klass)
      self._last_vft[(tier, klass)] = vft
      waiter = _Waiter(vft, self._seq, klass, excluded)
      bisect.insort(queue, waiter)
      deadline = time.monotonic() + self.queue_wait_s
      try:
        while True:
          replica = self._try_pick(tier, excluded)
          if replica is not None and not any(
              w is not waiter and w < waiter
              and self._try_pick(tier, w.excluded) is not None
              for w in queue):
            # Smallest placeable vft: take the slot and advance the
            # tier's virtual clock to this grant.
            queue.remove(waiter)
            self._vtime[tier] = max(self._vtime.get(tier, 0.0), vft)
            return self._grant(replica, klass, client)
          remaining = deadline - time.monotonic()
          if remaining <= 0:
            queue.remove(waiter)
            raise shared_faults.FleetRejection(
                f'{tier} tier saturated: class {klass!r} request shed '
                f'after a {self.queue_wait_s:.1f}s weighted-fair wait')
          # Short recheck period: replica state also changes on probe
          # cycles, which don't notify the condition.
          self._cond.wait(timeout=min(remaining, 0.05))
      except BaseException:
        if waiter in queue:
          queue.remove(waiter)
        raise

  def release(self, url: str, outcome: str,
              klass: Optional[str] = None,
              client: Optional[str] = None) -> None:
    """Returns a slot and its class/client accounting. outcome: 'ok' |
    'reject' (upstream typed 4xx/5xx rejection) | 'send_failure'
    (never acked) | 'lost' (acked, replica died)."""
    klass = klass or self.default_class
    with self._cond:
      replica = self._registry._replicas.get(url)
      if replica is not None:
        replica.in_flight = max(0, replica.in_flight - 1)
        if outcome == 'ok':
          replica.n_ok += 1
        elif outcome == 'reject':
          replica.n_upstream_rejects += 1
        elif outcome == 'send_failure':
          replica.n_send_failures += 1
        elif outcome == 'lost':
          replica.n_lost += 1
      held = self._class_inflight.get(klass, 0)
      if held > 0:
        self._class_inflight[klass] = held - 1
      if client is not None:
        held = self._client_inflight.get(client, 0)
        if held <= 1:
          self._client_inflight.pop(client, None)
        else:
          self._client_inflight[client] = held - 1
      self._cond.notify_all()

  # -- views -------------------------------------------------------------

  def qos_snapshot(self) -> Dict[str, Any]:
    """The admission-policy view the router's /metricz publishes."""
    with self._registry.lock:
      return {
          'class_weights': dict(self.class_weights),
          'default_class': self.default_class,
          'client_quota': self.client_quota,
          'queue_wait_s': self.queue_wait_s,
          'max_queued_per_class': self.max_queued_per_class,
          'class_in_flight': {
              k: v for k, v in sorted(self._class_inflight.items()) if v
          },
          'queued': {
              tier: len(q) for tier, q in self._waiters.items() if q
          },
          'clients_in_flight': len(self._client_inflight),
      }

  @staticmethod
  def _describe(members, excluded) -> str:
    parts = []
    for r in members:
      note = ' (excluded)' if r.url in excluded else ''
      parts.append(f'{r.url}={r.state}{note}')
    return ', '.join(sorted(parts))
