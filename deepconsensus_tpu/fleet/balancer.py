"""Weighted least-loaded replica pick with bounded in-flight counts.

The score is work-per-capacity: (router in-flight + replica queue
depth) / mesh_dp, with a degraded replica (its mesh stepped down a dp
level but /readyz stays green) weighted at half capacity so the
healthy replicas absorb more of the load. queue_depth comes from the
registry's cached /metricz probe, in_flight is the router's own
ground truth — together they see both work this router placed and
work other routers/clients placed directly.

In-flight is bounded per replica at max_inflight * mesh_dp: one slow
replica saturates its own bound and the pick moves on; when every
eligible replica of the tier is at its bound the fleet is saturated
and the caller sheds with a typed FleetRejection (503, transient) —
the router never queues, so backpressure reaches clients immediately.

acquire() and its in-flight increment are one atomic step under the
registry lock: two handler threads can't both claim the last slot.
"""
from __future__ import annotations

import dataclasses
from typing import Iterable

from deepconsensus_tpu import faults as shared_faults
from deepconsensus_tpu.fleet import registry as registry_lib


class LeastLoadedBalancer:

  def __init__(self, registry: registry_lib.ReplicaRegistry,
               max_inflight: int = 8):
    self._registry = registry
    self.max_inflight = max_inflight

  def _bound(self, replica: registry_lib.Replica) -> int:
    return self.max_inflight * max(1, replica.mesh_dp)

  def _score(self, replica: registry_lib.Replica) -> float:
    weight = max(1, replica.mesh_dp) * (0.5 if replica.degraded else 1.0)
    return (replica.in_flight + replica.queue_depth) / weight

  def acquire(self, tier: str,
              exclude: Iterable[str] = ()) -> registry_lib.Replica:
    """Picks the least-loaded READY replica of `tier` (skipping urls in
    `exclude` — the retry path never re-picks a replica it already
    tried) and claims one in-flight slot on it. Raises FleetRejection
    when no replica is eligible or every eligible one is at its
    in-flight bound."""
    excluded = set(exclude)
    with self._registry.lock:
      tier_members = [
          r for r in self._registry._replicas.values() if r.tier == tier
      ]
      candidates = [
          r for r in tier_members
          if r.state == registry_lib.ReplicaState.READY
          and r.url not in excluded
      ]
      open_slots = [r for r in candidates if r.in_flight < self._bound(r)]
      if not open_slots:
        if not tier_members:
          raise shared_faults.FleetRejection(
              f'no {tier} replicas registered')
        if not candidates:
          raise shared_faults.FleetRejection(
              f'no {tier} replica is ready '
              f'({self._describe(tier_members, excluded)})')
        raise shared_faults.FleetRejection(
            f'all {len(candidates)} ready {tier} replica(s) are at '
            f'their in-flight bound (max_inflight={self.max_inflight} '
            'per dp)')
      best = min(open_slots, key=lambda r: (self._score(r), r.url))
      best.in_flight += 1
      best.n_routed += 1
      return dataclasses.replace(best)

  def release(self, url: str, outcome: str) -> None:
    """Returns a slot. outcome: 'ok' | 'reject' (upstream typed 4xx/
    5xx rejection) | 'send_failure' (never acked) | 'lost' (acked,
    replica died)."""
    with self._registry.lock:
      replica = self._registry._replicas.get(url)
      if replica is None:
        return
      replica.in_flight = max(0, replica.in_flight - 1)
      if outcome == 'ok':
        replica.n_ok += 1
      elif outcome == 'reject':
        replica.n_upstream_rejects += 1
      elif outcome == 'send_failure':
        replica.n_send_failures += 1
      elif outcome == 'lost':
        replica.n_lost += 1

  @staticmethod
  def _describe(members, excluded) -> str:
    parts = []
    for r in members:
      note = ' (excluded)' if r.url in excluded else ''
      parts.append(f'{r.url}={r.state}{note}')
    return ', '.join(sorted(parts))
