"""`dctpu featurize-worker`: the CPU tier of the disaggregated fleet.

Accepts bam/1 frames (one molecule's subreads-to-CCS mini BAM plus
its draft-CCS mini BAM, as raw file bytes) on POST /v1/featurize and
answers with the same molecule featurized: a compact features/1 uint8
pack when the window tensor is losslessly packable, else the legacy
float32 request frame. Either answer is a valid /v1/polish body, so
the router forwards it to a model replica untouched.

Decode and pileup run through the exact machinery the batch pipeline
uses — io.bam's bounded readers via preprocess.create_proc_feeder,
then reads_to_pileup/iter_window_features — so the features this tier
ships are byte-identical to what a monolithic `dctpu run`/client-side
featurize would have produced; the model replica's ingest (triage,
format, pack) is unchanged. Nothing here imports jax: this role runs
on plain CPU boxes and scales horizontally.

Same HTTP conventions as serve/server.py: ThreadingHTTPServer,
absolute read deadlines, typed JSON errors, SIGTERM drain with
/readyz flipping to draining first.
"""
from __future__ import annotations

import dataclasses
import io
import json
import logging
import os
import shutil
import socket
import tempfile
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

from deepconsensus_tpu import faults as shared_faults
from deepconsensus_tpu import obs as obs_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.preprocess import (
    FeatureLayout,
    create_proc_feeder,
    reads_to_pileup,
)
from deepconsensus_tpu.serve import protocol
from deepconsensus_tpu.serve.server import _DeadlineSocketIO, _StopFlag

log = logging.getLogger(__name__)


@dataclasses.dataclass
class FeaturizeWorkerOptions:
  max_passes: int = 20
  max_length: int = config_lib.DEFAULT_MAX_LENGTH
  use_ccs_bq: bool = False
  window_buckets: Tuple[int, ...] = ()
  ins_trim: int = 0
  use_ccs_smart_windows: bool = False
  work_dir: Optional[str] = None     # scratch for per-request mini BAMs
  compact: bool = True               # prefer features/1 uint8 packs
  max_body_bytes: int = 64 << 20
  io_timeout_s: float = 20.0


class FeaturizeService:
  """bam/1 bytes -> features body. Handler threads call featurize()
  concurrently; the shared counters sit under one lock."""

  def __init__(self, options: FeaturizeWorkerOptions):
    self.options = options
    self.layout = FeatureLayout(
        options.max_passes, options.max_length, options.use_ccs_bq,
        window_buckets=options.window_buckets or None)
    self._lock = threading.Lock()
    # Central metrics registry (obs/metrics.py): counters pre-created
    # so /metricz always exposes the full set; the request-latency
    # histogram replaces the deque percentile math.
    self.obs = obs_lib.MetricsRegistry(tier='featurize')
    for key in ('n_requests', 'n_featurized', 'n_windows',
                'n_packed_compact', 'n_packed_float', 'n_bad_requests'):
      self.obs.counter(key)
    self._latency_hist = self.obs.histogram(
        'featurize_request_latency_s',
        help='bam/1 decode + featurize latency per request')
    self._in_flight = 0  # guarded by: self._lock
    self._draining = False  # dclint: lock-free (monotonic bool flip;
    # an admission racing the flip finishes normally before drain())

  def bump(self, key: str, n: int = 1) -> None:
    self.obs.inc(key, n)

  def featurize(self, body: bytes,
                trace_id: Optional[str] = None) -> bytes:
    """One bam/1 request -> one /v1/polish-ready body. Raises typed
    ServeRejection subtypes on anything malformed."""
    if self._draining:
      raise shared_faults.DrainingError('featurize worker is draining')
    self.bump('n_requests')
    with self._lock:
      self._in_flight += 1
    t0 = time.monotonic()
    t_wall = time.time()
    try:
      req = protocol.decode_bam_request(body)
      features = self._featurize_bam(req)
      pack: Optional[bytes] = None
      if self.options.compact:
        pack = protocol.features_pack_from_features(features)
      if pack is not None:
        self.bump('n_packed_compact')
      else:
        pack = protocol.request_from_features(features)
        self.bump('n_packed_float')
      self.bump('n_featurized')
      self.bump('n_windows', len(features))
      self._latency_hist.observe(time.monotonic() - t0)
      return pack
    except shared_faults.ServeRejection:
      self.bump('n_bad_requests')
      raise
    finally:
      with self._lock:
        self._in_flight -= 1
      # The worker's leg of the cross-tier trace: the featurize stage
      # span carries the router-minted trace id.
      obs_lib.record_stage(self.obs, obs_lib.trace.STAGE_FEATURIZE,
                           t_wall, time.time(), trace_id=trace_id)

  def _featurize_bam(self, req: Dict[str, Any]):
    """Runs the hardened feeder over the request's mini BAMs. The
    bytes land in per-request temp files because the BAM readers are
    file-based; they live under work_dir (tmpfs in production) for
    the few ms of the decode."""
    tmpdir = tempfile.mkdtemp(prefix='dctpu_featurize_',
                              dir=self.options.work_dir)
    try:
      subreads_path = os.path.join(tmpdir, 'subreads_to_ccs.bam')
      ccs_path = os.path.join(tmpdir, 'ccs.bam')
      with open(subreads_path, 'wb') as f:
        f.write(req['subreads_bam'])
      with open(ccs_path, 'wb') as f:
        f.write(req['ccs_bam'])
      try:
        feeder, _counter = create_proc_feeder(
            subreads_to_ccs=subreads_path,
            ccs_bam=ccs_path,
            layout=self.layout,
            ins_trim=self.options.ins_trim,
            use_ccs_smart_windows=self.options.use_ccs_smart_windows,
        )
        molecules = []
        for zmw_input in feeder():
          subreads, name, layout, _split, window_widths = zmw_input
          pileup = reads_to_pileup(subreads, name, layout, window_widths)
          molecules.append(list(pileup.iter_window_features()))
          if len(molecules) > 1:
            break
      except shared_faults.ServeRejection:
        raise
      except Exception as e:
        # Corrupt/truncated BAM bytes, unpaired records, expansion
        # failures: all client-data problems at this boundary.
        raise shared_faults.BadRequestError(
            f'featurize failed for {req["name"] or "<unnamed>"}: '
            f'{type(e).__name__}: {e}') from e
      if not molecules or not molecules[0]:
        raise shared_faults.BadRequestError(
            f'bam/1 payload for {req["name"] or "<unnamed>"} yielded '
            'no featurizable molecule')
      if len(molecules) > 1:
        raise shared_faults.BadRequestError(
            'bam/1 carries more than one molecule; send one request '
            'per ZMW (the /v1/polish contract)')
      return molecules[0]
    finally:
      shutil.rmtree(tmpdir, ignore_errors=True)

  # -- lifecycle / views -------------------------------------------------

  def begin_drain(self) -> None:
    self._draining = True

  def drain(self, timeout: float = 60.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
      with self._lock:
        if self._in_flight == 0:
          return True
      time.sleep(0.05)
    return False

  @property
  def ready(self) -> bool:
    return not self._draining

  def prom_text(self) -> str:
    """/metricz?format=prom payload."""
    return self.obs.to_prom('featurize')

  def stats(self) -> Dict[str, Any]:
    counters = self.obs.counter_values()
    registry_view = self.obs.snapshot()
    with self._lock:
      in_flight = self._in_flight
    return {
        # Unified cross-tier schema (docs/observability.md).
        'tier': 'featurize',
        'outstanding': in_flight,
        'draining': self._draining,
        'ready': self.ready,
        'counters': counters,
        'histograms': registry_view['histograms'],
        'latency': self._latency_hist.percentiles(),
    }


def _make_handler(service: FeaturizeService):
  opts = service.options

  class Handler(BaseHTTPRequestHandler):
    server_version = 'dctpu-featurize/1'
    protocol_version = 'HTTP/1.1'

    def setup(self):
      super().setup()
      self.connection.settimeout(opts.io_timeout_s)
      self._raw_in = _DeadlineSocketIO(self.connection, opts.io_timeout_s)
      self.rfile = io.BufferedReader(self._raw_in)

    def handle_one_request(self):
      self._raw_in.reset_deadline()
      super().handle_one_request()

    def log_message(self, fmt, *args):
      log.debug('%s %s', self.address_string(), fmt % args)

    def _reply(self, status: int, body: bytes,
               content_type: str = 'application/json') -> None:
      try:
        self.send_response(status)
        self.send_header('Content-Type', content_type)
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)
      except (BrokenPipeError, ConnectionResetError, socket.timeout,
              TimeoutError):
        self.close_connection = True

    def _reply_json(self, status: int, obj: Dict[str, Any]) -> None:
      self._reply(status, json.dumps(obj).encode())

    def _reply_error(self, e: shared_faults.ServeRejection) -> None:
      self._reply_json(
          e.http_status,
          {'error': str(e), 'kind': e.kind, 'status': e.http_status})

    def do_GET(self):
      path, _, query = self.path.partition('?')
      params_qs = urllib.parse.parse_qs(query)
      if path == '/healthz':
        self._reply_json(200, {'ok': True})
      elif path == '/readyz':
        if service.ready:
          self._reply_json(200, {'ready': True, 'tier': 'featurize'})
        else:
          self._reply_json(503, {'ready': False, 'tier': 'featurize',
                                 'draining': service._draining})
      elif path == '/metricz':
        if params_qs.get('format', [''])[0] == 'prom':
          self._reply(200, service.prom_text().encode(),
                      content_type='text/plain; version=0.0.4')
        else:
          self._reply_json(200, service.stats())
      else:
        self._reply_json(404, {'error': f'no such path: {self.path}'})

    def do_POST(self):
      if self.path != '/v1/featurize':
        self._reply_json(404, {'error': f'no such path: {self.path}'})
        return
      try:
        length = int(self.headers.get('Content-Length', ''))
      except ValueError:
        self._reply_json(411, {'error': 'Content-Length required'})
        return
      if length > opts.max_body_bytes:
        self.close_connection = True
        self._reply_error(shared_faults.RequestTooLargeError(
            f'body of {length} bytes exceeds '
            f'max_body_bytes={opts.max_body_bytes}'))
        return
      try:
        body = self.rfile.read(length)
      except (socket.timeout, TimeoutError, ConnectionResetError):
        self.close_connection = True
        return
      if len(body) < length:
        self.close_connection = True
        return
      try:
        pack = service.featurize(
            body, trace_id=self.headers.get(protocol.TRACE_HEADER) or None)
      except shared_faults.ServeRejection as e:
        self._reply_error(e)
        return
      self._reply(200, pack, content_type=protocol.CONTENT_TYPE)

  return Handler


class FeaturizeHTTPServer(ThreadingHTTPServer):
  daemon_threads = True
  allow_reuse_address = True


def build_worker(service: FeaturizeService, host: str,
                 port: int) -> FeaturizeHTTPServer:
  return FeaturizeHTTPServer((host, port), _make_handler(service))


def worker_main(options: FeaturizeWorkerOptions,
                host: str = '127.0.0.1', port: int = 0,
                ready_fn=None, stop_event=None) -> Dict[str, Any]:
  """Runs the worker until SIGTERM/SIGINT, then drains (same contract
  as serve_main / route_main)."""
  obs_lib.trace.configure_from_env(tier='featurize')
  service = FeaturizeService(options)
  httpd = build_worker(service, host, port)
  bound_port = httpd.server_address[1]
  http_thread = threading.Thread(
      target=httpd.serve_forever, name='dctpu-featurize-http',
      daemon=True)
  http_thread.start()
  stop = _StopFlag()
  stop.install()
  info = {'event': 'ready', 'host': host, 'port': bound_port,
          'tier': 'featurize'}
  log.info('dctpu featurize-worker ready on %s:%d', host, bound_port)
  if ready_fn is not None:
    ready_fn(info)
  try:
    while not stop.event.wait(timeout=0.5):
      if stop_event is not None and stop_event.is_set():
        break
    if stop.signum is not None:
      log.warning('signal %d: draining featurize worker', stop.signum)
    service.begin_drain()
    drained = service.drain(timeout=options.io_timeout_s + 30)
    if not drained:
      log.error('featurize drain timed out with work in flight')
  finally:
    stop.restore()
    httpd.shutdown()
    httpd.server_close()
  stats = service.stats()
  stats['drained'] = bool(drained)
  return stats
