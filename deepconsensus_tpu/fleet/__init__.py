"""Fleet tier: `dctpu route` load balancing + disaggregated featurize.

One resident `dctpu serve` daemon owns one device set; fleet scale is
N of them behind a router, with CPU-heavy BAM decode/pileup pushed
out to horizontally scaled featurize workers (the genomics analog of
prefill/decode disaggregation — accelerator replicas run nothing but
dispatch/finalize).

  registry.py          health-gated replica registration + probing
  balancer.py          weighted least-loaded pick, bounded in-flight,
                       weighted-fair multi-tenant admission
  router.py            `dctpu route`: the /v1/polish front tier
  featurize_worker.py  `dctpu featurize-worker`: bam/1 -> features/1
  autoscaler.py        `dctpu autoscale`: SLO-driven replica target
                       reconciliation + preemption replacement
"""
from deepconsensus_tpu.fleet.registry import (  # noqa: F401
    FEATURIZE_TIER,
    MODEL_TIER,
    Replica,
    ReplicaRegistry,
    ReplicaState,
)
from deepconsensus_tpu.fleet.balancer import (  # noqa: F401
    LeastLoadedBalancer,
)
from deepconsensus_tpu.fleet.autoscaler import (  # noqa: F401
    Autoscaler,
    AutoscalerOptions,
)
