"""Replica registry: health-gated membership for the fleet tier.

A replica (model or featurize) joins in state JOINING and receives no
traffic until a /readyz probe succeeds — registration is an intent,
health is earned. The probe thread then keeps per-replica balancing
signals fresh from endpoints the serve stack already exposes:

  /readyz   ready/draining + capacity (mesh_dp, degraded): a replica
            answering 503 with draining=true goes to DRAINING and gets
            no new work while it finishes its admitted requests — the
            rolling-restart handshake.
  /metricz  outstanding (queue depth), transfer_overlap_fraction, and
            the full unified counter split, cached per replica so the
            router's /metricz can aggregate the fleet without fanning
            out a probe per scrape.

Connection-level probe failures accumulate; dead_after consecutive
failures park the replica in DEAD. DEAD replicas keep being probed —
a restarted replica on the same address heals back to READY, so a
static fleet config survives rolling restarts.

Healing has hysteresis: after a DEAD verdict, READY requires
ready_after CONSECUTIVE healthy probes (any missed probe resets the
streak). A replica flapping between alive and dead therefore never
re-enters the balancer's candidate set mid-flap — without the streak
requirement a flapper would thrash the balancer, absorbing a request
on each one-probe revival and losing it on the next flap.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional
from urllib.parse import urlsplit

from deepconsensus_tpu.serve.client import ServeClient

MODEL_TIER = 'model'
FEATURIZE_TIER = 'featurize'
TIERS = (MODEL_TIER, FEATURIZE_TIER)


class ReplicaState:
  JOINING = 'joining'      # registered, no successful probe yet
  READY = 'ready'          # probed healthy: eligible for new work
  DRAINING = 'draining'    # answered /readyz 503 draining: no new work
  DEAD = 'dead'            # unreachable; still probed for revival

  ALL = (JOINING, READY, DRAINING, DEAD)


@dataclasses.dataclass
class Replica:
  """One fleet member and its latest probed signals. Mutable fields
  are owned by ReplicaRegistry._lock (see registry docstring); the
  snapshots handed out by snapshot()/eligible() are copies."""

  url: str
  tier: str = MODEL_TIER
  state: str = ReplicaState.JOINING
  mesh_dp: int = 1
  degraded: bool = False
  queue_depth: int = 0
  overlap_fraction: float = 0.0
  in_flight: int = 0
  probe_failures: int = 0
  # Probe hysteresis: healing=True after a DEAD verdict until the
  # replica earns ready_after consecutive healthy probes; heal_streak
  # counts them (reset by any missed probe).
  healing: bool = False
  heal_streak: int = 0
  last_probe_s: float = 0.0
  n_routed: int = 0
  n_ok: int = 0
  n_upstream_rejects: int = 0
  n_send_failures: int = 0
  n_lost: int = 0
  counters: Dict[str, Any] = dataclasses.field(default_factory=dict)

  @property
  def host_port(self):
    parts = urlsplit(self.url if '//' in self.url else f'//{self.url}')
    return parts.hostname or '127.0.0.1', parts.port or 80


class ReplicaRegistry:
  """Membership + probe loop. All replica mutation happens under one
  lock; the balancer shares it (via the `lock` property) so a pick and
  its in-flight increment are one atomic step."""

  def __init__(self, probe_interval_s: float = 0.5,
               probe_timeout_s: float = 5.0, dead_after: int = 3,
               ready_after: int = 2):
    self.probe_interval_s = probe_interval_s
    self.probe_timeout_s = probe_timeout_s
    self.dead_after = dead_after
    self.ready_after = max(1, ready_after)
    self._lock = threading.Lock()
    self._replicas: Dict[str, Replica] = {}  # guarded by: self._lock
    self._stop = threading.Event()
    self._thread: Optional[threading.Thread] = None  # dclint: lock-free
    # (written once by start(), read by stop(); both run on the
    # lifecycle thread — the prober itself never touches it)

  @property
  def lock(self) -> threading.Lock:
    return self._lock

  # -- membership --------------------------------------------------------

  def add(self, url: str, tier: str = MODEL_TIER) -> Replica:
    """Registers a replica in JOINING (health-gated: it becomes
    eligible only after a successful probe). Re-registering a known
    url resets its probe state — the rolling-restart rejoin path."""
    if tier not in TIERS:
      # dclint: allow=typed-faults (operator/config validation at the
      # registration boundary, surfaced as a 400 by the router)
      raise ValueError(f'unknown tier {tier!r}: must be one of {TIERS}')
    url = url.rstrip('/')
    with self._lock:
      replica = self._replicas.get(url)
      if replica is None or replica.tier != tier:
        replica = Replica(url=url, tier=tier)
        self._replicas[url] = replica
      else:
        replica.state = ReplicaState.JOINING
        replica.probe_failures = 0
        # Explicit re-registration is operator intent (rolling-restart
        # rejoin): it clears the hysteresis debt a DEAD spell accrued.
        replica.healing = False
        replica.heal_streak = 0
      return dataclasses.replace(replica)

  def remove(self, url: str) -> bool:
    with self._lock:
      return self._replicas.pop(url.rstrip('/'), None) is not None

  def urls(self) -> List[str]:
    with self._lock:
      return sorted(self._replicas)

  # -- probing -----------------------------------------------------------

  def start(self) -> None:
    self._thread = threading.Thread(
        target=self._probe_loop, name='dctpu-route-probe', daemon=True)
    self._thread.start()

  def stop(self) -> None:
    self._stop.set()
    if self._thread is not None:
      self._thread.join(timeout=self.probe_timeout_s + 1)

  def _probe_loop(self) -> None:
    while not self._stop.wait(timeout=self.probe_interval_s):
      self.probe_all()

  def probe_all(self) -> None:
    with self._lock:
      targets = [(r.url, r.host_port) for r in self._replicas.values()]
    for url, (host, port) in targets:
      self._probe_one(url, host, port)

  def _probe_one(self, url: str, host: str, port: int) -> None:
    client = ServeClient(host, port, timeout=self.probe_timeout_s)
    try:
      ready = client.readyz()
      stats = client.metricz()
    # dclint: allow=typed-faults (probe transport failure IS the
    # signal: it increments probe_failures and drives the replica to
    # DEAD below — routing, not swallowing)
    except Exception:  # noqa: BLE001 - any transport failure = missed probe
      with self._lock:
        replica = self._replicas.get(url)
        if replica is None:
          return
        replica.probe_failures += 1
        replica.last_probe_s = time.monotonic()
        replica.heal_streak = 0  # any missed probe breaks the streak
        if replica.probe_failures >= self.dead_after:
          replica.state = ReplicaState.DEAD
          replica.healing = True
      return
    with self._lock:
      replica = self._replicas.get(url)
      if replica is None:
        return  # removed while probing
      replica.probe_failures = 0
      replica.last_probe_s = time.monotonic()
      replica.mesh_dp = int(ready.get('mesh_dp', 0) or 1)
      replica.degraded = bool(ready.get('degraded', False))
      replica.queue_depth = int(stats.get('outstanding', 0) or 0)
      counters = stats.get('counters', {})
      replica.overlap_fraction = float(
          counters.get('transfer_overlap_fraction', 0.0) or 0.0)
      replica.counters = {
          k: v for k, v in counters.items() if isinstance(v, (int, float))
      }
      if ready.get('ready'):
        if replica.healing:
          # Hysteresis: a replica coming back from DEAD must answer
          # ready_after consecutive healthy probes before it re-enters
          # the candidate set — one good probe from a flapper is noise.
          replica.heal_streak += 1
          if replica.heal_streak >= self.ready_after:
            replica.healing = False
            replica.heal_streak = 0
            replica.state = ReplicaState.READY
          else:
            replica.state = ReplicaState.JOINING
        else:
          replica.state = ReplicaState.READY
      elif ready.get('draining'):
        replica.heal_streak = 0
        replica.state = ReplicaState.DRAINING
      else:
        # Alive but not ready (warming after restart): back to the
        # health gate; no new work until /readyz goes green.
        replica.heal_streak = 0
        replica.state = ReplicaState.JOINING

  # -- router-observed events -------------------------------------------

  def mark_unreachable(self, url: str) -> None:
    """The router saw a connection-level failure: park the replica in
    DEAD immediately instead of waiting out dead_after probe cycles
    (the probe loop revives it when it answers again)."""
    with self._lock:
      replica = self._replicas.get(url)
      if replica is not None:
        replica.probe_failures = max(replica.probe_failures,
                                     self.dead_after)
        replica.state = ReplicaState.DEAD
        replica.healing = True
        replica.heal_streak = 0

  def mark_draining(self, url: str) -> None:
    """The router saw a draining 503 from this replica before the next
    probe cycle would have: stop sending it new work now."""
    with self._lock:
      replica = self._replicas.get(url)
      if replica is not None and replica.state == ReplicaState.READY:
        replica.state = ReplicaState.DRAINING

  # -- views -------------------------------------------------------------

  def snapshot(self) -> List[Replica]:
    with self._lock:
      return [dataclasses.replace(r) for r in self._replicas.values()]

  def tier_states(self) -> Dict[str, Dict[str, int]]:
    """{tier: {state: count}} for /readyz."""
    out: Dict[str, Dict[str, int]] = {t: {} for t in TIERS}
    for replica in self.snapshot():
      states = out.setdefault(replica.tier, {})
      states[replica.state] = states.get(replica.state, 0) + 1
    return out

  def aggregate_counters(self) -> Dict[str, Any]:
    """Sum of every numeric counter across the latest cached /metricz
    of all replicas (fractions are averaged over replicas reporting
    them) — the fleet-wide view the router's /metricz publishes."""
    totals: Dict[str, float] = {}
    fractions: Dict[str, List[float]] = {}
    for replica in self.snapshot():
      for key, value in replica.counters.items():
        if key.endswith('_fraction') or key.endswith('_s'):
          fractions.setdefault(key, []).append(float(value))
        else:
          totals[key] = totals.get(key, 0) + value
    out: Dict[str, Any] = dict(totals)
    for key, values in fractions.items():
      out[key] = round(sum(values) / len(values), 4)
    return out
