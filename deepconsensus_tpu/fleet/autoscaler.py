"""SLO autoscaler: `dctpu autoscale` — the fleet's reconciliation loop.

Watches the router's unified /metricz and holds a per-tier replica
target so the configured SLO holds while paying for no more replicas
than the load needs:

  * scale OUT when the SLO-class p99 (falling back to the tier p99
    when the class has no samples yet) exceeds target_p99_s, or the
    mean READY-replica queue depth exceeds target_queue_depth. Spawns
    are cheap: every replica shares the persistent compilation cache,
    so a new one warms in seconds, not minutes.
  * scale IN when both signals sit well under target (scale_in_fraction)
    for a full cooldown. Scale-in only ever drains replicas THIS
    autoscaler spawned (the managed ledger) — operator-started
    replicas are never touched — and riding the SIGTERM drain
    contract means zero accepted requests are lost.
  * REPLACE whenever live (READY+JOINING) count drops under target:
    a preempted/dead/draining replica falls out of the live set and
    the deficit is respawned next tick. This is the preemption story:
    the notice (SIGUSR1 / DCTPU_FAULT_PREEMPT_AT_S) flips the doomed
    replica to DRAINING, the router stops routing to it, and the
    autoscaler restores capacity before the hard kill lands.

Asymmetric cooldowns (fast out, slow in) are deliberate: a missed
scale-out burns the SLO now, a missed scale-in burns only money.

The controller is transport-agnostic: `fetch_stats` / `spawn_fn` /
`drain_fn` are injected, so tests drive pure decision sequences and
the CLI binds them to HTTP + subprocesses. Every tick emits an
`autoscale_decision` span into the shared fleet trace (DCTPU_TRACE)
and counts decisions in its own MetricsRegistry.

stdlib-only (no jax): the autoscaler runs on any coordinator box.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from deepconsensus_tpu import obs as obs_lib
from deepconsensus_tpu.fleet import registry as registry_lib

log = logging.getLogger(__name__)

# Live = will take (or soon take) traffic; DRAINING and DEAD replicas
# are on their way out and count as capacity already lost.
_LIVE_STATES = (registry_lib.ReplicaState.READY,
                registry_lib.ReplicaState.JOINING)


@dataclasses.dataclass
class AutoscalerOptions:
  tier: str = registry_lib.MODEL_TIER
  min_replicas: int = 1
  max_replicas: int = 4
  # SLO signals: the p99 of slo_class (per-class histogram on the
  # router) and the mean queue depth across READY replicas of tier.
  target_p99_s: float = 2.0
  target_queue_depth: float = 4.0
  slo_class: str = 'interactive'
  poll_interval_s: float = 1.0
  scale_out_cooldown_s: float = 5.0
  scale_in_cooldown_s: float = 60.0
  # Scale in only when p99 AND queue depth sit under this fraction of
  # their targets — hysteresis so the fleet doesn't saw-tooth around
  # the threshold.
  scale_in_fraction: float = 0.5


class Autoscaler:
  """One reconciliation loop instance.

  fetch_stats() -> the router's /metricz dict (raising on transport
    failure is fine: the tick is skipped and counted).
  spawn_fn() -> url of a freshly spawned, router-registered replica.
  drain_fn(url) -> initiates the SIGTERM drain of a managed replica.

  tick() is the whole control law; run() loops it. State is
  lock-guarded because the CLI lifecycle thread (stop/shutdown) and
  the loop thread both touch the ledger."""

  def __init__(self, options: AutoscalerOptions,
               fetch_stats: Callable[[], Dict[str, Any]],
               spawn_fn: Callable[[], str],
               drain_fn: Callable[[str], None],
               on_decision: Optional[Callable[[Dict[str, Any]], None]]
               = None):
    self.options = options
    self.fetch_stats = fetch_stats
    self.spawn_fn = spawn_fn
    self.drain_fn = drain_fn
    self.on_decision = on_decision
    self.obs = obs_lib.MetricsRegistry(tier='autoscaler')
    for key in ('n_ticks', 'n_poll_errors', 'n_scale_out', 'n_scale_in',
                'n_replaced', 'n_spawned', 'n_drained', 'n_spawn_errors'):
      self.obs.counter(key)
    self._lock = threading.Lock()
    self._stop = threading.Event()
    self.target = max(0, options.min_replicas)  # guarded by: self._lock
    self._managed: List[str] = []  # guarded by: self._lock
    # Cooldown anchors: the first scale-out is never gated (an SLO
    # breach at startup is real), but the first scale-in waits a full
    # cooldown from start — the fleet must prove it is cold, not just
    # be observed before traffic arrives.
    self._last_out_s = float('-inf')  # guarded by: self._lock
    self._last_in_s = time.monotonic()  # guarded by: self._lock
    self._last_decision: Dict[str, Any] = {}  # guarded by: self._lock

  # -- signal extraction -------------------------------------------------

  def _signals(self, stats: Dict[str, Any]) -> Dict[str, Any]:
    opts = self.options
    replicas = [r for r in stats.get('replicas', [])
                if r.get('tier') == opts.tier]
    ready = [r for r in replicas if r.get('state')
             == registry_lib.ReplicaState.READY]
    n_live = sum(1 for r in replicas if r.get('state') in _LIVE_STATES)
    p99 = None
    class_lat = stats.get('class_latency', {}).get(opts.slo_class, {})
    if class_lat.get('p99') is not None:
      p99 = float(class_lat['p99'])
    else:
      tier_lat = stats.get('latency', {}).get(opts.tier, {})
      if tier_lat.get('p99') is not None:
        p99 = float(tier_lat['p99'])
    queue_depth = (sum(int(r.get('queue_depth', 0) or 0) for r in ready)
                   / len(ready)) if ready else 0.0
    return {
        'replicas': replicas,
        'n_live': n_live,
        'n_ready': len(ready),
        'p99': p99,
        'queue_depth': round(queue_depth, 3),
    }

  # -- control law -------------------------------------------------------

  def tick(self) -> Dict[str, Any]:
    """One reconcile step. Returns the decision record (also stored,
    traced, and handed to on_decision)."""
    opts = self.options
    t0 = time.time()
    self.obs.inc('n_ticks')
    try:
      stats = self.fetch_stats()
    # dclint: allow=typed-faults (a poll failure only skips this tick;
    # the router being briefly unreachable must not kill the loop)
    except Exception as e:  # noqa: BLE001
      self.obs.inc('n_poll_errors')
      decision = {'action': 'poll_error', 'reason': f'{type(e).__name__}: {e}'}
      self._finish(decision, t0)
      return decision
    sig = self._signals(stats)
    now = time.monotonic()
    hot = ((sig['p99'] is not None and sig['p99'] > opts.target_p99_s)
           or sig['queue_depth'] > opts.target_queue_depth)
    cold = ((sig['p99'] is None
             or sig['p99'] < opts.target_p99_s * opts.scale_in_fraction)
            and sig['queue_depth']
            < opts.target_queue_depth * opts.scale_in_fraction)
    action, reason = 'hold', 'within SLO at target capacity'
    drain_url = None
    with self._lock:
      # Prune managed urls that no longer exist or died out from under
      # us (externally killed): they are not drainable on shutdown.
      known = {r['url']: r.get('state') for r in sig['replicas']}
      self._managed = [
          u for u in self._managed
          if known.get(u) not in (None, registry_lib.ReplicaState.DEAD)
      ]
      pre_deficit = self.target - sig['n_live']
      if hot and self.target < opts.max_replicas and \
          now - self._last_out_s >= opts.scale_out_cooldown_s:
        self.target += 1
        self._last_out_s = now
        self.obs.inc('n_scale_out')
        action = 'scale_out'
        reason = (f'p99={sig["p99"]} > {opts.target_p99_s}s or '
                  f'queue={sig["queue_depth"]} > '
                  f'{opts.target_queue_depth}')
      elif cold and self.target > opts.min_replicas \
          and sig['n_live'] >= self.target \
          and now - self._last_in_s >= opts.scale_in_cooldown_s:
        self.target -= 1
        self._last_in_s = now
        self.obs.inc('n_scale_in')
        action = 'scale_in'
        reason = (f'p99={sig["p99"]} and queue={sig["queue_depth"]} '
                  f'under {opts.scale_in_fraction}x target for a full '
                  'cooldown')
        # Only a replica from the managed ledger is ever drained; the
        # newest goes first (operator-started replicas are the base).
        for url in reversed(self._managed):
          if known.get(url) in _LIVE_STATES:
            drain_url = url
            self._managed.remove(url)
            break
      deficit = self.target - sig['n_live']
      target = self.target
    if drain_url is not None:
      log.info('autoscale: draining %s (%s)', drain_url, reason)
      self.drain_fn(drain_url)
      self.obs.inc('n_drained')
    spawned = []
    for _ in range(max(0, deficit)):
      try:
        url = self.spawn_fn()
      # dclint: allow=typed-faults (one failed spawn must not kill the
      # control loop; the deficit persists and next tick retries)
      except Exception as e:  # noqa: BLE001
        self.obs.inc('n_spawn_errors')
        log.error('autoscale: spawn failed: %s', e)
        break
      spawned.append(url)
      self.obs.inc('n_spawned')
      with self._lock:
        self._managed.append(url)
    if spawned and action == 'hold':
      action = 'replace'
      reason = (f'live={sig["n_live"]} < target={target}: restoring '
                'capacity lost to preemption/death')
    if spawned and pre_deficit > 0:
      # Spawns that cover a pre-existing live deficit (not the slot a
      # scale_out just added) are replacements.
      self.obs.inc('n_replaced', min(len(spawned), pre_deficit))
    self.obs.set_gauge('target_replicas', target)
    self.obs.set_gauge('live_replicas', sig['n_live'])
    decision = {
        'action': action,
        'tier': opts.tier,
        'reason': reason,
        'p99': sig['p99'],
        'queue_depth': sig['queue_depth'],
        'n_live': sig['n_live'],
        'n_ready': sig['n_ready'],
        'target': target,
        'spawned': spawned,
        'drained': drain_url,
    }
    self._finish(decision, t0)
    return decision

  def _finish(self, decision: Dict[str, Any], t0: float) -> None:
    with self._lock:
      self._last_decision = dict(decision)
    obs_lib.trace.complete_event(
        'autoscale_decision', 'autoscaler', t0, time.time(), decision)
    if self.on_decision is not None:
      self.on_decision(decision)

  # -- lifecycle ---------------------------------------------------------

  def run(self, stop_event: Optional[threading.Event] = None) -> None:
    """Ticks until stop() (or stop_event) is set. Runs on the caller's
    thread — the CLI owns signal handling around it."""
    while not self._stop.is_set():
      if stop_event is not None and stop_event.is_set():
        return
      self.tick()
      if self._stop.wait(timeout=self.options.poll_interval_s):
        return
      if stop_event is not None and stop_event.is_set():
        return

  def stop(self) -> None:
    self._stop.set()

  def shutdown(self, drain_managed: bool = False) -> List[str]:
    """Stops the loop; with drain_managed, SIGTERM-drains every
    replica this autoscaler spawned (the default leaves them serving —
    an autoscaler restart must not take the fleet down with it)."""
    self.stop()
    with self._lock:
      managed = list(self._managed)
      if drain_managed:
        self._managed = []
    if drain_managed:
      for url in managed:
        try:
          self.drain_fn(url)
          self.obs.inc('n_drained')
        # dclint: allow=typed-faults (best-effort teardown: a replica
        # that already died mid-drain is the desired end state)
        except Exception as e:  # noqa: BLE001
          log.warning('autoscale: drain of %s failed: %s', url, e)
    return managed

  # -- views -------------------------------------------------------------

  def stats(self) -> Dict[str, Any]:
    registry_view = self.obs.snapshot()
    with self._lock:
      managed = list(self._managed)
      target = self.target
      last = dict(self._last_decision)
    return {
        # Unified cross-tier schema (docs/observability.md).
        'tier': 'autoscaler',
        'counters': registry_view['counters'],
        'gauges': registry_view['gauges'],
        'target': target,
        'managed': managed,
        'last_decision': last,
    }
