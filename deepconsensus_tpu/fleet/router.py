"""`dctpu route`: the fleet front tier for /v1/polish.

Same stdlib HTTP conventions as serve/server.py (ThreadingHTTPServer,
absolute read deadlines, typed JSON errors), but no model: the router
steers bodies by their protocol frame —

  bam/1        -> a featurize worker (/v1/featurize) turns raw BAM
                  bytes into a compact features/1 pack, then the pack
                  goes to a model replica;
  features/1 or
  legacy float -> straight to a model replica's /v1/polish.

Placement is the balancer's weighted least-loaded pick over READY
replicas (registry.py owns health). Failure semantics around a dying
replica are deliberately asymmetric:

  * connect/send-phase failure: the replica provably never read the
    request ("never acked") — safe to retry against a different
    replica, excluding every replica already tried;
  * explicit upstream rejection (429/503): the replica refused the
    request, so it was not accepted — also safe to retry elsewhere
    (a draining 503 additionally flips the replica to DRAINING now,
    not at the next probe — the rolling-restart fast path);
  * failure after the request was fully written: the replica may have
    accepted the work, so the router must NOT place it again — that
    could duplicate an accepted request. It surfaces as a typed
    ReplicaLostError (503, transient) and the client decides.

/metricz aggregates the fleet: router counters, per-tier end-to-end
latency percentiles, per-replica snapshots, and the summed counters
from every replica's cached /metricz probe.

Rollout: SIGTERM stops admissions (/readyz goes 503 draining, new
polish gets a typed 503) and waits for in-flight forwards to finish —
zero accepted-then-lost through the router, same contract as serve.
"""
from __future__ import annotations

import dataclasses
import http.client
import io
import json
import logging
import re
import threading
import time
import socket
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from deepconsensus_tpu import faults as shared_faults
from deepconsensus_tpu import obs as obs_lib
from deepconsensus_tpu.fleet import registry as registry_lib
from deepconsensus_tpu.fleet import balancer as balancer_lib
from deepconsensus_tpu.fleet.balancer import LeastLoadedBalancer
from deepconsensus_tpu.serve import protocol
from deepconsensus_tpu.serve.server import _DeadlineSocketIO, _StopFlag

log = logging.getLogger(__name__)

_RETRYABLE_UPSTREAM = (429, 503)  # explicit refusal: request not accepted
_CLASS_RE = re.compile(r'^[a-z0-9_-]{1,32}$')


@dataclasses.dataclass
class RouterOptions:
  max_body_bytes: int = 64 << 20
  io_timeout_s: float = 20.0
  upstream_timeout_s: float = 300.0  # one forwarded polish, end to end
  probe_interval_s: float = 0.5
  probe_timeout_s: float = 5.0
  max_inflight: int = 8              # per replica, scaled by mesh_dp
  max_attempts: int = 3              # distinct replicas tried per request
  latency_window: int = 2048         # per-tier latency samples retained
  # Multi-tenant QoS (balancer.py): class weights for weighted-fair
  # admission, the class unlabeled requests land in, the per-client
  # concurrent-request quota (0 = unlimited), how long a saturated
  # acquire may wait its weighted-fair turn (0 = shed immediately,
  # the pre-QoS behavior), and the per-class waiter bound.
  class_weights: Optional[Dict[str, float]] = None
  default_class: str = balancer_lib.DEFAULT_CLASS
  client_quota: int = 0
  queue_wait_s: float = 0.0
  max_queued_per_class: int = 16


class _SendPhaseError(OSError):
  """Connect or request-write failed: the replica never read the
  request, so retrying it elsewhere cannot duplicate accepted work.
  Internal control flow — never crosses the wire."""


class _UpstreamRejected(RuntimeError):
  """Upstream answered a retryable rejection (429/503): carry it so
  the last attempt can relay the replica's own typed error."""

  def __init__(self, status: int, body: bytes, draining: bool):
    super().__init__(f'upstream rejected with {status}')
    self.status = status
    self.body = body
    self.draining = draining


class RouterCore:
  """Steering + forwarding, HTTP-server-free so tests drive it
  directly. Handler threads call route() concurrently; shared mutable
  state is the counters/latency maps under self._lock (replica state
  lives in the registry, under its own lock)."""

  def __init__(self, registry: registry_lib.ReplicaRegistry,
               options: Optional[RouterOptions] = None):
    self.registry = registry
    self.options = options or RouterOptions()
    self.balancer = LeastLoadedBalancer(
        registry, max_inflight=self.options.max_inflight,
        class_weights=self.options.class_weights,
        default_class=self.options.default_class,
        client_quota=self.options.client_quota,
        queue_wait_s=self.options.queue_wait_s,
        max_queued_per_class=self.options.max_queued_per_class)
    self._lock = threading.Lock()
    # Central metrics registry (obs/metrics.py): counters pre-created
    # so /metricz always exposes the full set, per-tier forwarding
    # latency histograms replacing the deque percentile math.
    self.obs = obs_lib.MetricsRegistry(tier='router')
    for key in ('n_requests', 'n_routed_model', 'n_routed_featurize',
                'n_retries', 'n_rejected_saturated', 'n_replica_lost',
                'n_bad_requests', 'n_upstream_rejects_relayed',
                'n_registered', 'n_quota_rejected'):
      self.obs.counter(key)
    self._tier_hists = {
        tier: self.obs.histogram(
            f'route_{tier}_latency_s',
            help=f'forwarding latency to the {tier} tier')
        for tier in registry_lib.TIERS
    }
    # Per-class end-to-end latency (the per-class SLO signal): one
    # histogram per priority class, pre-created for the configured
    # weights so /metricz exposes the classes before traffic arrives.
    self._class_hists: Dict[str, Any] = {}  # guarded by: self._lock
    for klass in sorted(self.balancer.class_weights):
      self._class_hist(klass)
    self._draining = False  # dclint: lock-free (monotonic bool flip,
    # read per request; worst case one request admitted during drain
    # finishes normally before drain() returns)
    self._in_flight = 0  # guarded by: self._lock

  def bump(self, key: str, n: int = 1) -> None:
    self.obs.inc(key, n)

  def _class_hist(self, klass: str):
    with self._lock:
      hist = self._class_hists.get(klass)
      if hist is None:
        hist = self.obs.histogram(
            f'route_class_{klass}_latency_s',
            help=f'end-to-end routed latency for priority class {klass}')
        self._class_hists[klass] = hist
      return hist

  # -- forwarding --------------------------------------------------------

  def _forward_once(self, replica: registry_lib.Replica, path: str,
                    body: bytes, headers: Dict[str, str]
                    ) -> Tuple[int, bytes, str]:
    """One POST to one replica, with the ack boundary made explicit:
    failures while sending raise _SendPhaseError (safe to retry
    elsewhere); failures after the send completed raise
    ReplicaLostError (the replica may have accepted the request)."""
    host, port = replica.host_port
    conn = http.client.HTTPConnection(
        host, port, timeout=self.options.upstream_timeout_s)
    try:
      try:
        conn.request('POST', path, body=body, headers=headers)
      except (OSError, http.client.HTTPException) as e:
        # dclint: allow=typed-faults (internal retry control flow: the
        # caller converts it to a retry or a typed FleetRejection; it
        # never crosses the wire)
        raise _SendPhaseError(
            f'{replica.url}: send failed: {type(e).__name__}: {e}'
        ) from e
      try:
        resp = conn.getresponse()
        data = resp.read()
        ctype = resp.getheader('Content-Type', '') or ''
      except (OSError, http.client.HTTPException) as e:
        raise shared_faults.ReplicaLostError(
            f'replica {replica.url} died after accepting the request '
            f'({type(e).__name__}: {e}); not retried — an accepted '
            'request is never duplicated') from e
      return resp.status, data, ctype
    finally:
      conn.close()

  def _forward_with_retry(self, tier: str, path: str, body: bytes,
                          headers: Dict[str, str],
                          klass: Optional[str] = None,
                          client: Optional[str] = None
                          ) -> Tuple[int, bytes, str]:
    """Places the request on the least-loaded replica of `tier`,
    moving to a different replica only when the previous one provably
    never accepted it (send-phase failure or explicit rejection)."""
    tried: set = set()
    last_reject: Optional[_UpstreamRejected] = None
    t0 = time.monotonic()
    for attempt in range(self.options.max_attempts):
      try:
        replica = self.balancer.acquire(tier, exclude=tried,
                                        klass=klass, client=client)
      except shared_faults.QuotaExceededError:
        self.bump('n_quota_rejected')
        raise
      except shared_faults.FleetRejection:
        if last_reject is not None:
          # Every other replica is excluded/saturated; relay the
          # clearest signal we have — the replica's own rejection.
          self.bump('n_upstream_rejects_relayed')
          raise shared_faults.FleetRejection(
              f'{tier} tier: {last_reject.body[:300].decode("latin-1")}')
        self.bump('n_rejected_saturated')
        raise
      tried.add(replica.url)
      if attempt > 0:
        self.bump('n_retries')
      try:
        status, data, ctype = self._forward_once(
            replica, path, body, headers)
      except _SendPhaseError as e:
        log.warning('%s never acked (%s); retrying elsewhere',
                    replica.url, e)
        self.balancer.release(replica.url, 'send_failure',
                              klass=klass, client=client)
        self.registry.mark_unreachable(replica.url)
        continue
      except shared_faults.ReplicaLostError:
        self.balancer.release(replica.url, 'lost',
                              klass=klass, client=client)
        self.registry.mark_unreachable(replica.url)
        self.bump('n_replica_lost')
        raise
      if status in _RETRYABLE_UPSTREAM:
        draining = b'UNAVAILABLE' in data or b'draining' in data
        self.balancer.release(replica.url, 'reject',
                              klass=klass, client=client)
        if draining:
          self.registry.mark_draining(replica.url)
        last_reject = _UpstreamRejected(status, data, draining)
        continue
      self.balancer.release(replica.url, 'ok', klass=klass, client=client)
      self._tier_hists[tier].observe(time.monotonic() - t0)
      return status, data, ctype
    if last_reject is not None:
      self.bump('n_upstream_rejects_relayed')
      raise shared_faults.FleetRejection(
          f'{tier} tier rejected the request on all '
          f'{self.options.max_attempts} attempts: '
          f'{last_reject.body[:300].decode("latin-1")}')
    raise shared_faults.FleetRejection(
        f'no {tier} replica reachable after '
        f'{self.options.max_attempts} attempts')

  # -- request entry -----------------------------------------------------

  def route(self, body: bytes,
            deadline_header: Optional[str] = None,
            trace_id: Optional[str] = None,
            klass: Optional[str] = None,
            client: Optional[str] = None) -> Tuple[int, bytes, str]:
    """Routes one /v1/polish body; returns (status, body, ctype) to
    relay verbatim. Raises ServeRejection subtypes for router-level
    rejections (mapped to typed JSON by the HTTP layer).

    The router is the fleet's outermost tier, so it mints the trace id
    (unless the client sent one) and stamps it into the forwarded
    headers — every downstream span joins this request's trace.

    `klass`/`client` are the multi-tenant QoS attribution (protocol
    CLASS_HEADER / CLIENT_HEADER): the class buys its weighted-fair
    share of fleet capacity and its own latency histogram; the client
    id is what per-client quotas are charged against."""
    if self._draining:
      raise shared_faults.DrainingError('router is draining')
    self.bump('n_requests')
    klass = klass or self.options.default_class
    if not _CLASS_RE.match(klass):
      self.bump('n_bad_requests')
      raise shared_faults.BadRequestError(
          f'bad {protocol.CLASS_HEADER} value {klass!r}: '
          'want [a-z0-9_-]{1,32}')
    trace_id = trace_id or obs_lib.trace.mint_trace_id()
    t_route = time.time()
    t_mono = time.monotonic()
    frame = ''
    with self._lock:
      self._in_flight += 1
    try:
      frame = protocol.sniff_frame(body)
      headers = {'Content-Type': protocol.CONTENT_TYPE,
                 protocol.TRACE_HEADER: trace_id,
                 protocol.CLASS_HEADER: klass}
      if client:
        headers[protocol.CLIENT_HEADER] = client
      if deadline_header:
        headers[protocol.DEADLINE_HEADER] = deadline_header
      if frame == protocol.FRAME_BAM:
        self.bump('n_routed_featurize')
        status, pack, ctype = self._forward_with_retry(
            registry_lib.FEATURIZE_TIER, '/v1/featurize', body, headers,
            klass=klass, client=client)
        if status != 200:
          return status, pack, ctype  # worker's typed error, relayed
        body = pack
      self.bump('n_routed_model')
      status, data, ctype = self._forward_with_retry(
          registry_lib.MODEL_TIER, '/v1/polish', body, headers,
          klass=klass, client=client)
      if status == 200:
        self._class_hist(klass).observe(time.monotonic() - t_mono)
      return status, data, ctype
    except shared_faults.BadRequestError:
      self.bump('n_bad_requests')
      raise
    except shared_faults.FleetRejection:
      # Class-aware shed accounting (QuotaExceededError included):
      # which class absorbed the rejection is the starvation signal.
      self.bump(f'n_shed_{klass}')
      raise
    finally:
      with self._lock:
        self._in_flight -= 1
      obs_lib.trace.complete_event(
          'route', 'request', t_route, time.time(),
          {'trace_id': trace_id, 'frame': frame, 'class': klass})

  # -- lifecycle / views -------------------------------------------------

  def begin_drain(self) -> None:
    self._draining = True

  def drain(self, timeout: float = 60.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
      with self._lock:
        if self._in_flight == 0:
          return True
      time.sleep(0.05)
    return False

  @property
  def ready(self) -> bool:
    if self._draining:
      return False
    return any(
        r.state == registry_lib.ReplicaState.READY
        and r.tier == registry_lib.MODEL_TIER
        for r in self.registry.snapshot())

  def readyz(self) -> Dict[str, Any]:
    return {
        'ready': self.ready,
        'draining': self._draining,
        'tiers': self.registry.tier_states(),
    }

  def _latency_percentiles(self) -> Dict[str, Dict[str, Any]]:
    # Nearest-rank on the per-tier histograms (same fix as the serve
    # replica's latency_percentiles).
    return {tier: h.percentiles() for tier, h in self._tier_hists.items()}

  def prom_text(self) -> str:
    """/metricz?format=prom payload."""
    return (self.obs.to_prom('router')
            + obs_lib.metrics.prom_counters_text(
                self.registry.aggregate_counters(), tier='fleet'))

  def stats(self) -> Dict[str, Any]:
    counters = self.obs.counter_values()
    registry_view = self.obs.snapshot()
    with self._lock:
      in_flight = self._in_flight
    replicas = []
    for r in self.registry.snapshot():
      replicas.append({
          'url': r.url,
          'tier': r.tier,
          'state': r.state,
          'mesh_dp': r.mesh_dp,
          'degraded': r.degraded,
          'queue_depth': r.queue_depth,
          'transfer_overlap_fraction': r.overlap_fraction,
          'in_flight': r.in_flight,
          'n_routed': r.n_routed,
          'n_ok': r.n_ok,
          'n_upstream_rejects': r.n_upstream_rejects,
          'n_send_failures': r.n_send_failures,
          'n_lost': r.n_lost,
      })
    with self._lock:
      class_hists = dict(self._class_hists)
    return {
        # Unified cross-tier schema (docs/observability.md).
        'tier': 'router',
        'outstanding': in_flight,
        'draining': self._draining,
        'ready': self.ready,
        'counters': counters,
        'histograms': registry_view['histograms'],
        'latency': self._latency_percentiles(),
        'class_latency': {
            klass: h.percentiles()
            for klass, h in sorted(class_hists.items())
        },
        'qos': self.balancer.qos_snapshot(),
        'replicas': replicas,
        'fleet_counters': self.registry.aggregate_counters(),
    }


def _make_handler(core: RouterCore):
  opts = core.options

  class Handler(BaseHTTPRequestHandler):
    server_version = 'dctpu-route/1'
    protocol_version = 'HTTP/1.1'

    def setup(self):
      super().setup()
      self.connection.settimeout(opts.io_timeout_s)
      self._raw_in = _DeadlineSocketIO(self.connection, opts.io_timeout_s)
      self.rfile = io.BufferedReader(self._raw_in)

    def handle_one_request(self):
      self._raw_in.reset_deadline()
      super().handle_one_request()

    def log_message(self, fmt, *args):
      log.debug('%s %s', self.address_string(), fmt % args)

    def _reply(self, status: int, body: bytes,
               content_type: str = 'application/json') -> None:
      try:
        self.send_response(status)
        self.send_header('Content-Type', content_type)
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)
      except (BrokenPipeError, ConnectionResetError, socket.timeout,
              TimeoutError):
        self.close_connection = True

    def _reply_json(self, status: int, obj: Dict[str, Any]) -> None:
      self._reply(status, json.dumps(obj).encode())

    def _reply_error(self, e: shared_faults.ServeRejection) -> None:
      self._reply_json(
          e.http_status,
          {'error': str(e), 'kind': e.kind, 'status': e.http_status})

    def do_GET(self):
      path, _, query = self.path.partition('?')
      params_qs = urllib.parse.parse_qs(query)
      if path == '/healthz':
        self._reply_json(200, {'ok': True})
      elif path == '/readyz':
        info = core.readyz()
        self._reply_json(200 if info['ready'] else 503, info)
      elif path == '/metricz':
        if params_qs.get('format', [''])[0] == 'prom':
          self._reply(200, core.prom_text().encode(),
                      content_type='text/plain; version=0.0.4')
        else:
          self._reply_json(200, core.stats())
      else:
        self._reply_json(404, {'error': f'no such path: {self.path}'})

    def _read_body(self) -> Optional[bytes]:
      try:
        length = int(self.headers.get('Content-Length', ''))
      except ValueError:
        self._reply_json(411, {'error': 'Content-Length required'})
        return None
      if length > opts.max_body_bytes:
        self.close_connection = True
        self._reply_error(shared_faults.RequestTooLargeError(
            f'body of {length} bytes exceeds '
            f'max_body_bytes={opts.max_body_bytes}'))
        return None
      try:
        body = self.rfile.read(length)
      except (socket.timeout, TimeoutError, ConnectionResetError):
        self.close_connection = True
        return None
      if len(body) < length:
        self.close_connection = True
        return None
      return body

    def do_POST(self):
      if self.path == '/v1/polish':
        body = self._read_body()
        if body is None:
          return
        try:
          status, data, ctype = core.route(
              body,
              deadline_header=self.headers.get(protocol.DEADLINE_HEADER),
              trace_id=self.headers.get(protocol.TRACE_HEADER) or None,
              klass=self.headers.get(protocol.CLASS_HEADER) or None,
              client=self.headers.get(protocol.CLIENT_HEADER)
              or self.address_string())
        except shared_faults.ServeRejection as e:
          self._reply_error(e)
          return
        self._reply(status, data,
                    content_type=ctype or protocol.CONTENT_TYPE)
      elif self.path == '/v1/register':
        body = self._read_body()
        if body is None:
          return
        try:
          spec = json.loads(body)
          url = spec['url']
          tier = spec.get('tier', registry_lib.MODEL_TIER)
          replica = core.registry.add(url, tier=tier)
        except (ValueError, KeyError, TypeError) as e:
          self._reply_error(shared_faults.BadRequestError(
              f'register expects JSON {{"url", "tier"}}: {e}'))
          return
        core.bump('n_registered')
        self._reply_json(200, {
            'registered': replica.url,
            'tier': replica.tier,
            'state': replica.state,
        })
      else:
        self._reply_json(404, {'error': f'no such path: {self.path}'})

  return Handler


class RouteHTTPServer(ThreadingHTTPServer):
  daemon_threads = True
  allow_reuse_address = True


def build_router(core: RouterCore, host: str, port: int) -> RouteHTTPServer:
  return RouteHTTPServer((host, port), _make_handler(core))


def route_main(replicas: List[str], featurize_workers: List[str],
               options: Optional[RouterOptions] = None,
               host: str = '127.0.0.1', port: int = 0,
               ready_fn=None, stop_event=None) -> Dict[str, Any]:
  """Runs the router until SIGTERM/SIGINT, then drains in-flight
  forwards. Returns the final stats dict (CLI exits 0 on clean
  drain). Mirrors serve_main's contract: ready_fn(info) fires once
  listening; stop_event is the in-process SIGTERM stand-in."""
  options = options or RouterOptions()
  obs_lib.trace.configure_from_env(tier='router')
  registry = registry_lib.ReplicaRegistry(
      probe_interval_s=options.probe_interval_s,
      probe_timeout_s=options.probe_timeout_s)
  for url in replicas:
    registry.add(url, tier=registry_lib.MODEL_TIER)
  for url in featurize_workers:
    registry.add(url, tier=registry_lib.FEATURIZE_TIER)
  core = RouterCore(registry, options)
  registry.probe_all()  # first health gate before we announce ready
  registry.start()
  httpd = build_router(core, host, port)
  bound_port = httpd.server_address[1]
  http_thread = threading.Thread(
      target=httpd.serve_forever, name='dctpu-route-http', daemon=True)
  http_thread.start()
  stop = _StopFlag()
  stop.install()
  info = {
      'event': 'ready',
      'host': host,
      'port': bound_port,
      'replicas': registry.urls(),
  }
  log.info('dctpu route ready on %s:%d fronting %d url(s)',
           host, bound_port, len(registry.urls()))
  if ready_fn is not None:
    ready_fn(info)
  try:
    while not stop.event.wait(timeout=0.5):
      if stop_event is not None and stop_event.is_set():
        break
    if stop.signum is not None:
      log.warning('signal %d: draining router', stop.signum)
    core.begin_drain()
    drained = core.drain(timeout=options.upstream_timeout_s + 10)
    if not drained:
      log.error('router drain timed out with forwards in flight')
  finally:
    stop.restore()
    registry.stop()
    httpd.shutdown()
    httpd.server_close()
  stats = core.stats()
  stats['drained'] = bool(drained)
  return stats
