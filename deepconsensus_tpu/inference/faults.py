"""Fault-tolerance layer for the inference pipeline.

The serving path polishes millions of ZMWs per run; fail-fast semantics
(one malformed ZMW aborting the whole run, a crash at ZMW 9M losing all
output) don't survive production traffic. This module provides:

* a structured error taxonomy (stage x kind) and per-ZMW quarantine
  governed by --on-zmw-error={fail,skip,ccs-fallback},
* a dead-letter sidecar (<output>.failed.jsonl) recording every
  quarantined ZMW for replay,
* a watchdog for the featurization worker pool (per-batch timeout,
  bounded retry/backoff, pool re-spawn, shm reclamation),
* a resumable progress manifest for atomic <output>.tmp writes,
* env-var fault-injection hooks driven by scripts/inject_faults.py.

Counterpart of the training-side retry/resume stack
(models/train.py run_training_with_retry); inference needs per-item
granularity rather than restart-the-world.

The error taxonomy, dead-letter sidecar, and kill-style injection
hooks now live in the shared deepconsensus_tpu/faults.py (the training
loop uses the same primitives); they are re-exported here so existing
imports keep working.
"""
from __future__ import annotations

import collections
import dataclasses
import glob
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

# Full public surface of the shared module, so callers never have to
# know which side of the split a name lives on. tests/test_dclint.py
# asserts this block stays in sync (no drift: every shared public name
# resolves here to the identical object).
from deepconsensus_tpu.faults import (  # noqa: F401 - re-exports
    ENV_CRASH_AFTER_BATCHES,
    ENV_DEVICE_HANG_AT_PACK,
    ENV_DEVICE_HANG_S,
    ENV_DEVICE_LOST_AT_PACK,
    ENV_DEVICE_LOST_AT_STEP,
    ENV_DEVICE_OOM_AT_PACK,
    ENV_FLYWHEEL_KILL_AT_STAGE,
    ENV_HOST_LOST_AT_STEP,
    ENV_HOST_LOST_HOST,
    ENV_HOST_LOST_MODE,
    ENV_HOST_REJOIN_AT_STEP,
    ENV_KILL_SHARD_READER,
    ENV_KILL_TOKEN,
    ENV_KILL_TRAIN_AT_STEP,
    ENV_KILL_ZMW,
    ENV_NAN_AT_STEP,
    ENV_POISON_WINDOW,
    ENV_PREEMPT_AT_S,
    ENV_SERVE_CLIENT_FAULT,
    ENV_SERVE_CLIENT_FAULT_ZMW,
    ENV_SIGTERM_AT_STEP,
    _TRANSIENT_MARKERS,
    BackpressureError,
    BadRequestError,
    CorruptInputError,
    CrashLoopError,
    DeadLetterWriter,
    DeadlineExceededError,
    DeviceFault,
    DeviceLostError,
    DeviceOomError,
    DispatchTimeoutError,
    DrainingError,
    ElasticRebuildError,
    ExportedArtifactMismatchError,
    FaultKind,
    FleetRejection,
    FlywheelGateError,
    FlywheelResumeError,
    FlywheelStageError,
    HostLostError,
    InjectedHostDeath,
    NonFiniteTrainingError,
    QuotaExceededError,
    ReplicaLostError,
    RequestTooLargeError,
    ServeRejection,
    WindowBucketError,
    classify_device_error,
    classify_error,
    host_rejoin_step,
    injected_crash_after_batches,
    injected_device_fault,
    injected_device_hang,
    injected_train_device_fault,
    maybe_host_lost,
    maybe_kill_flywheel_at_stage,
    maybe_kill_shard_reader,
    maybe_kill_train_at_step,
    maybe_kill_worker,
    maybe_poison_batch,
    maybe_sigterm_at_step,
    preempt_notice_after_s,
    read_dead_letters,
)

log = logging.getLogger(__name__)


# ----------------------------------------------------------------------
# Error taxonomy (inference-side stages; kinds live in the shared
# deepconsensus_tpu/faults.py)


class FaultStage:
  """Pipeline stage where a fault surfaced."""

  DECODE = 'decode'        # BAM/BGZF stream decoding (feeder)
  FEATURIZE = 'featurize'  # alignment expansion / pileup / windows
  MODEL = 'model'          # device dispatch / forward pass
  STITCH = 'stitch'        # window stitching / output formatting

  ALL = (DECODE, FEATURIZE, MODEL, STITCH)


class OnZmwError:
  """--on-zmw-error policy values."""

  FAIL = 'fail'
  SKIP = 'skip'
  CCS_FALLBACK = 'ccs-fallback'

  CHOICES = (FAIL, SKIP, CCS_FALLBACK)


@dataclasses.dataclass
class ZmwFault(Exception):
  """A classified per-ZMW failure."""

  zmw: Optional[str]
  stage: str
  kind: str
  message: str

  def __str__(self) -> str:
    return (
        f'[{self.stage}/{self.kind}] zmw={self.zmw or "<stream>"}: '
        f'{self.message}'
    )


class WatchdogTimeout(RuntimeError):
  """A featurization batch exhausted its watchdog retries."""


# ----------------------------------------------------------------------
# CCS fallback payloads


@dataclasses.dataclass
class CcsFallback:
  """The draft CCS read emitted in place of a quarantined ZMW so yield
  degrades gracefully instead of the read (or run) disappearing."""

  molecule_name: str
  sequence: str
  quality_scores: np.ndarray  # int array, one per base
  ec: Optional[float] = None
  np_num_passes: Optional[int] = None
  rq: Optional[float] = None
  rg: Optional[str] = None


def fallback_from_record(record) -> CcsFallback:
  """Builds a fallback from a raw ccs BamRecord (feeder stage)."""
  n = len(record.seq)
  quals = (
      np.asarray(record.quals, dtype=np.int64)
      if record.quals is not None else np.zeros(n, dtype=np.int64)
  )
  tags = record.tags
  return CcsFallback(
      molecule_name=record.qname,
      sequence=record.seq,
      quality_scores=quals,
      ec=tags.get('ec'),
      np_num_passes=tags.get('np'),
      rq=tags.get('rq'),
      rg=tags.get('RG'),
  )


def fallback_from_ccs_read(ccs_read) -> CcsFallback:
  """Builds a fallback from an expanded AlignedRead draft CCS
  (featurize stage: zmw_input's subreads[-1])."""
  from deepconsensus_tpu.utils import phred

  return CcsFallback(
      molecule_name=ccs_read.name,
      sequence=phred.encoded_sequence_to_string(ccs_read.bases),
      quality_scores=np.asarray(ccs_read.base_quality_scores,
                                dtype=np.int64),
      ec=ccs_read.ec,
      np_num_passes=ccs_read.np_num_passes,
      rq=ccs_read.rq,
      rg=ccs_read.rg,
  )


# ----------------------------------------------------------------------
# Quarantine


class Quarantine:
  """Applies the --on-zmw-error policy to per-ZMW faults.

  handle() re-raises under the 'fail' policy; otherwise it records a
  dead letter, bumps counters, and returns the CcsFallback to emit (or
  None). Thread-safe: the producer thread (feeder/featurize) and the
  consumer thread (model/stitch) both report faults.
  """

  def __init__(self, policy: str, dead_letter: Optional[DeadLetterWriter]):
    if policy not in OnZmwError.CHOICES:
      # dclint: allow=typed-faults (flag validation at startup: the
      # CLI maps ValueError to operator-error exit code 2)
      raise ValueError(
          f'on_zmw_error must be one of {OnZmwError.CHOICES}, '
          f'got {policy!r}'
      )
    self.policy = policy
    self.dead_letter = dead_letter
    self.counters: collections.Counter = collections.Counter()
    self._lock = threading.Lock()

  def handle(
      self,
      zmw: Optional[str],
      stage: str,
      error: BaseException | str,
      fallback: Optional[Callable[[], Optional[CcsFallback]]] = None,
      extra: Optional[Dict[str, Any]] = None,
  ) -> Optional[CcsFallback]:
    """Quarantines one ZMW. fallback is a thunk (evaluated only under
    the ccs-fallback policy) producing the draft-CCS payload, or None
    when no draft is recoverable (the quarantine downgrades to skip).
    extra rides into the dead-letter line — model-pack failures use it
    to attribute one shared device fault to every member molecule."""
    if self.policy == OnZmwError.FAIL:
      if isinstance(error, BaseException):
        raise error
      raise ZmwFault(zmw, stage, classify_error(error), error)
    text = (
        error if isinstance(error, str)
        else f'{type(error).__name__}: {error}'
    )
    kind = classify_error(text)
    payload = None
    action = OnZmwError.SKIP
    if self.policy == OnZmwError.CCS_FALLBACK and fallback is not None:
      try:
        payload = fallback()
      # dclint: allow=typed-faults (the fallback failing degrades the
      # action to skip; the quarantine record below still routes it)
      except Exception as fb_err:  # fallback itself unrecoverable
        log.warning('ccs-fallback for %s failed (%s); skipping', zmw, fb_err)
      if payload is not None:
        action = OnZmwError.CCS_FALLBACK
    with self._lock:
      self.counters['n_zmw_quarantined'] += 1
      self.counters[f'n_fault_{stage}'] += 1
      if action == OnZmwError.CCS_FALLBACK:
        self.counters['n_zmw_ccs_fallback'] += 1
      else:
        self.counters['n_zmw_skipped_on_error'] += 1
      if self.dead_letter is not None:
        self.dead_letter.record(zmw, stage, kind, text, action, extra=extra)
    log.warning('quarantined zmw=%s stage=%s kind=%s action=%s: %s',
                zmw, stage, kind, action, text.splitlines()[-1] if text
                else text)
    return payload

  def bump(self, key: str, n: int = 1) -> None:
    with self._lock:
      self.counters[key] += n


# ----------------------------------------------------------------------
# Worker-pool watchdog


def reclaim_shm_segments(prefix: str) -> int:
  """Unlinks every /dev/shm segment carrying this run/batch prefix —
  the only owner record left after a worker was SIGKILLed (the worker
  unregisters its segments from its resource tracker before handing
  ownership to the parent)."""
  if not prefix:
    return 0
  n = 0
  for path in glob.glob(f'/dev/shm/{glob.escape(prefix)}*'):
    try:
      os.unlink(path)
      n += 1
    except OSError:
      pass
  if n:
    log.warning('reclaimed %d leaked shm segment(s) with prefix %s',
                n, prefix)
  return n


class PoolWatchdog:
  """Supervises the featurization multiprocessing.Pool.

  run_batch() bounds each starmap with a timeout; a hung or SIGKILLed
  worker (multiprocessing.Pool silently loses the in-flight task when a
  worker dies, so its result never arrives) surfaces as a timeout. The
  watchdog then reclaims the batch's shm segments, terminates and
  re-spawns the pool, backs off, and retries the whole batch; after
  `retries` failed retries it raises WatchdogTimeout for the quarantine
  layer to apply the --on-zmw-error policy.
  """

  # Pool-machinery failures that merit a respawn/retry like a timeout.
  _POOL_ERRORS = (BrokenPipeError, EOFError, ConnectionError)

  def __init__(
      self,
      make_pool: Callable[[], Any],
      timeout: float = 0.0,
      retries: int = 2,
      backoff: float = 0.5,
      quarantine: Optional[Quarantine] = None,
  ):
    self._make_pool = make_pool
    self.timeout = timeout
    self.retries = max(0, retries)
    self.backoff = backoff
    self.quarantine = quarantine
    self.pool = make_pool()

  def _bump(self, key: str) -> None:
    if self.quarantine is not None:
      self.quarantine.bump(key)

  def run_batch(self, func, tasks, chunksize: int, shm_prefix: str = ''):
    """starmap with watchdog semantics; returns the results list."""
    import multiprocessing

    if not self.timeout:
      return self.pool.starmap(func, tasks, chunksize=chunksize)
    last_error = 'timeout'
    for attempt in range(self.retries + 1):
      if attempt:
        self._bump('n_watchdog_retries')
        time.sleep(self.backoff * (2 ** (attempt - 1)))
      async_result = self.pool.starmap_async(
          func, tasks, chunksize=chunksize
      )
      try:
        return async_result.get(self.timeout)
      except multiprocessing.TimeoutError:
        last_error = f'no result within {self.timeout}s'
      except self._POOL_ERRORS as e:
        last_error = f'pool failure: {type(e).__name__}: {e}'
      self._bump('n_watchdog_timeouts')
      log.warning(
          'featurization batch watchdog fired (attempt %d/%d): %s; '
          're-spawning the worker pool',
          attempt + 1, self.retries + 1, last_error,
      )
      self.respawn(shm_prefix)
    raise WatchdogTimeout(
        f'featurization batch failed the watchdog {self.retries + 1} '
        f'time(s): {last_error}'
    )

  def respawn(self, shm_prefix: str = '') -> None:
    """Terminates the (possibly hung) pool, reclaims this batch's shm
    segments, and brings up a fresh pool."""
    try:
      self.pool.terminate()
      self.pool.join()
    # dclint: allow=typed-faults (teardown is best-effort: the pool is
    # being replaced; shm reclamation below still runs)
    except Exception as e:  # pragma: no cover - teardown best-effort
      log.warning('pool terminate failed: %s', e)
    reclaim_shm_segments(shm_prefix)
    self._bump('n_pool_respawns')
    self.pool = self._make_pool()

  def close(self) -> None:
    try:
      self.pool.close()
      self.pool.join()
    # dclint: allow=typed-faults (teardown is best-effort: escalate a
    # failed close to terminate, nothing to route)
    except Exception:  # pragma: no cover - teardown best-effort
      self.pool.terminate()
      self.pool.join()


# ----------------------------------------------------------------------
# Resumable, atomic output


class ProgressManifest:
  """Crash-consistent progress record for <output>.tmp.

  Commits are atomic (write + rename) and record the number of feeder
  groups fully written plus the flushed tmp-file size, so --resume can
  truncate the tmp output to the last committed byte and skip exactly
  the committed ZMW groups. `source` pins the input identity; resuming
  against a different input fails loudly.
  """

  VERSION = 1

  def __init__(self, path: str):
    self.path = path

  def commit(self, groups_done: int, tmp_size: int,
             source: Dict[str, Any], last_zmw: Optional[str] = None,
             extra: Optional[Dict[str, Any]] = None) -> None:
    state = {
        'version': self.VERSION,
        'groups_done': groups_done,
        'tmp_size': tmp_size,
        'last_zmw': last_zmw,
        'source': source,
        'time': time.time(),
    }
    if extra:
      state.update(extra)
    tmp = self.path + '.tmp'
    with open(tmp, 'w') as f:
      json.dump(state, f)
      f.flush()
      os.fsync(f.fileno())
    os.replace(tmp, self.path)

  def load(self) -> Optional[Dict[str, Any]]:
    try:
      with open(self.path) as f:
        state = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
      return None
    if state.get('version') != self.VERSION:
      log.warning('ignoring %s with unknown version %s', self.path,
                  state.get('version'))
      return None
    return state

  def delete(self) -> None:
    for path in (self.path, self.path + '.tmp'):
      try:
        os.unlink(path)
      except FileNotFoundError:
        pass


def validate_resume_source(state: Dict[str, Any],
                           source: Dict[str, Any]) -> None:
  """A manifest written for different inputs/options must not silently
  graft a resumed run onto them."""
  recorded = state.get('source') or {}
  for key, value in source.items():
    if recorded.get(key) != value:
      # dclint: allow=typed-faults (operator error at startup; tests
      # and the CLI rely on ValueError('manifest mismatch ...'))
      raise ValueError(
          f'--resume manifest mismatch for {key!r}: run was started '
          f'with {recorded.get(key)!r}, resume requested {value!r} '
          f'(delete the .progress.json to restart from scratch)'
      )


# Fault-injection hooks (ENV_KILL_ZMW / ENV_KILL_TOKEN /
# ENV_CRASH_AFTER_BATCHES, maybe_kill_worker,
# injected_crash_after_batches) are re-exported from the shared
# deepconsensus_tpu/faults.py above.
