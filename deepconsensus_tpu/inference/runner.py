"""Batched inference: BAM -> windows -> jitted model -> stitched FASTQ.

TPU-native re-design of the reference's quick_inference driver
(reference: deepconsensus/inference/quick_inference.py:68-984):

* Featurization runs the vectorized preprocess core (no per-base Python
  loops), so the host keeps up with the accelerator without a process
  pool for moderate workloads; a pool can still fan it out.
* The model step is one jitted function over fixed-shape batches
  (padded final batch) returning argmax bases and max probabilities,
  so only two small arrays cross the device boundary per batch.
* Window skip triage (CCS quality above threshold, overflow windows)
  happens on host exactly like the reference, including CCS-quality
  calibration of skipped windows.
* Per-stage wall-time is recorded and dumped to <output>.runtime.csv.
"""
from __future__ import annotations

import collections
import csv
import dataclasses
import itertools
import json
import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepconsensus_tpu.calibration import lib as calibration_lib
from deepconsensus_tpu.io import bam as bam_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import data as data_lib
from deepconsensus_tpu.models import model as model_lib
from deepconsensus_tpu.postprocess import stitch
from deepconsensus_tpu.preprocess import (
    FeatureLayout,
    create_proc_feeder,
    reads_to_pileup,
)
from deepconsensus_tpu.preprocess.pileup import row_indices
from deepconsensus_tpu.utils import phred

log = logging.getLogger(__name__)


@dataclasses.dataclass
class InferenceOptions:
  """Knobs shared across inference stages
  (reference: quick_inference.py:243-275)."""

  max_length: int = 100
  max_passes: int = 20
  min_quality: int = 20
  min_length: int = 0
  batch_size: int = 1024
  batch_zmws: int = 100
  use_ccs_bq: bool = False
  skip_windows_above: int = 45
  ins_trim: int = 5
  use_ccs_smart_windows: bool = False
  max_base_quality: int = 93
  limit: int = 0
  # (i, n): keep only ZMWs with zm % n == i — single-flag fleet scaling
  # over one shared BAM (the reference's shard-the-BAM pattern without
  # the external splitting step).
  shard: Optional[Tuple[int, int]] = None
  # >0: featurization worker pool. Measured caveat: shipping featurized
  # windows between processes is IPC-bound (~6 MB/ZMW), so on fast
  # hosts the serial path (~20k windows/s, matching one chip's forward
  # throughput) wins; scale across chips by sharding input BAMs into
  # separate runs like the reference's 500-shard pattern.
  cpus: int = 0
  # Max batches in flight on the device before the oldest is drained.
  # Per-dispatch round trips dominate run_model over a tunneled chip
  # (VERDICT r2 #2: 4.78 s of a 6.3 s batch at depth 1); a deeper
  # pipeline overlaps transfer latency of batches i+1..i+k with the
  # compute of batch i. Device-side cost per in-flight batch is one
  # uint8 input buffer (~21 MB at b1024) + tiny outputs.
  dispatch_depth: int = 8
  # Debug stage truncation (reference DebugStage: quick_inference.py:68-75).
  end_after_stage: str = 'full'  # dc_input | tf_examples | run_model | full
  dc_calibration_values: calibration_lib.QualityCalibrationValues = (
      dataclasses.field(
          default_factory=lambda: calibration_lib.parse_calibration_string(
              'skip'
          )
      )
  )
  ccs_calibration_values: calibration_lib.QualityCalibrationValues = (
      dataclasses.field(
          default_factory=lambda: calibration_lib.parse_calibration_string(
              'skip'
          )
      )
  )


_SN_ROWS = 4  # trailing rows: per-window SN constants (layout: pileup.py)


def _assemble_rows(main_u8: jnp.ndarray, sn: jnp.ndarray,
                   bq_row: Optional[int] = None) -> jnp.ndarray:
  """Device-side inverse of dispatch()'s compact split: uint8 rows ->
  f32, SN scalars re-broadcast across the window.

  bq_row: index of the ccs_bq row inside main_u8, if the model uses
  one. That row travels biased by +1 (its spaced values include -1
  sentinels at gap columns / padded tails, which a plain uint8 cast
  would wrap to 255); undo the bias here.
  """
  b, _, l, _ = main_u8.shape
  main = main_u8.astype(jnp.float32)
  if bq_row is not None:
    main = main.at[:, bq_row].add(-1.0)
  sn_rows = jnp.broadcast_to(
      sn.astype(jnp.float32)[:, :, None, None], (b, _SN_ROWS, l, 1)
  )
  return jnp.concatenate([main, sn_rows], axis=1)


def _bq_row_index(params) -> Optional[int]:
  """Row index of the ccs_bq row within the non-SN block, taken from
  the canonical layout (pileup.row_indices) rather than re-derived.

  Also guards the compact-transport assumption: every non-SN row must
  fit 0..255 after the ccs_bq +1 bias, and PW_MAX/IP_MAX are
  config-tunable, so fail loudly instead of silently truncating.
  """
  from deepconsensus_tpu.preprocess import pileup

  if params.PW_MAX > 255 or params.IP_MAX > 255:
    raise ValueError(
        f'compact uint8 dispatch requires PW_MAX/IP_MAX <= 255, got '
        f'{params.PW_MAX}/{params.IP_MAX}'
    )
  if not params.use_ccs_bq:
    return None
  bq_lo, _bq_hi = pileup.row_indices(params.max_passes, True)[5]
  return bq_lo


def _check_dp_divisible(options: 'InferenceOptions', mesh) -> int:
  """The compiled batch splits evenly over the mesh data axis; returns
  the data-axis size."""
  from deepconsensus_tpu.parallel import mesh as mesh_lib

  dp = mesh.shape[mesh_lib.DATA_AXIS]
  if options.batch_size % dp:
    raise ValueError(
        f'batch_size={options.batch_size} not divisible by the mesh '
        f'data axis ({dp} devices)'
    )
  return dp


class ModelRunner:
  """Jitted forward pass producing (bases, quality scores) per window.

  With a mesh, the window batch is sharded over the mesh's data axis
  (weights replicated), so one process drives every chip — the
  multi-chip counterpart of the reference's shard-the-BAM pattern
  (quick_inference.py 500-shard runs)."""

  def __init__(self, params, variables, options: InferenceOptions,
               mesh=None):
    self.params = params
    self.variables = variables
    self.options = options
    self.mesh = mesh
    if mesh is not None:
      from deepconsensus_tpu.parallel import mesh as mesh_lib

      _check_dp_divisible(options, mesh)
      # Place the weights on the mesh once; otherwise every forward
      # re-broadcasts host arrays to all devices. param_shardings
      # shards attention heads / FFN filters on the model axis under
      # tp>1 and degenerates to replication at tp=1 (same rules as
      # training); the non-params collections always replicate.
      if variables:
        self.variables = {
            key: jax.device_put(
                value,
                mesh_lib.param_shardings(mesh, value)
                if key == 'params' else mesh_lib.replicated(mesh),
            )
            for key, value in variables.items()
        }
    model = model_lib.get_model(params)
    self._bq_row = _bq_row_index(params)
    bq_row = self._bq_row

    def forward(variables, main_u8, sn):
      rows = _assemble_rows(main_u8, sn, bq_row)
      preds = model.apply(variables, rows)
      pred_ids = jnp.argmax(preds, axis=-1).astype(jnp.int32)
      max_prob = jnp.max(preds, axis=-1)
      return pred_ids, max_prob

    self._forward = self._jit_forward(forward, mesh)

  @staticmethod
  def _jit_forward(forward, mesh):
    if mesh is None:
      return jax.jit(forward)
    from deepconsensus_tpu.parallel import mesh as mesh_lib

    batch_sh = mesh_lib.batch_sharding(mesh)
    return jax.jit(
        forward,
        # Variables keep the placement __init__ gave them (replicated,
        # or model-axis sharded under tp>1).
        in_shardings=(None, batch_sh, batch_sh),
        out_shardings=(batch_sh, batch_sh),
    )

  @classmethod
  def from_checkpoint(cls, checkpoint_path: str,
                      options: InferenceOptions,
                      mesh=None) -> 'ModelRunner':
    """Loads either an orbax checkpoint or an exported StableHLO
    artifact directory (the reference's SavedModel-vs-checkpoint
    detection: quick_inference.py:797-800,512-529)."""
    import os

    from deepconsensus_tpu.models import export as export_lib
    from deepconsensus_tpu.models.checkpoints import load_params

    if os.path.isdir(checkpoint_path) and os.path.exists(
        os.path.join(checkpoint_path, export_lib.ARTIFACT_NAME)
    ):
      return cls.from_exported(checkpoint_path, options, mesh=mesh)

    params = config_lib.read_params_from_json(checkpoint_path)
    config_lib.finalize_params(params, is_training=False)
    return cls(params, {'params': load_params(checkpoint_path)}, options,
               mesh=mesh)

  @classmethod
  def from_exported(cls, export_dir: str,
                    options: InferenceOptions,
                    mesh=None) -> 'ModelRunner':
    """Serves an exported StableHLO artifact (params baked in).

    With a mesh, the single-device program serves data-parallel: each
    device runs the artifact on its batch shard under shard_map (the
    batch-polymorphic export accepts the per-device shape), matching
    the reference's any-topology SavedModel serving. Requires a
    polymorphic artifact and a pure-DP mesh — the baked program can't
    be re-sharded on the model axis.
    """
    from deepconsensus_tpu.models import export as export_lib

    serving, meta = export_lib.load_exported(export_dir)
    params = config_lib.read_params_from_json(export_dir)
    config_lib.finalize_params(params, is_training=False)
    runner = cls.__new__(cls)
    runner.params = params
    runner.variables = None
    if not meta.get('polymorphic_batch'):
      # Fixed-batch artifact: the compiled shape wins over the flag.
      if mesh is not None:
        raise ValueError(
            'mesh/--dp serving of an exported artifact requires a '
            'batch-polymorphic export (this artifact is fixed-batch; '
            're-export with polymorphic_batch=True)'
        )
      options.batch_size = int(meta['batch_size'])
    runner.options = options
    runner.mesh = mesh
    runner._bq_row = _bq_row_index(params)
    bq_row = runner._bq_row

    def apply_serving(main_u8, sn):
      preds = serving(_assemble_rows(main_u8, sn, bq_row))
      return (
          jnp.argmax(preds, axis=-1).astype(jnp.int32),
          jnp.max(preds, axis=-1),
      )

    if mesh is None:
      runner._forward = jax.jit(
          lambda _variables, main_u8, sn: apply_serving(main_u8, sn))
      return runner

    from jax.sharding import PartitionSpec
    try:
      from jax import shard_map as shard_map_lib  # jax >= 0.8
      shard_map = shard_map_lib
    except ImportError:  # pragma: no cover - older jax
      from jax.experimental.shard_map import shard_map
    from deepconsensus_tpu.parallel import mesh as mesh_lib

    if mesh_lib.MODEL_AXIS in mesh.shape and (
        mesh.shape[mesh_lib.MODEL_AXIS] > 1):
      raise ValueError(
          'exported artifacts serve data-parallel only (the compiled '
          'program cannot be re-sharded on the model axis); use tp=1 '
          'or an orbax checkpoint'
      )
    _check_dp_divisible(options, mesh)
    batch_spec = PartitionSpec(mesh_lib.DATA_AXIS)
    sharded_serving = shard_map(
        apply_serving, mesh=mesh,
        in_specs=(batch_spec, batch_spec),
        out_specs=(batch_spec, batch_spec),
    )
    runner._forward = jax.jit(
        lambda _variables, main_u8, sn: sharded_serving(main_u8, sn))
    return runner

  def dispatch(self, rows: np.ndarray):
    """Async device dispatch: rows [B, R, L, 1] -> (dev_ids, dev_prob, n).

    Pads to the fixed compiled batch shape and returns device arrays
    immediately so the next batch's host work overlaps device compute.

    Transfer is compact: every non-SN row holds clip-bounded integers
    (bases/ccs 0-4, pw/ip <= PW_MAX/IP_MAX = 255, strand 0-2, ccs_bq
    -1..93 shipped biased by +1), and the 4 SN rows are per-window
    constants, so the batch ships as uint8 rows + [B, 4] float SN
    scalars (~4x less than f32 rows over PCIe/tunnel) and reassembles
    losslessly on device (_assemble_rows undoes the ccs_bq bias).
    """
    n = rows.shape[0]
    batch = self.options.batch_size
    if n < batch:
      pad = np.zeros((batch - n,) + rows.shape[1:], rows.dtype)
      rows = np.concatenate([rows, pad])
    main = rows[:, :-_SN_ROWS]
    main_u8 = main.astype(np.uint8)
    if self._bq_row is not None:
      # Spaced ccs_bq holds -1 sentinels; bias to 0..94 so the uint8
      # cast is lossless (the device side subtracts 1 back).
      main_u8[:, self._bq_row] = (main[:, self._bq_row] + 1.0).astype(
          np.uint8)
    sn = np.ascontiguousarray(rows[:, -_SN_ROWS:, 0, 0].astype(np.float32))
    pred_ids, max_prob = self._forward(
        self.variables, jnp.asarray(main_u8), jnp.asarray(sn)
    )
    return pred_ids, max_prob, n

  def finalize(self, dispatched) -> Tuple[np.ndarray, np.ndarray]:
    """Resolves a dispatch into (base ids [n, L], quality [n, L])."""
    pred_ids, max_prob, n = dispatched
    pred_ids = np.asarray(pred_ids[:n])
    max_prob = np.asarray(max_prob[:n])
    error_prob = np.maximum(1.0 - max_prob, 1e-12)
    quality = -10.0 * np.log10(error_prob)
    opts = self.options
    if opts.dc_calibration_values.enabled:
      quality = calibration_lib.calibrate_quality_scores(
          quality, opts.dc_calibration_values
      )
    quality = np.minimum(quality, opts.max_base_quality)
    quality = np.round(quality, decimals=0).astype(np.int32)
    quality = np.maximum(quality, 0)
    return pred_ids, quality

  def predict(self, rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Synchronous convenience wrapper."""
    return self.finalize(self.dispatch(rows))


def preprocess_zmw(
    zmw_input, options: InferenceOptions
) -> Tuple[List[Dict[str, Any]], collections.Counter]:
  """One ZMW -> list of window feature dicts
  (reference: quick_inference.py:535-564)."""
  subreads, name, layout, _split, window_widths = zmw_input
  pileup = reads_to_pileup(subreads, name, layout, window_widths)
  features = list(pileup.iter_window_features())
  return features, pileup.counter


# Feature-dict fields shipped as plain pickled metadata by the shm
# transport (everything except the bulk 'subreads' tensor).
_SHM_META_FIELDS = (
    'subreads/num_passes', 'name', 'window_pos',
    'ccs_base_quality_scores', 'overflow', 'ec', 'np_num_passes', 'rq',
    'rg',
)


def preprocess_zmw_shm(zmw_input, options: InferenceOptions):
  """Pool-worker variant: the bulk window tensors travel through one
  POSIX shared-memory segment per ZMW instead of the result pickle.

  The pickle channel is the measured bottleneck of the worker pool
  (~6 MB/ZMW through a pipe); with shm the pickle carries only names
  and offsets. Returns (shm_name, window_metadata, counter); the
  parent re-views the tensors with _features_from_shm and owns the
  segment's lifetime (workers unregister from their resource tracker).
  """
  from multiprocessing import resource_tracker, shared_memory

  features, counter = preprocess_zmw(zmw_input, options)
  total = sum(f['subreads'].nbytes for f in features)
  if not total:
    return None, [{k: f[k] for k in _SHM_META_FIELDS} for f in features
                  ], counter
  shm = shared_memory.SharedMemory(create=True, size=total)
  try:
    meta = []
    offset = 0
    for f in features:
      arr = f['subreads']
      flat = np.ndarray(arr.shape, arr.dtype, buffer=shm.buf,
                        offset=offset)
      flat[...] = arr
      entry = {k: f[k] for k in _SHM_META_FIELDS}
      # bq values fit int16 (-1..93); int64 would dominate the metadata
      # pickle (~120 KB/ZMW of the ~130 KB total).
      entry['ccs_base_quality_scores'] = (
          entry['ccs_base_quality_scores'].astype(np.int16)
      )
      entry['_shape'] = arr.shape
      entry['_dtype'] = arr.dtype.str
      entry['_offset'] = offset
      offset += arr.nbytes
      meta.append(entry)
  except BaseException:
    # Packing failed: this worker still owns the segment.
    shm.close()
    shm.unlink()
    raise
  name = shm.name
  shm.close()
  # The worker's resource tracker would unlink the segment when the
  # worker exits; ownership transfers to the parent instead.
  try:
    resource_tracker.unregister(f'/{name}', 'shared_memory')
  except Exception:  # pragma: no cover - tracker internals shifted
    pass
  return name, meta, counter


def _pool_worker(zmw_input, options: InferenceOptions):
  """starmap payload: never raises, so the parent always receives every
  created shm name (a raising task would make starmap discard ALL
  results, orphaning the successful workers' segments forever)."""
  try:
    return 'ok', preprocess_zmw_shm(zmw_input, options)
  except BaseException:
    import traceback

    return 'error', traceback.format_exc()


def _features_from_shm(result):
  """Parent-side inverse of preprocess_zmw_shm.

  Returns (features, counter, shm_handle_or_None); the caller must
  close+unlink the handle once the features are consumed.
  """
  from multiprocessing import shared_memory

  shm_name, meta, counter = result
  shm = None
  features = []
  if shm_name is not None:
    shm = shared_memory.SharedMemory(name=shm_name)
  for entry in meta:
    f = {k: entry[k] for k in _SHM_META_FIELDS}
    f['ccs_base_quality_scores'] = (
        f['ccs_base_quality_scores'].astype(np.int64)
    )
    if shm is not None:
      f['subreads'] = np.ndarray(
          entry['_shape'], np.dtype(entry['_dtype']), buffer=shm.buf,
          offset=entry['_offset'],
      )
    features.append(f)
  return features, counter, shm


def process_skipped_window(
    feature_dict: Dict[str, Any], options: InferenceOptions
) -> stitch.DCModelOutput:
  """Adopts the CCS bases/qualities for a skipped window
  (reference: quick_inference.py:567-594)."""
  rows = feature_dict['subreads']
  ccs_range = row_indices(options.max_passes, options.use_ccs_bq)[4]
  ccs = rows[ccs_range[0], :, 0]
  ccs_seq = phred.encoded_sequence_to_string(ccs)
  quals = np.asarray(feature_dict['ccs_base_quality_scores'])
  if options.ccs_calibration_values.enabled:
    quals = calibration_lib.calibrate_quality_scores(
        quals, options.ccs_calibration_values
    )
  quals = np.minimum(quals, options.max_base_quality).astype(np.int32)
  return stitch.DCModelOutput(
      window_pos=feature_dict['window_pos'],
      molecule_name=feature_dict['name'],
      sequence=ccs_seq,
      quality_string=phred.quality_scores_to_string(np.maximum(quals, 0)),
      ec=feature_dict['ec'],
      np_num_passes=feature_dict['np_num_passes'],
      rq=feature_dict['rq'],
      rg=feature_dict['rg'],
  )


def _triage_windows(
    feature_dicts: List[Dict[str, Any]],
    options: InferenceOptions,
    counter: collections.Counter,
) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
  """Splits windows into (model, skip) per overflow/quality rules
  (reference: quick_inference.py:653-678)."""
  to_model: List[Dict[str, Any]] = []
  to_skip: List[Dict[str, Any]] = []
  for fd in feature_dicts:
    if fd['overflow']:
      to_skip.append(fd)
      counter['n_windows_overflow_skipped'] += 1
      continue
    if options.skip_windows_above:
      avg_q = phred.avg_phred(fd['ccs_base_quality_scores'])
      # Strictly above, matching the reference (quick_inference.py:671).
      if avg_q > options.skip_windows_above:
        to_skip.append(fd)
        counter['n_windows_quality_skipped'] += 1
        continue
    to_model.append(fd)
    counter['n_windows_to_model'] += 1
  return to_model, to_skip


def run_model_on_windows(
    feature_dicts: List[Dict[str, Any]],
    runner: ModelRunner,
    params,
    options: InferenceOptions,
) -> List[stitch.DCModelOutput]:
  """Formats, batches, and runs windows through the model
  (reference: quick_inference.py:341-415)."""
  outputs: List[stitch.DCModelOutput] = []

  # Pipelined: keep up to options.dispatch_depth batches in flight so
  # host-side stacking/quality math and per-dispatch transfer latency
  # overlap device compute; drain in order.
  pending: List[Tuple[List, Any]] = []
  depth = max(1, options.dispatch_depth)

  def drain(entry):
    chunk, dispatched = entry
    pred_ids, quality = runner.finalize(dispatched)
    for c, ids, quals in zip(chunk, pred_ids, quality):
      outputs.append(
          stitch.DCModelOutput(
              window_pos=c['window_pos'],
              molecule_name=c['name'] if isinstance(c['name'], str)
              else c['name'].decode(),
              sequence=phred.encoded_sequence_to_string(ids),
              quality_string=phred.quality_scores_to_string(quals),
              ec=c['ec'],
              np_num_passes=c['np_num_passes'],
              rq=c['rq'],
              rg=c['rg'],
          )
      )

  for start in range(0, len(feature_dicts), options.batch_size):
    chunk = feature_dicts[start : start + options.batch_size]
    raw = np.stack([c['subreads'] for c in chunk])
    rows = data_lib.format_rows_batch(raw, params)
    pending.append((chunk, runner.dispatch(rows)))
    if len(pending) > depth:
      drain(pending.pop(0))
  while pending:
    drain(pending.pop(0))
  return outputs


def run_inference(
    subreads_to_ccs: str,
    ccs_bam: Optional[str],
    checkpoint: Optional[str],
    output: str,
    options: Optional[InferenceOptions] = None,
    runner: Optional[ModelRunner] = None,
    ccs_fasta: Optional[str] = None,
    mesh=None,
) -> Dict[str, Any]:
  """Full inference pipeline; returns the counters dict
  (reference run(): quick_inference.py:794-963)."""
  options = options or InferenceOptions()
  if runner is None:
    if checkpoint is None:
      raise ValueError('need checkpoint or runner')
    runner = ModelRunner.from_checkpoint(checkpoint, options, mesh=mesh)
  params = runner.params
  options.max_passes = params.max_passes
  options.max_length = params.max_length
  options.use_ccs_bq = params.use_ccs_bq

  layout = FeatureLayout(
      max_passes=options.max_passes,
      max_length=options.max_length,
      use_ccs_bq=options.use_ccs_bq,
  )
  feeder, counter = create_proc_feeder(
      subreads_to_ccs=subreads_to_ccs,
      ccs_bam=ccs_bam,
      ccs_fasta=ccs_fasta,
      layout=layout,
      ins_trim=options.ins_trim,
      use_ccs_smart_windows=options.use_ccs_smart_windows,
      limit=options.limit,
      shard=options.shard,
  )
  pool = None
  if (options.cpus and options.cpus > 1
      and options.end_after_stage != 'dc_input'):
    # dc_input runs never featurize; forking idle workers would only
    # pollute the stage timing the flag exists to measure.
    import multiprocessing

    pool = multiprocessing.Pool(options.cpus)
  outcome = stitch.OutcomeCounter()
  window_counter: collections.Counter = collections.Counter()
  timing_rows: List[Dict[str, Any]] = []
  fastq_lines = 0

  if output.endswith('.bam'):
    from deepconsensus_tpu.io.bam_writer import BamWriter

    # Carry the CCS BAM header (RG/PG lines) into the output so the
    # per-read RG:Z tags reference declared read groups, as the
    # reference does by opening the writer with template=ccs
    # (quick_inference.py:894-897). Falls back to a bare @HD when no
    # CCS BAM is in play (ccs_fasta mode).
    header_text = '@HD\tVN:1.5\tSO:unknown\n'
    if ccs_bam:
      with bam_lib.BamReader(ccs_bam) as ccs_reader:
        if ccs_reader.header_text:
          header_text = ccs_reader.header_text
          if not header_text.endswith('\n'):
            header_text += '\n'
    writer = BamWriter(output, header_text=header_text)

    def emit(fastq_str: str, dc_outputs) -> None:
      name, seq, _, qual = fastq_str.rstrip('\n').split('\n')
      first = dc_outputs[0]
      tags = {}
      if first.ec is not None:
        tags['ec'] = float(first.ec)
      if first.np_num_passes is not None:
        tags['np'] = int(first.np_num_passes)
      if first.rq is not None:
        tags['rq'] = float(first.rq)
      if first.rg is not None:
        tags['RG'] = str(first.rg)
      tags['zm'] = int(name[1:].split('/')[1])
      writer.write(
          name[1:],
          seq,
          np.array(phred.quality_string_to_array(qual), dtype=np.uint8),
          tags=tags,
      )

    close_out = writer.close
  else:
    writer = open(output, 'w')

    def emit(fastq_str: str, dc_outputs) -> None:
      del dc_outputs
      writer.write(fastq_str)

    close_out = writer.close

  try:

    def featurize_batch(zmw_batch):
      """Producer-side: BAM records -> window features for one batch."""
      t0 = time.time()
      all_windows: List[Dict[str, Any]] = []
      zmw_counters = []
      shm_handles = []
      n_subreads = 0
      if pool is not None:
        # Bulk tensors travel via shared memory; the result pickle
        # carries only names/offsets (the pipe was the bottleneck).
        # _pool_worker never raises, so starmap always returns and the
        # parent always sees every created shm name (a raising task
        # would discard ALL results, orphaning sibling segments).
        raw = pool.starmap(
            _pool_worker, [(z, options) for z in zmw_batch], chunksize=4,
        )
        results = []
        try:
          for status, payload in raw:
            if status != 'ok':
              raise RuntimeError(
                  f'featurization worker failed:\n{payload}'
              )
            features, zmw_counter, shm = _features_from_shm(payload)
            results.append((features, zmw_counter))
            if shm is not None:
              shm_handles.append(shm)
        except BaseException:
          # Workers unregistered the segments from their resource
          # tracker, so this is the only cleanup: unlink every segment
          # named in raw (attached or not) before propagating.
          from multiprocessing import shared_memory

          attached = {s.name for s in shm_handles}
          for shm in shm_handles:
            try:
              shm.close()
              shm.unlink()
            except OSError:
              pass
          for status, payload in raw:
            if (status == 'ok' and payload[0] is not None
                and payload[0] not in attached):
              try:
                leaked = shared_memory.SharedMemory(name=payload[0])
                leaked.close()
                leaked.unlink()
              except OSError:
                pass
          raise
      else:
        results = (preprocess_zmw(z, options) for z in zmw_batch)
      for zmw_input, (features, zmw_counter) in zip(zmw_batch, results):
        n_subreads += len(zmw_input[0]) - 1
        zmw_counters.append(zmw_counter)
        all_windows.extend(features)
      return {
          'windows': all_windows,
          'counters': zmw_counters,
          'n_subreads': n_subreads,
          'n_zmws': len(zmw_batch),
          'preprocess_time': time.time() - t0,
          'shm_handles': shm_handles,
      }

    def release_shm(feat):
      for shm in feat.get('shm_handles', ()):
        try:
          shm.close()
          shm.unlink()
        except (FileNotFoundError, OSError):
          pass
      feat['shm_handles'] = []

    def consume_batch(feat):
      try:
        _consume_batch(feat)
      finally:
        release_shm(feat)

    def _consume_batch(feat):
      nonlocal fastq_lines
      all_windows = feat['windows']
      n_subreads = feat['n_subreads']
      n_batch_zmws = feat['n_zmws']
      for zmw_counter in feat['counters']:
        window_counter.update(zmw_counter)
      t1 = time.time()
      if options.end_after_stage == 'tf_examples':
        timing_rows.append(
            dict(stage='preprocess', runtime=feat['preprocess_time'],
                 n_zmws=n_batch_zmws, n_examples=len(all_windows),
                 n_subreads=n_subreads))
        return
      to_model, to_skip = _triage_windows(all_windows, options,
                                          window_counter)
      predictions = [
          process_skipped_window(fd, options) for fd in to_skip
      ]
      predictions.extend(
          run_model_on_windows(to_model, runner, params, options)
      )
      t2 = time.time()
      if options.end_after_stage == 'run_model':
        timing_rows.append(
            dict(stage='run_model', runtime=t2 - t1,
                 n_zmws=n_batch_zmws, n_examples=len(all_windows),
                 n_subreads=n_subreads))
        return
      predictions.sort(key=lambda p: (p.molecule_name, p.window_pos))
      for name, group in itertools.groupby(
          predictions, key=lambda p: p.molecule_name
      ):
        group = list(group)
        fastq = stitch.stitch_to_fastq(
            molecule_name=name,
            predictions=group,
            max_length=options.max_length,
            min_quality=options.min_quality,
            min_length=options.min_length,
            outcome_counter=outcome,
        )
        if fastq is not None:
          emit(fastq, group)
          fastq_lines += 1
      t3 = time.time()
      timing_rows.extend([
          dict(stage='preprocess', runtime=feat['preprocess_time'],
               n_zmws=n_batch_zmws, n_examples=len(all_windows),
               n_subreads=n_subreads),
          dict(stage='run_model', runtime=t2 - t1, n_zmws=n_batch_zmws,
               n_examples=len(all_windows), n_subreads=n_subreads),
          dict(stage='stitch_and_write_fastq', runtime=t3 - t2,
               n_zmws=n_batch_zmws, n_examples=len(all_windows),
               n_subreads=n_subreads),
      ])

    # Cross-batch pipelining: a producer thread reads BAMs and
    # featurizes batch N+1 while the main thread runs batch N through
    # the model and stitcher. Counter discipline: the producer owns the
    # feeder's `counter`; the main thread accumulates into
    # `window_counter` and the two merge after join.
    import queue as queue_lib
    import threading

    feat_queue: 'queue_lib.Queue' = queue_lib.Queue(maxsize=2)
    stop = threading.Event()
    skip_featurize = options.end_after_stage == 'dc_input'

    def put(item) -> bool:
      """Bounded put that aborts when the consumer has bailed."""
      while not stop.is_set():
        try:
          feat_queue.put(item, timeout=0.5)
          return True
        except queue_lib.Full:
          continue
      return False

    def producer():
      try:
        def flush(zmw_batch) -> bool:
          if not zmw_batch:
            return True
          if skip_featurize:
            # dc_input stage: measure BAM decode/grouping only, so the
            # runtime CSV still carries one row per batch.
            timing_rows.append(
                dict(stage='dc_input',
                     runtime=time.time() - flush.t_start,
                     n_zmws=len(zmw_batch), n_examples=0,
                     n_subreads=sum(len(z[0]) - 1 for z in zmw_batch)))
            flush.t_start = time.time()
            return True
          feat = featurize_batch(zmw_batch)
          ok = put(('batch', feat))
          if not ok:
            # Consumer bailed mid-flight: this batch will never be
            # consumed, and its shm segments have no other owner.
            release_shm(feat)
          return ok

        flush.t_start = time.time()
        zmw_batch = []
        for zmw_input in feeder():
          zmw_batch.append(zmw_input)
          if options.batch_zmws and len(zmw_batch) >= options.batch_zmws:
            if not flush(zmw_batch):
              return
            zmw_batch = []
        if not flush(zmw_batch):
          return
        put(('done', None))
      except BaseException as e:  # surface worker failures to the main thread
        put(('error', e))

    thread = threading.Thread(target=producer, daemon=True)
    thread.start()
    try:
      while True:
        kind, payload = feat_queue.get()
        if kind == 'done':
          break
        if kind == 'error':
          raise payload
        consume_batch(payload)
    finally:
      stop.set()
      thread.join(timeout=30)
      # Release any featurized batches still queued (error paths).
      while not feat_queue.empty():
        kind, payload = feat_queue.get_nowait()
        if kind == 'batch':
          release_shm(payload)
    counter.update(window_counter)
  finally:
    close_out()
    if pool is not None:
      pool.close()
      pool.join()

  # Sidecar outputs (reference: quick_inference.py:777-791,961-962).
  with open(output + '.runtime.csv', 'w', newline='') as f:
    writer = csv.DictWriter(
        f, fieldnames=['stage', 'runtime', 'n_zmws', 'n_examples',
                       'n_subreads']
    )
    writer.writeheader()
    writer.writerows(timing_rows)
  counters = dict(counter)
  counters.update(dataclasses.asdict(outcome))
  with open(output + '.inference.json', 'w') as f:
    json.dump(counters, f, indent=2, sort_keys=True)
  if not outcome.success and options.end_after_stage == 'full':
    log.warning('No reads passed filters; outcome=%s', outcome)
  return counters
