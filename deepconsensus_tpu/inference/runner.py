"""Batched inference: BAM -> windows -> jitted model -> stitched FASTQ.

TPU-native re-design of the reference's quick_inference driver
(reference: deepconsensus/inference/quick_inference.py:68-984):

* Featurization runs the vectorized preprocess core (no per-base Python
  loops), so the host keeps up with the accelerator without a process
  pool for moderate workloads; a pool can still fan it out.
* The model step is one jitted function over fixed-shape batches
  (padded final batch) returning argmax bases and max probabilities,
  so only two small arrays cross the device boundary per batch.
* Window skip triage (CCS quality above threshold, overflow windows)
  happens on host exactly like the reference, including CCS-quality
  calibration of skipped windows.
* Per-stage wall-time is recorded and dumped to <output>.runtime.csv.
"""
from __future__ import annotations

import collections
import csv
import dataclasses
import itertools
import json
import logging
import atexit
import os
import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# The forward donates its input buffers (see _jit_forward); backends
# that can't reuse a given donated buffer (host CPU, notably) warn per
# dispatch, which would flood batch runs.
warnings.filterwarnings(
    'ignore', message='Some donated buffers were not usable')

from deepconsensus_tpu import obs as obs_lib
from deepconsensus_tpu.calibration import lib as calibration_lib
from deepconsensus_tpu.inference import engine as engine_lib
from deepconsensus_tpu.inference import faults
from deepconsensus_tpu.io import bam as bam_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import data as data_lib
from deepconsensus_tpu.models import model as model_lib
from deepconsensus_tpu.ops import output_plane
from deepconsensus_tpu.postprocess import stitch
from deepconsensus_tpu.preprocess import (
    FeatureLayout,
    create_proc_feeder,
    reads_to_pileup,
)
from deepconsensus_tpu.preprocess.pileup import row_indices
from deepconsensus_tpu.utils import phred

log = logging.getLogger(__name__)


@dataclasses.dataclass
class InferenceOptions:
  """Knobs shared across inference stages
  (reference: quick_inference.py:243-275)."""

  max_length: int = config_lib.DEFAULT_MAX_LENGTH
  max_passes: int = 20
  min_quality: int = 20
  min_length: int = 0
  batch_size: int = 1024
  batch_zmws: int = 100
  use_ccs_bq: bool = False
  skip_windows_above: int = 45
  ins_trim: int = 5
  use_ccs_smart_windows: bool = False
  # Window length buckets (config.resolve_window_buckets): None = follow
  # params.window_buckets / single-shape at max_length. Each smart
  # window pads to the smallest bucket that fits; the engine packs and
  # dispatches each bucket separately (one compiled shape per bucket).
  window_buckets: Optional[Tuple[int, ...]] = None
  # Bucket starvation flush: when one bucket's partial tail has sat
  # buffered while the other buckets cut this many full packs, the
  # starved tail is cut as a padded partial pack so a rare bucket's
  # windows cannot be held back indefinitely behind a busy one
  # (0 disables; tails always flush at end-of-input regardless).
  bucket_flush_packs: int = 8
  # Single-pack-stream ragged dispatch: mixed-width windows pack
  # back-to-back into fixed [n_slots, R, slot_len] slots (slot_len =
  # the largest bucket) with a per-slot int32 lengths vector, and ONE
  # compiled ragged forward serves every width (n_forward_shapes == 1;
  # no per-bucket packer fleet, no starvation flush — partial packs
  # exist only at end-of-input). Requires the buckets to form a
  # divisibility chain (each bucket divides the next); the bucketed
  # path remains the byte-identical fallback when False.
  use_ragged_kernel: bool = False
  max_base_quality: int = 93
  limit: int = 0
  # (i, n): keep only ZMWs with zm % n == i — single-flag fleet scaling
  # over one shared BAM (the reference's shard-the-BAM pattern without
  # the external splitting step).
  shard: Optional[Tuple[int, int]] = None
  # >0: featurization worker pool. Measured caveat: shipping featurized
  # windows between processes is IPC-bound (~6 MB/ZMW), so on fast
  # hosts the serial path (~20k windows/s, matching one chip's forward
  # throughput) wins; scale across chips by sharding input BAMs into
  # separate runs like the reference's 500-shard pattern.
  cpus: int = 0
  # Max batches in flight on the device before the oldest is drained.
  # Per-dispatch round trips dominate run_model over a tunneled chip
  # (VERDICT r2 #2: 4.78 s of a 6.3 s batch at depth 1); a deeper
  # pipeline overlaps transfer latency of batches i+1..i+k with the
  # compute of batch i. Device-side cost per in-flight batch is one
  # uint8 input buffer (~21 MB at b1024) + tiny outputs.
  dispatch_depth: int = 8
  # Cross-batch window packing: model batches are cut from a window
  # buffer spanning featurize batches, so the compiled forward runs
  # full except for one end-of-input tail (False reverts to per-
  # featurize-batch packs, each padded to batch_size).
  pack_across_batches: bool = True
  # Bounded hand-off queue between the model stage and the stitch/emit
  # worker thread, in featurize batches. Deeper absorbs longer emit
  # stalls (slow disk) before the device pipeline feels them; each
  # queued batch holds its windows' output arrays (~2*L bytes/window).
  emit_queue_depth: int = 4
  # Fault tolerance (inference/faults.py). on_zmw_error governs the
  # per-ZMW quarantine: 'fail' keeps historical fail-fast semantics,
  # 'skip' drops the ZMW (dead-lettered), 'ccs-fallback' emits the
  # draft CCS read with its original base qualities instead.
  on_zmw_error: str = 'fail'  # fail | skip | ccs-fallback
  # Per-record allocation cap for the hardened BAM decoders
  # (io/bam.py): a record claiming more than this is treated as
  # corrupt — quarantined under on_zmw_error=skip — never allocated.
  max_record_bytes: int = 64 << 20
  # >0: per-batch watchdog timeout (s) on the featurization pool; a
  # hung/SIGKILLed worker surfaces as a timeout, triggering pool
  # re-spawn + bounded retry (batch_retries) before quarantine.
  batch_timeout: float = 0.0
  batch_retries: int = 2
  # Device fault domain (the sharded counterpart of on_zmw_error).
  # 'fail' keeps bare propagation of device-runtime errors; 'degrade'
  # turns RESOURCE_EXHAUSTED into pack bisection (retry at half batch,
  # floored at dp divisibility) and repeated permanent device faults
  # into mesh degradation (rebuild at the next lower dp, re-place
  # weights, resubmit the failed pack in featurize order).
  on_device_error: str = 'fail'  # fail | degrade
  # >0: dispatch watchdog — bound the blocking finalize of each
  # in-flight pack to this many seconds; a hung forward surfaces as a
  # DispatchTimeoutError through pack-failure attribution instead of
  # wedging the model loop.
  dispatch_timeout: float = 0.0
  # Resume an interrupted run from <output>.progress.json + <output>.tmp.
  resume: bool = False
  # Quantized-inference levers (models/quantize.py), applied once at
  # checkpoint load BEFORE device placement so sharded weight
  # transfers ship the shrunken bytes. inference_dtype: None keeps the
  # checkpoint's dtype; 'bfloat16' casts weights + runs activations
  # bf16 end-to-end. quantize_matmuls: None/'none' off; 'int8'
  # per-channel weight quantization of the encoder matmuls.
  inference_dtype: Optional[str] = None
  quantize_matmuls: Optional[str] = None
  # Device-resident output plane (ops/output_plane.py): the forward
  # emits the final uint8 (base ids, Phred quality) planes on device —
  # argmax plus a threshold-table quality byte-identical to the host
  # epilogue — so finalize becomes a pure 2-bytes/position drain (vs 8
  # for int32 ids + f32 max_prob). Tri-state: None (auto) turns it on
  # for checkpoints and follows the artifact metadata for exported
  # runs; an explicit True/False is enforced — disagreeing with an
  # exported artifact raises ExportedArtifactMismatchError. Falls back
  # to the host path (with a warning) when the calibration is not
  # device-representable (non-monotone, or top quality past uint8).
  device_epilogue: Optional[bool] = None
  # Debug stage truncation (reference DebugStage: quick_inference.py:68-75).
  end_after_stage: str = 'full'  # dc_input | tf_examples | run_model | full
  dc_calibration_values: calibration_lib.QualityCalibrationValues = (
      dataclasses.field(
          default_factory=lambda: calibration_lib.parse_calibration_string(
              'skip'
          )
      )
  )
  ccs_calibration_values: calibration_lib.QualityCalibrationValues = (
      dataclasses.field(
          default_factory=lambda: calibration_lib.parse_calibration_string(
              'skip'
          )
      )
  )


_SN_ROWS = 4  # trailing rows: per-window SN constants (layout: pileup.py)


def _assemble_rows(main_u8: jnp.ndarray, sn: jnp.ndarray,
                   bq_row: Optional[int] = None) -> jnp.ndarray:
  """Device-side inverse of dispatch()'s compact split: uint8 rows ->
  f32, SN scalars re-broadcast across the window.

  bq_row: index of the ccs_bq row inside main_u8, if the model uses
  one. That row travels biased by +1 (its spaced values include -1
  sentinels at gap columns / padded tails, which a plain uint8 cast
  would wrap to 255); undo the bias here.
  """
  b, _, l, _ = main_u8.shape
  main = main_u8.astype(jnp.float32)
  if bq_row is not None:
    main = main.at[:, bq_row].add(-1.0)
  sn_rows = jnp.broadcast_to(
      sn.astype(jnp.float32)[:, :, None, None], (b, _SN_ROWS, l, 1)
  )
  return jnp.concatenate([main, sn_rows], axis=1)


def _assemble_rows_ragged(main_u8: jnp.ndarray, sn_w: jnp.ndarray,
                          lengths: jnp.ndarray,
                          bq_row: Optional[int] = None) -> jnp.ndarray:
  """_assemble_rows for ragged slots: SN constants vary per WINDOW
  within a slot, so sn_w carries [B, wps, 4] per-window scalars and
  each position gathers its own window's values through the
  lengths-derived segment map (same slot_geometry the mask uses).
  Positions past the packed windows get zero SN (they are masked out
  of attention and sliced away at delivery)."""
  from deepconsensus_tpu.ops import ragged_window_attention as ragged_ops

  b, _, l, _ = main_u8.shape
  main = main_u8.astype(jnp.float32)
  if bq_row is not None:
    main = main.at[:, bq_row].add(-1.0)
  seg, _start, _width, valid = ragged_ops.slot_geometry(lengths, l)
  # seg is always in [0, wps) (invalid positions keep segment 0), so
  # the gather needs no clip; valid zeroes what it fetched there.
  sn_pos = jnp.take_along_axis(
      sn_w.astype(jnp.float32), seg[:, :, None], axis=1)  # [B, l, 4]
  sn_pos = jnp.where(valid[:, :, None], sn_pos, 0.0)
  sn_rows = jnp.transpose(sn_pos, (0, 2, 1))[:, :, :, None]
  return jnp.concatenate([main, sn_rows], axis=1)


def _bq_row_index(params) -> Optional[int]:
  """Row index of the ccs_bq row within the non-SN block, taken from
  the canonical layout (pileup.row_indices) rather than re-derived.

  Also guards the compact-transport assumption: every non-SN row must
  fit 0..255 after the ccs_bq +1 bias, and PW_MAX/IP_MAX are
  config-tunable, so fail loudly instead of silently truncating.
  """
  from deepconsensus_tpu.preprocess import pileup

  if params.PW_MAX > 255 or params.IP_MAX > 255:
    # dclint: allow=typed-faults (model-config validation at startup,
    # surfaced as operator error by the CLI, not a data-plane fault)
    raise ValueError(
        f'compact uint8 dispatch requires PW_MAX/IP_MAX <= 255, got '
        f'{params.PW_MAX}/{params.IP_MAX}'
    )
  if not params.use_ccs_bq:
    return None
  bq_lo, _bq_hi = pileup.row_indices(params.max_passes, True)[5]
  return bq_lo


def _apply_quant_levers(params, options: 'InferenceOptions') -> None:
  """Fold the CLI quantization levers into a loaded params config.

  inference_dtype also overrides the compute dtype so activations run
  end-to-end in the requested precision; attn_softmax_dtype is left
  alone (the independent f32 escape hatch). The actual weight
  cast/quantization happens in ModelRunner.__init__ via
  models/quantize.py, before any device placement.
  """
  with params.unlocked():
    if options.inference_dtype:
      params.inference_dtype = options.inference_dtype
      params.dtype = options.inference_dtype
    if options.quantize_matmuls and options.quantize_matmuls != 'none':
      params.quantize_matmuls = options.quantize_matmuls


def _check_exported_levers(meta, options: 'InferenceOptions',
                           export_dir: str) -> None:
  """Exported artifacts bake the quantization levers into the compiled
  program; an explicitly requested lever that disagrees with the
  artifact metadata is a serving mismatch, not a silent override."""
  checks = (
      ('inference_dtype', options.inference_dtype,
       meta.get('inference_dtype') or 'float32', '--inference_dtype'),
      ('quantize_matmuls', options.quantize_matmuls,
       meta.get('quantize_matmuls') or 'none', '--quantize_matmuls'),
  )
  mismatches = [
      (name, requested, baked, flag)
      for name, requested, baked, flag in checks
      if requested is not None and requested != baked
  ]
  if not mismatches:
    return
  detail = ', '.join(
      f'{name}: artifact has {baked!r}, requested {requested!r}'
      for name, requested, baked, _flag in mismatches)
  flags = ' '.join(
      f'{flag} {requested}' for _name, requested, _baked, flag in mismatches)
  raise faults.ExportedArtifactMismatchError(
      f'exported artifact quantization mismatch ({detail})',
      reexport_command=(
          f'dctpu export --checkpoint <orbax_ckpt> '
          f'--output {export_dir} {flags}'
      ),
  )


def _check_exported_epilogue(meta, options: 'InferenceOptions',
                             export_dir: str) -> None:
  """The output plane is compiled into exported artifacts: an epilogue
  artifact always emits uint8 (ids, quals) with its baked calibration
  and clamp, a pre-epilogue artifact can only feed the host quality
  path. An explicit --device_epilogue/--no_device_epilogue — or a
  quality knob disagreeing with what an epilogue artifact baked — is a
  serving mismatch, not a silent override (same contract as
  _check_exported_levers)."""
  baked = bool(meta.get('device_epilogue'))
  requested = options.device_epilogue
  if requested is not None and requested != baked:
    flag = '--device_epilogue' if requested else '--no_device_epilogue'
    raise faults.ExportedArtifactMismatchError(
        f'exported artifact output-plane mismatch (artifact has '
        f'device_epilogue={baked}, requested {flag})',
        reexport_command=(
            f'dctpu export --checkpoint <orbax_ckpt> '
            f'--output {export_dir} {flag}'
        ),
    )
  if not baked:
    return
  baked_maxq = int(meta.get('max_base_quality', 93))
  if int(options.max_base_quality) != baked_maxq:
    raise faults.ExportedArtifactMismatchError(
        f'exported artifact bakes max_base_quality={baked_maxq} into '
        f'its device epilogue, requested {options.max_base_quality}',
        reexport_command=(
            f'dctpu export --checkpoint <orbax_ckpt> '
            f'--output {export_dir} '
            f'--max_base_quality {options.max_base_quality}'
        ),
    )
  baked_cal_str = meta.get('dc_calibration') or 'skip'
  baked_cal = calibration_lib.parse_calibration_string(baked_cal_str)
  if options.dc_calibration_values != baked_cal:
    requested_cal = calibration_lib.calibration_string(
        options.dc_calibration_values)
    raise faults.ExportedArtifactMismatchError(
        f'exported artifact bakes dc-calibration {baked_cal_str!r} '
        f'into its device epilogue, requested {requested_cal!r}',
        reexport_command=(
            f'dctpu export --checkpoint <orbax_ckpt> '
            f'--output {export_dir} --dc_calibration {requested_cal}'
        ),
    )


def _check_dp_divisible(options: 'InferenceOptions', mesh) -> int:
  """The compiled batch splits evenly over the mesh data axis; returns
  the data-axis size."""
  from deepconsensus_tpu.parallel import mesh as mesh_lib

  dp = mesh.shape[mesh_lib.DATA_AXIS]
  if options.batch_size % dp:
    # dclint: allow=typed-faults (startup config validation: operator
    # picked a batch size the mesh cannot split)
    raise ValueError(
        f'batch_size={options.batch_size} not divisible by the mesh '
        f'data axis ({dp} devices)'
    )
  return dp


class _DispatchHandle:
  """One in-flight pack: the runner's dispatch contract.

  dispatch() returns one of these holding the pack's (dp-sharded)
  device inputs in the transfer slot; the matching forward launches
  either when the NEXT pack dispatches (so pack N+1's host->device
  transfer overlaps pack N's compute) or on demand in
  raw_outputs()/finalize(). A launch error is stored here and
  re-raised at finalize time, so the engine's pack-failure routing
  attributes it to the pack that actually failed, not the pack whose
  dispatch happened to trigger the launch.
  """

  __slots__ = ('inputs', 'n', 'outputs', 'error', 'seq', 'hang_s',
               't_launch', 'bucket', 'ragged')

  def __init__(self, inputs, n: int):
    self.inputs = inputs  # device input tuple; cleared at launch
    self.n = n
    self.outputs = None  # (pred_ids_dev, max_prob_dev) once launched
    self.error = None
    self.seq = 0  # 1-based dispatch ordinal (fault-injection target)
    self.hang_s = 0.0  # injected finalize hang (watchdog drills)
    self.t_launch = 0.0  # forward-launch wall stamp (device_compute span)
    self.bucket = 0  # window width / slot length (straggler context)
    self.ragged = False  # routes the launch to the ragged forward

  @property
  def launched(self) -> bool:
    return self.outputs is not None or self.error is not None


# Watchdog workers abandoned past their deadline. Joined (briefly) at
# interpreter exit: a daemon thread still inside an XLA sync when
# CPython tears down the runtime segfaults the process, so the exit
# hook trades a bounded wait for a clean exit code. Slow-but-alive
# packs finish inside the grace; a truly wedged device still exits
# after it (and may then crash teardown — unavoidable without killing
# the thread, which CPython cannot do safely).
_abandoned_watchdogs: List[threading.Thread] = []
_ABANDON_GRACE_S = 15.0


def _join_abandoned_watchdogs() -> None:
  deadline = time.monotonic() + _ABANDON_GRACE_S
  for t in list(_abandoned_watchdogs):
    t.join(max(0.0, deadline - time.monotonic()))


atexit.register(_join_abandoned_watchdogs)


def _finalize_with_watchdog(finalize_fn, dispatched, timeout: float):
  """Bounds a blocking finalize: runs finalize_fn(dispatched) in a
  worker thread and waits at most `timeout` seconds.

  A device-side hang (wedged transfer, halted chip mid-collective)
  otherwise blocks np.asarray forever and wedges the model loop; here
  it surfaces as a DispatchTimeoutError that the engine's pack-failure
  routing attributes to the hung pack's tickets. The worker is a
  daemon: if the device never answers, the thread is abandoned with
  its pack rather than keeping the process alive.

  Module-level (not a ModelRunner method) on purpose: the runner's
  dispatch state stays single-threaded — this helper owns the only
  cross-thread hand-off, a single-producer result cell.
  """
  # dclint: lock-free (single-producer result cell: exactly one worker
  # thread appends once; the waiter reads only after a successful join)
  box = []

  def worker():
    try:
      box.append(('ok', finalize_fn(dispatched)))
    # dclint: allow=typed-faults (error capture for the cross-thread
    # hand-off: the waiter re-raises it verbatim on the model loop)
    except BaseException as e:
      box.append(('error', e))

  t = threading.Thread(
      target=worker, name='dctpu-finalize-watchdog', daemon=True)
  t.start()
  t.join(timeout)
  if t.is_alive() or not box:
    if t.is_alive():
      _abandoned_watchdogs.append(t)
    raise faults.DispatchTimeoutError(
        f'pack finalize produced no result within '
        f'dispatch_timeout={timeout}s')
  status, value = box[0]
  if status == 'error':
    raise value
  return value


class ModelRunner:
  """Jitted forward pass producing (bases, quality scores) per window.

  With a mesh, the window batch is sharded over the mesh's data axis
  (weights replicated), so one process drives every chip — the
  multi-chip counterpart of the reference's shard-the-BAM pattern
  (quick_inference.py 500-shard runs)."""

  def __init__(self, params, variables, options: InferenceOptions,
               mesh=None):
    self.params = params
    # Quantize/cast once on the host BEFORE any device placement, so
    # the weight transfer below ships the shrunken bf16/int8 bytes
    # (and degrade_mesh()'s re-placement keeps shipping them).
    self._n_quantized_matmuls = 0
    if variables:
      from deepconsensus_tpu.models import quantize as quantize_lib

      variables, self._n_quantized_matmuls = (
          quantize_lib.prepare_inference_variables(variables, params))
    self.variables = variables
    self.options = options
    self.mesh = mesh
    if mesh is not None:
      from deepconsensus_tpu.parallel import mesh as mesh_lib

      _check_dp_divisible(options, mesh)
      # Place the weights on the mesh once; otherwise every forward
      # re-broadcasts host arrays to all devices. param_shardings
      # shards attention heads / FFN filters on the model axis under
      # tp>1 and degenerates to replication at tp=1 (same rules as
      # training); the non-params collections always replicate.
      if variables:
        self.variables = {
            key: jax.device_put(
                value,
                mesh_lib.param_shardings(mesh, value)
                if key == 'params' else mesh_lib.replicated(mesh),
            )
            for key, value in variables.items()
        }
    elif variables:
      # Single-device residency: pin the weights (and the quant
      # collections) on the device once, same as the mesh branch —
      # otherwise every forward re-transfers the host arrays, leaving
      # a host gap between consecutive packs' device_compute spans.
      # With the input buffers donated, the steady-state pack loop
      # then touches the host only for the uint8 pack in and the
      # uint8 (ids, quals) planes out.
      self.variables = jax.device_put(variables)
    model = model_lib.get_model(params)
    self._bq_row = _bq_row_index(params)
    bq_row = self._bq_row
    self._configure_epilogue()
    thresholds = self._epilogue_thresholds
    # The Pallas epilogue rides the fused hot path (appended after the
    # last fused encoder block's output); under a mesh the XLA epilogue
    # shards trivially with the existing out_shardings instead.
    pallas_epilogue = (
        thresholds is not None
        and bool(params.get('use_fused_hotpath', False))
        and mesh is None
    )

    def forward(variables, main_u8, sn):
      rows = _assemble_rows(main_u8, sn, bq_row)
      preds = model.apply(variables, rows)
      if thresholds is not None:
        return output_plane.phred_epilogue(
            preds, thresholds, use_pallas=pallas_epilogue)
      pred_ids = jnp.argmax(preds, axis=-1).astype(jnp.int32)
      max_prob = jnp.max(preds, axis=-1)
      return pred_ids, max_prob

    def ragged_forward(variables, main_u8, sn_w, lengths):
      rows = _assemble_rows_ragged(main_u8, sn_w, lengths, bq_row)
      preds = model.apply(variables, rows, window_lengths=lengths)
      if thresholds is not None:
        return output_plane.phred_epilogue(
            preds, thresholds, use_pallas=pallas_epilogue)
      pred_ids = jnp.argmax(preds, axis=-1).astype(jnp.int32)
      max_prob = jnp.max(preds, axis=-1)
      return pred_ids, max_prob

    # Retained so degrade_mesh() can recompile the same forward for a
    # rebuilt (smaller) mesh.
    self._make_forward = lambda m: self._jit_forward(forward, m)
    self._forward = self._make_forward(mesh)
    # The ragged forward compiles lazily at its first dispatch_ragged,
    # so wiring it up always costs nothing when use_ragged_kernel is
    # off (jit() does not trace).
    self._make_ragged_forward = (
        lambda m: self._jit_ragged_forward(ragged_forward, m))
    self._ragged_forward = self._make_ragged_forward(mesh)
    self._init_dispatch_state(mesh)

  def _configure_epilogue(self) -> None:
    """Resolves the tri-state device_epilogue option against the
    quality knobs: builds the exact threshold table
    (ops/output_plane.py) when the device output plane is on, or
    records the host fallback — warning when the operator asked for
    the device path but the prob->quality map is not
    device-representable."""
    opts = self.options
    want = opts.device_epilogue
    if want is None:
      want = True  # default on for checkpoint-loaded runners
    self._device_epilogue = False
    self._epilogue_thresholds = None
    if not want:
      return
    thresholds = output_plane.quality_thresholds(
        opts.dc_calibration_values, opts.max_base_quality)
    if thresholds is None:
      log.warning(
          'device epilogue unavailable for this dc-calibration/'
          'max_base_quality (non-monotone calibration, or top quality '
          'past the uint8 plane); falling back to host quality math')
      return
    self._device_epilogue = True
    self._epilogue_thresholds = thresholds

  def _init_dispatch_state(self, mesh) -> None:
    """Dispatch-contract state shared by __init__ and from_exported
    (which builds the runner via cls.__new__)."""
    if mesh is not None:
      from deepconsensus_tpu.parallel import mesh as mesh_lib

      self._input_sharding = mesh_lib.batch_sharding(mesh)
    else:
      self._input_sharding = None
    # One metrics registry per runner process; the engine, service and
    # batch driver all observe into this same registry so /metricz and
    # the run sidecar read one coherent view (obs/metrics.py).
    self.obs = obs_lib.MetricsRegistry()
    # dclint: lock-free (single transfer slot: the model-loop thread
    # is the sole device owner — dispatch/finalize are never called
    # concurrently, per the engine's single-thread contract)
    self._pending: Optional[_DispatchHandle] = None
    self._n_dispatched = 0
    self._n_dispatched_sharded = 0
    self._n_overlapped_launches = 0
    self._n_direct_launches = 0
    # Mesh-degradation ladder state: the dp we started with, and how
    # many times degrade_mesh() stepped down.
    if mesh is not None:
      from deepconsensus_tpu.parallel import mesh as mesh_lib

      self._initial_dp = int(mesh.shape[mesh_lib.DATA_AXIS])
    else:
      self._initial_dp = 0
    self._n_degraded = 0
    # Quantization lever labels for /metricz and the run sidecar.
    # from_exported builds the runner via cls.__new__ and never applies
    # the levers itself (they are baked into the artifact), so default
    # the counter here instead of in __init__.
    self._n_quantized_matmuls = getattr(self, '_n_quantized_matmuls', 0)
    self._inference_dtype_label = str(
        self.params.get('inference_dtype', None) or 'float32')
    # Output-plane state: checkpoint __init__ resolves it in
    # _configure_epilogue before reaching here; from_exported sets it
    # from the artifact metadata (the epilogue is compiled in, no
    # threshold table needed host-side). Same getattr pattern as
    # _n_quantized_matmuls.
    self._device_epilogue = getattr(self, '_device_epilogue', False)
    self._epilogue_thresholds = getattr(self, '_epilogue_thresholds', None)
    self._n_epilogue_packs = 0
    # Measured at the first finalize drain (actual device-array bytes
    # pulled host-side per pack), for /metricz and the bench A/B.
    self._d2h_bytes_per_pack = 0
    # Bucketed-dispatch accounting: every distinct (batch, L) input
    # shape traces (and compiles) the jitted forward once, so the set
    # size is the compile count the per-bucket compile-once contract
    # asserts on; the per-bucket dict counts dispatches (including
    # bisection retries, unlike the engine's per-packer n_packs).
    self._forward_shapes: set = set()
    self._n_dispatched_by_bucket: Dict[int, int] = {}
    # Ragged dispatch contract: absent on exported-artifact runners
    # (the baked program has no lengths input), present on checkpoint
    # runners regardless of the gate (jit never traces unless called).
    self._ragged_forward = getattr(self, '_ragged_forward', None)
    self._make_ragged_forward = getattr(self, '_make_ragged_forward', None)

  @staticmethod
  def _jit_forward(forward, mesh):
    # donate_argnums: the uint8 pack and SN buffers are dead after the
    # forward (finalize only touches the outputs), so steady state
    # reuses their device memory instead of growing the arena by one
    # pack per in-flight dispatch.
    if mesh is None:
      return jax.jit(forward, donate_argnums=(1, 2))
    from deepconsensus_tpu.parallel import mesh as mesh_lib

    batch_sh = mesh_lib.batch_sharding(mesh)
    return jax.jit(
        forward,
        # Variables keep the placement __init__ gave them (replicated,
        # or model-axis sharded under tp>1).
        in_shardings=(None, batch_sh, batch_sh),
        out_shardings=(batch_sh, batch_sh),
        donate_argnums=(1, 2),
    )

  @staticmethod
  def _jit_ragged_forward(forward, mesh):
    # Same donation contract as _jit_forward, with the lengths vector
    # riding along: all three pack buffers (uint8 rows, per-window SN,
    # int32 lengths) are dead after the forward, so steady state
    # cycles ONE set of donated device buffers across packs.
    if mesh is None:
      return jax.jit(forward, donate_argnums=(1, 2, 3))
    from deepconsensus_tpu.parallel import mesh as mesh_lib

    batch_sh = mesh_lib.batch_sharding(mesh)
    return jax.jit(
        forward,
        in_shardings=(None, batch_sh, batch_sh, batch_sh),
        out_shardings=(batch_sh, batch_sh),
        donate_argnums=(1, 2, 3),
    )

  @classmethod
  def from_checkpoint(cls, checkpoint_path: str,
                      options: InferenceOptions,
                      mesh=None) -> 'ModelRunner':
    """Loads either an orbax checkpoint or an exported StableHLO
    artifact directory (the reference's SavedModel-vs-checkpoint
    detection: quick_inference.py:797-800,512-529)."""
    import os

    from deepconsensus_tpu.models import export as export_lib
    from deepconsensus_tpu.models.checkpoints import load_params

    if os.path.isdir(checkpoint_path) and os.path.exists(
        os.path.join(checkpoint_path, export_lib.ARTIFACT_NAME)
    ):
      return cls.from_exported(checkpoint_path, options, mesh=mesh)

    params = config_lib.read_params_from_json(checkpoint_path)
    config_lib.finalize_params(params, is_training=False)
    _apply_quant_levers(params, options)
    return cls(params, {'params': load_params(checkpoint_path)}, options,
               mesh=mesh)

  @classmethod
  def from_exported(cls, export_dir: str,
                    options: InferenceOptions,
                    mesh=None) -> 'ModelRunner':
    """Serves an exported StableHLO artifact (params baked in).

    With a mesh, the single-device program serves data-parallel: each
    device runs the artifact on its batch shard under shard_map (the
    batch-polymorphic export accepts the per-device shape), matching
    the reference's any-topology SavedModel serving. Requires a
    polymorphic artifact and a pure-DP mesh — the baked program can't
    be re-sharded on the model axis.
    """
    from deepconsensus_tpu.models import export as export_lib

    serving, meta = export_lib.load_exported(export_dir)
    params = config_lib.read_params_from_json(export_dir)
    config_lib.finalize_params(params, is_training=False)
    _check_exported_levers(meta, options, export_dir)
    _check_exported_epilogue(meta, options, export_dir)
    baked_epilogue = bool(meta.get('device_epilogue'))
    runner = cls.__new__(cls)
    runner.params = params
    runner.variables = None
    # The output plane is part of the compiled program: when baked, the
    # serving call already returns the uint8 (ids, quals) planes and
    # finalize is a pure drain; no host-side threshold table exists.
    runner._device_epilogue = baked_epilogue
    runner._epilogue_thresholds = None
    if not meta.get('polymorphic_batch'):
      # Fixed-batch artifact: the compiled shape wins over the flag.
      if mesh is not None:
        raise faults.ExportedArtifactMismatchError(
            'mesh/--dp serving of an exported artifact requires a '
            'batch-polymorphic export (this artifact is fixed-batch; '
            're-export with polymorphic_batch=True)',
            reexport_command=(
                'dctpu export --checkpoint <orbax_ckpt> '
                f'--output {export_dir} --strict_polymorphic'
            ),
        )
      options.batch_size = int(meta['batch_size'])
    runner.options = options
    runner.mesh = mesh
    runner._bq_row = _bq_row_index(params)
    bq_row = runner._bq_row

    def apply_serving(main_u8, sn):
      out = serving(_assemble_rows(main_u8, sn, bq_row))
      if baked_epilogue:
        # Epilogue artifact: `out` already is the uint8 (ids, quals)
        # tuple — the whole output plane ran inside the baked program.
        return tuple(out)
      preds = out
      return (
          jnp.argmax(preds, axis=-1).astype(jnp.int32),
          jnp.max(preds, axis=-1),
      )

    if mesh is None:
      runner._forward = jax.jit(
          lambda _variables, main_u8, sn: apply_serving(main_u8, sn),
          donate_argnums=(1, 2))
      # No mesh, no degradation ladder: degrade_mesh() bails before
      # ever recompiling, so the identity rebuild is never called.
      runner._make_forward = lambda _m: runner._forward
      runner._init_dispatch_state(mesh)
      return runner

    from jax.sharding import PartitionSpec
    try:
      from jax import shard_map as shard_map_lib  # jax >= 0.8
      shard_map = shard_map_lib
    except ImportError:  # pragma: no cover - older jax
      from jax.experimental.shard_map import shard_map
    from deepconsensus_tpu.parallel import mesh as mesh_lib

    if mesh_lib.MODEL_AXIS in mesh.shape and (
        mesh.shape[mesh_lib.MODEL_AXIS] > 1):
      raise faults.ExportedArtifactMismatchError(
          'exported artifacts serve data-parallel only (the compiled '
          'program cannot be re-sharded on the model axis); use tp=1 '
          'or an orbax checkpoint'
      )
    _check_dp_divisible(options, mesh)
    batch_spec = PartitionSpec(mesh_lib.DATA_AXIS)

    def make_forward(m):
      sharded_serving = shard_map(
          apply_serving, mesh=m,
          in_specs=(batch_spec, batch_spec),
          out_specs=(batch_spec, batch_spec),
          # The exported-call primitive has no replication-check rule;
          # both specs are fully dp-sharded anyway, so there is nothing
          # for the checker to prove.
          check_rep=False,
      )
      return jax.jit(
          lambda _variables, main_u8, sn: sharded_serving(main_u8, sn),
          donate_argnums=(1, 2))

    runner._make_forward = make_forward
    runner._forward = make_forward(mesh)
    runner._init_dispatch_state(mesh)
    return runner

  def dispatch(self, rows: np.ndarray,
               batch_size: Optional[int] = None) -> _DispatchHandle:
    """Async sharded dispatch: rows [B, R, L, 1] -> _DispatchHandle.

    Pads to the fixed compiled batch shape, places the compact pack on
    the device(s) with an async `jax.device_put` (dp-sharded over the
    mesh data axis when a mesh is configured), and returns a handle
    holding the in-flight transfer slot. The matching forward is
    double-buffered: it launches when the NEXT pack dispatches — so
    this pack's compute overlaps that pack's host->device transfer —
    or on demand in finalize(). The forward donates the input buffers,
    so steady state reuses device memory.

    Transfer is compact: every non-SN row holds clip-bounded integers
    (bases/ccs 0-4, pw/ip <= PW_MAX/IP_MAX = 255, strand 0-2, ccs_bq
    -1..93 shipped biased by +1), and the 4 SN rows are per-window
    constants, so the batch ships as uint8 rows + [B, 4] float SN
    scalars (~4x less than f32 rows over PCIe/tunnel) and reassembles
    losslessly on device (_assemble_rows undoes the ccs_bq bias).

    batch_size overrides the compiled batch shape for this pack only
    (OOM bisection retries at half batch; jit's per-shape cache keeps
    one executable per distinct size).
    """
    n = rows.shape[0]
    batch = batch_size or self.options.batch_size
    if n < batch:
      pad = np.zeros((batch - n,) + rows.shape[1:], rows.dtype)
      rows = np.concatenate([rows, pad])
    main = rows[:, :-_SN_ROWS]
    main_u8 = main.astype(np.uint8)
    if self._bq_row is not None:
      # Spaced ccs_bq holds -1 sentinels; bias to 0..94 so the uint8
      # cast is lossless (the device side subtracts 1 back).
      main_u8[:, self._bq_row] = (main[:, self._bq_row] + 1.0).astype(
          np.uint8)
    sn = np.ascontiguousarray(rows[:, -_SN_ROWS:, 0, 0].astype(np.float32))
    width = int(rows.shape[2])
    # Launch the previous pack's forward BEFORE starting this pack's
    # transfer, so the device_put below overlaps its compute.
    self._launch_pending()
    t_h2d = time.time()
    if self._input_sharding is not None:
      main_dev = jax.device_put(main_u8, self._input_sharding)
      sn_dev = jax.device_put(sn, self._input_sharding)
      self._n_dispatched_sharded += 1
    else:
      main_dev = jax.device_put(main_u8)
      sn_dev = jax.device_put(sn)
    self._n_dispatched += 1
    obs_lib.record_stage(self.obs, obs_lib.trace.STAGE_H2D,
                         t_h2d, time.time(), pack=self._n_dispatched,
                         bucket=width, dp=self.mesh_dp, n_rows=n)
    if self._device_epilogue:
      self._n_epilogue_packs += 1
    # Per-bucket compile-once accounting: jit keeps one executable per
    # distinct (batch, L); the set is the compile count.
    self._forward_shapes.add((batch, width))
    self._n_dispatched_by_bucket[width] = (
        self._n_dispatched_by_bucket.get(width, 0) + 1)
    handle = _DispatchHandle((main_dev, sn_dev), n)
    handle.seq = self._n_dispatched
    handle.bucket = width
    self._pending = handle
    return handle

  def dispatch_ragged(self, rows: np.ndarray,
                      lengths: np.ndarray) -> _DispatchHandle:
    """dispatch() for the single ragged pack stream: rows
    [n_slots, R, slot_len, 1] with mixed-width windows packed
    back-to-back per slot, lengths [n_slots, wps] int32 window widths
    (0 = unused capacity). Same compact uint8 transport and
    double-buffered launch as dispatch(), with the SN plane shipped as
    PER-WINDOW scalars ([n_slots, wps, 4], sampled at each window's
    start column) that _assemble_rows_ragged re-broadcasts through the
    lengths-derived segment map. Every pack has the same shape, so the
    jitted ragged forward compiles exactly once (n_forward_shapes
    stays 1 for the whole run)."""
    if self._ragged_forward is None:
      # dclint: allow=typed-faults (serving contract: exported
      # artifacts bake a fixed-shape program with no lengths input)
      raise ValueError(
          'ragged dispatch is not available on this runner (exported '
          'artifacts serve the bucketed path only)')
    n_slots = int(rows.shape[0])
    slot_len = int(rows.shape[2])
    lengths = np.ascontiguousarray(np.asarray(lengths, dtype=np.int32))
    main = rows[:, :-_SN_ROWS]
    main_u8 = main.astype(np.uint8)
    if self._bq_row is not None:
      # Same lossless +1 bias as dispatch(); zero pad positions round-
      # trip 0 -> 1 -> 0 through the device-side -1.
      main_u8[:, self._bq_row] = (main[:, self._bq_row] + 1.0).astype(
          np.uint8)
    # Per-window SN scalars, sampled at each window's start column
    # (the packer broadcast them across the window, like the raw
    # feature layout). Empty window slots carry zeros.
    starts = np.zeros_like(lengths)
    starts[:, 1:] = np.cumsum(lengths[:, :-1], axis=1)
    sn_planes = rows[:, -_SN_ROWS:, :, 0]  # [n_slots, 4, slot_len]
    sn_w = np.take_along_axis(
        sn_planes, np.clip(starts, 0, slot_len - 1)[:, None, :], axis=2)
    sn_w = sn_w.transpose(0, 2, 1) * (lengths > 0)[:, :, None]
    sn_w = np.ascontiguousarray(sn_w.astype(np.float32))
    n_windows = int((lengths > 0).sum())
    self._launch_pending()
    t_h2d = time.time()
    if self._input_sharding is not None:
      main_dev = jax.device_put(main_u8, self._input_sharding)
      sn_dev = jax.device_put(sn_w, self._input_sharding)
      len_dev = jax.device_put(lengths, self._input_sharding)
      self._n_dispatched_sharded += 1
    else:
      main_dev = jax.device_put(main_u8)
      sn_dev = jax.device_put(sn_w)
      len_dev = jax.device_put(lengths)
    self._n_dispatched += 1
    obs_lib.record_stage(self.obs, obs_lib.trace.STAGE_H2D,
                         t_h2d, time.time(), pack=self._n_dispatched,
                         bucket=slot_len, dp=self.mesh_dp,
                         n_rows=n_windows)
    if self._device_epilogue:
      self._n_epilogue_packs += 1
    # One entry for the whole run: the collapse the ragged path buys.
    self._forward_shapes.add(('ragged', n_slots, slot_len))
    self._n_dispatched_by_bucket[slot_len] = (
        self._n_dispatched_by_bucket.get(slot_len, 0) + 1)
    handle = _DispatchHandle((main_dev, sn_dev, len_dev), n_slots)
    handle.seq = self._n_dispatched
    handle.bucket = slot_len
    handle.ragged = True
    self._pending = handle
    return handle

  def _launch_pending(self) -> None:
    """Launches the forward for the pack currently in the transfer
    slot, if any (the overlapped half of the double buffer)."""
    handle, self._pending = self._pending, None
    if handle is None or handle.launched:
      return
    self._launch(handle)
    self._n_overlapped_launches += 1

  def _launch(self, handle: _DispatchHandle) -> None:
    """Runs the jitted forward on a handle's device inputs. An error is
    stored on the handle (re-raised by raw_outputs/finalize) so the
    engine attributes it to the failing pack, not to whichever later
    dispatch happened to trigger this launch."""
    inputs = handle.inputs
    # Drop our references before the call: the jit donates these
    # buffers, so they must not be reachable (or reused) afterwards.
    handle.inputs = None
    # Launch stamp: the device_compute span runs launch -> drain, and
    # launch-before-finalize ordering is the span-derived overlap
    # signal dctpu trace reconciles against the counters.
    handle.t_launch = time.time()
    fwd = self._ragged_forward if handle.ragged else self._forward
    try:
      faults.injected_device_fault(handle.seq)
      handle.hang_s = faults.injected_device_hang(handle.seq)
      handle.outputs = fwd(self.variables, *inputs)
    # dclint: allow=typed-faults (deferred-launch error capture: the
    # classified error is re-raised at finalize time, where
    # pack-failure routing can attribute it to the right tickets)
    except Exception as e:
      handle.error = faults.classify_device_error(e)

  def raw_outputs(self, dispatched: _DispatchHandle):
    """Device arrays (pred_ids, max_prob, n) for a dispatch handle —
    (ids_u8, quals_u8, n) when the device epilogue is on — launching
    its forward now if no later dispatch overlapped it."""
    handle = dispatched
    if not handle.launched:
      if self._pending is handle:
        self._pending = None
      self._launch(handle)
      self._n_direct_launches += 1
    if handle.error is not None:
      raise handle.error
    pred_ids, max_prob = handle.outputs
    return pred_ids, max_prob, handle.n

  def dispatch_stats(self) -> Dict[str, Any]:
    """Transfer/overlap counters for /metricz and the bench stages."""
    launches = self._n_overlapped_launches + self._n_direct_launches
    return {
        'n_packs_dispatched_sharded': self._n_dispatched_sharded,
        'n_transfer_overlapped': self._n_overlapped_launches,
        'n_transfer_direct': self._n_direct_launches,
        'transfer_overlap_fraction': (
            round(self._n_overlapped_launches / launches, 4)
            if launches else 0.0),
        'n_mesh_degradations': self._n_degraded,
        'mesh_dp': self.mesh_dp,
        'inference_dtype': self._inference_dtype_label,
        'n_quantized_matmuls': self._n_quantized_matmuls,
        'device_epilogue': int(self._device_epilogue),
        'n_epilogue_packs': self._n_epilogue_packs,
        'd2h_bytes_per_pack': self._d2h_bytes_per_pack,
        'n_forward_shapes': len(self._forward_shapes),
        'n_dispatched_by_bucket': {
            w: self._n_dispatched_by_bucket[w]
            for w in sorted(self._n_dispatched_by_bucket)},
    }

  @property
  def mesh_dp(self) -> int:
    """Current data-axis width (0 without a mesh)."""
    if self.mesh is None:
      return 0
    from deepconsensus_tpu.parallel import mesh as mesh_lib

    return int(self.mesh.shape[mesh_lib.DATA_AXIS])

  @property
  def is_degraded(self) -> bool:
    """True once degrade_mesh() stepped below the launch topology."""
    return self._n_degraded > 0

  def degrade_mesh(self) -> Optional[int]:
    """Rebuilds the mesh at the next lower dp (8 -> 4 -> 2 -> 1) after
    a permanent device fault; returns the new dp, or None when no
    smaller topology exists (single device, or no mesh at all).

    Re-places the weights on the surviving devices and recompiles the
    forward (jit caches per mesh, so a later un-degrade would be
    cheap). The caller owns resubmission of whatever was in flight on
    the old mesh; the stale transfer slot is abandoned here — its
    buffers lived on the dead topology.
    """
    if self.mesh is None:
      return None
    from deepconsensus_tpu.parallel import mesh as mesh_lib

    dp = int(self.mesh.shape[mesh_lib.DATA_AXIS])
    tp = int(self.mesh.shape.get(mesh_lib.MODEL_AXIS, 1))
    new_dp = dp // 2
    # The compiled batch must still split evenly over the data axis.
    while new_dp >= 1 and self.options.batch_size % new_dp:
      new_dp //= 2
    if new_dp < 1 or new_dp >= dp:
      return None
    devices = np.asarray(self.mesh.devices).reshape(-1)[:new_dp * tp]
    mesh = mesh_lib.make_mesh(dp=new_dp, tp=tp, devices=list(devices))
    if self.variables:
      self.variables = {
          key: jax.device_put(
              value,
              mesh_lib.param_shardings(mesh, value)
              if key == 'params' else mesh_lib.replicated(mesh),
          )
          for key, value in self.variables.items()
      }
    self.mesh = mesh
    self._forward = self._make_forward(mesh)
    if self._make_ragged_forward is not None:
      self._ragged_forward = self._make_ragged_forward(mesh)
    self._input_sharding = mesh_lib.batch_sharding(mesh)
    self._pending = None
    self._n_degraded += 1
    log.warning('mesh degraded to dp=%d (step %d of the ladder)',
                new_dp, self._n_degraded)
    return new_dp

  def finalize(self, dispatched) -> Tuple[np.ndarray, np.ndarray]:
    """Resolves a dispatch into (base ids [n, L], quality [n, L]).

    With --dispatch_timeout > 0 the blocking device sync is bounded by
    the dispatch watchdog; a hang becomes DispatchTimeoutError.
    """
    timeout = self.options.dispatch_timeout
    if timeout and timeout > 0:
      return _finalize_with_watchdog(self._finalize_sync, dispatched,
                                     timeout)
    return self._finalize_sync(dispatched)

  def _finalize_sync(self, dispatched) -> Tuple[np.ndarray, np.ndarray]:
    """Timing shell around the blocking drain: emits the pack's
    finalize_drain span, and a device_compute span running from the
    forward-launch stamp to drain completion. The two spans' start
    ordering is the span-derived overlap fraction: an overlapped pack
    was launched by a later dispatch (launch stamp BEFORE finalize
    began); a direct launch happens inside finalize."""
    t_fin = time.time()
    try:
      return self._drain_sync(dispatched)
    finally:
      t_end = time.time()
      handle = dispatched
      obs_lib.record_stage(self.obs, obs_lib.trace.STAGE_FINALIZE,
                           t_fin, t_end, pack=handle.seq)
      if handle.t_launch:
        obs_lib.record_stage(
            self.obs, obs_lib.trace.STAGE_DEVICE_COMPUTE,
            handle.t_launch, t_end, pack=handle.seq,
            bucket=handle.bucket, dp=self.mesh_dp, n_rows=handle.n)

  def _drain_sync(self, dispatched) -> Tuple[np.ndarray, np.ndarray]:
    """The blocking half of finalize: device sync, plus host quality
    math only on the fallback path (with the device epilogue on, the
    quality integers already left the device final — this is a pure
    uint8 drain)."""
    out_a, out_b, n = self.raw_outputs(dispatched)
    hang_s = getattr(dispatched, 'hang_s', 0.0)
    if hang_s:
      # Injected device hang (ENV_DEVICE_HANG_AT_PACK): simulate a
      # wedged sync so the watchdog path is provable on CPU.
      dispatched.hang_s = 0.0
      time.sleep(hang_s)
    if not self._d2h_bytes_per_pack:
      # Actual drain size: 2 uint8 planes with the epilogue, int32 ids
      # + f32 max_prob without (the bench A/B's measured numerator).
      self._d2h_bytes_per_pack = int(out_a.nbytes + out_b.nbytes)
    # Slice on the host: indexing the device array with a varying [:n]
    # would lower (and cache) a fresh jitted slice per tail size.
    # dclint: allow=jit-hazards (finalize IS the sync point: results
    # must land on the host here, after the async dispatch window)
    out_a = np.asarray(out_a)[:n]
    # dclint: allow=jit-hazards (same deliberate sync as out_a)
    out_b = np.asarray(out_b)[:n]
    if self._device_epilogue:
      return out_a, out_b  # (ids_u8, quals_u8): nothing left to compute
    pred_ids, max_prob = out_a, out_b
    error_prob = np.maximum(1.0 - max_prob, 1e-12)
    quality = -10.0 * np.log10(error_prob)
    opts = self.options
    if opts.dc_calibration_values.enabled:
      quality = calibration_lib.calibrate_quality_scores(
          quality, opts.dc_calibration_values
      )
    quality = np.minimum(quality, opts.max_base_quality)
    quality = np.round(quality, decimals=0).astype(np.int32)
    quality = np.maximum(quality, 0)
    return pred_ids, quality

  def predict(self, rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Synchronous convenience wrapper."""
    return self.finalize(self.dispatch(rows))


def preprocess_zmw(
    zmw_input, options: InferenceOptions
) -> Tuple[List[Dict[str, Any]], collections.Counter]:
  """One ZMW -> list of window feature dicts
  (reference: quick_inference.py:535-564)."""
  subreads, name, layout, _split, window_widths = zmw_input
  pileup = reads_to_pileup(subreads, name, layout, window_widths)
  features = list(pileup.iter_window_features())
  return features, pileup.counter


# Feature-dict fields shipped as plain pickled metadata by the shm
# transport (everything except the bulk 'subreads' tensor).
_SHM_META_FIELDS = (
    'subreads/num_passes', 'name', 'window_pos',
    'ccs_base_quality_scores', 'overflow', 'ec', 'np_num_passes', 'rq',
    'rg',
)


def _create_shm(size: int, prefix: Optional[str] = None):
  """One shm segment, named under `prefix` when given so the watchdog
  can reclaim a killed worker's orphans by glob (faults
  .reclaim_shm_segments) without touching other batches' segments."""
  from multiprocessing import shared_memory

  if not prefix:
    return shared_memory.SharedMemory(create=True, size=size)
  for attempt in itertools.count():
    name = f'{prefix}{os.getpid()}_{attempt}'
    try:
      return shared_memory.SharedMemory(name=name, create=True, size=size)
    except FileExistsError:
      continue


def preprocess_zmw_shm(zmw_input, options: InferenceOptions,
                       shm_prefix: Optional[str] = None):
  """Pool-worker variant: the bulk window tensors travel through one
  POSIX shared-memory segment per ZMW instead of the result pickle.

  The pickle channel is the measured bottleneck of the worker pool
  (~6 MB/ZMW through a pipe); with shm the pickle carries only names
  and offsets. Returns (shm_name, window_metadata, counter); the
  parent re-views the tensors with _features_from_shm and owns the
  segment's lifetime (workers unregister from their resource tracker).
  """
  from multiprocessing import resource_tracker

  features, counter = preprocess_zmw(zmw_input, options)
  total = sum(f['subreads'].nbytes for f in features)
  if not total:
    return None, [{k: f[k] for k in _SHM_META_FIELDS} for f in features
                  ], counter
  shm = _create_shm(total, shm_prefix)
  try:
    meta = []
    offset = 0
    for f in features:
      arr = f['subreads']
      flat = np.ndarray(arr.shape, arr.dtype, buffer=shm.buf,
                        offset=offset)
      flat[...] = arr
      entry = {k: f[k] for k in _SHM_META_FIELDS}
      # bq values fit int16 (-1..93); int64 would dominate the metadata
      # pickle (~120 KB/ZMW of the ~130 KB total).
      entry['ccs_base_quality_scores'] = (
          entry['ccs_base_quality_scores'].astype(np.int16)
      )
      entry['_shape'] = arr.shape
      entry['_dtype'] = arr.dtype.str
      entry['_offset'] = offset
      offset += arr.nbytes
      meta.append(entry)
  except BaseException:
    # Packing failed: this worker still owns the segment.
    shm.close()
    shm.unlink()
    raise
  name = shm.name
  shm.close()
  # The worker's resource tracker would unlink the segment when the
  # worker exits; ownership transfers to the parent instead.
  try:
    resource_tracker.unregister(f'/{name}', 'shared_memory')
  # dclint: allow=typed-faults (best-effort unregister: on failure the
  # tracker merely logs a spurious leak warning at exit)
  except Exception:  # pragma: no cover - tracker internals shifted
    pass
  return name, meta, counter


def _pool_worker(zmw_input, options: InferenceOptions,
                 shm_prefix: Optional[str] = None):
  """starmap payload: never raises, so the parent always receives every
  created shm name (a raising task would make starmap discard ALL
  results, orphaning the successful workers' segments forever)."""
  try:
    name = zmw_input[1] if len(zmw_input) > 1 else None
    if isinstance(name, str):
      faults.maybe_kill_worker(name)
    return 'ok', preprocess_zmw_shm(zmw_input, options, shm_prefix)
  # dclint: allow=typed-faults (routes the error to the parent as an
  # ('error', traceback) result; raising would make starmap discard
  # the whole batch and orphan sibling shm segments)
  except BaseException:
    import traceback

    return 'error', traceback.format_exc()


def _features_from_shm(result):
  """Parent-side inverse of preprocess_zmw_shm.

  Returns (features, counter, shm_handle_or_None); the caller must
  close+unlink the handle once the features are consumed.
  """
  from multiprocessing import shared_memory

  shm_name, meta, counter = result
  shm = None
  features = []
  if shm_name is not None:
    shm = shared_memory.SharedMemory(name=shm_name)
  for entry in meta:
    f = {k: entry[k] for k in _SHM_META_FIELDS}
    f['ccs_base_quality_scores'] = (
        f['ccs_base_quality_scores'].astype(np.int64)
    )
    if shm is not None:
      f['subreads'] = np.ndarray(
          entry['_shape'], np.dtype(entry['_dtype']), buffer=shm.buf,
          offset=entry['_offset'],
      )
    features.append(f)
  return features, counter, shm


def process_skipped_window(
    feature_dict: Dict[str, Any], options: InferenceOptions
) -> stitch.DCModelOutput:
  """Adopts the CCS bases/qualities for a skipped window
  (reference: quick_inference.py:567-594)."""
  rows = feature_dict['subreads']
  ccs_range = row_indices(options.max_passes, options.use_ccs_bq)[4]
  ccs = rows[ccs_range[0], :, 0]
  ccs_seq = phred.encoded_sequence_to_string(ccs)
  quals = np.asarray(feature_dict['ccs_base_quality_scores'])
  if options.ccs_calibration_values.enabled:
    quals = calibration_lib.calibrate_quality_scores(
        quals, options.ccs_calibration_values
    )
  quals = np.minimum(quals, options.max_base_quality).astype(np.int32)
  return stitch.DCModelOutput(
      window_pos=feature_dict['window_pos'],
      molecule_name=feature_dict['name'],
      sequence=ccs_seq,
      quality_string=phred.quality_scores_to_string(np.maximum(quals, 0)),
      ec=feature_dict['ec'],
      np_num_passes=feature_dict['np_num_passes'],
      rq=feature_dict['rq'],
      rg=feature_dict['rg'],
  )


# The model stage (triage -> pack -> dispatch -> finalize) lives in
# inference/engine.py as ConsensusEngine; this pipeline is one of its
# thin clients (the serve daemon is the other). Aliases keep the
# historical runner.py names importable.
_ccs_quals_array = engine_lib.ccs_quals_array
skipped_window_arrays = engine_lib.skipped_window_arrays
_triage_windows = engine_lib.triage_windows
_WindowPacker = engine_lib._WindowPacker
ConsensusEngine = engine_lib.ConsensusEngine


class _MolState:
  """One molecule's windows accumulating toward stitch/emit.

  Entries are appended in the legacy prediction order (skip windows
  first, then model windows, each in featurize order) so the stable
  in-stitch sort reproduces the string plane's byte-exact output.
  Model windows are appended as placeholders and filled in when their
  pack finalizes; model_entries keeps each one's draft-CCS copy so a
  failed pack can adopt the CCS without the (released) feature tensor.
  """

  __slots__ = ('name', 'batch', 'meta', 'pos', 'ids', 'quals',
               'model_entries', 'status')

  def __init__(self, name: str, batch: '_BatchState', meta: Tuple):
    self.name = name
    self.batch = batch
    self.meta = meta  # (ec, np_num_passes, rq, rg)
    self.pos: List[int] = []
    self.ids: List[Optional[np.ndarray]] = []
    self.quals: List[Optional[np.ndarray]] = []
    self.model_entries: List[Tuple[int, np.ndarray, np.ndarray]] = []
    self.status = 'ok'  # ok | adopted (ccs-fallback) | dropped

  def append_resolved(self, window_pos: int, ids: np.ndarray,
                      quals: np.ndarray) -> None:
    self.pos.append(window_pos)
    self.ids.append(ids)
    self.quals.append(quals)

  def append_pending(self, window_pos: int, ccs_ids: np.ndarray,
                     ccs_bq: np.ndarray) -> int:
    idx = len(self.pos)
    self.pos.append(window_pos)
    self.ids.append(None)
    self.quals.append(None)
    self.model_entries.append((idx, ccs_ids, ccs_bq))
    self.batch.pending += 1
    return idx

  def set_result(self, idx: int, ids: Optional[np.ndarray],
                 quals: Optional[np.ndarray]) -> None:
    """Resolves one model slot (ids=None marks a failed pack's slot).
    Always decrements the batch's pending count, even for molecules
    already adopted/dropped by an earlier pack failure."""
    if self.status == 'ok' and ids is not None:
      self.ids[idx] = ids
      self.quals[idx] = quals
    self.batch.pending -= 1

  def adopt_ccs(self, options: InferenceOptions) -> bool:
    """ccs-fallback for a model-stage fault: every model window (in
    this pack, other packs, resolved or not) adopts its draft CCS so
    the molecule degrades consistently, like the string plane's whole-
    molecule fallback."""
    for idx, ccs_ids, ccs_bq in self.model_entries:
      self.ids[idx] = ccs_ids
      self.quals[idx] = _ccs_quals_array(ccs_bq, options)
    return True


class _BatchState:
  """Completion tracker for one featurize batch flowing through the
  packed model stage toward the stitch/emit worker."""

  __slots__ = ('feat', 'mols', 'pending', 'featurized', 'n_windows')

  def __init__(self, feat: Dict[str, Any]):
    self.feat = feat
    self.mols: Dict[str, _MolState] = {}
    self.pending = 0
    self.featurized = False
    self.n_windows = 0

  def mol(self, fd: Dict[str, Any]) -> _MolState:
    name = (fd['name'] if isinstance(fd['name'], str)
            else fd['name'].decode())
    state = self.mols.get(name)
    if state is None:
      state = self.mols[name] = _MolState(
          name, self,
          (fd['ec'], fd['np_num_passes'], fd['rq'], fd['rg']))
    return state

  @property
  def complete(self) -> bool:
    return self.featurized and self.pending == 0


def run_model_on_windows(
    feature_dicts: List[Dict[str, Any]],
    runner: ModelRunner,
    params,
    options: InferenceOptions,
) -> List[stitch.DCModelOutput]:
  """Formats, batches, and runs windows through the model
  (reference: quick_inference.py:341-415)."""
  outputs: List[stitch.DCModelOutput] = []

  # Pipelined: keep up to options.dispatch_depth batches in flight so
  # host-side stacking/quality math and per-dispatch transfer latency
  # overlap device compute; drain in order.
  pending: List[Tuple[List, Any]] = []
  depth = max(1, options.dispatch_depth)

  def drain(entry):
    chunk, dispatched = entry
    pred_ids, quality = runner.finalize(dispatched)
    for c, ids, quals in zip(chunk, pred_ids, quality):
      outputs.append(
          stitch.DCModelOutput(
              window_pos=c['window_pos'],
              molecule_name=c['name'] if isinstance(c['name'], str)
              else c['name'].decode(),
              sequence=phred.encoded_sequence_to_string(ids),
              quality_string=phred.quality_scores_to_string(quals),
              ec=c['ec'],
              np_num_passes=c['np_num_passes'],
              rq=c['rq'],
              rg=c['rg'],
          )
      )

  for start in range(0, len(feature_dicts), options.batch_size):
    chunk = feature_dicts[start : start + options.batch_size]
    raw = np.stack([c['subreads'] for c in chunk])
    rows = data_lib.format_rows_batch(raw, params)
    pending.append((chunk, runner.dispatch(rows)))
    if len(pending) > depth:
      drain(pending.pop(0))
  while pending:
    drain(pending.pop(0))
  return outputs


def run_inference(
    subreads_to_ccs: str,
    ccs_bam: Optional[str],
    checkpoint: Optional[str],
    output: str,
    options: Optional[InferenceOptions] = None,
    runner: Optional[ModelRunner] = None,
    ccs_fasta: Optional[str] = None,
    mesh=None,
) -> Dict[str, Any]:
  """Full inference pipeline; returns the counters dict
  (reference run(): quick_inference.py:794-963).

  Fault tolerance (inference/faults.py): with options.on_zmw_error !=
  'fail', per-ZMW failures in any stage are quarantined to
  <output>.failed.jsonl — optionally emitting the draft CCS read —
  instead of aborting the run; the featurization pool runs under a
  watchdog (batch_timeout/batch_retries); and output streams into
  <output>.tmp with a crash-consistent progress manifest, renamed into
  place only on success. options.resume replays the feeder past the
  committed groups of an interrupted run.
  """
  options = options or InferenceOptions()
  if runner is None:
    if checkpoint is None:
      # dclint: allow=typed-faults (API misuse by the caller, not a
      # data-plane fault; the CLI maps it to exit code 2)
      raise ValueError('need checkpoint or runner')
    runner = ModelRunner.from_checkpoint(checkpoint, options, mesh=mesh)
  params = runner.params
  options.max_passes = params.max_passes
  options.max_length = params.max_length
  options.use_ccs_bq = params.use_ccs_bq
  # Bucket-aware geometry: an explicit options.window_buckets (CLI
  # --window_buckets) must be consistent with the checkpoint's base
  # max_length; unset follows params.window_buckets (single shape when
  # that too is unset).
  options.window_buckets = config_lib.normalize_window_buckets(
      options.window_buckets or getattr(params, 'window_buckets', None),
      params.max_length)

  # Run-scoped tracing: honor DCTPU_TRACE unless the CLI already
  # configured a writer, and stamp every span (and dead letter) from
  # this run's threads with one minted trace id.
  if not obs_lib.trace.enabled():
    obs_lib.trace.configure_from_env(tier='run')
  run_trace_id = obs_lib.trace.mint_trace_id()
  obs_lib.trace.set_trace_id(run_trace_id)

  fail_fast = options.on_zmw_error == faults.OnZmwError.FAIL
  dead_letter: Optional[faults.DeadLetterWriter] = None
  quarantine: Optional[faults.Quarantine] = None

  # Atomic, resumable output: everything streams into <output>.tmp; the
  # manifest records (feeder groups committed, flushed tmp size) after
  # every consumed batch, and the tmp file is renamed into place only
  # when the run completes. A crashed run never leaves a plausible-
  # looking final output, and --resume truncates the tmp file to the
  # last committed byte and replays the feeder past committed groups.
  manifest = faults.ProgressManifest(output + '.progress.json')
  source = {
      'subreads_to_ccs': subreads_to_ccs,
      'ccs_bam': ccs_bam,
      'ccs_fasta': ccs_fasta,
      'output': output,
      'shard': list(options.shard) if options.shard else None,
  }
  out_tmp = output + '.tmp'
  resume_skip_groups = 0
  resuming = False
  if options.resume and options.end_after_stage == 'full':
    state = manifest.load()
    if state is None:
      log.info('--resume: no usable progress manifest; starting fresh')
    else:
      faults.validate_resume_source(state, source)
      committed = int(state['tmp_size'])
      if os.path.exists(out_tmp) and os.path.getsize(out_tmp) >= committed:
        with open(out_tmp, 'r+b') as f:
          f.truncate(committed)
        resume_skip_groups = int(state['groups_done'])
        resuming = True
        log.info(
            'resuming after %d committed feeder group(s); %s truncated '
            'to %d bytes', resume_skip_groups, out_tmp, committed)
      else:
        log.warning(
            '--resume: %s missing or shorter than the committed %d '
            'bytes; restarting from scratch', out_tmp, committed)

  if not fail_fast:
    dead_letter = faults.DeadLetterWriter(output + '.failed.jsonl',
                                          append=resuming)
    quarantine = faults.Quarantine(options.on_zmw_error, dead_letter)

  layout = FeatureLayout(
      max_passes=options.max_passes,
      max_length=options.max_length,
      use_ccs_bq=options.use_ccs_bq,
      window_buckets=options.window_buckets,
  )
  # dclint: lock-free (producer thread owns the feeder's counter while
  # it runs; the main thread merges into it only after the join)
  feeder, counter = create_proc_feeder(
      subreads_to_ccs=subreads_to_ccs,
      ccs_bam=ccs_bam,
      ccs_fasta=ccs_fasta,
      layout=layout,
      ins_trim=options.ins_trim,
      use_ccs_smart_windows=options.use_ccs_smart_windows,
      limit=options.limit,
      shard=options.shard,
      quarantine=quarantine,
      resume_skip_groups=resume_skip_groups,
      max_record_bytes=options.max_record_bytes,
  )
  watchdog: Optional[faults.PoolWatchdog] = None
  if (options.cpus and options.cpus > 1
      and options.end_after_stage != 'dc_input'):
    # dc_input runs never featurize; forking idle workers would only
    # pollute the stage timing the flag exists to measure.
    import multiprocessing

    watchdog = faults.PoolWatchdog(
        lambda: multiprocessing.Pool(options.cpus),
        timeout=options.batch_timeout,
        retries=options.batch_retries,
        quarantine=quarantine,
    )
  # Per-batch shm namespace: pool segments are created under
  # <run>b<seq>_ so a SIGKILLed worker's orphans can be reclaimed by
  # prefix without touching other in-flight batches' segments.
  shm_run_prefix = f'dctpu_{os.getpid()}_'
  outcome = stitch.OutcomeCounter()
  # dclint: lock-free (emit worker owns it while running; the main
  # thread writes only the disjoint n_model_pack* keys, merges after
  # the join — see the counter-discipline note in the main loop)
  window_counter: collections.Counter = collections.Counter()
  # dclint: lock-free (list.append is atomic under the GIL; rows are
  # only aggregated after both worker threads have joined)
  timing_rows: List[Dict[str, Any]] = []
  # dclint: lock-free (single writer: the emit worker via nonlocal;
  # the main thread reads it after the emit queue drains)
  fastq_lines = 0

  if output.endswith('.bam'):
    from deepconsensus_tpu.io.bam_writer import BamWriter

    # Carry the CCS BAM header (RG/PG lines) into the output so the
    # per-read RG:Z tags reference declared read groups, as the
    # reference does by opening the writer with template=ccs
    # (quick_inference.py:894-897). Falls back to a bare @HD when no
    # CCS BAM is in play (ccs_fasta mode).
    header_text = '@HD\tVN:1.5\tSO:unknown\n'
    if ccs_bam:
      with bam_lib.BamReader(
          ccs_bam, max_record_bytes=options.max_record_bytes) as ccs_reader:
        if ccs_reader.header_text:
          header_text = ccs_reader.header_text
          if not header_text.endswith('\n'):
            header_text += '\n'
    writer = BamWriter(out_tmp, header_text=header_text, append=resuming)

    def emit_read(name: str, seq: bytes, quals: np.ndarray, meta) -> None:
      ec, np_passes, rq, rg = meta
      tags = {}
      if ec is not None:
        tags['ec'] = float(ec)
      if np_passes is not None:
        tags['np'] = int(np_passes)
      if rq is not None:
        tags['rq'] = float(rq)
      if rg is not None:
        tags['RG'] = str(rg)
      # Non-PacBio names (e.g. ccs_fasta inputs with plain names) have
      # no movie/zmw/type structure; omit the zm tag rather than crash.
      parts = name.split('/')
      if len(parts) >= 2:
        try:
          tags['zm'] = int(parts[1])
        except ValueError:
          pass
      writer.write(
          name,
          seq.decode('ascii'),
          np.asarray(quals, dtype=np.uint8),
          tags=tags,
      )

    close_out = writer.close
    sink_flush = writer.flush
    sink_tell = writer.tell
  else:
    writer = open(out_tmp, 'ab' if resuming else 'wb')

    def emit_read(name: str, seq: bytes, quals: np.ndarray, meta) -> None:
      del meta
      writer.write(stitch.format_fastq_bytes(name, seq, quals))

    close_out = writer.close
    sink_flush = writer.flush
    sink_tell = writer.tell

  partial = True
  counters: Dict[str, Any] = {}
  try:
    try:

      def featurize_batch(zmw_batch, shm_prefix=''):
        """Producer-side: BAM records -> window features for one batch."""
        t0 = time.time()
        fallbacks = [
            z for z in zmw_batch if isinstance(z, faults.CcsFallback)
        ]
        zmws = [
            z for z in zmw_batch if not isinstance(z, faults.CcsFallback)
        ]
        all_windows: List[Dict[str, Any]] = []
        zmw_counters = []
        shm_handles = []
        n_subreads = 0
        pairs = []  # (zmw_input, features, per-zmw counter)

        def quarantine_featurize(zmw_input, error):
          ccs_read = zmw_input[0][-1]
          item = quarantine.handle(
              zmw_input[1], 'featurize', error,
              fallback=lambda r=ccs_read: faults.fallback_from_ccs_read(r),
          )
          if item is not None:
            fallbacks.append(item)

        if watchdog is not None:
          # Bulk tensors travel via shared memory; the result pickle
          # carries only names/offsets (the pipe was the bottleneck).
          # _pool_worker never raises, so starmap always returns and the
          # parent always sees every created shm name (a raising task
          # would discard ALL results, orphaning sibling segments).
          try:
            raw = watchdog.run_batch(
                _pool_worker,
                [(z, options, shm_prefix) for z in zmws],
                chunksize=4,
                shm_prefix=shm_prefix,
            )
          except faults.WatchdogTimeout as e:
            if quarantine is None:
              raise
            # The whole batch exhausted the watchdog; quarantine every
            # ZMW in it (the pool is already re-spawned and the batch's
            # shm segments reclaimed).
            for z in zmws:
              quarantine_featurize(z, e)
            raw = []
          try:
            for zmw_input, (status, payload) in zip(zmws, raw):
              if status != 'ok':
                if quarantine is None:
                  zmw_name = (zmw_input[1]
                              if len(zmw_input) > 1 else None)
                  raise faults.ZmwFault(
                      zmw_name if isinstance(zmw_name, str) else None,
                      'featurize', faults.classify_error(payload),
                      f'featurization worker failed:\n{payload}'
                  )
                quarantine_featurize(
                    zmw_input,
                    f'featurization worker failed:\n{payload}',
                )
                continue
              features, zmw_counter, shm = _features_from_shm(payload)
              pairs.append((zmw_input, features, zmw_counter))
              if shm is not None:
                shm_handles.append(shm)
          except BaseException:
            # Workers unregistered the segments from their resource
            # tracker, so this is the only cleanup: unlink every segment
            # named in raw (attached or not) before propagating.
            from multiprocessing import shared_memory

            attached = {s.name for s in shm_handles}
            for shm in shm_handles:
              try:
                shm.close()
                shm.unlink()
              except OSError:
                pass
            for status, payload in raw:
              if (status == 'ok' and payload[0] is not None
                  and payload[0] not in attached):
                try:
                  leaked = shared_memory.SharedMemory(name=payload[0])
                  leaked.close()
                  leaked.unlink()
                except OSError:
                  pass
            faults.reclaim_shm_segments(shm_prefix)
            raise
        else:
          for z in zmws:
            try:
              features, zmw_counter = preprocess_zmw(z, options)
            except Exception as e:
              if quarantine is None:
                raise
              quarantine_featurize(z, e)
              continue
            pairs.append((z, features, zmw_counter))
        for zmw_input, features, zmw_counter in pairs:
          n_subreads += len(zmw_input[0]) - 1
          zmw_counters.append(zmw_counter)
          all_windows.extend(features)
        t_end = time.time()
        obs_lib.record_stage(runner.obs, obs_lib.trace.STAGE_FEATURIZE,
                             t0, t_end, n_zmws=len(zmw_batch),
                             n_windows=len(all_windows))
        return {
            'windows': all_windows,
            'counters': zmw_counters,
            'n_subreads': n_subreads,
            'n_zmws': len(zmw_batch),
            'preprocess_time': t_end - t0,
            'shm_handles': shm_handles,
            'fallbacks': fallbacks,
        }

      def release_shm(feat):
        for shm in feat.get('shm_handles', ()):
          try:
            shm.close()
            shm.unlink()
          except (FileNotFoundError, OSError):
            pass
        feat['shm_handles'] = []

      def emit_fallback(fb) -> None:
        """Emits a quarantined ZMW's draft CCS read (ccs-fallback)."""
        nonlocal fastq_lines
        result = stitch.fallback_to_arrays(
            fb.molecule_name,
            fb.sequence,
            fb.quality_scores,
            min_quality=options.min_quality,
            min_length=options.min_length,
            max_base_quality=options.max_base_quality,
            counter=window_counter,
        )
        if result is None:
          return
        emit_read(fb.molecule_name, result[0], result[1],
                  (fb.ec, fb.np_num_passes, fb.rq, fb.rg))
        fastq_lines += 1

      # Three-stage pipeline: featurize (producer thread) -> model
      # (main thread: triage + cross-batch packer + dispatch pipeline)
      # -> stitch/emit (dedicated worker thread behind a bounded
      # queue), so device forwards never wait on postprocess or disk.
      # Counter discipline: the producer owns the feeder's `counter`;
      # the main thread updates window triage counts, the emit worker
      # updates outcome/fallback counts (disjoint keys), and everything
      # merges in the sidecar epilogue.
      import queue as queue_lib
      import threading

      feat_queue: 'queue_lib.Queue' = queue_lib.Queue(maxsize=2)
      stop = threading.Event()
      skip_featurize = options.end_after_stage == 'dc_input'

      def put(item) -> bool:
        """Bounded put that aborts when the consumer has bailed."""
        while not stop.is_set():
          try:
            feat_queue.put(item, timeout=0.5)
            return True
          except queue_lib.Full:
            continue
        return False

      def producer():
        obs_lib.trace.set_trace_id(run_trace_id)  # thread-local
        try:
          def flush(zmw_batch) -> bool:
            if not zmw_batch:
              return True
            if skip_featurize:
              # dc_input stage: measure BAM decode/grouping only, so the
              # runtime CSV still carries one row per batch.
              timing_rows.append(
                  dict(stage='dc_input',
                       runtime=time.time() - flush.t_start,
                       n_zmws=len(zmw_batch), n_examples=0,
                       n_subreads=sum(
                           len(z[0]) - 1 for z in zmw_batch
                           if not isinstance(z, faults.CcsFallback))))
              flush.t_start = time.time()
              return True
            feat = featurize_batch(
                zmw_batch, f'{shm_run_prefix}b{flush.seq}_')
            flush.seq += 1
            # Resume bookkeeping: how far the feeder had advanced when
            # this batch was cut (includes skipped/sharded-out groups,
            # which the resume replay skips the same way).
            feat['groups_end'] = counter['n_zmw_processed']
            last = zmw_batch[-1]
            feat['last_zmw'] = (
                last.molecule_name
                if isinstance(last, faults.CcsFallback) else last[1]
            )
            ok = put(('batch', feat))
            if not ok:
              # Consumer bailed mid-flight: this batch will never be
              # consumed, and its shm segments have no other owner.
              release_shm(feat)
            return ok

          flush.t_start = time.time()
          flush.seq = 0
          zmw_batch = []
          for zmw_input in feeder():
            zmw_batch.append(zmw_input)
            if options.batch_zmws and len(zmw_batch) >= options.batch_zmws:
              if not flush(zmw_batch):
                return
              zmw_batch = []
          if not flush(zmw_batch):
            return
          put(('done', None))
        except BaseException as e:  # surface worker failures to the main thread
          put(('error', e))

      full_mode = options.end_after_stage == 'full'
      model_mode = options.end_after_stage in ('run_model', 'full')
      crash_after = faults.injected_crash_after_batches()
      ccs_row = row_indices(options.max_passes, options.use_ccs_bq)[4][0]
      states: 'collections.deque[_BatchState]' = collections.deque()

      def on_pack_failure(slots, pack_seq: int, error) -> None:
        """Attributes a packed-batch failure to its member molecules:
        each affected molecule is quarantined once (adopting its draft
        CCS under ccs-fallback, or dropped under skip), with the pack id
        and its window count recorded in the dead-letter entry."""
        for mol, idx in slots:
          mol.set_result(idx, None, None)
        if quarantine is None:
          raise error
        members: Dict[_MolState, int] = {}
        for mol, _ in slots:
          members[mol] = members.get(mol, 0) + 1
        for mol, n_in_pack in members.items():
          if mol.status != 'ok':
            continue  # already quarantined by an earlier failed pack
          adopted = quarantine.handle(
              mol.name, 'model', error,
              fallback=lambda m=mol: m.adopt_ccs(options),
              extra={'model_pack': pack_seq,
                     'n_windows_in_pack': n_in_pack},
          )
          mol.status = 'adopted' if adopted else 'dropped'

      engine: Optional[ConsensusEngine] = None
      if model_mode:
        # Tickets are (mol, idx) slots; a delivered row resolves its
        # molecule's pending window directly.
        engine = ConsensusEngine(
            runner, options,
            deliver=lambda slot, ids, quals: slot[0].set_result(
                slot[1], ids, quals),
            on_pack_failure=on_pack_failure,
            timing_rows=timing_rows)

      def ingest_batch(feat) -> None:
        """Main-thread stage: triage a featurize batch, copy what the
        emit stage will need out of shm, and feed model windows to the
        packer. The batch's _BatchState completes (and becomes eligible
        for emit) once every pack containing its windows has drained."""
        for zmw_counter in feat['counters']:
          window_counter.update(zmw_counter)
        all_windows = feat['windows']
        timing_rows.append(
            dict(stage='preprocess', runtime=feat['preprocess_time'],
                 n_zmws=feat['n_zmws'], n_examples=len(all_windows),
                 n_subreads=feat['n_subreads']))
        if not model_mode:  # tf_examples: featurization was the point
          return
        state = _BatchState(feat)
        state.n_windows = len(all_windows)
        to_model, to_skip = _triage_windows(all_windows, options,
                                            window_counter)
        for fd in to_skip:
          state.mol(fd).append_resolved(
              fd['window_pos'], *skipped_window_arrays(fd, options))
        slots: List[Tuple[_MolState, int]] = []
        for fd in to_model:
          mol = state.mol(fd)
          # Copies: the feature tensors may live in shm segments that
          # are released as soon as this function returns.
          ccs_ids = fd['subreads'][ccs_row, :, 0].astype(np.uint8)
          ccs_bq = np.array(fd['ccs_base_quality_scores'])
          slots.append(
              (mol,
               mol.append_pending(fd['window_pos'], ccs_ids, ccs_bq)))
        if to_model:
          # A list (not a stacked array): widths may mix across buckets;
          # the engine groups per bucket preserving featurize order.
          engine.submit([fd['subreads'] for fd in to_model], slots)
          if not options.pack_across_batches:
            # Compat/debug mode: pad out this batch's tail instead of
            # carrying it into the next featurize batch's pack.
            engine.flush(drain=False)
        feat['windows'] = None
        state.featurized = True
        states.append(state)

      emit_queue: Optional['queue_lib.Queue'] = None
      emit_thread: Optional[threading.Thread] = None
      # dclint: lock-free (single-writer cell: only the emit worker
      # stores into it; the main thread polls it via check_emit)
      emit_error: List[Optional[BaseException]] = [None]
      emit_stop = threading.Event()

      def check_emit() -> None:
        if emit_error[0] is not None:
          raise emit_error[0]

      def emit_batch_state(state: _BatchState) -> None:
        """Emit-worker stage: stitch + filter + write one featurize
        batch's molecules (sorted by name, matching the string plane's
        global (name, pos) sort order), then its ccs-fallback reads,
        then commit the progress manifest — only after the sink flushed
        this batch's bytes, preserving the durability contract."""
        nonlocal fastq_lines
        feat = state.feat
        t0 = time.time()
        for name in sorted(state.mols):
          mol = state.mols[name]
          if mol.status == 'dropped':
            continue
          try:
            result = stitch.stitch_arrays(
                name,
                np.asarray(mol.pos, dtype=np.int64),
                mol.ids,
                mol.quals,
                max_length=options.max_length,
                min_quality=options.min_quality,
                min_length=options.min_length,
                outcome_counter=outcome,
            )
            if result is not None:
              emit_read(name, result[0], result[1], mol.meta)
              fastq_lines += 1
          except Exception as e:
            if quarantine is None:
              raise
            # No draft CCS survives to this stage; stitch faults can
            # only skip the molecule.
            quarantine.handle(name, 'stitch', e, fallback=None)
        for fb in feat.get('fallbacks', ()):
          emit_fallback(fb)
        t_end = time.time()
        obs_lib.record_stage(runner.obs, obs_lib.trace.STAGE_STITCH,
                             t0, t_end, n_zmws=feat['n_zmws'],
                             n_windows=state.n_windows)
        timing_rows.append(
            dict(stage='stitch_and_write_fastq',
                 runtime=t_end - t0, n_zmws=feat['n_zmws'],
                 n_examples=state.n_windows,
                 n_subreads=feat['n_subreads']))
        if 'groups_end' in feat:
          # Durability point: flush the sink so the manifest's
          # (groups_done, tmp_size) pair names a valid output prefix
          # that --resume can truncate back to.
          sink_flush()
          manifest.commit(
              groups_done=feat['groups_end'],
              tmp_size=sink_tell(),
              source=source,
              last_zmw=feat.get('last_zmw'),
          )

      def emit_worker() -> None:
        obs_lib.trace.set_trace_id(run_trace_id)  # thread-local
        emitted = 0
        try:
          while not emit_stop.is_set():
            try:
              state = emit_queue.get(timeout=0.2)
            except queue_lib.Empty:
              continue
            if state is None:
              return
            emit_batch_state(state)
            emitted += 1
            if crash_after and emitted >= crash_after:
              # dclint: allow=typed-faults (fault-injection hook: the
              # resilience tests expect a bare RuntimeError crash)
              raise RuntimeError(
                  f'injected crash after {emitted} batch(es) '
                  f'({faults.ENV_CRASH_AFTER_BATCHES})'
              )
        # dclint: allow=typed-faults (routes the error to the main
        # thread through the emit_error cell; check_emit() re-raises)
        except BaseException as e:  # surfaced via check_emit()
          emit_error[0] = e

      def emit_put(state) -> None:
        """Bounded put that surfaces an emit-worker death instead of
        blocking forever on its abandoned queue."""
        while True:
          check_emit()
          try:
            emit_queue.put(state, timeout=0.5)
            return
          except queue_lib.Full:
            continue

      def pop_ready() -> None:
        """Hands completed featurize batches to the emit worker, in
        featurize order (pack completion is monotone in that order
        because packs drain FIFO, so per-batch emission order — and
        resume byte-identity — are preserved)."""
        while states and states[0].complete:
          state = states.popleft()
          if emit_thread is not None:
            emit_put(state)

      if full_mode:
        emit_queue = queue_lib.Queue(
            maxsize=max(1, options.emit_queue_depth))
        emit_thread = threading.Thread(target=emit_worker, daemon=True)
        emit_thread.start()

      thread = threading.Thread(target=producer, daemon=True)
      thread.start()
      batches_ingested = 0
      try:
        while True:
          kind, payload = feat_queue.get()
          if kind == 'done':
            break
          if kind == 'error':
            raise payload
          try:
            check_emit()
            ingest_batch(payload)
          finally:
            release_shm(payload)
          pop_ready()
          batches_ingested += 1
          if (crash_after and emit_thread is None
              and batches_ingested >= crash_after):
            # Without an emit stage the main thread is the whole
            # consumer; with one, the injection moves there so the
            # crash still lands just after a manifest commit (see
            # emit_worker).
            # dclint: allow=typed-faults (fault-injection hook: the
            # resilience tests expect a bare RuntimeError crash)
            raise RuntimeError(
                f'injected crash after {batches_ingested} batch(es) '
                f'({faults.ENV_CRASH_AFTER_BATCHES})'
            )
        if engine is not None:
          engine.flush()  # end of input: cut the tail pack, drain all
        pop_ready()
        if states:
          # dclint: allow=typed-faults (internal invariant violation —
          # a packer accounting bug, not an input or request fault)
          raise RuntimeError(
              f'{len(states)} featurize batch(es) never completed the '
              'model stage (packer accounting bug)')
        if emit_thread is not None:
          emit_put(None)
          emit_thread.join()
          check_emit()
      finally:
        stop.set()
        emit_stop.set()
        thread.join(timeout=30)
        if emit_thread is not None:
          emit_thread.join(timeout=30)
        if engine is not None:
          window_counter['n_model_packs'] = engine.n_packs
          window_counter['n_model_pack_rows'] = engine.n_pack_rows
          window_counter['n_model_pad_rows'] = engine.n_pad_rows
          window_counter['n_starvation_flushes'] = (
              engine.n_starvation_flushes)
          window_counter['flush_padding_fraction'] = round(
              engine.flush_padding_fraction, 4)
          window_counter['n_oom_bisections'] = engine.n_oom_bisections
          window_counter['n_device_faults'] = engine.n_device_faults
          window_counter['n_dispatch_timeouts'] = (
              engine.n_dispatch_timeouts)
          dispatch_stats = getattr(runner, 'dispatch_stats', None)
          if dispatch_stats is not None:
            for key, value in dispatch_stats().items():
              window_counter[key] = value
        if thread.is_alive():
          # Draining now would race the producer's put(); anything it
          # enqueues after our drain would leak its shm segments.
          log.warning(
              'producer thread still alive after 30s join; skipping '
              'queue drain (shm segments may leak until exit)')
        else:
          # Producer confirmed dead: drain queued batches (error paths)
          # without racing a concurrent put().
          while True:
            try:
              kind, payload = feat_queue.get_nowait()
            except queue_lib.Empty:
              break
            if kind == 'batch':
              release_shm(payload)
    finally:
      close_out()
      if watchdog is not None:
        watchdog.close()
    # Success: promote <output>.tmp to its final name atomically and
    # drop the progress manifest.
    os.replace(out_tmp, output)
    manifest.delete()
    partial = False
  finally:
    if dead_letter is not None:
      dead_letter.close()
    # dispatch_stats() carries non-numeric labels (inference_dtype);
    # Counter.update would try to add them to 0, so merge those by
    # assignment and keep the numeric tally semantics for the rest.
    for key, value in window_counter.items():
      if isinstance(value, (int, float)):
        counter[key] += value
      else:
        counter[key] = value
    if quarantine is not None:
      counter.update(quarantine.counters)
    # Sidecar outputs (reference: quick_inference.py:777-791,961-962),
    # written on failure too but stamped "partial": true so downstream
    # tooling can't mistake a crashed run for a complete one.
    counters = dict(counter)
    counters.update(dataclasses.asdict(outcome))
    if partial:
      counters['partial'] = True
    try:
      with open(output + '.runtime.csv', 'w', newline='') as f:
        csv_writer = csv.DictWriter(
            f, fieldnames=['stage', 'runtime', 'n_zmws', 'n_examples',
                           'n_subreads']
        )
        csv_writer.writeheader()
        csv_writer.writerows(timing_rows)
      with open(output + '.inference.json', 'w') as f:
        json.dump(counters, f, indent=2, sort_keys=True)
    # dclint: allow=typed-faults (sidecar stats are best-effort: a
    # failed write is logged, never masks the run's own outcome)
    except Exception:  # never mask the run's own error with sidecar IO
      log.exception('failed to write sidecar outputs for %s', output)
  if not outcome.success and options.end_after_stage == 'full':
    log.warning('No reads passed filters; outcome=%s', outcome)
  return counters
