from deepconsensus_tpu.inference.runner import (  # noqa: F401
    InferenceOptions,
    ModelRunner,
    run_inference,
)
