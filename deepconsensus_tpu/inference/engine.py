"""ConsensusEngine: the shared window -> consensus model stage.

The model stage of inference (triage -> pack -> dispatch -> finalize)
used to live entangled with BAM-pipeline concerns inside
inference/runner.py, which made every new consumer — sharded inference,
`dctpu serve`, variable-length workloads — re-touch the same 600-line
file (ROADMAP item 5). This module extracts it behind a narrow
interface:

  engine = ConsensusEngine(runner, options, deliver=..., on_pack_failure=...)
  engine.submit(raw_windows, tickets)   # featurized windows in
  engine.flush()                        # end of input
  # finalized uint8 (ids, quals) rows come back through deliver()

* `tickets` are opaque, one per submitted window; the engine never
  inspects them. deliver(ticket, ids_u8, quals_u8) fires once per
  window as its pack finalizes (same thread as submit/flush).
* The engine owns the cross-batch `_WindowPacker` (full fixed-shape
  packs cut across submissions, pad only on flush), the dispatch depth
  (packs in flight on the device), and — through the ModelRunner and
  its params — the fused-Pallas vs XLA path choice
  (`use_fused_hotpath`, models/model.py `_fused_hotpath_eligible`).
* A pack that fails to dispatch or finalize routes its tickets to
  on_pack_failure(tickets, pack_seq, error); without the callback the
  error propagates (fail-fast).

Two thin clients consume it: the batch CLI pipeline
(inference/runner.py run_inference) and the resident service
(deepconsensus_tpu/serve/). The engine is deliberately NOT thread-safe:
each client drives it from a single model-loop thread.
"""
from __future__ import annotations

import collections
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepconsensus_tpu import faults as faults_lib
from deepconsensus_tpu import obs as obs_lib
from deepconsensus_tpu.calibration import lib as calibration_lib
from deepconsensus_tpu.preprocess.pileup import row_indices
from deepconsensus_tpu.utils import phred

Ticket = Any
DeliverFn = Callable[[Ticket, np.ndarray, np.ndarray], None]
PackFailureFn = Callable[[Sequence[Ticket], int, BaseException], None]


# ----------------------------------------------------------------------
# Window triage (shared by the batch pipeline and the serve path)


def triage_windows(
    feature_dicts: List[Dict[str, Any]],
    options,
    counter: collections.Counter,
) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
  """Splits windows into (model, skip) per overflow/quality rules
  (reference: quick_inference.py:653-678)."""
  to_model: List[Dict[str, Any]] = []
  to_skip: List[Dict[str, Any]] = []
  for fd in feature_dicts:
    if fd['overflow']:
      to_skip.append(fd)
      counter['n_windows_overflow_skipped'] += 1
      continue
    if options.skip_windows_above:
      avg_q = phred.avg_phred(fd['ccs_base_quality_scores'])
      # Strictly above, matching the reference (quick_inference.py:671).
      if avg_q > options.skip_windows_above:
        to_skip.append(fd)
        counter['n_windows_quality_skipped'] += 1
        continue
    to_model.append(fd)
    counter['n_windows_to_model'] += 1
  return to_model, to_skip


def ccs_quals_array(bq_scores, options) -> np.ndarray:
  """CCS base qualities -> emitted phred uint8 (calibration, cap at
  max_base_quality, floor at 0) — the quality half of a skipped-window
  CCS adoption without the string round-trip."""
  quals = np.asarray(bq_scores)
  if options.ccs_calibration_values.enabled:
    quals = calibration_lib.calibrate_quality_scores(
        quals, options.ccs_calibration_values
    )
  quals = np.minimum(quals, options.max_base_quality).astype(np.int32)
  return np.maximum(quals, 0).astype(np.uint8)


def skipped_window_arrays(
    feature_dict: Dict[str, Any], options
) -> Tuple[np.ndarray, np.ndarray]:
  """Array-native skipped-window CCS adoption: (vocab ids uint8 [L],
  phred uint8 [L]) adopted from the draft CCS. Copies out of the
  feature tensor, so any backing shm segment can be released."""
  rows = feature_dict['subreads']
  ccs_range = row_indices(options.max_passes, options.use_ccs_bq)[4]
  ids = rows[ccs_range[0], :, 0].astype(np.uint8)
  return ids, ccs_quals_array(
      feature_dict['ccs_base_quality_scores'], options)


# ----------------------------------------------------------------------
# Cross-batch window packer


class _WindowPacker:
  """Cross-batch window packer feeding the fixed-shape compiled forward.

  Formatted model-input rows accumulate across submissions; full
  batch_size packs are cut and dispatched as soon as they exist, so in
  steady state the forward never runs padded and the dispatch pipeline
  never drains at submission seams (only the end-of-input tail pads).
  Up to dispatch_depth packs stay in flight; draining the oldest hands
  its (ids, quals) rows to deliver(), one call per ticket.

  A pack that fails to dispatch or finalize is routed to
  on_pack_failure(tickets, pack_seq, error) — ticket bookkeeping plus
  any quarantine policy live with the caller. Under
  on_device_error=degrade, typed device faults are absorbed first:
  RESOURCE_EXHAUSTED bisects the pack (retry at half batch), a
  lost/halted device rebuilds the mesh one dp step down and resubmits
  everything that was in flight, in featurize order. Degrade mode
  retains each in-flight pack's host rows to make that resubmission
  possible (up to dispatch_depth packs of extra host memory).
  """

  def __init__(self, runner, options, timing_rows: List[Dict[str, Any]],
               on_pack_failure: PackFailureFn, deliver: DeliverFn,
               poisoned: Optional[set] = None,
               pack_clock: Optional[List[int]] = None):
    self._runner = runner
    self._batch = options.batch_size
    self._depth = max(1, options.dispatch_depth)
    self._degrade = getattr(options, 'on_device_error', 'fail') == 'degrade'
    self._timing_rows = timing_rows
    self._on_pack_failure = on_pack_failure
    self._deliver = deliver
    self._rows: List[np.ndarray] = []
    self._tickets: List[Ticket] = []
    self._buffered = 0
    self._in_flight: 'collections.deque' = collections.deque()
    # Shared across a bucketed engine's packers: one poison set (the
    # caller doesn't know which bucket a ticket landed in) and one
    # global pack clock (every bucket's dispatches tick it) so the
    # starvation rule below can measure "packs the OTHER buckets cut
    # while my tail sat buffered".
    self._poisoned: set = poisoned if poisoned is not None else set()
    self._pack_clock: List[int] = (
        pack_clock if pack_clock is not None else [0])
    # Clock reading when the current buffered tail started waiting.
    self._starve_mark = 0
    # Wall stamp of the same event, for the pack_wait span: how long
    # rows sat buffered before their pack was cut.
    self._t_buf_start = 0.0
    # The runner's metrics registry, when it has one (test stubs don't).
    self._obs = getattr(runner, 'obs', None)
    self.n_packs = 0
    self.n_pack_rows = 0
    self.n_pad_rows = 0
    self.n_starvation_flushes = 0
    self.n_flush_pad_rows = 0
    self.n_oom_bisections = 0
    self.n_device_faults = 0
    self.n_dispatch_timeouts = 0
    self.model_wall = 0.0

  def add(self, rows: np.ndarray, tickets: Sequence[Ticket]) -> None:
    """Buffers one submission's formatted model rows ([k, R, L, 1],
    aligned with tickets) and dispatches every full pack now cuttable."""
    if not self._buffered:
      self._starve_mark = self._pack_clock[0]
      self._t_buf_start = time.time()
    self._rows.append(rows)
    self._tickets.extend(tickets)
    self._buffered += len(rows)
    self._cut_packs(flush=False)

  def maybe_flush_starved(self, limit: int) -> None:
    """Bucket starvation flush: if this packer's partial tail has sat
    buffered while the engine (all buckets together) cut >= limit
    packs, cut it now as a padded partial pack rather than holding its
    windows hostage to a bucket the input stream rarely feeds."""
    if self._buffered and self._pack_clock[0] - self._starve_mark >= limit:
      # Attribute the pad rows of this flush to starvation ONCE, here:
      # _cut_packs -> _dispatch adds the same pads to the general
      # n_pad_rows pool, and the end-of-input flush() cannot re-pad an
      # already-flushed tail (buffered is 0 after the cut), so neither
      # counter double-counts a bucket whose FINAL pack was a
      # starvation flush.
      self.n_starvation_flushes += 1
      self.n_flush_pad_rows += self._batch - self._buffered
      self._cut_packs(flush=True)

  def poison(self, ticket: Ticket) -> None:
    """Fault injection: the pack containing this ticket fails at
    dispatch (simulates a window payload that breaks the model stage —
    DCTPU_FAULT_POISON_WINDOW)."""
    self._poisoned.add(id(ticket))

  def _cut_packs(self, flush: bool) -> None:
    while self._buffered >= self._batch or (flush and self._buffered):
      if len(self._rows) > 1:
        self._rows = [np.concatenate(self._rows)]
      buf = self._rows[0]
      n = min(self._batch, self._buffered)
      pack, rest = buf[:n], buf[n:]
      self._rows = [rest] if len(rest) else []
      tickets = self._tickets[:n]
      del self._tickets[:n]
      self._buffered -= n
      self._dispatch(pack, tickets)

  def _dispatch(self, pack: np.ndarray, tickets: List[Ticket]) -> None:
    seq = self.n_packs
    self.n_packs += 1
    self._pack_clock[0] += 1
    self._starve_mark = self._pack_clock[0]
    t_cut = time.time()
    obs_lib.record_stage(
        self._obs, obs_lib.trace.STAGE_PACK_WAIT,
        self._t_buf_start or t_cut, t_cut,
        bucket=int(pack.shape[2]), n_rows=len(pack))
    # Any leftover tail starts a fresh wait from this cut.
    self._t_buf_start = t_cut
    self.n_pack_rows += len(pack)
    self.n_pad_rows += self._batch - len(pack)
    try:
      if self._poisoned:
        hit = [t for t in tickets if id(t) in self._poisoned]
        if hit:
          for t in hit:
            self._poisoned.discard(id(t))
          # dclint: allow=typed-faults (fault-injection hook: must be
          # a bare RuntimeError so it trips the pack-failure path the
          # same way a real dispatch error would)
          raise RuntimeError(
              'injected poison window payload '
              f'({faults_lib.ENV_POISON_WINDOW}; {len(hit)} window(s) '
              f'in pack {seq})')
      handle = self._runner.dispatch(pack)
    except Exception as e:
      self._handle_pack_fault(pack if self._degrade else None,
                              tickets, seq, e)
      return
    # Degrade mode keeps the host rows so a device fault can bisect or
    # resubmit the pack; fail mode drops them (steady-state memory).
    self._in_flight.append(
        (handle, tickets, seq, pack if self._degrade else None))
    while len(self._in_flight) > self._depth:
      self._drain_one()

  def _drain_one(self) -> None:
    handle, tickets, seq, pack = self._in_flight.popleft()
    t0 = time.time()
    try:
      pred_ids, quality = self._runner.finalize(handle)
    except Exception as e:
      self._handle_pack_fault(pack, tickets, seq, e)
      return
    self._deliver_pack(tickets, pred_ids, quality, t0)

  def _deliver_pack(self, tickets: List[Ticket], pred_ids: np.ndarray,
                    quality: np.ndarray, t0: float) -> None:
    # uint8 transport into the stitch plane (values are 0..4 / 0..93).
    ids_u8 = pred_ids.astype(np.uint8)
    quals_u8 = quality.astype(np.uint8)
    elapsed = time.time() - t0
    self.model_wall += elapsed
    for ticket, row_ids, row_quals in zip(tickets, ids_u8, quals_u8):
      self._deliver(ticket, row_ids, row_quals)
    self._timing_rows.append(dict(
        stage='run_model', runtime=elapsed, n_zmws=0,
        n_examples=len(tickets), n_subreads=0))

  def _handle_pack_fault(self, pack: Optional[np.ndarray],
                         tickets: List[Ticket], seq: int,
                         error: BaseException,
                         batch_size: Optional[int] = None) -> None:
    """Device-fault policy for one failed pack.

    Classifies the error into the DeviceFault family; under
    on_device_error=degrade (and with the pack's host rows retained)
    OOM bisects and a lost device degrades the mesh. Anything
    unrecovered routes to on_pack_failure with the classified error,
    so dead-letters carry the device-fault kind.
    """
    error = faults_lib.classify_device_error(error)
    if isinstance(error, faults_lib.DeviceFault):
      self.n_device_faults += 1
      if isinstance(error, faults_lib.DispatchTimeoutError):
        # The watchdog already bounded the loss; retrying a hung
        # device at the same (or any) shape would hang again.
        self.n_dispatch_timeouts += 1
      elif self._degrade and pack is not None:
        if isinstance(error, faults_lib.DeviceOomError):
          if self._bisect(pack, tickets, seq,
                          batch_size or self._batch):
            return
        elif isinstance(error, faults_lib.DeviceLostError):
          if self._try_degrade(pack, tickets, seq):
            return
    self._on_pack_failure(tickets, seq, error)

  def _bisect(self, pack: np.ndarray, tickets: List[Ticket], seq: int,
              batch_size: int) -> bool:
    """OOM bisection: retry the pack as halves at half batch shape.

    Floors at mesh-dp divisibility (the compiled batch must still
    split over the data axis); returns False when no smaller shape
    exists, handing the pack back to on_pack_failure.
    """
    dp = max(1, getattr(self._runner, 'mesh_dp', 0))
    half = batch_size // 2
    if half < 1 or half % dp:
      return False
    self.n_oom_bisections += 1
    for lo in range(0, len(pack), half):
      self._run_pack_at(pack[lo:lo + half], tickets[lo:lo + half],
                        seq, half)
    return True

  def _try_degrade(self, pack: np.ndarray, tickets: List[Ticket],
                   seq: int) -> bool:
    """Mesh degradation: rebuild at the next lower dp and resubmit the
    failed pack plus everything else in flight (launched on the dead
    topology), in featurize (seq) order."""
    degrade = getattr(self._runner, 'degrade_mesh', None)
    if degrade is None or not degrade():
      return False
    pending = [(pack, tickets, seq)]
    while self._in_flight:
      _handle, ts, s, p = self._in_flight.popleft()
      pending.append((p, ts, s))
    for p, ts, s in sorted(pending, key=lambda entry: entry[2]):
      self._run_pack_at(p, ts, s, self._batch)
    return True

  def _run_pack_at(self, pack: np.ndarray, tickets: List[Ticket],
                   seq: int, batch_size: int) -> None:
    """Synchronous retry of one (possibly bisected) pack at an explicit
    batch shape. Further faults recurse through _handle_pack_fault, so
    a bisected half can bisect again down to the dp floor."""
    t0 = time.time()
    try:
      handle = self._runner.dispatch(pack, batch_size=batch_size)
      pred_ids, quality = self._runner.finalize(handle)
    except Exception as e:
      self._handle_pack_fault(pack, tickets, seq, e,
                              batch_size=batch_size)
      return
    self._deliver_pack(tickets, pred_ids, quality, t0)

  def flush(self, drain: bool = True) -> None:
    """Cuts the sub-batch tail as a final (padded) pack; with drain,
    also resolves every in-flight pack (end of input)."""
    self._cut_packs(flush=True)
    while drain and self._in_flight:
      self._drain_one()

  @property
  def has_work(self) -> bool:
    return bool(self._buffered or self._in_flight)


# ----------------------------------------------------------------------
# Single-stream ragged packer (use_ragged_kernel)


class _RaggedPacker:
  """One pack stream for every bucket width: mixed-width windows pack
  into fixed [n_slots, R, slot_len, 1] slots (slot_len = the largest
  bucket) with a per-slot int32 `lengths` vector, and dispatch through
  the runner's ragged forward (ModelRunner.dispatch_ragged). One
  compiled forward serves the whole run (n_forward_shapes == 1), so
  there is no per-bucket starvation and no starvation flush: packs cut
  only when every slot fills exactly (zero padding in steady state);
  partial, zero-length-padded slots appear only at end-of-input flush.

  Packing is greedy largest-first against the bucket divisibility
  chain (each bucket divides every larger one, enforced by the model's
  ragged path), so every placed window starts at a multiple of its own
  width and the device reshape-select recovers it exactly — per-window
  output stays byte-identical to the per-bucket packers.

  Fault policy is fail-only: typed device faults are classified and
  counted, then the whole pack routes to on_pack_failure. (Bisect /
  mesh-degrade recovery stays a bucketed-path feature; the ragged
  path's single compiled shape is the point.)
  """

  def __init__(self, runner, options, buckets: Tuple[int, ...],
               timing_rows: List[Dict[str, Any]],
               on_pack_failure: PackFailureFn, deliver: DeliverFn,
               poisoned: Optional[set] = None,
               pack_clock: Optional[List[int]] = None):
    buckets = tuple(sorted(int(b) for b in buckets))
    if not buckets or buckets[0] <= 0:
      # dclint: allow=typed-faults (configuration contract, not a
      # data-plane fault: buckets come from resolved model params)
      raise ValueError(f'ragged packing needs positive buckets: {buckets}')
    for small, large in zip(buckets, buckets[1:]):
      if large % small:
        # dclint: allow=typed-faults (same configuration contract —
        # mirrors ops.ragged_window_attention.validate_ragged_buckets
        # without importing jax into the engine)
        raise ValueError(
            'ragged packing needs a bucket divisibility chain '
            f'(each bucket divides the next): {buckets}')
    self._runner = runner
    self._buckets = buckets
    self._slot_len = buckets[-1]
    self._wps = self._slot_len // buckets[0]  # windows per slot, max
    batch = max(1, int(options.batch_size))
    n_slots = max(1, batch // self._wps)
    dp = int(getattr(runner, 'mesh_dp', 0) or 0)
    if dp > 1:
      # The compiled slot batch must split over the data axis.
      n_slots = ((n_slots + dp - 1) // dp) * dp
    self._n_slots = n_slots
    self._depth = max(1, options.dispatch_depth)
    self._timing_rows = timing_rows
    self._on_pack_failure = on_pack_failure
    self._deliver = deliver
    # Per-width FIFO queues of (rows [R, w, 1], ticket): within a
    # width, placement order == submission order, which is what the
    # byte-identity contract pins downstream.
    self._queues: Dict[int, 'collections.deque'] = {
        w: collections.deque() for w in buckets}
    self._buffered = 0
    self._in_flight: 'collections.deque' = collections.deque()
    self._poisoned: set = poisoned if poisoned is not None else set()
    self._pack_clock: List[int] = (
        pack_clock if pack_clock is not None else [0])
    self._t_buf_start = 0.0
    self._obs = getattr(runner, 'obs', None)
    self.n_packs = 0
    self.n_pack_rows = 0
    self.n_pad_rows = 0
    # Structurally zero on the single-stream path (no starvation
    # flush); kept so the engine can aggregate uniformly.
    self.n_starvation_flushes = 0
    self.n_flush_pad_rows = 0
    self.n_oom_bisections = 0
    self.n_device_faults = 0
    self.n_dispatch_timeouts = 0
    self.model_wall = 0.0

  @property
  def slot_len(self) -> int:
    return self._slot_len

  @property
  def n_slots(self) -> int:
    return self._n_slots

  @property
  def windows_per_slot(self) -> int:
    return self._wps

  def add(self, rows: np.ndarray, tickets: Sequence[Ticket]) -> None:
    """Buffers one submission's formatted rows ([k, R, w, 1], one
    bucket width, aligned with tickets) and cuts every pack whose
    n_slots slots can now be filled exactly."""
    width = int(rows.shape[2])
    queue = self._queues.get(width)
    if queue is None:
      # dclint: allow=typed-faults (caller shape contract: windows
      # must arrive pre-padded to a configured bucket)
      raise ValueError(
          f'window width {width} not in window buckets {self._buckets}')
    if not self._buffered:
      self._t_buf_start = time.time()
    for row, ticket in zip(rows, tickets):
      queue.append((row, ticket))
    self._buffered += len(rows)
    self._cut_packs(flush=False)

  def maybe_flush_starved(self, limit: int) -> None:
    """No-op: one pack stream serves every width, so no bucket's tail
    can starve behind another's traffic."""
    del limit

  def poison(self, ticket: Ticket) -> None:
    self._poisoned.add(id(ticket))

  def _plan(self, allow_partial: bool) -> Optional[List[Tuple[int, int, int]]]:
    """Greedy largest-first slot plan: [(slot, offset, width), ...] in
    per-width FIFO order, or None when the slots cannot all be filled
    exactly (and partial packs are not allowed). With the divisibility
    chain, any remaining slot capacity is a multiple of every smaller
    bucket, so largest-first never strands capacity a different order
    could have filled."""
    counts = {w: len(q) for w, q in self._queues.items()}
    plan: List[Tuple[int, int, int]] = []
    for slot in range(self._n_slots):
      remaining = self._slot_len
      while remaining:
        width = next(
            (w for w in reversed(self._buckets)
             if w <= remaining and counts[w]), None)
        if width is None:
          if allow_partial:
            break
          return None
        counts[width] -= 1
        plan.append((slot, self._slot_len - remaining, width))
        remaining -= width
      if allow_partial and not any(counts.values()):
        break
    return plan

  def _cut_packs(self, flush: bool) -> None:
    while True:
      plan = self._plan(allow_partial=False)
      if plan is None:
        break
      self._dispatch(plan)
    while flush and self._buffered:
      self._dispatch(self._plan(allow_partial=True))

  def _dispatch(self, plan: List[Tuple[int, int, int]]) -> None:
    seq = self.n_packs
    self.n_packs += 1
    self._pack_clock[0] += 1
    t_cut = time.time()
    obs_lib.record_stage(
        self._obs, obs_lib.trace.STAGE_PACK_WAIT,
        self._t_buf_start or t_cut, t_cut,
        bucket=self._slot_len, n_rows=len(plan))
    self._t_buf_start = t_cut
    # Materialize the pack from the plan, popping each width's FIFO.
    first_row = self._queues[plan[0][2]][0][0]
    n_rows = first_row.shape[0]
    pack = np.zeros((self._n_slots, n_rows, self._slot_len, 1),
                    dtype=np.float32)
    lengths = np.zeros((self._n_slots, self._wps), dtype=np.int32)
    slot_fill = [0] * self._n_slots
    placements: List[Tuple[Ticket, int, int, int]] = []
    used = 0
    for slot, off, width in plan:
      row, ticket = self._queues[width].popleft()
      pack[slot, :, off:off + width] = row
      lengths[slot, slot_fill[slot]] = width
      slot_fill[slot] += 1
      placements.append((ticket, slot, off, width))
      used += width
    self._buffered -= len(placements)
    self.n_pack_rows += len(placements)
    # Unused position capacity in min-bucket units: the windows a full
    # pack of the same shape could additionally have carried.
    self.n_pad_rows += (
        self._n_slots * self._slot_len - used) // self._buckets[0]
    tickets = [p[0] for p in placements]
    try:
      if self._poisoned:
        hit = [t for t in tickets if id(t) in self._poisoned]
        if hit:
          for t in hit:
            self._poisoned.discard(id(t))
          # dclint: allow=typed-faults (fault-injection hook: must be
          # a bare RuntimeError so it trips the pack-failure path the
          # same way a real dispatch error would)
          raise RuntimeError(
              'injected poison window payload '
              f'({faults_lib.ENV_POISON_WINDOW}; {len(hit)} window(s) '
              f'in ragged pack {seq})')
      handle = self._runner.dispatch_ragged(pack, lengths)
    except Exception as e:
      self._handle_pack_fault(placements, seq, e)
      return
    self._in_flight.append((handle, placements, seq))
    while len(self._in_flight) > self._depth:
      self._drain_one()

  def _drain_one(self) -> None:
    handle, placements, seq = self._in_flight.popleft()
    t0 = time.time()
    try:
      pred_ids, quality = self._runner.finalize(handle)
    except Exception as e:
      self._handle_pack_fault(placements, seq, e)
      return
    # uint8 transport into the stitch plane (values are 0..4 / 0..93).
    ids_u8 = pred_ids.astype(np.uint8)
    quals_u8 = quality.astype(np.uint8)
    elapsed = time.time() - t0
    self.model_wall += elapsed
    for ticket, slot, off, width in placements:
      self._deliver(ticket, ids_u8[slot, off:off + width],
                    quals_u8[slot, off:off + width])
    self._timing_rows.append(dict(
        stage='run_model', runtime=elapsed, n_zmws=0,
        n_examples=len(placements), n_subreads=0))

  def _handle_pack_fault(self, placements, seq: int,
                         error: BaseException) -> None:
    error = faults_lib.classify_device_error(error)
    if isinstance(error, faults_lib.DeviceFault):
      self.n_device_faults += 1
      if isinstance(error, faults_lib.DispatchTimeoutError):
        self.n_dispatch_timeouts += 1
    self._on_pack_failure([p[0] for p in placements], seq, error)

  def flush(self, drain: bool = True) -> None:
    """Cuts the buffered tail as final (zero-length-padded) packs;
    with drain, also resolves every in-flight pack (end of input).
    The ONLY place partial packs exist on the ragged path."""
    self._cut_packs(flush=True)
    while drain and self._in_flight:
      self._drain_one()

  @property
  def has_work(self) -> bool:
    return bool(self._buffered or self._in_flight)


# ----------------------------------------------------------------------
# The engine


def _raise_pack_failure(tickets, pack_seq: int, error: BaseException):
  del tickets, pack_seq
  raise error


class ConsensusEngine:
  """Submit featurized windows, receive finalized uint8 (ids, quals).

  Owns one window packer PER LENGTH BUCKET (params.window_buckets /
  options.window_buckets; single bucket = the historical fixed-shape
  engine), the dispatch depth, and (via the ModelRunner / model
  config) the fused-kernel vs XLA path choice — eligibility is
  per-bucket: traces at L <= the fused VMEM limit run the Pallas hot
  path, longer buckets the XLA fallback. Mixed-width submissions are
  grouped by trailing window width; within each bucket, delivery stays
  in featurize order, so per-bucket output is byte-identical to a
  single-bucket run over the same windows. See the module docstring
  for the contract; construct via __init__ with an existing
  ModelRunner or via from_checkpoint.
  """

  def __init__(self, runner, options, deliver: DeliverFn,
               on_pack_failure: Optional[PackFailureFn] = None,
               timing_rows: Optional[List[Dict[str, Any]]] = None):
    self.runner = runner
    self.options = options
    self.timing_rows = timing_rows if timing_rows is not None else []
    self._deliver_fn = deliver
    self._on_pack_failure = on_pack_failure or _raise_pack_failure
    self._buckets = self._resolve_buckets()
    # One packer per bucket, created on first window of that width;
    # all packers share the poison set and the global pack clock.
    self._packers: Dict[int, _WindowPacker] = {}
    self._poisoned: set = set()
    self._pack_clock: List[int] = [0]
    self._n_windows_by_bucket: Dict[int, int] = {}
    # use_ragged_kernel: ONE pack stream for every width — a single
    # _RaggedPacker replaces the per-bucket fleet, and every pack
    # dispatches at the same [n_slots, R, slot_len] shape.
    self._ragged = bool(getattr(options, 'use_ragged_kernel', False))
    self._ragged_packer: Optional[_RaggedPacker] = None

  def _resolve_buckets(self) -> Tuple[int, ...]:
    buckets = getattr(self.options, 'window_buckets', None)
    if buckets:
      return tuple(int(b) for b in buckets)
    params = getattr(self.runner, 'params', None)
    if params is not None:
      from deepconsensus_tpu.models import config as config_lib

      return config_lib.resolve_window_buckets(params)
    return (int(self.options.max_length),)

  @property
  def window_buckets(self) -> Tuple[int, ...]:
    return self._buckets

  def _packer_for(self, width: int):
    if self._ragged:
      if width not in self._buckets:
        # dclint: allow=typed-faults (caller shape contract: windows
        # must arrive pre-padded to a configured bucket)
        raise ValueError(
            f'window width {width} not in window buckets {self._buckets}')
      if self._ragged_packer is None:
        self._ragged_packer = _RaggedPacker(
            self.runner, self.options, self._buckets, self.timing_rows,
            lambda ts, seq, err: self._on_pack_failure(ts, seq, err),
            lambda t, ids, quals: self._deliver_fn(t, ids, quals),
            poisoned=self._poisoned, pack_clock=self._pack_clock)
      return self._ragged_packer
    packer = self._packers.get(width)
    if packer is None:
      if width not in self._buckets:
        # dclint: allow=typed-faults (caller shape contract: windows
        # must arrive pre-padded to a configured bucket)
        raise ValueError(
            f'window width {width} not in window buckets {self._buckets}')
      packer = _WindowPacker(
          self.runner, self.options, self.timing_rows,
          # Indirection so predict_windows can swap the deliver sink
          # for every bucket at once.
          lambda ts, seq, err: self._on_pack_failure(ts, seq, err),
          lambda t, ids, quals: self._deliver_fn(t, ids, quals),
          poisoned=self._poisoned, pack_clock=self._pack_clock)
      self._packers[width] = packer
    return packer

  def _add_rows(self, rows: np.ndarray, tickets: List[Ticket]) -> None:
    width = int(rows.shape[2])
    self._n_windows_by_bucket[width] = (
        self._n_windows_by_bucket.get(width, 0) + len(rows))
    self._packer_for(width).add(rows, tickets)

  def _all_packers(self) -> List[Any]:
    """Every live packer: the per-bucket fleet, or the one ragged
    packer. Counter aggregation and flush iterate this so neither path
    double-counts."""
    if self._ragged:
      return [self._ragged_packer] if self._ragged_packer else []
    return [self._packers[w] for w in sorted(self._packers)]

  def _flush_starved(self) -> None:
    if self._ragged:
      return  # single pack stream: no bucket can starve
    limit = int(getattr(self.options, 'bucket_flush_packs', 0) or 0)
    if limit <= 0 or len(self._packers) < 2:
      return
    for width in sorted(self._packers):
      self._packers[width].maybe_flush_starved(limit)

  @staticmethod
  def _group_by_width(windows, tickets) -> Dict[int, Tuple[list, list]]:
    """Groups per-window tensors by trailing window width, preserving
    submission order within each group (delivery order within a bucket
    is what the byte-identity contract pins)."""
    groups: Dict[int, Tuple[list, list]] = {}
    for w, t in zip(windows, tickets):
      w = np.asarray(w)
      ws, ts = groups.setdefault(int(w.shape[-2]), ([], []))
      ws.append(w)
      ts.append(t)
    return groups

  @classmethod
  def from_checkpoint(cls, checkpoint_path: str, options,
                      deliver: DeliverFn,
                      on_pack_failure: Optional[PackFailureFn] = None,
                      timing_rows: Optional[List[Dict[str, Any]]] = None,
                      mesh=None) -> 'ConsensusEngine':
    from deepconsensus_tpu.inference import runner as runner_lib
    from deepconsensus_tpu.models import config as config_lib

    runner = runner_lib.ModelRunner.from_checkpoint(
        checkpoint_path, options, mesh=mesh)
    options.max_passes = runner.params.max_passes
    options.max_length = runner.params.max_length
    options.use_ccs_bq = runner.params.use_ccs_bq
    # Bucket-aware options: an explicit options.window_buckets must be
    # consistent with the checkpoint's base geometry; unset follows
    # params.window_buckets (single shape when that too is unset).
    options.window_buckets = config_lib.normalize_window_buckets(
        getattr(options, 'window_buckets', None) or
        getattr(runner.params, 'window_buckets', None),
        runner.params.max_length)
    return cls(runner, options, deliver,
               on_pack_failure=on_pack_failure, timing_rows=timing_rows)

  @property
  def params(self):
    return self.runner.params

  def submit(self, raw_windows,
             tickets: Sequence[Ticket]) -> None:
    """Feeds featurized window tensors (one ticket per window) through
    format -> pack -> dispatch. Accepts a uniform [k, total_rows, L, 1]
    array or a sequence of [total_rows, L, 1] tensors with mixed L;
    mixed widths are grouped per bucket. Full packs dispatch
    immediately; each bucket's tail waits for more windows, the
    starvation flush, or flush()."""
    from deepconsensus_tpu.models import data as data_lib

    if len(raw_windows) != len(tickets):
      # dclint: allow=typed-faults (caller API misuse guard, not a
      # data-plane fault: both args come from the same client code)
      raise ValueError(
          f'{len(raw_windows)} windows vs {len(tickets)} tickets')
    if not len(raw_windows):
      return
    if isinstance(raw_windows, np.ndarray) and raw_windows.dtype != object:
      rows = data_lib.format_rows_batch(
          np.asarray(raw_windows), self.runner.params,
          window_buckets=self._buckets)
      self._add_rows(rows, list(tickets))
    else:
      for width, (ws, ts) in sorted(
          self._group_by_width(raw_windows, tickets).items()):
        self._add_rows(
            data_lib.format_rows_batch(np.stack(ws), self.runner.params,
                                       window_buckets=self._buckets),
            ts)
    self._flush_starved()

  def submit_formatted(self, rows,
                       tickets: Sequence[Ticket]) -> None:
    """submit() for rows already through data.format_rows_batch (the
    serve retry path re-dispatches without re-formatting). Accepts a
    uniform [k, R, L, 1] array or a sequence of [R, L, 1] rows with
    mixed L."""
    if len(rows) != len(tickets):
      # dclint: allow=typed-faults (caller API misuse guard, not a
      # data-plane fault: both args come from the same client code)
      raise ValueError(f'{len(rows)} rows vs {len(tickets)} tickets')
    if not len(rows):
      return
    if isinstance(rows, np.ndarray) and rows.dtype != object:
      self._add_rows(np.asarray(rows), list(tickets))
    else:
      for _width, (ws, ts) in sorted(
          self._group_by_width(rows, tickets).items()):
        self._add_rows(np.stack(ws), ts)
    self._flush_starved()

  def flush(self, drain: bool = True) -> None:
    """Cuts every bucket's buffered tail as a padded pack; with drain,
    resolves every in-flight pack (every submitted ticket has been
    delivered or failed when this returns). Tails cut for all buckets
    before any drain so the end-of-input packs overlap on device."""
    for packer in self._all_packers():
      packer.flush(drain=False)
    if drain:
      for packer in self._all_packers():
        packer.flush(drain=True)

  def poison_ticket(self, ticket: Ticket) -> None:
    # Shared across buckets: the caller doesn't know (or care) which
    # bucket the window landed in.
    self._poisoned.add(id(ticket))

  @property
  def has_work(self) -> bool:
    """True while any submitted window is still buffered or in flight."""
    return any(p.has_work for p in self._all_packers())

  def _agg(self, name: str):
    return sum(getattr(p, name) for p in self._all_packers())

  @property
  def n_packs(self) -> int:
    return self._agg('n_packs')

  @property
  def n_pack_rows(self) -> int:
    return self._agg('n_pack_rows')

  @property
  def n_pad_rows(self) -> int:
    return self._agg('n_pad_rows')

  @property
  def model_wall(self) -> float:
    return self._agg('model_wall')

  @property
  def n_oom_bisections(self) -> int:
    return self._agg('n_oom_bisections')

  @property
  def n_device_faults(self) -> int:
    return self._agg('n_device_faults')

  @property
  def n_dispatch_timeouts(self) -> int:
    return self._agg('n_dispatch_timeouts')

  @property
  def n_starvation_flushes(self) -> int:
    return self._agg('n_starvation_flushes')

  @property
  def n_packs_by_bucket(self) -> Dict[int, int]:
    if self._ragged:
      packer = self._ragged_packer
      return {packer.slot_len: packer.n_packs} if packer else {}
    return {w: self._packers[w].n_packs for w in sorted(self._packers)}

  @property
  def flush_padding_fraction(self) -> float:
    """Fraction of all dispatched positions that were starvation-flush
    padding: sum_b(n_flush_pad_rows_b * L_b) / sum_b(n_packs_b * B * L_b).
    Separates the cost of the bucket_flush_packs policy from ordinary
    end-of-input padding; structurally 0.0 on the ragged path."""
    dispatched = 0
    flushed = 0
    for width, packer in sorted(self._packers.items()):
      dispatched += packer.n_packs * packer._batch * width
      flushed += packer.n_flush_pad_rows * width
    return flushed / dispatched if dispatched else 0.0

  @property
  def padding_fraction(self) -> float:
    """Fraction of positions a pad-to-max policy would have dispatched
    on top of the bucketed dispatch: 1 - sum(n_b * L_b) / (N * L_max).
    0.0 with a single bucket or before any window arrives."""
    total = sum(self._n_windows_by_bucket.values())
    if not total or len(self._buckets) < 2:
      return 0.0
    bucketed = sum(
        n * w for w, n in self._n_windows_by_bucket.items())
    return 1.0 - bucketed / (total * max(self._buckets))

  def stats(self) -> Dict[str, Any]:
    out = {
        'n_model_packs': self.n_packs,
        'n_model_pack_rows': self.n_pack_rows,
        'n_model_pad_rows': self.n_pad_rows,
        'n_starvation_flushes': self.n_starvation_flushes,
        'flush_padding_fraction': round(self.flush_padding_fraction, 4),
        'model_wall_s': round(self.model_wall, 3),
        'n_oom_bisections': self.n_oom_bisections,
        'n_device_faults': self.n_device_faults,
        'n_dispatch_timeouts': self.n_dispatch_timeouts,
    }
    # Sharded-dispatch / transfer-overlap counters (stub runners in
    # tests may not implement the full dispatch contract).
    dispatch_stats = getattr(self.runner, 'dispatch_stats', None)
    if dispatch_stats is not None:
      out.update(dispatch_stats())
    # Bucketed-dispatch counters (after the runner merge: the engine's
    # per-packer view is authoritative for pack accounting).
    out['window_buckets'] = list(self._buckets)
    out['use_ragged_kernel'] = int(self._ragged)
    out['n_packs_by_bucket'] = self.n_packs_by_bucket
    out['n_windows_by_bucket'] = {
        w: self._n_windows_by_bucket[w]
        for w in sorted(self._n_windows_by_bucket)}
    out['padding_fraction'] = round(self.padding_fraction, 4)
    return out

  def predict_windows(
      self, raw_windows
  ) -> Tuple[Any, Any]:
    """Synchronous convenience: featurized windows -> (ids, quals),
    in submission order. Flushes the pipeline, so only for tools/tests
    — streaming callers use submit()/flush() with tickets. Uniform
    widths return stacked arrays; mixed widths return aligned lists."""
    results: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
    save = self._deliver_fn
    try:
      self._deliver_fn = (
          lambda ticket, ids, quals: results.__setitem__(
              ticket, (ids, quals)))
      self.submit(raw_windows, list(range(len(raw_windows))))
      self.flush()
    finally:
      self._deliver_fn = save
    ids = [results[i][0] for i in range(len(raw_windows))]
    quals = [results[i][1] for i in range(len(raw_windows))]
    if len({i.shape for i in ids}) <= 1:
      return np.stack(ids), np.stack(quals)
    return ids, quals
