"""Command-line interface: dctpu {preprocess,run,train,calibrate,filter_reads}.

Mirrors the reference's subcommand surface (reference:
deepconsensus/cli.py:50-118) with argparse.
"""
from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def _coerce_override(raw: str, current):
  """Parses a --set value against the config entry's current type."""
  if raw.lower() in ('none', 'null'):
    return None
  if isinstance(current, bool):
    if raw.lower() in ('true', '1', 'yes'):
      return True
    if raw.lower() in ('false', '0', 'no'):
      return False
    raise ValueError(f'expected a boolean, got {raw!r}')
  for cast in (int, float):
    if isinstance(current, cast):
      return cast(raw)
  if current is None:
    # Untyped (e.g. band_width / use_pallas_wavefront default to
    # None): best-effort bool, then numeric.
    if raw.lower() in ('true', 'yes'):
      return True
    if raw.lower() in ('false', 'no'):
      return False
    for cast in (int, float):
      try:
        return cast(raw)
      except ValueError:
        continue
  return raw


def _apply_overrides(params, overrides: List[str]) -> None:
  """Applies --set KEY=VALUE items to an unlocked-able config. Must run
  before finalize_params so derived values (total_rows, hidden_size)
  see the overrides. Transformer size keys (num_hidden_layers,
  num_heads, filter_size) only materialize inside finalize_params,
  which fills them from the size preset ONLY when absent — so
  pre-setting them here is legal and wins over the preset."""
  from deepconsensus_tpu.models import config as config_lib

  late_keys = frozenset(
      k for preset in config_lib.TRANSFORMER_SIZE_PARAMS.values()
      for k in preset)
  with params.unlocked():
    for item in overrides:
      key, eq, raw = item.partition('=')
      if not eq or not (hasattr(params, key) or key in late_keys):
        raise ValueError(f'unknown config override {item!r}')
      setattr(params, key,
              _coerce_override(raw, getattr(params, key, None)))


def _add_preprocess(sub):
  p = sub.add_parser('preprocess', help='Generate examples from BAMs.')
  p.add_argument('--subreads_to_ccs', required=True)
  p.add_argument('--ccs_bam', required=True)
  p.add_argument('--output', required=True,
                 help="Output path; '@split' expands per split.")
  p.add_argument('--max_passes', type=int, default=20)
  p.add_argument('--example_width', type=int, default=100)
  p.add_argument('--use_ccs_bq', action='store_true')
  p.add_argument('--ins_trim', type=int, default=5)
  p.add_argument('--use_ccs_smart_windows', action='store_true')
  p.add_argument('--truth_bed')
  p.add_argument('--truth_to_ccs')
  p.add_argument('--truth_split')
  p.add_argument('--limit', type=int, default=0)
  p.add_argument('--cpus', type=int, default=0)
  p.add_argument('--shard', default=None, metavar='I/N',
                 type=_parse_shard,
                 help='Process only ZMWs with zm %% N == I (fleet '
                 'scaling; shard the output paths too).')
  p.add_argument('--compression', choices=['bgzf', 'gzip'], default='bgzf',
                 help='.gz shard framing: bgzf (default; valid gzip, '
                 'parallel-decodable blocks) or single-member gzip.')


def _add_run(sub):
  p = sub.add_parser('run', help='Run inference: BAMs -> polished FASTQ.')
  p.add_argument('--subreads_to_ccs', required=True)
  p.add_argument('--ccs_bam', required=True)
  p.add_argument('--checkpoint', required=True)
  p.add_argument('--output', required=True)
  p.add_argument('--batch_size', type=int, default=1024)
  p.add_argument('--batch_zmws', type=int, default=100)
  p.add_argument('--min_length', type=int, default=0)
  p.add_argument('--min_quality', type=int, default=20)
  p.add_argument('--skip_windows_above', type=int, default=45)
  p.add_argument('--ins_trim', type=int, default=5)
  p.add_argument('--use_ccs_smart_windows', action='store_true')
  p.add_argument('--max_base_quality', type=int, default=93)
  p.add_argument('--dc_calibration', default=None)
  p.add_argument('--ccs_calibration', default='skip')
  p.add_argument('--limit', type=int, default=0)
  p.add_argument('--dp', type=int, default=0,
                 help='Shard the window batch over this many devices '
                 '(0 = single device).')
  p.add_argument('--tp', type=int, default=1,
                 help='Tensor-parallel mesh size per data shard '
                 '(attention heads / FFN filter shard).')
  p.add_argument('--cpus', type=int, default=0,
                 help='Featurization worker processes (0 or 1 = '
                 'in-process; tensors travel via shared memory).')
  p.add_argument('--end_after_stage', default='full',
                 choices=['dc_input', 'tf_examples', 'run_model', 'full'],
                 help='Stop the pipeline early for debugging/timing '
                 '(reference DebugStage).')
  p.add_argument('--shard', default=None, metavar='I/N',
                 type=_parse_shard,
                 help='Process only ZMWs with zm %% N == I, e.g. 3/500 '
                 '— fleet scaling over one shared BAM without '
                 'splitting it.')
  p.add_argument('--on_zmw_error', default='fail',
                 choices=['fail', 'skip', 'ccs-fallback'],
                 help='Per-ZMW fault policy: fail aborts the run '
                 '(historical behavior); skip quarantines the ZMW to '
                 '<output>.failed.jsonl; ccs-fallback additionally '
                 'emits the draft CCS read with its original base '
                 'qualities.')
  p.add_argument('--batch_timeout', type=float, default=0.0,
                 help='Watchdog timeout (s) per featurization batch '
                 'when --cpus > 1; a hung or killed worker triggers '
                 'pool re-spawn and retry (0 disables).')
  p.add_argument('--batch_retries', type=int, default=2,
                 help='Watchdog retries per featurization batch before '
                 'the batch is quarantined.')
  p.add_argument('--resume', action='store_true',
                 help='Resume an interrupted run from '
                 '<output>.progress.json + <output>.tmp, replaying the '
                 'feeder past already-committed ZMWs.')
  p.add_argument('--dispatch_depth', type=int, default=8,
                 help='Model packs kept in flight on the device before '
                 'the oldest is drained; raise to hide host-side '
                 'stacking latency, lower to bound memory.')
  p.add_argument('--emit_queue_depth', type=int, default=4,
                 help='Featurize batches buffered between the model '
                 'stage and the stitch/emit worker before the model '
                 'stage blocks.')
  p.add_argument('--no_cross_batch_packing', action='store_true',
                 help='Pad out each featurize batch\'s model tail '
                 'instead of packing windows across batches into full '
                 'fixed-shape model batches (debug/compat).')
  p.add_argument('--max_record_bytes', type=int, default=64 << 20,
                 help='Per-record allocation cap for the BAM decoders: '
                 'a record claiming more than this many bytes is '
                 'treated as corrupt (quarantined under '
                 '--on_zmw_error=skip) instead of allocated.')
  _add_epilogue_flag(p)
  _add_quant_flags(p)
  _add_bucket_flag(p)
  _add_device_fault_flags(p)
  _add_trace_flag(p)


def _add_trace_flag(p):
  p.add_argument('--trace', default=None, metavar='TRACE.jsonl',
                 help='Append Chrome-trace-event spans (Perfetto-'
                 'loadable) to this file. Equivalent to setting '
                 'DCTPU_TRACE; fleet tiers may share one file. '
                 'Summarize with `dctpu trace`.')


def _add_epilogue_flag(p):
  # Tri-state (None/auto by default): an explicit choice is enforced
  # against exported-artifact metadata, auto follows it.
  g = p.add_mutually_exclusive_group()
  g.add_argument('--device_epilogue', dest='device_epilogue',
                 action='store_true', default=None,
                 help='Device-resident output plane: compute argmax + '
                 'Phred quality (threshold table, byte-identical to '
                 'the host math) on device and drain uint8 planes — 2 '
                 'bytes/position D2H instead of 8. Default: on for '
                 'checkpoints, follow-the-artifact for exported runs.')
  g.add_argument('--no_device_epilogue', dest='device_epilogue',
                 action='store_false',
                 help='Force the host quality path (ship int32 ids + '
                 'f32 max_prob and do the Phred math on the host).')


def _add_quant_flags(p):
  p.add_argument('--inference_dtype', default=None,
                 choices=['float32', 'bfloat16'],
                 help='Inference weight/activation dtype: bfloat16 '
                 'casts checkpoint weights once at load and runs the '
                 'model end-to-end in bf16 (softmax accumulation '
                 'stays f32). Default keeps the checkpoint dtype.')
  p.add_argument('--quantize_matmuls', default=None,
                 choices=['none', 'int8'],
                 help='int8: per-channel symmetric weight '
                 'quantization of the encoder attention/FFN matmuls '
                 'at load; dequant runs in the fused-kernel epilogue.')


def _parse_window_buckets(text):
  try:
    buckets = tuple(int(x) for x in text.split(',') if x.strip())
  except ValueError:
    raise argparse.ArgumentTypeError(
        f'--window_buckets must be comma-separated ints, got {text!r}')
  if not buckets:
    raise argparse.ArgumentTypeError('--window_buckets is empty')
  return buckets


def _add_bucket_flag(p):
  p.add_argument('--window_buckets', default=None,
                 type=_parse_window_buckets, metavar='L1,L2,...',
                 help='Window length buckets, e.g. 100,200: each '
                 'variable-width (smart) window pads to the smallest '
                 'bucket that fits instead of pad-to-max, and each '
                 'bucket dispatches through its own compile-once '
                 'forward (fused hot path for L<=128, XLA above). The '
                 'smallest bucket must equal the model max_length. '
                 'Default: the checkpoint\'s params.window_buckets '
                 '(single-shape when unset).')
  p.add_argument('--use_ragged_kernel', action='store_true',
                 default=False,
                 help='Single-pack-stream ragged dispatch: pack mixed-'
                 'width windows back-to-back into fixed-length slots '
                 '(slot = the largest bucket) with a per-slot lengths '
                 'vector and run ONE compiled ragged forward for every '
                 'width — no per-bucket packer fleet, no starvation '
                 'flush, n_forward_shapes == 1. Requires buckets that '
                 'form a divisibility chain (the default 100,200 '
                 'does). Off: the per-bucket packers (byte-identical '
                 'output either way).')


def _add_train_bucket_flag(p):
  # Training-side counterpart of _add_bucket_flag: buckets only (the
  # ragged pack stream is an inference dispatch mode).
  p.add_argument('--window_buckets', default=None,
                 type=_parse_window_buckets, metavar='L1,L2,...',
                 help='Bucketed multi-width training, e.g. 100,200: '
                 'each window pads to the smallest bucket that fits, '
                 'batches stay width-pure, and each bucket compiles '
                 'exactly ONE train step over the shared param tree '
                 '(n_train_forward_shapes == number of buckets, zero '
                 'mid-run recompiles). Widths at or past 256 route '
                 'attention through the blockwise ring scan (the L=500 '
                 'long-insert path; requires attention_dropout=0). The '
                 'smallest bucket must equal max_length. Default: '
                 'single-shape pad-to-max.')


def _add_device_fault_flags(p):
  p.add_argument('--on_device_error', default='fail',
                 choices=['fail', 'degrade'],
                 help='Device fault policy: fail propagates device '
                 'runtime errors (historical behavior); degrade '
                 'bisects RESOURCE_EXHAUSTED packs to half batch and '
                 'rebuilds the mesh one dp step down (8->4->2->1) '
                 'after a lost/halted device, resubmitting the failed '
                 'pack in featurize order.')
  p.add_argument('--dispatch_timeout', type=float, default=0.0,
                 help='Dispatch watchdog: bound each pack\'s blocking '
                 'finalize to this many seconds; a hung forward '
                 'surfaces as DispatchTimeoutError through pack '
                 'failure attribution instead of wedging the model '
                 'loop (0 disables).')


def _add_serve(sub):
  p = sub.add_parser(
      'serve',
      help='Resident consensus service: keep the compiled forward '
      'warm and polish molecules over a local HTTP endpoint.')
  p.add_argument('--checkpoint', default=None,
                 help='Checkpoint or exported-artifact dir (required '
                 'unless --random_init).')
  p.add_argument('--host', default='127.0.0.1')
  p.add_argument('--port', type=int, default=8764,
                 help='Listen port (0 = pick a free port; the bound '
                 'port is printed in the ready line).')
  p.add_argument('--batch_size', type=int, default=1024)
  p.add_argument('--dispatch_depth', type=int, default=8)
  p.add_argument('--min_length', type=int, default=0)
  p.add_argument('--min_quality', type=int, default=20)
  p.add_argument('--skip_windows_above', type=int, default=45)
  p.add_argument('--max_base_quality', type=int, default=93)
  p.add_argument('--dc_calibration', default=None)
  p.add_argument('--ccs_calibration', default='skip')
  p.add_argument('--max_pending', type=int, default=64,
                 help='Outstanding admitted requests before new ones '
                 'are shed with 429 backpressure.')
  p.add_argument('--admit_queue_depth', type=int, default=32,
                 help='Requests queued ahead of the model loop before '
                 'admission sheds with 429.')
  p.add_argument('--max_windows_per_request', type=int, default=512)
  p.add_argument('--max_body_mb', type=int, default=64,
                 help='Request bodies above this are rejected (413) '
                 'before any bytes are read.')
  p.add_argument('--default_deadline_s', type=float, default=120.0,
                 help='Per-request deadline when the client sends no '
                 'X-Dctpu-Deadline-S header; expiry cancels the '
                 'request (504) and reclaims its queued windows.')
  p.add_argument('--max_deadline_s', type=float, default=600.0)
  p.add_argument('--io_timeout_s', type=float, default=20.0,
                 help='Per-socket read/write timeout; a slow-drip or '
                 'half-dead client is cut after this long.')
  p.add_argument('--on_request_error', default='ccs-fallback',
                 choices=['skip', 'ccs-fallback'],
                 help='Policy for a request whose windows fail the '
                 'model stage twice (shared pack + isolation retry).')
  p.add_argument('--dead_letter', default=None,
                 help='Append quarantined-request records (with '
                 'request attribution) to this JSONL sidecar.')
  p.add_argument('--compilation_cache_dir', default=None,
                 help='Persistent JAX compilation cache: restarts skip '
                 'the jit compile, so /readyz flips in seconds.')
  p.add_argument('--random_init', action='store_true',
                 help='Serve randomly initialized weights from '
                 '--config instead of a checkpoint (tests/demos).')
  p.add_argument('--config', default='transformer_learn_values+test',
                 help='Model preset for --random_init.')
  p.add_argument('--dp', type=int, default=0,
                 help='Data-parallel devices: each pack is dp-sharded '
                 'over the mesh data axis (batch_size must divide '
                 'evenly). 0 = single-device serving.')
  p.add_argument('--tp', type=int, default=1,
                 help='Tensor-parallel devices per replica (model-axis '
                 'sharded attention/FFN weights); exported artifacts '
                 'require tp=1.')
  _add_epilogue_flag(p)
  _add_quant_flags(p)
  _add_bucket_flag(p)
  _add_device_fault_flags(p)
  _add_trace_flag(p)


def _add_route(sub):
  p = sub.add_parser(
      'route',
      help='Fleet front tier: load-balance /v1/polish across dctpu '
      'serve replicas, steering bam/1 bodies through featurize '
      'workers first.')
  p.add_argument('--replica', action='append', default=[],
                 metavar='HOST:PORT',
                 help='Model replica address; repeatable. Replicas '
                 'join health-gated (no traffic until /readyz '
                 'passes); more can join at runtime via '
                 'POST /v1/register.')
  p.add_argument('--featurize_worker', action='append', default=[],
                 metavar='HOST:PORT',
                 help='Featurize worker address; repeatable. bam/1 '
                 'requests are featurized here before a model '
                 'replica sees them.')
  p.add_argument('--host', default='127.0.0.1')
  p.add_argument('--port', type=int, default=8765)
  p.add_argument('--probe_interval_s', type=float, default=0.5,
                 help='Health/signal probe cadence per replica '
                 '(/readyz + /metricz).')
  p.add_argument('--max_inflight', type=int, default=8,
                 help='Bounded in-flight requests per replica, scaled '
                 'by its mesh_dp; when every ready replica is at its '
                 'bound the router sheds with a typed 503.')
  p.add_argument('--max_attempts', type=int, default=3,
                 help='Distinct replicas tried per request; only '
                 'requests a replica provably never accepted are '
                 'retried.')
  p.add_argument('--io_timeout_s', type=float, default=20.0)
  p.add_argument('--upstream_timeout_s', type=float, default=300.0,
                 help='End-to-end budget for one forwarded request.')
  p.add_argument('--max_body_mb', type=int, default=64)
  p.add_argument('--default_class', default='interactive',
                 help='Priority class for requests without an '
                 'X-Dctpu-Class header.')
  p.add_argument('--class_weight', action='append', default=[],
                 metavar='CLASS=WEIGHT',
                 help='Weighted-fair admission share for a priority '
                 'class; repeatable (default: interactive=4 bulk=1).')
  p.add_argument('--client_quota', type=int, default=0,
                 help='Max concurrent requests per client id (429 '
                 'RESOURCE_EXHAUSTED above it); 0 = unlimited.')
  p.add_argument('--queue_wait_s', type=float, default=0.0,
                 help='How long a saturated request may wait its '
                 'weighted-fair turn before shedding (0 = shed '
                 'immediately).')
  p.add_argument('--max_queued_per_class', type=int, default=16,
                 help='Waiting requests per class before that class '
                 '(and only that class) sheds.')
  _add_trace_flag(p)


def _add_autoscale(sub):
  p = sub.add_parser(
      'autoscale',
      help='SLO autoscaler: watch a router\'s /metricz and '
      'spawn/drain serve replicas to hold a p99/queue-depth target, '
      'replacing preempted replicas.')
  p.add_argument('--router', required=True, metavar='HOST:PORT',
                 help='The dctpu route endpoint to watch and register '
                 'spawned replicas with.')
  p.add_argument('--tier', default='model', choices=['model', 'featurize'])
  p.add_argument('--min_replicas', type=int, default=1)
  p.add_argument('--max_replicas', type=int, default=4)
  p.add_argument('--target_p99_s', type=float, default=2.0,
                 help='SLO: scale out while the slo_class p99 exceeds '
                 'this.')
  p.add_argument('--target_queue_depth', type=float, default=4.0,
                 help='Scale out while mean READY-replica queue depth '
                 'exceeds this.')
  p.add_argument('--slo_class', default='interactive',
                 help='Priority class whose p99 drives scaling.')
  p.add_argument('--poll_interval_s', type=float, default=1.0)
  p.add_argument('--scale_out_cooldown_s', type=float, default=5.0)
  p.add_argument('--scale_in_cooldown_s', type=float, default=60.0)
  p.add_argument('--spawn_ready_timeout_s', type=float, default=180.0,
                 help='How long a spawned replica may take to print '
                 'its ready line (first spawn pays the jit compile; '
                 'later ones hit the shared compilation cache).')
  p.add_argument('--serve_arg', action='append', default=[],
                 metavar='ARG',
                 help='Extra argv token for spawned `dctpu serve` '
                 'replicas; repeatable (e.g. --serve_arg=--random_init '
                 '--serve_arg=--compilation_cache_dir=/ramdisk/cc). '
                 'Spawns always get --host 127.0.0.1 --port 0.')
  p.add_argument('--leave_managed', action='store_true',
                 help='On exit, leave spawned replicas serving instead '
                 'of draining them (an autoscaler restart then adopts '
                 'nothing but the fleet stays up).')
  _add_trace_flag(p)


def _add_featurize_worker(sub):
  p = sub.add_parser(
      'featurize-worker',
      help='Disaggregated featurize tier: BAM decode/pileup on CPU '
      'boxes, shipping compact uint8 window packs to model replicas.')
  p.add_argument('--host', default='127.0.0.1')
  p.add_argument('--port', type=int, default=8766)
  p.add_argument('--config', default='transformer_learn_values+test',
                 help='Model preset naming the feature layout '
                 '(max_passes/max_length/use_ccs_bq) this worker '
                 'produces; must match the model replicas behind the '
                 'same router.')
  p.add_argument('--ins_trim', type=int, default=0)
  p.add_argument('--use_ccs_smart_windows', action='store_true')
  p.add_argument('--work_dir', default=None,
                 help='Scratch dir for per-request mini BAMs (use a '
                 'tmpfs in production).')
  p.add_argument('--no_compact', action='store_true',
                 help='Always ship legacy float32 frames instead of '
                 'features/1 uint8 packs.')
  p.add_argument('--io_timeout_s', type=float, default=20.0)
  p.add_argument('--max_body_mb', type=int, default=64)
  _add_bucket_flag(p)
  _add_trace_flag(p)


def _add_validate(sub):
  p = sub.add_parser(
      'validate',
      help='Preflight-check inputs before spending TPU time on them.')
  p.add_argument('--subreads_to_ccs', default=None,
                 help='actc output BAM (subreads aligned to ccs).')
  p.add_argument('--ccs_bam', default=None,
                 help='ccs BAM; with --subreads_to_ccs also checks '
                 'name/order consistency between the pair.')
  p.add_argument('--tfrecord', action='append', default=[],
                 metavar='GLOB',
                 help='TFRecord path or glob (repeatable); every '
                 'matching shard is CRC-checked end to end.')
  p.add_argument('--max_record_bytes', type=int, default=None,
                 help='Per-record allocation cap (default 64 MiB).')
  p.add_argument('--report', default=None,
                 help='Also write the JSON report to this path '
                 '(always printed to stdout).')


def _add_lint(sub):
  p = sub.add_parser(
      'lint',
      help='AST static analysis over the package (tools/dclint): '
      'typed-faults, jit-hazards, guarded-by, shape-literals.')
  p.add_argument('lint_paths', nargs='*', metavar='PATH',
                 help='Files/dirs to lint (default: the whole '
                 'deepconsensus_tpu package).')
  p.add_argument('--root', default=None, dest='lint_root',
                 help='Repository root (default: autodetected).')
  p.add_argument('--baseline', default=None, dest='lint_baseline',
                 help='Baseline JSON path (default: '
                 'tools/dclint/baseline.json).')
  p.add_argument('--update-baseline', action='store_true',
                 help='Rewrite the baseline with the current findings '
                 '(refuses typed-faults/guarded-by entries: those get '
                 'fixed, not suppressed).')
  p.add_argument('--no-baseline', action='store_true',
                 help='Ignore the baseline; report and fail on every '
                 'finding.')
  p.add_argument('--format', choices=('text', 'json'), default='text',
                 dest='lint_format')


def _add_trace(sub):
  p = sub.add_parser(
      'trace',
      help='Summarize a DCTPU_TRACE span file: per-stage breakdown, '
      'critical-path attribution, straggler packs, span-derived '
      'transfer overlap.')
  p.add_argument('trace_file', metavar='TRACE.jsonl',
                 help='Trace written by --trace / DCTPU_TRACE '
                 '(one file, possibly shared by a whole fleet).')
  p.add_argument('--json', action='store_true', dest='trace_json',
                 help='Emit the summary as JSON instead of text.')
  p.add_argument('--top', type=int, default=10,
                 help='Max straggler packs listed (default 10).')


def _add_train(sub):
  p = sub.add_parser('train', help='Train a model.')
  p.add_argument('--config', default='transformer_learn_values+test',
                 help='{model}+{dataset} preset name.')
  p.add_argument('--out_dir', required=True)
  p.add_argument('--train_path', nargs='*')
  p.add_argument('--eval_path', nargs='*')
  p.add_argument('--num_epochs', type=int)
  p.add_argument('--batch_size', type=int)
  p.add_argument('--set', action='append', default=[], metavar='KEY=VALUE',
                 dest='overrides',
                 help='Config override, repeatable (e.g. '
                 '--set use_pallas_wavefront=true --set loss_reg=0.5).')
  p.add_argument('--checkpoint', help='Warm-start checkpoint.')
  p.add_argument('--on_shard_error', choices=('fail', 'skip'),
                 help='Streaming-loader policy for an undecodable '
                 'shard: fail (default) aborts, skip counts + logs '
                 'the shard and keeps training.')
  p.add_argument('--tp', type=int, default=1,
                 help='Tensor-parallel mesh size.')
  p.add_argument('--dp', type=int, default=None,
                 help='Data-parallel mesh size (default: all devices '
                 'not used by --tp).')
  p.add_argument('--on_device_error', default='fail',
                 choices=['fail', 'degrade'],
                 help='Mid-training device fault policy: fail '
                 'propagates (the retry wrapper restarts from the '
                 'last checkpoint at full dp), degrade rebuilds the '
                 'mesh one dp step down over the surviving devices, '
                 're-places the live state, and keeps training.')
  p.add_argument('--coordinator_address',
                 help='host:port of process 0 (multi-host training).')
  p.add_argument('--num_processes', type=int,
                 help='Total number of hosts (multi-host training).')
  p.add_argument('--process_id', type=int,
                 help='This host\'s index (multi-host training).')
  p.add_argument('--elastic', action='store_true',
                 help='Elastic multi-host mode: every cross-host '
                 'collective is a bounded barrier over a shared '
                 'filesystem under <out_dir>/.pod, a lost host '
                 'triggers a coordinated pod rebuild instead of a '
                 'hang, and a recovered host is re-admitted at the '
                 'next step boundary. Uses --process_id/'
                 '--num_processes for membership; jax.distributed is '
                 'NOT initialized (the pod owns cross-host transport).')
  p.add_argument('--on_host_error', default='degrade',
                 choices=['fail', 'degrade'],
                 help='Elastic policy when a barrier times out on a '
                 'missing host: fail propagates HostLostError (the '
                 'retry wrapper restarts from the last checkpoint), '
                 'degrade rebuilds the pod over the surviving hosts, '
                 're-places the live state, and resumes from the '
                 'failed step (default).')
  p.add_argument('--elastic_barrier_timeout', type=float, default=30.0,
                 help='Deadline in seconds for every elastic '
                 'collective (step sync, checkpoint barrier, '
                 'stop-vote). On expiry the missing host is named in '
                 'a typed HostLostError; no collective waits '
                 'unbounded (default 30).')
  p.add_argument('--elastic_readmit', dest='elastic_readmit',
                 action='store_true', default=True,
                 help='Allow a recovered host to rejoin the pod at a '
                 'step boundary (default on).')
  p.add_argument('--no_elastic_readmit', dest='elastic_readmit',
                 action='store_false',
                 help='Refuse re-admission; a lost host stays lost '
                 'until the run restarts.')
  _add_train_bucket_flag(p)


def _add_evaluate(sub):
  p = sub.add_parser(
      'evaluate',
      help='Offline eval over labeled TFRecords -> inference.csv '
      '(counterpart of the reference model_inference binary).',
  )
  p.add_argument('--checkpoint', required=True)
  p.add_argument('--eval_path', nargs='+', required=True)
  p.add_argument('--out_dir', required=True)
  p.add_argument('--limit', type=int, default=-1,
                 help='Max eval examples (-1 = all).')
  p.add_argument('--batch_size', type=int)


def _add_port(sub):
  p = sub.add_parser(
      'port',
      help='Port a reference TF checkpoint to a servable orbax '
      'checkpoint (requires tensorflow).',
  )
  p.add_argument('--tf_checkpoint', required=True,
                 help='TF checkpoint prefix (.../checkpoint-N).')
  p.add_argument('--params', required=True,
                 help='params.json path or directory containing it.')
  p.add_argument('--out_dir', required=True)


def _add_export(sub):
  p = sub.add_parser(
      'export',
      help='Export a checkpoint as a serving artifact (StableHLO), the '
      'counterpart of the reference convert_to_saved_model tool.',
  )
  p.add_argument('--checkpoint', required=True,
                 help='Orbax checkpoint directory (with params.json).')
  p.add_argument('--output', required=True, help='Output directory.')
  p.add_argument('--batch_size', type=int, default=1024,
                 help='Recommended serving batch size recorded in the '
                 'artifact metadata. The export is batch-polymorphic '
                 '(serves any batch size) unless symbolic export fails, '
                 'in which case this size is baked in.')
  p.add_argument('--strict_polymorphic', action='store_true',
                 help='Fail instead of falling back to a fixed-batch '
                 'artifact when batch-polymorphic export fails.')
  p.add_argument('--device_epilogue', dest='device_epilogue',
                 action='store_true', default=True,
                 help='Bake the device output plane into the artifact: '
                 'the serving call returns final uint8 (ids, quals) '
                 'planes with the calibration/clamp below compiled in '
                 '(default).')
  p.add_argument('--no_device_epilogue', dest='device_epilogue',
                 action='store_false',
                 help='Export a pre-epilogue artifact that returns '
                 'softmax preds (host computes qualities).')
  p.add_argument('--max_base_quality', type=int, default=93,
                 help='Quality clamp baked into the device epilogue '
                 '(must match serving; recorded in the metadata).')
  p.add_argument('--dc_calibration', default=None,
                 help='Calibration string baked into the device '
                 'epilogue; default reads dc_calibration from the '
                 'checkpoint params.json (like dctpu run).')
  _add_quant_flags(p)


def _add_distill(sub):
  p = sub.add_parser('distill', help='Distill a teacher into a student.')
  p.add_argument('--teacher_checkpoint', required=True)
  p.add_argument('--config', default='transformer_learn_values_distill+test')
  p.add_argument('--out_dir', required=True)
  p.add_argument('--train_path', nargs='*')
  p.add_argument('--eval_path', nargs='*')
  p.add_argument('--num_epochs', type=int)
  p.add_argument('--batch_size', type=int)
  p.add_argument('--set', action='append', default=[], metavar='KEY=VALUE',
                 dest='overrides',
                 help='Student config override, repeatable (same semantics '
                 'as train --set; applied before finalize_params).')
  _add_train_bucket_flag(p)


def _add_flywheel(sub):
  p = sub.add_parser(
      'flywheel',
      help='Train -> distill -> quantization gates -> export, one '
      'command: produces a servable baked artifact plus a manifest '
      'recording every stage and gate result. A failed gate aborts '
      'before export (exit 3).',
  )
  p.add_argument('--out_dir', required=True,
                 help='Flywheel root; stages land in teacher/, '
                 'student/, gates/, export/ plus flywheel_manifest.json.')
  p.add_argument('--train_path', nargs='+', required=True)
  p.add_argument('--eval_path', nargs='+', required=True)
  p.add_argument('--config', default='transformer_learn_values+test',
                 help='Teacher {model}+{dataset} preset.')
  p.add_argument('--student_config',
                 default='transformer_learn_values_distill+test',
                 help='Student (distillation) preset.')
  p.add_argument('--teacher_checkpoint', default=None,
                 help='Existing teacher checkpoint: skip the training '
                 'stage and spin the flywheel from here (the common '
                 'retrain-student loop).')
  p.add_argument('--num_epochs', type=int)
  p.add_argument('--batch_size', type=int)
  p.add_argument('--set', action='append', default=[], metavar='KEY=VALUE',
                 dest='overrides',
                 help='Teacher config override, repeatable.')
  p.add_argument('--student_set', action='append', default=[],
                 metavar='KEY=VALUE', dest='student_overrides',
                 help='Student config override, repeatable.')
  p.add_argument('--export_batch_size', type=int, default=1024)
  p.add_argument('--int8_gate', type=float, default=None,
                 help='Override the int8 alignment-identity delta gate '
                 '(default 0.002, from the acceptance test).')
  p.add_argument('--bf16_gate', type=int, default=None,
                 help='Override the bf16 max per-base QV delta gate '
                 '(default 3, from the acceptance test).')
  p.add_argument('--tp', type=int, default=1,
                 help='Tensor-parallel mesh size for train/distill.')
  p.add_argument('--resume', action='store_true',
                 help='Adopt <out_dir>/flywheel_journal.json: skip '
                 'completed stages (inputs re-validated — a changed '
                 'flag raises a typed FlywheelResumeError, exit 2) and '
                 're-enter the in-flight stage idempotently.')
  p.add_argument('--elastic', action='store_true',
                 help='Run the train and distill stages under the '
                 'elastic pod protocol (dctpu train --elastic); a lost '
                 'host degrades the pod at the stage retry instead of '
                 'killing the cycle.')
  p.add_argument('--num_processes', type=int, default=None,
                 help='Elastic pod size (hosts).')
  p.add_argument('--process_id', type=int, default=None,
                 help='This host\'s id within the elastic pod.')
  p.add_argument('--on_host_error', choices=('fail', 'degrade'),
                 default='degrade')
  p.add_argument('--elastic_barrier_timeout', type=float, default=30.0)
  p.add_argument('--elastic_readmit', dest='elastic_readmit',
                 action='store_true', default=True)
  p.add_argument('--no_elastic_readmit', dest='elastic_readmit',
                 action='store_false')
  _add_train_bucket_flag(p)
  p.add_argument('--baseline_checkpoint', default=None,
                 help='Reference checkpoint (e.g. the L=100 production '
                 'model) to evaluate on the same eval shards as the '
                 'student: the gates stage records an informational '
                 'long_insert_identity_vs_baseline entry comparing '
                 'alignment_identity student vs baseline in the '
                 'manifest (never vetoes export).')
  _add_quant_flags(p)


def _add_calibrate(sub):
  p = sub.add_parser(
      'calibrate', help='Measure empirical base-quality calibration.')
  p.add_argument('--bam', required=True,
                 help='Predictions aligned to the reference genome.')
  p.add_argument('--ref', required=True, help='Reference FASTA.')
  p.add_argument('--output', required=True, help='Output CSV.')
  p.add_argument('--region')
  p.add_argument('--cpus', type=int, default=0)


def _add_yield_metrics(sub):
  p = sub.add_parser(
      'yield_metrics', help='Yield@Q table from truth-aligned reads.')
  p.add_argument('--bam', required=True,
                 help='Polished reads aligned to the truth.')
  p.add_argument('--ref', required=True, help='Truth FASTA.')
  p.add_argument('--output', required=True, help='Output CSV.')
  p.add_argument('--identity_bar', type=float, default=0.999)


def _add_filter_reads(sub):
  p = sub.add_parser('filter_reads', help='Filter reads by avg quality.')
  p.add_argument('--input', required=True, help='FASTQ or BAM input.')
  p.add_argument('--output', required=True, help='FASTQ output (.gz ok).')
  p.add_argument('--quality', type=int, required=True)


def _parse_shard(value):
  """argparse type: 'I/N' -> (i, n) with 0 <= i < n."""
  try:
    i_str, n_str = value.split('/')
    i, n = int(i_str), int(n_str)
  except ValueError:
    raise argparse.ArgumentTypeError(
        f'expected I/N (e.g. 3/500), got {value!r}'
    )
  if not 0 <= i < n:
    raise argparse.ArgumentTypeError(f'need 0 <= I < N, got {value!r}')
  return (i, n)


def build_parser() -> argparse.ArgumentParser:
  parser = argparse.ArgumentParser(
      prog='dctpu',
      description='DeepConsensus-TPU: TPU-native CCS polishing.',
  )
  sub = parser.add_subparsers(dest='command', required=True)
  _add_preprocess(sub)
  _add_run(sub)
  _add_serve(sub)
  _add_route(sub)
  _add_autoscale(sub)
  _add_featurize_worker(sub)
  _add_validate(sub)
  _add_lint(sub)
  _add_trace(sub)
  _add_train(sub)
  _add_distill(sub)
  _add_flywheel(sub)
  _add_export(sub)
  _add_port(sub)
  _add_evaluate(sub)
  _add_calibrate(sub)
  _add_yield_metrics(sub)
  _add_filter_reads(sub)
  return parser


def main(argv: Optional[List[str]] = None) -> int:
  try:
    return _dispatch(build_parser().parse_args(argv))
  except FileNotFoundError as e:
    print(f'dctpu: file not found: {e}', file=sys.stderr)
    return 2
  except ValueError as e:
    print(f'dctpu: {e}', file=sys.stderr)
    return 2
  except KeyboardInterrupt:
    print('dctpu: interrupted', file=sys.stderr)
    return 130


def _dispatch(args) -> int:
  if getattr(args, 'trace', None):
    # --trace is sugar for DCTPU_TRACE: the env var is what each tier's
    # *_main reads (and what spawned fleet processes inherit).
    import os

    os.environ['DCTPU_TRACE'] = args.trace

  if args.command == 'trace':
    import json

    from deepconsensus_tpu import faults as faults_lib
    from deepconsensus_tpu.obs import summarize as summarize_lib

    try:
      events = summarize_lib.load_trace(args.trace_file)
      summary = summarize_lib.summarize(events)
    except faults_lib.CorruptInputError as e:
      print(f'dctpu: {e}', file=sys.stderr)
      return 2
    summary['stragglers'] = summary['stragglers'][:max(args.top, 0)]
    if args.trace_json:
      print(json.dumps(summary, indent=2))
    else:
      print(summarize_lib.format_summary(summary))
    return 0

  if args.command == 'preprocess':
    from deepconsensus_tpu.preprocess.driver import run_preprocess

    run_preprocess(
        subreads_to_ccs=args.subreads_to_ccs,
        ccs_bam=args.ccs_bam,
        output=args.output,
        max_passes=args.max_passes,
        example_width=args.example_width,
        use_ccs_bq=args.use_ccs_bq,
        ins_trim=args.ins_trim,
        use_ccs_smart_windows=args.use_ccs_smart_windows,
        truth_bed=args.truth_bed,
        truth_to_ccs=args.truth_to_ccs,
        truth_split=args.truth_split,
        limit=args.limit,
        cpus=args.cpus,
        shard=args.shard,
        compression=args.compression.upper(),
    )
    return 0

  if args.command == 'validate':
    import json

    from deepconsensus_tpu.io import validate as validate_lib

    if (args.subreads_to_ccs is None and args.ccs_bam is None
        and not args.tfrecord):
      raise ValueError(
          'validate needs at least one of --subreads_to_ccs, '
          '--ccs_bam, --tfrecord')
    report = validate_lib.validate_inputs(
        subreads_to_ccs=args.subreads_to_ccs,
        ccs_bam=args.ccs_bam,
        tfrecords=args.tfrecord,
        max_record_bytes=args.max_record_bytes,
    )
    text = json.dumps(report, indent=2)
    print(text)
    if args.report:
      with open(args.report, 'w') as f:
        f.write(text + '\n')
    return 0 if report['ok'] else 1

  if args.command == 'lint':
    import os

    try:
      from tools.dclint import __main__ as dclint_main
    except ImportError:
      # Installed-package invocation: tools/ is not shipped, but a
      # source checkout keeps it two levels above this file.
      import deepconsensus_tpu

      repo_root = os.path.dirname(os.path.dirname(
          os.path.abspath(deepconsensus_tpu.__file__)))
      if not os.path.isdir(os.path.join(repo_root, 'tools', 'dclint')):
        raise ValueError(
            'dctpu lint needs a source checkout (tools/dclint not '
            f'found under {repo_root})')
      sys.path.insert(0, repo_root)
      from tools.dclint import __main__ as dclint_main
    lint_argv = list(args.lint_paths)
    if args.lint_root:
      lint_argv += ['--root', args.lint_root]
    if args.lint_baseline:
      lint_argv += ['--baseline', args.lint_baseline]
    if args.update_baseline:
      lint_argv.append('--update-baseline')
    if args.no_baseline:
      lint_argv.append('--no-baseline')
    lint_argv += ['--format', args.lint_format]
    return dclint_main.run(lint_argv)

  if args.command == 'serve':
    import json

    from deepconsensus_tpu.calibration import lib as calibration_lib
    from deepconsensus_tpu.inference import runner as runner_lib
    from deepconsensus_tpu.models import config as config_lib
    from deepconsensus_tpu.serve import server as server_lib
    from deepconsensus_tpu.serve.service import ServeOptions

    if args.compilation_cache_dir:
      import jax

      jax.config.update(
          'jax_compilation_cache_dir', args.compilation_cache_dir)
      jax.config.update(
          'jax_persistent_cache_min_compile_time_secs', 0.0)
    dc_cal = args.dc_calibration
    if dc_cal is None and args.checkpoint:
      params_json = config_lib.read_params_from_json(args.checkpoint)
      dc_cal = params_json.get('dc_calibration', 'skip') or 'skip'
    options = runner_lib.InferenceOptions(
        batch_size=args.batch_size,
        dispatch_depth=args.dispatch_depth,
        min_length=args.min_length,
        min_quality=args.min_quality,
        skip_windows_above=args.skip_windows_above,
        max_base_quality=args.max_base_quality,
        on_device_error=args.on_device_error,
        dispatch_timeout=args.dispatch_timeout,
        inference_dtype=args.inference_dtype,
        quantize_matmuls=args.quantize_matmuls,
        device_epilogue=args.device_epilogue,
        window_buckets=args.window_buckets,
        use_ragged_kernel=args.use_ragged_kernel,
        dc_calibration_values=calibration_lib.parse_calibration_string(
            dc_cal or 'skip'),
        ccs_calibration_values=calibration_lib.parse_calibration_string(
            args.ccs_calibration),
    )
    mesh = None
    if args.dp or args.tp > 1:
      import jax

      from deepconsensus_tpu.parallel import mesh as mesh_lib

      dp = args.dp or 1
      mesh = mesh_lib.make_mesh(
          dp=dp, tp=args.tp, devices=jax.devices()[:dp * args.tp]
      )
    if args.random_init:
      import jax
      import jax.numpy as jnp

      from deepconsensus_tpu.models import model as model_lib

      params = config_lib.get_config(args.config)
      config_lib.finalize_params(params, is_training=False)
      # Checkpoint loads fold the levers in inside from_checkpoint;
      # random-init weights get the same treatment here so --random_init
      # serves exercise the identical quantized path.
      runner_lib._apply_quant_levers(params, options)
      variables = model_lib.get_model(params).init(
          jax.random.PRNGKey(0),
          jnp.zeros((1, params.total_rows, params.max_length, 1)))
      runner = runner_lib.ModelRunner(params, variables, options,
                                      mesh=mesh)
    elif args.checkpoint:
      runner = runner_lib.ModelRunner.from_checkpoint(
          args.checkpoint, options, mesh=mesh)
    else:
      raise ValueError('serve needs --checkpoint or --random_init')
    options.max_passes = runner.params.max_passes
    options.max_length = runner.params.max_length
    options.use_ccs_bq = runner.params.use_ccs_bq
    options.window_buckets = config_lib.normalize_window_buckets(
        options.window_buckets
        or getattr(runner.params, 'window_buckets', None),
        runner.params.max_length)
    serve_options = ServeOptions(
        max_pending=args.max_pending,
        admit_queue_depth=args.admit_queue_depth,
        max_windows_per_request=args.max_windows_per_request,
        max_body_bytes=args.max_body_mb << 20,
        default_deadline_s=args.default_deadline_s,
        max_deadline_s=args.max_deadline_s,
        io_timeout_s=args.io_timeout_s,
        on_request_error=args.on_request_error,
        dead_letter_path=args.dead_letter,
    )
    stats = server_lib.serve_main(
        runner, options, serve_options,
        host=args.host, port=args.port,
        ready_fn=lambda info: print(json.dumps(info), flush=True))
    print(json.dumps({'event': 'drained', **stats}, default=str),
          flush=True)
    return 0 if stats.get('drained') else 1

  if args.command == 'route':
    import json

    from deepconsensus_tpu.fleet import router as router_lib

    if not args.replica and not args.featurize_worker:
      raise ValueError(
          'route needs at least one --replica or --featurize_worker')
    class_weights = None
    if args.class_weight:
      class_weights = {}
      for spec in args.class_weight:
        name, sep, weight = spec.partition('=')
        if not sep:
          raise ValueError(
              f'--class_weight expects CLASS=WEIGHT, got {spec!r}')
        class_weights[name] = float(weight)
    options = router_lib.RouterOptions(
        max_body_bytes=args.max_body_mb << 20,
        io_timeout_s=args.io_timeout_s,
        upstream_timeout_s=args.upstream_timeout_s,
        probe_interval_s=args.probe_interval_s,
        max_inflight=args.max_inflight,
        max_attempts=args.max_attempts,
        class_weights=class_weights,
        default_class=args.default_class,
        client_quota=args.client_quota,
        queue_wait_s=args.queue_wait_s,
        max_queued_per_class=args.max_queued_per_class,
    )
    stats = router_lib.route_main(
        replicas=args.replica,
        featurize_workers=args.featurize_worker,
        options=options,
        host=args.host, port=args.port,
        ready_fn=lambda info: print(json.dumps(info), flush=True))
    print(json.dumps({'event': 'drained', **stats}, default=str),
          flush=True)
    return 0 if stats.get('drained') else 1

  if args.command == 'autoscale':
    import json
    import signal as signal_lib
    import subprocess
    import threading
    import time

    from deepconsensus_tpu import obs as obs_lib
    from deepconsensus_tpu.fleet import autoscaler as autoscaler_lib
    from deepconsensus_tpu.serve.client import ServeClient
    from deepconsensus_tpu.serve.server import _StopFlag

    obs_lib.trace.configure_from_env(tier='autoscaler')
    router_host, _, router_port = args.router.partition(':')
    router_client = ServeClient(
        router_host or '127.0.0.1', int(router_port), timeout=10.0)
    subcommand = 'serve' if args.tier == 'model' else 'featurize-worker'
    procs = {}  # url -> Popen; only the autoscale loop thread touches it
    all_procs = []  # every Popen ever spawned, for final reaping

    def spawn():
      cmd = ([sys.executable, '-m', 'deepconsensus_tpu.cli', subcommand,
              '--host', '127.0.0.1', '--port', '0']
             + list(args.serve_arg))
      proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True)
      deadline = time.monotonic() + args.spawn_ready_timeout_s
      info = None
      while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
          raise RuntimeError(
              f'spawned {subcommand} replica exited rc={proc.poll()} '
              'before its ready line')
        try:
          parsed = json.loads(line)
        except ValueError:
          continue
        if parsed.get('event') == 'ready':
          info = parsed
          break
      if info is None:
        proc.kill()
        raise RuntimeError(
            f'spawned {subcommand} replica not ready within '
            f'{args.spawn_ready_timeout_s}s')
      url = f'127.0.0.1:{info["port"]}'
      status, body, _ = router_client._request(
          'POST', '/v1/register',
          body=json.dumps({'url': url, 'tier': args.tier}).encode(),
          headers={'Content-Type': 'application/json'})
      if status != 200:
        proc.terminate()
        raise RuntimeError(
            f'router register of {url} failed: HTTP {status} '
            f'{body[:200].decode("latin-1")}')
      procs[url] = proc
      all_procs.append(proc)
      print(json.dumps({'event': 'spawned', 'url': url,
                        'tier': args.tier}), flush=True)
      return url

    def drain(url):
      proc = procs.pop(url, None)
      if proc is None or proc.poll() is not None:
        return
      proc.send_signal(signal_lib.SIGTERM)
      # Reap off-thread: the SIGTERM drain may take max_deadline_s and
      # must not stall the control loop.
      threading.Thread(target=proc.wait, daemon=True).start()

    options = autoscaler_lib.AutoscalerOptions(
        tier=args.tier,
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        target_p99_s=args.target_p99_s,
        target_queue_depth=args.target_queue_depth,
        slo_class=args.slo_class,
        poll_interval_s=args.poll_interval_s,
        scale_out_cooldown_s=args.scale_out_cooldown_s,
        scale_in_cooldown_s=args.scale_in_cooldown_s,
    )
    scaler = autoscaler_lib.Autoscaler(
        options, fetch_stats=router_client.metricz,
        spawn_fn=spawn, drain_fn=drain,
        on_decision=lambda d: d['action'] not in ('hold',) and print(
            json.dumps({'event': 'autoscale', **d}), flush=True))
    stop = _StopFlag()
    stop.install()
    print(json.dumps({'event': 'ready', 'router': args.router,
                      'tier': args.tier,
                      'min': args.min_replicas,
                      'max': args.max_replicas}), flush=True)
    try:
      scaler.run(stop_event=stop.event)
    finally:
      stop.restore()
      scaler.shutdown(drain_managed=not args.leave_managed)
      if not args.leave_managed:
        for proc in all_procs:
          try:
            proc.wait(timeout=60)
          except subprocess.TimeoutExpired:
            proc.kill()
    stats = scaler.stats()
    print(json.dumps({'event': 'drained', **stats}, default=str),
          flush=True)
    return 0

  if args.command == 'featurize-worker':
    import json

    from deepconsensus_tpu.fleet import featurize_worker as worker_lib
    from deepconsensus_tpu.models import config as config_lib

    params = config_lib.get_config(args.config)
    config_lib.finalize_params(params, is_training=False)
    buckets = config_lib.normalize_window_buckets(
        args.window_buckets
        or getattr(params, 'window_buckets', None),
        params.max_length)
    options = worker_lib.FeaturizeWorkerOptions(
        max_passes=params.max_passes,
        max_length=params.max_length,
        use_ccs_bq=params.use_ccs_bq,
        window_buckets=tuple(buckets or ()),
        ins_trim=args.ins_trim,
        use_ccs_smart_windows=args.use_ccs_smart_windows,
        work_dir=args.work_dir,
        compact=not args.no_compact,
        max_body_bytes=args.max_body_mb << 20,
        io_timeout_s=args.io_timeout_s,
    )
    stats = worker_lib.worker_main(
        options, host=args.host, port=args.port,
        ready_fn=lambda info: print(json.dumps(info), flush=True))
    print(json.dumps({'event': 'drained', **stats}, default=str),
          flush=True)
    return 0 if stats.get('drained') else 1

  if args.command == 'run':
    from deepconsensus_tpu.calibration import lib as calibration_lib
    from deepconsensus_tpu.inference import runner as runner_lib
    from deepconsensus_tpu.models import config as config_lib

    dc_cal = args.dc_calibration
    if dc_cal is None:
      params = config_lib.read_params_from_json(args.checkpoint)
      dc_cal = params.get('dc_calibration', 'skip') or 'skip'
    options = runner_lib.InferenceOptions(
        batch_size=args.batch_size,
        batch_zmws=args.batch_zmws,
        min_length=args.min_length,
        min_quality=args.min_quality,
        skip_windows_above=args.skip_windows_above,
        ins_trim=args.ins_trim,
        use_ccs_smart_windows=args.use_ccs_smart_windows,
        max_base_quality=args.max_base_quality,
        limit=args.limit,
        cpus=args.cpus,
        end_after_stage=args.end_after_stage,
        shard=args.shard,
        on_zmw_error=args.on_zmw_error,
        batch_timeout=args.batch_timeout,
        batch_retries=args.batch_retries,
        resume=args.resume,
        dispatch_depth=args.dispatch_depth,
        emit_queue_depth=args.emit_queue_depth,
        on_device_error=args.on_device_error,
        dispatch_timeout=args.dispatch_timeout,
        inference_dtype=args.inference_dtype,
        quantize_matmuls=args.quantize_matmuls,
        device_epilogue=args.device_epilogue,
        window_buckets=args.window_buckets,
        use_ragged_kernel=args.use_ragged_kernel,
        pack_across_batches=not args.no_cross_batch_packing,
        max_record_bytes=args.max_record_bytes,
        dc_calibration_values=calibration_lib.parse_calibration_string(
            dc_cal
        ),
        ccs_calibration_values=calibration_lib.parse_calibration_string(
            args.ccs_calibration
        ),
    )
    mesh = None
    if args.dp or args.tp > 1:
      import jax

      from deepconsensus_tpu.parallel import mesh as mesh_lib

      dp = args.dp or 1
      mesh = mesh_lib.make_mesh(
          dp=dp, tp=args.tp, devices=jax.devices()[:dp * args.tp]
      )
    from deepconsensus_tpu import obs as obs_lib

    # SIGUSR2 -> short on-demand jax.profiler capture next to the
    # output (the batch counterpart of serve's /debugz/profile).
    obs_lib.profiler.install_sigusr2(args.output + '.profile')
    counters = runner_lib.run_inference(
        subreads_to_ccs=args.subreads_to_ccs,
        ccs_bam=args.ccs_bam,
        checkpoint=args.checkpoint,
        output=args.output,
        options=options,
        mesh=mesh,
    )
    if args.end_after_stage != 'full':
      # Debug-truncated runs never stitch reads; completing the
      # requested stages is the success criterion.
      return 0
    # ccs-fallback emissions count as yield: a run whose every read
    # degraded to the draft CCS still produced usable output (exit 0),
    # while the dead-letter sidecar carries the forensic detail.
    if counters.get('success', 0) > 0:
      return 0
    if counters.get('n_fallback_emitted', 0) > 0:
      return 0
    return 1

  if args.command == 'train':
    from deepconsensus_tpu.models import config as config_lib
    from deepconsensus_tpu.models import train as train_lib
    from deepconsensus_tpu.parallel import mesh as mesh_lib

    params = config_lib.get_config(args.config)
    _apply_overrides(params, args.overrides)
    config_lib.finalize_params(params)
    with params.unlocked():
      if args.batch_size:
        params.batch_size = args.batch_size
      if args.on_shard_error:
        params.on_shard_error = args.on_shard_error
      if args.window_buckets:
        params.window_buckets = args.window_buckets
      params.on_device_error = args.on_device_error
      params.on_host_error = args.on_host_error
      params.elastic_barrier_timeout = args.elastic_barrier_timeout
      params.tp = args.tp  # local_mesh size in elastic mode
    elastic_config = None
    if args.elastic:
      # The pod owns cross-host transport (bounded file barriers under
      # <out_dir>/.pod); jax.distributed must NOT be initialized or its
      # unbounded collectives would race the pod's membership protocol.
      elastic_config = {
          'host_id': args.process_id or 0,
          'n_hosts': args.num_processes or 1,
          'barrier_timeout': args.elastic_barrier_timeout,
          'on_host_error': args.on_host_error,
          'readmit': args.elastic_readmit,
      }
    elif (args.coordinator_address or args.num_processes
          or args.process_id is not None):
      # Initialize before the mesh is built so it spans all hosts
      # (run_training's own distributed_config hook is for programmatic
      # callers; the CLI must init before make_mesh below).
      from deepconsensus_tpu.parallel import distributed

      distributed.initialize(
          coordinator_address=args.coordinator_address,
          num_processes=args.num_processes,
          process_id=args.process_id,
      )
    if elastic_config is not None:
      # Each elastic host runs a LOCAL mesh over its own devices;
      # run_training builds it (mesh_lib.local_mesh) so state
      # re-placement after a rebuild stays host-local.
      mesh = None
    elif args.dp:
      import jax

      mesh = mesh_lib.make_mesh(
          dp=args.dp, tp=args.tp,
          devices=jax.devices()[:args.dp * args.tp])
    else:
      mesh = mesh_lib.make_mesh(tp=args.tp)
    train_lib.run_training_with_retry(
        params=params,
        out_dir=args.out_dir,
        train_patterns=args.train_path,
        eval_patterns=args.eval_path,
        num_epochs=args.num_epochs,
        mesh=mesh,
        warm_start=args.checkpoint,
        elastic_config=elastic_config,
    )
    return 0

  if args.command == 'evaluate':
    from deepconsensus_tpu.models import config as config_lib
    from deepconsensus_tpu.models import evaluate as evaluate_lib

    params = config_lib.read_params_from_json(args.checkpoint)
    config_lib.finalize_params(params, is_training=False)
    with params.unlocked():
      if args.batch_size:
        params.batch_size = args.batch_size
    metrics = evaluate_lib.run_evaluation(
        params=params,
        checkpoint_path=args.checkpoint,
        eval_patterns=args.eval_path,
        out_dir=args.out_dir,
        limit=args.limit,
    )
    print(' '.join(f'{k}={v:.5f}' for k, v in sorted(metrics.items())))
    return 0

  if args.command == 'port':
    from deepconsensus_tpu.models import port_tf_checkpoint as port_lib

    path = port_lib.port_to_orbax(
        args.tf_checkpoint, args.params, args.out_dir
    )
    print(f'ported: {path}')
    return 0

  if args.command == 'export':
    from deepconsensus_tpu.models import config as config_lib
    from deepconsensus_tpu.models import export as export_lib

    dc_cal = args.dc_calibration
    if dc_cal is None:
      params = config_lib.read_params_from_json(args.checkpoint)
      dc_cal = params.get('dc_calibration', 'skip') or 'skip'
    artifact = export_lib.export_model(
        checkpoint_path=args.checkpoint,
        out_dir=args.output,
        batch_size=args.batch_size,
        strict_polymorphic=args.strict_polymorphic,
        inference_dtype=args.inference_dtype,
        quantize_matmuls=args.quantize_matmuls,
        device_epilogue=args.device_epilogue,
        max_base_quality=args.max_base_quality,
        dc_calibration=dc_cal,
    )
    print(f'exported: {artifact}')
    return 0

  if args.command == 'distill':
    from deepconsensus_tpu.models.checkpoints import load_params
    from deepconsensus_tpu.models import config as config_lib
    from deepconsensus_tpu.models import distill as distill_lib

    teacher_params = config_lib.read_params_from_json(
        args.teacher_checkpoint
    )
    config_lib.finalize_params(teacher_params)
    teacher_weights = load_params(args.teacher_checkpoint)
    student_params = config_lib.get_config(args.config)
    _apply_overrides(student_params, args.overrides)
    config_lib.finalize_params(student_params)
    with student_params.unlocked():
      if args.batch_size:
        student_params.batch_size = args.batch_size
      if args.window_buckets:
        student_params.window_buckets = args.window_buckets
    distill_lib.run_distillation(
        params=student_params,
        teacher_params_cfg=teacher_params,
        teacher_variables={'params': teacher_weights},
        out_dir=args.out_dir,
        train_patterns=args.train_path,
        eval_patterns=args.eval_path,
        num_epochs=args.num_epochs,
    )
    return 0

  if args.command == 'flywheel':
    import json

    from deepconsensus_tpu import faults as faults_lib
    from deepconsensus_tpu.models import flywheel as flywheel_lib
    from deepconsensus_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.make_mesh(tp=args.tp) if args.tp > 1 else None
    kwargs = {}
    if args.int8_gate is not None:
      kwargs['int8_gate_threshold'] = args.int8_gate
    if args.bf16_gate is not None:
      kwargs['bf16_gate_threshold'] = args.bf16_gate
    elastic_config = None
    if args.elastic:
      elastic_config = {
          'host_id': args.process_id or 0,
          'n_hosts': args.num_processes or 1,
          'barrier_timeout': args.elastic_barrier_timeout,
          'on_host_error': args.on_host_error,
          'readmit': args.elastic_readmit,
      }
    try:
      manifest = flywheel_lib.run_flywheel(
          out_dir=args.out_dir,
          train_patterns=args.train_path,
          eval_patterns=args.eval_path,
          teacher_config=args.config,
          student_config=args.student_config,
          teacher_checkpoint=args.teacher_checkpoint,
          teacher_overrides=args.overrides,
          student_overrides=args.student_overrides,
          num_epochs=args.num_epochs,
          batch_size=args.batch_size,
          export_batch_size=args.export_batch_size,
          inference_dtype=args.inference_dtype,
          quantize_matmuls=args.quantize_matmuls,
          mesh=mesh,
          resume=args.resume,
          elastic_config=elastic_config,
          window_buckets=args.window_buckets,
          baseline_checkpoint=args.baseline_checkpoint,
          **kwargs,
      )
    except faults_lib.FlywheelGateError as e:
      # The partial manifest (with the failing gate recorded) is
      # already on disk; exit 3 distinguishes a gate veto from the
      # operator-error exit 2. (FlywheelResumeError is a ValueError:
      # main() maps it to the operator-error exit 2.)
      print(f'dctpu: {e}', file=sys.stderr)
      return 3
    if manifest.get('interrupted'):
      # Preemption mid-cycle is a clean exit, not a failure: the
      # journal records the stage to re-enter and --resume on the same
      # out_dir picks the cycle back up.
      print(json.dumps({
          'interrupted': manifest['interrupted'],
          'journal': f'{args.out_dir}/{flywheel_lib.JOURNAL_NAME}',
          'resume': 'rerun with --resume',
      }, indent=2))
      return 0
    print(json.dumps({
        'artifact': manifest['stages']['export']['artifact'],
        'manifest': f'{args.out_dir}/{flywheel_lib.MANIFEST_NAME}',
        'gates': [{k: g[k] for k in ('name', 'measured', 'threshold',
                                     'passed')}
                  for g in manifest['gates']],
    }, indent=2))
    return 0

  if args.command == 'calibrate':
    from deepconsensus_tpu.calibration.measure import (
        calculate_quality_calibration,
    )

    calculate_quality_calibration(
        bam=args.bam,
        ref=args.ref,
        output=args.output,
        region=args.region,
        cpus=args.cpus,
    )
    return 0

  if args.command == 'yield_metrics':
    from deepconsensus_tpu.calibration.yield_metrics import (
        calculate_yield_metrics,
    )

    calculate_yield_metrics(
        bam=args.bam,
        ref=args.ref,
        output=args.output,
        identity_bar=args.identity_bar,
    )
    return 0

  if args.command == 'filter_reads':
    from deepconsensus_tpu.calibration.filter_reads import (
        filter_bam_or_fastq_by_quality,
    )

    filter_bam_or_fastq_by_quality(
        input_path=args.input,
        output_path=args.output,
        min_quality=args.quality,
    )
    return 0

  return 2


if __name__ == '__main__':
  sys.exit(main())
