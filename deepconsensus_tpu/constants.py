"""Shared constants for DeepConsensus-TPU.

Mirrors the domain constants of the reference implementation
(reference: deepconsensus/utils/dc_constants.py:38-131) without depending
on pysam or tensorflow: cigar op codes are the BAM-spec integer codes.
"""
from __future__ import annotations

import enum

import numpy as np

__version__ = '0.1.0'

# Vocabulary. Gap must be index 0: the model's class 0 is "no base here"
# and zero-masked embeddings rely on it.
GAP = ' '
ALLOWED_BASES = 'ATCG'
SEQ_VOCAB = GAP + ALLOWED_BASES
SEQ_VOCAB_SIZE = len(SEQ_VOCAB)
GAP_INT = SEQ_VOCAB.index(GAP)

# Byte lookup table: ASCII code -> vocab index (gap for anything unknown).
_VOCAB_LUT = np.zeros(256, dtype=np.uint8)
for _i, _c in enumerate(SEQ_VOCAB):
  _VOCAB_LUT[ord(_c)] = _i
VOCAB_LUT = _VOCAB_LUT

# Reverse lookup: vocab index -> ASCII byte.
VOCAB_BYTES = np.frombuffer(SEQ_VOCAB.encode('ascii'), dtype=np.uint8).copy()


# BAM-spec cigar operation codes (SAMv1 spec section 4.2; same ints pysam
# exposes as CMATCH..CBACK in the reference).
class Cigar(enum.IntEnum):
  MATCH = 0       # M
  INS = 1         # I
  DEL = 2         # D
  REF_SKIP = 3    # N
  SOFT_CLIP = 4   # S
  HARD_CLIP = 5   # H
  PAD = 6         # P
  EQUAL = 7       # =
  DIFF = 8        # X
  BACK = 9        # B


CIGAR_CHARS = 'MIDNSHP=XB'
CIGAR_OPS = {c: Cigar(i) for i, c in enumerate(CIGAR_CHARS)}

# Ops that consume bases of the read ("query-advancing"), used when mapping
# label truth coordinates (reference: dc_constants.py:47-49).
READ_ADVANCING_OPS = (Cigar.MATCH, Cigar.INS, Cigar.EQUAL, Cigar.DIFF)
READ_ADVANCING_OPS_ARR = np.array([int(x) for x in READ_ADVANCING_OPS])


class Issue(int, enum.Enum):
  TRUTH_ALIGNMENT_NOT_FOUND = 1
  SUPP_TRUTH_ALIGNMENT = 2


class Strand(int, enum.Enum):
  UNKNOWN = 0
  FORWARD = 1
  REVERSE = 2


NP_DATA_TYPE = np.float32

# Train/eval/test region splits per genome
# (reference: dc_constants.py:87-111).
ECOLI_REGIONS = {
    'TRAIN': (464253, 4178270),
    'EVAL': (0, 464252),
    'TEST': (4178271, 4642522),
}
TRAIN_REGIONS = {
    'HUMAN': (
        [str(i) for i in range(1, 19)]
        + ['chr%d' % i for i in range(1, 19)]
        + ['X', 'Y', 'chrX', 'chrY']
    ),
    'MAIZE': [str(i) for i in range(1, 9)] + ['chr%d' % i for i in range(1, 9)],
}
EVAL_REGIONS = {
    'HUMAN': ['21', '22', 'chr21', 'chr22'],
    'MAIZE': ['9', 'chr9'],
}
TEST_REGIONS = {
    'HUMAN': ['19', '20', 'chr19', 'chr20'],
    'MAIZE': ['10', 'chr10'],
}

# Feature keys of a batched example fed to the model
# (reference: dc_constants.py:114-125).
DC_FEATURES = [
    'rows',
    'label',
    'num_passes',
    'window_pos',
    'name',
    'ccs_base_quality_scores',
    'ec',
    'np_num_passes',
    'rq',
    'rg',
]

EMPTY_QUAL = 0

MAIN_EVAL_METRIC_NAME = 'eval/per_example_accuracy'
