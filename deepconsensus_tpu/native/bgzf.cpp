// Parallel BGZF decompression for BAM/TFRecord-style gzip-block files.
//
// The reference stack leans on pysam/htslib (C) for BAM I/O; this is the
// framework's native equivalent: BGZF files are sequences of independent
// gzip members, so blocks decompress in parallel across a thread pool.
// Exposed through a minimal C ABI for ctypes (no pybind11 dependency).
//
// Build: g++ -O3 -march=native -shared -fPIC bgzf.cpp -o libdcnative.so -lz -lpthread

#include <climits>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <atomic>
#include <thread>
#include <vector>

#include <zlib.h>

namespace {

struct Block {
  size_t in_offset;   // offset of compressed payload (past header)
  size_t in_size;     // compressed payload size (without header/footer)
  size_t out_offset;  // offset in the output buffer
  size_t out_size;    // isize from the gzip footer
  uint32_t crc;       // crc32 from the gzip footer
};

// Parses BGZF block boundaries. Returns false on malformed input.
bool scan_blocks(const uint8_t* data, size_t len, std::vector<Block>* blocks,
                 size_t* total_out) {
  size_t pos = 0;
  size_t out = 0;
  while (pos + 18 <= len) {
    if (data[pos] != 0x1f || data[pos + 1] != 0x8b) return false;
    // BGZF fixes CM=8 (deflate) and FLG=4 (FEXTRA only).  Any other
    // FLG bits change the gzip member layout, which the pure-Python
    // fallback would parse differently — reject rather than diverge.
    if (data[pos + 2] != 8) return false;
    if (data[pos + 3] != 4) return false;
    const uint16_t xlen = data[pos + 10] | (data[pos + 11] << 8);
    size_t extra = pos + 12;
    size_t extra_end = extra + xlen;
    if (extra_end > len) return false;
    int bsize = -1;
    while (extra + 4 <= extra_end) {
      const uint8_t si1 = data[extra], si2 = data[extra + 1];
      const uint16_t slen = data[extra + 2] | (data[extra + 3] << 8);
      if (si1 == 'B' && si2 == 'C' && slen == 2 &&
          extra + 6 <= extra_end) {
        bsize = (data[extra + 4] | (data[extra + 5] << 8)) + 1;
      }
      extra += 4 + slen;
    }
    if (bsize <= 0) return false;
    const size_t payload = pos + 12 + xlen;
    const size_t block_end = pos + bsize;
    if (block_end > len || block_end < payload + 8) return false;
    const uint8_t* footer = data + block_end - 8;
    const uint32_t crc = footer[0] | (footer[1] << 8) | (footer[2] << 16) |
                         ((uint32_t)footer[3] << 24);
    const uint32_t isize = footer[4] | (footer[5] << 8) | (footer[6] << 16) |
                           ((uint32_t)footer[7] << 24);
    blocks->push_back(
        Block{payload, block_end - 8 - payload, out, isize, crc});
    out += isize;
    pos = block_end;
  }
  *total_out = out;
  return pos == len;
}

bool inflate_block(const uint8_t* src, size_t src_len, uint8_t* dst,
                   size_t dst_len, uint32_t expected_crc) {
  z_stream zs;
  memset(&zs, 0, sizeof(zs));
  if (inflateInit2(&zs, -15) != Z_OK) return false;
  zs.next_in = const_cast<uint8_t*>(src);
  zs.avail_in = (uInt)src_len;
  zs.next_out = dst;
  zs.avail_out = (uInt)dst_len;
  const int ret = inflate(&zs, Z_FINISH);
  inflateEnd(&zs);
  if (ret != Z_STREAM_END || zs.total_out != dst_len) return false;
  // Raw-deflate mode (-15) skips zlib's own gzip footer handling, so
  // verify the member CRC here — Python's gzip module does, and the
  // native path must never accept bytes the fallback would reject.
  return crc32(crc32(0L, Z_NULL, 0), dst, (uInt)dst_len) == expected_crc;
}

}  // namespace

extern "C" {

// Decompresses a whole BGZF buffer with n_threads workers.
// Returns 0 on success; *out is malloc'd (caller frees via dc_free).
// max_out caps the decompressed size (0 = unlimited): the block scan
// knows the exact total before any allocation, so an oversized buffer
// is rejected (rc 6) before a byte is inflated — callers fall back to
// the streaming Python path, which holds only small buffers.
int dc_bgzf_decompress(const uint8_t* data, size_t len, int n_threads,
                       uint8_t** out, size_t* out_len, size_t max_out) {
  std::vector<Block> blocks;
  size_t total = 0;
  if (!scan_blocks(data, len, &blocks, &total)) return 1;
  if (max_out && total > max_out) return 6;
  uint8_t* buffer = (uint8_t*)malloc(total ? total : 1);
  if (!buffer) return 2;

  std::atomic<size_t> next(0);
  std::atomic<bool> failed(false);
  auto worker = [&]() {
    for (;;) {
      const size_t i = next.fetch_add(1);
      if (i >= blocks.size() || failed.load(std::memory_order_relaxed)) break;
      const Block& b = blocks[i];
      // Zero-output blocks (the BGZF EOF marker) still carry a deflate
      // payload and CRC footer; inflate them too so footer corruption
      // is rejected exactly like the pure-Python gzip path does.
      if (!inflate_block(data + b.in_offset, b.in_size,
                         buffer + b.out_offset, b.out_size, b.crc)) {
        failed.store(true, std::memory_order_relaxed);
      }
    }
  };
  if (n_threads < 1) n_threads = 1;
  std::vector<std::thread> pool;
  for (int t = 1; t < n_threads; ++t) pool.emplace_back(worker);
  worker();
  for (auto& th : pool) th.join();
  if (failed.load()) {
    free(buffer);
    return 3;
  }
  *out = buffer;
  *out_len = total;
  return 0;
}

// File-path convenience wrapper. max_out as in dc_bgzf_decompress
// (0 = unlimited; oversized output rejects with rc 6 before inflating).
int dc_bgzf_decompress_file(const char* path, int n_threads, uint8_t** out,
                            size_t* out_len, size_t max_out) {
  FILE* f = fopen(path, "rb");
  if (!f) return 10;
  fseek(f, 0, SEEK_END);
  const long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (size < 0) {
    fclose(f);
    return 11;
  }
  uint8_t* data = (uint8_t*)malloc(size ? size : 1);
  if (!data) {
    fclose(f);
    return 12;
  }
  const size_t got = fread(data, 1, size, f);
  fclose(f);
  if (got != (size_t)size) {
    free(data);
    return 13;
  }
  const int rc =
      dc_bgzf_decompress(data, size, n_threads, out, out_len, max_out);
  free(data);
  return rc;
}

void dc_free(uint8_t* ptr) { free(ptr); }

// Whole-buffer inflate for arbitrary (possibly multi-member) gzip —
// the fallback when a shard is NOT BGZF (plain gzip from the
// pure-Python writer or the reference's TF writer has one member and
// no BC field, so the parallel block path can't apply). Serial, but
// the inflate + framing cost still moves from Python to C.
// max_out (0 = unlimited) aborts with rc 6 as soon as the output
// exceeds the cap — the only sound bound for arbitrary gzip, whose
// footer ISIZE wraps mod 2^32 and covers only the final member.
int dc_gzip_decompress(const uint8_t* data, size_t len, uint8_t** out,
                       size_t* out_len, size_t max_out) {
  // avail_in is a uInt; a >=4 GiB input would silently truncate to
  // len mod 2^32 (possibly decoding a clean prefix and returning 0).
  if (len > UINT_MAX) return 5;
  size_t cap = len * 4 + (1 << 16);
  // Clamp to max_out + 1: one byte past the cap is all the over-cap
  // check below needs, and it keeps the allocation bounded by the
  // caller's budget instead of transiently ~2x over it.
  if (max_out && cap > max_out + 1) cap = max_out + 1;
  uint8_t* buffer = (uint8_t*)malloc(cap);
  if (!buffer) return 2;
  size_t total = 0;

  z_stream zs;
  memset(&zs, 0, sizeof(zs));
  // 15+16: gzip wrapper with max window.
  if (inflateInit2(&zs, 15 + 16) != Z_OK) {
    free(buffer);
    return 4;
  }
  zs.next_in = const_cast<uint8_t*>(data);
  zs.avail_in = (uInt)len;
  for (;;) {
    if (total == cap) {
      cap *= 2;
      if (max_out && cap > max_out + 1) cap = max_out + 1;
      uint8_t* grown = (uint8_t*)realloc(buffer, cap);
      if (!grown) {
        inflateEnd(&zs);
        free(buffer);
        return 2;
      }
      buffer = grown;
    }
    zs.next_out = buffer + total;
    zs.avail_out = (uInt)(cap - total);
    const int ret = inflate(&zs, Z_NO_FLUSH);
    total = cap - zs.avail_out;
    // Cap check must follow EVERY inflate call: the Z_STREAM_END exit
    // below must not return success for an over-cap output that fit
    // the adaptive buffer in one call.
    if (max_out && total > max_out) {
      inflateEnd(&zs);
      free(buffer);
      return 6;
    }
    if (ret == Z_STREAM_END) {
      if (zs.avail_in == 0) break;
      // Concatenated member: restart on the remaining input.
      if (inflateReset2(&zs, 15 + 16) != Z_OK) {
        inflateEnd(&zs);
        free(buffer);
        return 4;
      }
      continue;
    }
    if (ret != Z_OK) {
      inflateEnd(&zs);
      free(buffer);
      return 3;
    }
  }
  inflateEnd(&zs);
  *out = buffer;
  *out_len = total;
  return 0;
}

uint32_t dc_crc32c(const uint8_t* data, size_t len, uint32_t seed);

// TFRecord masked crc (crc32c rotated + constant), as used by the
// length and payload checksums.
static uint32_t dc_masked_crc(const uint8_t* data, size_t len) {
  const uint32_t crc = dc_crc32c(data, len, 0);
  return (uint32_t)(((crc >> 15) | (crc << 17)) + 0xA282EAD8u);
}

// Parses TFRecord framing (u64 length, u32 len-crc, payload, u32
// payload-crc) over a decompressed buffer. Emits (offset, length)
// pairs of the PAYLOADS into a malloc'd u64 array (caller frees via
// dc_free). The length crc IS validated before the length is trusted
// (matching the hardened Python reader); payload crcs are not
// (matching the Python reader's check_crc=False default). Framing
// errors return nonzero.
int dc_tfrecord_index(const uint8_t* data, size_t len, uint64_t** pairs,
                      size_t* n_records) {
  size_t cap = 1024;
  uint64_t* out = (uint64_t*)malloc(cap * 2 * sizeof(uint64_t));
  if (!out) return 2;
  size_t n = 0;
  size_t pos = 0;
  while (pos < len) {
    if (pos + 12 > len) {
      free(out);
      return 1;  // truncated header
    }
    uint64_t rec_len;
    memcpy(&rec_len, data + pos, 8);  // little-endian hosts only (x86/ARM)
    uint32_t len_crc;
    memcpy(&len_crc, data + pos + 8, 4);
    if (len_crc != dc_masked_crc(data + pos, 8)) {
      free(out);
      return 1;  // corrupt length header
    }
    const size_t payload = pos + 12;
    if (rec_len > len || payload + rec_len + 4 > len) {
      free(out);
      return 1;  // truncated payload
    }
    if (n == cap) {
      cap *= 2;
      uint64_t* grown = (uint64_t*)realloc(out, cap * 2 * sizeof(uint64_t));
      if (!grown) {
        free(out);
        return 2;
      }
      out = grown;
    }
    out[2 * n] = payload;
    out[2 * n + 1] = rec_len;
    ++n;
    pos = payload + rec_len + 4;
  }
  *pairs = out;
  *n_records = n;
  return 0;
}

// crc32c (Castagnoli), software table implementation, for TFRecord
// framing without per-byte Python cost.
// Eagerly initialized: ctypes releases the GIL during calls, so a
// lazily built table would race between Python threads.
static uint32_t kCrcTable[256];

static bool crc_init() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = i;
    for (int j = 0; j < 8; ++j) {
      crc = (crc & 1) ? (crc >> 1) ^ 0x82F63B78u : crc >> 1;
    }
    kCrcTable[i] = crc;
  }
  return true;
}
static const bool kCrcInit = crc_init();

uint32_t dc_crc32c(const uint8_t* data, size_t len, uint32_t seed) {
  (void)kCrcInit;
  uint32_t crc = seed ^ 0xFFFFFFFFu;
  for (size_t i = 0; i < len; ++i) {
    crc = kCrcTable[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  }
  return crc ^ 0xFFFFFFFFu;
}

}  // extern "C"
