"""Native (C++) accelerators with build-on-first-use and ctypes bindings.

The reference's native surface is htslib via pysam; here the equivalent
is a small C++ library (bgzf.cpp) compiled on demand with the system
toolchain. Everything degrades gracefully to the pure-Python paths when
a compiler is unavailable.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

log = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, 'bgzf.cpp')
_LIB = os.path.join(_DIR, 'libdcnative.so')

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
  cmd = [
      'g++', '-O3', '-shared', '-fPIC', '-std=c++17', _SRC,
      '-o', _LIB, '-lz', '-lpthread',
  ]
  try:
    subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    return True
  except (subprocess.CalledProcessError, FileNotFoundError,
          subprocess.TimeoutExpired) as e:
    log.warning('native build failed (%s); using pure-Python fallback', e)
    return False


def get_lib() -> Optional[ctypes.CDLL]:
  """Loads (building if needed) the native library, or None.

  DC_TPU_NO_NATIVE=1 disables it (emergency off-switch + the
  native-vs-Python A/B knob for bench_loader.py; checked per call so
  spawn-based worker processes honor it too)."""
  if os.environ.get('DC_TPU_NO_NATIVE') == '1':
    return None
  global _lib, _build_failed
  with _lock:
    if _lib is not None:
      return _lib
    if _build_failed:
      return None
    if not os.path.exists(_LIB) or (
        os.path.exists(_SRC)
        and os.path.getmtime(_SRC) > os.path.getmtime(_LIB)
    ):
      if not _build():
        _build_failed = True
        return None
    try:
      lib = ctypes.CDLL(_LIB)
    except OSError as e:
      log.warning('native load failed (%s)', e)
      _build_failed = True
      return None
    lib.dc_bgzf_decompress_file.restype = ctypes.c_int
    lib.dc_bgzf_decompress_file.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_size_t),
        ctypes.c_size_t,
    ]
    lib.dc_free.argtypes = [ctypes.c_void_p]
    lib.dc_crc32c.restype = ctypes.c_uint32
    lib.dc_crc32c.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32
    ]
    lib.dc_bgzf_decompress.restype = ctypes.c_int
    lib.dc_bgzf_decompress.argtypes = [
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.c_int,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_size_t),
        ctypes.c_size_t,
    ]
    lib.dc_gzip_decompress.restype = ctypes.c_int
    lib.dc_gzip_decompress.argtypes = [
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_size_t),
        ctypes.c_size_t,
    ]
    lib.dc_tfrecord_index.restype = ctypes.c_int
    lib.dc_tfrecord_index.argtypes = [
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),
        ctypes.POINTER(ctypes.c_size_t),
    ]
    _lib = lib
    return _lib


def bgzf_decompress_file(path: str, n_threads: int = 4,
                         max_out: int = 0) -> Optional[bytes]:
  """Decompresses a whole BGZF file in parallel; None -> use fallback.

  max_out (0 = unlimited) bounds the decompressed size: the BGZF block
  scan knows the total before inflating anything, so an oversized (or
  length-field-inflated) file returns None without allocating."""
  lib = get_lib()
  if lib is None:
    return None
  out = ctypes.POINTER(ctypes.c_uint8)()
  out_len = ctypes.c_size_t()
  rc = lib.dc_bgzf_decompress_file(
      path.encode(), n_threads, ctypes.byref(out), ctypes.byref(out_len),
      max_out
  )
  if rc != 0:
    return None
  try:
    return ctypes.string_at(out, out_len.value)
  finally:
    lib.dc_free(out)


def crc32c(data: bytes, seed: int = 0) -> Optional[int]:
  lib = get_lib()
  if lib is None:
    return None
  return int(lib.dc_crc32c(data, len(data), seed))


def _looks_bgzf(raw: bytes) -> bool:
  return (len(raw) > 18 and raw[:2] == b'\x1f\x8b'
          and bool(raw[3] & 4))


def read_tfrecord_records(path: str, n_threads: int = 4,
                          compressed: Optional[bool] = None,
                          max_out: int = 0):
  """Decodes a whole TFRecord shard natively: gzip/BGZF inflate (BGZF
  blocks in parallel) + record framing in C, one Python slice per
  record. Returns a list of record payload bytes, or None -> caller
  must use the streaming Python fallback. Whole-shard decode trades
  memory (the decompressed shard) for the per-record Python
  read/struct overhead that dominates the measured decode path.

  max_out (0 = unlimited) bounds the decompressed size in C: BGZF
  rejects from the block scan before inflating anything; arbitrary
  gzip aborts as soon as output exceeds the cap. Either way the caller
  gets None and must stream."""
  lib = get_lib()
  if lib is None:
    return None
  try:
    with open(path, 'rb') as f:
      raw = f.read()
  except OSError:
    return None
  if compressed is None:
    compressed = path.endswith('.gz')
  if not compressed:
    return _index_and_slice(lib, raw, len(raw))
  out = ctypes.POINTER(ctypes.c_uint8)()
  out_len = ctypes.c_size_t()
  rc = 1
  if _looks_bgzf(raw):
    rc = lib.dc_bgzf_decompress(raw, len(raw), n_threads,
                                ctypes.byref(out), ctypes.byref(out_len),
                                max_out)
    if rc == 6:  # over max_out — retrying via gzip would just re-reject
      return None
  if rc != 0:
    rc = lib.dc_gzip_decompress(raw, len(raw),
                                ctypes.byref(out), ctypes.byref(out_len),
                                max_out)
  if rc != 0:
    return None
  del raw  # compressed copy no longer needed; keep the peak low
  try:
    # Index and slice records straight off the C buffer: copying it
    # wholesale into a Python bytes first would add a full extra
    # decompressed-shard copy to the peak (the records themselves are
    # the one unavoidable copy).
    return _index_and_slice(
        lib, ctypes.cast(out, ctypes.c_char_p), out_len.value,
        base=ctypes.addressof(out.contents))
  finally:
    lib.dc_free(out)


def _index_and_slice(lib, buf, buf_len: int, base: Optional[int] = None):
  """Runs dc_tfrecord_index over `buf` (bytes, or a C pointer with
  `base` set to its address) and returns the record payload slices."""
  pairs = ctypes.POINTER(ctypes.c_uint64)()
  n_records = ctypes.c_size_t()
  rc = lib.dc_tfrecord_index(buf, buf_len, ctypes.byref(pairs),
                             ctypes.byref(n_records))
  if rc != 0:
    return None
  try:
    n = n_records.value
    if base is not None:
      return [ctypes.string_at(base + pairs[2 * i], pairs[2 * i + 1])
              for i in range(n)]
    return [buf[pairs[2 * i]:pairs[2 * i] + pairs[2 * i + 1]]
            for i in range(n)]
  finally:
    lib.dc_free(pairs)
