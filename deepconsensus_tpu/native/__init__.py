"""Native (C++) accelerators with build-on-first-use and ctypes bindings.

The reference's native surface is htslib via pysam; here the equivalent
is a small C++ library (bgzf.cpp) compiled on demand with the system
toolchain. Everything degrades gracefully to the pure-Python paths when
a compiler is unavailable.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

log = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, 'bgzf.cpp')
_LIB = os.path.join(_DIR, 'libdcnative.so')

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
  cmd = [
      'g++', '-O3', '-shared', '-fPIC', '-std=c++17', _SRC,
      '-o', _LIB, '-lz', '-lpthread',
  ]
  try:
    subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    return True
  except (subprocess.CalledProcessError, FileNotFoundError,
          subprocess.TimeoutExpired) as e:
    log.warning('native build failed (%s); using pure-Python fallback', e)
    return False


def get_lib() -> Optional[ctypes.CDLL]:
  """Loads (building if needed) the native library, or None."""
  global _lib, _build_failed
  with _lock:
    if _lib is not None:
      return _lib
    if _build_failed:
      return None
    if not os.path.exists(_LIB) or (
        os.path.exists(_SRC)
        and os.path.getmtime(_SRC) > os.path.getmtime(_LIB)
    ):
      if not _build():
        _build_failed = True
        return None
    try:
      lib = ctypes.CDLL(_LIB)
    except OSError as e:
      log.warning('native load failed (%s)', e)
      _build_failed = True
      return None
    lib.dc_bgzf_decompress_file.restype = ctypes.c_int
    lib.dc_bgzf_decompress_file.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.dc_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    lib.dc_crc32c.restype = ctypes.c_uint32
    lib.dc_crc32c.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32
    ]
    _lib = lib
    return _lib


def bgzf_decompress_file(path: str, n_threads: int = 4) -> Optional[bytes]:
  """Decompresses a whole BGZF file in parallel; None -> use fallback."""
  lib = get_lib()
  if lib is None:
    return None
  out = ctypes.POINTER(ctypes.c_uint8)()
  out_len = ctypes.c_size_t()
  rc = lib.dc_bgzf_decompress_file(
      path.encode(), n_threads, ctypes.byref(out), ctypes.byref(out_len)
  )
  if rc != 0:
    return None
  try:
    return ctypes.string_at(out, out_len.value)
  finally:
    lib.dc_free(out)


def crc32c(data: bytes, seed: int = 0) -> Optional[int]:
  lib = get_lib()
  if lib is None:
    return None
  return int(lib.dc_crc32c(data, len(data), seed))
