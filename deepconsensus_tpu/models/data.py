"""Input pipeline: TFRecord parsing, row formatting, batching.

TF-free equivalent of the reference's tf.data pipeline (reference:
deepconsensus/models/data_providers.py:41-425): examples parse into
numpy, PW/IP/SN rows are clipped, and batches are produced by a
lightweight shuffling loader that feeds jax.device_put directly.
"""
from __future__ import annotations

import collections
import dataclasses
import logging
from typing import Dict, Iterator, List, Sequence, Union

import ml_collections
import numpy as np

from deepconsensus_tpu import constants
from deepconsensus_tpu.faults import CorruptInputError, WindowBucketError
from deepconsensus_tpu.io.example_proto import Example
from deepconsensus_tpu.models import config
from deepconsensus_tpu.io.tfrecord import read_tfrecords
from deepconsensus_tpu.preprocess.pileup import layout_from_shape, row_indices
from deepconsensus_tpu.utils import phred

log = logging.getLogger(__name__)


class OnShardError:
  """--on_shard_error policy values (StreamingDataset)."""

  FAIL = 'fail'
  SKIP = 'skip'

  CHOICES = (FAIL, SKIP)


def format_rows(
    subreads: np.ndarray,
    params: ml_collections.ConfigDict,
) -> np.ndarray:
  """Clips PW/IP/SN rows and crops passes to the model's max_passes
  (reference format_rows: data_providers.py:128-184)."""
  return format_rows_batch(subreads[None], params)[0]


def format_rows_batch(
    subreads: np.ndarray,
    params: ml_collections.ConfigDict,
    window_buckets: Sequence[int] = (),
    names: Sequence = (),
) -> np.ndarray:
  """format_rows over a whole window batch [N, H, L, 1] at once —
  one set of slice/clip/concat ops instead of N (the per-window calls
  were a measured host-side cost in the inference model stage).
  window_buckets overrides the allowed widths (callers whose buckets
  come from InferenceOptions rather than params). `names` (window ids,
  when the caller tracks them) only feeds the rejection message so an
  off-bucket window is attributable to its ZMW."""
  example_layout = layout_from_shape(subreads.shape[1:], params.use_ccs_bq)
  (base_r, pw_r, ip_r, strand_r, ccs_r, ccs_bq_r, sn_r) = row_indices(
      example_layout.max_passes, params.use_ccs_bq
  )
  keep = params.max_passes

  def rows_of(r, cap=None):
    block = subreads[:, r[0]:r[1]]
    return block[:, :cap] if cap else block

  features = [
      rows_of(base_r, keep),
      np.clip(rows_of(pw_r, keep), 0, params.PW_MAX),
      np.clip(rows_of(ip_r, keep), 0, params.IP_MAX),
      rows_of(strand_r, keep),
      rows_of(ccs_r),
  ]
  if params.use_ccs_bq:
    features.append(rows_of(ccs_bq_r))
  features.append(np.clip(rows_of(sn_r), 0, params.SN_MAX))
  rows = np.concatenate(features, axis=1)
  buckets = (tuple(window_buckets) if window_buckets
             else config.resolve_window_buckets(params))
  width = rows.shape[2]
  if width not in buckets:
    who = ''
    if len(names):
      shown = [str(n) for n in list(names)[:3]]
      who = f' (window id(s) {shown}{"..." if len(names) > 3 else ""})'
    raise WindowBucketError(
        f'window width {width} not in window buckets {buckets}{who}; '
        f'triage the window into a bucket (pad) or run with '
        f'--on_shard_error=skip to quarantine it (n_width_rejected)')
  expected = (len(subreads), params.total_rows, width, 1)
  assert rows.shape == expected, rows.shape
  return rows


def parse_example(
    raw: bytes,
    params: ml_collections.ConfigDict,
    inference: bool = False,
) -> Dict[str, np.ndarray]:
  """Parses one serialized example into formatted features
  (reference process_input: data_providers.py:249-297)."""
  ex = Example.parse(raw)
  shape = ex['subreads/shape']
  subreads = np.frombuffer(
      ex['subreads/encoded'][0], dtype=constants.NP_DATA_TYPE
  ).reshape(shape)
  out = {
      'rows': format_rows(subreads, params),
      'num_passes': np.asarray(
          ex['subreads/num_passes'][0], dtype=constants.NP_DATA_TYPE
      ),
      'window_pos': np.asarray(ex['window_pos'][0], dtype=np.int64),
      'name': ex['name'][0],
      'ccs_base_quality_scores': np.asarray(
          ex['ccs_base_quality_scores'], dtype=np.int64
      ),
  }
  if not inference:
    label = np.frombuffer(
        ex['label/encoded'][0], dtype=constants.NP_DATA_TYPE
    ).reshape(ex['label/shape'])
    if params.remove_label_gaps:
      label = phred.left_shift_seq(label)
    out['label'] = label
  return out


# The only proto fields the training batch path needs; everything else
# (notably the 100-varint ccs_base_quality_scores walk) is skipped.
_MINIMAL_FIELDS = frozenset({
    'subreads/encoded', 'subreads/shape', 'label/encoded', 'label/shape',
})


_MINIMAL_FIELDS_WITH_NAME = _MINIMAL_FIELDS | {'name'}


def parse_example_minimal(
    raw: bytes, inference: bool = False, with_name: bool = False
) -> Dict[str, np.ndarray]:
  """Training/eval fast path: decodes only the subreads tensor (raw,
  unformatted) and the label. Row formatting and label gap-shifting
  are deferred to the batch level (format_rows_batch /
  phred.left_shift), which is ~4x cheaper per example than the
  per-example path (measured on the bundled train shard).

  with_name additionally decodes the window id ('name'), so the NaN
  sentinel's dead letters can attribute a diverged batch to its
  windows (params.track_window_ids)."""
  fields = _MINIMAL_FIELDS_WITH_NAME if with_name else _MINIMAL_FIELDS
  ex = Example.parse(raw, fields=fields)
  out = {
      'subreads': np.frombuffer(
          ex['subreads/encoded'][0], dtype=constants.NP_DATA_TYPE
      ).reshape(ex['subreads/shape'])
  }
  if with_name and 'name' in ex:
    out['name'] = ex['name'][0]
  if not inference:
    out['label'] = np.frombuffer(
        ex['label/encoded'][0], dtype=constants.NP_DATA_TYPE
    ).reshape(ex['label/shape'])
  return out


def _shard_reader_main(paths, inference: bool, seed: int, out_queue,
                       chunk: int = 64, on_shard_error: str = 'fail',
                       with_name: bool = False,
                       worker_idx: int = -1) -> None:
  """StreamingDataset worker: reads its shard subset forever (gzip +
  framing + minimal parse all inside this process) and ships parsed
  chunks to the parent as ('chunk', (worker_idx, parses)) tuples — the
  index feeds the parent's per-worker decode counters. A shard that
  fails
  to decode under on_shard_error='skip' is reported as a
  ('shard_error', description) tuple and the worker moves on; under
  'fail' the worker exits nonzero and the parent's liveness check
  raises. Terminated by the parent; blocking put keeps it idle when
  the consumer falls behind."""
  from deepconsensus_tpu import faults as faults_lib
  from deepconsensus_tpu.io.tfrecord import TFRecordReader

  rng = np.random.default_rng(seed)
  pending: List[Dict[str, np.ndarray]] = []
  while True:
    # One shard at a time (native whole-shard decode: memory per worker
    # is bounded by its largest shard); the parent's reservoir buffer
    # plus this per-epoch permutation provide the mixing.
    produced = False
    for i in rng.permutation(len(paths)):
      path = paths[i]
      faults_lib.maybe_kill_shard_reader(path)
      try:
        for raw in TFRecordReader(path, native_decode=True):
          try:
            parsed = parse_example_minimal(raw, inference, with_name)
          except Exception as e:  # noqa: BLE001 - policy-gated
            if on_shard_error != OnShardError.SKIP:
              raise
            # Record-local payload corruption (see the serial path).
            out_queue.put(
                ('corrupt_record', f'{path}: {type(e).__name__}: {e}'))
            continue
          pending.append(parsed)
          produced = True
          if len(pending) >= chunk:
            out_queue.put(('chunk', (worker_idx, pending)))
            pending = []
      except Exception as e:  # noqa: BLE001 - policy-gated
        if on_shard_error != OnShardError.SKIP:
          raise
        # Records decoded before the fault are good parses; keep them.
        # The corrupt flag lets the parent count decode-layer
        # corruption (n_corrupt_records) separately from other shard
        # failures in the faults metrics split.
        out_queue.put(
            ('shard_error', (f'{path}: {type(e).__name__}: {e}',
                             isinstance(e, faults_lib.CorruptInputError)))
        )
    if not produced and on_shard_error == OnShardError.SKIP:
      # dclint: allow=typed-faults (aggregate stop after every
      # per-shard fault was already routed to the counters; tests pin
      # RuntimeError('every shard failed ...'))
      raise RuntimeError(
          f'every shard failed to decode under on_shard_error=skip: '
          f'{paths}'
      )


def _window_width(parsed: Dict[str, np.ndarray]) -> int:
  """Window width of one minimal parse ([H, L, 1] subreads)."""
  return int(parsed['subreads'].shape[1])


def _pad_minimal(
    parsed: Dict[str, np.ndarray], pad_to: int
) -> Dict[str, np.ndarray]:
  """Pads one minimal parse's window axis up to its bucket width.

  Zero is the canonical absent value for every row family (gap base,
  no kinetics, UNKNOWN strand) and for the label (gap, shifted away by
  left_shift / ignored by the alignment loss), so padding a width-w
  window to its bucket is semantically a no-op — the same pad the
  featurize stage applies when a smart window comes up short."""
  w = _window_width(parsed)
  if w == pad_to:
    return parsed
  out = dict(parsed)
  out['subreads'] = np.pad(
      parsed['subreads'], ((0, 0), (0, pad_to - w), (0, 0)))
  if 'label' in parsed:
    out['label'] = np.pad(parsed['label'], (0, pad_to - w))
  return out


def _batch_from_minimal(
    chosen: List[Dict[str, np.ndarray]],
    params: ml_collections.ConfigDict,
    inference: bool,
    pad_to: int = 0,
) -> Dict[str, np.ndarray]:
  """Stacks minimal parses into a formatted (rows, label) batch.
  pad_to > 0 pads every window up to that bucket width first (the
  bucketed-training triage path)."""
  if pad_to:
    chosen = [_pad_minimal(c, pad_to) for c in chosen]
  names = ([c['name'] for c in chosen] if 'name' in chosen[0] else [])
  batch = {
      'rows': format_rows_batch(
          np.stack([c['subreads'] for c in chosen]), params, names=names
      )
  }
  if names:
    batch['name'] = np.asarray(names, dtype=object)
  if not inference:
    label = np.stack([c['label'] for c in chosen])
    if params.remove_label_gaps:
      label = phred.left_shift(label)
    batch['label'] = label
  return batch


def process_feature_dict(
    features: Dict, params: ml_collections.ConfigDict
) -> Dict:
  """Formats an in-memory inference feature dict
  (reference: data_providers.py:187-223)."""
  return {
      'rows': format_rows(features['subreads'], params),
      'label': np.empty(0, dtype=constants.NP_DATA_TYPE),
      'num_passes': features['subreads/num_passes'],
      'window_pos': features['window_pos'],
      'name': features['name'],
      'ccs_base_quality_scores': features['ccs_base_quality_scores'],
      'ec': features['ec'],
      'np_num_passes': features['np_num_passes'],
      'rq': features['rq'],
      'rg': features['rg'],
  }


@dataclasses.dataclass
class DatasetIterator:
  """Shuffled, repeating, fixed-batch iterator over TFRecord shards.

  Eagerly loads the shard contents once (training corpora stream via
  multiple shards; the bundled test sets fit in memory), then yields
  (rows, label) batches. drop_remainder semantics match the reference
  (data_providers.py:361).
  """

  patterns: Union[str, Sequence[str]]
  params: ml_collections.ConfigDict
  batch_size: int
  inference: bool = False
  seed: int = 1
  shuffle: bool = True
  drop_remainder: bool = True
  limit: int = -1

  def __post_init__(self):
    with_name = bool(self.params.get('track_window_ids', False))
    buckets = config.resolve_window_buckets(self.params)
    grouped: Dict[int, List[Dict[str, np.ndarray]]] = {}
    for i, raw in enumerate(read_tfrecords(self.patterns)):
      if 0 <= self.limit <= i:
        break
      parsed = parse_example_minimal(raw, self.inference, with_name)
      width = _window_width(parsed)
      bucket = config.bucket_for(width, buckets)
      if bucket is None:
        who = parsed.get('name')
        raise WindowBucketError(
            f'window width {width} overflows window buckets {buckets}'
            + (f' (window id {who!r})' if who is not None else ''))
      grouped.setdefault(bucket, []).append(parsed)
    if not grouped:
      # dclint: allow=typed-faults (startup config error: the operator
      # pointed the loader at an empty glob)
      raise ValueError(f'no examples matched {self.patterns!r}')
    # One formatted array group per occupied bucket, every window
    # padded to its bucket width; single-occupied-bucket corpora keep
    # the legacy flat rows/labels/names layout (and its exact sampling
    # order) so fixed-shape training is bit-identical to before.
    # Per-example pre-pad widths ride along for the padding-waste
    # counters.
    self._groups = {}
    for b in sorted(grouped):
      group = _batch_from_minimal(grouped[b], self.params,
                                  self.inference, pad_to=b)
      group['width'] = np.asarray(
          [_window_width(p) for p in grouped[b]], dtype=np.int64)
      self._groups[b] = group
    grouped.clear()
    self.counters: collections.Counter = collections.Counter()
    if len(self._groups) == 1:
      batch = next(iter(self._groups.values()))
      self.rows = batch['rows']
      self.labels = batch.get('label')
      self.names = batch.get('name')
    else:
      self.rows = self.labels = self.names = None
    self._rng = np.random.default_rng(self.seed)

  def __len__(self) -> int:
    return sum(len(g['rows']) for g in self._groups.values())

  @property
  def window_buckets_present(self) -> tuple:
    return tuple(sorted(self._groups))

  @property
  def steps_per_epoch(self) -> int:
    if self.drop_remainder:
      return sum(
          len(g['rows']) // self.batch_size
          for g in self._groups.values())
    return sum(
        -(-len(g['rows']) // self.batch_size)
        for g in self._groups.values())

  def _count_emit(self, bucket: int, widths: np.ndarray) -> None:
    self.counters[f'n_train_batches_by_bucket_{bucket}'] += 1
    self.counters['n_train_padded_positions'] += int(
        (bucket - widths).sum())
    self.counters['n_train_window_positions'] += int(
        bucket * len(widths))

  def epoch(self) -> Iterator[Dict[str, np.ndarray]]:
    if self.rows is not None:
      # Legacy single-shape path, untouched ordering.
      bucket, g = next(iter(self._groups.items()))
      order = np.arange(len(self.rows))
      if self.shuffle:
        self._rng.shuffle(order)
      n = len(order)
      stop = (
          n - n % self.batch_size if self.drop_remainder else n
      )
      for start in range(0, stop, self.batch_size):
        idx = order[start : start + self.batch_size]
        batch = {'rows': self.rows[idx]}
        if self.names is not None:
          batch['name'] = self.names[idx]
        if self.labels is not None:
          batch['label'] = self.labels[idx]
        self._count_emit(bucket, g['width'][idx])
        yield batch
      return
    # Bucketed epoch: shuffle within each bucket, then interleave the
    # per-bucket batch slots deterministically (seeded rng when
    # shuffling, narrow-to-wide otherwise) so resume/fast-forward
    # replays the identical batch sequence.
    slots: List[tuple] = []
    orders: Dict[int, np.ndarray] = {}
    for b in sorted(self._groups):
      g = self._groups[b]
      order = np.arange(len(g['rows']))
      if self.shuffle:
        self._rng.shuffle(order)
      orders[b] = order
      n = len(order)
      stop = n - n % self.batch_size if self.drop_remainder else n
      slots.extend((b, start) for start in range(0, stop, self.batch_size))
    if self.shuffle:
      self._rng.shuffle(slots)
    for b, start in slots:
      g = self._groups[b]
      idx = orders[b][start : start + self.batch_size]
      batch = {'rows': g['rows'][idx]}
      if g.get('name') is not None:
        batch['name'] = g['name'][idx]
      if g.get('label') is not None:
        batch['label'] = g['label'][idx]
      self._count_emit(b, g['width'][idx])
      yield batch

  def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
    while True:
      yield from self.epoch()


@dataclasses.dataclass
class StreamingDataset:
  """Shard-interleaved streaming loader with a shuffle buffer.

  For corpora too large for memory (the reference trains on ~100M
  examples): shards are read round-robin on a background thread, parsed
  examples fill a reservoir shuffle buffer, and fixed-size batches are
  drawn indefinitely (reference semantics: data_providers.py:395-425).
  """

  patterns: Union[str, Sequence[str]]
  params: ml_collections.ConfigDict
  batch_size: int
  buffer_size: int = 100_000
  seed: int = 1
  inference: bool = False
  # >0: decode raw records in worker processes (chunked imap). The
  # per-core decode ceiling is ~10k ex/s (measured, minimal parse);
  # dp>=8 training (~12k ex/s/host) needs either workers on a
  # many-core host or per-host input sharding (docs/training.md).
  workers: int = 0
  # 'fail' (default): a shard that fails to decode aborts training.
  # 'skip': log + count it and move on to the next shard — a single
  # corrupt shard out of thousands must not kill a multi-day run.
  on_shard_error: str = OnShardError.FAIL
  # Per-host shard assignment for pod-scale streaming: host `host_rank`
  # of `host_count` reads every host_count-th shard (round-robin over
  # the sorted glob). Default (0 of 1) reads everything — the
  # identical-batches mode the elastic identity drills rely on. An
  # elastic rebuild retargets the assignment via reassign_hosts().
  host_rank: int = 0
  host_count: int = 1

  def __post_init__(self):
    from deepconsensus_tpu.io.tfrecord import glob_paths

    if self.on_shard_error not in OnShardError.CHOICES:
      # dclint: allow=typed-faults (flag validation at startup)
      raise ValueError(
          f'on_shard_error must be one of {OnShardError.CHOICES}, '
          f'got {self.on_shard_error!r}'
      )
    if not 0 <= self.host_rank < max(self.host_count, 1):
      # dclint: allow=typed-faults (flag validation at startup)
      raise ValueError(
          f'host_rank={self.host_rank} out of range for '
          f'host_count={self.host_count}'
      )
    self._all_paths = glob_paths(self.patterns)
    if not self._all_paths:
      # dclint: allow=typed-faults (startup config error: the operator
      # pointed the loader at an empty glob)
      raise ValueError(f'no shards matched {self.patterns!r}')
    # dclint: lock-free (reassign_hosts replaces the whole list in one
    # reference assignment; the reader thread sees the old or the new
    # list, never a mix)
    self._paths = self._assigned_paths(self.host_rank, self.host_count)
    self._rng = np.random.default_rng(self.seed)
    self._with_name = bool(self.params.get('track_window_ids', False))
    self._buckets = config.resolve_window_buckets(self.params)
    # Fault counters (n_shard_errors, ...) survive the iterator so the
    # training driver can report them at end of run.
    # dclint: lock-free (the reader thread and the consuming train loop
    # increment DISJOINT key sets — producer: shard/record decode
    # faults; consumer: per-bucket emission counters — and each
    # Counter bump is a single GIL-atomic dict op per key)
    self.counters: collections.Counter = collections.Counter()

  def _assigned_paths(self, rank: int, count: int) -> list:
    """Round-robin shard assignment for one host. A host whose slot is
    empty (more hosts than shards) falls back to the full set — reading
    duplicate data beats deadlocking an admitted member with no
    input."""
    assigned = self._all_paths[rank::max(count, 1)]
    if not assigned:
      log.warning(
          'host %d/%d has no shards under round-robin assignment of '
          '%d path(s); falling back to the full shard set',
          rank, count, len(self._all_paths))
      return list(self._all_paths)
    return assigned

  def reassign_hosts(self, rank: int, count: int) -> None:
    """Retargets the per-host shard assignment after an elastic
    membership change (rebuild shrinks host_count, re-admission grows
    it back). Takes effect at the next epoch's shard permutation — the
    shard currently being read finishes under the old assignment. The
    swap is a single reference assignment, so the reader thread sees
    either the old or the new list, never a mix."""
    # dclint: lock-free (host_rank/host_count are written only here,
    # on the consuming thread; the reader thread takes the companion
    # self._paths swap below — these two scalars only feed logging and
    # this no-op check)
    if (rank, count) == (self.host_rank, self.host_count):
      return
    self.host_rank, self.host_count = int(rank), int(count)
    self._paths = self._assigned_paths(self.host_rank, self.host_count)
    self.counters['n_shard_reassignments'] += 1
    log.warning('streaming shards reassigned: host %d/%d now owns %d '
                'of %d shard(s)', rank, count, len(self._paths),
                len(self._all_paths))

  def _raw_stream(self) -> Iterator[bytes]:
    """Shards in a fresh random order each epoch, consumed ONE AT A
    TIME with whole-shard native decode (memory stays bounded by the
    largest single shard; an interleave across open native readers
    would hold every shard's records at once). Cross-shard mixing is
    the reference's shuffle-files + shuffle-buffer recipe: per-epoch
    shard permutation here, reservoir buffer in __iter__
    (data_providers.py:395-425)."""
    from deepconsensus_tpu.io.tfrecord import TFRecordReader

    while True:
      produced = False
      # Snapshot the assignment for this epoch: reassign_hosts swaps
      # self._paths from the training thread, and indexing a shrunk
      # list with a stale permutation would walk off the end.
      paths = self._paths
      for i in self._rng.permutation(len(paths)):
        path = paths[i]
        try:
          for raw in TFRecordReader(path, native_decode=True):
            produced = True
            yield raw
        except Exception as e:  # noqa: BLE001 - policy-gated below
          if self.on_shard_error != OnShardError.SKIP:
            raise
          self.counters['n_shard_errors'] += 1
          if isinstance(e, CorruptInputError):
            self.counters['n_corrupt_records'] += 1
          log.warning('on_shard_error=skip: skipping shard %s (%s: %s)',
                      path, type(e).__name__, e)
      if not produced:
        # All shards bad: without this the skip policy would spin
        # forever yielding nothing while the consumer waits.
        # dclint: allow=typed-faults (aggregate stop after every
        # per-shard fault was already routed to the counters; tests
        # pin RuntimeError('every shard failed ...'))
        raise RuntimeError(
            f'every shard failed to decode under on_shard_error=skip: '
            f'{self._paths}'
        )

  def _minimal_stream(self, stop) -> Iterator[Dict[str, np.ndarray]]:
    """Raw records -> minimal parses, optionally via worker processes.

    workers>0 assigns each worker a round-robin subset of the SHARDS,
    so gzip decompression + record framing (the measured single-core
    bottleneck, ~10k rec/s) parallelizes along with the proto parse;
    the parent only drains parsed chunks. Cross-worker mixing comes
    from the caller's reservoir shuffle buffer.
    """
    if self.workers <= 0:
      for raw in self._raw_stream():
        if stop.is_set():
          return
        try:
          parsed = parse_example_minimal(raw, self.inference,
                                         self._with_name)
        except Exception as e:  # noqa: BLE001 - policy-gated
          if self.on_shard_error != OnShardError.SKIP:
            raise
          # Frame-intact but undecodable payload: the streaming loader
          # skips payload CRCs for speed, so bit rot inside a record
          # surfaces here at proto-parse time. Record-local — skip just
          # this record, not the shard.
          self.counters['n_corrupt_records'] += 1
          log.warning('on_shard_error=skip: undecodable record '
                      '(%s: %s)', type(e).__name__, e)
          continue
        yield parsed
      return
    import multiprocessing
    import queue as queue_lib

    n_workers = min(self.workers, len(self._paths))
    # spawn, not fork: the parent is multi-threaded (producer threads)
    # and typically has a TPU backend initialized by the time training
    # iterates the dataset — forking that process can deadlock the
    # child on an inherited lock. Workers only need numpy + the
    # TFRecord/proto codecs, so a fresh interpreter is cheap.
    ctx = multiprocessing.get_context('spawn')
    out_queue = ctx.Queue(maxsize=64)  # of <=64-parse chunks (~2 MB each)
    procs = []
    worker_paths = [self._paths[w::n_workers] for w in range(n_workers)]
    for w in range(n_workers):
      proc = ctx.Process(
          target=_shard_reader_main,
          args=(worker_paths[w], self.inference, self.seed + w, out_queue,
                64, self.on_shard_error, self._with_name, w),
          daemon=True,
      )
      proc.start()
      procs.append(proc)
    def check_liveness():
      # A worker that died cleanly (exit 0) simply exhausted its
      # repeat-forever stream early — impossible in practice, so treat
      # ANY dead worker with a nonzero code as fatal: letting training
      # continue on the survivors' shard subsets silently skews the
      # data distribution. Checked on EVERY drain iteration, not just
      # when the queue runs dry — survivors can keep the queue fed
      # forever, which is exactly the silent-skew case.
      crashed = [
          (w, p.exitcode)
          for w, p in enumerate(procs)
          if not p.is_alive() and p.exitcode not in (0, None)
      ]
      if crashed:
        # Name the dead workers' shard subsets: 'worker 1 crashed' is
        # undebuggable, 'worker 1 owned these 3 files' points straight
        # at the corrupt shard.
        detail = '; '.join(
            f'worker {w} (exit code {code}) owned shards '
            f'{worker_paths[w]}'
            for w, code in crashed
        )
        # dclint: allow=typed-faults (worker-process death is an infra
        # failure, not an input fault; tests pin the RuntimeError
        # message naming the dead worker's owned shards)
        raise RuntimeError(
            f'StreamingDataset worker(s) crashed ({len(crashed)} of '
            f'{n_workers}): {detail}; check shard paths/integrity '
            f'(corrupt shard or OOM)'
        )
      if not any(p.is_alive() for p in procs):
        codes = [p.exitcode for p in procs]
        # dclint: allow=typed-faults (worker-process death is an infra
        # failure, not an input fault)
        raise RuntimeError(
            f'all {n_workers} StreamingDataset workers exited '
            f'(exit codes {codes}); check shard paths/integrity'
        )

    try:
      while not stop.is_set():
        check_liveness()
        try:
          kind, payload = out_queue.get(timeout=5)
        except queue_lib.Empty:
          continue
        if kind == 'shard_error':
          message, corrupt = payload
          self.counters['n_shard_errors'] += 1
          if corrupt:
            self.counters['n_corrupt_records'] += 1
          log.warning('on_shard_error=skip: worker skipped shard (%s)',
                      message)
          continue
        if kind == 'corrupt_record':
          self.counters['n_corrupt_records'] += 1
          log.warning('on_shard_error=skip: worker skipped record (%s)',
                      payload)
          continue
        w_idx, parses = payload
        # Per-worker decode counters: with N workers on an M-core host
        # these prove (or disprove) that the decode load actually
        # splits ~evenly — the evidence behind any "N workers -> ~N x
        # throughput" extrapolation (docs/training.md).
        self.counters[f'n_parsed_worker_{w_idx}'] += len(parses)
        yield from parses
    finally:
      for proc in procs:
        proc.terminate()
      for proc in procs:
        proc.join(timeout=5)

  def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
    import queue as queue_lib
    import threading

    parsed_queue: 'queue_lib.Queue' = queue_lib.Queue(maxsize=4096)
    stop = threading.Event()

    def producer():
      # Decode errors (bad shard, dead workers) must surface at the
      # consumer, not die with this thread: forward them as items.
      try:
        for parsed in self._minimal_stream(stop):
          while not stop.is_set():
            try:
              parsed_queue.put(('item', parsed), timeout=0.5)
              break
            except queue_lib.Full:
              continue
          if stop.is_set():
            return
      except BaseException as e:  # noqa: BLE001 - re-raised at consumer
        while not stop.is_set():
          try:
            parsed_queue.put(('error', e), timeout=0.5)
            return
          except queue_lib.Full:
            continue

    thread = threading.Thread(target=producer, daemon=True)
    thread.start()

    def next_parsed():
      kind, payload = parsed_queue.get()
      if kind == 'error':
        raise payload
      return payload

    try:
      if len(self._buckets) == 1:
        # Legacy fixed-shape reservoir. The rng draw sequence is
        # bit-identical to the pre-bucketing loader for on-bucket
        # corpora (triage only intervenes on narrow windows, which pad,
        # and overflow widths, which quarantine under skip).
        bucket = self._buckets[0]
        buffer: List[Dict[str, np.ndarray]] = []
        fill_target = max(self.buffer_size, self.batch_size * 2)
        while True:
          while len(buffer) < fill_target:
            triaged = self._triage(next_parsed())
            if triaged is not None:
              buffer.append(triaged[1])
          idx = self._rng.choice(len(buffer), self.batch_size,
                                 replace=False)
          idx_set = set(idx.tolist())
          chosen = [buffer[i] for i in idx]
          buffer = [b for i, b in enumerate(buffer) if i not in idx_set]
          self._count_emit(bucket, chosen)
          yield _batch_from_minimal(chosen, self.params, self.inference,
                                    pad_to=bucket)
      else:
        yield from self._bucketed_batches(next_parsed)
    finally:
      # Stop the producer when the consumer abandons the iterator
      # (GeneratorExit) so retries don't accumulate blocked threads.
      # Then JOIN it: its own finally terminates+joins the worker
      # processes, so returning before it finishes would leave workers
      # decoding (and competing for cores) into whatever runs next.
      # Bounded so a wedged worker can't hang the consumer; daemons
      # die with the interpreter in that case.
      stop.set()
      thread.join(timeout=15)

  def _triage(self, parsed: Dict[str, np.ndarray]):
    """(bucket, parse) for the smallest bucket that fits the window, or
    None after quarantining an overflow width (on_shard_error=skip +
    n_width_rejected; under 'fail' the typed fault names the window)."""
    width = _window_width(parsed)
    bucket = config.bucket_for(width, self._buckets)
    if bucket is not None:
      return bucket, parsed
    who = parsed.get('name')
    if self.on_shard_error != OnShardError.SKIP:
      raise WindowBucketError(
          f'window width {width} overflows window buckets '
          f'{self._buckets}'
          + (f' (window id {who!r})' if who is not None else '')
          + '; widen window_buckets or run with --on_shard_error=skip '
          'to quarantine it')
    self.counters['n_width_rejected'] += 1
    log.warning(
        'on_shard_error=skip: window width %d overflows buckets %s%s; '
        'rejected (n_width_rejected)', width, self._buckets,
        f' (window id {who!r})' if who is not None else '')
    return None

  def _count_emit(self, bucket: int, chosen: List[Dict]) -> None:
    """Per-bucket emission counters. The padded/total position pair is
    what the trainer turns into train_padding_fraction."""
    self.counters[f'n_train_batches_by_bucket_{bucket}'] += 1
    pad = sum(bucket - _window_width(c) for c in chosen)
    self.counters['n_train_padded_positions'] += pad
    self.counters['n_train_window_positions'] += bucket * len(chosen)

  def _bucketed_batches(self, next_parsed) -> Iterator[Dict[str, np.ndarray]]:
    """Multi-bucket consumer: per-bucket accumulation under a shared
    batch clock, mirroring the PR-12 inference engine's per-bucket
    packers.

    Every parse is triaged into the smallest fitting bucket's buffer.
    A bucket emits when it holds a full batch (largest buffer first —
    the backlog drain rule); a bucket whose oldest pending window has
    waited `bucket_starvation_batches` clock ticks without filling is
    flushed by PROMOTING windows from narrower buffers (any window fits
    a wider bucket at the cost of more padding), so rare wide windows
    never go stale and every emitted batch still carries batch_size
    real windows — a fixed per-bucket geometry, never a partial batch
    that would retrace the jitted step. The whole schedule is a
    deterministic function of the parse stream and the seeded rng, so
    skip-based resume/fast-forward replays the identical batch
    sequence."""
    batch = self.batch_size
    buckets = self._buckets
    starvation = int(
        self.params.get('bucket_starvation_batches', 8) or 8)
    fill_target = max(self.buffer_size, batch * 2 * len(buckets))
    buffers: Dict[int, List[Dict[str, np.ndarray]]] = {
        b: [] for b in buckets}
    # Clock tick at which each bucket's current backlog started
    # waiting; -1 = empty.
    waiting = {b: -1 for b in buckets}
    clock = 0

    def ready():
      return [b for b in buckets if len(buffers[b]) >= batch]

    def starved():
      out = []
      for b in buckets:
        if waiting[b] < 0 or clock - waiting[b] < starvation:
          continue
        # Flushable only if promotion from narrower buckets can top the
        # batch up to full size.
        if sum(len(buffers[x]) for x in buckets if x <= b) >= batch:
          out.append(b)
      return out

    def draw(bucket, take):
      pool = buffers[bucket]
      idx = self._rng.choice(len(pool), take, replace=False)
      idx_set = set(idx.tolist())
      chosen = [pool[i] for i in idx]
      buffers[bucket] = [p for i, p in enumerate(pool)
                         if i not in idx_set]
      return chosen

    while True:
      while True:
        total = sum(len(v) for v in buffers.values())
        if (ready() or starved()) and total >= fill_target:
          break
        triaged = self._triage(next_parsed())
        if triaged is None:
          continue
        b, parsed = triaged
        buffers[b].append(parsed)
        if waiting[b] < 0:
          waiting[b] = clock
      star = starved()
      if star:
        # Widest starving bucket first: its windows cannot be promoted
        # anywhere else, so it is the one at risk of going stale. (A
        # starved bucket that meanwhile filled up just emits a normal
        # full draw — the promotion loop below is a no-op.)
        bucket = max(star)
        chosen = draw(bucket, min(len(buffers[bucket]), batch))
        if len(chosen) < batch:
          self.counters['n_train_starvation_flushes'] += 1
          for nb in sorted((x for x in buckets if x < bucket),
                           reverse=True):
            need = batch - len(chosen)
            if not need:
              break
            take = min(need, len(buffers[nb]))
            if take:
              chosen.extend(draw(nb, take))
              self.counters['n_train_promoted_windows'] += take
      else:
        # Largest backlog first (ties to the wider bucket) keeps every
        # buffer bounded instead of letting the dominant width starve
        # the rest of reservoir space.
        bucket = max(ready(), key=lambda b: (len(buffers[b]), b))
        chosen = draw(bucket, batch)
      clock += 1
      for b in buckets:
        if not buffers[b]:
          waiting[b] = -1
      waiting[bucket] = clock if buffers[bucket] else -1
      self._count_emit(bucket, chosen)
      yield _batch_from_minimal(chosen, self.params, self.inference,
                                pad_to=bucket)


def prefetch_iterator(iterator, depth: int = 2):
  """Runs `iterator` in a background thread, keeping up to `depth`
  batches ready, so host-side decode/shuffle/stacking overlaps device
  compute (the reference gets this from tf.data prefetch;
  data_providers.py uses AUTOTUNE). Exceptions re-raise at the
  consumer; closing the generator stops the producer.
  """
  import queue
  import threading

  q: 'queue.Queue' = queue.Queue(maxsize=depth)
  stop = threading.Event()
  _END = object()

  def producer():
    try:
      for item in iterator:
        while not stop.is_set():
          try:
            q.put(('item', item), timeout=0.2)
            break
          except queue.Full:
            continue
        if stop.is_set():
          return
      while not stop.is_set():
        try:
          q.put(('end', _END), timeout=0.2)
          return
        except queue.Full:
          continue
    except BaseException as e:  # noqa: BLE001 - surfaced to consumer
      # Same retry-until-stopped discipline as item puts: dropping the
      # sentinel on a momentarily-full queue would leave the consumer
      # blocked on q.get() forever instead of seeing the error.
      while not stop.is_set():
        try:
          q.put(('error', e), timeout=0.2)
          return
        except queue.Full:
          continue

  thread = threading.Thread(target=producer, daemon=True)
  thread.start()
  try:
    while True:
      kind, payload = q.get()
      if kind == 'end':
        return
      if kind == 'error':
        raise payload
      yield payload
  finally:
    stop.set()
    # Drain so a blocked producer can observe stop and exit.
    while not q.empty():
      try:
        q.get_nowait()
      except queue.Empty:
        break
    thread.join(timeout=10)


# Complement map over SEQ_VOCAB ' ATCG': gap fixed, A<->T, C<->G.
_COMPLEMENT_LUT = np.array([0, 2, 1, 4, 3], dtype=constants.NP_DATA_TYPE)
# Strand values (constants.Strand): UNKNOWN fixed, FORWARD<->REVERSE.
_STRAND_FLIP_LUT = np.array([0, 2, 1], dtype=constants.NP_DATA_TYPE)
# SN rows are per-channel [A, C, G, T]; under reverse-complement each
# base is read as its partner, so channels swap A<->T, C<->G.
_SN_RC_ORDER = np.array([3, 2, 1, 0])


def augment_batch(
    batch: Dict[str, np.ndarray],
    params: ml_collections.ConfigDict,
    rng: np.random.Generator,
) -> Dict[str, np.ndarray]:
  """Training-time window augmentation over a formatted (rows, label)
  batch. No reference counterpart: the reference trains on ~100M unique
  windows (train_tpu_model.md:234-239) while small corpora re-show the
  same ones, so augmentation substitutes for data diversity. Four
  independent per-example transforms, each gated by its
  params.augment_*_prob:

    * subread permutation — shuffle the order of present subreads
      (consensus is order-invariant; the model should be too);
    * subread downsampling — keep a random >= half subset, compacted
      to the front (simulates lower-pass ZMWs);
    * reverse-complement — flip the occupied extent of every row along
      the window, complement bases/ccs/label, swap strand and SN
      channels (the same molecule read in the other orientation);
    * PW/IP jitter — +/-1 on a quarter of nonzero kinetics entries,
      clipped back to [1, PW_MAX/IP_MAX].

  Returns a new batch; never mutates the input. Presence of a subread
  is read off its strand row (absent rows are all-zero = UNKNOWN).
  """
  rows = batch['rows'].copy()  # [B, H, L, 1]
  label = batch['label'].copy() if batch.get('label') is not None else None
  b, _, length, _ = rows.shape
  p = params.max_passes
  blocks = rows[:, : 4 * p, :, 0].reshape(b, 4, p, length)  # views rows
  bases, pw, ip, strand = (blocks[:, i] for i in range(4))
  present = strand.max(axis=2) > 0  # [B, P]
  n_present = present.sum(axis=1)  # [B]

  # --- subread permutation + downsampling (one combined gather) ---
  perm_on = rng.random(b) < params.get('augment_perm_prob', 0.0)
  drop_on = rng.random(b) < params.get('augment_drop_prob', 0.0)
  keep = np.where(
      drop_on & (n_present > 1),
      rng.integers(np.maximum(1, -(-n_present // 2)),
                   np.maximum(n_present, 1) + 1),
      n_present,
  )
  # Which subreads survive: a RANDOM size-`keep` subset of the present
  # ones (selection must be random even when the independent
  # permutation transform does not fire, or every drop would remove
  # the trailing subreads and bias the augmented distribution).
  sel_keys = np.where(present, rng.random((b, p)), 2.0)
  sel_rank = np.argsort(np.argsort(sel_keys, axis=1), axis=1)
  kept = (sel_rank < keep[:, None]) & present
  # Output order: random when permuting, original subread order
  # otherwise; non-kept rows sort to the end.
  order_keys = np.where(
      perm_on[:, None], rng.random((b, p)), np.arange(p)[None, :] / p
  )
  order_keys = np.where(kept, order_keys, 2.0)
  order = np.argsort(order_keys, axis=1, kind='stable')  # [B, P]
  fired = perm_on | (keep < n_present)  # [B]
  if fired.any():
    sel = np.take_along_axis(
        blocks, order[:, None, :, None], axis=2
    )  # [B, 4, P, L]
    # Zero out dropped tail (and previously-absent rows stay zero).
    live = np.arange(p)[None, :] < keep[:, None]  # [B, P]
    sel = np.where(live[:, None, :, None], sel, 0.0)
    # Gate the write per-example: for an example where neither
    # transform fired, the gather is only the identity if its present
    # subreads are front-compacted — an example with an interior
    # all-zero row would be silently compacted by the batch-wide write.
    sel = np.where(fired[:, None, None, None], sel, blocks)
    rows[:, : 4 * p, :, 0] = sel.reshape(b, 4 * p, length)
    blocks = rows[:, : 4 * p, :, 0].reshape(b, 4, p, length)
    bases, pw, ip, strand = (blocks[:, i] for i in range(4))

  # --- reverse-complement ---
  rc_on = rng.random(b) < params.get('augment_rc_prob', 0.0)
  if rc_on.any():
    ccs_row = 4 * p
    sn_start = 4 * p + 1 + (1 if params.use_ccs_bq else 0)
    # Occupied extent: last column with any base content (subreads or
    # ccs); reversal happens inside it so tail padding stays the tail.
    content = (bases.max(axis=1) > 0) | (rows[:, ccs_row, :, 0] > 0)
    width = length - np.argmax(content[:, ::-1], axis=1)  # [B]
    width = np.where(content.any(axis=1), width, 0)
    rev_idx = np.arange(length)[None, :]  # [B, L] source index map
    rev_idx = np.where(
        rev_idx < width[:, None], width[:, None] - 1 - rev_idx, rev_idx
    )
    flip = rc_on[:, None]

    def rev(block):  # [B, R, L] reverse occupied extent where rc_on
      rev_b = np.take_along_axis(block, rev_idx[:, None, :], axis=2)
      return np.where(flip[:, :, None] if block.ndim == 3 else flip,
                      rev_b, block)

    comp = _COMPLEMENT_LUT
    new_bases = rev(comp[bases.astype(np.int64)])
    rows[:, :p, :, 0] = np.where(flip[:, :, None], new_bases, bases)
    rows[:, p : 2 * p, :, 0] = rev(pw)
    rows[:, 2 * p : 3 * p, :, 0] = rev(ip)
    flipped_strand = _STRAND_FLIP_LUT[strand.astype(np.int64)]
    rows[:, 3 * p : 4 * p, :, 0] = np.where(
        flip[:, :, None], flipped_strand, strand
    )
    ccs = rows[:, ccs_row : ccs_row + 1, :, 0]
    # Fall-through must be the ORIGINAL row: rev()'s internal where
    # would otherwise hand non-flipped examples the complemented (but
    # unreversed) ccs.
    ccs_rc = np.take_along_axis(
        comp[ccs.astype(np.int64)], rev_idx[:, None, :], axis=2
    )
    rows[:, ccs_row : ccs_row + 1, :, 0] = np.where(
        flip[:, :, None], ccs_rc, ccs
    )
    if params.use_ccs_bq:
      rows[:, ccs_row + 1 : ccs_row + 2, :, 0] = rev(
          rows[:, ccs_row + 1 : ccs_row + 2, :, 0]
      )
    sn = rows[:, sn_start : sn_start + 4, :, 0]
    rows[:, sn_start : sn_start + 4, :, 0] = np.where(
        flip[:, :, None], sn[:, _SN_RC_ORDER], sn
    )
    if label is not None and label.size:
      # The loss treats the label as a gap-collapsible SEQUENCE
      # (left_shift_sequence), so a full reverse + complement is exact;
      # leading gaps are shifted away by the loss.
      lab_rc = _COMPLEMENT_LUT[label.astype(np.int64)][:, ::-1]
      label = np.where(rc_on[:, None], lab_rc, label).astype(label.dtype)

  # --- PW/IP jitter ---
  jit_on = rng.random(b) < params.get('augment_jitter_prob', 0.0)
  if jit_on.any():
    blocks = rows[:, : 4 * p, :, 0].reshape(b, 4, p, length)
    for bi, cap in ((1, params.PW_MAX), (2, params.IP_MAX)):
      block = blocks[:, bi]
      # Draw from {-1, +1}: integers(-1, 2) would include 0 and silently
      # cut the effective jitter rate to ~17% of entries.
      delta = (rng.integers(0, 2, size=block.shape) * 2 - 1).astype(
          rows.dtype
      )
      mask = (
          jit_on[:, None, None]
          & (block > 0)
          & (rng.random(block.shape) < 0.25)
      )
      blocks[:, bi] = np.where(
          mask, np.clip(block + delta, 1, cap), block
      )
    rows[:, : 4 * p, :, 0] = blocks.reshape(b, 4 * p, length)

  out = dict(batch)
  out['rows'] = rows
  if label is not None:
    out['label'] = label
  return out
