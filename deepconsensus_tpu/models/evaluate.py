"""Offline evaluation over labeled TFRecords -> inference.csv.

Equivalent of the reference's model_inference binary (reference:
deepconsensus/models/model_inference.py:79-137,
model_utils.py:379-421): restores a checkpoint, sweeps the eval set,
and writes one CSV row of metrics.
"""
from __future__ import annotations

import csv
import os
from typing import Dict, Optional

import jax
import ml_collections

from deepconsensus_tpu import constants
from deepconsensus_tpu.models import data as data_lib
from deepconsensus_tpu.models import metrics as metrics_lib
from deepconsensus_tpu.models import model as model_lib
from deepconsensus_tpu.models import train as train_lib


def run_evaluation(
    params: ml_collections.ConfigDict,
    checkpoint_path: Optional[str],
    eval_patterns,
    out_dir: str,
    variables: Optional[Dict] = None,
    limit: int = -1,
) -> Dict[str, float]:
  """Evaluates and writes <out_dir>/inference.csv; returns metrics."""
  model = model_lib.get_model(params)
  if variables is None:
    from deepconsensus_tpu.models.checkpoints import load_params

    variables = {'params': load_params(checkpoint_path)}

  loss_obj = train_lib.make_loss(params)
  align_metric = metrics_lib.AlignmentMetric()

  @jax.jit
  def eval_step(batch):
    preds = model.apply(variables, batch['rows'])
    loss = loss_obj(batch['label'], preds)
    correct, total = metrics_lib.per_example_accuracy_counts(
        batch['label'], preds
    )
    ccs = train_lib.ccs_row_from_batch(batch['rows'], params)
    id_ccs, id_pred = metrics_lib.batch_identity_ccs_pred(
        ccs, preds, batch['label'], align_metric
    )
    out = {
        'loss': loss,
        'accuracy_correct': correct,
        'accuracy_total': total,
        'identity_ccs': id_ccs,
        'identity_pred': id_pred,
    }
    for cls in range(constants.SEQ_VOCAB_SIZE):
      c, t = metrics_lib.per_class_accuracy_counts(
          batch['label'], preds, cls
      )
      out[f'class{cls}_correct'] = c
      out[f'class{cls}_total'] = t
    return out

  ds = data_lib.DatasetIterator(
      patterns=eval_patterns,
      params=params,
      batch_size=params.batch_size,
      shuffle=False,
      limit=limit,
  )
  sums: Dict[str, float] = {}
  batches = 0
  yield_metric = metrics_lib.YieldOverCCS()
  for batch in ds.epoch():
    out = {k: float(v) for k, v in eval_step(batch).items()}
    yield_metric.update(out['identity_ccs'], out['identity_pred'])
    for k, v in out.items():
      sums[k] = sums.get(k, 0.0) + v
    batches += 1
  if not batches:
    raise ValueError(
        f'no complete eval batches: {eval_patterns!r} yielded fewer '
        f'than batch_size={params.batch_size} examples '
        '(limit counts examples, not batches)'
    )
  metrics = {
      'loss': sums['loss'] / batches,
      'per_example_accuracy': (
          sums['accuracy_correct'] / max(sums['accuracy_total'], 1)
      ),
      'alignment_identity': sums['identity_pred'] / batches,
      'ccs_identity': sums['identity_ccs'] / batches,
      'yield_over_ccs': yield_metric.result(),
  }
  for cls in range(constants.SEQ_VOCAB_SIZE):
    total = sums.get(f'class{cls}_total', 0.0)
    if total:
      metrics[f'class{cls}_accuracy'] = sums[f'class{cls}_correct'] / total

  os.makedirs(out_dir, exist_ok=True)
  csv_path = os.path.join(out_dir, 'inference.csv')
  with open(csv_path, 'w', newline='') as f:
    writer = csv.writer(f)
    writer.writerow(sorted(metrics))
    writer.writerow([metrics[k] for k in sorted(metrics)])
  return metrics
