from deepconsensus_tpu.models.config import (  # noqa: F401
    get_config,
    finalize_params,
    read_params_from_json,
    save_params_as_json,
)
from deepconsensus_tpu.models.model import (  # noqa: F401
    DeepConsensusModel,
    get_model,
)
