"""Model package. Re-exports resolve lazily (PEP 562): config.py is
numpy-only, model.py pulls in flax/jax — featurize workers read the
feature-layout presets from config on jax-free CPU boxes, and an eager
model import here would drag the whole jax stack along."""

_CONFIG_EXPORTS = ('get_config', 'finalize_params',
                   'read_params_from_json', 'save_params_as_json')
_MODEL_EXPORTS = ('DeepConsensusModel', 'get_model')

__all__ = list(_CONFIG_EXPORTS + _MODEL_EXPORTS)


def __getattr__(name):
  if name in _CONFIG_EXPORTS:
    from deepconsensus_tpu.models import config

    return getattr(config, name)
  if name in _MODEL_EXPORTS:
    from deepconsensus_tpu.models import model

    return getattr(model, name)
  raise AttributeError(
      f'module {__name__!r} has no attribute {name!r}')
