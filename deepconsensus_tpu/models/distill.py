"""Knowledge distillation: frozen teacher -> smaller student.

Mirrors the reference's distillation trainer (reference:
deepconsensus/models/model_distillation.py:104-420): the student is
initialized from a teacher layer map, then trained with
student_alpha * AlignmentLoss + distill_alpha * logit-space loss while
the teacher runs inference-only. Both models share one jitted step.

As a flywheel stage (models/flywheel.py), distillation is durable:
mid-run checkpoints every params.checkpoint_every_n_steps, crash/
preemption resume from the latest valid checkpoint (fast-forwarding
the deterministic data stream so the replayed prefix is dropped, not
re-applied), a shared PreemptionGuard so SIGTERM checkpoints and
returns {'preempted': 1, 'stop_step': N} like run_training, and an
elastic-pod-lite mode (grads cross hosts through the bounded
step_sync; a HostLostError propagates to the flywheel's stage retry,
which degrades the pod, rather than rebuilding in place).
"""
from __future__ import annotations

import logging
import os
from typing import Dict, Optional

import jax
import ml_collections
import numpy as np

from deepconsensus_tpu import faults as faults_lib
from deepconsensus_tpu.models import checkpoints as checkpoints_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import data as data_lib
from deepconsensus_tpu.models import losses as losses_lib
from deepconsensus_tpu.models import metrics as metrics_lib
from deepconsensus_tpu.models import model as model_lib
from deepconsensus_tpu.models import train as train_lib
from deepconsensus_tpu.parallel import mesh as mesh_lib
from deepconsensus_tpu.parallel import partition_rules

log = logging.getLogger(__name__)


def init_student_from_teacher(
    student_params: Dict,
    teacher_params: Dict,
    cfg: ml_collections.ConfigDict,
) -> Dict:
  """Copies teacher weights into the student per the layer maps
  (reference: model_distillation.py:104-144)."""
  student = jax.tree_util.tree_map(lambda x: x, student_params)  # copy

  if cfg.get('init_nonencoder_layers', True):
    for key in student:
      if key != 'encoder' and key in teacher_params:
        student[key] = jax.tree_util.tree_map(
            lambda x: x, teacher_params[key]
        )

  if cfg.get('init_encoder_stack', True):
    t_layers = list(cfg.teacher_encoder_layers)
    s_layers = list(cfg.student_encoder_layers)
    enc_s = dict(student['encoder'])
    enc_t = teacher_params['encoder']
    for t, s in zip(t_layers, s_layers):
      for stem in ('self_attention', 'attention_wrapper', 'ffn',
                   'ffn_wrapper'):
        src = f'{stem}_{t}'
        dst = f'{stem}_{s}'
        if src in enc_t and dst in enc_s:
          enc_s[dst] = jax.tree_util.tree_map(lambda x: x, enc_t[src])
    if 'output_normalization' in enc_t:
      enc_s['output_normalization'] = jax.tree_util.tree_map(
          lambda x: x, enc_t['output_normalization']
      )
    student['encoder'] = enc_s
  return student


def run_distillation(
    params: ml_collections.ConfigDict,
    teacher_params_cfg: ml_collections.ConfigDict,
    teacher_variables: Dict,
    out_dir: str,
    train_patterns=None,
    eval_patterns=None,
    num_epochs: Optional[int] = None,
    mesh=None,
    elastic_config: Optional[Dict] = None,
    preemption_guard=None,
) -> Dict[str, float]:
  """Distillation training driver; returns final eval metrics.

  A preemption (SIGTERM/SIGINT via the guard, or a pod stop vote)
  checkpoints at the step boundary and returns
  {'preempted': 1.0, 'stop_step': N}; a rerun on the same out_dir
  resumes from that checkpoint. elastic_config (host_id, n_hosts,
  barrier_timeout) is the pod-lite version of run_training's: grads
  cross hosts through parallel/elastic.py step_sync on a local mesh,
  but a HostLostError propagates to the caller (the flywheel's stage
  retry degrades the pod) instead of an in-place rebuild.
  """
  train_patterns = train_patterns or list(params.train_path)
  eval_patterns = eval_patterns or list(params.eval_path)
  num_epochs = num_epochs or params.num_epochs

  pod = None
  if elastic_config and int(elastic_config.get('n_hosts', 1) or 1) > 1:
    from deepconsensus_tpu.parallel import elastic as elastic_lib

    pod = elastic_lib.ElasticPod(
        os.path.join(os.path.abspath(out_dir), '.pod'),
        host_id=int(elastic_config['host_id']),
        n_hosts=int(elastic_config['n_hosts']),
        barrier_timeout=float(
            elastic_config.get('barrier_timeout')
            or params.get('elastic_barrier_timeout', 30.0) or 30.0),
        heartbeat_interval=float(
            elastic_config.get('heartbeat_interval', 0.25) or 0.25),
        readmit=False,
    )
  if pod is not None and mesh is None:
    mesh = mesh_lib.local_mesh(tp=int(params.get('tp', 1) or 1))

  owns_guard = preemption_guard is None
  guard = preemption_guard or train_lib.PreemptionGuard(
      barrier_timeout=float(
          params.get('elastic_barrier_timeout', 30.0) or 30.0)
  ).install()

  teacher_model = model_lib.get_model(teacher_params_cfg)
  student_model = model_lib.get_model(params)

  train_ds = data_lib.DatasetIterator(
      patterns=train_patterns, params=params,
      batch_size=params.batch_size, seed=params.seed,
  )
  eval_ds = data_lib.DatasetIterator(
      patterns=eval_patterns, params=params,
      batch_size=params.batch_size, shuffle=False,
  )
  decay_steps = train_ds.steps_per_epoch * params.get(
      'num_epochs_for_decay', num_epochs
  )
  trainer = train_lib.Trainer(params=params, out_dir=out_dir, mesh=mesh,
                              pod=pod)
  if pod is not None:
    pod.start()
  if trainer._is_writer():
    config_lib.save_params_as_json(out_dir, params)
  state = trainer.init_state(steps_total=max(decay_steps, 1))
  # Crash/preemption resume: a valid checkpoint under this out_dir
  # means a previous distill attempt got that far — restore it (full
  # state: params + LAMB moments + LR position) and fast-forward the
  # deterministic data stream past the applied prefix. Only a fresh
  # start initializes from the teacher layer map.
  resume_from = trainer.latest_valid_checkpoint()
  start_step = 0
  if resume_from is not None:
    state = trainer.restore_checkpoint(state, resume_from)
    start_step = checkpoints_lib.checkpoint_step(resume_from)
    log.warning('distill: resuming from %s (step %d)', resume_from,
                start_step)
  else:
    state = state.replace(
        params=init_student_from_teacher(
            state.params, teacher_variables['params'], params
        )
    )

  align_loss = train_lib.make_loss(params)
  student_alpha = float(params.student_alpha)
  distill_alpha = float(params.distill_alpha)
  temperature = float(params.temperature)
  logit_loss = params.get('logit_loss_identifier', 'mean_squared_error')

  def grads_and_metrics(state, batch):
    rng = jax.random.fold_in(state.dropout_rng, state.step)
    teacher_out = teacher_model.apply(
        teacher_variables, batch['rows'],
        method=teacher_model.apply_with_intermediates,
    )

    def loss_of(p):
      out = student_model.apply(
          {'params': p}, batch['rows'], train=True,
          rngs={'dropout': rng},
          method=student_model.apply_with_intermediates,
      )
      l_student = align_loss(batch['label'], out['preds'])
      l_distill = losses_lib.distillation_loss(
          teacher_out['logits'], out['logits'],
          temperature=temperature, kind=logit_loss,
      )
      total = student_alpha * l_student + distill_alpha * l_distill
      return total, (l_student, l_distill, out['preds'])

    (loss, (l_s, l_d, preds)), grads = jax.value_and_grad(
        loss_of, has_aux=True
    )(state.params)
    correct, total = metrics_lib.per_example_accuracy_counts(
        batch['label'], preds
    )
    return grads, {
        'loss': loss,
        'student_loss': l_s,
        'distill_loss': l_d,
        'accuracy_correct': correct,
        'accuracy_total': total,
    }

  # Trace count == distinct compiled batch geometries: a bucketed
  # corpus (DatasetIterator emits per-bucket batches) compiles one
  # teacher+student step per bucket width over the shared param trees,
  # exactly like run_training's n_train_forward_shapes.
  n_forward_shapes = [0]

  def step(state, batch):
    n_forward_shapes[0] += 1
    grads, m = grads_and_metrics(state, batch)
    return state.apply_gradients(grads=grads), m

  # Same declarative rule table as run_training: the student state
  # (params + LAMB moments) shards by partition_rules.DEFAULT_RULES and
  # the batch over the data axis, so distillation scales on the same
  # meshes as training without its own sharding map. compile_parallel
  # is jax.jit underneath: one executable is cached per bucket width,
  # with no mid-run recompiles for a fixed bucket set.
  state_sh = trainer.state_shardings(state)
  batch_sh = trainer._batch_sharding()
  train_step = partition_rules.compile_parallel(
      step,
      in_shardings=(state_sh, {'rows': batch_sh, 'label': batch_sh}),
      out_shardings=(state_sh, None),
      donate_argnums=(0,),
  )
  # Pod-lite split: local grads, host-level bounded allreduce, local
  # apply — every member applies the same weighted-mean grads, so the
  # states evolve identically (same LAMB update, same fold_in rng).
  grad_step = partition_rules.compile_parallel(
      grads_and_metrics,
      in_shardings=(state_sh, {'rows': batch_sh, 'label': batch_sh}),
  )

  log_every = params.get('log_every_n_steps', 100)
  checkpoint_every = int(params.get('checkpoint_every_n_steps', 0) or 0)
  step_count = 0
  try:
    for _ in range(num_epochs):
      for batch in train_ds.epoch():
        batch.pop('name', None)
        step_count += 1
        if step_count <= start_step:
          # Resume fast-forward: the data stream is deterministic
          # (same patterns, same seed, same epoch order), so skipping
          # the first start_step batches replays the stream position
          # without re-applying the already-checkpointed prefix.
          continue
        sync = None
        if pod is not None:
          local = trainer.localize_batch(batch)
          grads, m = grad_step(state, local)
          g_leaves, treedef = jax.tree_util.tree_flatten(
              jax.device_get(grads))
          sync = pod.step_sync(
              step_count,
              [np.asarray(leaf, np.float32) for leaf in g_leaves],
              weight=float(next(iter(local.values())).shape[0]),
              meta={'loss': float(m['loss'])},
              stop_vote=guard.local(),
          )
          avg = jax.tree_util.tree_unflatten(treedef, sync.arrays)
          state = state.apply_gradients(grads=avg)
        else:
          state, m = train_step(state, batch)
        if step_count % log_every == 0:
          trainer.log_metrics(
              step_count, 'train', {k: float(v) for k, v in m.items()}
          )
        if checkpoint_every and step_count % checkpoint_every == 0:
          trainer.save_checkpoint(state, step_count, {})
        stop = sync.stop if sync is not None else guard.requested()
        if stop:
          # Preemption: commit the step boundary and hand control back
          # (the flywheel marks its journal `interrupted` and exits 0;
          # the next --resume run restores from this checkpoint).
          trainer.save_checkpoint(state, step_count, {})
          return {'preempted': 1.0, 'stop_step': float(step_count)}
    # Final eval + checkpoint, through the same aggregation as
    # run_training so the metric key set (identity_pred, class
    # accuracies, yield) and best_checkpoint_metric behave identically.
    # The bucket telemetry (batches per width, padding fraction,
    # compile-once proof) rides the same 'faults' sidecar channel.
    fault_counters = {k: float(v) for k, v in train_ds.counters.items()}
    fault_counters['n_train_forward_shapes'] = float(n_forward_shapes[0])
    total_pos = fault_counters.get('n_train_window_positions', 0.0)
    if total_pos:
      fault_counters['train_padding_fraction'] = (
          fault_counters.get('n_train_padded_positions', 0.0) / total_pos)
    trainer.log_metrics(step_count, 'faults', fault_counters)
    final = trainer.run_eval(state, eval_ds)
    trainer.save_checkpoint(state, step_count, final)
    return final
  finally:
    if pod is not None:
      pod.close()
    if owns_guard:
      guard.restore()
