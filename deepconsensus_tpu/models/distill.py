"""Knowledge distillation: frozen teacher -> smaller student.

Mirrors the reference's distillation trainer (reference:
deepconsensus/models/model_distillation.py:104-420): the student is
initialized from a teacher layer map, then trained with
student_alpha * AlignmentLoss + distill_alpha * logit-space loss while
the teacher runs inference-only. Both models share one jitted step.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import ml_collections

from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import data as data_lib
from deepconsensus_tpu.models import losses as losses_lib
from deepconsensus_tpu.models import metrics as metrics_lib
from deepconsensus_tpu.models import model as model_lib
from deepconsensus_tpu.models import train as train_lib
from deepconsensus_tpu.parallel import partition_rules


def init_student_from_teacher(
    student_params: Dict,
    teacher_params: Dict,
    cfg: ml_collections.ConfigDict,
) -> Dict:
  """Copies teacher weights into the student per the layer maps
  (reference: model_distillation.py:104-144)."""
  student = jax.tree_util.tree_map(lambda x: x, student_params)  # copy

  if cfg.get('init_nonencoder_layers', True):
    for key in student:
      if key != 'encoder' and key in teacher_params:
        student[key] = jax.tree_util.tree_map(
            lambda x: x, teacher_params[key]
        )

  if cfg.get('init_encoder_stack', True):
    t_layers = list(cfg.teacher_encoder_layers)
    s_layers = list(cfg.student_encoder_layers)
    enc_s = dict(student['encoder'])
    enc_t = teacher_params['encoder']
    for t, s in zip(t_layers, s_layers):
      for stem in ('self_attention', 'attention_wrapper', 'ffn',
                   'ffn_wrapper'):
        src = f'{stem}_{t}'
        dst = f'{stem}_{s}'
        if src in enc_t and dst in enc_s:
          enc_s[dst] = jax.tree_util.tree_map(lambda x: x, enc_t[src])
    if 'output_normalization' in enc_t:
      enc_s['output_normalization'] = jax.tree_util.tree_map(
          lambda x: x, enc_t['output_normalization']
      )
    student['encoder'] = enc_s
  return student


def run_distillation(
    params: ml_collections.ConfigDict,
    teacher_params_cfg: ml_collections.ConfigDict,
    teacher_variables: Dict,
    out_dir: str,
    train_patterns=None,
    eval_patterns=None,
    num_epochs: Optional[int] = None,
    mesh=None,
) -> Dict[str, float]:
  """Distillation training driver; returns final eval metrics."""
  train_patterns = train_patterns or list(params.train_path)
  eval_patterns = eval_patterns or list(params.eval_path)
  num_epochs = num_epochs or params.num_epochs

  teacher_model = model_lib.get_model(teacher_params_cfg)
  student_model = model_lib.get_model(params)

  train_ds = data_lib.DatasetIterator(
      patterns=train_patterns, params=params,
      batch_size=params.batch_size, seed=params.seed,
  )
  eval_ds = data_lib.DatasetIterator(
      patterns=eval_patterns, params=params,
      batch_size=params.batch_size, shuffle=False,
  )
  decay_steps = train_ds.steps_per_epoch * params.get(
      'num_epochs_for_decay', num_epochs
  )
  trainer = train_lib.Trainer(params=params, out_dir=out_dir, mesh=mesh)
  config_lib.save_params_as_json(out_dir, params)
  state = trainer.init_state(steps_total=max(decay_steps, 1))
  state = state.replace(
      params=init_student_from_teacher(
          state.params, teacher_variables['params'], params
      )
  )

  align_loss = train_lib.make_loss(params)
  student_alpha = float(params.student_alpha)
  distill_alpha = float(params.distill_alpha)
  temperature = float(params.temperature)
  logit_loss = params.get('logit_loss_identifier', 'mean_squared_error')

  def step(state, batch):
    rng = jax.random.fold_in(state.dropout_rng, state.step)
    teacher_out = teacher_model.apply(
        teacher_variables, batch['rows'],
        method=teacher_model.apply_with_intermediates,
    )

    def loss_of(p):
      out = student_model.apply(
          {'params': p}, batch['rows'], train=True,
          rngs={'dropout': rng},
          method=student_model.apply_with_intermediates,
      )
      l_student = align_loss(batch['label'], out['preds'])
      l_distill = losses_lib.distillation_loss(
          teacher_out['logits'], out['logits'],
          temperature=temperature, kind=logit_loss,
      )
      total = student_alpha * l_student + distill_alpha * l_distill
      return total, (l_student, l_distill, out['preds'])

    (loss, (l_s, l_d, preds)), grads = jax.value_and_grad(
        loss_of, has_aux=True
    )(state.params)
    new_state = state.apply_gradients(grads=grads)
    correct, total = metrics_lib.per_example_accuracy_counts(
        batch['label'], preds
    )
    return new_state, {
        'loss': loss,
        'student_loss': l_s,
        'distill_loss': l_d,
        'accuracy_correct': correct,
        'accuracy_total': total,
    }

  # Same declarative rule table as run_training: the student state
  # (params + LAMB moments) shards by partition_rules.DEFAULT_RULES and
  # the batch over the data axis, so distillation scales on the same
  # meshes as training without its own sharding map.
  state_sh = trainer.state_shardings(state)
  batch_sh = trainer._batch_sharding()
  train_step = partition_rules.compile_parallel(
      step,
      in_shardings=(state_sh, {'rows': batch_sh, 'label': batch_sh}),
      out_shardings=(state_sh, None),
      donate_argnums=(0,),
  )

  step_count = 0
  for _ in range(num_epochs):
    for batch in train_ds.epoch():
      batch.pop('name', None)
      state, m = train_step(state, batch)
      step_count += 1
      if step_count % params.get('log_every_n_steps', 100) == 0:
        trainer.log_metrics(
            step_count, 'train', {k: float(v) for k, v in m.items()}
        )
  # Final eval + checkpoint, through the same aggregation as
  # run_training so the metric key set (identity_pred, class
  # accuracies, yield) and best_checkpoint_metric behave identically.
  final = trainer.run_eval(state, eval_ds)
  trainer.save_checkpoint(state, step_count, final)
  return final
