"""Deployable model export via jax.export (StableHLO).

Equivalent of the reference's checkpoint->SavedModel conversion
(reference: deepconsensus/models/convert_to_saved_model.py:67-105):
bakes restored parameters into a fixed-batch serving function, exports
it as portable StableHLO bytes, and copies params.json alongside. The
artifact reloads without any model code, like a SavedModel signature.
"""
from __future__ import annotations

import json
import logging
import os
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import export as jax_export
import ml_collections

from deepconsensus_tpu.calibration import lib as calibration_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import model as model_lib
from deepconsensus_tpu.ops import output_plane

ARTIFACT_NAME = 'serving.stablehlo'


def export_model(
    checkpoint_path: str,
    out_dir: str,
    batch_size: int = 1024,
    variables: Optional[Dict] = None,
    params: Optional[ml_collections.ConfigDict] = None,
    polymorphic_batch: bool = True,
    strict_polymorphic: bool = False,
    inference_dtype: Optional[str] = None,
    quantize_matmuls: Optional[str] = None,
    device_epilogue: bool = True,
    max_base_quality: int = 93,
    dc_calibration: str = 'skip',
) -> str:
  """Exports a serving function; returns the artifact path.

  With device_epilogue (the default) the whole output plane is
  compiled into the artifact: the serving call returns the final uint8
  (base ids, Phred quality) planes — argmax plus the exact
  threshold-table quality (ops/output_plane.py) for the given
  dc_calibration / max_base_quality, which are baked into the program
  and recorded in the metadata (from_exported refuses a load whose
  quality knobs disagree). Without it, the serving call returns
  softmax preds and the host computes qualities, as before. The XLA
  epilogue is used unconditionally here — a Pallas call would pin the
  artifact to one backend's custom-call ABI; StableHLO keeps it
  portable.

  polymorphic_batch exports the batch dimension symbolically, so the
  artifact serves ANY batch size (the reference's SavedModel does
  this; a fixed-batch artifact was the round-2 limitation).
  batch_size is kept in the metadata as the recommended serving batch.
  Falls back to a fixed-batch export if symbolic export fails — unless
  strict_polymorphic, which re-raises so automated pipelines cannot
  silently ship an artifact that rejects every batch size but the
  baked one. The fallback is always surfaced in export_meta.json's
  `polymorphic_batch` field; callers that require a polymorphic
  artifact should assert on it (see load_exported).
  """
  if strict_polymorphic and not polymorphic_batch:
    raise ValueError(
        'strict_polymorphic=True requires polymorphic_batch=True (a '
        'fixed-batch export can never satisfy the strict guarantee).')
  if params is None:
    params = config_lib.read_params_from_json(checkpoint_path)
    config_lib.finalize_params(params, is_training=False)
  if inference_dtype or (quantize_matmuls and quantize_matmuls != 'none'):
    with params.unlocked():
      if inference_dtype:
        params.inference_dtype = inference_dtype
        params.dtype = inference_dtype
      if quantize_matmuls and quantize_matmuls != 'none':
        params.quantize_matmuls = quantize_matmuls
  model = model_lib.get_model(params)

  if variables is None:
    from deepconsensus_tpu.models.checkpoints import load_params

    variables = {'params': load_params(checkpoint_path)}
  # Bake the quantization levers into the exported program: weights
  # are cast/quantized before tracing, so the artifact carries the
  # quantized-effective weights and the metadata below records which
  # levers it was built with (from_exported refuses a mismatched load).
  from deepconsensus_tpu.models import quantize as quantize_lib

  variables, _ = quantize_lib.prepare_inference_variables(variables, params)

  thresholds = None
  if device_epilogue:
    thresholds = output_plane.quality_thresholds(
        calibration_lib.parse_calibration_string(dc_calibration),
        max_base_quality)
    if thresholds is None:
      logging.warning(
          'device epilogue requested but dc_calibration=%r / '
          'max_base_quality=%d is not device-representable; exporting '
          'a pre-epilogue (softmax-preds) artifact instead.',
          dc_calibration, max_base_quality)
      device_epilogue = False

  def serving_fn(rows):
    preds = model.apply(variables, rows)
    if thresholds is None:
      return preds
    return output_plane.phred_epilogue(preds, thresholds)

  static_shape = (batch_size, params.total_rows, params.max_length, 1)
  exported = None
  is_polymorphic = False
  if polymorphic_batch:
    try:
      (b,) = jax_export.symbolic_shape('b')
      exported = jax_export.export(jax.jit(serving_fn))(
          jax.ShapeDtypeStruct(
              (b,) + static_shape[1:], jnp.float32
          )
      )
      is_polymorphic = True
    except Exception as e:  # pragma: no cover - model not batch-polymorphic
      if strict_polymorphic:
        raise RuntimeError(
            'Batch-polymorphic export failed and strict_polymorphic is '
            'set; refusing to fall back to a fixed-batch artifact.'
        ) from e
      logging.warning(
          'Batch-polymorphic export failed (%s: %s); falling back to a '
          'fixed-batch artifact that only serves batch_size=%d.',
          type(e).__name__, e, batch_size)
      exported = None
  if exported is None:
    exported = jax_export.export(jax.jit(serving_fn))(
        jax.ShapeDtypeStruct(static_shape, jnp.float32)
    )
  os.makedirs(out_dir, exist_ok=True)
  artifact = os.path.join(out_dir, ARTIFACT_NAME)
  with open(artifact, 'wb') as f:
    f.write(exported.serialize())
  config_lib.save_params_as_json(out_dir, params)
  with open(os.path.join(out_dir, 'export_meta.json'), 'w') as f:
    json.dump({'batch_size': batch_size, 'rows_shape': static_shape,
               'polymorphic_batch': is_polymorphic,
               'inference_dtype': params.get('inference_dtype', None)
               or 'float32',
               'quantize_matmuls': params.get('quantize_matmuls', None)
               or 'none',
               'device_epilogue': bool(device_epilogue),
               'max_base_quality': int(max_base_quality),
               'dc_calibration': dc_calibration}, f)
  return artifact


def load_exported(out_dir: str) -> Tuple[Callable, Dict]:
  """Loads an exported artifact; returns (callable, meta)."""
  with open(os.path.join(out_dir, ARTIFACT_NAME), 'rb') as f:
    exported = jax_export.deserialize(f.read())
  with open(os.path.join(out_dir, 'export_meta.json')) as f:
    meta = json.load(f)
  return exported.call, meta
