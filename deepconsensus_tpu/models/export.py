"""Deployable model export via jax.export (StableHLO).

Equivalent of the reference's checkpoint->SavedModel conversion
(reference: deepconsensus/models/convert_to_saved_model.py:67-105):
bakes restored parameters into a fixed-batch serving function, exports
it as portable StableHLO bytes, and copies params.json alongside. The
artifact reloads without any model code, like a SavedModel signature.
"""
from __future__ import annotations

import json
import os
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import export as jax_export
import ml_collections

from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import model as model_lib

ARTIFACT_NAME = 'serving.stablehlo'


def export_model(
    checkpoint_path: str,
    out_dir: str,
    batch_size: int = 1024,
    variables: Optional[Dict] = None,
    params: Optional[ml_collections.ConfigDict] = None,
) -> str:
  """Exports a serving function rows->softmax; returns artifact path."""
  if params is None:
    params = config_lib.read_params_from_json(checkpoint_path)
    config_lib.finalize_params(params, is_training=False)
  model = model_lib.get_model(params)
  rows_shape = (batch_size, params.total_rows, params.max_length, 1)

  if variables is None:
    from deepconsensus_tpu.models.checkpoints import load_params

    variables = {'params': load_params(checkpoint_path)}

  def serving_fn(rows):
    return model.apply(variables, rows)

  exported = jax_export.export(jax.jit(serving_fn))(
      jax.ShapeDtypeStruct(rows_shape, jnp.float32)
  )
  os.makedirs(out_dir, exist_ok=True)
  artifact = os.path.join(out_dir, ARTIFACT_NAME)
  with open(artifact, 'wb') as f:
    f.write(exported.serialize())
  config_lib.save_params_as_json(out_dir, params)
  with open(os.path.join(out_dir, 'export_meta.json'), 'w') as f:
    json.dump({'batch_size': batch_size, 'rows_shape': rows_shape}, f)
  return artifact


def load_exported(out_dir: str) -> Tuple[Callable, Dict]:
  """Loads an exported artifact; returns (callable, meta)."""
  with open(os.path.join(out_dir, ARTIFACT_NAME), 'rb') as f:
    exported = jax_export.deserialize(f.read())
  with open(os.path.join(out_dir, 'export_meta.json')) as f:
    meta = json.load(f)
  return exported.call, meta
