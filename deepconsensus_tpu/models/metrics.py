"""Alignment metric (approximate pbmm2 identity) and accuracy metrics.

AlignmentMetric runs a Needleman-Wunsch alignment with affine gaps
(scores A=2, B=5, o=5, e=4 approximating pbmm2) as a wavefront scan with
three states (M/I/D), records per-antidiagonal argmax directions, then
backtracks to per-example match/insertion/deletion counts and percent
identity (reference: deepconsensus/models/losses_and_metrics.py:
666-1111). Both recursions are lax.scans and run on device.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from deepconsensus_tpu import constants
from deepconsensus_tpu.models.losses import left_shift_sequence
from deepconsensus_tpu.ops import wavefront

Array = jnp.ndarray


def _preprocess_true(y_true: Array) -> Tuple[Array, Array]:
  y_true = left_shift_sequence(y_true.astype(jnp.int32))
  lens = jnp.sum((y_true != constants.GAP_INT).astype(jnp.int32), -1)
  return y_true, lens


def _preprocess_pred(y_pred_scores: Array) -> Tuple[Array, Array]:
  y_pred = jnp.argmax(y_pred_scores, axis=-1).astype(jnp.int32)
  y_pred = left_shift_sequence(y_pred)
  lens = jnp.sum((y_pred != constants.GAP_INT).astype(jnp.int32), -1)
  return y_pred, lens


class AlignmentMetric:
  """NW affine-gap alignment + identity metrics."""

  def __init__(
      self,
      matching_score: float = 2.0,
      mismatch_penalty: float = 5.0,
      gap_open_penalty: float = 5.0,
      gap_extend_penalty: float = 4.0,
  ):
    self.matching_score = matching_score
    self.mismatch_penalty = mismatch_penalty
    # pbmm2 charges o + k*e; the DP uses o + (k-1)*e, so fold one extend
    # into the open (reference: losses_and_metrics.py:698-701).
    self.gap_open_penalty = gap_open_penalty + gap_extend_penalty
    self.gap_extend_penalty = gap_extend_penalty

  def alignment(
      self, y_true: Array, y_pred_scores: Array
  ) -> Tuple[Array, Array, Dict[str, Array]]:
    """Returns (v_opt [B], paths [B, m+1, n+1], metric dict)."""
    dtype = jnp.float32
    inf = jnp.asarray(1e9, dtype)
    b, m = y_true.shape
    n = y_pred_scores.shape[1]

    y_true, y_true_lens = _preprocess_true(y_true)
    y_pred, y_pred_lens = _preprocess_pred(y_pred_scores)

    subs_costs = jnp.where(
        y_true[:, :, None] == y_pred[:, None, :],
        jnp.asarray(self.matching_score, dtype),
        jnp.asarray(-self.mismatch_penalty, dtype),
    )  # [B, m, n]
    subs_w = wavefront.wavefrontify(subs_costs)  # [m+n-1, B, m]

    go = jnp.asarray(self.gap_open_penalty, dtype)
    ge = jnp.asarray(self.gap_extend_penalty, dtype)

    i_range = jnp.arange(m + 1)
    k_end = y_true_lens + y_pred_lens
    samp = jnp.arange(b)

    # ---- init (k=0, k=1) --------------------------------------------
    # v_all_*: [B, 3, *] for states (M, I, D).
    v_all_p2 = jnp.full((b, 3, m), -inf).at[:, 0, 0].set(0.0)
    v_all_p1 = jnp.full((b, 3, m + 1), -inf)
    v_all_p1 = v_all_p1.at[:, 1, 0].set(-go)
    v_all_p1 = v_all_p1.at[:, 2, 1].set(-go)

    dir0 = jnp.full((b, 3, m + 1), -2, jnp.int8).at[:, 0, 0].set(-1)
    dir1 = jnp.full((b, 3, m + 1), -2, jnp.int8)
    dir1 = dir1.at[:, 1, 0].set(0)
    dir1 = dir1.at[:, 2, 1].set(0)

    def argmax_over_states(v):  # v: [B, 3, X]
      return jnp.max(v, axis=1), jnp.argmax(v, axis=1).astype(jnp.int8)

    def maybe_update(k, v_opt, m_opt, v_all_p1):
      v_k, m_k = argmax_over_states(v_all_p1)  # [B, m+1]
      v_at = jnp.take_along_axis(v_k, y_true_lens[:, None], 1)[:, 0]
      m_at = jnp.take_along_axis(m_k, y_true_lens[:, None], 1)[:, 0]
      cond = k_end == k
      return (
          jnp.where(cond, v_at, v_opt),
          jnp.where(cond, m_at.astype(jnp.int32), m_opt),
      )

    v_opt = jnp.zeros((b,), dtype)
    m_opt = jnp.full((b,), -1, jnp.int32)
    v_opt, m_opt = maybe_update(1, v_opt, m_opt, v_all_p1)

    ks = jnp.arange(2, m + n + 1)

    def fwd_step(carry, xs):
      v_all_p2, v_all_p1, v_opt, m_opt = carry
      k, subs_k = xs
      j_range = k - i_range
      valid = (j_range >= 0) & (j_range <= n)  # [m+1]

      o_match = v_all_p2 + subs_k[:, None, :]  # [B, 3, m]
      o_ins = v_all_p1[:, :2] - jnp.stack([go, ge])[None, :, None]
      v_all_p2_next = v_all_p1[:, :, :-1]
      o_del = v_all_p2_next - jnp.stack([go, go, ge])[None, :, None]

      v_match, dir_match = argmax_over_states(o_match)  # [B, m]
      v_ins, dir_ins = argmax_over_states(o_ins)  # [B, m+1]
      v_del, dir_del = argmax_over_states(o_del)  # [B, m]

      pad_val = jnp.full((b, 1), -inf)
      pad_dir = jnp.full((b, 1), -2, jnp.int8)
      v_match = jnp.concatenate([pad_val, v_match], axis=1)
      v_del = jnp.concatenate([pad_val, v_del], axis=1)
      dir_match = jnp.concatenate([pad_dir, dir_match], axis=1)
      dir_del = jnp.concatenate([pad_dir, dir_del], axis=1)

      v_new = jnp.where(
          valid[None, None, :],
          jnp.stack([v_match, v_ins, v_del], axis=1),
          -inf,
      )
      dirs = jnp.stack([dir_match, dir_ins, dir_del], axis=1)
      v_opt, m_opt = maybe_update(k, v_opt, m_opt, v_new)
      return (v_all_p2_next, v_new, v_opt, m_opt), dirs

    (_, _, v_opt, m_opt), dir_rows = jax.lax.scan(
        fwd_step, (v_all_p2, v_all_p1, v_opt, m_opt), (ks, subs_w),
        unroll=wavefront.SCAN_UNROLL,
    )
    # dir_all[k] for k = 0..m+n.
    dir_all = jnp.concatenate([dir0[None], dir1[None], dir_rows], axis=0)

    # ---- backtracking ------------------------------------------------
    steps_k = jnp.asarray([-2, -1, -1], jnp.int32)
    steps_i = jnp.asarray([-1, 0, -1], jnp.int32)
    trans_enc = jnp.asarray(
        [[1, 1, 1], [2, 3, 2], [4, 4, 5]], jnp.int32
    )  # [state_curr, state_prev] -> edge id

    def bwd_step(carry, xs):
      k, dirs_k = xs  # dirs_k: [B, 3, m+1]
      k_opt, i_opt, m_opt = carry
      safe_m = jnp.maximum(m_opt, 0)
      safe_i = jnp.maximum(i_opt, 0)
      k_opt_n = k_opt + steps_k[safe_m]
      i_opt_n = i_opt + steps_i[safe_m]
      m_opt_n = dirs_k[samp, safe_m, safe_i].astype(jnp.int32)
      safe_m_n = jnp.maximum(m_opt_n, 0)
      edges_n = trans_enc[safe_m, safe_m_n]
      reached_start = m_opt_n == -1
      cond = (k_opt == k) & ~reached_start
      paths_row = jnp.where(
          cond[:, None],
          jnp.stack([samp, i_opt, k_opt - i_opt, edges_n], axis=-1),
          jnp.zeros((b, 4), jnp.int32),
      )
      k_opt = jnp.where(cond, k_opt_n, k_opt)
      i_opt = jnp.where(cond, i_opt_n, i_opt)
      m_opt = jnp.where(cond, m_opt_n, m_opt)
      return (k_opt, i_opt, m_opt), paths_row

    ks_rev = jnp.arange(m + n, -1, -1)
    (_, _, _), path_rows = jax.lax.scan(
        bwd_step, (k_end, y_true_lens, m_opt), (ks_rev, dir_all[ks_rev]),
        unroll=wavefront.SCAN_UNROLL,
    )
    paths_sp = path_rows.reshape(-1, 4)
    paths = jnp.zeros((b, m + 1, n + 1), jnp.int32).at[
        paths_sp[:, 0], paths_sp[:, 1], paths_sp[:, 2]
    ].add(paths_sp[:, 3])

    # ---- metrics -----------------------------------------------------
    matches_mask = paths == 1
    ins_mask = (paths == 2) | (paths == 3)
    del_mask = (paths == 4) | (paths == 5)
    correct = matches_mask[:, 1:, 1:] & (subs_costs > 0)

    def count(t):
      return jnp.sum(t.astype(jnp.int32), axis=(1, 2))

    metric_values = {
        'num_matches': count(matches_mask),
        'num_insertions': count(ins_mask),
        'num_deletions': count(del_mask),
        'num_correct_matches': count(correct),
    }
    metric_values['alignment_length'] = (
        metric_values['num_matches']
        + metric_values['num_insertions']
        + metric_values['num_deletions']
    )
    unsafe_pid = metric_values['num_correct_matches'] / jnp.maximum(
        metric_values['alignment_length'], 1
    )
    metric_values['pid'] = jnp.where(
        metric_values['alignment_length'] > 0,
        unsafe_pid.astype(dtype),
        jnp.asarray(1.0, dtype),
    )
    return v_opt, paths, metric_values


def per_batch_identity(metric_values: Dict[str, Array]) -> Array:
  """Batch-pooled identity (reference: losses_and_metrics.py:1101-1111)."""
  total = jnp.sum(metric_values['alignment_length'])
  pid = jnp.sum(metric_values['num_correct_matches']) / jnp.maximum(total, 1)
  return jnp.where(total > 0, pid.astype(jnp.float32), 1.0)


def batch_identity_ccs_pred(
    ccs: Array,
    y_pred_scores: Array,
    y_true: Array,
    alignment_metric: AlignmentMetric,
) -> Tuple[Array, Array]:
  """Identity of CCS and of the prediction vs truth
  (reference: losses_and_metrics.py:1061-1098)."""
  _, _, mv_pred = alignment_metric.alignment(y_true, y_pred_scores)
  ccs_oh = jax.nn.one_hot(
      ccs.astype(jnp.int32), constants.SEQ_VOCAB_SIZE, dtype=jnp.float32
  )
  _, _, mv_ccs = alignment_metric.alignment(y_true, ccs_oh)
  return per_batch_identity(mv_ccs), per_batch_identity(mv_pred)


def per_example_accuracy_counts(
    y_true: Array, y_pred_scores: Array
) -> Tuple[Array, Array]:
  """(correct_examples, total_examples) after left-shifting both
  (reference PerExampleAccuracy: losses_and_metrics.py:37-65)."""
  y_true = left_shift_sequence(y_true.astype(jnp.int32))
  y_pred = left_shift_sequence(
      jnp.argmax(y_pred_scores, axis=-1).astype(jnp.int32)
  )
  row_correct = jnp.all(y_true == y_pred, axis=-1)
  return jnp.sum(row_correct.astype(jnp.int32)), y_true.shape[0]


def per_class_accuracy_counts(
    y_true: Array, y_pred_scores: Array, class_value: int
) -> Tuple[Array, Array]:
  """(correct, total) over positions whose label is class_value
  (reference PerClassAccuracy: losses_and_metrics.py:68-89)."""
  y_pred = jnp.argmax(y_pred_scores, axis=-1).astype(jnp.int32)
  mask = y_true.astype(jnp.int32) == class_value
  correct = (y_pred == y_true.astype(jnp.int32)) & mask
  return jnp.sum(correct.astype(jnp.int32)), jnp.sum(mask.astype(jnp.int32))


@dataclasses.dataclass
class Mean:
  """Tiny streaming mean accumulator (host side)."""

  total: float = 0.0
  count: float = 0.0

  def update(self, value, weight=1.0):
    self.total += float(value) * float(weight)
    self.count += float(weight)

  def result(self) -> float:
    return self.total / self.count if self.count else 0.0

  def reset(self):
    self.total = 0.0
    self.count = 0.0


@dataclasses.dataclass
class YieldOverCCS:
  """Batches where identity >= threshold, DC vs CCS
  (reference YieldOverCCSMetric: losses_and_metrics.py:1114-1167)."""

  quality_threshold: float = 0.997
  yield_dc: float = 0.0
  yield_ccs: float = 0.0

  def update(self, identity_ccs: float, identity_pred: float):
    self.yield_dc += float(identity_pred >= self.quality_threshold)
    self.yield_ccs += float(identity_ccs >= self.quality_threshold)

  def result(self) -> float:
    return self.yield_dc / self.yield_ccs if self.yield_ccs else 0.0

  def reset(self):
    self.yield_dc = 0.0
    self.yield_ccs = 0.0
