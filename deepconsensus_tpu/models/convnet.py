"""Convolutional model family: pre-activation ResNet-v2 backbones.

Counterpart of the reference's ConvNet wrapper over keras ResNet50/101/
152-V2 (reference: deepconsensus/models/networks.py:95-170): the pileup
tensor is treated as an image, run through a ResNet-v2 trunk with global
average pooling, optionally concatenated with the SN rows, and mapped to
per-position vocab logits. Implemented natively in Flax (no pretrained
weights, matching the reference's weights=None)."""
from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
import ml_collections

from deepconsensus_tpu import constants

RESNET_DEPTHS = {
    'resnet50': (3, 4, 6, 3),
    'resnet101': (3, 4, 23, 3),
    'resnet152': (3, 8, 36, 3),
}


class BottleneckV2(nn.Module):
  """Pre-activation bottleneck: BN-ReLU-1x1 / BN-ReLU-3x3 / BN-ReLU-1x1."""

  filters: int
  strides: Tuple[int, int] = (1, 1)
  project: bool = False
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x, train: bool):
    preact = nn.BatchNorm(
        use_running_average=not train, dtype=jnp.float32, name='preact_bn'
    )(x)
    preact = nn.relu(preact)
    if self.project or self.strides != (1, 1):
      shortcut = nn.Conv(
          self.filters * 4, (1, 1), strides=self.strides, dtype=self.dtype,
          name='shortcut',
      )(preact)
    else:
      shortcut = x
    y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype,
                name='conv1')(preact)
    y = nn.BatchNorm(use_running_average=not train, dtype=jnp.float32,
                     name='bn1')(y)
    y = nn.relu(y)
    y = nn.Conv(self.filters, (3, 3), strides=self.strides, use_bias=False,
                dtype=self.dtype, name='conv2')(y)
    y = nn.BatchNorm(use_running_average=not train, dtype=jnp.float32,
                     name='bn2')(y)
    y = nn.relu(y)
    y = nn.Conv(self.filters * 4, (1, 1), dtype=self.dtype, name='conv3')(y)
    return shortcut + y


class ResNetV2Trunk(nn.Module):
  stage_sizes: Sequence[int]
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x, train: bool):
    x = nn.Conv(64, (7, 7), strides=(2, 2), use_bias=True,
                dtype=self.dtype, name='stem')(x)
    x = nn.max_pool(x, (3, 3), strides=(2, 2), padding='SAME')
    for stage, n_blocks in enumerate(self.stage_sizes):
      filters = 64 * 2**stage
      for block in range(n_blocks):
        strides = (2, 2) if block == 0 and stage > 0 else (1, 1)
        x = BottleneckV2(
            filters=filters,
            strides=strides,
            project=block == 0,
            dtype=self.dtype,
            name=f'stage{stage}_block{block}',
        )(x, train)
    x = nn.BatchNorm(use_running_average=not train, dtype=jnp.float32,
                     name='final_bn')(x)
    x = nn.relu(x)
    return jnp.mean(x, axis=(1, 2))  # global average pool


class ConvNetModel(nn.Module):
  """Pileup-as-image ResNet producing per-position vocab softmax."""

  params: ml_collections.FrozenConfigDict

  @nn.compact
  def __call__(self, rows: jnp.ndarray, train: bool = False) -> jnp.ndarray:
    p = self.params
    dtype = jnp.dtype(p.get('dtype', 'float32'))
    if rows.ndim == 3:
      rows = rows[..., None]
    x = rows.astype(dtype)
    # Scale like the keras preprocess_input(mode='tf'): x/127.5 - 1.
    x = x / 127.5 - 1.0
    trunk = ResNetV2Trunk(
        RESNET_DEPTHS[p.get('conv_model', 'resnet50')], dtype=dtype,
        name='trunk',
    )
    feats = trunk(x, train)
    if p.use_sn:
      sn_rows = rows[:, -4:, :, 0].reshape(rows.shape[0], -1)
      feats = jnp.concatenate([feats, sn_rows.astype(dtype)], axis=1)
    out = nn.Dense(
        p.max_length * constants.SEQ_VOCAB_SIZE, dtype=jnp.float32,
        name='head',
    )(feats.astype(jnp.float32))
    out = out.reshape(rows.shape[0], p.max_length, constants.SEQ_VOCAB_SIZE)
    return jnp.asarray(jnp.exp(nn.log_softmax(out, axis=-1)))
