"""Port reference TensorFlow checkpoints into flax parameters.

The reference publishes tf.train.Checkpoint weights for its
EncoderOnlyLearnedValuesTransformer (variable inventory per
testdata/model/checkpoint-1.index). Kernel layouts line up one-to-one
with this framework's modules (EinsumDense [E,N,H]/[N,H,E] match
DenseGeneral; embeddings/[vocab,width]; LayerNorm gamma/beta ->
scale/bias), so porting is a pure renaming.

The bundled testdata checkpoints are stripped of their data blobs, so
round-1 tests validate the complete name/shape mapping against the
.index inventory; `port_checkpoint` performs the actual value transfer
when run against a full checkpoint.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

FlaxPath = Tuple[str, ...]

_SUFFIX = '/.ATTRIBUTES/VARIABLE_VALUE'

_STATIC_MAP: Dict[str, FlaxPath] = {
    'model/bases_embedding_layer/embeddings':
        ('bases_embedding', 'embedding'),
    'model/pw_embedding_layer/embeddings': ('pw_embedding', 'embedding'),
    'model/ip_embedding_layer/embeddings': ('ip_embedding', 'embedding'),
    'model/sn_embedding_layer/embeddings': ('sn_embedding', 'embedding'),
    'model/strand_embedding_layer/embeddings':
        ('strand_embedding', 'embedding'),
    'model/ccs_base_quality_scores_embedding_layer/embeddings':
        ('ccs_bq_embedding', 'embedding'),
    'model/transformer_input_condenser/kernel': ('condenser', 'kernel'),
    'model/fc1/kernel': ('logits', 'kernel'),
    'model/fc1/bias': ('logits', 'bias'),
    'model/encoder_stack/output_normalization/gamma':
        ('encoder', 'output_normalization', 'scale'),
    'model/encoder_stack/output_normalization/beta':
        ('encoder', 'output_normalization', 'bias'),
}

_ATTN_DENSE = {
    'query_dense_layer': 'query',
    'key_dense_layer': 'key',
    'value_dense_layer': 'value',
    'output_dense_layer': 'output_transform',
}

_FFN_DENSE = {
    'filter_dense_layer': 'filter_layer',
    'output_dense_layer': 'output_layer',
}


def tf_name_to_flax_path(name: str) -> Optional[FlaxPath]:
  """Maps one reference checkpoint variable name to a flax param path.

  Returns None for non-model variables (optimizer slots, counters).
  """
  if not name.endswith(_SUFFIX):
    return None
  base = name[: -len(_SUFFIX)]
  if '.OPTIMIZER_SLOT' in base or base in (
      'save_counter', '_CHECKPOINTABLE_OBJECT_GRAPH'
  ):
    return None
  if base in _STATIC_MAP:
    return _STATIC_MAP[base]

  # Encoder layers: model/encoder_stack/layers/{n}/{0|1}/...
  m = re.fullmatch(
      r'model/encoder_stack/layers/(\d+)/([01])/(.*)', base
  )
  if not m:
    return None
  layer, sublayer, rest = int(m.group(1)), int(m.group(2)), m.group(3)
  wrapper = 'attention_wrapper' if sublayer == 0 else 'ffn_wrapper'
  # Pre-LN checkpoints (rezero=False) store a per-sublayer LayerNorm
  # (reference encoder_stack.py:62) instead of the rezero alpha.
  mm = re.fullmatch(r'layer_norm/(gamma|beta)', rest)
  if mm:
    part = 'scale' if mm.group(1) == 'gamma' else 'bias'
    return ('encoder', f'{wrapper}_{layer}', 'layer_norm', part)
  if sublayer == 0:  # attention
    if rest == 'alpha':
      return ('encoder', f'attention_wrapper_{layer}', 'alpha')
    mm = re.fullmatch(r'layer/(\w+)/(kernel|bias)', rest)
    if mm and mm.group(1) in _ATTN_DENSE:
      return (
          'encoder', f'self_attention_{layer}', _ATTN_DENSE[mm.group(1)],
          mm.group(2),
      )
  else:  # ffn
    if rest == 'alpha':
      return ('encoder', f'ffn_wrapper_{layer}', 'alpha')
    mm = re.fullmatch(r'layer/(\w+)/(kernel|bias)', rest)
    if mm and mm.group(1) in _FFN_DENSE:
      return (
          'encoder', f'ffn_{layer}', _FFN_DENSE[mm.group(1)], mm.group(2),
      )
  return None


def map_checkpoint_names(
    tf_checkpoint_prefix: str,
) -> Tuple[Dict[str, FlaxPath], List[str]]:
  """Maps every model variable in a TF checkpoint index.

  Returns (mapping, unmapped_model_variables).
  """
  import tensorflow as tf

  mapping: Dict[str, FlaxPath] = {}
  unmapped: List[str] = []
  for name, _shape in tf.train.list_variables(tf_checkpoint_prefix):
    path = tf_name_to_flax_path(name)
    if path is not None:
      mapping[name] = path
    elif (
        name.endswith(_SUFFIX)
        and '.OPTIMIZER_SLOT' not in name
        and not name.startswith(('save_counter', '_CHECKPOINTABLE'))
        and 'optimizer' not in name
    ):
      unmapped.append(name)
  return mapping, unmapped


def port_checkpoint(tf_checkpoint_prefix: str, flax_params):
  """Copies TF checkpoint values into a (template) flax params tree.

  Raises if any model variable cannot be mapped or shapes mismatch.
  """
  import numpy as np
  import tensorflow as tf

  mapping, unmapped = map_checkpoint_names(tf_checkpoint_prefix)
  if unmapped:
    raise ValueError(f'unmapped reference variables: {unmapped}')
  reader = tf.train.load_checkpoint(tf_checkpoint_prefix)
  out = flax_params
  import jax

  def set_path(tree, path, value):
    node = tree
    for key in path[:-1]:
      node = node[key]
    expected = np.asarray(node[path[-1]])
    if tuple(expected.shape) != tuple(value.shape):
      raise ValueError(
          f'shape mismatch at {path}: {expected.shape} vs {value.shape}'
      )
    node[path[-1]] = value.astype(expected.dtype)

  assigned = set()
  for tf_name, path in mapping.items():
    value = reader.get_tensor(tf_name)
    set_path(out, path, value)
    assigned.add(path)

  # Reverse coverage: every flax leaf must have been overwritten, or
  # the result would silently mix ported weights with init values
  # (e.g. a config enabling a module the TF checkpoint lacks).
  all_paths = {
      tuple(str(getattr(k, 'key', k)) for k in path)
      for path, _ in jax.tree_util.tree_flatten_with_path(flax_params)[0]
  }
  missing = sorted(all_paths - assigned)
  if missing:
    raise ValueError(
        'flax parameters not covered by the TF checkpoint (config/'
        f'checkpoint mismatch): {missing}'
    )
  return out


def port_to_orbax(tf_checkpoint_prefix: str, params_json: str,
                  out_dir: str) -> str:
  """Ports a reference TF checkpoint to a servable orbax checkpoint.

  Writes <out_dir>/checkpoints/checkpoint-0 + params.json so the result
  drives `dctpu run --checkpoint <out_dir>/checkpoints/checkpoint-0`
  (or warm-starts training) directly.
  """
  import os

  import jax
  import jax.numpy as jnp
  import numpy as np
  import orbax.checkpoint as ocp

  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import model as model_lib

  params = config_lib.read_params_from_json(params_json)
  config_lib.finalize_params(params, is_training=False)
  model = model_lib.get_model(params)
  rows = jnp.zeros(
      (1, params.total_rows, params.max_length, 1), jnp.float32
  )
  template = jax.tree.map(
      np.asarray,
      model.init(jax.random.PRNGKey(0), rows)['params'],
  )
  ported = port_checkpoint(tf_checkpoint_prefix, template)
  path = os.path.join(
      os.path.abspath(out_dir), 'checkpoints', 'checkpoint-0'
  )
  checkpointer = ocp.StandardCheckpointer()
  checkpointer.save(path, {'params': ported}, force=True)
  wait = getattr(checkpointer, 'wait_until_finished', None)
  if wait is not None:
    wait()
  # Never clobber the source config: when --params already points at
  # <out_dir>/params.json, the stripped/derived rewrite would destroy
  # the original (losing e.g. its dataset keys).
  target_json = os.path.join(os.path.abspath(out_dir), 'params.json')
  source_json = (
      params_json if params_json.endswith('.json')
      else os.path.join(params_json, 'params.json')
  )
  if os.path.abspath(source_json) != target_json:
    config_lib.save_params_as_json(out_dir, params)
  return path


if __name__ == '__main__':
  # Single source of truth for flags/dispatch: the dctpu CLI.
  import sys

  from deepconsensus_tpu import cli

  raise SystemExit(cli.main(['port', *sys.argv[1:]]))
