"""Model/hparam configuration system.

Mirrors the reference's {model}+{dataset} ConfigDict presets and the
hardware-dependent parameter derivation of modify_params (reference:
deepconsensus/models/model_configs.py:40-379,
models/model_utils.py:237-354, models/transformer_basic_params.py:33-97),
with TPU-native additions: compute dtype, mesh axes, and kernel toggles.

params.json written next to checkpoints is the source of truth at
inference time, exactly like the reference (model_utils.py:434-476).
"""
from __future__ import annotations

import json
import os
from typing import Optional

import ml_collections

from deepconsensus_tpu.preprocess.pileup import total_rows as _total_rows

# Canonical window geometry. All shape literals live here (dclint's
# shape-literals checker fences them out of the rest of the tree):
# DEFAULT_MAX_LENGTH is the reference window length (reference:
# model_configs.py max_length=100); FUSED_MAX_WINDOW_LEN is the VMEM
# row budget of the Pallas fused hot path — buckets at or under it run
# fused, longer buckets fall back to XLA.
DEFAULT_MAX_LENGTH = 100
FUSED_MAX_WINDOW_LEN = 128
# Default bucket set when params.window_buckets is requested but unset
# by a config: the reference L=100 plus one 2x bucket (the distill
# configs' target geometry, arxiv 2211.09862).
DEFAULT_WINDOW_BUCKETS = (100, 200)
# Long-insert geometry. Training windows at or past
# RING_ATTENTION_MIN_LEN route BandedSelfAttention through the
# blockwise ring-attention scan (parallel/ring_attention.py) instead
# of materializing the [B, N, L, L] logits: at L=500 the full logits
# tensor no longer fits the fused kernel's VMEM tiling, and the
# banded structure makes the blockwise online-softmax pass both exact
# and memory-bounded. Buckets below the crossover (100, 200) keep the
# XLA einsum path, whose fused/Pallas eligibility is decided
# downstream by _fused_hotpath_eligible.
RING_ATTENTION_MIN_LEN = 256
LONG_INSERT_WINDOW_LEN = 500

# Quantization acceptance gates — the ONE shared home. The runtime
# gates (models/flywheel.py) and the acceptance tests
# (tests/test_quantized_inference.py) both import these so the
# documented thresholds can never drift between test and release gate:
# int8 — held-out alignment identity within this delta of the f32
# baseline; bf16 — per-base Phred QVs within this many units of f32 on
# argmax-agreeing positions.
INT8_IDENTITY_GATE = 0.002
BF16_QV_GATE = 3


def normalize_window_buckets(buckets, max_length: int):
  """Validate and canonicalize a window-bucket spec.

  None/empty means bucketing is off: the single-shape pipeline runs
  exactly as before with one bucket equal to max_length. A non-empty
  spec must be strictly ascending positive ints whose smallest entry
  equals params.max_length — max_length stays the featurize stride and
  base window geometry; buckets only widen the variable-width (smart
  window) path.
  """
  if not buckets:
    return (int(max_length),)
  if isinstance(buckets, str):
    # '--set window_buckets=100,200' reaches here as the raw string;
    # accept the same comma form as the dedicated CLI flag.
    buckets = [b for b in buckets.replace(',', ' ').split()]
  out = tuple(int(b) for b in buckets)
  if any(b <= 0 for b in out):
    raise ValueError(f'window_buckets must be positive ints, got {out}')
  if list(out) != sorted(set(out)):
    raise ValueError(
        f'window_buckets must be strictly ascending, got {out}')
  if out[0] != int(max_length):
    raise ValueError(
        f'smallest window bucket {out[0]} must equal max_length '
        f'{max_length} (max_length is the featurize stride)')
  return out


def resolve_window_buckets(params):
  """Bucket set for a params object: normalized params.window_buckets,
  or the single-bucket (max_length,) when unset."""
  buckets = getattr(params, 'window_buckets', None)
  return normalize_window_buckets(buckets, int(params.max_length))


def bucket_for(width: int, buckets):
  """Smallest bucket that fits `width`, or None when it overflows all
  buckets (the caller's overflow-skip path)."""
  for b in buckets:
    if width <= b:
      return int(b)
  return None


# Transformer size presets (reference: transformer_basic_params.py).
TRANSFORMER_SIZE_PARAMS = {
    'tiny': dict(
        num_hidden_layers=6,
        num_heads=4,
        filter_size=256,
    ),
    'base': dict(
        num_hidden_layers=6,
        num_heads=8,
        filter_size=2048,
    ),
    'big': dict(
        num_hidden_layers=6,
        num_heads=16,
        filter_size=4096,
    ),
}


def _set_base_transformer_hparams(params):
  params.model_name = 'transformer'
  params.add_pos_encoding = True
  params.num_heads = 2
  params.layer_norm = False
  params.rezero = True
  params.condense_transformer_input = False
  params.transformer_model_size = 'base'
  # Band half-width; full band is 2*attn_win_size+1 columns.
  params.attn_win_size = 12

  params.num_channels = 1
  params.per_base_hidden_size = 1
  params.pw_hidden_size = 1
  params.ip_hidden_size = 1
  params.sn_hidden_size = 1
  params.ccs_bq_hidden_size = 1
  params.strand_hidden_size = 1

  params.layer_postprocess_dropout = 0.1
  params.attention_dropout = 0.1
  params.relu_dropout = 0.1

  params.batch_size = 256
  params.num_epochs = 9
  params.num_epochs_for_decay = 9
  params.buffer_size = 1_000_000

  params.initial_learning_rate = 3.6246e-3
  params.end_learning_rate = 2.86594e-5
  params.warmup_steps = 35536
  params.weight_decay_rate = 6.9868e-3
  params.beta_1 = 0.9
  params.beta_2 = 0.999
  params.epsilon = 1e-6


def _set_transformer_learned_embeddings_hparams(params):
  _set_base_transformer_hparams(params)
  params.model_name = 'transformer_learn_values'
  params.per_base_hidden_size = 8
  params.pw_hidden_size = 8
  params.ip_hidden_size = 8
  params.strand_hidden_size = 2
  params.sn_hidden_size = 8
  params.ccs_bq_hidden_size = 8
  params.condense_transformer_input = True
  params.transformer_input_size = 280


def _set_transformer_learned_embeddings_distill_hparams(params):
  _set_transformer_learned_embeddings_hparams(params)
  params.model_name = 'transformer_learn_values_distill'
  params.num_hidden_layers = 5
  params.filter_size = 2048
  params.layer_postprocess_dropout = 0.0
  params.attention_dropout = 0.1
  params.relu_dropout = 0.0
  params.init_encoder_stack = True
  params.init_nonencoder_layers = True
  params.teacher_encoder_layers = [1, 2, 3, 4, 5]
  params.student_encoder_layers = [0, 1, 2, 3, 4]
  params.warmup_steps = 0
  params.distill_alpha = 1.0e5
  params.student_alpha = 1.0
  params.temperature = 1.0
  params.logit_loss_identifier = 'mean_squared_error'


def _set_base_fc_hparams(params):
  params.model_name = 'fc'
  params.fc_size = [256, 512, 256, 128]
  params.fc_dropout = 0.0
  params.num_channels = 1
  params.per_base_hidden_size = 1
  params.pw_hidden_size = 1
  params.ip_hidden_size = 1
  params.strand_hidden_size = 1
  params.ccs_bq_hidden_size = 1
  params.sn_hidden_size = 1
  params.l2 = 0.0
  params.batch_size = 256
  params.num_epochs = 15
  params.num_epochs_for_decay = 15
  params.buffer_size = 1_000_000
  params.initial_learning_rate = 3.6246e-3
  params.end_learning_rate = 2.86594e-5
  params.warmup_steps = 35536
  params.weight_decay_rate = 6.9868e-3
  params.beta_1 = 0.9
  params.beta_2 = 0.999
  params.epsilon = 1e-6


def _set_base_conv_hparams(params):
  """Convolutional (ResNet-v2) model family."""
  params.model_name = 'conv_net'
  params.conv_model = 'resnet50'
  params.num_channels = 1
  params.per_base_hidden_size = 1
  params.pw_hidden_size = 1
  params.ip_hidden_size = 1
  params.strand_hidden_size = 1
  params.ccs_bq_hidden_size = 1
  params.sn_hidden_size = 1
  params.batch_size = 256
  params.num_epochs = 9
  params.num_epochs_for_decay = 9
  params.buffer_size = 1_000_000
  params.initial_learning_rate = 3.6246e-3
  params.end_learning_rate = 2.86594e-5
  params.warmup_steps = 35536
  params.weight_decay_rate = 6.9868e-3
  params.beta_1 = 0.9
  params.beta_2 = 0.999
  params.epsilon = 1e-6


_TESTDATA = '/root/reference/deepconsensus/testdata'


def _set_test_data_hparams(params):
  params.train_path = [
      os.path.join(_TESTDATA, 'human_1m/tf_examples/train/*')
  ]
  params.eval_path = params.train_path
  params.test_path = params.train_path
  params.inference_path = os.path.join(
      _TESTDATA, 'human_1m/tf_examples/inference/*'
  )
  params.n_examples_train = 253
  params.n_examples_eval = 253
  params.max_passes = 20
  params.batch_size = 1
  params.num_epochs = 1
  params.buffer_size = 10
  if params.model_name == 'fc':
    params.fc_size = [4, 4]


def _set_test_bq_data_hparams(params):
  _set_test_data_hparams(params)
  params.use_ccs_bq = True
  params.train_path = [
      os.path.join(_TESTDATA, 'human_1m/tf_examples_bq/train/*')
  ]
  params.eval_path = params.train_path
  params.test_path = params.train_path
  params.inference_path = os.path.join(
      _TESTDATA, 'human_1m/tf_examples_bq/inference/*'
  )


def _set_custom_data_hparams(params):
  params.tf_dataset = ['/path_to_training_data']
  params.max_passes = 20


def get_config(config_name: Optional[str] = None) -> ml_collections.ConfigDict:
  """Builds a ConfigDict for '{model}+{dataset}' preset names."""
  params = ml_collections.ConfigDict()

  params.trial = 1
  params.rezero = False

  params.PW_MAX = 255
  params.IP_MAX = 255
  params.SN_MAX = 500
  params.CCS_BQ_MAX = 95
  params.STRAND_MAX = 2

  params.use_bases = True
  params.use_pw = True
  params.use_ip = True
  params.use_strand = True
  params.use_sn = True
  params.use_ccs = True
  params.use_ccs_bq = False
  params.per_base_hidden_size = 1
  params.pw_hidden_size = 1
  params.ip_hidden_size = 1
  params.sn_hidden_size = 1
  params.strand_hidden_size = 1
  params.ccs_bq_hidden_size = 1

  params.total_rows = ml_collections.config_dict.placeholder(int)

  params.vocab_size = 5
  params.seed = 1
  params.remove_label_gaps = False
  # Use the shard-interleaved StreamingDataset for training input
  # instead of the eager in-memory DatasetIterator. Requires
  # n_examples_train to size the per-epoch step budget
  # (--set streaming=true --set n_examples_train=N).
  params.streaming = False
  # Streaming-loader decode processes (0 = in-process decode). Each
  # worker sustains ~10k ex/s (gzip + minimal proto parse, measured
  # per-core); size to the mesh's consumption rate on multi-core hosts.
  params.loader_workers = 0
  params.loss_function = 'alignment_loss'

  # Training-time window augmentation (no reference counterpart: the
  # reference effectively never repeats a window across ~100M-example
  # epochs, train_tpu_model.md:234-239; augmentation substitutes for
  # that diversity on small corpora). Probabilities are per example,
  # applied to training batches only (models/data.py:augment_batch).
  params.augment = False
  params.augment_perm_prob = 0.5     # shuffle subread order
  params.augment_drop_prob = 0.3     # downsample subreads (keep >= half)
  params.augment_rc_prob = 0.5       # reverse-complement the window
  params.augment_jitter_prob = 0.3   # +/-1 jitter on nonzero PW/IP

  # AlignmentLoss parameters (reference: model_configs.py:320-323).
  params.del_cost = 10.0
  params.loss_reg = 0.1
  params.band_width = ml_collections.config_dict.placeholder(int)

  params.max_length = DEFAULT_MAX_LENGTH

  params.model_config_name = 'transformer_learn_values'
  params.dataset_config_name = 'ccs'

  # TPU-native execution knobs (not in the reference).
  params.dtype = 'bfloat16'          # compute dtype; params stay float32
  # MFU A/B levers (see scripts/profile_forward.py): one-hot matmul
  # embeddings for small-vocab feature families (gather -> MXU), and
  # the attention softmax accumulation dtype on the XLA path
  # (None/'float32' = reference-matching default).
  params.embed_onehot = False
  params.attn_softmax_dtype = ml_collections.config_dict.placeholder(str)
  params.use_pallas_attention = False
  # Batch-major fused embed->condense->layer-0-attention Pallas kernel
  # for the short-window (L<=128) inference hot path
  # (ops/fused_window_attention.py). Falls back to the XLA path for
  # training, init, non-condensed/non-ReZero configs, and long windows.
  params.use_fused_hotpath = False
  # Quantized-inference levers (inference-only; training ignores both).
  # inference_dtype: 'bfloat16' casts checkpoint weights once at load
  # and runs activations end-to-end in bf16 (attn_softmax_dtype stays
  # an independent f32 escape hatch). quantize_matmuls: 'int8' applies
  # per-output-channel symmetric weight quantization to the encoder's
  # attention-projection and FFN matmuls, with the dequant folded into
  # the fused kernel epilogue (models/quantize.py).
  params.inference_dtype = ml_collections.config_dict.placeholder(str)
  params.quantize_matmuls = ml_collections.config_dict.placeholder(str)
  # Window length buckets for variable-width inference (None = single
  # shape at max_length, the reference behavior). When set (e.g.
  # (100, 200)), featurize pads each smart window to the smallest
  # bucket that fits instead of pad-to-max, and the engine packs and
  # dispatches each bucket separately with one compiled executable per
  # bucket (resolve_window_buckets / bucket_for above). The smallest
  # bucket must equal max_length.
  params.window_buckets = ml_collections.config_dict.placeholder(object)
  # Route AlignmentLoss through the whole-DP Pallas wavefront kernels
  # (forward scorer + custom-VJP backward) instead of the lax.scan DP.
  # Only applies when band_width is None (the training default).
  # None = auto: Pallas on a real TPU backend (measured 1.24x the scan
  # DP on v5e at batch 256), lax.scan elsewhere (the interpreted kernel
  # would dominate CPU runs).
  params.use_pallas_wavefront = None
  # Rematerialize encoder blocks in the backward pass (jax.checkpoint):
  # trades FLOPs for HBM headroom at large batch/long windows.
  params.remat = False
  params.dp_axis = 'data'            # mesh axis names
  params.tp_axis = 'model'
  params.eval_every_n_steps = 3000
  params.log_every_n_steps = 100
  # Eval metric that selects best_checkpoint.txt (HIGHER is better —
  # do not point it at eval/loss). The reference pins
  # eval/per_example_accuracy; on small held-out eval sets that ties
  # at 0.0 for every checkpoint, so eval/identity_pred is the
  # useful override there.
  params.best_checkpoint_metric = 'eval/per_example_accuracy'

  params.tpu_scale_factor = 1

  # Training fault tolerance (models/train.py, models/data.py).
  # on_shard_error: StreamingDataset policy for an undecodable shard —
  # 'fail' aborts, 'skip' counts + moves on (--on_shard_error).
  params.on_shard_error = 'fail'
  # NaN/Inf sentinel: after this many CONSECUTIVE non-finite train
  # steps, roll back to the last valid checkpoint (0 disables).
  params.nan_sentinel_steps = 3
  # Rollback budget; divergence persisting past it raises a permanent
  # NonFiniteTrainingError instead of ping-ponging forever.
  params.nan_max_rollbacks = 2
  # Decode window ids ('name') into training batches so NaN dead
  # letters can attribute a diverged batch to its windows (small
  # decode cost; off by default).
  params.track_window_ids = False
  # Mid-run checkpoint cadence for distillation (models/distill.py):
  # save every N steps so a killed/preempted distill stage resumes
  # from the last save instead of restarting (0 = final-only, the
  # pre-flywheel behavior). Training proper already checkpoints on its
  # eval_every_n_steps cadence.
  params.checkpoint_every_n_steps = 0

  if config_name is None:
    return params

  model_config_name, dataset_config_name = config_name.split('+')
  params.model_config_name = model_config_name
  params.dataset_config_name = dataset_config_name
  params.tf_dataset = None
  params.limit = -1
  if model_config_name == 'fc':
    _set_base_fc_hparams(params)
  elif model_config_name == 'conv_net':
    _set_base_conv_hparams(params)
  elif model_config_name == 'transformer':
    _set_base_transformer_hparams(params)
  elif model_config_name == 'transformer_learn_values':
    _set_transformer_learned_embeddings_hparams(params)
  elif model_config_name == 'transformer_learn_values_distill':
    _set_transformer_learned_embeddings_distill_hparams(params)
  else:
    raise ValueError(f'Unknown model_config_name: {model_config_name}')

  if dataset_config_name == 'test':
    _set_test_data_hparams(params)
  elif dataset_config_name == 'test_bq':
    _set_test_bq_data_hparams(params)
  elif dataset_config_name == 'custom':
    _set_custom_data_hparams(params)
  else:
    raise ValueError(
        f'dataset_config_name is {dataset_config_name}. Must be one of: '
        'test, test_bq, custom'
    )
  return params


def finalize_params(
    params: ml_collections.ConfigDict,
    max_length: Optional[int] = None,
    num_devices: int = 1,
    is_training: bool = True,
) -> None:
  """Derives dependent parameters (reference modify_params).

  Batch size scales by device count (global batch = per-replica x N,
  reference: model_utils.py:279-299); hidden size derives from the
  enabled per-feature embedding widths.
  """
  with params.unlocked():
    if not is_training:
      for key in ('tf_dataset', 'train_path', 'eval_path', 'test_path',
                  'inference_path'):
        if key in params:
          del params[key]

    if num_devices > 1:
      params.batch_size = params.batch_size * params.tpu_scale_factor
      params.batch_size *= num_devices

    if max_length is not None:
      params.max_length = max_length
    if 'max_length' not in params:
      raise ValueError('No params.max_length provided.')

    params.total_rows = _total_rows(params.max_passes, params.use_ccs_bq)

    if 'transformer_learn_values' in params.model_name:
      dim = (
          params.use_bases * params.per_base_hidden_size
          + params.use_pw * params.pw_hidden_size
          + params.use_ip * params.ip_hidden_size
          + params.use_strand * params.strand_hidden_size
          + params.use_ccs_bq * params.ccs_bq_hidden_size
      )
      params.hidden_size = (
          params.max_passes * dim
          + params.use_ccs * params.per_base_hidden_size
          + params.use_ccs_bq * params.ccs_bq_hidden_size
          + params.use_sn * params.sn_hidden_size * 4
      )
    else:
      params.hidden_size = params.total_rows

    if 'transformer' in params.model_name and params.hidden_size % 2 != 0:
      params.hidden_size += 1

    if 'transformer_learn_values' in params.model_name:
      if params.condense_transformer_input:
        params.hidden_size = params.transformer_input_size
    if 'transformer' in params.model_name:
      for name, value in TRANSFORMER_SIZE_PARAMS[
          params.get('transformer_model_size', 'base')
      ].items():
        if name not in params:
          params[name] = value


def save_params_as_json(out_dir: str, params: ml_collections.ConfigDict) -> str:
  """Writes params.json beside checkpoints (model_utils.py:468-476)."""
  os.makedirs(out_dir, exist_ok=True)
  path = os.path.join(out_dir, 'params.json')
  with open(path, 'w') as f:
    json.dump(params.to_dict(), f, indent=2, sort_keys=True, default=str)
  return path


def read_params_from_json(
    checkpoint_path: str,
) -> ml_collections.ConfigDict:
  """Loads params.json from a checkpoint directory or file prefix
  (model_utils.py:434-465). Unknown keys are kept (forward compat)."""
  # Orbax checkpoints are directories under <out_dir>/checkpoints/, so
  # walk up from the given path until params.json is found.
  candidates = []
  base = checkpoint_path if os.path.isdir(checkpoint_path) else (
      os.path.dirname(checkpoint_path)
  )
  for _ in range(3):
    candidates.append(os.path.join(base, 'params.json'))
    base = os.path.dirname(base)
  for json_path in candidates:
    if os.path.exists(json_path):
      break
  else:
    raise FileNotFoundError(
        f'params.json not found near {checkpoint_path!r}; looked in '
        f'{candidates}'
    )
  with open(json_path) as f:
    loaded = json.load(f)
  params = get_config()
  with params.unlocked():
    for key, value in loaded.items():
      params[key] = value
  return params
