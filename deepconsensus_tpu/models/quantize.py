"""Load-time quantization levers for inference.

Two independent levers, both applied ONCE at checkpoint load (before
any device placement, so sharded transfers ship the shrunken bytes):

* `params.inference_dtype = 'bfloat16'`: cast every float param leaf
  to bf16. The model's compute dtype follows (runner sets params.dtype
  to match), activations run bf16 end-to-end, and the
  `attn_softmax_dtype` escape hatch stays an independent f32 knob.

* `params.quantize_matmuls = 'int8'`: per-output-channel symmetric
  weight quantization of the encoder's attention-projection and FFN
  matmul kernels. scale[n] = max|W[:, n]| / 127, values = round(W /
  scale) clipped to int8. Two artifacts come out:

  - the params leaf is REPLACED by the dequantized weight
    (values * scale, f32) so every consumer that reads raw params —
    the XLA fallback path, the PR-5 layer-0 attention kernel,
    models/evaluate.py — sees the exact quantized-effective weights,
    making accuracy gates and parity tests consistent across paths;
  - a parallel 'quant' collection carries the int8 values + f32
    scales, mirroring the params tree shape, for the fused encoder
    block kernel (ops/fused_encoder_block.py) to consume directly:
    int8 stays int8 in HBM/VMEM and the dequant runs in the matmul
    epilogue.

Quantization happens on the f32 checkpoint BEFORE any bf16 cast, so
scales are computed at full precision and stay f32.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

_ATTN_SUBS = ('query', 'key', 'value', 'output_transform')
_FFN_SUBS = ('filter_layer', 'output_layer')


def _as_mutable(tree):
  """Deep-copy a (possibly frozen) nested mapping into plain dicts."""
  if hasattr(tree, 'items'):
    return {k: _as_mutable(v) for k, v in tree.items()}
  return tree


def _quantize_2d(w2: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
  """[K, N] f32 -> (int8 values [K, N], f32 scale [N])."""
  w2 = jnp.asarray(w2, jnp.float32)
  scale = jnp.max(jnp.abs(w2), axis=0) / 127.0
  scale = jnp.where(scale == 0.0, 1.0, scale)
  values = jnp.clip(jnp.round(w2 / scale), -127, 127).astype(jnp.int8)
  return values, scale


def quantize_matmul_params(
    variables: Dict[str, Any], num_layers: int
) -> Tuple[Dict[str, Any], int]:
  """int8-quantize the encoder matmul kernels of a loaded checkpoint.

  Returns (variables with dequantized params + 'quant' collection,
  number of quantized matmuls). Attention kernels quantize in their 2D
  matmul form (q/k/v [H, heads, hd] -> [H, H]; output [heads, hd, H]
  -> [H, H]) so the per-output-channel axis matches how the fused
  kernel contracts them.
  """
  variables = _as_mutable(variables)
  encoder = variables.get('params', {}).get('encoder')
  if encoder is None:
    return variables, 0
  quant_encoder: Dict[str, Any] = {}
  n_quantized = 0

  def quantize_leaf(module: Dict[str, Any], mod_name: str, sub: str,
                    to2d, from2d):
    nonlocal n_quantized
    kernel = module[sub]['kernel']
    values, scale = _quantize_2d(to2d(kernel))
    module[sub] = dict(module[sub])
    module[sub]['kernel'] = from2d(
        values.astype(jnp.float32) * scale).astype(kernel.dtype)
    quant_encoder.setdefault(mod_name, {})[sub] = {
        'values': values, 'scale': scale}
    n_quantized += 1

  for n in range(num_layers):
    attn_name = f'self_attention_{n}'
    if attn_name in encoder:
      attn = encoder[attn_name] = dict(encoder[attn_name])
      for sub in _ATTN_SUBS:
        kernel = attn[sub]['kernel']
        shape = kernel.shape
        if sub == 'output_transform':
          to2d = lambda w: w.reshape(-1, w.shape[-1])
        else:
          to2d = lambda w: w.reshape(w.shape[0], -1)
        quantize_leaf(attn, attn_name, sub, to2d,
                      lambda w2, shape=shape: w2.reshape(shape))
    ffn_name = f'ffn_{n}'
    if ffn_name in encoder:
      ffn = encoder[ffn_name] = dict(encoder[ffn_name])
      for sub in _FFN_SUBS:
        quantize_leaf(ffn, ffn_name, sub, lambda w: w, lambda w2: w2)

  if n_quantized:
    variables.setdefault('quant', {})['encoder'] = quant_encoder
  return variables, n_quantized


def cast_params(variables: Dict[str, Any], dtype: Any) -> Dict[str, Any]:
  """Cast the float leaves of the 'params' collection to `dtype`,
  leaving every other collection (int8 values, f32 scales) untouched."""
  variables = dict(variables)
  dtype = jnp.dtype(dtype)
  variables['params'] = jax.tree_util.tree_map(
      lambda x: x.astype(dtype)
      if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
      _as_mutable(variables['params']),
  )
  return variables


def prepare_inference_variables(
    variables: Dict[str, Any], params
) -> Tuple[Dict[str, Any], int]:
  """Apply the configured quantization levers to loaded variables.

  Order matters: int8 quantization runs on the f32 checkpoint first
  (full-precision scales), then the bf16 weight cast rounds the
  already-dequantized leaves. Returns (variables, n_quantized_matmuls).
  """
  n_quantized = 0
  if params.get('quantize_matmuls', None) == 'int8':
    variables, n_quantized = quantize_matmul_params(
        variables, params.num_hidden_layers)
  inference_dtype = params.get('inference_dtype', None)
  if inference_dtype and jnp.dtype(inference_dtype) != jnp.float32:
    variables = cast_params(variables, inference_dtype)
  return variables, n_quantized
