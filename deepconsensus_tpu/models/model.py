"""Flax implementation of the DeepConsensus model zoo.

The flagship model is the gap-aware encoder-only transformer with
learned per-feature embeddings (reference:
deepconsensus/models/networks.py:368-520, encoder_stack.py:43-198,
attention_layer.py:34-237, ffn_layer.py:34-87), re-designed TPU-first:

* All per-row embedding lookups are a single vectorized gather per
  feature family (the reference loops over 85 rows in Python, emitting
  85 small gathers), so XLA sees a handful of large fused gathers.
* Attention uses one batched einsum per projection, a static banded
  mask, and optionally a Pallas fused kernel (ops/banded_attention).
* Compute runs in bfloat16 on the MXU with float32 parameters and a
  float32 softmax; ReZero residual scalars keep training stable.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import ml_collections
import numpy as np

from deepconsensus_tpu import constants
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.parallel import ring_attention as ring_lib
from deepconsensus_tpu.preprocess.pileup import row_indices


def sinusoidal_position_encoding(
    length: int, hidden_size: int, min_timescale: float = 1.0,
    max_timescale: float = 1.0e4) -> np.ndarray:
  """Transformer timing signal: [sin | cos] halves, matching tf-models
  RelativePositionEmbedding used at networks.py:203,319-323."""
  position = np.arange(length, dtype=np.float32)
  num_timescales = hidden_size // 2
  log_increment = np.log(max_timescale / min_timescale) / max(
      num_timescales - 1, 1
  )
  inv_timescales = min_timescale * np.exp(
      np.arange(num_timescales, dtype=np.float32) * -log_increment
  )
  scaled = position[:, None] * inv_timescales[None, :]
  return np.concatenate([np.sin(scaled), np.cos(scaled)], axis=1)


# Vocab bound for the one-hot matmul embedding path: above this the
# materialized one-hot outweighs any MXU win (pw/ip vocab 256 stay on
# the gather path even with the flag on).
_ONEHOT_MAX_VOCAB = 32


class MaskedEmbed(nn.Module):
  """Embedding with zero vectors for id 0 and sqrt(dim) output scaling
  (reference ModifiedOnDeviceEmbedding: networks.py:42-63).

  onehot=True routes small-vocab lookups through a one-hot matmul
  instead of a gather — a candidate MFU lever: gathers run on the
  scalar/vector units while the matmul rides the MXU and fuses with
  the downstream condenser. Values are identical (each output row is
  a single table row either way); the flag exists to A/B on hardware.
  """

  vocab_size: int
  features: int
  dtype: Any = jnp.float32
  onehot: bool = False

  @nn.compact
  def __call__(self, ids: jnp.ndarray) -> jnp.ndarray:
    table = self.param(
        'embedding',
        nn.initializers.normal(stddev=self.features**-0.5),
        (self.vocab_size, self.features),
        jnp.float32,
    )
    if self.onehot and self.vocab_size <= _ONEHOT_MAX_VOCAB:
      # Clip first to match the gather path's mode='clip' semantics
      # (one_hot would zero out-of-range rows instead of clamping).
      ids_c = jnp.clip(ids, 0, self.vocab_size - 1)
      oh = jax.nn.one_hot(ids_c, self.vocab_size, dtype=self.dtype)
      # HIGHEST precision: each output row is one table row, and the
      # default-precision matmul would bf16-round f32 tables, breaking
      # exact equivalence with the gather path.
      emb = jnp.matmul(oh, table.astype(self.dtype),
                       precision=jax.lax.Precision.HIGHEST)
    else:
      # clip mode: out-of-range ids (already clipped upstream by
      # format_rows) clamp instead of producing NaN fill values.
      emb = jnp.take(table.astype(self.dtype), ids, axis=0, mode='clip')
    emb = emb * jnp.asarray(self.features**0.5, self.dtype)
    mask = (ids != 0).astype(self.dtype)
    return emb * mask[..., None]


class BandedSelfAttention(nn.Module):
  """Multi-head self-attention with a static banded (local) mask
  (reference Attention/SelfAttention: attention_layer.py:34-237)."""

  hidden_size: int
  num_heads: int
  dropout_rate: float
  attn_win_size: Optional[int]
  dtype: Any = jnp.float32
  use_pallas: bool = False
  # Softmax accumulation dtype (XLA path). float32 matches the
  # reference; bfloat16 is a candidate MFU lever (drops the f32
  # up/downcast round-trip around the [B, N, L, L] weights) to A/B on
  # hardware — banded logits are bounded, so bf16 is numerically safe
  # at inference; keep f32 for training unless measured otherwise.
  softmax_dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x: jnp.ndarray, deterministic: bool,
               ragged_widths: Optional[jnp.ndarray] = None,
               ragged_buckets: Optional[tuple] = None) -> jnp.ndarray:
    if self.hidden_size % self.num_heads:
      raise ValueError('hidden_size must be divisible by num_heads')
    head_dim = self.hidden_size // self.num_heads
    dense = lambda name: nn.DenseGeneral(
        features=(self.num_heads, head_dim),
        axis=-1,
        use_bias=False,
        dtype=self.dtype,
        kernel_init=nn.initializers.glorot_uniform(),
        name=name,
    )
    query_raw = dense('query')(x)
    query = query_raw * (head_dim**-0.5)
    key = dense('key')(x)
    value = dense('value')(x)

    if ragged_widths is not None:
      # Ragged slots (inference, use_ragged_kernel): x holds windows of
      # bucket widths packed back-to-back into slots of length S, every
      # window starting at a multiple of its own width (the divisibility
      # -chain packing invariant). The projections above are position-
      # wise, so reshaping [B, S] to [B*S/w, w] recovers each width-w
      # window as one contiguous attention batch whose compute is THE
      # SAME SHAPE as the bucketed path's — XLA produces bitwise-equal
      # outputs (a masked wide softmax would not: reassociating the
      # reduction over a different contraction length drifts 1 ulp).
      # Each position then selects the candidate from its own width.
      out = jnp.zeros(query.shape, query.dtype)
      bsz, length = x.shape[0], x.shape[1]
      for w in ragged_buckets:
        n = bsz * length // w
        shaped = lambda a: a.reshape(n, w, self.num_heads, head_dim)
        logits = jnp.einsum('BTNH,BFNH->BNFT', shaped(key), shaped(query))
        if self.attn_win_size:
          i = np.arange(w)
          band = np.abs(i[:, None] - i[None, :]) <= self.attn_win_size
          logits = jnp.where(band[None, None, :, :], logits, -1e9)
        weights = jax.nn.softmax(
            logits.astype(self.softmax_dtype), axis=-1
        ).astype(self.dtype)
        cand = jnp.einsum(
            'BNFT,BTNH->BFNH', weights, shaped(value)
        ).reshape(bsz, length, self.num_heads, head_dim)
        out = out + jnp.where(
            (ragged_widths == w)[:, :, None, None], cand,
            jnp.zeros((), cand.dtype))
      return nn.DenseGeneral(
          features=self.hidden_size,
          axis=(-2, -1),
          use_bias=False,
          dtype=self.dtype,
          kernel_init=nn.initializers.glorot_uniform(),
          name='output_transform',
      )(out)

    use_dropout = not deterministic and self.dropout_rate > 0.0
    if (x.shape[1] >= config_lib.RING_ATTENTION_MIN_LEN
        and not use_dropout):
      # Long-insert windows: past the crossover the [B, N, L, L]
      # logits/weights tensors dominate memory (at L=500 the fused
      # kernel's whole-L VMEM tiling no longer fits either), so
      # attention runs as the blockwise ring scan — exact, banded, and
      # differentiable, with K/V streamed through the online softmax.
      # The scan never materializes attention weights, so weight
      # dropout is unavailable here; long-insert configs set
      # attention_dropout=0 (training with dropout falls through to
      # the paths below).
      out = ring_lib.ring_attention_blockwise(
          query_raw, key, value, self.attn_win_size or None
      )
      return nn.DenseGeneral(
          features=self.hidden_size,
          axis=(-2, -1),
          use_bias=False,
          dtype=self.dtype,
          kernel_init=nn.initializers.glorot_uniform(),
          name='output_transform',
      )(out)
    use_pallas = self.use_pallas
    long_window = False
    if use_pallas:
      # Fused VMEM kernel with custom VJP, so it serves training too.
      # Dropout uses a caller-generated bernoulli keep-mask shared by
      # forward and backward (ops/banded_attention.py).
      from deepconsensus_tpu.ops import banded_attention as ba
      from deepconsensus_tpu.ops import flash_band_attention as fba

      long_window = x.shape[1] > fba.WHOLE_L_LIMIT
      if use_dropout and long_window:
        # The whole-L dropout kernel stops compiling past its VMEM
        # limit and would materialize a [B, N, L, L] bernoulli mask;
        # long-window training with attention dropout routes to the
        # XLA path below instead (the flash kernel has no dropout).
        use_pallas = False
    if use_pallas:
      if long_window:
        # Long windows: the whole-L kernel's [G, L, L] VMEM block no
        # longer fits; the block-banded flash kernel scales as L*band
        # instead (measured 1.1-3.2x the XLA path at L=256..4096 on
        # v5e) and trains through its own custom VJP.
        out = fba.flash_band_attention_vjp(
            query, key, value, self.attn_win_size or None
        )
      elif not use_dropout:
        out = ba.banded_attention_vjp(
            query, key, value, self.attn_win_size or None
        )
      else:
        b, l, n, _ = query.shape
        keep_prob = 1.0 - self.dropout_rate
        mask = jax.random.bernoulli(
            self.make_rng('dropout'), keep_prob, (b, n, l, l)
        ).astype(jnp.uint8)
        out = ba.banded_attention_dropout_vjp(
            query, key, value, mask, self.attn_win_size or None,
            keep_prob,
        )
    else:
      # [B, N, Lq, Lk]
      logits = jnp.einsum('BTNH,BFNH->BNFT', key, query)
      length = x.shape[1]
      if self.attn_win_size:
        i = np.arange(length)
        band = np.abs(i[:, None] - i[None, :]) <= self.attn_win_size
        logits = jnp.where(band[None, None, :, :], logits, -1e9)
      weights = jax.nn.softmax(
          logits.astype(self.softmax_dtype), axis=-1
      ).astype(self.dtype)
      # Expose attention maps like the reference's intermediate outputs
      # (attention_scores_{n}: encoder_stack.py:184-187); retrieve with
      # apply(..., capture_intermediates=True).
      self.sow('intermediates', 'attention_scores', weights)
      weights = nn.Dropout(rate=self.dropout_rate)(
          weights, deterministic=deterministic
      )
      out = jnp.einsum('BNFT,BTNH->BFNH', weights, value)
    return nn.DenseGeneral(
        features=self.hidden_size,
        axis=(-2, -1),
        use_bias=False,
        dtype=self.dtype,
        kernel_init=nn.initializers.glorot_uniform(),
        name='output_transform',
    )(out)


class FeedForward(nn.Module):
  """filter_size relu -> hidden_size (reference ffn_layer.py:34-87)."""

  hidden_size: int
  filter_size: int
  dropout_rate: float
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x: jnp.ndarray, deterministic: bool) -> jnp.ndarray:
    h = nn.Dense(self.filter_size, dtype=self.dtype, name='filter_layer')(x)
    h = nn.relu(h)
    h = nn.Dropout(rate=self.dropout_rate)(h, deterministic=deterministic)
    return nn.Dense(self.hidden_size, dtype=self.dtype, name='output_layer')(h)


class ResidualWrapper(nn.Module):
  """ReZero (x + alpha*f(x), alpha init 0) or pre-LN residual
  (reference PrePostProcessingWrapper: encoder_stack.py:43-93)."""

  sublayer: nn.Module
  rezero: bool
  dropout_rate: float

  @nn.compact
  def __call__(self, x: jnp.ndarray, deterministic: bool,
               **sublayer_kwargs) -> jnp.ndarray:
    if self.rezero:
      y = x
    else:
      y = nn.LayerNorm(epsilon=1e-6, dtype=jnp.float32, name='layer_norm')(x)
    y = self.sublayer(y, deterministic=deterministic, **sublayer_kwargs)
    y = nn.Dropout(rate=self.dropout_rate)(y, deterministic=deterministic)
    if self.rezero:
      alpha = self.param('alpha', nn.initializers.zeros, (), jnp.float32)
      return x + alpha.astype(y.dtype) * y
    return x + y


class EncoderStack(nn.Module):
  """N x (banded self-attention + FFN), final LayerNorm
  (reference encoder_stack.py:96-198)."""

  params: ml_collections.FrozenConfigDict
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x: jnp.ndarray, deterministic: bool,
               skip_first_attention: bool = False,
               skip_blocks: bool = False,
               ragged_widths: Optional[jnp.ndarray] = None,
               ragged_buckets: Optional[tuple] = None) -> jnp.ndarray:
    p = self.params

    if skip_blocks:
      # The fused hot path (ops/fused_encoder_block.py) already ran
      # every attention/FFN block including the ReZero residuals; only
      # the final normalization remains. Init never takes this branch,
      # so the param tree is created identically.
      return nn.LayerNorm(
          epsilon=1e-6, dtype=jnp.float32, name='output_normalization'
      )(x)

    # Optional rematerialization: drop each residual block's
    # activations and recompute them in the backward pass, trading
    # FLOPs for HBM so long-window/large-batch runs fit
    # (params.remat; jax.checkpoint under the hood).
    def run_block(wrapper, x, **kw):
      return wrapper(x, deterministic=deterministic, **kw)

    # Ragged routing is inference-only; remat is a training lever and
    # would treat the static bucket tuple as traced args, so the two
    # never compose.
    if p.get('remat', False) and ragged_widths is None:
      run_block = nn.remat(run_block)

    attn_kwargs = {}
    if ragged_widths is not None:
      attn_kwargs = dict(ragged_widths=ragged_widths,
                         ragged_buckets=ragged_buckets)

    for n in range(p.num_hidden_layers):
      if skip_first_attention and n == 0:
        # The fused hot path (ops/fused_window_attention.py) already
        # applied attention_wrapper_0's block including the residual;
        # module names below stay aligned so the param tree is
        # unchanged (init never takes this branch).
        pass
      else:
        attn = BandedSelfAttention(
            hidden_size=p.hidden_size,
            num_heads=p.num_heads,
            dropout_rate=p.attention_dropout,
            attn_win_size=p.attn_win_size,
            dtype=self.dtype,
            use_pallas=p.get('use_pallas_attention', False),
            softmax_dtype=jnp.dtype(
                p.get('attn_softmax_dtype', None) or 'float32'),
            name=f'self_attention_{n}',
        )
        x = run_block(
            ResidualWrapper(
                attn, rezero=p.rezero,
                dropout_rate=p.layer_postprocess_dropout,
                name=f'attention_wrapper_{n}',
            ),
            x,
            **attn_kwargs,
        )
      ffn = FeedForward(
          hidden_size=p.hidden_size,
          filter_size=p.filter_size,
          dropout_rate=p.relu_dropout,
          dtype=self.dtype,
          name=f'ffn_{n}',
      )
      x = run_block(
          ResidualWrapper(
              ffn, rezero=p.rezero,
              dropout_rate=p.layer_postprocess_dropout,
              name=f'ffn_wrapper_{n}',
          ),
          x,
      )
    return nn.LayerNorm(
        epsilon=1e-6, dtype=jnp.float32, name='output_normalization'
    )(x)


class DeepConsensusModel(nn.Module):
  """Encoder-only transformer with learned per-feature embeddings.

  Input: rows [batch, total_rows, max_length, 1] float32 as produced by
  the feature pipeline; output: per-position softmax over
  {gap, A, T, C, G} (reference networks.py:368-520).
  """

  params: ml_collections.FrozenConfigDict

  def setup(self):
    p = self.params
    self.compute_dtype = jnp.dtype(p.get('dtype', 'float32'))
    self.learn_values = 'learn_values' in p.model_name
    dt = self.compute_dtype
    if not self.learn_values:
      # Plain transformer: raw rows are the per-position feature vector
      # (reference EncoderOnlyTransformer: networks.py:173-365).
      self.encoder = EncoderStack(p, dtype=dt, name='encoder')
      self.logits_layer = nn.Dense(
          constants.SEQ_VOCAB_SIZE, use_bias=True, dtype=jnp.float32,
          kernel_init=nn.initializers.glorot_uniform(), name='logits')
      return
    onehot = p.get('embed_onehot', False)
    if p.use_bases or p.use_ccs:
      self.bases_embedding = MaskedEmbed(
          constants.SEQ_VOCAB_SIZE, p.per_base_hidden_size, dt,
          onehot=onehot, name='bases_embedding')
    if p.use_pw:
      self.pw_embedding = MaskedEmbed(
          p.PW_MAX + 1, p.pw_hidden_size, dt, onehot=onehot,
          name='pw_embedding')
    if p.use_ip:
      self.ip_embedding = MaskedEmbed(
          p.IP_MAX + 1, p.ip_hidden_size, dt, onehot=onehot,
          name='ip_embedding')
    if p.use_strand:
      self.strand_embedding = MaskedEmbed(
          p.STRAND_MAX + 1, p.strand_hidden_size, dt, onehot=onehot,
          name='strand_embedding')
    if p.use_ccs_bq:
      self.ccs_bq_embedding = MaskedEmbed(
          p.CCS_BQ_MAX, p.ccs_bq_hidden_size, dt, onehot=onehot,
          name='ccs_bq_embedding')
    if p.use_sn:
      self.sn_embedding = MaskedEmbed(
          p.SN_MAX + 1, p.sn_hidden_size, dt, onehot=onehot,
          name='sn_embedding')
    if p.condense_transformer_input:
      self.condenser = nn.Dense(
          p.transformer_input_size, use_bias=False, dtype=dt,
          kernel_init=nn.initializers.glorot_uniform(), name='condenser')
    self.encoder = EncoderStack(p, dtype=dt, name='encoder')
    self.logits_layer = nn.Dense(
        constants.SEQ_VOCAB_SIZE, use_bias=True, dtype=jnp.float32,
        kernel_init=nn.initializers.glorot_uniform(), name='logits')

  def _embed_rows(self, rows: jnp.ndarray) -> jnp.ndarray:
    """Vectorized per-feature embedding of the stacked pileup tensor.

    rows: [B, R, L]; returns [B, L, sum(feature_rows * widths)], the
    concat order matching the reference's per-row append order
    (networks.py:436-506).
    """
    p = self.params
    (base_r, pw_r, ip_r, strand_r, ccs_r, ccs_bq_r, sn_r) = row_indices(
        p.max_passes, p.use_ccs_bq
    )
    blocks = []

    def gather(embedding, row_range, shift: int = 0):
      ids = rows[:, row_range[0]:row_range[1], :].astype(jnp.int32) + shift
      emb = embedding(ids)  # [B, r, L, E]
      b, r, l, e = emb.shape
      return jnp.transpose(emb, (0, 2, 1, 3)).reshape(b, l, r * e)

    if p.use_bases:
      blocks.append(gather(self.bases_embedding, base_r))
    if p.use_pw:
      blocks.append(gather(self.pw_embedding, pw_r))
    if p.use_ip:
      blocks.append(gather(self.ip_embedding, ip_r))
    if p.use_strand:
      blocks.append(gather(self.strand_embedding, strand_r))
    if p.use_ccs:
      blocks.append(gather(self.bases_embedding, ccs_r))
    if p.use_ccs_bq:
      # Shift -1 (gap) to 0 (networks.py:491-497).
      blocks.append(gather(self.ccs_bq_embedding, ccs_bq_r, shift=1))
    if p.use_sn:
      blocks.append(gather(self.sn_embedding, sn_r))
    return jnp.concatenate(blocks, axis=-1)

  def _fused_hotpath_eligible(self, rows: jnp.ndarray, train: bool) -> bool:
    """True when this apply can route through the batch-major fused
    embed->condense->attention kernel. Init always runs the XLA path so
    the param tree is created identically; training needs gradients and
    dropout the kernel doesn't serve; the kernel assumes the condensed
    learn-values input, a ReZero residual for layer 0, and a window
    short enough for whole-L score blocks. rows.shape is static under
    trace, so with window buckets the routing is per bucket: each
    bucket's compiled forward independently picks fused
    (L <= MAX_WINDOW_LEN) or the XLA fallback."""
    from deepconsensus_tpu.ops import fused_window_attention as fwa

    p = self.params
    return bool(
        p.get('use_fused_hotpath', False)
        and not train
        and not self.is_initializing()
        and self.learn_values
        and p.condense_transformer_input
        and p.rezero
        and p.num_hidden_layers >= 1
        and rows.shape[-1] <= fwa.MAX_WINDOW_LEN
    )

  def _fused_forward(self, rows: jnp.ndarray) -> jnp.ndarray:
    """Embed+condense+pos+layer-0 attention block via the fused Pallas
    kernel; returns activations ready for the remaining encoder blocks
    (call the encoder with skip_first_attention=True)."""
    from deepconsensus_tpu.ops import fused_window_attention as fwa

    p = self.params
    specs, table_keys, _ = fwa.build_family_specs(p)
    params = self.variables['params']
    tables = {k: params[f'{k}_embedding']['embedding'] for k in table_keys}
    h = p.hidden_size
    # Sublayers are constructed outside ResidualWrapper, so Flax names
    # them as siblings of their wrapper inside the encoder scope.
    attn0 = params['encoder']['self_attention_0']
    wrap0 = params['encoder']['attention_wrapper_0']
    pos = None
    if p.add_pos_encoding:
      # dclint: allow=dtype-downcast (position encodings enter the
      # fused kernel at the configured compute dtype)
      pos = jnp.asarray(
          sinusoidal_position_encoding(rows.shape[-1], h),
          self.compute_dtype)
    x_base, attn_out = fwa.fused_embed_condense_attention(
        rows,
        tables,
        params['condenser']['kernel'],
        attn0['query']['kernel'].reshape(h, h),
        attn0['key']['kernel'].reshape(h, h),
        attn0['value']['kernel'].reshape(h, h),
        attn0['output_transform']['kernel'].reshape(h, h),
        pos,
        specs=specs,
        table_keys=table_keys,
        num_heads=p.num_heads,
        attn_win_size=p.attn_win_size or None,
        softmax_dtype=jnp.dtype(p.get('attn_softmax_dtype', None)
                                or 'float32'),
        compute_dtype=self.compute_dtype,
    )
    alpha = wrap0['alpha']
    return x_base + alpha.astype(x_base.dtype) * attn_out

  def _fused_encoder_blocks(
      self, x: jnp.ndarray,
      lengths: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Run every remaining encoder block (layer-0 FFN onward) through
    the fused Pallas block kernel (ops/fused_encoder_block.py); the
    caller finishes with the encoder's output LayerNorm
    (skip_blocks=True). int8-quantized matmul weights ride in from the
    'quant' collection when params.quantize_matmuls is set. lengths:
    per-slot window widths for ragged slots (every attention block
    masks with the lengths-derived ragged mask)."""
    from deepconsensus_tpu.ops import fused_encoder_block as feb

    p = self.params
    quant = None
    if p.get('quantize_matmuls', None) == 'int8':
      quant = self.variables.get('quant', {}).get('encoder')
    blocks = feb.blocks_from_params(
        self.variables['params']['encoder'],
        quant,
        p.num_hidden_layers,
        skip_first_attention=True,
    )
    return feb.fused_encoder_stack(
        x,
        blocks,
        num_heads=p.num_heads,
        attn_win_size=p.attn_win_size or None,
        softmax_dtype=jnp.dtype(p.get('attn_softmax_dtype', None)
                                or 'float32'),
        compute_dtype=self.compute_dtype,
        lengths=lengths,
    )

  def _ragged_hotpath_eligible(self, rows: jnp.ndarray) -> bool:
    """Fused-kernel eligibility for ragged slots: same levers as
    _fused_hotpath_eligible except the window-length bound — slots
    span the LARGEST bucket, so the ragged kernel carries its own
    (higher) slot-length ceiling."""
    from deepconsensus_tpu.ops import ragged_window_attention as rwa

    p = self.params
    return bool(
        p.get('use_fused_hotpath', False)
        and not self.is_initializing()
        and self.learn_values
        and p.condense_transformer_input
        and p.rezero
        and p.num_hidden_layers >= 1
        and rows.shape[-1] <= rwa.RAGGED_MAX_SLOT_LEN
    )

  def _ragged_fused_forward(self, rows: jnp.ndarray,
                            lengths: jnp.ndarray) -> jnp.ndarray:
    """Embed+condense+pos+layer-0 attention over ragged slots via the
    ragged Pallas kernel (ops/ragged_window_attention.py); mirrors
    _fused_forward's weight plumbing and residual split."""
    from deepconsensus_tpu.ops import fused_window_attention as fwa
    from deepconsensus_tpu.ops import ragged_window_attention as rwa

    p = self.params
    specs, table_keys, _ = fwa.build_family_specs(p)
    params = self.variables['params']
    tables = {k: params[f'{k}_embedding']['embedding'] for k in table_keys}
    h = p.hidden_size
    attn0 = params['encoder']['self_attention_0']
    wrap0 = params['encoder']['attention_wrapper_0']
    pos = None
    if p.add_pos_encoding:
      # dclint: allow=dtype-downcast (position encodings enter the
      # fused kernel at the configured compute dtype)
      pos = jnp.asarray(
          sinusoidal_position_encoding(rows.shape[-1], h),
          self.compute_dtype)
    x_base, attn_out = rwa.ragged_embed_condense_attention(
        rows,
        lengths,
        tables,
        params['condenser']['kernel'],
        attn0['query']['kernel'].reshape(h, h),
        attn0['key']['kernel'].reshape(h, h),
        attn0['value']['kernel'].reshape(h, h),
        attn0['output_transform']['kernel'].reshape(h, h),
        pos,
        specs=specs,
        table_keys=table_keys,
        num_heads=p.num_heads,
        attn_win_size=p.attn_win_size or None,
        softmax_dtype=jnp.dtype(p.get('attn_softmax_dtype', None)
                                or 'float32'),
        compute_dtype=self.compute_dtype,
    )
    alpha = wrap0['alpha']
    return x_base + alpha.astype(x_base.dtype) * attn_out

  def _ragged_forward_with_intermediates(
      self, rows: jnp.ndarray,
      window_lengths: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """Single-shape ragged forward: rows [B, R, S] with mixed-width
    windows packed back-to-back per slot, window_lengths [B, wps] the
    per-slot widths. The XLA route is bitwise-identical per position
    to the bucketed forward at each window's own width (reshape-select
    attention + exact per-position pos gather); the Pallas route (when
    use_fused_hotpath is on) is the ragged kernel pair, allclose-
    validated against the reference in interpret mode."""
    from deepconsensus_tpu.models import config as config_lib
    from deepconsensus_tpu.ops import ragged_window_attention as rwa

    p = self.params
    if not self.learn_values:
      raise ValueError('ragged forward requires the learn_values model')
    slot_len = rows.shape[-1]
    # Only widths that tile the slot can be recovered by reshape; the
    # packer feeds exactly these (slot_len is the largest bucket of a
    # divisibility chain, so normally every bucket qualifies).
    buckets = rwa.validate_ragged_buckets(
        tuple(b for b in config_lib.resolve_window_buckets(p)
              if slot_len % b == 0))
    lengths = jnp.asarray(window_lengths, jnp.int32)
    if self._ragged_hotpath_eligible(rows):
      x = self._ragged_fused_forward(rows, lengths)
      x = self._fused_encoder_blocks(x, lengths=lengths)
      encoded = self.encoder(x, deterministic=True, skip_blocks=True)
      logits = self.logits_layer(encoded.astype(jnp.float32))
      preds = jax.nn.softmax(logits, axis=-1)
      return {'final_output': encoded, 'logits': logits, 'preds': preds}
    _seg, start, width, valid = rwa.slot_geometry(lengths, slot_len)
    x = self._embed_rows(rows)
    if p.condense_transformer_input:
      x = self.condenser(x)
    if p.add_pos_encoding:
      pos = jnp.asarray(
          sinusoidal_position_encoding(slot_len, x.shape[2]), x.dtype)
      off = jnp.clip(
          jnp.arange(slot_len, dtype=jnp.int32)[None, :] - start,
          0, slot_len - 1)
      # Per-position gather pos[p - window_start(p)]: the same value
      # (and the same single add) the bucketed path applies at this
      # position's window offset, so the sum is bitwise-equal.
      x = x + jnp.where(valid[:, :, None], jnp.take(pos, off, axis=0),
                        jnp.zeros((), x.dtype))
    encoded = self.encoder(x, deterministic=True, ragged_widths=width,
                           ragged_buckets=buckets)
    logits = self.logits_layer(encoded.astype(jnp.float32))
    preds = jax.nn.softmax(logits, axis=-1)
    return {'final_output': encoded, 'logits': logits, 'preds': preds}

  def __call__(
      self, rows: jnp.ndarray, train: bool = False,
      window_lengths: Optional[jnp.ndarray] = None
  ) -> jnp.ndarray:
    return self.apply_with_intermediates(
        rows, train, window_lengths=window_lengths)['preds']

  @nn.compact_name_scope
  def apply_with_intermediates(
      self, rows: jnp.ndarray, train: bool = False,
      window_lengths: Optional[jnp.ndarray] = None
  ) -> Dict[str, jnp.ndarray]:
    p = self.params
    deterministic = not train
    if rows.ndim == 4:
      rows = jnp.squeeze(rows, -1)
    if window_lengths is not None and not train:
      return self._ragged_forward_with_intermediates(rows, window_lengths)
    if self._fused_hotpath_eligible(rows, train):
      x = self._fused_forward(rows)
      x = self._fused_encoder_blocks(x)
      encoded = self.encoder(x, deterministic=True, skip_blocks=True)
      logits = self.logits_layer(encoded.astype(jnp.float32))
      preds = jax.nn.softmax(logits, axis=-1)
      return {'final_output': encoded, 'logits': logits, 'preds': preds}
    if self.learn_values:
      x = self._embed_rows(rows)
      if p.condense_transformer_input:
        x = self.condenser(x)
    else:
      # Raw per-position feature vectors [B, L, total_rows], zero-padded
      # to an even width for the positional encoding
      # (reference: networks.py:266-306).
      # dclint: allow=dtype-downcast (model entry point: inputs adopt
      # the configured compute dtype once, here)
      x = jnp.transpose(rows, (0, 2, 1)).astype(self.compute_dtype)
      if p.add_pos_encoding and x.shape[-1] % 2 != 0:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, 1)))
    if p.add_pos_encoding:
      pos = sinusoidal_position_encoding(x.shape[1], x.shape[2])
      x = x + jnp.asarray(pos, x.dtype)
    if train and p.layer_postprocess_dropout > 0:
      x = nn.Dropout(rate=p.layer_postprocess_dropout, name='input_dropout')(
          x, deterministic=deterministic
      )
    encoded = self.encoder(x, deterministic=deterministic)
    logits = self.logits_layer(encoded.astype(jnp.float32))
    preds = jax.nn.softmax(logits, axis=-1)
    return {'final_output': encoded, 'logits': logits, 'preds': preds}


class FullyConnectedModel(nn.Module):
  """Simple FC baseline (reference networks.py:67-92)."""

  params: ml_collections.FrozenConfigDict

  @nn.compact
  def __call__(self, rows: jnp.ndarray, train: bool = False) -> jnp.ndarray:
    p = self.params
    x = rows.reshape(rows.shape[0], -1)
    for width in p.fc_size:
      x = nn.Dense(width)(x)
      x = nn.relu(x)
      x = nn.Dropout(rate=p.fc_dropout)(x, deterministic=not train)
    x = nn.Dense(p.max_length * constants.SEQ_VOCAB_SIZE)(x)
    x = x.reshape(rows.shape[0], p.max_length, constants.SEQ_VOCAB_SIZE)
    return jax.nn.softmax(x, axis=-1)


def summarize_params(variables) -> str:
  """Human-readable parameter summary with per-module counts
  (counterpart of reference print_model_summary: model_utils.py)."""
  lines = []
  total = 0
  flat = jax.tree_util.tree_flatten_with_path(variables)[0]
  for path, leaf in flat:
    name = '/'.join(getattr(k, 'key', str(k)) for k in path)
    count = int(np.prod(leaf.shape)) if leaf.shape else 1
    total += count
    lines.append(f'{name:70s} {str(leaf.shape):20s} {count:>12,}')
  lines.append(f'{"TOTAL":70s} {"":20s} {total:>12,}')
  return '\n'.join(lines)


def get_model(params: ml_collections.ConfigDict) -> nn.Module:
  """Model factory (reference model_utils.py:142-152)."""
  frozen = ml_collections.FrozenConfigDict(params)
  if 'transformer' in params.model_name:
    return DeepConsensusModel(frozen)
  if params.model_name == 'fc':
    return FullyConnectedModel(frozen)
  if params.model_name == 'conv_net':
    from deepconsensus_tpu.models.convnet import ConvNetModel

    return ConvNetModel(frozen)
  raise ValueError(f'Unknown model name: {params.model_name}')
