"""Differentiable alignment loss and related losses (JAX).

AlignmentLoss is the reference's soft edit-distance training objective
(reference: deepconsensus/models/losses_and_metrics.py:263-609): a
wavefront DP over cross-entropy substitution/insertion costs with a
constant deletion cost and a logsumexp soft minimum, optionally
band-restricted. Here the DP is a lax.scan (ops/wavefront) and the
whole loss jits and differentiates end-to-end on TPU.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from deepconsensus_tpu import constants
from deepconsensus_tpu.ops import wavefront

Array = jnp.ndarray


def left_shift_sequence(y: Array) -> Array:
  """Moves internal gaps to the end per row via the two-stage sort trick
  (reference: losses_and_metrics.py:92-115)."""
  seq_length = y.shape[1]
  ixs = jnp.broadcast_to(jnp.arange(seq_length), y.shape)
  sort_order = jnp.sort(
      jnp.where(y != constants.GAP_INT, ixs, seq_length + ixs), axis=1
  )
  sort_order = jnp.where(
      sort_order < seq_length, sort_order, sort_order - seq_length
  )
  return jnp.take_along_axis(y, sort_order, axis=1)


def xentropy_subs_cost(y_true: Array, y_pred: Array,
                       eps: float = 1e-7) -> Array:
  """[B, m, n] pairwise cross-entropy costs for integer labels
  (reference: losses_and_metrics.py:123-143).

  Computed as an exact vocab gather rather than a one-hot matmul: on
  TPU a default-precision matmul would round the log-probs to bfloat16.
  """
  log_p = jnp.log(jnp.clip(y_pred, eps, 1 - eps))  # [B, n, V]
  b, n, _ = y_pred.shape
  bi = jnp.arange(b)[:, None, None]
  ji = jnp.arange(n)[None, None, :]
  return -log_p[bi, ji, y_true[:, :, None]]


def xentropy_ins_cost(y_pred: Array, eps: float = 1e-7) -> Array:
  """[B, n] insertion costs: -log P(gap)
  (reference: losses_and_metrics.py:191-207)."""
  return -jnp.log(jnp.clip(y_pred[..., constants.GAP_INT], eps, 1 - eps))


class AlignmentLoss:
  """Soft alignment loss; callable returns the mean over the batch."""

  def __init__(
      self,
      del_cost: float = 1.0,
      loss_reg: Optional[float] = 1.0,
      width: Optional[int] = None,
      eps: float = 1e-7,
      inf: float = 1e9,
      use_pallas: bool = False,
  ):
    self.del_cost = del_cost
    self.loss_reg = loss_reg
    self.width = width
    self.eps = eps
    self.inf = inf
    # Whole-DP Pallas kernels (ops/wavefront_pallas): forward scorer +
    # custom-VJP backward, so training differentiates through Pallas.
    self.use_pallas = use_pallas

  def per_example(self, y_true: Array, y_pred: Array) -> Array:
    """[B] loss values for y_true [B, m] ints and y_pred [B, n, V]."""
    y_true = left_shift_sequence(y_true.astype(jnp.int32))
    seq_lens = jnp.sum(
        (y_true != constants.GAP_INT).astype(jnp.int32), axis=-1
    )
    y_pred = y_pred / jnp.sum(y_pred, axis=-1, keepdims=True)

    subs_costs = xentropy_subs_cost(y_true, y_pred, self.eps)
    ins_costs = xentropy_ins_cost(y_pred, self.eps)
    del_cost = jnp.asarray(self.del_cost, y_pred.dtype)

    if self.loss_reg is None:
      minop = lambda t: jnp.min(t, axis=0)
    else:
      reg = jnp.asarray(self.loss_reg, y_pred.dtype)
      minop = lambda t: -reg * jax.nn.logsumexp(-t / reg, axis=0)

    if self.width is None:
      if self.use_pallas:
        from deepconsensus_tpu.ops import wavefront_pallas

        return wavefront_pallas.alignment_scores_vjp(
            subs_costs, ins_costs, seq_lens, self.del_cost,
            self.loss_reg, self.inf,
        )
      return wavefront.alignment_scan(
          subs_costs, ins_costs, del_cost, seq_lens, minop, self.inf
      )
    if self.use_pallas:
      from deepconsensus_tpu.ops import wavefront_pallas

      return wavefront_pallas.banded_alignment_scores_vjp(
          subs_costs, ins_costs, seq_lens, self.del_cost,
          self.loss_reg, int(self.width), self.inf,
      )
    return wavefront.banded_alignment_scan(
        subs_costs, ins_costs, del_cost, seq_lens, int(self.width), minop,
        self.inf,
    )

  def __call__(self, y_true: Array, y_pred: Array) -> Array:
    return jnp.mean(self.per_example(y_true, y_pred))


def distillation_loss(
    teacher_logits: Array,
    student_logits: Array,
    temperature: float = 1.0,
    kind: str = 'mean_squared_error',
) -> Array:
  """Temperature-scaled prob-space loss between teacher and student
  (reference DistillationLoss: losses_and_metrics.py:1170-1213)."""
  teacher = jax.nn.softmax(teacher_logits / temperature, axis=-1)
  student = jax.nn.softmax(student_logits / temperature, axis=-1)
  if kind == 'mean_squared_error':
    per_pos = jnp.mean((teacher - student) ** 2, axis=-1)
  elif kind == 'kl_divergence':
    per_pos = jnp.sum(
        teacher * (jnp.log(jnp.clip(teacher, 1e-10, 1.0))
                   - jnp.log(jnp.clip(student, 1e-10, 1.0))),
        axis=-1,
    )
  else:
    raise ValueError(f'unknown distillation loss {kind!r}')
  return jnp.mean(per_pos)
